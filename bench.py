"""North-star benchmark: InceptionV3 DeepImageFeaturizer throughput.

Reports, in ONE JSON line (driver contract):

* ``value`` — end-to-end host-fed images/sec/chip through the
  production ``BatchRunner`` (uint8 NHWC host arrays in, 2048-d
  features out; preprocess fused into the same XLA program). This is
  the north-star metric's shape.
* ``device_resident_ips`` / ``device_tflops`` — the same program timed
  with device-resident input and a forced-sync readback: the chip's
  compute-side capability with host↔device transfer excluded.
* ``link_h2d_MBps`` / ``link_d2h_MBps`` — measured host↔device
  bandwidth, and ``host_fed_ceiling_ips`` — the hard upper bound the
  link imposes on ANY host-fed pipeline (bandwidth ÷ bytes/image).
* ``value_packed`` — end-to-end with the byte-shrunk payload
  (VERDICT r2 next #3): the host packs uint8 at a smaller source size
  (``packed_src_hw``) and bilinear resize to 299² runs ON DEVICE,
  fused into the same XLA program (``deviceResizeFrom`` mode) — the
  wire carries ~4× fewer bytes/image, lifting the link ceiling
  (``host_fed_ceiling_ips_packed``) in proportion.
* ``host_decode_ips`` — the fused decode→resize→pack reader
  (``readImagesPacked``, native libjpeg+OpenMP shim) measured on
  synthesized JPEGs: proof the host decode stage outruns the device
  featurize rate budgeted in SURVEY §6.

Separating these is the point (round-1 lesson): on a tunneled TPU the
link moves ~10-35 MB/s, capping end-to-end at ~40-134 img/s regardless
of the device program, while the device program itself runs thousands
of img/s. ``vs_baseline`` stays honest (end-to-end vs the 1,250
img/s/chip target = 10k/s ÷ 8 chips, BASELINE.md) and the extra keys
attribute any gap to link vs compute.

Sync methodology: ``jax.block_until_ready`` returns at enqueue on the
tunneled platform, so timing forces a tiny dependent readback instead.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

PER_CHIP_TARGET = 1250.0  # 10k img/s ÷ 8 chips (BASELINE.md)
INCEPTION_GFLOPS = 11.5   # fwd FLOPs per 299x299 image (SURVEY §6)


def _probe_accelerator(timeout_s: int = 180) -> bool:
    """Whether the ambient accelerator backend initializes, checked in a
    throwaway subprocess with a hard timeout — the tunneled TPU can HANG
    backend init when the link is down, which would otherwise hang the
    whole bench. On False the bench forces CPU so a JSON line is always
    produced."""
    import os
    import subprocess

    if os.environ.get("JAX_PLATFORMS") == "cpu" \
            and not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return True  # plain CPU run: nothing to probe, fallback is a no-op
    code = "import jax; print(jax.devices()[0].platform)"
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s)
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def measure_host_decode(size=(299, 299), n_images: int = 64,
                        src_hw=(375, 500)) -> float:
    """images/sec through the fused decode→resize→pack reader on
    synthesized JPEGs (tf_flowers-like source dims), best of 2 passes
    (pass 1 also warms the page cache and builds the shim)."""
    import os
    import shutil
    import tempfile

    from PIL import Image

    from sparkdl_tpu.image import imageIO

    d = tempfile.mkdtemp(prefix="sparkdl_bench_decode_")
    try:
        rng = np.random.default_rng(7)
        for i in range(n_images):
            arr = rng.integers(0, 255, size=src_hw + (3,), dtype=np.uint8)
            Image.fromarray(arr, "RGB").save(
                os.path.join(d, f"i{i:03d}.jpg"), quality=90)
        df = imageIO.readImagesPacked(d, size, numPartitions=4)
        rates = []
        for _ in range(2):
            t0 = time.perf_counter()
            table = df.collect()
            rates.append(table.num_rows / (time.perf_counter() - t0))
        return float(max(rates))
    finally:
        shutil.rmtree(d, ignore_errors=True)


def main() -> None:
    if not _probe_accelerator():
        import jax
        jax.config.update("jax_platforms", "cpu")
        print("accelerator backend unavailable; benching on CPU",
              file=sys.stderr)
    import jax
    try:
        # persistent XLA cache: repeat bench runs skip the multi-minute
        # InceptionV3 compile (single-core CPU fallback especially)
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/sparkdl_tpu_jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)
    except Exception:
        pass

    from sparkdl_tpu.models.zoo import getModelFunction
    from sparkdl_tpu.runtime.runner import BatchRunner
    from sparkdl_tpu.utils.measure import (
        measure_device_resident,
        measure_link,
    )

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    # 128: best measured device-resident batch (sweep 2026-07-30 @16
    # batches: 128→6425, 256→6103, 512→6187 img/s); e2e is link-bound
    # at any batch size
    batch_size = 128 if on_tpu else 8
    n_rows = batch_size * (4 if on_tpu else 2)

    mf = getModelFunction("InceptionV3", featurize=True)
    link = measure_link(32 if on_tpu else 8)
    # 16 batches: the timed window must amortize per-call dispatch
    # latency (RPC on the tunneled platform) — measured 4651 img/s at 4
    # batches vs 6425 at 16 for the same program (sweep 2026-07-30)
    device = measure_device_resident(mf, batch_size,
                                     n_batches=16 if on_tpu else 2)

    rng = np.random.default_rng(0)
    images = rng.integers(0, 255, size=(n_rows, 299, 299, 3),
                          dtype=np.uint8)
    runner = BatchRunner(mf, batch_size=batch_size)
    runner.run({"image": images[:batch_size]})  # steady-state warmup

    # Median of 3 passes: the tunneled link's throughput varies
    # several-x between minutes; the median is robust to one contended
    # pass without overstating sustained throughput.
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = runner.run({"image": images})
        elapsed = time.perf_counter() - t0
        assert out["features"].shape == (n_rows, 2048), \
            out["features"].shape
        rates.append(n_rows / elapsed)
    e2e_ips = float(np.median(rates))

    # packed path: ship small uint8, resize on device (fused). The only
    # in-env lever on the link-bound headline — bytes/image shrinks
    # (150²/299²≈¼) so the ceiling and the measured value lift together.
    from sparkdl_tpu.transformers.utils import deviceResizeModel
    packed_src = (150, 150)
    runner_packed = BatchRunner(deviceResizeModel(mf, packed_src),
                                batch_size=batch_size)
    images_small = rng.integers(
        0, 255, size=(n_rows,) + packed_src + (3,), dtype=np.uint8)
    runner_packed.run({"image": images_small[:batch_size]})  # warmup
    rates_packed = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = runner_packed.run({"image": images_small})
        elapsed = time.perf_counter() - t0
        assert out["features"].shape == (n_rows, 2048)
        rates_packed.append(n_rows / elapsed)
    packed_ips = float(np.median(rates_packed))

    host_decode_ips = measure_host_decode(
        n_images=64 if on_tpu else 24)

    image_mb = 299 * 299 * 3 / (1024.0 * 1024.0)  # uint8 NHWC on the wire
    packed_mb = packed_src[0] * packed_src[1] * 3 / (1024.0 * 1024.0)
    ceiling = link["h2d_MBps"] / image_mb
    ceiling_packed = link["h2d_MBps"] / packed_mb
    print(json.dumps({
        "metric": (f"images_per_sec_per_chip_inceptionv3_featurize"
                   f"[{platform}]"),
        "value": round(e2e_ips, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(e2e_ips / PER_CHIP_TARGET, 3),
        "device_resident_ips": device["ips"],
        "device_tflops": round(
            device["ips"] * INCEPTION_GFLOPS / 1000.0, 2),
        "vs_baseline_device_resident": round(
            device["ips"] / PER_CHIP_TARGET, 3),
        "link_h2d_MBps": link["h2d_MBps"],
        "link_d2h_MBps": link["d2h_MBps"],
        "host_fed_ceiling_ips": round(ceiling, 1),
        "value_packed": round(packed_ips, 1),
        "vs_baseline_packed": round(packed_ips / PER_CHIP_TARGET, 3),
        "packed_src_hw": list(packed_src),
        "host_fed_ceiling_ips_packed": round(ceiling_packed, 1),
        "host_decode_ips": round(host_decode_ips, 1),
        "runner_strategy": runner.strategy,
        "note": ("end-to-end is host-link-bound when value ~= "
                 "host_fed_ceiling_ips; value_packed ships "
                 "device-resized small uint8 (~4x fewer bytes/image); "
                 "device_resident_ips is the chip's compute capability "
                 "with transfers excluded; host_decode_ips is the fused "
                 "JPEG decode-resize-pack reader"),
    }))


if __name__ == "__main__":
    sys.exit(main())
