"""North-star benchmark: InceptionV3 DeepImageFeaturizer throughput.

Output contract (since the r05 tail-window truncation): the FULL
result — every key below — is written as a JSON file to
``SPARKDL_TPU_BENCH_RESULT`` (default ``bench_result.json``), and the
LAST stdout line is a compact (<1,200-char) headline carrying the
top-line numbers plus ``result_path`` — small enough for the driver's
2,000-char stdout tail window to always parse. ``tools/ci.sh``'s
schema gates read the result file.

The full result reports:

* ``value`` — the FULL measured pipeline, images/sec/chip: JPEG files
  on disk → fused native decode/resize/pack (4:2:0 planes) on engine
  host threads → ship → device-reconstructed featurize, ONE stream.
  This is the north-star metric's true shape (BASELINE.md: "end-to-end
  InceptionV3 featurization over a 1M-row image DataFrame" INCLUDES
  read+decode). Rounds 1–4 headlined the pre-decoded full-res
  transfer shape instead; that number continues as
  ``value_fullres_transfer`` for cross-round comparability, and the
  shape change is recorded here and in BASELINE.md.
* ``value_fullres_transfer`` — host-fed images/sec/chip through the
  production ``BatchRunner`` from PRE-DECODED uint8 299² NHWC host
  arrays (the rounds-1–4 ``value``): transfer-bound on this link, no
  decode included.
* ``device_resident_ips`` / ``device_tflops`` — the same program timed
  with device-resident input and a forced-sync readback: the chip's
  compute-side capability with host↔device transfer excluded.
* ``link_h2d_MBps`` / ``link_d2h_MBps`` — measured host↔device
  bandwidth, and ``host_fed_ceiling_ips`` — the hard upper bound the
  link imposes on ANY host-fed pipeline (bandwidth ÷ bytes/image).
* ``value_packed`` — end-to-end with the byte-shrunk payload
  (VERDICT r2 next #3): the host packs uint8 at a smaller source size
  (``packed_src_hw``) and bilinear resize to 299² runs ON DEVICE,
  fused into the same XLA program (``deviceResizeFrom`` mode) — the
  wire carries ~4× fewer bytes/image, lifting the link ceiling
  (``host_fed_ceiling_ips_packed``) in proportion.
* ``host_decode_ips`` — the fused decode→resize→pack reader
  (``readImagesPacked``, native libjpeg+OpenMP shim) measured on
  synthesized TEXTURED JPEGs (photo-like compressibility): proof the
  host decode stage outruns the device featurize rate budgeted in
  SURVEY §6.
* ``value_packed420`` / ``host_fed_ceiling_ips_packed420`` — the
  payload halved again (VERDICT r4 next #1): planar YCbCr 4:2:0 at
  1.5 B/px shipped, chroma upsample + BT.601 reconstruction + resize
  fused on-device (``packedFormat="yuv420"``).
* ``value_packed420_fullres`` — the NO-resolution-loss packed shape:
  298² 4:2:0 planes (even-dims; ~133 KB/img, half the 299² RGB
  payload) device-resized the 1px to the model's 299² — for pipelines
  that must not trade source resolution for link bytes.
* ``value_pipeline`` — same number as ``value`` (kept under the round
  2–4 key so round-over-round tooling reads continuously);
  ``pipeline_bound_by`` names the stage (decode | link | compute)
  whose own measured ceiling binds it.
* ``serve`` — the online-serving shape (docs/SERVING.md): concurrent
  sub-batch requests through the ModelServer's dynamic micro-batching
  front-end — offered vs achieved rows/sec, mean batch fill ratio,
  p99 request latency, rejection/deadline-miss/failure counts.
  tools/ci.sh gates the schema and (armed) the fill ratio +
  serve-lane trace.
* ``tails`` — per-request tail attribution (docs/OBSERVABILITY.md):
  the serve pass runs with the request log armed, and the measured
  request p50/p99 plus the p99 specimen's phase breakdown
  (queue/coalesce/staging/device/reassembly) come from the recorded
  timelines. tools/ci.sh gates the schema and the ≥95% attribution
  bar.
* ``bound`` — the live roofline (sparkdl_tpu/obs/ledger.py,
  docs/PERFORMANCE.md): one utilization-ledger window over the
  measured pipeline pass — per-stage utilization fractions
  (decode/link/compute/serve), the continuous ``bound_by`` verdict
  with its headroom, the probed/injected ceilings, and the offline
  ceilings-based twin. ``pipeline_bound_by`` itself is re-derived
  through the SAME ``ledger.attribute()`` call, so the offline and
  live verdicts are one code path. tools/ci.sh gates the schema,
  the [0,1] bounds, and verdict == max-utilization stage.
* ``compile`` — compile forensics (docs/OBSERVABILITY.md,
  obs/compile_log.py): the run's jit compiles per function with wall
  time, cost/memory analysis, retrace attribution (a diff naming the
  argument that moved), and the steady-state zero-retrace verdict
  (``unexpected_retraces`` — the warmed serve pass must report 0);
  ``device_gflops_ceiling`` is the model-calibrated compute roofline
  the ledger's ``compute_basis`` divides by. tools/ci.sh gates the
  schema, the clean-pass zero, and an injected off-ladder shape
  showing the attributed retrace.
* ``autotune`` — the closed-loop infeed autotuner
  (sparkdl_tpu/autotune, docs/PERFORMANCE.md): tuned-vs-fixed
  throughput with the baseline's recorded noise band, decision /
  oscillation / clamp counts, and the converged knob config.
  tools/ci.sh gates schema + convergence (settled, zero
  oscillations, no loss outside the band).

Separating these is the point (round-1 lesson): on a tunneled TPU the
link moves ~10-35 MB/s, capping end-to-end at ~40-134 img/s regardless
of the device program, while the device program itself runs thousands
of img/s. ``vs_baseline`` stays honest (end-to-end vs the 1,250
img/s/chip target = 10k/s ÷ 8 chips, BASELINE.md) and the extra keys
attribute any gap to link vs compute.

Sync methodology: ``jax.block_until_ready`` returns at enqueue on the
tunneled platform, so timing forces a tiny dependent readback instead.

The ``"obs"`` block carries the unified observability layer's output
(docs/OBSERVABILITY.md): the metrics-registry snapshot always, plus
the exported Perfetto trace path/span count when ``SPARKDL_TPU_TRACE=1``
armed the run (``SPARKDL_TPU_TRACE_EXPORT`` names the path).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

PER_CHIP_TARGET = 1250.0  # 10k img/s ÷ 8 chips (BASELINE.md)
INCEPTION_GFLOPS = 11.5   # fwd FLOPs per 299x299 image (SURVEY §6)

# SPARKDL_TPU_BENCH_TINY=1: the CI smoke shape — TestNet instead of
# InceptionV3, tiny corpora, same JSON contract. tools/ci.sh runs this
# under JAX_PLATFORMS=cpu and gates on the emitted schema (every key a
# round-over-round reader or the driver contract consumes must be
# present), so a bench refactor that drops pipeline_bound_by, a
# ceiling, or the host-copy counters fails CI instead of failing the
# next TPU round.
BENCH_TINY = os.environ.get("SPARKDL_TPU_BENCH_TINY") == "1"


def _probe_accelerator(timeout_s: int = 180) -> bool:
    """Whether the ambient accelerator backend initializes, checked in a
    throwaway subprocess with a hard timeout — the tunneled TPU can HANG
    backend init when the link is down, which would otherwise hang the
    whole bench. On False the bench forces CPU so a JSON line is always
    produced."""
    import os
    import subprocess

    if os.environ.get("JAX_PLATFORMS") == "cpu" \
            and not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return True  # plain CPU run: nothing to probe, fallback is a no-op
    code = "import jax; print(jax.devices()[0].platform)"
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s)
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def measure_host_decode(size=(299, 299), n_images: int = 64,
                        packedFormat: str = "rgb") -> float:
    """images/sec through the fused decode→resize→pack reader on a
    TEXTURED corpus (photo-like ~2 bits/pixel; round-3's noise JPEGs
    sat at ~7 bpp and understated throughput ~3× — VERDICT r3 weak #8),
    best of 2 passes (pass 1 warms the page cache, builds the shim)."""
    import shutil
    import tempfile

    from sparkdl_tpu.image import imageIO
    from sparkdl_tpu.utils.synth import write_textured_jpegs

    d = tempfile.mkdtemp(prefix="sparkdl_bench_decode_")
    try:
        write_textured_jpegs(d, n_images)
        df = imageIO.readImagesPacked(d, size, numPartitions=4,
                                      packedFormat=packedFormat)
        rates = []
        for _ in range(2):
            t0 = time.perf_counter()
            table = df.collect()
            rates.append(table.num_rows / (time.perf_counter() - t0))
        return float(max(rates))
    finally:
        shutil.rmtree(d, ignore_errors=True)


def measure_pipeline(mf, packed_src, batch_size: int,
                     n_images: int, packedFormat: str = "rgb") -> dict:
    """THE full-pipeline headline (VERDICT r3 next #1): JPEG files on
    disk → ``readImagesPacked(packed_src)`` (fused native
    decode→resize→pack on engine host threads) → device-resized
    featurize — ONE streamed pipeline, decode running ahead of device
    dispatch (host stages parallelize across partitions while the
    device stage serializes under the device lock). images/sec over the
    whole corpus, single pass per repeat, best of 2 (pass 1 is
    steady-state warmup for the jit + page cache). Returns the rate
    plus the runner's host-copy counters over both passes — the proof
    the ship path stages/copies what it claims and nothing more."""
    import shutil
    import tempfile

    from sparkdl_tpu.image import imageIO
    from sparkdl_tpu.transformers.tensor_transform import TensorTransformer
    from sparkdl_tpu.transformers.utils import deviceResizeModel, single_io
    from sparkdl_tpu.utils.synth import write_textured_jpegs

    d = tempfile.mkdtemp(prefix="sparkdl_bench_pipe_")
    try:
        write_textured_jpegs(d, n_images)
        mf_packed = deviceResizeModel(mf, packed_src,
                                      packedFormat=packedFormat)
        in_name, out_name = single_io(mf_packed)
        t = TensorTransformer(modelFunction=mf_packed,
                              inputMapping={"image": in_name},
                              outputMapping={out_name: "features"},
                              batchSize=batch_size)
        # partition count is deliberately batch-MISALIGNED: the engine's
        # cross-partition re-chunking (Stage.batch_hint) feeds the
        # device stage batch-aligned blocks regardless, so the 2.4×
        # small-partition padding tax of rounds ≤4 no longer applies
        # (r4 measured 130 img/s at 32-row partitions vs ~310 aligned;
        # the old workaround sized partitions to the batch)
        parts = 8
        rates = []
        for _ in range(2):
            df = imageIO.readImagesPacked(d, packed_src,
                                          numPartitions=parts,
                                          packedFormat=packedFormat)
            out = t.transform(df)
            n = 0
            t0 = time.perf_counter()
            for b in out.stream():
                n += b.num_rows
            elapsed = time.perf_counter() - t0
            assert n == n_images, (n, n_images)
            rates.append(n / elapsed)
        m = t.metrics
        # the measured pipeline's ship counters also land in the obs
        # registry so the bench "obs" block carries them
        from sparkdl_tpu.obs import default_registry
        m.publish(default_registry())
        return {"ips": float(max(rates)),
                "bytes_staged": int(m.bytes_staged),
                "bytes_copied": int(m.bytes_copied),
                "transfer_wait_s": round(m.transfer_wait_seconds, 4)}
    finally:
        shutil.rmtree(d, ignore_errors=True)


def measure_pipeline_overlap(mf, packed_src, batch_size: int,
                             n_images: int,
                             packedFormat: str = "rgb") -> dict:
    """The parallel host pipeline's proof block (ROADMAP item 3,
    docs/PERFORMANCE.md "Parallel host pipeline"): the SAME
    disk→decode→ship→featurize pipeline as :func:`measure_pipeline`,
    measured twice on ONE corpus — once through the serial engine
    (``pipeline_workers=0``) and once through the pooled engine
    (``SPARKDL_TPU_PIPELINE_WORKERS`` or 2) — plus the overlap proof:
    ``overlap_ratio = (decode_busy + ship_busy) / wall`` over the
    pooled pass's best timed run. Ratio > 1 is only possible when
    decode genuinely overlaps ship/dispatch; on a 1-core host the
    pooled path auto-degrades to serial (``mode: "serial"``) and the
    ratio honestly stays ≤ ~1. tools/ci.sh's pipeline gate reads this
    block."""
    import shutil
    import tempfile

    from sparkdl_tpu.data import pipeline as host_pipeline
    from sparkdl_tpu.data.engine import LocalEngine
    from sparkdl_tpu.image import imageIO
    from sparkdl_tpu.obs import default_registry
    from sparkdl_tpu.transformers.tensor_transform import TensorTransformer
    from sparkdl_tpu.transformers.utils import deviceResizeModel, single_io

    from sparkdl_tpu.utils.synth import write_textured_jpegs

    d = tempfile.mkdtemp(prefix="sparkdl_bench_overlap_")
    try:
        write_textured_jpegs(d, n_images)
        mf_packed = deviceResizeModel(mf, packed_src,
                                      packedFormat=packedFormat)
        in_name, out_name = single_io(mf_packed)
        reg = default_registry()

        def one_pass(engine):
            # best of 2 (pass 1 is jit/page-cache warmup), with the
            # best pass's busy/wall accounting for the overlap ratio
            best = None
            for _ in range(2):
                df = imageIO.readImagesPacked(
                    d, packed_src, numPartitions=8,
                    packedFormat=packedFormat, engine=engine)
                t = TensorTransformer(modelFunction=mf_packed,
                                      inputMapping={"image": in_name},
                                      outputMapping={out_name: "features"},
                                      batchSize=batch_size)
                out = t.transform(df)
                decode0 = reg.counter("engine.busy_seconds").value
                ship0 = reg.counter("device.run_seconds").value
                n = 0
                t0 = time.perf_counter()
                for b in out.stream():
                    n += b.num_rows
                wall = time.perf_counter() - t0
                assert n == n_images, (n, n_images)
                row = {
                    "ips": n / wall, "wall_s": wall,
                    "decode_busy_s":
                        reg.counter("engine.busy_seconds").value
                        - decode0,
                    "ship_busy_s":
                        reg.counter("device.run_seconds").value
                        - ship0,
                }
                if best is None or row["ips"] > best["ips"]:
                    best = row
            return best

        requested = host_pipeline.resolve_workers(None) or 2
        serial_engine = LocalEngine(pipeline_workers=0)
        pooled_engine = LocalEngine(pipeline_workers=requested)
        try:
            serial = one_pass(serial_engine)
            pooled = one_pass(pooled_engine)
        finally:
            serial_engine.shutdown()
            pooled_engine.shutdown()
        effective = host_pipeline.effective_workers(
            requested, pooled_engine.pipeline_mode, record=False)
        mode = (host_pipeline.state().get("mode") or "serial") \
            if effective >= 2 else "serial"
        ratio = (pooled["decode_busy_s"] + pooled["ship_busy_s"]) \
            / max(pooled["wall_s"], 1e-9)
        return {
            "workers": requested,
            "effective_workers": effective,
            "read_ahead": int(pooled_engine.pipeline_read_ahead),
            "mode": mode,
            "serial_ips": round(serial["ips"], 1),
            "pooled_ips": round(pooled["ips"], 1),
            "pooled_vs_serial": round(
                pooled["ips"] / max(serial["ips"], 1e-9), 3),
            "overlap_ratio": round(ratio, 3),
            "decode_busy_s": round(pooled["decode_busy_s"], 4),
            "ship_busy_s": round(pooled["ship_busy_s"], 4),
            "wall_s": round(pooled["wall_s"], 4),
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def measure_fidelity(mf, packed_src, n_images: int = 32) -> dict:
    """Quantify what the packed-ship headline shape costs in feature
    fidelity (VERDICT r4 #2): the same JPEG corpus featurized through
    (a) full decode→native-res RGB and (b) the ``packed_src`` yuv420
    ship + fused device reconstruct/resize, compared row-wise by
    cosine.

    THREE numbers, because the raw cosine alone is vacuous under this
    env's seeded-random weights: random-BN features share a large
    constant component, so DIFFERENT images already cosine ~0.998 —
    any pipeline would "score" 1.0. ``centered`` subtracts each path's
    corpus-mean feature first (the discriminative part that transfer
    learning actually consumes), and ``cross_image_centered_baseline``
    is the same metric between MISMATCHED rows — the floor the path
    cosine must clear to mean anything (measured ~0.03 vs ~0.999
    same-image). End-accuracy parity on the capstone task is pinned in
    tests/test_integration_capstone.py::test_packed_ship_fidelity."""
    import shutil
    import tempfile

    from sparkdl_tpu.image import imageIO
    from sparkdl_tpu.runtime.runner import BatchRunner
    from sparkdl_tpu.transformers.utils import deviceResizeModel, single_io
    from sparkdl_tpu.utils.synth import write_textured_jpegs

    in_name, out_name = single_io(mf)
    (h, w, _c), _ = mf.input_signature[in_name]
    d = tempfile.mkdtemp(prefix="sparkdl_bench_fid_")
    try:
        write_textured_jpegs(d, n_images)
        full = imageIO.readImagesPacked(d, (h, w),
                                        numPartitions=2).tensor("image")
        packed = imageIO.readImagesPacked(
            d, packed_src, numPartitions=2,
            packedFormat="yuv420").tensor("image")
        fa = BatchRunner(mf, batch_size=n_images).run(
            {in_name: full})[out_name]
        mfp = deviceResizeModel(mf, packed_src, packedFormat="yuv420")
        fb = BatchRunner(mfp, batch_size=n_images).run(
            {in_name: packed})[out_name]
        fa = np.asarray(fa).reshape(n_images, -1)
        fb = np.asarray(fb).reshape(n_images, -1)

        def cos_rows(a, b):
            return (a * b).sum(1) / np.maximum(
                np.linalg.norm(a, axis=1) * np.linalg.norm(b, axis=1),
                1e-9)

        cos = cos_rows(fa, fb)
        ca, cb = fa - fa.mean(0), fb - fb.mean(0)
        cen = cos_rows(ca, cb)
        base = cos_rows(ca, np.roll(cb, 1, axis=0))
        return {"feature_cosine_mean": round(float(cos.mean()), 4),
                "feature_cosine_min": round(float(cos.min()), 4),
                "centered_cosine_mean": round(float(cen.mean()), 4),
                "centered_cosine_min": round(float(cen.min()), 4),
                "cross_image_centered_baseline": round(
                    float(base.mean()), 4),
                "paths": f"decode->{h}x{w} RGB vs {packed_src[0]}x"
                         f"{packed_src[1]} yuv420 ship + device resize"}
    finally:
        shutil.rmtree(d, ignore_errors=True)


def measure_serve(mf, batch_size: int, n_requests: int,
                  rows_per_request: int, threads: int = 4) -> tuple:
    """The online-serving shape (docs/SERVING.md): a ModelServer over
    the production BatchRunner, hammered by concurrent submitter
    threads at offered load above the bounded queue's capacity.
    Reports offered vs achieved rows/sec, the mean batch fill ratio
    (what dynamic micro-batching exists to maximize), p99 request
    latency, and the rejection count — the backpressure contract made
    a number instead of an assertion. Requests are sized at a fraction
    of the device batch so the achieved rate is earned by coalescing,
    not by callers pre-batching; a couple of OVERSIZED requests ride
    along so the tails block sees the split-and-reassemble path.

    Returns ``(serve_block, tails_block)``: the request log is armed
    for the measurement window, so every request records a phase
    timeline and the ``"tails"`` block attributes the measured p99
    across phases (tails_from_records)."""
    import threading as th

    from sparkdl_tpu.obs.request_log import request_log, tails_from_records
    from sparkdl_tpu.serve import ModelServer, ServeConfig, ServerOverloaded

    in_name = mf.input_names[0]
    shape, dtype = mf.input_signature[in_name]
    server = ModelServer(ServeConfig(
        max_wait_s=0.05,
        max_queue_rows=max(batch_size * 8,
                           rows_per_request * threads * 2)))
    server.register("bench", mf, batch_size=batch_size)
    server.warmup()

    rlog = request_log()
    # save the OVERRIDE, not the derived armed bit: an env/tracer-armed
    # log must come back override-free (a stuck override would outlive
    # the tracer's disarm), and a caller's explicit disarm must survive
    # this measurement (the flight.autoarm override-inspection precedent)
    rlog_override = rlog._override
    rlog.arm()
    rlog.clear()

    futures, lock = [], th.Lock()

    def fire(tid: int):
        rng = np.random.default_rng(tid)
        x = rng.integers(0, 255, (rows_per_request,) + tuple(shape)
                         ).astype(dtype)
        for _ in range(n_requests):
            try:
                f = server.submit({in_name: x})
            except ServerOverloaded:
                pass    # counted by ServeMetrics.rejections
            else:
                with lock:
                    futures.append(f)

    workers = [th.Thread(target=fire, args=(t,)) for t in range(threads)]
    t0 = time.perf_counter()
    for w in workers:
        w.start()
    # the split-path specimens: two requests larger than the device
    # batch, so the tails block covers reassembled multi-batch flows
    rng = np.random.default_rng(99)
    big = rng.integers(0, 255, (batch_size + rows_per_request,)
                       + tuple(shape)).astype(dtype)
    for _ in range(2):
        try:
            f = server.submit({in_name: big})
        except ServerOverloaded:
            pass
        else:
            with lock:
                futures.append(f)
    for w in workers:
        w.join()
    # offered load is a SUBMISSION-side rate: clocked at worker join,
    # before the result drain — folding the drain into it would pull
    # offered toward achieved and erase exactly the gap this block
    # exists to report
    submit_elapsed = max(time.perf_counter() - t0, 1e-9)
    completed_rows = 0
    for f in futures:
        out = f.result()
        completed_rows += len(next(iter(out.values())))
    elapsed = time.perf_counter() - t0
    server.close()
    tails = tails_from_records(rlog.records())
    rlog._override = rlog_override
    m = server.metrics.as_dict()
    offered_rows = (threads * n_requests * rows_per_request
                    + 2 * len(big))
    serve = {
        "offered_rows_per_s": round(offered_rows / submit_elapsed, 1),
        "achieved_rows_per_s": round(completed_rows / elapsed, 1),
        "requests": m["requests"],
        "rows": m["rows"],
        "batches": m["batches"],
        "batch_fill_ratio": m["batch_fill_ratio"],
        "p99_latency_ms": m["latency_p99_ms"],
        "rejections": m["rejections"],
        "deadline_misses": m["deadline_misses"],
        "failures": m["failures"]}
    return serve, tails


def measure_autotune(mf, batch_size: int, n_rows: int) -> dict:
    """The closed-loop infeed autotuner's acceptance shape
    (docs/PERFORMANCE.md): a RunnerTarget-tuned prefetch runner vs the
    fixed ``host_async`` expert default, same model, same rows.

    Phases: (1) baseline — 3 passes through the static host_async
    runner; the pass-to-pass spread is the recorded noise band the
    tuned number is judged inside (the tunneled link legitimately
    moves several-x between minutes, so a single-point comparison
    would be theater). (2) settle — the armed controller steps on
    every pass (interval 0) while the tuned runner runs its warmup +
    settle window; trials/reverts happen HERE. (3) converged — timed
    passes with the decision counter snapshotted around them:
    ``changes_after_warmup`` and ``oscillations`` are what tools/ci.sh
    gates (a controller that keeps hunting after its settle window is
    worse than no controller)."""
    from sparkdl_tpu.autotune import RunnerTarget, controller
    from sparkdl_tpu.obs import default_registry
    from sparkdl_tpu.runtime.runner import BatchRunner

    in_name = mf.input_names[0]
    shape, dtype = mf.input_signature[in_name]
    rng = np.random.default_rng(7)
    x = rng.integers(0, 255, (n_rows,) + tuple(shape)).astype(dtype)
    warm = {in_name: x[:batch_size]}
    full = {in_name: x}

    def passes(runner, n):
        rates = []
        for _ in range(n):
            t0 = time.perf_counter()
            runner.run(full)
            rates.append(n_rows / (time.perf_counter() - t0))
        return rates

    baseline = BatchRunner(mf, batch_size=batch_size,
                           strategy="host_async")
    baseline.run(warm)                      # compile warmup
    base_rates = passes(baseline, 3)
    baseline_ips = float(max(base_rates))
    noise_band = (max(base_rates) - min(base_rates)) / max(base_rates)

    ctl = controller()
    reg = default_registry()
    # the tuned runner starts from the PLATFORM default strategy (the
    # config a user who set nothing gets — host_async on the tunnel,
    # where it coincides with the fixed comparator's family): the
    # controller's job is to beat-or-match the default it inherits,
    # not a hand-picked shape. prefetch-depth tuning is pinned in
    # tests/test_autotune.py and measured by measure_transfer --sweep.
    tuned = BatchRunner(mf, batch_size=batch_size)
    try:
        ctl.attach(RunnerTarget(tuned))
        ctl.arm(interval_s=0.0)             # step on every pass
        tuned.run(warm)                     # compile warmup
        # settle window: long enough for BOTH overlap knobs to run a
        # full explore→evaluate(→revert+freeze) trial before the timed
        # passes — the convergence gate counts changes AFTER this
        passes(tuned, 6)
        decisions_before = reg.counter("autotune.decisions").value
        tuned_rates = passes(tuned, 3)
        changes_after = (reg.counter("autotune.decisions").value
                         - decisions_before)
        state = ctl.state()
    finally:
        ctl.reset()                         # detach + follow the env
    return {
        "armed": True,
        "strategy": tuned.strategy,
        "baseline_strategy": baseline.strategy,
        "baseline_ips": round(baseline_ips, 1),
        "tuned_ips": round(float(max(tuned_rates)), 1),
        "noise_band_pct": round(noise_band * 100.0, 1),
        "decisions": int(state["decisions"]),
        "changes_after_warmup": int(changes_after),
        "oscillations": int(state["oscillations"]),
        "clamps": int(state["clamps"]),
        "steps": int(state["steps"]),
        "converged": {
            "max_inflight": int(tuned.max_inflight),
            "prefetch_depth": int(tuned.prefetch_depth),
        },
    }


def measure_ship_ring(mf, batch_size: int, n_rows: int) -> dict:
    """The device-resident infeed ring's acceptance shape
    (docs/PERFORMANCE.md "Infeed ring & transfer interleave"): a
    repeated-corpus steady pass through a ringed prefetch runner vs
    the same runner with no ring, same model, same rows. The ring is
    sized to hold the whole corpus (depth = corpus chunks, floored at
    2) — the shape serving steady traffic actually sees, and the one
    the zero-re-ship guarantee is defined over. tools/ci.sh gates:
    ``steady_bytes_reshipped == 0``, ``steady_bytes_shipped == 0``
    (every steady byte served from resident HBM),
    ``unexpected_retraces == 0`` (the donated program compiled at
    warmup, never at a steady request), and ring_ips against the
    no-ring baseline inside the same noise discipline as
    measure_autotune."""
    from sparkdl_tpu.obs import default_registry
    from sparkdl_tpu.runtime.runner import BatchRunner, warmup_runner

    in_name = mf.input_names[0]
    shape, dtype = mf.input_signature[in_name]
    rng = np.random.default_rng(7)
    x = rng.integers(0, 255, (n_rows,) + tuple(shape)).astype(dtype)
    full = {in_name: x}
    corpus_chunks = -(-n_rows // batch_size)
    depth = max(2, corpus_chunks)
    reg = default_registry()

    def passes(runner, n):
        rates = []
        for _ in range(n):
            t0 = time.perf_counter()
            runner.run(full)
            rates.append(n_rows / (time.perf_counter() - t0))
        return rates

    baseline = BatchRunner(mf, batch_size=batch_size,
                           strategy="prefetch")
    warmup_runner(baseline)
    base_rates = passes(baseline, 3)
    baseline_ips = float(max(base_rates))
    noise_band = (max(base_rates) - min(base_rates)) / max(base_rates)
    # the no-ring pass re-ships the whole corpus every time — the
    # per-pass link traffic the ring's steady pass is gated to kill
    s0 = reg.counter("ship.bytes_shipped").value
    baseline.run(full)
    baseline_bytes = reg.counter("ship.bytes_shipped").value - s0

    ringed = BatchRunner(mf, batch_size=batch_size,
                         strategy="prefetch", infeed_ring=depth)
    warmup_runner(ringed)
    ringed.run(full)                         # fill pass (ships once)
    retr0 = reg.counter("compile.unexpected_retraces").value
    h0 = reg.counter("ship.ring_hits").value
    r0 = reg.counter("ship.bytes_reshipped").value
    s0 = reg.counter("ship.bytes_shipped").value
    res0 = reg.counter("ship.bytes_resident").value
    ring_rates = passes(ringed, 3)
    return {
        "batch": int(batch_size),
        "rows": int(n_rows),
        "ring_depth": int(ringed.infeed_ring),
        "corpus_chunks": int(corpus_chunks),
        "baseline_ips": round(baseline_ips, 1),
        "ring_ips": round(float(max(ring_rates)), 1),
        "noise_band_pct": round(noise_band * 100.0, 1),
        "baseline_bytes_per_pass": int(baseline_bytes),
        "steady_bytes_shipped": int(
            reg.counter("ship.bytes_shipped").value - s0),
        "steady_bytes_reshipped": int(
            reg.counter("ship.bytes_reshipped").value - r0),
        "steady_ring_hits": int(
            reg.counter("ship.ring_hits").value - h0),
        "steady_bytes_resident": int(
            reg.counter("ship.bytes_resident").value - res0),
        "unexpected_retraces": int(
            reg.counter("compile.unexpected_retraces").value - retr0),
        "ring_state": ringed.ring_state(),
    }


def measure_input_service(n_rows: int = 4096,
                          n_partitions: int = 8) -> dict:
    """The disaggregated input service's acceptance shape
    (docs/DATA_SERVICE.md): the SAME decode plan over ONE synthetic
    corpus run three ways — local pooled decode, a one-worker remote
    decode fleet (in-process ``DecodeServer`` over the real socket
    transport), and a two-worker fleet — plus the snapshot tier's
    epoch amortization: a cold snapshot epoch (decode + persist) vs a
    warm epoch (stream packed chunks straight off disk), with the warm
    pass's ``engine.busy_seconds`` delta as the decode-work proof.
    tools/ci.sh's input-service gate re-proves the warm-busy ≈ 0 and
    row-identity claims in a two-process drill; this block carries the
    measured rows/s so bench_compare can track regressions."""
    import shutil
    import tempfile

    import pyarrow as pa
    import pyarrow.compute as pc

    from sparkdl_tpu.data.engine import LocalEngine
    from sparkdl_tpu.data.frame import DataFrame
    from sparkdl_tpu.inputsvc import DecodeServer
    from sparkdl_tpu.obs import default_registry

    reg = default_registry()
    table = pa.table({
        "id": pa.array(range(n_rows), type=pa.int64()),
        "x": pa.array([float(i % 997) for i in range(n_rows)],
                      type=pa.float64()),
    })

    def plan(df):
        def work(batch):
            i = batch.schema.get_field_index("x")
            col = batch.column("x")
            for _ in range(8):           # give decode measurable work
                col = pc.add(pc.multiply(col, 1.0000001), 0.5)
            return batch.set_column(i, "x", col)
        return df.map_batches(work, name="bench_decode")

    def timed_collect(engine):
        df = plan(DataFrame.from_table(table, n_partitions, engine))
        t0 = time.perf_counter()
        out = df.collect()
        wall = time.perf_counter() - t0
        assert out.num_rows == n_rows, (out.num_rows, n_rows)
        return n_rows / max(wall, 1e-9)

    local_engine = LocalEngine()
    try:
        local_ips = max(timed_collect(local_engine) for _ in range(2))
    finally:
        local_engine.shutdown()

    servers = [DecodeServer().start() for _ in range(2)]
    fleet = [f"127.0.0.1:{s.port}" for s in servers]
    remote = {}
    try:
        for width in (1, 2):
            eng = LocalEngine(inputsvc_endpoints=fleet[:width])
            try:
                remote[width] = max(timed_collect(eng)
                                    for _ in range(2))
            finally:
                eng.shutdown()
    finally:
        for s in servers:
            s.close()

    snap_root = tempfile.mkdtemp(prefix="sparkdl_bench_snap_")
    snap_engine = LocalEngine()
    try:
        base = plan(DataFrame.from_table(table, n_partitions,
                                         snap_engine))

        def epoch():
            busy0 = reg.counter("engine.busy_seconds").value
            df = base.snapshot(snap_root, fingerprint="bench-corpus")
            t0 = time.perf_counter()
            out = df.collect()
            wall = time.perf_counter() - t0
            assert out.num_rows == n_rows
            busy = reg.counter("engine.busy_seconds").value - busy0
            return n_rows / max(wall, 1e-9), busy

        cold_ips, cold_busy = epoch()
        warm_ips, warm_busy = epoch()
    finally:
        snap_engine.shutdown()
        shutil.rmtree(snap_root, ignore_errors=True)

    counters = reg.snapshot()
    return {
        "rows": int(n_rows),
        "partitions": int(n_partitions),
        "local_ips": round(local_ips, 1),
        "remote_ips_1worker": round(remote[1], 1),
        "remote_ips_2workers": round(remote[2], 1),
        "remote_vs_local_1worker": round(
            remote[1] / max(local_ips, 1e-9), 3),
        "remote_vs_local_2workers": round(
            remote[2] / max(local_ips, 1e-9), 3),
        "snapshot_cold_ips": round(cold_ips, 1),
        "snapshot_warm_ips": round(warm_ips, 1),
        "snapshot_warm_vs_cold": round(
            warm_ips / max(cold_ips, 1e-9), 3),
        # the amortization proof: a warm epoch streams packed chunks,
        # it does not re-run decode — this must read ~0 while the cold
        # epoch's busy covers the whole corpus
        "cold_decode_busy_s": round(cold_busy, 4),
        "warm_decode_busy_s": round(warm_busy, 4),
        "rpc_errors": int(counters.get("inputsvc.rpc_errors", 0)),
        "local_failovers": int(
            counters.get("inputsvc.local_decodes", 0)),
        "snapshot_hits": int(
            counters.get("inputsvc.snapshot_hits", 0)),
        "snapshot_misses": int(
            counters.get("inputsvc.snapshot_misses", 0)),
    }


def measure_fleet(batch_size: int = 16) -> dict:
    """The fleet control plane's acceptance numbers (docs/SERVING.md
    "Fleet control plane"): on one small synthetic model,

    * **swap latency** — deploy at 2 replicas, hot-swap the weights
      (``ModelRegistry.swap_weights``: stage → flip → zero-retrace
      probe) and report the measured wall plus the output-flip and
      zero-``unexpected_retraces`` proofs;
    * **cold vs warm first request** — the same signature deployed
      cold (empty warm-start cache: first request pays the compile)
      and then fresh into a NEW server from the now-populated cache
      (AOT deserialize: ``compiles_of`` must read ZERO). ci.sh's
      step-22 drill re-proves this across a real process boundary;
      this block carries the measured milliseconds;
    * **packing decision** — the live planner's verdict for this
      model at 2 replicas against the measured/assumed device budgets
      (the same plan tools/fleet_pack.py prints).
    """
    import shutil
    import tempfile

    from sparkdl_tpu.fleet import ModelRegistry, WarmStartCache
    from sparkdl_tpu.fleet.placement import (estimate_footprint,
                                             plan_placement)
    from sparkdl_tpu.graph.function import ModelFunction
    from sparkdl_tpu.obs.compile_log import compile_log
    from sparkdl_tpu.serve import ModelServer, ServeConfig

    dim = 8

    def apply(params, inputs):
        return {"y": inputs["x"] @ params["w"]}

    def fresh_mf(name: str, scale: float) -> ModelFunction:
        params = {"w": (scale * np.eye(dim)).astype(np.float32)}
        return ModelFunction(apply, params,
                             {"x": ((dim,), np.float32)}, ["y"],
                             name=name)

    x = np.ones((batch_size, dim), np.float32)
    cache_root = tempfile.mkdtemp(prefix="sparkdl_bench_fleet_")
    clog = compile_log()
    out: dict = {}
    try:
        cache = WarmStartCache(cache_root)
        server = ModelServer(ServeConfig(max_wait_s=0.0))
        reg = ModelRegistry(server, warmstart=cache)
        try:
            # cold: empty cache, no warmup — the first request pays
            # the jit compile, and deploy persists the AOT blob
            reg.deploy("fleetcold", fresh_mf("fleetcold", 2.0),
                       batch_size=batch_size, replicas=1,
                       warmup=False)
            t0 = time.perf_counter()
            y = reg.submit({"x": x}, model="fleetcold").result()["y"]
            cold_ms = (time.perf_counter() - t0) * 1000.0
            assert float(np.asarray(y)[0, 0]) == 2.0, y[0, 0]

            # in-process scale-out: replica r1 warm-starts from the
            # blob the cold deploy just persisted
            reg.scale("fleetcold", 2)

            # the swap: same shapes, new values — flip under load
            # machinery, probe for retraces, report the wall
            retraces0 = clog.unexpected_retraces
            reg.swap_weights("fleetcold",
                             {"w": (3.0 * np.eye(dim)
                                    ).astype(np.float32)})
            y2 = reg.submit({"x": x}, model="fleetcold").result()["y"]
            st = reg.state()
            out.update({
                "swap_ms": st["last_swap_ms"],
                "swap_output_flipped":
                    float(np.asarray(y2)[0, 0]) == 3.0,
                "swap_retraces":
                    clog.unexpected_retraces - retraces0,
                "swaps": st["swaps"],
                "swap_failures": st["swap_failures"],
            })
        finally:
            server.close()

        # warm: a NEW server + registry, a fresh same-signature
        # model — first request must deserialize, not compile
        server2 = ModelServer(ServeConfig(max_wait_s=0.0))
        reg2 = ModelRegistry(server2, warmstart=cache)
        try:
            reg2.deploy("fleetwarm", fresh_mf("fleetwarm", 5.0),
                        batch_size=batch_size, replicas=1,
                        warmup=False)
            t0 = time.perf_counter()
            y3 = reg2.submit({"x": x}).result()["y"]
            warm_ms = (time.perf_counter() - t0) * 1000.0
            assert float(np.asarray(y3)[0, 0]) == 5.0, y3[0, 0]
            out.update({
                "cold_first_request_ms": round(cold_ms, 2),
                "warm_first_request_ms": round(warm_ms, 2),
                "warm_vs_cold": round(warm_ms / max(cold_ms, 1e-9),
                                      3),
                "warm_compiles":
                    clog.compiles_of("fleetwarm@r0.jitted"),
                "warmstart": cache.state(),
            })
            # the packing decision for THIS model at 2 replicas,
            # against the live (or assumed) budgets
            fp = estimate_footprint(reg2.entry("fleetwarm").model_fn,
                                    batch_size)
            plan = plan_placement([fp],
                                  replicas={fp.name: 2})
            out["placement"] = {
                "footprint_bytes": fp.bytes,
                "footprint_source": fp.detail["source"],
                "mode": plan.mode[fp.name],
                "devices": plan.assignments[fp.name],
            }
        finally:
            server2.close()
    finally:
        shutil.rmtree(cache_root, ignore_errors=True)
    return out


_bench_done = None  # set by main(); threading.Event


def _start_watchdog(seconds: int = 2400, on_cpu: bool = False) -> None:
    """The tunneled TPU can STALL (not error) mid-run — without this,
    a stall at round end means no JSON line at all. After ``seconds``
    the watchdog prints a minimal contract line naming the failure and
    exits; a finished main() disarms it. The cause named in the line
    depends on the active backend — blaming a tunnel stall on a run
    that was already forced to CPU would misdirect whoever reads it."""
    import os
    import threading

    global _bench_done
    _bench_done = threading.Event()
    cause = ("CPU fallback run overran the budget (slow host or cold "
             "XLA cache; the persistent cache makes repeats faster)"
             if on_cpu else
             "tunneled TPU stall mid-run is the known cause")

    def run():
        if not _bench_done.wait(seconds):
            print(json.dumps({
                "metric": "images_per_sec_per_chip_inceptionv3_"
                          "featurize[stalled]",
                "value": None, "unit": "images/sec/chip",
                "vs_baseline": None,
                "error": f"bench watchdog: run exceeded {seconds}s "
                         f"({cause}; BASELINE.md records this round's "
                         "live v5e measurements)"}), flush=True)
            os._exit(3)

    threading.Thread(target=run, daemon=True).start()


def main() -> None:
    # FIRST: a wedged bench is exactly the flight recorder's use case —
    # SPARKDL_TPU_FLIGHT=1 must install the SIGUSR2 trigger + span
    # retention before any section that can stall, not at reporting time
    from sparkdl_tpu.obs import flight as obs_flight
    obs_flight.autoarm()
    # compile forensics are part of the bench contract (the "compile"
    # block + the ledger's model-specific compute ceiling both read
    # it) — armed for the whole run, before the first model builds.
    # The AOT cost-analysis pass this enables rides the persistent XLA
    # compilation cache configured below, so big programs compile once.
    from sparkdl_tpu.obs.compile_log import compile_log
    compile_log().arm()
    tpu_down = False
    if not _probe_accelerator():
        import jax
        tpu_down = True
        jax.config.update("jax_platforms", "cpu")
        print("accelerator backend unavailable; benching on CPU",
              file=sys.stderr)
    # CPU fallback legitimately takes ~30-40 min on a 1-core host
    # (InceptionV3 compiles + 6 img/s passes); the TPU run finishes in
    # minutes unless the tunnel stalls
    _start_watchdog(3600 if tpu_down else 2400, on_cpu=tpu_down)
    import jax
    try:
        # persistent XLA cache: repeat bench runs skip the multi-minute
        # InceptionV3 compile (single-core CPU fallback especially)
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/sparkdl_tpu_jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)
    except Exception:
        pass

    from sparkdl_tpu.models.zoo import getModelFunction
    from sparkdl_tpu.runtime.runner import BatchRunner
    from sparkdl_tpu.runtime.sanitize import armed_run_count, sanitize_enabled
    from sparkdl_tpu.utils.measure import (
        measure_device_resident,
        measure_host_copy,
        measure_link,
    )

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    # 128: best measured device-resident batch (sweep 2026-07-30 @16
    # batches: 128→6425, 256→6103, 512→6187 img/s); e2e is link-bound
    # at any batch size
    batch_size = 128 if on_tpu else 8
    n_rows = batch_size * (4 if on_tpu else 2)

    model_name = "TestNet" if BENCH_TINY else "InceptionV3"
    mf = getModelFunction(model_name, featurize=True)
    (src_h, src_w, _c), _ = mf.input_signature["image"]
    link = measure_link(32 if on_tpu else (4 if BENCH_TINY else 8))
    # 16 batches: the timed window must amortize per-call dispatch
    # latency (RPC on the tunneled platform) — measured 4651 img/s at 4
    # batches vs 6425 at 16 for the same program (sweep 2026-07-30)
    device = measure_device_resident(mf, batch_size,
                                     n_batches=16 if on_tpu else 2)

    # the host-copy micro-shape: PROOF (RunnerMetrics counters, not
    # assertion) that batch-aligned ship is zero-copy and only the
    # padded tail stages — the ship-side twin of the transfer-strategy
    # measurements
    host_copy = measure_host_copy(mf, batch_size,
                                  n_batches=4 if on_tpu else 2)

    def time_runner(runner, images, batch_size):
        """Warmup, then median of 3 full passes: the tunneled link's
        throughput varies several-x between minutes; the median is
        robust to one contended pass without overstating sustained
        throughput."""
        n = len(images)
        runner.run({"image": images[:batch_size]})  # steady-state warmup
        rates = []
        for _ in range(3):
            t0 = time.perf_counter()
            out = runner.run({"image": images})
            elapsed = time.perf_counter() - t0
            assert out["features"].shape[0] == n, \
                out["features"].shape
            rates.append(n / elapsed)
        return float(np.median(rates))

    rng = np.random.default_rng(0)
    images = rng.integers(0, 255, size=(n_rows, src_h, src_w, 3),
                          dtype=np.uint8)
    runner = BatchRunner(mf, batch_size=batch_size)
    e2e_ips = time_runner(runner, images, batch_size)

    # packed path: ship small uint8, resize on device (fused). The big
    # in-env lever on the link-bound headline — bytes/image shrinks
    # (150²/299²≈¼) so the ceiling and the measured value lift together.
    from sparkdl_tpu.transformers.utils import deviceResizeModel
    packed_src = (16, 16) if BENCH_TINY else (150, 150)
    images_small = rng.integers(
        0, 255, size=(n_rows,) + packed_src + (3,), dtype=np.uint8)
    packed_ips = time_runner(
        BatchRunner(deviceResizeModel(mf, packed_src),
                    batch_size=batch_size),
        images_small, batch_size)

    # 4:2:0 packed path (VERDICT r4 next #1): planar YCbCr payload at
    # 1.5 B/px — HALF the RGB packed bytes — reconstructed+resized on
    # device fused into the model program.
    from sparkdl_tpu.image.imageIO import rgbToYuv420
    packed_420 = np.stack([rgbToYuv420(im) for im in images_small])
    packed420_ips = time_runner(
        BatchRunner(deviceResizeModel(mf, packed_src,
                                      packedFormat="yuv420"),
                    batch_size=batch_size),
        packed_420, batch_size)

    # NO-resolution-loss 4:2:0 shape: ship 298² planes (even-dims
    # requirement; 1.5 B/px ≈ 133 KB/img, half the 299² RGB payload)
    # and device-resize the 1px up to the model's 299² — the packed
    # option for pipelines that must not trade source resolution.
    # TPU-only: on the CPU fallback this extra InceptionV3 compile
    # (minutes on one core) would risk the watchdog budget.
    fullres_420_src = (298, 298)
    packed420_fullres_ips = None
    if on_tpu:
        images_298 = rng.integers(
            0, 255, size=(n_rows,) + fullres_420_src + (3,),
            dtype=np.uint8)
        packed_420_fullres = np.stack([rgbToYuv420(im)
                                       for im in images_298])
        packed420_fullres_ips = time_runner(
            BatchRunner(deviceResizeModel(mf, fullres_420_src,
                                          packedFormat="yuv420"),
                        batch_size=batch_size),
            packed_420_fullres, batch_size)

    n_decode = 64 if on_tpu else (12 if BENCH_TINY else 24)
    host_decode_ips = measure_host_decode(
        size=(src_h, src_w), n_images=n_decode)
    # the pipeline decodes at the PACKED size (cheaper resize/pack than
    # 299²) — its decode ceiling must be measured at the same size
    host_decode_ips_packed = measure_host_decode(
        size=packed_src, n_images=n_decode)
    host_decode_ips_420 = measure_host_decode(
        size=packed_src, n_images=n_decode,
        packedFormat="yuv420")

    # the full-pipeline headline: disk → decode → pack(4:2:0) → ship →
    # device reconstruct+resize+featurize, one stream. The utilization
    # ledger (obs/ledger.py) windows EXACTLY this pass: ceilings are
    # injected from the link measurement above (the probe is never
    # paid twice in one process), the baseline snaps right before the
    # pass, and one tick after it publishes the live ledger.util.* /
    # ledger.bound_by gauges the "bound" block and ci.sh gate read.
    from sparkdl_tpu.obs.ledger import ledger as _ledger
    led = _ledger()
    # the model-calibrated compute ceiling (docs/OBSERVABILITY.md):
    # device-resident images/s × the compiled program's cost_analysis
    # FLOPs/image (compile log) = the device's demonstrated FLOP rate
    # ON THIS PROGRAM — the compute lane's roofline denominator, with
    # compute_basis naming it in the ledger verdict. Degrades to None
    # (busy-time attribution) on backends whose cost_analysis returns
    # nothing.
    model_flops = getattr(mf.jitted(), "last_flops", None)
    device_gflops = (
        round(device["ips"] * (model_flops / batch_size) / 1e9, 3)
        if model_flops else None)
    led.ensure_ceilings({"link_h2d_MBps": link["h2d_MBps"],
                         "link_d2h_MBps": link["d2h_MBps"],
                         "device_gflops": device_gflops,
                         "source": "bench.measure_link"})
    led.baseline()
    pipeline = measure_pipeline(mf, packed_src, batch_size,
                                n_images=256 if on_tpu else 24,
                                packedFormat="yuv420")
    pipeline_ips = pipeline["ips"]
    ledger_window = led.tick()

    # the parallel host pipeline's serial-vs-pooled proof on the same
    # corpus (ROADMAP item 3) — AFTER the ledger tick so the measured
    # pass's window covers exactly the headline pipeline pass
    pipeline_overlap = measure_pipeline_overlap(
        mf, packed_src, batch_size,
        n_images=128 if on_tpu else 24, packedFormat="yuv420")

    fidelity = measure_fidelity(mf, packed_src,
                                n_images=32 if on_tpu else 8)

    # online serving shape (docs/SERVING.md): concurrent sub-batch
    # requests coalesced by the ModelServer into full device batches.
    # Sized per platform: the CPU InceptionV3 fallback runs ~6 img/s,
    # so its serve pass stays at a couple of batches.
    if on_tpu:
        serve_args = dict(n_requests=16, rows_per_request=batch_size // 2)
    elif BENCH_TINY:
        serve_args = dict(n_requests=24, rows_per_request=batch_size // 2)
    else:
        serve_args = dict(n_requests=2, rows_per_request=batch_size // 2,
                          threads=2)
    # the serve pass runs with the request log armed: the "tails"
    # block attributes the measured request p99 across the named
    # phases (queue/coalesce/staging/device/reassembly) from the
    # per-request timelines — tools/ci.sh gates its schema and the
    # ≥95% attribution bar
    serve, tails = measure_serve(mf, batch_size, **serve_args)

    # the closed-loop infeed autotuner (sparkdl_tpu/autotune,
    # docs/PERFORMANCE.md): controller settles (few changes, zero
    # oscillations) and must not lose to the fixed host_async default
    # outside the recorded noise band — tools/ci.sh gates it
    autotune = measure_autotune(mf, batch_size, n_rows=n_rows)

    # the device-resident infeed ring (runtime/runner.py InfeedRing):
    # a repeated-corpus steady pass must ship ZERO bytes (all content
    # hits), re-ship zero, and retrace zero — tools/ci.sh gates it
    ship_ring = measure_ship_ring(mf, batch_size, n_rows=n_rows)

    # the disaggregated input service (sparkdl_tpu/inputsvc/,
    # docs/DATA_SERVICE.md): remote-fleet vs local decode rows/s and
    # the snapshot tier's cold/warm epoch amortization — warm decode
    # busy-seconds must read ~0 (ci.sh's two-process drill gates it)
    input_service = measure_input_service(
        n_rows=512 if BENCH_TINY else 4096)

    # the fleet control plane (sparkdl_tpu/fleet/, docs/SERVING.md):
    # hot-swap latency + output-flip proof, persisted-AOT cold vs warm
    # first-request ms (zero compiles on the warm one), and the live
    # packing decision — ci.sh step 22 gates the cross-process drills
    fleet = measure_fleet()

    # Race the two fused-resize implementations device-resident
    # (VERDICT r4 #7, the transfer-strategy precedent: measured, not
    # asserted): the XLA einsum chain is the library default
    # (ops/infeed.py — it fuses into the model program and shards under
    # GSPMD); the Pallas kernel is TPU-only, so the race runs on real
    # hardware only. The faster one must be the default — a mismatch
    # is reported rather than silently accepted.
    infeed_race = {"einsum_ips": None, "pallas_ips": None,
                   "default_margin_pct": None,
                   "default": "einsum", "default_is_fastest": None,
                   "race_note": (
                       "measured swings of +/-5-6% BETWEEN sessions in "
                       "both directions through the tunnel (einsum "
                       "6103-6170 vs pallas 5719-6481 across "
                       "2026-07-31 runs) put the two variants inside "
                       "each other's noise; the default stays einsum "
                       "on the structural tiebreak — only it fuses "
                       "into the consuming model program and shards "
                       "under GSPMD (the pallas variant is single-"
                       "device and rejects yuv420). A sustained >10% "
                       "pallas margin would justify switching.")}
    if on_tpu:
        try:
            m_e = deviceResizeModel(mf, packed_src, use_pallas=False)
            m_p = deviceResizeModel(mf, packed_src, use_pallas=True)
            # INTERLEAVED repeats, per-variant max: a single-shot race
            # on the tunneled device confuses drift for a winner (one
            # run measured pallas +4% where three interleaved repeats
            # showed einsum +6% every time, 2026-07-31)
            e_best = p_best = 0.0
            for _ in range(2):
                e_best = max(e_best, measure_device_resident(
                    m_e, batch_size, n_batches=16)["ips"])
                p_best = max(p_best, measure_device_resident(
                    m_p, batch_size, n_batches=16)["ips"])
            infeed_race["einsum_ips"] = e_best
            infeed_race["pallas_ips"] = p_best
            infeed_race["default_margin_pct"] = round(
                (e_best - p_best) / p_best * 100.0, 2)
            # 1% noise floor: repeated same-program measurements move
            # ±0.5-1% through the tunnel (one run scored a 0.04% "loss"
            # that three interleaved repeats reversed) — a dead heat
            # must not read as a wrong default
            infeed_race["default_is_fastest"] = \
                e_best >= 0.99 * p_best
        except Exception as e:  # kernel lowering can shift across jax
            infeed_race["error"] = f"{type(e).__name__}: {e}"[:200]

    # uint8 NHWC on the wire, at the model's native input size
    image_mb = src_h * src_w * 3 / (1024.0 * 1024.0)
    packed_mb = packed_src[0] * packed_src[1] * 3 / (1024.0 * 1024.0)
    packed420_mb = packed_mb / 2.0  # 1.5 B/px vs 3
    ceiling = link["h2d_MBps"] / image_mb
    ceiling_packed = link["h2d_MBps"] / packed_mb
    ceiling_420 = link["h2d_MBps"] / packed420_mb
    # which stage's own ceiling binds the measured pipeline — derived
    # FROM the ledger's attribute() (obs/ledger.py), not bench-local
    # math: utilization per stage = measured pipeline rate over that
    # stage's own ceiling, verdict = the max-utilization stage (which
    # is exactly the min-ceiling stage — the offline and live verdicts
    # are one code path)
    from sparkdl_tpu.obs.ledger import attribute as ledger_attribute
    stage_ceilings = {"decode": host_decode_ips_420,
                      "link": ceiling_420,
                      "compute": device["ips"]}
    offline_util = {k: (pipeline_ips / v if v else 0.0)
                    for k, v in stage_ceilings.items()}
    offline_verdict = ledger_attribute(offline_util)
    pipeline_bound_by = offline_verdict["bound_by"]

    # unified observability (sparkdl_tpu/obs, docs/OBSERVABILITY.md):
    # the registry snapshot always ships; when SPARKDL_TPU_TRACE=1
    # armed the run, the span timeline exports as Perfetto trace-event
    # JSON (SPARKDL_TPU_TRACE_EXPORT names the path) and ci.sh's obs
    # gate schema-checks it (≥1 span per engine/ship/device lane)
    from sparkdl_tpu.obs import default_registry, stall_watchdog, tracer
    trc = tracer()
    obs_block = {
        "trace_armed": bool(trc.armed),
        "trace_events": None,
        "trace_export": None,
        "trace_dropped": trc.dropped,
        "registry": default_registry().snapshot(),
        # the operability layer's own state (docs/OBSERVABILITY.md):
        # whether the run was stall-monitored and whether any flight
        # bundle was written during it
        "watchdog": stall_watchdog().verdict(),
        "flight": obs_flight.recorder().status(),
    }
    from sparkdl_tpu.obs.request_log import request_log as _rlog
    from sparkdl_tpu.obs.slo import slo_tracker as _slo
    # SLO verdicts + request-log retention state: the same shapes
    # /statusz and the flight bundle carry
    obs_block["slo"] = _slo().status()
    obs_block["request_log"] = _rlog().status()
    # the resilience layer's drill/recovery state (docs/RESILIENCE.md):
    # injection config + per-site counts, retry/shed totals, live
    # circuit verdicts — literally the same renderer /statusz and the
    # flight bundle use, so a bench row and a postmortem cannot drift
    resilience_block = obs_flight.resilience_state()
    if trc.armed:
        trace_path = os.environ.get("SPARKDL_TPU_TRACE_EXPORT",
                                    "/tmp/sparkdl_tpu_trace.json")
        obs_block["trace_events"] = trc.export(trace_path)
        obs_block["trace_export"] = trace_path
    ledger_status = led.status()
    result = {
        # monotonically bumped whenever a key is REMOVED or retyped
        # (additions are compatible); tools/bench_compare.py gates a
        # fresh tiny-bench against the committed round schema so
        # bench-trajectory tracking can't silently drift
        "schema_version": 1,
        "metric": (f"images_per_sec_per_chip_testnet_featurize"
                   f"[{platform},tiny]" if BENCH_TINY else
                   f"images_per_sec_per_chip_inceptionv3_featurize"
                   f"[{platform}]"),
        "value": round(pipeline_ips, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(pipeline_ips / PER_CHIP_TARGET, 3),
        "value_fullres_transfer": round(e2e_ips, 1),
        "vs_baseline_fullres_transfer": round(
            e2e_ips / PER_CHIP_TARGET, 3),
        "headline_shape": ("full pipeline: JPEG files -> native "
                           "decode/pack(yuv420) -> ship -> fused "
                           "device featurize, one stream (r1-r4 "
                           "headlined value_fullres_transfer; see "
                           "note + BASELINE.md)"),
        "device_resident_ips": device["ips"],
        "device_tflops": round(
            device["ips"] * INCEPTION_GFLOPS / 1000.0, 2),
        "vs_baseline_device_resident": round(
            device["ips"] / PER_CHIP_TARGET, 3),
        "link_h2d_MBps": link["h2d_MBps"],
        "link_d2h_MBps": link["d2h_MBps"],
        "host_fed_ceiling_ips": round(ceiling, 1),
        "value_packed": round(packed_ips, 1),
        "vs_baseline_packed": round(packed_ips / PER_CHIP_TARGET, 3),
        "packed_src_hw": list(packed_src),
        "host_fed_ceiling_ips_packed": round(ceiling_packed, 1),
        "value_packed420": round(packed420_ips, 1),
        "vs_baseline_packed420": round(
            packed420_ips / PER_CHIP_TARGET, 3),
        "host_fed_ceiling_ips_packed420": round(ceiling_420, 1),
        "value_packed420_fullres": (
            round(packed420_fullres_ips, 1)
            if packed420_fullres_ips is not None else None),
        "vs_baseline_packed420_fullres": (
            round(packed420_fullres_ips / PER_CHIP_TARGET, 3)
            if packed420_fullres_ips is not None else None),
        "packed420_fullres_src_hw": list(fullres_420_src),
        "host_fed_ceiling_ips_packed420_fullres": round(
            link["h2d_MBps"]
            / (fullres_420_src[0] * fullres_420_src[1] * 1.5
               / (1024.0 * 1024.0)), 1),
        "host_decode_ips": round(host_decode_ips, 1),
        "host_decode_ips_packed": round(host_decode_ips_packed, 1),
        "host_decode_ips_packed420": round(host_decode_ips_420, 1),
        "value_pipeline": round(pipeline_ips, 1),
        "vs_baseline_pipeline": round(pipeline_ips / PER_CHIP_TARGET, 3),
        "pipeline_packed_format": "yuv420",
        # the parallel host pipeline (data/pipeline.py,
        # docs/PERFORMANCE.md "Parallel host pipeline"):
        # serial-vs-pooled ips on one corpus, worker/read-ahead
        # config, and the overlap proof — overlap_ratio =
        # (decode_busy + ship_busy) / wall over the pooled pass,
        # > 1 only when decode genuinely overlaps ship. tools/ci.sh's
        # pipeline gate reads it.
        "pipeline_overlap": pipeline_overlap,
        # host-copy counters: aligned must read 0/0 (the zero-copy hot
        # path); tail stages exactly one partial batch through the
        # persistent pad buffer; pipeline_* are the measured pipeline's
        # own RunnerMetrics over both timed passes
        "host_copy": {
            **host_copy,
            "pipeline_bytes_staged": pipeline["bytes_staged"],
            "pipeline_bytes_copied": pipeline["bytes_copied"],
            "pipeline_transfer_wait_s": pipeline["transfer_wait_s"],
        },
        "fidelity": fidelity,
        "serve": serve,
        "tails": tails,
        "autotune": autotune,
        # the device-resident infeed ring's steady-pass verdict
        # (runtime/runner.py InfeedRing; ci.sh step [18/18] gates
        # zero re-ship / zero steady link bytes / zero retraces)
        "ship_ring": ship_ring,
        # the disaggregated input service + snapshot tier
        # (sparkdl_tpu/inputsvc/, docs/DATA_SERVICE.md): remote vs
        # local decode rows/s by fleet size, snapshot cold vs warm
        # epoch, and the warm-epoch decode-busy ≈ 0 amortization proof
        "input_service": input_service,
        # the fleet control plane's swap/warm-start/packing numbers
        # (sparkdl_tpu/fleet/, docs/SERVING.md "Fleet control plane")
        "fleet": fleet,
        "resilience": resilience_block,
        # compile forensics (docs/OBSERVABILITY.md, obs/compile_log.py):
        # per-function compile counts + wall time, retrace attribution,
        # and the zero-retrace verdict over the whole run — literally
        # the same renderer /statusz and the flight bundle use. A
        # warmed serve pass must show unexpected_retraces == 0 (ci.sh
        # gates it, plus an injected off-ladder shape showing > 0 with
        # the diff naming the argument).
        "compile": obs_flight.compile_state(),
        "device_gflops_ceiling": device_gflops,
        "infeed_race": infeed_race,
        **({"tpu_fallback": ("tunneled TPU backend did not initialize; "
                             "CPU numbers are compute-bound on this "
                             "1-core host. BASELINE.md records this "
                             "round's live v5e measurements: "
                             "value_packed420 973.7, pipeline 463-563, "
                             "device-resident 6,440 img/s")}
           if tpu_down else {}),
        "pipeline_bound_by": pipeline_bound_by,
        "pipeline_stage_ceilings_ips": {
            k: round(v, 1) for k, v in stage_ceilings.items()},
        # the live roofline (obs/ledger.py, docs/PERFORMANCE.md): ONE
        # ledger window over the measured pipeline pass — utilization
        # fractions, the continuous bound_by verdict (same attribute()
        # as pipeline_bound_by above), and the ceilings it divided by;
        # ci.sh gates the schema, the [0,1] bounds, and verdict ==
        # max-utilization stage against the published ledger.util.*
        "bound": {
            **({"bound_by": ledger_window["bound_by"],
                "headroom_pct": ledger_window["headroom_pct"],
                "util": ledger_window["util"],
                "window_s": ledger_window["dt_s"],
                "link_basis": ledger_window["link_basis"],
                "compute_basis": ledger_window["compute_basis"],
                "decode_basis": ledger_window["decode_basis"],
                "ship_MBps": ledger_window["ship_MBps"]}
               if ledger_window is not None else
               {"bound_by": None, "headroom_pct": None, "util": None,
                "window_s": None, "link_basis": None,
                "compute_basis": None, "decode_basis": None,
                "ship_MBps": None}),
            **{k: ledger_status[k] for k in ("windows", "ceilings")},
            "offline": {"bound_by": pipeline_bound_by,
                        "util": {k: round(v, 4)
                                 for k, v in offline_util.items()}},
        },
        "runner_strategy": runner.strategy,
        # whether the runners' ship path ran under the runtime
        # sanitizer's transfer guard (SPARKDL_TPU_SANITIZE=1 —
        # runtime/sanitize.py): True means the zero-copy numbers above
        # were enforced by the JAX runtime, not just counted. Requiring
        # armed_run_count() > 0 (not just the env var) makes a
        # degraded-guard backend report False — ci.sh's schema gate
        # then fails instead of certifying unenforced numbers.
        "sanitize": sanitize_enabled() and armed_run_count() > 0,
        "obs": obs_block,
        "note": ("value IS the full measured pipeline (JPEG files -> "
                 "fused native DCT-prescaled decode/resize/pack to "
                 "planar YCbCr 4:2:0 (1.5 B/px, half the RGB payload; "
                 "standard 4:2:0 sources stream out of libjpeg raw) "
                 "-> ship -> fused on-device chroma-upsample+BT.601+"
                 "resize+featurize, ONE stream) — the north-star's "
                 "own shape, which includes read+decode; rounds 1-4 "
                 "headlined the pre-decoded 299^2 transfer shape, "
                 "continued as value_fullres_transfer. "
                 "pipeline_bound_by names the stage whose own ceiling "
                 "binds the pipeline. On this 1-core host decode and "
                 "ship-side host work serialize (1/decode + 1/ship ~= "
                 "1/pipeline) and the tunnel's bandwidth varies "
                 "several-x between minutes (a value above a ceiling "
                 "key means the link moved between the two "
                 "measurements); on a many-core host they overlap and "
                 "the pipeline converges to the binding ceiling. "
                 "value_fullres_transfer/value_packed/value_packed420 "
                 "feed pre-decoded arrays (transfer-only shapes); "
                 "device_resident_ips is compute with transfers "
                 "excluded; host_decode_ips uses a textured "
                 "(photo-compressibility) corpus. The fidelity block "
                 "quantifies what the reduced-resolution ship costs "
                 "(CENTERED feature cosine vs its cross-image "
                 "baseline — raw cosine is degenerate under this "
                 "env's random weights; end-accuracy parity within "
                 "0.05 is pinned in test_integration_capstone.py::"
                 "test_packed_ship_fidelity, pixel parity in "
                 "test_ops/test_native)"),
    }
    # The FULL result (every key above — ~4 KB as one line) goes to a
    # file: BENCH_r05 landed `parsed: null` because the single JSON
    # line outgrew the driver's 2,000-char stdout tail window. The
    # LAST stdout line is now a compact headline (<1,200 chars) the
    # driver can always parse, carrying the path to the full result;
    # tools/ci.sh's gates read the file (SPARKDL_TPU_BENCH_RESULT
    # names it; default ./bench_result.json).
    result_path = os.environ.get("SPARKDL_TPU_BENCH_RESULT",
                                 "bench_result.json")
    with open(result_path, "w", encoding="utf-8") as f:
        json.dump(result, f, indent=2, default=str)
    headline = {
        "schema_version": result["schema_version"],
        "metric": result["metric"],
        "value": result["value"],
        "unit": result["unit"],
        "vs_baseline": result["vs_baseline"],
        "value_pipeline": result["value_pipeline"],
        "value_fullres_transfer": result["value_fullres_transfer"],
        "value_packed420": result["value_packed420"],
        "device_resident_ips": result["device_resident_ips"],
        "link_h2d_MBps": result["link_h2d_MBps"],
        "pipeline_bound_by": result["pipeline_bound_by"],
        # the LIVE verdict (ledger window over the measured pipeline
        # pass) with its headroom — the offline ceilings verdict above
        # stays for round-over-round continuity
        "bound_by": result["bound"]["bound_by"],
        "bound_headroom_pct": result["bound"]["headroom_pct"],
        "runner_strategy": result["runner_strategy"],
        "sanitize": result["sanitize"],
        "serve_rows_per_s": result["serve"].get("achieved_rows_per_s"),
        "serve_p99_ms": result["serve"].get("p99_latency_ms"),
        "tails_p99_ms": result["tails"].get("p99_ms"),
        "autotune_converged": result["autotune"].get("converged"),
        # compile forensics: total compiles observed + the zero-
        # retrace verdict (docs/OBSERVABILITY.md)
        "compiles": result["compile"].get("events"),
        "unexpected_retraces": result["compile"].get(
            "unexpected_retraces"),
        **({"tpu_fallback": True} if tpu_down else {}),
        "result_path": result_path,
        # a POINTER, not prose: long notes are how BENCH_r05's headline
        # outgrew the tail window (tools/ci.sh step 4 gates the size)
        "note": "headline only; full result at result_path",
    }
    line = json.dumps(headline)
    if len(line) > 1200:        # the driver tail window is the contract
        line = json.dumps({k: headline[k] for k in
                           ("schema_version", "metric", "value",
                            "unit", "vs_baseline", "result_path")})
    print(line)
    if _bench_done is not None:
        _bench_done.set()  # disarm the stall watchdog


if __name__ == "__main__":
    sys.exit(main())
