"""North-star benchmark: InceptionV3 DeepImageFeaturizer throughput.

Measures images/sec/chip for the full device program (uint8 NHWC infeed
→ fused preprocess → InceptionV3 → 2048-d features) through the
production ``BatchRunner`` on whatever accelerator is attached (the one
real TPU chip under the driver; CPU as fallback).

``vs_baseline`` compares against the BASELINE.json north-star of 10,000
images/sec aggregate on v5e-8 == 1,250 images/sec/chip under linear DP
scaling (see BASELINE.md "Unit note").

Prints exactly ONE JSON line.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

PER_CHIP_TARGET = 1250.0  # 10k img/s ÷ 8 chips (BASELINE.md)


def main() -> None:
    import jax

    from sparkdl_tpu.models.zoo import getModelFunction
    from sparkdl_tpu.runtime.runner import BatchRunner

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    batch_size = 256 if on_tpu else 16
    n_rows = batch_size * (8 if on_tpu else 2)

    rng = np.random.default_rng(0)
    images = rng.integers(0, 255, size=(n_rows, 299, 299, 3),
                          dtype=np.uint8)

    mf = getModelFunction("InceptionV3", featurize=True)
    runner = BatchRunner(mf, batch_size=batch_size)

    # Warmup: compile + one full pass so caches/transfers are steady.
    runner.run({"image": images[: batch_size * 2]})

    # Median of 3 passes: host->device link throughput varies several-x
    # between minutes in shared environments; the median is robust to
    # one contended pass without overstating sustained throughput.
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = runner.run({"image": images})
        elapsed = time.perf_counter() - t0
        assert out["features"].shape == (n_rows, 2048), \
            out["features"].shape
        rates.append(n_rows / elapsed)
    ips = float(np.median(rates))
    print(json.dumps({
        "metric": f"images_per_sec_per_chip_inceptionv3_featurize[{platform}]",
        "value": round(ips, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(ips / PER_CHIP_TARGET, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
