"""Cross-process telemetry plane (obs/remote.py): config resolution,
worker-side frames, the clock-aligned trace merge, counter folding,
cross-process warn_once dedup, worker stall/death health, flight
bundle ``workers[]``, ``report --workers``, and the disarmed
zero-extra-bytes hand-off contract.

The ISSUE-18 pins: skewed synthetic clocks align within tolerance, a
merged Perfetto export carries one process group per worker with no
timestamp inversions against the parent spans, an injected
``pipeline.worker_decode`` fault in a directly-invoked worker task is
counted AND attributed in its frame, and a dead pid is marked (while a
cleanly-retired one never reads as a death).
"""

import json
import multiprocessing
import os
import time
import urllib.error
import urllib.request

import cloudpickle
import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.data import DataFrame, LocalEngine
from sparkdl_tpu.data import pipeline as host_pipeline
from sparkdl_tpu.obs import default_registry, report, start_telemetry
from sparkdl_tpu.obs import remote
from sparkdl_tpu.obs.trace import span, tracer
from sparkdl_tpu.obs.watchdog import watchdog
from sparkdl_tpu.resilience import faults


@pytest.fixture(autouse=True)
def clean_plane(monkeypatch):
    """Every test starts and ends with the plane disarmed: the agent
    and aggregator are process-wide singletons, and an armed leftover
    would leak worker groups into OTHER suites' trace exports."""
    monkeypatch.delenv(remote.ENV_REMOTE, raising=False)
    remote._AGENT = None
    remote.aggregator().clear()
    yield
    remote._AGENT = None
    remote.aggregator().clear()
    faults.disarm()
    wd = watchdog()
    wd.disarm()
    wd.arm_from_env()
    trc = tracer()
    trc.disarm()
    trc.clear()
    trc.arm_from_env()


def _ids_df(ids, parts, engine):
    return DataFrame(
        DataFrame.from_table(pa.table({"id": ids}), parts)._sources,
        engine=engine)


def _frame(pid=4242, clock=None, spans=(), counters=None, gauges=None,
           degrades=(), verdict=None, fault_state=None, dropped=0):
    """A synthetic worker frame in the transport schema."""
    if clock is None:
        clock = (time.time(), time.perf_counter())
    return {
        "v": remote.FRAME_SCHEMA,
        "pid": pid,
        "clock": clock,
        "spans": list(spans),
        "spans_dropped": dropped,
        "counters": dict(counters or {}),
        "gauges": dict(gauges or {}),
        "watchdog": verdict,
        "degrades": list(degrades),
        "faults": fault_state,
    }


# ---------------------------------------------------------------------------
# config resolution
# ---------------------------------------------------------------------------

class TestTelemetryConfig:
    def test_disarmed_is_none(self):
        assert remote.telemetry_config() is None

    def test_armed_fields(self):
        tracer().arm()
        watchdog().arm(threshold_s=7.0)
        faults.inject("pipeline.worker_decode", "transient", 0.5,
                      seed=3)
        cfg = remote.telemetry_config()
        assert cfg is not None
        assert cfg["v"] == remote.FRAME_SCHEMA
        assert cfg["trace"] is True
        assert cfg["watchdog"] is True
        assert cfg["threshold_s"] == 7.0
        assert "pipeline.worker_decode:transient:0.5" in cfg["faults"]

    def test_env_pins_off(self, monkeypatch):
        tracer().arm()
        monkeypatch.setenv(remote.ENV_REMOTE, "0")
        assert remote.telemetry_config() is None

    def test_env_forces_on(self, monkeypatch):
        monkeypatch.setenv(remote.ENV_REMOTE, "1")
        cfg = remote.telemetry_config()
        assert cfg is not None and cfg["trace"] is True


# ---------------------------------------------------------------------------
# the worker-side agent
# ---------------------------------------------------------------------------

class TestAgent:
    def test_frame_carries_deltas_only(self):
        reg = default_registry()
        reg.counter("pipeline.worker_rows").add(100)   # pre-agent
        agent = remote.TelemetryAgent({"v": 1, "trace": True})
        with span("worker.decode", lane="worker", partition=0):
            pass
        reg.counter("pipeline.worker_rows").add(7)
        frame = agent.cut_frame()
        assert frame["pid"] == os.getpid()
        assert len(frame["clock"]) == 2
        names = [s[0] for s in frame["spans"]]
        assert "worker.decode" in names
        # the fork-inheritance rebase: only the post-arm delta ships
        assert frame["counters"]["pipeline.worker_rows"] == 7.0
        # a second cut ships nothing stale
        again = agent.cut_frame()
        assert again["spans"] == []
        assert "pipeline.worker_rows" not in again["counters"]

    def test_module_capture_degrade_disarmed(self):
        assert remote.capture_degrade("pipeline:x", "msg") is False

    def test_module_capture_degrade_armed(self):
        remote._AGENT = remote.TelemetryAgent({"v": 1})
        assert remote.capture_degrade("pipeline:x", "msg") is True
        frame = remote._AGENT.cut_frame()
        assert ("pipeline:x", "msg") in frame["degrades"]

    def test_refit_switches_fault_spec_only(self):
        agent = remote.worker_agent({"v": 1, "faults": None})
        assert not faults.armed()
        remote.worker_agent(
            {"v": 1, "faults": "pipeline.worker_decode:transient:1.0"})
        assert faults.armed()
        assert agent is remote._AGENT
        # spec removal disarms (a drill must not outlive its stream)
        remote.worker_agent({"v": 1, "faults": None})
        assert not faults.armed()

    def test_disarmed_capture_overhead(self):
        """The ISSUE's acceptance bound: the disarmed path is ONE
        module-global check, same <10 µs/call regime as the tracer's
        no-op span (min over repeats — noise only adds time)."""
        n = 20_000
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(n):
                remote.capture_degrade("hot", "msg")
            best = min(best, (time.perf_counter() - t0) / n)
        assert best < 10e-6, \
            f"disarmed capture_degrade costs {best * 1e6:.2f} µs"


# ---------------------------------------------------------------------------
# clock alignment
# ---------------------------------------------------------------------------

class TestClockAlignment:
    def test_skewed_epochs_align_within_tolerance(self):
        """A worker whose perf_counter origin is 100 s away from the
        parent's still lands its spans at the right parent-relative
        microsecond (the wall/mono bridge handshake)."""
        agg = remote.TelemetryAggregator()
        now_unix = time.time()
        now_pc = time.perf_counter()
        skew = 100.0
        w_pc = now_pc - skew
        # the worker saw this span end 0.5 s before it cut the frame
        rec = ("worker.decode", "worker", 1, "MainThread",
               w_pc - 0.5, w_pc - 0.4, {"partition": 0})
        agg.ingest(_frame(clock=(now_unix, w_pc), spans=[rec]))
        epoch = now_pc - 10.0
        events = agg.trace_events(epoch)
        xs = [e for e in events if e["ph"] == "X"]
        assert len(xs) == 1
        # true wall position: 10 s into the epoch window minus 0.5 s
        expected_ts = 9.5e6
        assert abs(xs[0]["ts"] - expected_ts) < 50_000, xs[0]
        assert abs(xs[0]["dur"] - 0.1e6) < 1_000, xs[0]

    def test_unclocked_worker_exports_nothing(self):
        agg = remote.TelemetryAggregator()
        f = _frame(spans=[("s", "worker", 1, "t", 0.0, 1.0, {})])
        f["clock"] = None
        agg.ingest(f)
        assert agg.trace_events(0.0) == []


# ---------------------------------------------------------------------------
# merged perfetto schema (end-to-end, process pool)
# ---------------------------------------------------------------------------

class TestMergedTrace:
    def test_process_stream_merges_aligned_worker_tracks(self, tmp_path,
                                                         monkeypatch):
        monkeypatch.setenv("SPARKDL_TPU_PIPELINE_MPCTX", "fork")
        tracer().arm()
        tracer().clear()
        eng = LocalEngine(pipeline_workers=2, pipeline_mode="process")
        try:
            ids = np.arange(120)
            out = _ids_df(ids, 4, eng).map_batches(
                lambda b: b, name="noop").collect()
            np.testing.assert_array_equal(
                out.column("id").to_numpy(zero_copy_only=False), ids)
        finally:
            eng.shutdown()
        path = tmp_path / "merged.json"
        tracer().export(str(path))
        events = json.loads(path.read_text())
        worker_pids = sorted({e["pid"] for e in events
                              if e["pid"] >= remote.WORKER_PID_BASE})
        assert worker_pids, "no worker process tracks in merged trace"
        # ONE process group (one process_name meta) per worker pid
        metas = [e for e in events if e["ph"] == "M"
                 and e["name"] == "process_name"
                 and e["pid"] >= remote.WORKER_PID_BASE]
        assert sorted(m["pid"] for m in metas) == worker_pids
        for m in metas:
            assert m["args"]["name"].startswith("worker.")
        wx = [e for e in events if e["ph"] == "X"
              and e["pid"] >= remote.WORKER_PID_BASE]
        px = [e for e in events if e["ph"] == "X"
              and e["pid"] < remote.WORKER_PID_BASE]
        assert {e["name"] for e in wx} >= {"worker.decode",
                                           "worker.source_load"}
        # no inversions: every worker span inside the parent stream's
        # window (generous slack for the handshake's sampling delay)
        pmin = min(e["ts"] for e in px)
        pmax = max(e["ts"] + e["dur"] for e in px)
        for e in wx:
            assert pmin - 2e5 <= e["ts"] <= pmax + 2e5, \
                (e["name"], e["ts"], pmin, pmax)
            assert e["args"]["worker"] in (0, 1)

    def test_non_singleton_tracer_does_not_merge(self):
        """Only THE process tracer merges worker spans — a standalone
        Tracer (tests, tools) exports its own spans only."""
        from sparkdl_tpu.obs.trace import Tracer
        agg = remote.aggregator()
        agg.ingest(_frame(spans=[("worker.decode", "worker", 1, "t",
                                  time.perf_counter() - 0.1,
                                  time.perf_counter(), {})]))
        solo = Tracer()
        solo.arm()
        with solo.span("mine", lane="engine"):
            pass
        events = solo.trace_events()
        assert all(e["pid"] < remote.WORKER_PID_BASE for e in events)


# ---------------------------------------------------------------------------
# counter folding + warn_once dedup
# ---------------------------------------------------------------------------

class TestFolding:
    def test_counters_fold_per_worker_and_rollup(self):
        reg = default_registry()
        agg = remote.aggregator()
        k = "pipeline.worker_rows"
        w0 = reg.counter(f"worker.0.{k}").value
        w1 = reg.counter(f"worker.1.{k}").value
        wall = reg.counter(f"worker.all.{k}").value
        frames0 = reg.counter("worker.frames").value
        agg.ingest(_frame(pid=111, counters={k: 5.0}))
        agg.ingest(_frame(pid=222, counters={k: 7.0}))
        agg.ingest(_frame(pid=111, counters={k: 2.0}))
        assert reg.counter(f"worker.0.{k}").value == w0 + 7.0
        assert reg.counter(f"worker.1.{k}").value == w1 + 7.0
        assert reg.counter(f"worker.all.{k}").value == wall + 14.0
        assert reg.counter("worker.frames").value == frames0 + 3
        status = agg.workers_status()
        assert [s["index"] for s in status] == [0, 1]
        assert status[0]["counters"][k] == 7.0

    def test_malformed_frame_counts_ingest_error(self):
        reg = default_registry()
        errs0 = reg.counter("worker.ingest_errors").value
        bad = _frame()
        bad["counters"] = {"k": "not-a-number"}
        remote.aggregator().ingest(bad)
        assert reg.counter("worker.ingest_errors").value == errs0 + 1

    def test_warn_once_dedup_across_workers(self, caplog):
        reg = default_registry()
        agg = remote.aggregator()
        d0 = reg.counter("worker.all.degrade_events").value
        msg = ("pipeline: no usable process pool on this platform; "
               "falling back to the thread pool")
        with caplog.at_level("WARNING", logger="sparkdl_tpu.obs.remote"):
            agg.ingest(_frame(pid=111,
                              degrades=[("pipeline:noproc", msg)]))
            agg.ingest(_frame(pid=222,
                              degrades=[("pipeline:noproc", msg)]))
        lines = [r for r in caplog.records if msg in r.getMessage()]
        assert len(lines) == 1, "degrade reason logged more than once"
        assert reg.counter("worker.0.degrade_events").value >= 1
        assert reg.counter("worker.1.degrade_events").value >= 1
        assert reg.counter("worker.all.degrade_events").value == d0 + 2


# ---------------------------------------------------------------------------
# worker stall + death health
# ---------------------------------------------------------------------------

class TestWorkerHealth:
    def _stall_verdict(self):
        return {"armed": True, "threshold_s": 0.2,
                "active_sources": {"pipeline.worker_decode": 0.9},
                "stalled_sources": ["pipeline.worker_decode"],
                "stalls_fired": 1, "healthy": False}

    def test_worker_stall_reaches_health_and_healthz(self):
        reg = default_registry()
        agg = remote.aggregator()
        stalls0 = reg.counter("worker.stalls").value
        agg.ingest(_frame(pid=111, verdict=self._stall_verdict()))
        assert reg.counter("worker.stalls").value == stalls0 + 1
        assert agg.health()["stalled"] == ["worker.0"]
        tel = start_telemetry()
        try:
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                urllib.request.urlopen(tel.url("/healthz"), timeout=5)
            assert exc_info.value.code == 503
            body = json.loads(exc_info.value.read().decode())
            assert body["worker_stalled"] == ["worker.0"]
        finally:
            tel.close()

    def test_stall_recovery_clears_health(self):
        agg = remote.aggregator()
        agg.ingest(_frame(pid=111, verdict=self._stall_verdict()))
        recovered = dict(self._stall_verdict(), stalled_sources=[],
                         healthy=True)
        agg.ingest(_frame(pid=111, verdict=recovered))
        assert agg.health()["stalled"] == []

    def _reaped_pid(self):
        proc = multiprocessing.get_context("fork").Process(target=int)
        proc.start()
        proc.join()
        return proc.pid

    def test_dead_pid_marked_and_counted(self):
        reg = default_registry()
        agg = remote.aggregator()
        deaths0 = reg.counter("pipeline.worker_deaths").value
        agg.ingest(_frame(pid=self._reaped_pid()))
        dead = agg.note_pool_broken("process pool broke (test)")
        assert dead == [0]
        assert reg.counter("pipeline.worker_deaths").value == deaths0 + 1
        assert agg.health()["dead"] == ["worker.0"]
        status = agg.workers_status()[0]
        assert status["dead"] is True
        assert "broke" in status["death_reason"]

    def test_retired_worker_is_not_a_death(self):
        reg = default_registry()
        agg = remote.aggregator()
        deaths0 = reg.counter("pipeline.worker_deaths").value
        pid = self._reaped_pid()
        agg.ingest(_frame(pid=pid))
        agg.note_pool_retired([pid])
        assert agg.note_pool_broken("pool broke later") == []
        assert reg.counter("pipeline.worker_deaths").value == deaths0
        assert agg.health()["dead"] == []
        assert agg.workers_status()[0]["retired"] is True

    def test_flight_bundle_carries_workers_section(self):
        from sparkdl_tpu.obs import flight
        remote.aggregator().ingest(
            _frame(pid=111, counters={"pipeline.worker_rows": 3.0}))
        bundle = flight.recorder().bundle(reason="test")
        assert isinstance(bundle.get("workers"), list)
        row = bundle["workers"][0]
        assert row["pid"] == 111
        assert row["counters"]["pipeline.worker_rows"] == 3.0


# ---------------------------------------------------------------------------
# the worker task end of the wire
# ---------------------------------------------------------------------------

class TestWorkerTask:
    def _blobs(self, n=6, parts=1):
        src = DataFrame.from_table(
            pa.table({"id": list(range(n))}), parts)._sources[0]
        return cloudpickle.dumps([]), cloudpickle.dumps(src)

    def test_disarmed_tuples_are_base_shapes(self):
        plan_blob, src_blob = self._blobs()
        r = host_pipeline._pooled_partition_task(
            "t1", plan_blob, src_blob, 0, 1 << 30, None)
        assert r[0] == "buf"
        assert len(r) == host_pipeline._RESULT_BASE_LEN["buf"]
        base, frame = host_pipeline._split_frame(r)
        assert base is r and frame is None

    def test_armed_task_ships_frame(self):
        plan_blob, src_blob = self._blobs()
        r = host_pipeline._pooled_partition_task(
            "t2", plan_blob, src_blob, 0, 1 << 30,
            {"v": 1, "trace": True, "watchdog": False,
             "threshold_s": 0.0, "faults": None})
        assert len(r) == host_pipeline._RESULT_BASE_LEN["buf"] + 1
        base, frame = host_pipeline._split_frame(r)
        assert len(base) == host_pipeline._RESULT_BASE_LEN["buf"]
        names = [s[0] for s in frame["spans"]]
        assert "worker.decode" in names
        assert frame["counters"]["pipeline.worker_rows"] == 6.0

    def test_injected_worker_fault_attributed_in_frame(self):
        """Rate-1.0 pipeline.worker_decode: the typed fault ships in
        the err tuple AND its worker-side counters ride the frame."""
        plan_blob, src_blob = self._blobs()
        r = host_pipeline._pooled_partition_task(
            "t3", plan_blob, src_blob, 0, 1 << 30,
            {"v": 1, "trace": True, "watchdog": False,
             "threshold_s": 0.0,
             "faults": "pipeline.worker_decode:transient:1.0"})
        base, frame = host_pipeline._split_frame(r)
        assert base[0] == "err"
        assert base[3] == "InjectedFault"
        assert frame["faults"]["armed"] is True
        site = frame["faults"]["sites"]["pipeline.worker_decode"]
        assert site["injected"] == 1
        assert frame["counters"][
            "faults.pipeline.worker_decode.injected"] == 1.0
        # the parent-side fold makes it a registry series
        reg = default_registry()
        before = reg.counter(
            "worker.all.faults.pipeline.worker_decode.injected").value
        host_pipeline._ingest_frame(frame)
        assert reg.counter(
            "worker.all.faults.pipeline.worker_decode.injected"
        ).value == before + 1.0

    def test_err_frames_ingest_before_raise(self):
        agg = remote.aggregator()
        err = ("err", None, "boom", "ValueError",
               _frame(pid=333, counters={"pipeline.worker_rows": 1.0}))
        with pytest.raises(host_pipeline.PipelineWorkerError):
            host_pipeline._consume_result(err)
        assert any(s["pid"] == 333 for s in agg.workers_status())


# ---------------------------------------------------------------------------
# report --workers
# ---------------------------------------------------------------------------

class TestReportWorkers:
    def _events(self):
        return [
            {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
             "args": {"name": "engine"}},
            {"name": "pipeline.fragment", "cat": "engine", "ph": "X",
             "ts": 0.0, "dur": 10_000.0, "pid": 1, "tid": 1,
             "args": {}},
            {"name": "process_name", "ph": "M", "pid": 1000, "tid": 0,
             "args": {"name": "worker.0 (pid 4242)"}},
            {"name": "worker.decode", "cat": "worker", "ph": "X",
             "ts": 1_000.0, "dur": 4_000.0, "pid": 1000, "tid": 1,
             "args": {"worker": 0, "partition": 0}},
        ]

    def test_workers_summary_rows(self):
        w = report.workers_summary(self._events())
        assert w is not None
        assert len(w["workers"]) == 1
        row = w["workers"][0]
        assert row["index"] == 0
        assert row["partitions"] == 1
        assert row["busy_pct"] == pytest.approx(40.0, abs=1.0)

    def test_workers_summary_bundle_join(self):
        bundle = {"workers": [{
            "index": 0, "pid": 4242, "dead": True,
            "counters": {"pipeline.worker_rows": 64.0,
                         "pipeline.degrade_events": 1.0},
            "degrades": [{"reason": "r", "message": "m"}],
            "faults": {"sites": {"pipeline.worker_decode":
                                 {"injected": 2}}},
        }]}
        w = report.workers_summary(self._events(), bundle=bundle)
        row = w["workers"][0]
        assert row["rows"] == 64
        assert row["faults_injected"] == 2
        assert row["dead"] is True
        text = report.summarize_workers(self._events(), bundle=bundle)
        assert "worker.0" in text and "[DEAD]" in text

    def test_no_worker_tracks_is_forward_compatible(self):
        events = [e for e in self._events() if e["pid"] < 1000]
        assert report.workers_summary(events) is None
        assert "no worker process tracks" in \
            report.summarize_workers(events)
        # and the plain summary still renders merged traces
        assert "worker.0" in report.summarize(self._events())
