"""Params/pipeline system tests (reference pattern: pyspark Params
semantics exercised by every transformer test; SURVEY §2.1 param system)."""

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.data import DataFrame
from sparkdl_tpu.params import (
    CrossValidator,
    TrainValidationSplit,
    Estimator,
    Evaluator,
    HasInputCol,
    HasOutputCol,
    Model,
    Param,
    ParamGridBuilder,
    Pipeline,
    Transformer,
    TypeConverters,
    keyword_only,
)


class AddConst(Transformer, HasInputCol, HasOutputCol):
    value = Param("AddConst", "value", "constant to add",
                  TypeConverters.toFloat)

    @keyword_only
    def __init__(self, *, inputCol=None, outputCol=None, value=1.0):
        super().__init__()
        self._setDefault(value=1.0)
        self._set(inputCol=inputCol, outputCol=outputCol, value=value)

    def _transform(self, dataset):
        incol = self.getInputCol()
        v = self.getOrDefault("value")

        def _fn(batch):
            x = batch.column(batch.schema.get_field_index(incol)) \
                .to_numpy(zero_copy_only=False)
            return pa.array(x + v)

        return dataset.with_column(self.getOutputCol(), _fn)


class MeanModel(Model, HasInputCol, HasOutputCol):
    def __init__(self, mean, inputCol, outputCol):
        super().__init__()
        self.mean = mean
        self._set(inputCol=inputCol, outputCol=outputCol)

    def _transform(self, dataset):
        m = self.mean

        def _fn(batch):
            n = batch.num_rows
            return pa.array(np.full(n, m))

        return dataset.with_column(self.getOutputCol(), _fn)


class MeanEstimator(Estimator, HasInputCol, HasOutputCol):
    shift = Param("MeanEstimator", "shift", "added to the learned mean",
                  TypeConverters.toFloat)

    @keyword_only
    def __init__(self, *, inputCol=None, outputCol=None, shift=0.0):
        super().__init__()
        self._setDefault(shift=0.0)
        self._set(inputCol=inputCol, outputCol=outputCol, shift=shift)

    def _fit(self, dataset):
        x = dataset.select(self.getInputCol()).collect() \
            .column(0).to_numpy(zero_copy_only=False)
        return MeanModel(float(x.mean()) + self.getOrDefault("shift"),
                         self.getInputCol(), self.getOutputCol())


def _df(n=20, parts=4):
    return DataFrame.from_table(
        pa.table({"x": np.arange(n, dtype=np.float64)}), parts)


class TestParams:
    def test_set_get_default(self):
        t = AddConst(inputCol="x", outputCol="y")
        assert t.getInputCol() == "x"
        assert t.getOrDefault("value") == 1.0
        t.set("value", 2)
        assert t.getOrDefault("value") == 2.0

    def test_keyword_only_rejects_positional(self):
        with pytest.raises(TypeError):
            AddConst("x")

    def test_type_converter_rejects(self):
        t = AddConst(inputCol="x", outputCol="y")
        with pytest.raises(TypeError):
            t.set("value", "not-a-number")
        with pytest.raises(TypeError):
            t.set("inputCol", 42)

    def test_copy_isolation(self):
        t = AddConst(inputCol="x", outputCol="y", value=1.0)
        t2 = t.copy({t.value: 5.0})
        assert t.getOrDefault("value") == 1.0
        assert t2.getOrDefault("value") == 5.0

    def test_unknown_param(self):
        t = AddConst(inputCol="x", outputCol="y")
        with pytest.raises(AttributeError):
            t.getParam("nope")

    def test_set_params(self):
        """pyspark convention: setParams(**kwargs) sets several params
        through the typed converters, raising on unknown names; an
        explicit None clears back to the default (the only way typed
        converters allow returning a nullable param to None)."""
        t = AddConst(inputCol="x", outputCol="y")
        assert t.setParams(value=3, outputCol="z") is t
        assert t.getOrDefault("value") == 3.0  # converter applied
        assert t.getOutputCol() == "z"
        with pytest.raises(AttributeError):
            t.setParams(nope=1)
        with pytest.raises(TypeError):
            t.setParams(value="not-a-number")
        t.setParams(value=None)  # clear → default
        assert t.getOrDefault("value") == 1.0
        from sparkdl_tpu.params.tuning import CrossValidator
        cv = CrossValidator(cacheDir="/tmp/x")
        cv.setParams(cacheDir=None)
        assert cv.getOrDefault("cacheDir") is None

    def test_explain_params(self):
        t = AddConst(inputCol="x", outputCol="y")
        s = t.explainParams()
        assert "inputCol" in s and "value" in s
        # singular form (pyspark convention), by name or Param
        assert t.explainParam("inputCol").startswith("inputCol:")
        assert "'x'" in t.explainParam(t.inputCol)
        with pytest.raises(AttributeError):
            t.explainParam("nope")
        # a Param OBJECT from another class raises (pyspark), instead
        # of silently explaining this instance's same-named param
        from sparkdl_tpu.estimators import ClassificationEvaluator
        with pytest.raises(ValueError, match="does not belong"):
            t.explainParam(ClassificationEvaluator.labelCol)

    def test_evaluator_params_override(self):
        """evaluate(dataset, params) scores through a COPY carrying the
        override (pyspark convention); the instance is untouched."""
        import pyarrow as pa

        from sparkdl_tpu.estimators import ClassificationEvaluator

        rows = [{"label": 0, "prediction": 0.0, "alt": 1.0},
                {"label": 1, "prediction": 1.0, "alt": 0.0}]
        df = DataFrame.from_batches([pa.RecordBatch.from_pylist(rows)])
        ev = ClassificationEvaluator(predictionCol="prediction")
        assert ev.evaluate(df) == 1.0
        assert ev.evaluate(df, {ev.predictionCol: "alt"}) == 0.0
        assert ev.getOrDefault("predictionCol") == "prediction"
        with pytest.raises(TypeError, match="dict"):
            ev.evaluate(df, [{ev.predictionCol: "alt"}])


class TestTransform:
    def test_transform(self):
        out = AddConst(inputCol="x", outputCol="y", value=10.0) \
            .transform(_df())
        tab = out.collect()
        x = tab.column("x").to_numpy()
        y = tab.column("y").to_numpy()
        np.testing.assert_allclose(y, x + 10.0)

    def test_transform_with_extra_params(self):
        t = AddConst(inputCol="x", outputCol="y", value=1.0)
        out = t.transform(_df(), {t.value: 3.0})
        tab = out.collect()
        np.testing.assert_allclose(tab.column("y").to_numpy(),
                                   tab.column("x").to_numpy() + 3.0)


class TestPipeline:
    def test_pipeline_fit_transform(self):
        p = Pipeline(stages=[
            AddConst(inputCol="x", outputCol="x2", value=1.0),
            MeanEstimator(inputCol="x2", outputCol="m"),
        ])
        model = p.fit(_df(10))
        tab = model.transform(_df(10)).collect()
        # mean of x+1 for x in 0..9 = 5.5
        np.testing.assert_allclose(tab.column("m").to_numpy(), 5.5)

    def test_param_grid(self):
        e = MeanEstimator(inputCol="x", outputCol="m")
        grid = ParamGridBuilder() \
            .addGrid(e.shift, [0.0, 1.0]) \
            .addGrid(e.getParam("outputCol"), ["m1", "m2"]).build()
        assert len(grid) == 4

    def test_fit_multiple(self):
        e = MeanEstimator(inputCol="x", outputCol="m")
        maps = [{e.shift: 0.0}, {e.shift: 10.0}]
        models = dict(e.fitMultiple(_df(10), maps))
        assert models[1].mean == models[0].mean + 10.0

    def test_copy_distributes_stage_params(self):
        """pyspark semantics: a param-map entry keyed by a CHILD
        stage's Param reaches that stage's copy — what
        CrossValidator(Pipeline([...]), grid_on_stage_params) relies
        on (fixed round 5: Pipeline.copy used to resolve the entry
        against the Pipeline itself and raise)."""
        add = AddConst(inputCol="x", outputCol="x2", value=1.0)
        est = MeanEstimator(inputCol="x2", outputCol="m")
        p = Pipeline(stages=[add, est])
        p2 = p.copy({est.shift: 7.0, add.value: 2.0})
        s_add, s_est = p2.getStages()
        assert s_add.getOrDefault("value") == 2.0
        assert s_est.getOrDefault("shift") == 7.0
        # originals untouched (copy-on-write)
        assert add.getOrDefault("value") == 1.0
        assert est.getOrDefault("shift") == 0.0

    def test_fit_with_stage_param_map(self):
        add = AddConst(inputCol="x", outputCol="x2", value=1.0)
        est = MeanEstimator(inputCol="x2", outputCol="m")
        p = Pipeline(stages=[add, est])
        base = p.fit(_df(10)).transform(_df(10)).collect()
        shifted = p.fit(_df(10), {est.shift: 10.0}) \
            .transform(_df(10)).collect()
        np.testing.assert_allclose(
            shifted.column("m").to_numpy(),
            base.column("m").to_numpy() + 10.0)

    def test_pipeline_grid_on_stage_params(self):
        """CrossValidator-shaped: fitMultiple over grids keyed by a
        stage's params."""
        add = AddConst(inputCol="x", outputCol="x2", value=1.0)
        est = MeanEstimator(inputCol="x2", outputCol="m")
        p = Pipeline(stages=[add, est])
        grid = ParamGridBuilder().addGrid(est.shift, [0.0, 5.0]).build()
        models = dict(p.fitMultiple(_df(10), grid))
        m0 = models[0].transform(_df(4)).collect().column("m").to_numpy()
        m1 = models[1].transform(_df(4)).collect().column("m").to_numpy()
        np.testing.assert_allclose(m1, m0 + 5.0)

    def test_foreign_param_still_raises(self):
        stray = MeanEstimator(inputCol="q", outputCol="r")
        p = Pipeline(stages=[AddConst(inputCol="x", outputCol="y")])
        with pytest.raises(AttributeError, match="neither"):
            p.copy({stray.shift: 1.0})

    def test_copy_honors_stages_override(self):
        """Overriding the Pipeline's OWN ``stages`` param must replace
        the stage list — and stage-param entries then distribute over
        the REPLACED stages (fixed round 5: the override was applied
        and immediately overwritten by copies of the old list)."""
        a = AddConst(inputCol="x", outputCol="ya", value=1.0)
        b = AddConst(inputCol="x", outputCol="yb", value=2.0)
        p = Pipeline(stages=[a])
        p2 = p.copy({p.getParam("stages"): [b],
                     b.value: 9.0})
        (s,) = p2.getStages()
        assert s.getOrDefault("outputCol") == "yb"
        assert s.getOrDefault("value") == 9.0
        assert b.getOrDefault("value") == 2.0  # original untouched

    def test_nested_pipeline_param_distribution(self):
        """pyspark forwards extra recursively through nested pipeline
        stages; a grid entry on an inner stage must reach it."""
        add = AddConst(inputCol="x", outputCol="x2", value=1.0)
        est = MeanEstimator(inputCol="x2", outputCol="m")
        outer = Pipeline(stages=[Pipeline(stages=[add, est])])
        o2 = outer.copy({est.shift: 4.0})
        (inner,) = o2.getStages()
        _, s_est = inner.getStages()
        assert s_est.getOrDefault("shift") == 4.0
        base = outer.fit(_df(10)).transform(_df(4)).collect()
        shifted = o2.fit(_df(10)).transform(_df(4)).collect()
        np.testing.assert_allclose(
            shifted.column("m").to_numpy(),
            base.column("m").to_numpy() + 4.0)

    def test_model_transform_with_stage_param(self):
        add = AddConst(inputCol="x", outputCol="x2", value=1.0)
        est = MeanEstimator(inputCol="x2", outputCol="m")
        model = Pipeline(stages=[add, est]).fit(_df(10))
        out = model.transform(_df(4), {add.value: 3.0}).collect()
        np.testing.assert_allclose(out.column("x2").to_numpy(),
                                   out.column("x").to_numpy() + 3.0)


class MAE(Evaluator):
    """Mean |m - x| — lower is better."""

    def evaluate(self, dataset):
        tab = dataset.collect()
        return float(np.abs(tab.column("m").to_numpy()
                            - tab.column("x").to_numpy()).mean())

    def isLargerBetter(self):
        return False


class TestCrossValidator:
    def test_cv_selects_best_shift(self):
        e = MeanEstimator(inputCol="x", outputCol="m")
        grid = [{e.shift: 0.0}, {e.shift: 100.0}]
        cv = CrossValidator(estimator=e, estimatorParamMaps=grid,
                            evaluator=MAE(), numFolds=3)
        cvm = cv.fit(_df(30))
        assert cvm.avgMetrics[0] < cvm.avgMetrics[1]
        assert isinstance(cvm.bestModel, MeanModel)
        # best model trained with shift=0
        assert abs(cvm.bestModel.mean - np.arange(30).mean()) < 1e-9


    def test_cv_excludes_nan_fold_from_all_candidates(self, caplog):
        """ADVICE r5: a fold one candidate nan-skipped (its transform
        emptied the validation side) is excluded from EVERY candidate's
        average — candidates are compared on the same fold subset, and
        avgMetrics stays finite. The well-behaved candidate's average
        must equal its mean over exactly the surviving folds."""
        import logging

        import pyarrow as pa

        from sparkdl_tpu.params.pipeline import EmptyScoredFrameError

        class StrictMAE(MAE):
            def evaluate(self, dataset):
                tab = dataset.collect()
                if tab.num_rows == 0:
                    raise EmptyScoredFrameError("validation side empty")
                return float(np.abs(tab.column("m").to_numpy()
                                    - tab.column("x").to_numpy()).mean())

        class DroppingMeanModel(MeanModel):
            def __init__(self, mean, inputCol, outputCol, drop):
                super().__init__(mean, inputCol, outputCol)
                self._drop = set(drop)

            def _transform(self, dataset):
                out = super()._transform(dataset)
                drop = self._drop

                def _filter(batch):
                    x = batch.column(
                        batch.schema.get_field_index("x")) \
                        .to_numpy(zero_copy_only=False)
                    keep = ~np.isin(x, sorted(drop))
                    return batch.filter(pa.array(keep))

                return out.map_batches(_filter, name="drop",
                                       row_preserving=False)

        class DropMeanEstimator(MeanEstimator):
            dropRows = Param("DropMeanEstimator", "dropRows",
                             "x values the fitted model's transform "
                             "drops")

            def _fit(self, dataset):
                base = super()._fit(dataset)
                drop = (self.getOrDefault("dropRows")
                        if self.isDefined(self.dropRows) else ())
                if drop:
                    return DroppingMeanModel(
                        base.mean, self.getInputCol(),
                        self.getOutputCol(), drop)
                return base

        df = _df(60, parts=5)
        e = DropMeanEstimator(inputCol="x", outputCol="m")
        e._setDefault(dropRows=())
        cv_probe = CrossValidator(estimator=e, estimatorParamMaps=[{}],
                                  evaluator=StrictMAE(), numFolds=3,
                                  seed=7)
        # fold 1's validation x values, from the same deterministic
        # seeded draw the fit will use
        folds = list(cv_probe._kfold(df))
        fold1_valid = folds[1][1].collect().column("x").to_pylist()
        assert fold1_valid  # the engineered skip must be real

        grid = [{e.shift: 0.0},
                {e.shift: 50.0, e.dropRows: tuple(fold1_valid)}]
        cv = CrossValidator(estimator=e, estimatorParamMaps=grid,
                            evaluator=StrictMAE(), numFolds=3, seed=7)
        with caplog.at_level(logging.WARNING,
                             logger="sparkdl_tpu.params.tuning"):
            cvm = cv.fit(df)
        assert np.isfinite(cvm.avgMetrics).all(), cvm.avgMetrics
        assert any("common" in r.getMessage() for r in caplog.records)

        # candidate 0's average over exactly the surviving folds {0, 2}
        expect = []
        for fold, (train, valid) in enumerate(cv._kfold(df)):
            if fold == 1:
                continue
            model = e.fit(train, {e.shift: 0.0})
            expect.append(StrictMAE().evaluate(model.transform(valid)))
        assert cvm.avgMetrics[0] == pytest.approx(
            float(np.mean(expect)))
        # shift=0 still wins on the common subset
        assert cvm.avgMetrics[0] < cvm.avgMetrics[1]
        assert isinstance(cvm.bestModel, MeanModel)

    def test_cv_all_folds_skipped_raises(self):
        """When no fold is scored by every candidate there is no
        common subset to compare on — the fit raises instead of
        returning NaN averages."""
        from sparkdl_tpu.params.pipeline import EmptyScoredFrameError

        class AlwaysEmpty(MAE):
            def evaluate(self, dataset):
                raise EmptyScoredFrameError("empty")

        e = MeanEstimator(inputCol="x", outputCol="m")
        cv = CrossValidator(estimator=e, estimatorParamMaps=[{}],
                            evaluator=AlwaysEmpty(), numFolds=3)
        with pytest.raises(ValueError, match="no fold"):
            cv.fit(_df(30))

    def test_cv_materializes_dataset_once(self):
        """A decode-bearing plan must run ONCE per fit — the old fold
        construction re-collected the frame on every filter_rows call,
        fully re-decoding 2×numFolds times (VERDICT r2 weak #2)."""
        calls = {"n": 0}

        def counting(batch):
            if batch.num_rows:  # ignore zero-row schema probes
                calls["n"] += 1
            return batch

        df = _df(30).map_batches(counting, name="decode")
        e = MeanEstimator(inputCol="x", outputCol="m")
        cv = CrossValidator(estimator=e,
                            estimatorParamMaps=[{e.shift: 0.0}],
                            evaluator=MAE(), numFolds=3)
        cv.fit(df)
        assert calls["n"] == df.num_partitions  # one pass, ever


class StreamingMean(Estimator, HasInputCol, HasOutputCol):
    """Mean estimator that only ever streams partition batches."""

    shift = Param("StreamingMean", "shift", "added to the learned mean",
                  TypeConverters.toFloat)

    @keyword_only
    def __init__(self, *, inputCol=None, outputCol=None, shift=0.0):
        super().__init__()
        self._setDefault(shift=0.0)
        self._set(inputCol=inputCol, outputCol=outputCol, shift=shift)

    def _fit(self, dataset):
        tot, n = 0.0, 0
        idx = None
        for b in dataset.stream():
            if idx is None:
                idx = b.schema.get_field_index(self.getInputCol())
            x = b.column(idx).to_numpy(zero_copy_only=False)
            tot += float(x.sum())
            n += len(x)
        return MeanModel(tot / n + self.getOrDefault("shift"),
                         self.getInputCol(), self.getOutputCol())


class StreamingMAE(Evaluator):
    def evaluate(self, dataset):
        tot, n = 0.0, 0
        for b in dataset.stream():
            m = b.column(b.schema.get_field_index("m")) \
                .to_numpy(zero_copy_only=False)
            x = b.column(b.schema.get_field_index("x")) \
                .to_numpy(zero_copy_only=False)
            tot += float(np.abs(m - x).sum())
            n += len(x)
        return tot / n

    def isLargerBetter(self):
        return False


class TestOutOfCoreTuning:
    def test_folds_disjoint_and_covering(self):
        """Plan-stage fold membership: per fold, train+valid partition
        the rows exactly, deterministically across materializations."""
        cv = CrossValidator(estimator=MeanEstimator(inputCol="x"),
                            estimatorParamMaps=[{}], evaluator=MAE(),
                            numFolds=3, seed=11)
        df = _df(60, parts=5)
        seen = []
        for train, valid in cv._kfold(df):
            tr = set(train.collect().column("x").to_pylist())
            va = set(valid.collect().column("x").to_pylist())
            assert tr | va == set(np.arange(60.0))
            assert not (tr & va)
            # deterministic on re-materialization
            assert set(valid.collect().column("x").to_pylist()) == va
            seen.append(va)
        # the k validation folds partition the dataset
        assert set().union(*seen) == set(np.arange(60.0))
        assert sum(len(s) for s in seen) == 60

    def test_cv_cachedir_fit_never_collects(self, tmp_path,
                                            monkeypatch):
        """VERDICT r3 #3 'done' criterion: with cacheDir, a 3-fold fit
        runs with NO full-table collect() anywhere in the tuning layer
        (streaming estimator + evaluator prove the layer itself is
        bounded-memory), while the upstream plan still runs once."""
        calls = {"n": 0}

        def counting(batch):
            if batch.num_rows:
                calls["n"] += 1
            return batch

        df = _df(60, parts=5).map_batches(counting, name="decode")
        e = StreamingMean(inputCol="x", outputCol="m")
        cv = CrossValidator(estimator=e,
                            estimatorParamMaps=[{e.shift: 0.0},
                                                {e.shift: 100.0}],
                            evaluator=StreamingMAE(), numFolds=3,
                            cacheDir=str(tmp_path))

        def no_collect(self):
            raise AssertionError(
                "tuning layer collected a full table in cacheDir mode")

        monkeypatch.setattr(DataFrame, "collect", no_collect)
        try:
            cvm = cv.fit(df)
        finally:
            monkeypatch.undo()
        assert cvm.avgMetrics[0] < cvm.avgMetrics[1]
        assert calls["n"] == df.num_partitions  # decode-once preserved
        # the per-fit spill subdirectory is cleaned up afterwards
        assert list(tmp_path.iterdir()) == []

    def test_tvs_cachedir_fit_never_collects(self, tmp_path,
                                             monkeypatch):
        e = StreamingMean(inputCol="x", outputCol="m")
        tvs = TrainValidationSplit(
            estimator=e,
            estimatorParamMaps=[{e.shift: 0.0}, {e.shift: 100.0}],
            evaluator=StreamingMAE(), trainRatio=0.75, seed=3,
            cacheDir=str(tmp_path))
        df = _df(80, parts=4)

        def no_collect(self):
            raise AssertionError(
                "tuning layer collected a full table in cacheDir mode")

        monkeypatch.setattr(DataFrame, "collect", no_collect)
        try:
            m = tvs.fit(df)
        finally:
            monkeypatch.undo()
        assert m.validationMetrics[0] < m.validationMetrics[1]
        assert abs(m.bestModel.mean - np.arange(80.0).mean()) < 1e-9
        assert list(tmp_path.iterdir()) == []


class TestTrainValidationSplit:
    def test_selects_best_and_refits_on_full_data(self):
        e = MeanEstimator(inputCol="x", outputCol="m")
        grid = [{e.shift: 0.0}, {e.shift: 100.0}]
        tvs = TrainValidationSplit(estimator=e, estimatorParamMaps=grid,
                                   evaluator=MAE(), trainRatio=0.7,
                                   seed=1)
        m = tvs.fit(_df(40))
        assert len(m.validationMetrics) == 2
        assert m.validationMetrics[0] < m.validationMetrics[1]
        # best model is REFIT on the full dataset with the winning map
        assert abs(m.bestModel.mean - np.arange(40).mean()) < 1e-9
        # the fitted wrapper transforms through the best model
        tab = m.transform(_df(5)).collect()
        np.testing.assert_allclose(tab.column("m").to_numpy(),
                                   np.arange(40).mean())

    def test_split_is_seeded_and_ratio_respected(self):
        e = MeanEstimator(inputCol="x", outputCol="m")
        grid = [{e.shift: 0.0}]
        a = TrainValidationSplit(estimator=e, estimatorParamMaps=grid,
                                 evaluator=MAE(), trainRatio=0.75,
                                 seed=7).fit(_df(200))
        b = TrainValidationSplit(estimator=e, estimatorParamMaps=grid,
                                 evaluator=MAE(), trainRatio=0.75,
                                 seed=7).fit(_df(200))
        assert a.validationMetrics == b.validationMetrics  # same split


class TestSharedParamDistribution:
    def test_multi_stage_claim_warns(self, caplog):
        """A param-map entry carried by several stages applies to all
        of them (documented divergence from pyspark's uid-scoped
        params) — and WARNS so the ambiguity is visible."""
        import logging

        from sparkdl_tpu.params import pipeline as pipeline_mod
        pipeline_mod._warned_shared_claims.clear()  # once-per-process guard
        a1 = AddConst(inputCol="x", outputCol="y1", value=1.0)
        a2 = AddConst(inputCol="x", outputCol="y2", value=2.0)
        p = Pipeline(stages=[a1, a2])
        with caplog.at_level(logging.WARNING,
                             logger="sparkdl_tpu.params.pipeline"):
            p2 = p.copy({a1.value: 9.0})
            p.copy({a1.value: 9.0})  # repeat: deduped
        s1, s2 = p2.getStages()
        assert s1.getOrDefault("value") == 9.0
        assert s2.getOrDefault("value") == 9.0
        hits = [r for r in caplog.records
                if "carried by 2 stages" in r.message]
        assert len(hits) == 1  # warned once, not per copy

    def test_single_stage_claim_is_silent(self, caplog):
        import logging
        add = AddConst(inputCol="x", outputCol="y", value=1.0)
        est = MeanEstimator(inputCol="y", outputCol="m")
        with caplog.at_level(logging.WARNING,
                             logger="sparkdl_tpu.params.pipeline"):
            Pipeline(stages=[add, est]).copy({est.shift: 1.0})
        assert not caplog.records
