"""Flight recorder + stall watchdog + telemetry surface
(sparkdl_tpu/obs/{flight,watchdog,export}.py).

The contracts pinned here, in ISSUE order: disarmed watchdog/flight
instrumentation stays in the tracer's shared-no-op regime (<10 µs per
call, no allocation); an injected dispatcher stall fires the watchdog
within its threshold, flips /healthz to 503, and produces a
self-contained bundle carrying recent spans + a registry snapshot with
``watchdog.stalls`` >= 1 + the serve queue state; recovery clears the
verdict; /metricsz renders valid Prometheus text with kinds preserved;
SIGUSR2 and dispatch-failure triggers dump; everything degrades
gracefully (no backend, no signal) and survives cloudpickle.
"""

import json
import os
import re
import signal
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.obs import default_registry, tracer
from sparkdl_tpu.obs import flight, watchdog
from sparkdl_tpu.obs.export import (
    TelemetryServer,
    prom_name,
    render_prometheus,
)
from sparkdl_tpu.obs.registry import MetricsRegistry
from sparkdl_tpu.obs.watchdog import StallWatchdog
from sparkdl_tpu.serve import ModelServer, ServeConfig


def _get(url: str):
    try:
        with urllib.request.urlopen(url, timeout=5) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _wait_for(predicate, timeout=10.0, what="condition"):
    deadline = time.perf_counter() + timeout
    while not predicate():
        assert time.perf_counter() < deadline, f"timed out on {what}"
        time.sleep(0.01)


def _blocking_host_model(gate: threading.Event,
                         name: str = "wedge") -> ModelFunction:
    """A host-backend model whose apply blocks on ``gate`` — the
    synthetic stall: the serve dispatcher wedges INSIDE a dispatch,
    the silent-hang shape of the collective-launch deadlock."""

    def blocked_apply(params, inputs):
        gate.wait()
        return {"y": np.asarray(inputs["x"], np.float32) * 2.0}

    return ModelFunction(blocked_apply, None,
                         input_signature={"x": ((2,), np.float32)},
                         output_names=["y"], backend="host", name=name)


@pytest.fixture()
def armed_singleton_watchdog():
    """The process-wide watchdog armed with a test-speed threshold and
    restored afterwards (other tests must see it disarmed)."""
    wd = watchdog.watchdog()
    wd.arm(threshold_s=0.2)
    yield wd
    wd.disarm()
    wd._threshold_override = None


@pytest.fixture()
def flight_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("SPARKDL_TPU_FLIGHT_DIR", str(tmp_path))
    return tmp_path


# ---------------------------------------------------------------------------
# watchdog core


class TestWatchdog:
    def test_disarmed_watch_is_shared_noop(self, monkeypatch):
        monkeypatch.delenv("SPARKDL_TPU_WATCHDOG", raising=False)
        wd = watchdog.watchdog()
        assert not wd.armed
        # one shared object back for every disarmed call — no
        # allocation, no tracking
        assert watchdog.watch("a") is watchdog.watch("b")
        watchdog.pulse("a")     # ignored, no entry created
        assert wd.verdict()["active_sources"] == {}

    def test_disarmed_overhead(self, monkeypatch):
        """The ISSUE's acceptance bound: disarmed heartbeats ride the
        same <10 µs/call regime the tracer's no-op span is pinned to
        (min over repeats — noise only ever adds time)."""
        monkeypatch.delenv("SPARKDL_TPU_WATCHDOG", raising=False)
        n = 20_000
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(n):
                watchdog.pulse("hot.loop")
                with watchdog.watch("hot.loop"):
                    pass
            best = min(best, (time.perf_counter() - t0) / n)
        assert best < 10e-6, \
            f"disarmed pulse+watch costs {best * 1e6:.2f} µs"

    def test_stall_fires_counter_and_recovers(self):
        wd = StallWatchdog()
        wd.arm(threshold_s=0.05)
        try:
            reg = default_registry()
            before = reg.counter("watchdog.stalls").value
            with wd.watch("test.loop"):
                _wait_for(lambda: not wd.healthy(), timeout=5.0,
                          what="stall verdict")
                v = wd.verdict()
                assert v["stalled_sources"] == ["test.loop"]
                assert v["stalls_fired"] >= 1
                assert reg.counter("watchdog.stalls").value > before
                # progress resumes -> the verdict clears (no restart)
                wd.pulse("test.loop")
                _wait_for(wd.healthy, timeout=5.0, what="recovery")
            assert wd.verdict()["active_sources"] == {}
        finally:
            wd.disarm()

    def test_pulsing_loop_never_stalls(self):
        wd = StallWatchdog()
        wd.arm(threshold_s=0.1)
        try:
            with wd.watch("busy.loop"):
                end = time.perf_counter() + 0.35
                while time.perf_counter() < end:
                    wd.pulse("busy.loop")
                    time.sleep(0.01)
                assert wd.healthy()
            assert wd.stalls_fired == 0
        finally:
            wd.disarm()

    def test_idle_is_not_a_stall(self):
        """No active watch window → nothing to flag, however long the
        process sits idle (the serve dispatcher opens its window only
        after collect() returns work)."""
        wd = StallWatchdog()
        wd.arm(threshold_s=0.02)
        try:
            time.sleep(0.1)
            assert wd.healthy()
            assert wd.check_once() == []
        finally:
            wd.disarm()

    def test_end_without_armed_cleans_up(self):
        """A disarm between begin and end must not leak an active
        source into a false stall after re-arming."""
        wd = StallWatchdog()
        wd.arm(threshold_s=0.05)
        try:
            ctx = wd.watch("flip.loop")
            ctx.__enter__()
            wd.disarm()
            ctx.__exit__(None, None, None)
            wd.arm(threshold_s=0.05)
            time.sleep(0.15)
            assert wd.healthy(), wd.verdict()
        finally:
            wd.disarm()

    def test_collective_hold_feeds_watchdog(
            self, armed_singleton_watchdog):
        from sparkdl_tpu.parallel import mesh
        with mesh._COLLECTIVE_LAUNCH:
            active = watchdog.watchdog().verdict()["active_sources"]
            assert "collective.hold" in active
        active = watchdog.watchdog().verdict()["active_sources"]
        assert "collective.hold" not in active

    def test_dispatch_chunks_feeds_watchdog(
            self, armed_singleton_watchdog):
        """An offline runner.run registers (and deregisters) a
        ship-dispatch source — the batch path is covered, not just
        serving."""
        from sparkdl_tpu.runtime.runner import BatchRunner
        mf = ModelFunction.fromSingle(lambda x: x * 2.0, None,
                                      input_shape=(3,))
        x = np.arange(24, dtype=np.float32).reshape(8, 3)
        out = BatchRunner(mf, batch_size=4).run({"input": x})
        np.testing.assert_allclose(out["output"], x * 2)
        # the window closed with the run: nothing left active
        active = watchdog.watchdog().verdict()["active_sources"]
        assert not any(s.startswith("ship.dispatch") for s in active)

    def test_env_threshold_typo_degrades(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_TPU_WATCHDOG_THRESHOLD_S", "soon")
        wd = StallWatchdog()
        assert wd.threshold_s == watchdog.DEFAULT_THRESHOLD_S

    def test_pickle_drops_runtime_state(self):
        import cloudpickle as cp
        wd = StallWatchdog()
        wd.arm(threshold_s=1.5)
        try:
            with wd.watch("here"):
                wd2 = cp.loads(cp.dumps(wd))
            assert wd2.armed
            assert wd2.threshold_s == 1.5
            # active sources are process-local and did not travel
            assert wd2.verdict()["active_sources"] == {}
        finally:
            wd.disarm()


# ---------------------------------------------------------------------------
# flight recorder


class TestFlightRecorder:
    def test_dump_bundle_is_self_contained(self, tmp_path):
        rec = flight.FlightRecorder()
        trc = tracer()
        trc.arm()
        try:
            with trc.span("work", lane="engine", rows=1):
                pass
            default_registry().counter("test.flight.counter").add(3)
            path = rec.dump(path=str(tmp_path / "bundle.json"),
                            reason="unit test")
        finally:
            trc.disarm()
            trc.arm_from_env()
            trc.clear()
        with open(path) as f:
            bundle = json.load(f)
        assert bundle["schema"] == flight.BUNDLE_SCHEMA
        assert bundle["reason"] == "unit test"
        assert bundle["pid"] == os.getpid()
        assert bundle["span_count"] >= 1
        names = {e.get("name") for e in bundle["spans"]}
        assert "work" in names
        assert bundle["registry"]["test.flight.counter"] == 3.0
        assert "watchdog" in bundle and "healthy" in bundle["watchdog"]
        assert "platform" in bundle and "memory_stats" in bundle
        assert isinstance(bundle["serve"], list)
        assert rec.dumps == 1
        assert rec.last_dump_path == path

    def test_memory_stats_degrades_not_raises(self):
        stats = flight.memory_stats()
        assert isinstance(stats, dict)   # CPU: values may be None

    def test_record_failure_counts_but_only_dumps_armed(
            self, flight_dir):
        rec = flight.FlightRecorder()
        reg = default_registry()
        before = reg.counter("flight.failures").value
        assert rec.record_failure(RuntimeError("x"), "unit") is None
        assert reg.counter("flight.failures").value == before + 1
        rec._armed_override = True   # arm WITHOUT the signal handler
        path = rec.record_failure(RuntimeError("y"), "unit")
        assert path is not None and os.path.exists(path)
        with open(path) as f:
            assert "unit" in json.load(f)["reason"]

    def test_sigusr2_dumps(self, flight_dir):
        rec = flight.recorder()
        old_handler = signal.getsignal(signal.SIGUSR2)
        before = rec.dumps
        rec.arm()
        try:
            os.kill(os.getpid(), signal.SIGUSR2)
            _wait_for(lambda: rec.dumps > before, timeout=10.0,
                      what="SIGUSR2 dump")
            with open(rec.last_dump_path) as f:
                assert json.load(f)["reason"] == "SIGUSR2"
        finally:
            rec.disarm()
            tracer().arm_from_env()
            signal.signal(signal.SIGUSR2, old_handler)
            rec._signal_installed = False

    def test_serve_dispatch_failure_triggers_dump(self, flight_dir):
        """The unhandled-failure trigger: a dispatch that raises fails
        its futures (PR-4 contract) AND, armed, leaves a bundle naming
        the failure."""
        rec = flight.recorder()
        rec._armed_override = True
        before = rec.dumps

        def boom(params, inputs):
            raise RuntimeError("synthetic dispatch failure")

        mf = ModelFunction(boom, None,
                           input_signature={"x": ((2,), np.float32)},
                           output_names=["y"], backend="host",
                           name="boom")
        server = ModelServer(ServeConfig(max_wait_s=0.0))
        try:
            server.register("boom", mf, batch_size=4)
            fut = server.submit({"x": np.zeros((2, 2), np.float32)})
            with pytest.raises(RuntimeError, match="synthetic"):
                fut.result(timeout=10)
            _wait_for(lambda: rec.dumps > before, timeout=10.0,
                      what="failure dump")
            with open(rec.last_dump_path) as f:
                bundle = json.load(f)
            assert "serve.dispatch:boom" in bundle["reason"]
            [srv] = [s for s in bundle["serve"]
                     if "boom" in s.get("models", {})]
            assert srv["models"]["boom"]["runner"]["type"] == \
                "BatchRunner"
        finally:
            server.close()
            rec._armed_override = None

    def test_autoarm_follows_env(self, monkeypatch, flight_dir):
        rec = flight.FlightRecorder()
        monkeypatch.setattr(flight, "_RECORDER", rec)
        monkeypatch.delenv("SPARKDL_TPU_FLIGHT", raising=False)
        assert flight.autoarm() is False
        monkeypatch.setenv("SPARKDL_TPU_FLIGHT", "1")
        # ModelServer construction applies the env's side effects
        server = ModelServer()
        try:
            assert rec.armed
        finally:
            server.close()
            tracer().arm_from_env()
            tracer().clear()

    def test_pickle_travels_armedness_not_history(self):
        import cloudpickle as cp
        rec = flight.FlightRecorder()
        rec._armed_override = True
        rec.dumps = 7
        rec2 = cp.loads(cp.dumps(rec))
        assert rec2.armed
        # history travels as data; the signal handler does not
        assert rec2.dumps == 7
        assert rec2._signal_installed is False


# ---------------------------------------------------------------------------
# telemetry endpoint + prometheus rendering


_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? "
    r"[-+]?([0-9]*\.?[0-9]+([eE][-+]?[0-9]+)?|nan|inf)$")


def _assert_valid_prometheus(text: str) -> int:
    n = 0
    for line in text.strip().splitlines():
        if not line:
            continue
        if line.startswith("#"):
            assert re.match(r"^# (TYPE|HELP) ", line), repr(line)
            continue
        assert _PROM_SAMPLE.match(line), f"bad line: {line!r}"
        n += 1
    return n


class TestPrometheusRendering:
    def test_kinds_and_names(self):
        reg = MetricsRegistry()
        reg.counter("ship.rows").add(5)
        reg.gauge("serve.queue_rows").set(3)
        res = reg.reservoir("serve.latency_seconds")
        for v in (0.1, 0.2, 0.3):
            res.observe(v)
        text = render_prometheus(reg)
        assert "# TYPE sparkdl_ship_rows counter" in text
        assert "sparkdl_ship_rows 5" in text
        assert "# TYPE sparkdl_serve_queue_rows gauge" in text
        assert "# TYPE sparkdl_serve_latency_seconds_count counter" \
            in text
        assert "sparkdl_serve_latency_seconds_p99" in text
        assert _assert_valid_prometheus(text) == 5

    def test_name_sanitization(self):
        assert prom_name("a.b-c d") == "sparkdl_a_b_c_d"

    def test_default_registry_renders_valid(self):
        default_registry().counter("flight.dumps")  # ensure non-empty
        assert _assert_valid_prometheus(
            render_prometheus(default_registry())) > 0


class TestTelemetryEndpoints:
    def test_standalone_endpoints(self):
        reg = MetricsRegistry()
        reg.counter("test.requests").add(2)
        with TelemetryServer(registry=reg) as tel:
            assert tel.port > 0
            code, body = _get(tel.url("/metricsz"))
            assert code == 200
            assert "sparkdl_test_requests 2" in body
            _assert_valid_prometheus(body)
            code, body = _get(tel.url("/healthz"))
            assert code == 200
            assert json.loads(body)["status"] == "ok"
            code, body = _get(tel.url("/statusz"))
            assert code == 200
            st = json.loads(body)
            assert st["pid"] == os.getpid()
            assert st["uptime_s"] >= 0
            assert "watchdog" in st and "flight" in st
            code, _body = _get(tel.url("/nope"))
            assert code == 404

    def test_model_server_statusz_and_close(self):
        mf = ModelFunction.fromSingle(lambda x: x * 2.0, None,
                                      input_shape=(3,))
        server = ModelServer(ServeConfig(max_wait_s=0.0))
        server.register("m", mf, batch_size=4)
        tel = server.serve_telemetry()
        try:
            code, body = _get(tel.url("/statusz"))
            assert code == 200
            st = json.loads(body)
            [srv] = st["servers"]
            model = srv["models"]["m"]
            assert model["warmed"] is None       # not warmed yet
            assert model["queue_rows"] == 0
            assert model["chunk"] == 4
            assert model["runner"]["type"] == "BatchRunner"
            assert model["runner"]["strategy"] in (
                "immediate", "deferred", "host_async", "prefetch")
            server.warmup()
            code, body = _get(tel.url("/statusz"))
            st = json.loads(body)
            assert st["servers"][0]["models"]["m"]["warmed"] is True
            port = tel.port
        finally:
            server.close()
        # close() took the attached endpoint down with the server
        with pytest.raises(urllib.error.URLError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=1)

    def test_serve_telemetry_is_idempotent(self):
        server = ModelServer()
        try:
            t1 = server.serve_telemetry()
            assert server.serve_telemetry() is t1
        finally:
            server.close()


# ---------------------------------------------------------------------------
# the end-to-end injected stall (the acceptance scenario)


class TestInjectedStall:
    def test_stall_dump_health_and_recovery(
            self, flight_dir, armed_singleton_watchdog):
        rec = flight.recorder()
        rec._armed_override = True    # arm triggers; skip the signal
        trc = tracer()
        trc.arm()
        gate = threading.Event()
        server = ModelServer(ServeConfig(max_wait_s=0.0,
                                         drain_timeout_s=5.0))
        tel = None
        try:
            server.register("wedge", _blocking_host_model(gate),
                            batch_size=4)
            tel = server.serve_telemetry()
            before = rec.dumps
            fut = server.submit({"x": np.zeros((2, 2), np.float32)})
            wd = watchdog.watchdog()
            _wait_for(lambda: not wd.healthy(), what="stall verdict")

            code, body = _get(tel.url("/healthz"))
            assert code == 503, (code, body)
            health = json.loads(body)
            assert health["status"] == "stalled"
            assert any("serve.dispatcher:wedge" in s
                       for s in health["stalled_sources"]), health

            _wait_for(lambda: rec.dumps > before, what="stall dump")
            with open(rec.last_dump_path) as f:
                bundle = json.load(f)
            assert bundle["span_count"] >= 1
            assert bundle["registry"].get("watchdog.stalls", 0) >= 1
            [srv] = [s for s in bundle["serve"]
                     if "wedge" in s.get("models", {})]
            assert srv["models"]["wedge"]["chunk"] == 4
            assert "watchdog stall" in bundle["reason"]

            gate.set()
            out = fut.result(timeout=10)
            assert out["y"].shape == (2, 2)
            _wait_for(wd.healthy, what="recovery")
            code, body = _get(tel.url("/healthz"))
            assert code == 200, (code, body)
            code, body = _get(tel.url("/metricsz"))
            assert code == 200
            assert _assert_valid_prometheus(body) > 0
            assert "sparkdl_watchdog_stalls" in body
        finally:
            gate.set()
            server.close()
            if tel is not None:
                tel.close()
            rec._armed_override = None
            trc.disarm()
            trc.arm_from_env()
            trc.clear()
