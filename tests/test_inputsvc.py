"""Disaggregated input service + snapshot tier tests
(sparkdl_tpu/inputsvc/, docs/DATA_SERVICE.md): socket transport
framing, remote-fleet decode with exact row identity, fault drills at
the two new sites, loud degrade paths (unreachable fleet, killed
worker, malformed endpoint spec), the snapshot invalidation matrix
(corpus change, decode-config change, truncated/corrupted chunk,
manifest version bump — each forces a clean re-decode, never a silent
stale read or a crash), the ledger's scaled decode ceiling, and the
``python -m sparkdl_tpu.inputsvc serve`` CLI."""

import json
import os
import pickle
import socket
import struct
import subprocess
import sys
import time

import pyarrow as pa
import pyarrow.compute as pc
import pytest

from sparkdl_tpu.data.engine import LocalEngine
from sparkdl_tpu.data.frame import DataFrame
from sparkdl_tpu.inputsvc import (
    DecodeServer,
    RemotePipeline,
    TransportError,
    recv_msg,
    resolve_endpoints,
    send_msg,
    snapshot_key,
)
from sparkdl_tpu.inputsvc import client as isvc_client
from sparkdl_tpu.inputsvc import snapshot as isvc_snapshot
from sparkdl_tpu.inputsvc import transport as isvc_transport
from sparkdl_tpu.obs import default_registry
from sparkdl_tpu.resilience import faults as rfaults


@pytest.fixture(autouse=True)
def _disarm_faults():
    """Every test here starts and ends with the fault plane disarmed —
    injection is per-test, never ambient."""
    rfaults.disarm()
    yield
    rfaults.disarm()


def _counter(name):
    return default_registry().snapshot().get(name, 0.0)


def _table(n=100):
    return pa.table({"id": pa.array(range(n), type=pa.int64()),
                     "x": pa.array([float(i) for i in range(n)],
                                   type=pa.float64())})


def _double(batch):
    i = batch.schema.get_field_index("x")
    return batch.set_column(i, "x", pc.multiply(batch.column("x"), 2.0))


def _collect(engine, n=100, parts=8):
    df = DataFrame.from_table(_table(n), parts, engine)
    return df.map_batches(_double, name="double").collect()


@pytest.fixture()
def server():
    srv = DecodeServer().start()
    yield srv
    srv.close()


@pytest.fixture()
def local_result():
    engine = LocalEngine(num_workers=0)
    try:
        return _collect(engine)
    finally:
        engine.shutdown()


# ---------------------------------------------------------------------------
# transport framing
# ---------------------------------------------------------------------------

class TestTransport:
    def test_round_trip(self):
        a, b = socket.socketpair()
        try:
            send_msg(a, {"kind": "ping", "n": 7}, b"payload-bytes")
            header, payload = recv_msg(b)
            assert header == {"kind": "ping", "n": 7}
            assert payload == b"payload-bytes"
        finally:
            a.close()
            b.close()

    def test_empty_payload(self):
        a, b = socket.socketpair()
        try:
            send_msg(a, {"kind": "ok"})
            header, payload = recv_msg(b)
            assert header["kind"] == "ok" and payload == b""
        finally:
            a.close()
            b.close()

    def test_bad_magic_raises_transport_error(self):
        a, b = socket.socketpair()
        try:
            a.sendall(b"XXXX" + b"\x00" * 14)
            with pytest.raises(TransportError):
                recv_msg(b)
        finally:
            a.close()
            b.close()

    def test_oversized_header_rejected(self):
        a, b = socket.socketpair()
        try:
            prefix = struct.pack(
                ">4sHIQ", isvc_transport.MAGIC,
                isvc_transport.WIRE_VERSION,
                isvc_transport.MAX_HEADER_BYTES + 1, 0)
            a.sendall(prefix)
            with pytest.raises(TransportError):
                recv_msg(b)
        finally:
            a.close()
            b.close()

    def test_truncated_stream_raises(self):
        a, b = socket.socketpair()
        try:
            send_msg(a, {"kind": "ping"}, b"full-payload")
            a.close()
            recv_msg(b)                     # the complete message
            with pytest.raises(TransportError):
                recv_msg(b)                 # peer gone mid-frame
        finally:
            b.close()

    def test_parse_endpoint(self):
        assert isvc_transport.parse_endpoint("127.0.0.1:80") == \
            ("127.0.0.1", 80)
        assert isvc_transport.parse_endpoint("host:0") is None
        assert isvc_transport.parse_endpoint("no-port") is None
        assert isvc_transport.parse_endpoint("h:notanint") is None
        assert isvc_transport.parse_endpoint("h:99999") is None
        assert isvc_transport.parse_endpoint("") is None


# ---------------------------------------------------------------------------
# endpoint config resolution
# ---------------------------------------------------------------------------

class TestResolveEndpoints:
    def test_explicit_string_and_list(self):
        assert resolve_endpoints("h1:1234, h2:5678") == \
            [("h1", 1234), ("h2", 5678)]
        assert resolve_endpoints(["h1:1234"]) == [("h1", 1234)]

    def test_env_spec(self, monkeypatch):
        monkeypatch.setenv(isvc_client.ENV_ENDPOINTS,
                           "h1:1111,h2:2222")
        assert resolve_endpoints() == [("h1", 1111), ("h2", 2222)]

    def test_malformed_spec_degrades_whole_fleet(self, monkeypatch,
                                                 caplog):
        """ANY malformed entry drops the WHOLE spec (a partial fleet
        is a different deployment than the one configured), with one
        warning and a counted config error — never a crash."""
        before = _counter("inputsvc.config_errors")
        monkeypatch.setenv(isvc_client.ENV_ENDPOINTS,
                           "h1:1111;badness")
        with caplog.at_level(
                "WARNING", logger="sparkdl_tpu.inputsvc.client"):
            assert resolve_endpoints() == []
        assert _counter("inputsvc.config_errors") == before + 1
        assert any("badness" in r.getMessage()
                   for r in caplog.records)

    def test_empty_env_means_no_fleet(self, monkeypatch):
        monkeypatch.delenv(isvc_client.ENV_ENDPOINTS, raising=False)
        assert resolve_endpoints() == []


# ---------------------------------------------------------------------------
# remote decode: identity, fleet fan-out, degrade paths
# ---------------------------------------------------------------------------

class TestRemoteDecode:
    def test_identity_single_worker(self, server, local_result):
        engine = LocalEngine(
            inputsvc_endpoints=f"127.0.0.1:{server.port}")
        try:
            out = _collect(engine)
        finally:
            engine.shutdown()
        assert out.equals(local_result)
        snap = default_registry().snapshot()
        assert snap.get("inputsvc.server_requests", 0) > 0

    def test_identity_two_worker_fleet(self, local_result):
        s1, s2 = DecodeServer().start(), DecodeServer().start()
        try:
            engine = LocalEngine(inputsvc_endpoints=[
                f"127.0.0.1:{s1.port}", f"127.0.0.1:{s2.port}"])
            try:
                out = _collect(engine)
            finally:
                engine.shutdown()
        finally:
            s1.close()
            s2.close()
        assert out.equals(local_result)

    def test_rows_and_tasks_counted(self, server):
        rows0 = _counter("inputsvc.rows")
        tasks0 = _counter("inputsvc.tasks")
        engine = LocalEngine(
            inputsvc_endpoints=f"127.0.0.1:{server.port}")
        try:
            _collect(engine, n=60, parts=6)
        finally:
            engine.shutdown()
        assert _counter("inputsvc.rows") == rows0 + 60
        assert _counter("inputsvc.tasks") == tasks0 + 6

    def test_unreachable_fleet_falls_back_loudly(self, local_result,
                                                 caplog):
        """A fleet that never answers degrades to LOCAL decode for the
        whole stream — correct rows, counted fallback, one warning."""
        fb0 = _counter("inputsvc.fallbacks")
        # a port from the ephemeral range with nothing listening
        sock = socket.create_server(("127.0.0.1", 0))
        port = sock.getsockname()[1]
        sock.close()                        # nothing listens now
        engine = LocalEngine(inputsvc_endpoints=f"127.0.0.1:{port}")
        try:
            with caplog.at_level(
                    "WARNING", logger="sparkdl_tpu.inputsvc.client"):
                out = _collect(engine)
        finally:
            engine.shutdown()
        assert out.equals(local_result)
        assert _counter("inputsvc.fallbacks") == fb0 + 1

    def test_killed_worker_fails_over_per_partition(self,
                                                    local_result):
        """A worker that dies MID-STREAM: every partition still lands
        exactly once, through per-partition local failover."""
        srv = DecodeServer().start()
        ld0 = _counter("inputsvc.local_decodes")
        engine = LocalEngine(
            inputsvc_endpoints=f"127.0.0.1:{srv.port}")
        try:
            srv.close()                     # dies before the stream
            out = _collect(engine)
        finally:
            engine.shutdown()
        assert out.equals(local_result)
        snap = default_registry().snapshot()
        assert (snap.get("inputsvc.local_decodes", 0) > ld0
                or snap.get("inputsvc.fallbacks", 0) > 0)

    def test_rpc_fault_drill_keeps_identity(self, server,
                                            local_result):
        """10%+ transient injection at ``inputsvc.rpc``: the shared
        RetryPolicy re-runs the fragment, rows stay exact — zero
        lost, zero duplicated."""
        inj0 = _counter("faults.inputsvc.rpc.injected")
        rfaults.inject("inputsvc.rpc", "transient", 0.3, seed=7)
        engine = LocalEngine(
            inputsvc_endpoints=f"127.0.0.1:{server.port}")
        try:
            out = _collect(engine)
        finally:
            engine.shutdown()
            rfaults.disarm()
        assert out.equals(local_result)
        assert _counter("faults.inputsvc.rpc.injected") > inj0

    def test_engine_pickles_without_sockets(self, server):
        """H3: connections are per-stream — a pickled engine carries
        endpoint STRINGS, never live sockets."""
        engine = LocalEngine(
            inputsvc_endpoints=f"127.0.0.1:{server.port}")
        try:
            _collect(engine)                # opens + closes conns
            clone = pickle.loads(pickle.dumps(engine))
            assert clone.inputsvc_endpoints == \
                engine.inputsvc_endpoints
        finally:
            engine.shutdown()

    def test_server_refuses_pickle(self, server):
        with pytest.raises(TypeError):
            pickle.dumps(server)

    def test_remote_pipeline_none_without_endpoints(self):
        assert RemotePipeline([]).stream(
            [], [], LocalEngine(num_workers=0)) is None

    def test_client_state_shape(self, server):
        """ONE state() shape shared by /statusz, flight bundles, and
        the bench block."""
        engine = LocalEngine(
            inputsvc_endpoints=f"127.0.0.1:{server.port}")
        try:
            _collect(engine)
        finally:
            engine.shutdown()
        st = isvc_client.state()
        for key in ("endpoints", "live_endpoints", "streams_active",
                    "workers_live", "counters"):
            assert key in st, key
        assert st["streams_active"] == 0
        assert all(k.startswith("inputsvc.")
                   for k in st["counters"])


# ---------------------------------------------------------------------------
# observability integration
# ---------------------------------------------------------------------------

class TestObsIntegration:
    def test_ledger_decode_ceiling_scales_with_fleet(self, server):
        """The remote fleet ADDS decode lanes: a window that covers a
        remote stream divides decode busy by (local + remote) workers
        — the CI drill's assertion surface."""
        from sparkdl_tpu.obs.ledger import UtilizationLedger
        led = UtilizationLedger(window_s=1.0, history=4)
        led.ensure_ceilings({"link_h2d_MBps": 1.0,
                             "link_d2h_MBps": 1.0, "source": "test"})
        led.baseline(now=0.0)               # drains stale peaks
        other = DecodeServer().start()
        engine = LocalEngine(inputsvc_endpoints=[
            f"127.0.0.1:{server.port}",
            f"127.0.0.1:{other.port}"])
        try:
            _collect(engine)
        finally:
            engine.shutdown()
            other.close()
        w = led.tick(now=1.0)
        assert w is not None
        assert w["decode_workers"] >= 2     # the remote fleet's lanes

    def test_statusz_and_flight_carry_inputsvc(self, server):
        from sparkdl_tpu.obs import export as obs_export
        from sparkdl_tpu.obs import flight as obs_flight
        engine = LocalEngine(
            inputsvc_endpoints=f"127.0.0.1:{server.port}")
        try:
            _collect(engine)
        finally:
            engine.shutdown()
        st = obs_flight.inputsvc_state()
        assert "endpoints" in st
        with obs_export.TelemetryServer(
                registry=default_registry()) as tel:
            import urllib.request
            with urllib.request.urlopen(
                    tel.url("/statusz"), timeout=10) as resp:
                statusz = json.loads(resp.read())
        assert "inputsvc" in statusz
        assert "endpoints" in statusz["inputsvc"]
        bundle = obs_flight.recorder().bundle(reason="test")
        assert "inputsvc" in bundle
        assert "endpoints" in bundle["inputsvc"]

    def test_remote_telemetry_frames_ingested(self, server,
                                              monkeypatch):
        """With remote telemetry forced on, decode replies carry
        TelemetryAgent frames and the worker shows up in the
        aggregator — same plane as pool workers."""
        from sparkdl_tpu.obs import remote as obs_remote
        monkeypatch.setenv(obs_remote.ENV_REMOTE, "1")
        agg = obs_remote.aggregator()
        agg.clear()
        engine = LocalEngine(
            inputsvc_endpoints=f"127.0.0.1:{server.port}")
        try:
            _collect(engine)
        finally:
            engine.shutdown()
        try:
            assert len(agg.workers_status()) >= 1
        finally:
            agg.clear()

    def test_disarmed_pin_new_sites(self):
        """The two new sites ride the same <10 µs disarmed regime as
        every other site (min over repeats — noise only adds time)."""
        for site in ("inputsvc.rpc", "snapshot.read"):
            assert site in rfaults.SITES
            best = float("inf")
            for _ in range(5):
                t0 = time.perf_counter()
                for _ in range(2000):
                    rfaults.maybe_fail(site)
                best = min(best, (time.perf_counter() - t0) / 2000)
            assert best < 10e-6, \
                f"disarmed {site} costs {best * 1e6:.2f} µs"


# ---------------------------------------------------------------------------
# snapshot tier
# ---------------------------------------------------------------------------

class TestSnapshot:
    def _base(self, engine, n=100, parts=8):
        df = DataFrame.from_table(_table(n), parts, engine)
        return df.map_batches(_double, name="double")

    def test_cold_then_warm_epoch(self, tmp_path, local_result):
        """Epoch 1 decodes + persists; epoch 2 streams packed chunks
        with decode busy-seconds ≈ 0 — the amortization the tier
        exists for."""
        engine = LocalEngine(num_workers=0)
        reg = default_registry()
        try:
            base = self._base(engine)
            m0 = _counter("inputsvc.snapshot_misses")
            cold = base.snapshot(str(tmp_path), fingerprint="c1")
            assert cold.collect().equals(local_result)
            assert _counter("inputsvc.snapshot_misses") == m0 + 8

            h0 = _counter("inputsvc.snapshot_hits")
            busy0 = reg.counter("engine.busy_seconds").value
            warm = base.snapshot(str(tmp_path), fingerprint="c1")
            assert warm.collect().equals(local_result)
            warm_busy = reg.counter("engine.busy_seconds").value \
                - busy0
            assert _counter("inputsvc.snapshot_hits") == h0 + 8
            assert warm_busy < 0.1, warm_busy
        finally:
            engine.shutdown()

    def test_schema_preserved(self, tmp_path):
        engine = LocalEngine(num_workers=0)
        try:
            base = self._base(engine)
            snapped = base.snapshot(str(tmp_path), fingerprint="c1")
            assert snapped.schema.equals(base.schema)
            out = snapped.collect()
            assert out.schema.equals(base.collect().schema)
        finally:
            engine.shutdown()

    def test_corpus_change_changes_key(self):
        assert snapshot_key("corpus-a", "plan") != \
            snapshot_key("corpus-b", "plan")

    def test_decode_config_change_changes_key(self, tmp_path):
        """A different stage list lands in a DIFFERENT store — the
        old snapshot can never serve the new decode config."""
        assert snapshot_key("c1", "double") != \
            snapshot_key("c1", "double,resize")
        engine = LocalEngine(num_workers=0)
        try:
            base = self._base(engine)
            base.snapshot(str(tmp_path), fingerprint="c1").collect()

            def triple(batch):
                i = batch.schema.get_field_index("x")
                return batch.set_column(
                    i, "x", pc.multiply(batch.column("x"), 3.0))

            df = DataFrame.from_table(_table(), 8, engine)
            other = df.map_batches(triple, name="triple")
            m0 = _counter("inputsvc.snapshot_misses")
            out = other.snapshot(str(tmp_path),
                                 fingerprint="c1").collect()
            # a fresh key => cold decode, and the rows are the NEW
            # plan's rows, not the stale double-plan chunks
            assert _counter("inputsvc.snapshot_misses") == m0 + 8
            assert out.column("x").to_pylist()[1] == 3.0
            assert len(os.listdir(tmp_path)) == 2
        finally:
            engine.shutdown()

    def _store_dir(self, root):
        dirs = [d for d in os.listdir(root)
                if os.path.isdir(os.path.join(root, d))]
        assert len(dirs) == 1, dirs
        return os.path.join(root, dirs[0])

    def test_corrupted_chunk_re_decodes(self, tmp_path,
                                        local_result):
        engine = LocalEngine(num_workers=0)
        try:
            base = self._base(engine)
            base.snapshot(str(tmp_path), fingerprint="c1").collect()
            store = self._store_dir(tmp_path)
            chunk = sorted(f for f in os.listdir(store)
                           if f.endswith(".snap"))[0]
            with open(os.path.join(store, chunk), "r+b") as f:
                f.seek(60)
                f.write(b"\xff\xff\xff")
            c0 = _counter("inputsvc.snapshot_corruptions")
            out = base.snapshot(str(tmp_path),
                                fingerprint="c1").collect()
            assert out.equals(local_result)
            assert _counter("inputsvc.snapshot_corruptions") == c0 + 1
        finally:
            engine.shutdown()

    def test_truncated_chunk_re_decodes(self, tmp_path,
                                        local_result):
        engine = LocalEngine(num_workers=0)
        try:
            base = self._base(engine)
            base.snapshot(str(tmp_path), fingerprint="c1").collect()
            store = self._store_dir(tmp_path)
            chunk = sorted(f for f in os.listdir(store)
                           if f.endswith(".snap"))[0]
            path = os.path.join(store, chunk)
            with open(path, "r+b") as f:
                f.truncate(20)              # mid-header truncation
            out = base.snapshot(str(tmp_path),
                                fingerprint="c1").collect()
            assert out.equals(local_result)
            # the bad chunk was replaced by a fresh, valid one
            assert os.path.getsize(path) > 20
        finally:
            engine.shutdown()

    def test_missing_chunk_re_decodes(self, tmp_path, local_result):
        engine = LocalEngine(num_workers=0)
        try:
            base = self._base(engine)
            base.snapshot(str(tmp_path), fingerprint="c1").collect()
            store = self._store_dir(tmp_path)
            chunk = sorted(f for f in os.listdir(store)
                           if f.endswith(".snap"))[0]
            os.remove(os.path.join(store, chunk))
            m0 = _counter("inputsvc.snapshot_misses")
            out = base.snapshot(str(tmp_path),
                                fingerprint="c1").collect()
            assert out.equals(local_result)
            assert _counter("inputsvc.snapshot_misses") == m0 + 1
        finally:
            engine.shutdown()

    def test_manifest_version_bump_invalidates_store(self, tmp_path,
                                                     local_result):
        engine = LocalEngine(num_workers=0)
        try:
            base = self._base(engine)
            base.snapshot(str(tmp_path), fingerprint="c1").collect()
            store = self._store_dir(tmp_path)
            mpath = os.path.join(store, isvc_snapshot.MANIFEST_NAME)
            with open(mpath, encoding="utf-8") as f:
                manifest = json.load(f)
            manifest["version"] = isvc_snapshot.SNAPSHOT_VERSION + 99
            with open(mpath, "w", encoding="utf-8") as f:
                json.dump(manifest, f)
            i0 = _counter("inputsvc.snapshot_invalidations")
            out = base.snapshot(str(tmp_path),
                                fingerprint="c1").collect()
            assert out.equals(local_result)
            assert _counter("inputsvc.snapshot_invalidations") == \
                i0 + 1
        finally:
            engine.shutdown()

    def test_unreadable_manifest_invalidates_store(self, tmp_path,
                                                   local_result):
        engine = LocalEngine(num_workers=0)
        try:
            base = self._base(engine)
            base.snapshot(str(tmp_path), fingerprint="c1").collect()
            store = self._store_dir(tmp_path)
            mpath = os.path.join(store, isvc_snapshot.MANIFEST_NAME)
            with open(mpath, "w", encoding="utf-8") as f:
                f.write("{not json")
            out = base.snapshot(str(tmp_path),
                                fingerprint="c1").collect()
            assert out.equals(local_result)
        finally:
            engine.shutdown()

    def test_snapshot_read_fault_drill(self, tmp_path, local_result):
        """``snapshot.read`` at rate 1.0: every warm read fails, every
        partition re-decodes cleanly — identical rows, no crash."""
        engine = LocalEngine(num_workers=0)
        try:
            base = self._base(engine)
            base.snapshot(str(tmp_path), fingerprint="c1").collect()
            c0 = _counter("inputsvc.snapshot_corruptions")
            rfaults.inject("snapshot.read", "transient", 1.0)
            try:
                out = base.snapshot(str(tmp_path),
                                    fingerprint="c1").collect()
            finally:
                rfaults.disarm()
            assert out.equals(local_result)
            assert _counter("inputsvc.snapshot_corruptions") >= \
                c0 + 8
        finally:
            engine.shutdown()

    def test_chunk_round_trip_and_digest(self, tmp_path):
        blob = b"chunk-payload" * 100
        good = tmp_path / "good.snap"
        good.write_bytes(isvc_snapshot._encode_chunk(blob))
        assert isvc_snapshot._read_chunk(str(good)) == blob
        bad = bytearray(isvc_snapshot._encode_chunk(blob))
        bad[-1] ^= 0xFF
        flipped = tmp_path / "bad.snap"
        flipped.write_bytes(bytes(bad))
        with pytest.raises(isvc_snapshot.SnapshotCorruption):
            isvc_snapshot._read_chunk(str(flipped))


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

class TestCli:
    def test_serve_ready_line_and_ping(self):
        """``python -m sparkdl_tpu.inputsvc serve --port 0`` prints
        the READY line with its bound endpoint and answers a ping
        over the wire — the two-process drill's contract."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "sparkdl_tpu.inputsvc", "serve",
             "--port", "0"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)
        try:
            deadline = time.time() + 60
            line = ""
            while time.time() < deadline:
                line = proc.stdout.readline()
                if "SPARKDL_TPU_INPUTSVC READY" in line:
                    break
            assert "SPARKDL_TPU_INPUTSVC READY" in line, line
            endpoint = line.strip().rsplit(" ", 1)[-1]
            host, port = isvc_transport.parse_endpoint(endpoint)
            with socket.create_connection((host, port),
                                          timeout=10) as sock:
                send_msg(sock, {"op": "ping"})
                header, _ = recv_msg(sock)
            assert header.get("ok") is True
            assert header.get("version") == \
                isvc_transport.WIRE_VERSION
        finally:
            proc.terminate()
            proc.wait(timeout=15)

    def test_serve_rejects_bad_subcommand(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-m", "sparkdl_tpu.inputsvc", "bogus"],
            capture_output=True, text=True, env=env, timeout=120)
        assert proc.returncode != 0
