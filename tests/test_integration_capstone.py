"""Whole-stack integration: every round-3 surface in ONE flow.

The reference's user story end-to-end at test scale: read images →
join labels → featurize (committed trained TestNet) → persist the
features as parquet → train a minibatch LogisticRegression on the
reloaded features → score with all evaluators → save the fitted
pipeline → reload in-process and serve identical predictions. Each
piece has focused tests elsewhere; this exercises their interactions.
"""

import numpy as np
import pyarrow as pa
import pytest

import sparkdl_tpu
from sparkdl_tpu.data.frame import DataFrame


@pytest.fixture(scope="module")
def labeled_images(tmp_path_factory):
    from PIL import Image

    d = tmp_path_factory.mktemp("capstone")
    rng = np.random.default_rng(33)
    rows = []
    for i in range(60):
        label = i % 2
        base = 45 if label == 0 else 205
        arr = np.clip(rng.normal(base, 14, (24, 24, 3)),
                      0, 255).astype(np.uint8)
        p = str(d / f"img_{i:04d}.png")
        Image.fromarray(arr, "RGB").save(p)
        rows.append({"filePath": p, "label": label})
    return str(d), rows


def test_full_pipeline_capstone(tmp_path, labeled_images):
    data_dir, rows = labeled_images
    images = sparkdl_tpu.readImages(data_dir, numPartitions=4)
    labels_df = DataFrame.from_pylist(rows, num_partitions=1)
    labeled = images.join(labels_df, on="filePath")
    assert labeled.count() == 60

    # featurize once, persist the feature table as parquet
    feats = sparkdl_tpu.DeepImageFeaturizer(
        modelName="TestNet", inputCol="image",
        outputCol="features").transform(labeled)
    pq_dir = str(tmp_path / "features")
    feats.select("filePath", "features", "label").write_parquet(pq_dir)

    # train the head on the RELOADED features (the featurize-once,
    # train-many workflow parquet exists for), minibatch path
    table = DataFrame.read_parquet(pq_dir)
    assert table.count() == 60
    lr = sparkdl_tpu.LogisticRegression(maxIter=40, learningRate=0.2,
                                        batchSize=16)
    head = lr.fit(table)
    scored = head.transform(table)

    y = np.array([r["label"] for r in scored.collect_rows()])
    acc = sparkdl_tpu.ClassificationEvaluator(
        predictionCol="prediction").evaluate(scored)
    f1 = sparkdl_tpu.ClassificationEvaluator(
        predictionCol="prediction", metricName="f1").evaluate(scored)
    auc = sparkdl_tpu.BinaryClassificationEvaluator().evaluate(scored)
    loss = sparkdl_tpu.LossEvaluator().evaluate(scored)
    assert acc >= 0.9 and f1 >= 0.9 and auc >= 0.95
    assert loss < 0.5
    assert np.mean(
        scored.tensor("probability").argmax(-1) == y) == acc

    # persist the FULL fitted pipeline (featurizer + head) and serve
    # identical predictions from the reload
    from sparkdl_tpu.params.pipeline import PipelineModel
    pipeline_model = PipelineModel([
        sparkdl_tpu.DeepImageFeaturizer(
            modelName="TestNet", inputCol="image",
            outputCol="features"),
        head,
    ])
    save_dir = str(tmp_path / "model")
    pipeline_model.save(save_dir)
    served = sparkdl_tpu.load_model(save_dir)
    a = pipeline_model.transform(labeled).tensor("probability")
    b = served.transform(labeled).tensor("probability")
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_packed_ship_fidelity(tmp_path, labeled_images):
    """VERDICT r4 #2: the packed-ship path (half-res yuv420 ship +
    device resize) is the throughput headline's shape — quantify its
    fidelity cost on the capstone task instead of assuming it. Features
    must stay directionally faithful (mean cosine vs the full-res path)
    and end accuracy must match within a stated delta."""
    data_dir, rows = labeled_images
    labels_df = DataFrame.from_pylist(rows, num_partitions=1)

    from sparkdl_tpu.image import imageIO
    from sparkdl_tpu.models.zoo import getModelFunction
    from sparkdl_tpu.transformers.tensor_transform import TensorTransformer
    from sparkdl_tpu.transformers.utils import deviceResizeModel, single_io

    def featurize_full():
        images = sparkdl_tpu.readImages(data_dir, numPartitions=4)
        labeled = images.join(labels_df, on="filePath")
        return sparkdl_tpu.DeepImageFeaturizer(
            modelName="TestNet", inputCol="image",
            outputCol="features").transform(labeled)

    def featurize_packed():
        mf = getModelFunction("TestNet", featurize=True)
        mfp = deviceResizeModel(mf, (16, 16), packedFormat="yuv420")
        in_name, out_name = single_io(mfp)
        packed = imageIO.readImagesPacked(
            data_dir, (16, 16), numPartitions=4, packedFormat="yuv420")
        labeled = packed.join(labels_df, on="filePath")
        return TensorTransformer(
            modelFunction=mfp, inputMapping={"image": in_name},
            outputMapping={out_name: "features"},
            batchSize=16).transform(labeled)

    full = featurize_full()
    packed = featurize_packed()
    fa = full.tensor("features")
    fb = packed.tensor("features")
    order_a = [r["filePath"] for r in full.select("filePath")
               .collect_rows()]
    order_b = [r["filePath"] for r in packed.select("filePath")
               .collect_rows()]
    fb = fb[np.argsort(order_b)][np.argsort(np.argsort(order_a))]
    cos = (fa * fb).sum(1) / np.maximum(
        np.linalg.norm(fa, axis=1) * np.linalg.norm(fb, axis=1), 1e-9)
    assert cos.mean() >= 0.97, cos.mean()

    # end-accuracy parity: train the head on each path's features
    def head_acc(df):
        lr = sparkdl_tpu.LogisticRegression(maxIter=40,
                                            learningRate=0.2,
                                            batchSize=16)
        scored = lr.fit(df).transform(df)
        return sparkdl_tpu.ClassificationEvaluator(
            predictionCol="prediction").evaluate(scored)

    acc_full = head_acc(full)
    acc_packed = head_acc(packed)
    assert acc_full >= 0.9 and acc_packed >= 0.9
    assert abs(acc_full - acc_packed) <= 0.05, (acc_full, acc_packed)


def test_cv_grid_over_pipeline_stage_params(labeled_images):
    """CrossValidator over a Pipeline with the grid keyed by the CHILD
    LR stage's params — the standard Spark ML tuning pattern (grid
    entries must reach the stage copy through Pipeline.copy, fixed
    round 5), composed with the streaming LR head and an evaluator."""
    from sparkdl_tpu.estimators.evaluators import ClassificationEvaluator
    from sparkdl_tpu.params.tuning import CrossValidator, ParamGridBuilder

    data_dir, rows = labeled_images
    images = sparkdl_tpu.readImages(data_dir, numPartitions=3)
    labels_df = DataFrame.from_pylist(rows, num_partitions=1)
    labeled = images.join(labels_df, on="filePath")

    feat = sparkdl_tpu.DeepImageFeaturizer(
        modelName="TestNet", inputCol="image", outputCol="features")
    lr = sparkdl_tpu.LogisticRegression(
        maxIter=30, streaming=True, batchSize=16, numClasses=0)
    pipe = sparkdl_tpu.Pipeline(stages=[feat, lr])
    grid = (ParamGridBuilder()
            .addGrid(lr.learningRate, [0.05, 0.2]).build())
    ev = ClassificationEvaluator(predictionCol="prediction",
                                 labelCol="label")
    cv = CrossValidator(estimator=pipe, estimatorParamMaps=grid,
                        evaluator=ev, numFolds=2)
    model = cv.fit(labeled)
    assert len(model.avgMetrics) == 2
    out = model.transform(labeled).collect_rows()
    acc = np.mean([r["prediction"] == r["label"] for r in out])
    assert len(out) == 60 and acc >= 0.9, acc


def test_frame_ops_compose_with_mesh_device_stage(tmp_path,
                                                  labeled_images):
    """Round-5 composition probe, kept as a regression test: an
    out-of-core upward repartition, a union with a differently
    partitioned frame, and a limit all feed the SAME mesh device stage
    (yuv420 packed payload, batch-misaligned partitions re-chunked by
    the engine) with row identity and duplicate-half feature equality
    preserved."""
    from sparkdl_tpu.image import imageIO
    from sparkdl_tpu.models.zoo import getModelFunction
    from sparkdl_tpu.transformers.tensor_transform import TensorTransformer
    from sparkdl_tpu.transformers.utils import deviceResizeModel, single_io

    data_dir, rows = labeled_images
    packed = imageIO.readImagesPacked(data_dir, (16, 16),
                                      numPartitions=2,
                                      packedFormat="yuv420")
    rep = packed.repartition(9, cacheDir=str(tmp_path / "spill"))
    uni = rep.union(packed)  # 120 rows, two different layouts
    mfp = deviceResizeModel(getModelFunction("TestNet", featurize=True),
                            (16, 16), packedFormat="yuv420")
    i_n, o_n = single_io(mfp)
    t = TensorTransformer(modelFunction=mfp, inputMapping={"image": i_n},
                          outputMapping={o_n: "f"}, batchSize=16,
                          useMesh=True)
    out = t.transform(uni).collect_rows()
    assert len(out) == 120
    fps = [r["filePath"] for r in out]
    assert fps[:60] == sorted(fps[:60])      # rep half, in order
    assert fps[60:] == sorted(fps[60:])      # original half, in order
    f = np.stack([np.asarray(r["f"]) for r in out])
    np.testing.assert_allclose(f[:60], f[60:], rtol=1e-5, atol=1e-6)

    lim = t.transform(uni.limit(70)).collect_rows()
    assert len(lim) == 70
