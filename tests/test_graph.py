"""Graph toolkit tests.

The centerpiece mirrors the reference's strongest L4 suite
(``python/tests/graph/test_input.py``, SURVEY §4.3): one tiny MLP with
fixed weights persisted through every ingestion source, all asserted to
produce identical outputs.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparkdl_tpu.graph import (
    ModelFunction,
    ModelIngest,
    TFInputGraph,
    buildFlattener,
    buildSpImageConverter,
)

IN_DIM, HID, OUT_DIM = 4, 8, 3


@pytest.fixture(scope="module")
def mlp_weights():
    r = np.random.default_rng(1)
    return {
        "W1": r.normal(size=(IN_DIM, HID)).astype(np.float32),
        "b1": r.normal(size=(HID,)).astype(np.float32),
        "W2": r.normal(size=(HID, OUT_DIM)).astype(np.float32),
        "b2": r.normal(size=(OUT_DIM,)).astype(np.float32),
    }


def mlp_apply(params, x):
    h = jax.nn.relu(x @ params["W1"] + params["b1"])
    return h @ params["W2"] + params["b2"]


@pytest.fixture(scope="module")
def x_batch():
    return np.random.default_rng(2).normal(size=(6, IN_DIM)) \
        .astype(np.float32)


@pytest.fixture(scope="module")
def expected(mlp_weights, x_batch):
    return np.asarray(mlp_apply(
        {k: jnp.asarray(v) for k, v in mlp_weights.items()},
        jnp.asarray(x_batch)))


def _assert_matches(mf, x_batch, expected, atol=1e-5):
    out = mf(x_batch)
    if isinstance(out, dict):
        (out,) = out.values()
    np.testing.assert_allclose(np.asarray(out), expected, atol=atol)


class TestModelFunction:
    def test_from_single_call(self, mlp_weights, x_batch, expected):
        mf = ModelFunction.fromSingle(mlp_apply, mlp_weights,
                                      input_shape=(IN_DIM,))
        _assert_matches(mf, x_batch, expected)

    def test_output_signature(self, mlp_weights):
        mf = ModelFunction.fromSingle(mlp_apply, mlp_weights,
                                      input_shape=(IN_DIM,))
        sig = mf.output_signature()
        assert sig["output"][0] == (OUT_DIM,)

    def test_from_list_composition(self, mlp_weights, x_batch, expected):
        """converter ⊕ model ⊕ flattener — the tf_image.py composition."""
        model = ModelFunction.fromSingle(mlp_apply, mlp_weights,
                                         input_shape=(IN_DIM,))
        flat = buildFlattener(input_shape=(OUT_DIM,))
        composed = ModelFunction.fromList([model, flat])
        out = composed(x_batch)
        np.testing.assert_allclose(np.asarray(out), expected, atol=1e-5)

    def test_from_list_rejects_multi_io_chain(self, mlp_weights):
        def two_out(params, inputs):
            x = inputs["input"]
            return {"a": x, "b": x}
        multi = ModelFunction(two_out, None,
                              {"input": ((IN_DIM,), np.float32)})
        flat = buildFlattener(input_shape=(IN_DIM,))
        composed = ModelFunction.fromList([multi, flat])
        with pytest.raises(ValueError):
            composed(np.zeros((2, IN_DIM), np.float32))

    def test_rename_io(self, mlp_weights, x_batch, expected):
        mf = ModelFunction.fromSingle(mlp_apply, mlp_weights,
                                      input_shape=(IN_DIM,))
        mf2 = mf.rename_io({"input": "features"}, {"output": "logits"})
        assert mf2.input_names == ["features"]
        out = mf2({"features": x_batch})
        np.testing.assert_allclose(np.asarray(out["logits"]), expected,
                                   atol=1e-5)

    def test_image_converter_piece(self):
        conv = buildSpImageConverter(2, 2, 3, scale=1 / 127.5, offset=-1.0)
        x = np.full((1, 2, 2, 3), 255, np.uint8)
        out = np.asarray(conv(x))
        np.testing.assert_allclose(out, 1.0, atol=1e-6)
        assert out.dtype == np.float32

    def test_image_converter_bgr(self):
        conv = buildSpImageConverter(1, 1, 3, channel_order="BGR")
        x = np.zeros((1, 1, 1, 3), np.uint8)
        x[..., 0] = 10  # R
        out = np.asarray(conv(x))
        assert out[0, 0, 0, 2] == 10  # R moved to last channel


class TestIngestionMatrix:
    """All sources must produce identical outputs (reference
    test_input.py conformance pattern)."""

    def test_from_function(self, mlp_weights, x_batch, expected):
        mf = ModelIngest.fromFunction(mlp_apply, mlp_weights,
                                      input_shape=(IN_DIM,))
        _assert_matches(mf, x_batch, expected)

    def test_from_export_roundtrip(self, mlp_weights, x_batch, expected):
        mf = ModelIngest.fromFunction(mlp_apply, mlp_weights,
                                      input_shape=(IN_DIM,))
        blob = mf.export(batch_size=None)  # symbolic batch
        mf2 = ModelIngest.fromExport(blob)
        assert mf2.input_signature["input"][0] == (IN_DIM,)
        _assert_matches(mf2, x_batch, expected)

    def test_from_export_fixed_batch(self, mlp_weights, x_batch, expected):
        mf = ModelIngest.fromFunction(mlp_apply, mlp_weights,
                                      input_shape=(IN_DIM,))
        mf2 = ModelIngest.fromExport(mf.export(batch_size=6))
        _assert_matches(mf2, x_batch, expected)

    def test_fixed_batch_survives_wrappers(self, mlp_weights, x_batch,
                                           expected):
        """Graph-surgery wrappers over a FIXED-batch deserialized
        program must keep its batch constraint: their eval_shape probes
        previously used batch 1, which such exports reject
        (regression)."""
        from sparkdl_tpu.graph import utils as tfx

        mf = ModelIngest.fromFunction(mlp_apply, mlp_weights,
                                      input_shape=(IN_DIM,))
        frozen = ModelIngest.fromExport(mf.export(batch_size=6))

        post = tfx.with_postprocessor(frozen,
                                      lambda o: {"y2": o["output"] * 2})
        assert post.output_names == ["y2"]  # probe at batch 6, not 1
        np.testing.assert_allclose(
            np.asarray(post({"input": x_batch})["y2"]), expected * 2,
            rtol=1e-5)

        sel = tfx.select_outputs(frozen, ["output"])
        assert sel.output_signature()["output"][0] == (OUT_DIM,)

        renamed = frozen.rename_io(output_map={"output": "z"})
        assert renamed.output_signature()["z"][0] == (OUT_DIM,)

    def _keras_model(self, mlp_weights):
        import keras
        m = keras.Sequential([
            keras.layers.Input((IN_DIM,)),
            keras.layers.Dense(HID, activation="relu"),
            keras.layers.Dense(OUT_DIM),
        ])
        m.set_weights([mlp_weights["W1"], mlp_weights["b1"],
                       mlp_weights["W2"], mlp_weights["b2"]])
        return m

    def test_from_keras_model(self, mlp_weights, x_batch, expected):
        mf = ModelIngest.fromKerasModel(self._keras_model(mlp_weights))
        _assert_matches(mf, x_batch, expected)

    @pytest.mark.parametrize("ext", ["h5", "keras"])
    def test_from_keras_file(self, mlp_weights, x_batch, expected,
                             tmp_path, ext):
        path = str(tmp_path / f"model.{ext}")
        self._keras_model(mlp_weights).save(path)
        mf = ModelIngest.fromKerasFile(path)
        _assert_matches(mf, x_batch, expected)

    def _saved_model(self, mlp_weights, tmp_path):
        import tensorflow as tf
        W1, b1 = tf.constant(mlp_weights["W1"]), tf.constant(mlp_weights["b1"])
        W2, b2 = tf.constant(mlp_weights["W2"]), tf.constant(mlp_weights["b2"])

        @tf.function(input_signature=[
            tf.TensorSpec([None, IN_DIM], tf.float32, name="x")])
        def fn(x):
            h = tf.nn.relu(tf.matmul(x, W1) + b1)
            return {"y": tf.matmul(h, W2) + b2}

        mod = tf.Module()
        d = str(tmp_path / "sm")
        tf.saved_model.save(mod, d, signatures={"serving_default": fn,
                                                "featurize": fn})
        return d

    def test_from_saved_model(self, mlp_weights, x_batch, expected,
                              tmp_path):
        d = self._saved_model(mlp_weights, tmp_path)
        mf = ModelIngest.fromSavedModel(d)
        assert mf.backend == "host"
        assert mf.input_signature["x"][0] == (IN_DIM,)
        out = mf({"x": x_batch})
        np.testing.assert_allclose(out["y"], expected, atol=1e-5)

    def test_from_saved_model_with_signature(self, mlp_weights, x_batch,
                                             expected, tmp_path):
        d = self._saved_model(mlp_weights, tmp_path)
        mf = ModelIngest.fromSavedModelWithSignature(d, "featurize")
        out = mf({"x": x_batch})
        np.testing.assert_allclose(out["y"], expected, atol=1e-5)

    def test_host_backend_refuses_to_ship(self, mlp_weights, tmp_path):
        """Host-backend ModelFunctions wrap live TF state; pickling one
        for a Spark task must fail with the re-ingest instruction, not
        ship something that can't run on the executor."""
        import pickle

        d = self._saved_model(mlp_weights, tmp_path)
        mf = ModelIngest.fromSavedModel(d)
        with pytest.raises(TypeError, match="re-create it on the worker"):
            pickle.dumps(mf)

    def _frozen_graph_def(self, mlp_weights):
        """The TF1-era artifact: a frozen (constants-only) GraphDef with
        named feed/fetch tensors, as serialized bytes."""
        import tensorflow as tf

        def _import():
            x = tf.compat.v1.placeholder(tf.float32, [None, IN_DIM],
                                         name="x")
            h = tf.nn.relu(
                tf.matmul(x, tf.constant(mlp_weights["W1"]))
                + tf.constant(mlp_weights["b1"]))
            tf.add(tf.matmul(h, tf.constant(mlp_weights["W2"])),
                   tf.constant(mlp_weights["b2"]), name="y")

        g = tf.compat.v1.wrap_function(_import, []).graph
        return g.as_graph_def().SerializeToString()

    def test_from_graphdef_multi_output_op(self, x_batch):
        """Two fetches off the SAME op (split:0, split:1) must keep
        distinct keys — stripping the output index collided them and
        silently dropped all but the last fetch (regression)."""
        import tensorflow as tf

        def _import():
            x = tf.compat.v1.placeholder(tf.float32, [None, IN_DIM],
                                         name="x")
            tf.split(x, 2, axis=1, name="split")

        blob = tf.compat.v1.wrap_function(_import, []) \
            .graph.as_graph_def().SerializeToString()
        mf = ModelIngest.fromGraphDef(blob, ["x:0"],
                                      ["split:0", "split:1"])
        assert mf.output_names == ["split_0", "split_1"]
        out = mf({"x": x_batch})
        half = IN_DIM // 2
        np.testing.assert_allclose(out["split_0"], x_batch[:, :half])
        np.testing.assert_allclose(out["split_1"], x_batch[:, half:])
        with pytest.raises(ValueError, match="duplicate fetch"):
            ModelIngest.fromGraphDef(blob, ["x:0"], ["split:0",
                                                     "split:0"])

    def test_from_graphdef_bytes(self, mlp_weights, x_batch, expected):
        blob = self._frozen_graph_def(mlp_weights)
        mf = ModelIngest.fromGraphDef(blob, ["x:0"], ["y:0"])
        assert mf.backend == "host"
        assert mf.input_signature["x"][0] == (IN_DIM,)
        out = mf({"x": x_batch})
        np.testing.assert_allclose(out["y"], expected, atol=1e-5)

    def test_from_graph_live(self, mlp_weights, x_batch, expected):
        import tensorflow as tf
        blob = self._frozen_graph_def(mlp_weights)
        proto = tf.compat.v1.GraphDef()
        proto.ParseFromString(blob)
        graph = tf.Graph()
        with graph.as_default():
            tf.compat.v1.import_graph_def(proto, name="")
        mf = ModelIngest.fromGraph(graph, ["x"], ["y"])  # bare op names
        out = mf({"x": x_batch})
        np.testing.assert_allclose(out["y"], expected, atol=1e-5)

    def test_from_saved_model_bad_signature(self, mlp_weights, tmp_path):
        d = self._saved_model(mlp_weights, tmp_path)
        with pytest.raises(KeyError):
            ModelIngest.fromSavedModel(d, signatureDefKey="nope")

    def _checkpoint(self, mlp_weights, tmp_path):
        import tensorflow as tf
        ckpt = tf.train.Checkpoint(
            W1=tf.Variable(mlp_weights["W1"]),
            b1=tf.Variable(mlp_weights["b1"]),
            W2=tf.Variable(mlp_weights["W2"]),
            b2=tf.Variable(mlp_weights["b2"]))
        return ckpt.save(str(tmp_path / "ckpt" / "model"))

    def test_from_checkpoint(self, mlp_weights, x_batch, expected,
                             tmp_path):
        prefix = self._checkpoint(mlp_weights, tmp_path)

        def apply_fn(params, inputs):
            return {"output": mlp_apply(params, inputs["input"])}

        mf = ModelIngest.fromCheckpoint(
            prefix, apply_fn,
            input_signature={"input": ((IN_DIM,), np.float32)})
        out = mf({"input": x_batch})
        np.testing.assert_allclose(np.asarray(out["output"]), expected,
                                   atol=1e-5)

    def test_from_checkpoint_dir_latest(self, mlp_weights, x_batch,
                                        expected, tmp_path):
        self._checkpoint(mlp_weights, tmp_path)

        def apply_fn(params, inputs):
            return {"output": mlp_apply(params, inputs["input"])}

        mf = ModelIngest.fromCheckpoint(
            str(tmp_path / "ckpt"), apply_fn,
            input_signature={"input": ((IN_DIM,), np.float32)})
        out = mf({"input": x_batch})
        np.testing.assert_allclose(np.asarray(out["output"]), expected,
                                   atol=1e-5)

    def test_from_checkpoint_with_signature(self, mlp_weights, x_batch,
                                            expected, tmp_path):
        prefix = self._checkpoint(mlp_weights, tmp_path)

        def apply_fn(params, inputs):
            return {"output": mlp_apply(params, inputs["input"])}

        mf = ModelIngest.fromCheckpointWithSignature(
            prefix, apply_fn,
            input_signature={"input": ((IN_DIM,), np.float32)},
            input_mapping={"input": "features"},
            output_mapping={"output": "logits"})
        out = mf({"features": x_batch})
        np.testing.assert_allclose(np.asarray(out["logits"]), expected,
                                   atol=1e-5)

    def test_alias(self):
        assert TFInputGraph is ModelIngest
