"""Whole-program analyzer tests: callgraph/lock-scope inference, the
H7/H8 program rules (including the reconstructed PR-2 deadlock
fixture), the H9 contract-drift round-trip, and the per-file result
cache.

Fixture style mirrors tests/test_analysis.py: deliberately broken
multi-module trees under tmp_path trip the rules; idiomatic clean
trees don't; inline suppressions downgrade without hiding. The PR-2
fixture is the acceptance bar: the production deadlock this repo
actually shipped (racing per-device collective enqueues under
fitMultiple, fixed by collective_launch in PR 2) reconstructed as two
modules whose witness path H7 must print module-by-module.
"""

import json
import os
import subprocess
import sys

import pytest

import sparkdl_tpu
from sparkdl_tpu.analysis import analyze_paths, build_graph
from sparkdl_tpu.analysis.callgraph import CallGraph, module_name
from sparkdl_tpu.analysis.contracts import check_h9, names_overlap
from sparkdl_tpu.analysis.walker import analyze_source

PKG_DIR = os.path.dirname(os.path.abspath(sparkdl_tpu.__file__))
REPO_ROOT = os.path.dirname(PKG_DIR)


def _tree(tmp_path, files: dict) -> str:
    for name, src in files.items():
        (tmp_path / name).write_text(src)
    return str(tmp_path)


def _unsup(findings, rule):
    return [f for f in findings if f.rule == rule and not f.suppressed]


def _sup(findings, rule):
    return [f for f in findings if f.rule == rule and f.suppressed]


# ---------------------------------------------------------------------------
# callgraph + lock-scope inference


class TestCallGraphInference:
    def test_module_name_anchors_at_package(self):
        assert module_name("sparkdl_tpu/serve/server.py") == \
            "sparkdl_tpu.serve.server"
        assert module_name("tools/measure_transfer.py") == \
            "tools.measure_transfer"

    def test_self_method_edge_resolves(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "class A:\n"
            "    def outer(self):\n"
            "        self.inner()\n"
            "    def inner(self):\n"
            "        pass\n")})
        g = build_graph([os.path.join(root, "m.py")])
        f = next(v for k, v in g.functions.items()
                 if v.qualname == "A.outer")
        call = next(c for c in f.calls if c.name == "inner")
        assert g.resolve(f, call) is not None

    def test_cross_module_import_edge_resolves(self, tmp_path):
        root = _tree(tmp_path, {
            "a.py": "from b import helper\n"
                    "def caller():\n"
                    "    helper()\n",
            "b.py": "def helper():\n"
                    "    pass\n"})
        g = build_graph([os.path.join(root, "a.py"),
                         os.path.join(root, "b.py")])
        f = next(v for v in g.functions.values()
                 if v.qualname == "caller")
        call = next(c for c in f.calls if c.name == "helper")
        assert g.resolve(f, call).endswith("b::helper")

    def test_ambiguous_method_does_not_resolve(self, tmp_path):
        """Two classes defining `run`: obj.run() must resolve to
        NEITHER — a guessed edge would manufacture false deadlocks."""
        root = _tree(tmp_path, {"m.py": (
            "class A:\n"
            "    def run(self):\n"
            "        pass\n"
            "class B:\n"
            "    def run(self):\n"
            "        pass\n"
            "def drive(obj):\n"
            "    obj.run()\n")})
        g = build_graph([os.path.join(root, "m.py")])
        f = next(v for v in g.functions.values()
                 if v.qualname == "drive")
        call = next(c for c in f.calls if c.name == "run")
        assert g.resolve(f, call) is None

    def test_with_lock_held_set_is_lexical(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "import threading, time\n"
            "class A:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def __getstate__(self):\n"
            "        return {}\n"
            "    def locked(self):\n"
            "        with self._lock:\n"
            "            time.sleep(1)\n"
            "    def unlocked(self):\n"
            "        time.sleep(1)\n")})
        g = build_graph([os.path.join(root, "m.py")])
        by_qual = {v.qualname: v for v in g.functions.values()}
        assert by_qual["A.locked"].blocks[0].held
        assert not by_qual["A.unlocked"].blocks[0].held

    def test_acquire_release_region_is_line_scoped(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "import threading, time\n"
            "LOCK = threading.Lock()\n"
            "def f():\n"
            "    LOCK.acquire()\n"
            "    time.sleep(1)\n"
            "    LOCK.release()\n"
            "    time.sleep(2)\n")})
        g = build_graph([os.path.join(root, "m.py")])
        f = next(v for v in g.functions.values() if v.qualname == "f")
        held = {b.line: bool(b.held) for b in f.blocks}
        assert held[5] is True      # inside acquire..release
        assert held[7] is False     # after release

    def test_try_acquire_is_not_an_acquire(self, tmp_path):
        """acquire(blocking=False) cannot deadlock — the
        checkout_staging idiom must produce no lock events."""
        root = _tree(tmp_path, {"m.py": (
            "import threading, time\n"
            "LOCK = threading.Lock()\n"
            "def f():\n"
            "    got = LOCK.acquire(blocking=False)\n"
            "    time.sleep(1)\n")})
        g = build_graph([os.path.join(root, "m.py")])
        f = next(v for v in g.functions.values() if v.qualname == "f")
        assert f.acquires == []
        assert not f.blocks[0].held

    def test_condition_aliases_to_its_mutex(self, tmp_path):
        """Condition(self._lock): `with self._cond` and `with
        self._lock` are ONE lock — no false self-cycle between them."""
        root = _tree(tmp_path, {"m.py": (
            "import threading\n"
            "class Q:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._cond = threading.Condition(self._lock)\n"
            "    def __getstate__(self):\n"
            "        return {}\n"
            "    def f(self):\n"
            "        with self._cond:\n"
            "            pass\n")})
        g = build_graph([os.path.join(root, "m.py")])
        f = next(v for v in g.functions.values() if v.qualname == "Q.f")
        assert f.acquires[0].lock.endswith("Q._lock")

    def test_may_block_propagates_across_modules(self, tmp_path):
        root = _tree(tmp_path, {
            "a.py": "from b import drain\n"
                    "def outer():\n"
                    "    drain()\n",
            "b.py": "import time\n"
                    "def drain():\n"
                    "    time.sleep(1)\n"})
        g = build_graph([os.path.join(root, "a.py"),
                         os.path.join(root, "b.py")])
        key = next(k for k, v in g.functions.items()
                   if v.qualname == "outer")
        hit = g.may_block(key)
        assert hit is not None
        chain, op = hit
        assert "drain" in chain and "sleep" in op


# ---------------------------------------------------------------------------
# H7 — lock-order cycles


#: the PR-2 production deadlock, reconstructed: two trial launchers
#: enqueue a collective (multi-device) program onto per-device FIFO
#: queues in OPPOSITE orders — exactly the racing-enqueue shape
#: collective_launch() serializes away (parallel/mesh.py).
PR2_FIXTURE = {
    "devqueues.py": (
        "import threading\n"
        "\n"
        "# each XLA device executes its queue in FIFO order; the lock\n"
        "# stands in for exclusive use of that queue's tail\n"
        "DEV0_QUEUE = threading.Lock()\n"
        "DEV1_QUEUE = threading.Lock()\n"),
    "trial_a.py": (
        "from devqueues import DEV0_QUEUE, DEV1_QUEUE\n"
        "\n"
        "def enqueue_collective(step):\n"
        "    # device 0 first, then device 1\n"
        "    with DEV0_QUEUE:\n"
        "        with DEV1_QUEUE:\n"
        "            step()\n"),
    "trial_b.py": (
        "from devqueues import DEV0_QUEUE, DEV1_QUEUE\n"
        "\n"
        "def enqueue_collective_racing(step):\n"
        "    # the race: device 1 first — the all-reduce on device 0\n"
        "    # now waits behind trial A while A waits behind us\n"
        "    with DEV1_QUEUE:\n"
        "        with DEV0_QUEUE:\n"
        "            step()\n"),
}


class TestH7LockOrder:
    def test_pr2_deadlock_fixture_is_caught_with_witness(self, tmp_path):
        """THE acceptance fixture: the reconstructed PR-2 collective-
        enqueue deadlock must be caught, and the finding must print
        the cross-module witness path (both modules named, both
        acquire sites located)."""
        root = _tree(tmp_path, PR2_FIXTURE)
        found = analyze_paths([root])
        h7 = _unsup(found, "H7")
        assert len(h7) == 1, [f.render() for f in found]
        msg = h7[0].message
        assert "lock-order cycle" in msg
        # module-by-module: both trial modules appear in the witness,
        # with their file:line acquire sites
        assert "trial_a" in msg and "trial_b" in msg
        assert "trial_a.py:5" in msg or "trial_a.py:6" in msg
        assert "trial_b.py:7" in msg or "trial_b.py:8" in msg
        assert "DEV0_QUEUE" in msg and "DEV1_QUEUE" in msg

    def test_consistent_order_is_clean(self, tmp_path):
        fixture = dict(PR2_FIXTURE)
        fixture["trial_b.py"] = fixture["trial_b.py"].replace(
            "with DEV1_QUEUE:\n        with DEV0_QUEUE:",
            "with DEV0_QUEUE:\n        with DEV1_QUEUE:")
        root = _tree(tmp_path, fixture)
        assert _unsup(analyze_paths([root]), "H7") == []

    def test_transitive_cross_module_cycle(self, tmp_path):
        """A serve-shaped lock held into collective_launch while the
        launch holder calls back into a serve-lock taker: the cycle
        exists only across the call graph."""
        root = _tree(tmp_path, {
            "mesh.py": (
                "import threading\n"
                "from serve import publish_status\n"
                "LAUNCH_LOCK = threading.Lock()\n"
                "def launch(program):\n"
                "    with LAUNCH_LOCK:\n"
                "        program()\n"
                "        publish_status()\n"),
            "serve.py": (
                "import threading\n"
                "from mesh import launch\n"
                "STATUS_LOCK = threading.Lock()\n"
                "def publish_status():\n"
                "    with STATUS_LOCK:\n"
                "        pass\n"
                "def dispatch(program):\n"
                "    with STATUS_LOCK:\n"
                "        launch(program)\n")})
        found = analyze_paths([root])
        h7 = _unsup(found, "H7")
        assert any("LAUNCH_LOCK" in f.message
                   and "STATUS_LOCK" in f.message
                   and "via" in f.message for f in h7), \
            [f.render() for f in found]

    def test_reentry_through_call_chain(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "import threading\n"
            "LOCK = threading.Lock()\n"
            "def notify():\n"
            "    with LOCK:\n"
            "        pass\n"
            "def work():\n"
            "    with LOCK:\n"
            "        notify()\n")})
        h7 = _unsup(analyze_paths([root]), "H7")
        assert any("re-entry" in f.message for f in h7)

    def test_suppressed_with_reason(self, tmp_path):
        # the finding anchors at the acquired-while-holding site: the
        # INNER with of the first witness edge (trial_a holds DEV0,
        # acquires DEV1)
        fixture = dict(PR2_FIXTURE)
        fixture["trial_a.py"] = fixture["trial_a.py"].replace(
            "        with DEV1_QUEUE:\n",
            "        # sparkdl-lint: allow[H7] -- fixture: order "
            "proven safe by the global launch lock\n"
            "        with DEV1_QUEUE:\n")
        root = _tree(tmp_path, fixture)
        found = analyze_paths([root])
        assert _unsup(found, "H7") == []
        sup = _sup(found, "H7")
        assert len(sup) == 1
        assert "proven safe" in sup[0].suppression


# ---------------------------------------------------------------------------
# H8 — blocking under a lock


class TestH8BlockingUnderLock:
    def test_direct_sleep_under_lock(self):
        src = ("import threading, time\n"
               "LOCK = threading.Lock()\n"
               "def f():\n"
               "    with LOCK:\n"
               "        time.sleep(0.5)\n")
        found = analyze_source(src, "fixture.py")
        hits = _unsup(found, "H8")
        assert len(hits) == 1
        assert hits[0].line == 5
        assert "time.sleep" in hits[0].message

    def test_device_sync_under_lock(self):
        src = ("import threading, jax\n"
               "LOCK = threading.Lock()\n"
               "def drain(res):\n"
               "    with LOCK:\n"
               "        return jax.device_get(res)\n")
        assert len(_unsup(analyze_source(src, "fixture.py"), "H8")) == 1

    def test_transitive_block_under_lock_cross_module(self, tmp_path):
        """The lock is in one module, the blocking op two calls away
        in another — the finding must print the chain."""
        root = _tree(tmp_path, {
            "holder.py": (
                "import threading\n"
                "from worker import do_work\n"
                "LOCK = threading.Lock()\n"
                "def guarded():\n"
                "    with LOCK:\n"
                "        do_work()\n"),
            "worker.py": (
                "from io_layer import fetch\n"
                "def do_work():\n"
                "    fetch()\n"),
            "io_layer.py": (
                "import urllib.request\n"
                "def fetch():\n"
                "    urllib.request.urlopen('http://x')\n")})
        h8 = _unsup(analyze_paths([root]), "H8")
        assert len(h8) >= 1
        msg = next(f.message for f in h8 if "do_work" in f.message)
        assert "fetch" in msg and "urlopen" in msg

    def test_blocking_outside_lock_is_clean(self):
        src = ("import threading, time\n"
               "LOCK = threading.Lock()\n"
               "def f():\n"
               "    with LOCK:\n"
               "        x = 1\n"
               "    time.sleep(0.5)\n")
        assert _unsup(analyze_source(src, "fixture.py"), "H8") == []

    def test_queue_get_under_lock(self):
        src = ("import threading\n"
               "LOCK = threading.Lock()\n"
               "def f(work_queue):\n"
               "    with LOCK:\n"
               "        return work_queue.get()\n")
        assert len(_unsup(analyze_source(src, "fixture.py"), "H8")) == 1

    def test_suppressed(self):
        src = ("import threading, time\n"
               "LOCK = threading.Lock()\n"
               "def f():\n"
               "    with LOCK:\n"
               "        time.sleep(0.5)"
               "  # sparkdl-lint: allow[H8] -- rate limiter: the hold"
               " is the product\n")
        found = analyze_source(src, "fixture.py")
        assert _unsup(found, "H8") == []
        assert len(_sup(found, "H8")) == 1

    def test_meta_dispatcher_wait_is_allowlisted_not_invisible(self):
        """The serve dispatcher's intentional coalescing
        Condition.wait must APPEAR as a suppressed H8 finding (the
        allowlist-not-skipped discipline, H1 precedent)."""
        found = analyze_paths([os.path.join(PKG_DIR, "serve")])
        h8 = [f for f in found if f.rule == "H8"]
        assert any("RequestQueue.collect" in (f.qualname or "")
                   for f in h8), [f.render() for f in h8]
        assert all(f.suppressed for f in h8), \
            [f.render() for f in h8 if not f.suppressed]


# ---------------------------------------------------------------------------
# H9 — contract drift


class TestH9ContractDrift:
    def test_fake_registry_key_names_the_doc_table(self, tmp_path):
        """THE round-trip: inject an undocumented registry key and the
        failure must name the doc table to edit."""
        bad = tmp_path / "rogue.py"
        bad.write_text(
            "def publish(reg):\n"
            "    reg.counter('zzz.totally_undocumented_key').add()\n")
        found = analyze_paths([str(bad)], docs_root=REPO_ROOT)
        h9 = _unsup(found, "H9")
        assert len(h9) == 1, [f.render() for f in found]
        assert "zzz.totally_undocumented_key" in h9[0].message
        assert "docs/OBSERVABILITY.md" in h9[0].message \
            or "docs/SERVING.md" in h9[0].message
        assert str(bad.name) in h9[0].path

    def test_documented_key_passes(self, tmp_path):
        good = tmp_path / "ok.py"
        good.write_text(
            "def publish(reg):\n"
            "    reg.counter('collective.launches').add()\n")
        assert _unsup(analyze_paths([str(good)],
                                    docs_root=REPO_ROOT), "H9") == []

    def test_fstring_key_matches_wildcard_doc_row(self, tmp_path):
        """`slo.{name}.burn_rate` must satisfy the documented
        `slo.<objective>.burn_rate` row."""
        good = tmp_path / "ok.py"
        good.write_text(
            "def publish(reg, name):\n"
            "    reg.gauge(f'slo.{name}.burn_rate').set(1.0)\n")
        assert _unsup(analyze_paths([str(good)],
                                    docs_root=REPO_ROOT), "H9") == []

    def test_undocumented_env_var_trips(self, tmp_path):
        bad = tmp_path / "rogue.py"
        bad.write_text(
            "import os\n"
            "def f():\n"
            "    return os.environ.get('SPARKDL_TPU_NOT_A_REAL_KNOB')\n")
        h9 = _unsup(analyze_paths([str(bad)], docs_root=REPO_ROOT),
                    "H9")
        assert len(h9) == 1
        assert "SPARKDL_TPU_NOT_A_REAL_KNOB" in h9[0].message

    def test_doc_side_stale_row_detected(self, tmp_path):
        """A documented-but-gone registry key fails pointing at the
        DOC row — exercised against a synthetic docs tree so the real
        docs stay authoritative for the meta-test."""
        docs = tmp_path / "docs"
        docs.mkdir()
        (docs / "OBSERVABILITY.md").write_text(
            "| key | kind | meaning |\n"
            "|-----|------|---------|\n"
            "| `real.key` | counter | exists |\n"
            "| `ghost.key` | counter | no longer published |\n")
        (docs / "SERVING.md").write_text("nothing\n")
        (docs / "PERFORMANCE.md").write_text("nothing\n")
        # the doc-side direction only arms on a full-package view:
        # the marker module is obs/registry.py
        pkg = tmp_path / "obs"
        pkg.mkdir()
        reg = pkg / "registry.py"
        reg.write_text(
            "def publish(registry):\n"
            "    registry.counter('real.key').add()\n")
        found = analyze_paths([str(reg)], docs_root=str(tmp_path))
        h9 = _unsup(found, "H9")
        assert len(h9) == 1, [f.render() for f in found]
        assert "ghost.key" in h9[0].message
        assert h9[0].path.endswith("OBSERVABILITY.md")

    def test_fixture_tree_without_docs_skips_h9(self, tmp_path):
        bad = tmp_path / "rogue.py"
        bad.write_text(
            "def publish(reg):\n"
            "    reg.counter('zzz.undocumented').add()\n")
        # no docs_root and no docs/ up-tree from tmp: H9 must not run
        assert _unsup(analyze_paths([str(bad)]), "H9") == []

    def test_suppressed_with_reason(self, tmp_path):
        bad = tmp_path / "rogue.py"
        bad.write_text(
            "def publish(reg):\n"
            "    reg.counter('zzz.scratch_key').add()"
            "  # sparkdl-lint: allow[H9] -- scratch key for a local "
            "experiment, not a contract\n")
        found = analyze_paths([str(bad)], docs_root=REPO_ROOT)
        assert _unsup(found, "H9") == []
        assert len(_sup(found, "H9")) == 1

    def test_names_overlap_semantics(self):
        assert names_overlap("serve.*", "serve.latency_p50_ms")
        assert names_overlap("autotune.knob.*.*",
                             "autotune.knob.*.*")
        assert names_overlap("engine.stage.*.*",
                             "engine.stage.*.seconds")
        assert not names_overlap("serve.queue_rows", "ship.rows")
        assert not names_overlap("serve", "serve.rows")


# ---------------------------------------------------------------------------
# the result cache


class TestResultCache:
    def _run(self, targets, cache):
        stats: dict = {}
        found = analyze_paths(targets, cache_path=cache,
                              cache_stats=stats)
        return found, stats

    def test_second_run_hits_and_findings_match(self, tmp_path):
        src = tmp_path / "m.py"
        src.write_text("import jax\n"
                       "def f(x):\n"
                       "    return jax.device_get(x)\n")
        cache = str(tmp_path / "cache.json")
        first, s1 = self._run([str(src)], cache)
        second, s2 = self._run([str(src)], cache)
        assert s1 == {**s1, "hits": 0, "misses": 1}
        assert s2 == {**s2, "hits": 1, "misses": 0}
        assert [f.render() for f in first] == \
            [f.render() for f in second]

    def test_touched_file_reanalyzes(self, tmp_path):
        src = tmp_path / "m.py"
        src.write_text("x = 1\n")
        cache = str(tmp_path / "cache.json")
        found, _ = self._run([str(src)], cache)
        assert found == []
        src.write_text("import jax\n"
                       "def f(x):\n"
                       "    return jax.device_get(x)\n")
        found, stats = self._run([str(src)], cache)
        assert stats["misses"] == 1
        assert len(_unsup(found, "H1")) == 1

    def test_new_suppression_invalidates_via_hash(self, tmp_path):
        """Adding an inline allow[] edits the file, so the hash keys a
        fresh analysis — a cache must never pin a stale verdict."""
        src = tmp_path / "m.py"
        src.write_text("import jax\n"
                       "def f(x):\n"
                       "    return jax.device_get(x)\n")
        cache = str(tmp_path / "cache.json")
        found, _ = self._run([str(src)], cache)
        assert len(_unsup(found, "H1")) == 1
        src.write_text("import jax\n"
                       "def f(x):\n"
                       "    return jax.device_get(x)"
                       "  # sparkdl-lint: allow[H1] -- test drain\n")
        found, _ = self._run([str(src)], cache)
        assert _unsup(found, "H1") == []
        assert len(_sup(found, "H1")) == 1

    def test_corrupt_cache_degrades_to_fresh_analysis(self, tmp_path):
        src = tmp_path / "m.py"
        src.write_text("import jax\n"
                       "def f(x):\n"
                       "    return jax.device_get(x)\n")
        cache = tmp_path / "cache.json"
        cache.write_text("{ not json ]")
        found, stats = self._run([str(src)], str(cache))
        assert len(_unsup(found, "H1")) == 1
        assert stats["misses"] == 1


# ---------------------------------------------------------------------------
# CLI --json schema (what the ci.sh analyzer gate consumes)


class TestCliJson:
    def test_json_schema_and_exit_codes(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("import threading, time\n"
                       "LOCK = threading.Lock()\n"
                       "def f():\n"
                       "    with LOCK:\n"
                       "        time.sleep(1)\n")
        env = {**os.environ, "PYTHONPATH": REPO_ROOT}
        r = subprocess.run(
            [sys.executable, "-m", "sparkdl_tpu.analysis", "--json",
             "--no-cache", str(bad)],
            capture_output=True, text=True, env=env)
        assert r.returncode == 1
        d = json.loads(r.stdout)
        for key in ("findings", "unsuppressed", "suppressed", "rules",
                    "by_rule", "targets", "cache"):
            assert key in d, sorted(d)
        assert d["unsuppressed"] == 1
        assert d["by_rule"]["H8"]["unsuppressed"] == 1
        assert d["cache"]["enabled"] is False

    def test_json_cache_stats_round_trip(self, tmp_path):
        ok = tmp_path / "ok.py"
        ok.write_text("x = 1\n")
        cache = str(tmp_path / "c.json")
        env = {**os.environ, "PYTHONPATH": REPO_ROOT}
        for expect_hits in (0, 1):
            r = subprocess.run(
                [sys.executable, "-m", "sparkdl_tpu.analysis",
                 "--json", "--cache", cache, str(ok)],
                capture_output=True, text=True, env=env)
            assert r.returncode == 0, r.stderr
            d = json.loads(r.stdout)
            assert d["cache"]["hits"] == expect_hits

    def test_list_rules_covers_all_nineteen(self):
        env = {**os.environ, "PYTHONPATH": REPO_ROOT}
        r = subprocess.run(
            [sys.executable, "-m", "sparkdl_tpu.analysis",
             "--list-rules"],
            capture_output=True, text=True, env=env)
        assert r.returncode == 0
        for rule in ("H1", "H2", "H3", "H4", "H5", "H6", "H7", "H8",
                     "H9", "H10", "H11", "H12", "H13", "H14", "H15",
                     "H16", "H17", "H18", "H19"):
            assert f"{rule}:" in r.stdout


# ---------------------------------------------------------------------------
# the package-level meta pins (nine rules, tools/examples included)


class TestMetaNineRules:
    def test_package_tools_examples_lint_clean_all_rules(self):
        """THE acceptance gate: zero unsuppressed findings under the
        full rule set (now nineteen — the program-level rules ride the
        same default sweep) across the package + tools/ + examples/."""
        targets = [PKG_DIR]
        for extra in ("tools", "examples"):
            d = os.path.join(REPO_ROOT, extra)
            if os.path.isdir(d):
                targets.append(d)
        found = analyze_paths(targets)
        unsup = [f for f in found if not f.suppressed]
        assert unsup == [], "\n".join(f.render() for f in unsup)

    def test_real_package_has_no_h7_cycles(self):
        found = analyze_paths([PKG_DIR])
        assert _unsup(found, "H7") == [], \
            [f.render() for f in _unsup(found, "H7")]

    def test_native_build_hold_is_suppressed_not_invisible(self):
        """The one real H8 the first whole-program run surfaced — the
        native shim's g++ build under the load lock — must APPEAR as
        a suppressed finding with its justification."""
        found = analyze_paths([os.path.join(PKG_DIR, "native")])
        h8 = [f for f in found if f.rule == "H8"]
        assert any(f.suppressed and "g++" in f.suppression
                   for f in h8), [f.render() for f in h8]

    def test_collective_launch_is_one_lock_identity(self, tmp_path):
        """`with collective_launch(mesh)` canonicalizes to ONE global
        lock id wherever it is spelled — the PR-2 fix's ordering
        point must not fragment per importing module."""
        root = _tree(tmp_path, {
            "a.py": ("from sparkdl_tpu.parallel.mesh import "
                     "collective_launch\n"
                     "def f(mesh, prog):\n"
                     "    with collective_launch(mesh):\n"
                     "        prog()\n")})
        g = build_graph([os.path.join(root, "a.py")])
        f = next(v for v in g.functions.values() if v.qualname == "f")
        assert f.acquires[0].lock == "collective_launch"
