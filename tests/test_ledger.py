"""The windowed utilization ledger (sparkdl_tpu/obs/ledger.py): live
roofline accounting and the one-code-path bottleneck verdict.

The contracts pinned here, in ISSUE order: ``attribute()`` is the one
verdict (argmax utilization, deterministic ties, floored headroom,
idle on silence); windowed-rate edge cases — a zero-duration window
is a no-op, a feed counter moving backwards (registry cleared /
re-created) reads as an empty delta and is counted, the history ring
evicts with accounting and never silently; the probe cache degrades
to a fresh probe on corruption/absence; the disarmed hot-path poll
costs <10 µs (the tracer's shared-no-op regime); cloudpickle drops
the ring and carries the config; the hot paths actually feed the
ledger's counters; the Prometheus render pairs every ``# TYPE`` with
its ``# HELP``; ``throughput_report`` and ``report --bound`` print
the same-code-path verdict.
"""

import json
import time

import numpy as np
import pytest

from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.obs import MetricsRegistry, default_registry
from sparkdl_tpu.obs.export import render_prometheus
from sparkdl_tpu.obs.ledger import (
    PROBE_SCHEMA,
    STAGES,
    UtilizationLedger,
    attribute,
    ledger,
    ledger_poll,
    probe_ceilings,
)
from sparkdl_tpu.obs.report import bound_summary, summarize_bound

MB = 1024.0 * 1024.0


@pytest.fixture()
def fresh_ledger(tmp_path):
    """A standalone ledger with an isolated probe file and injected
    ceilings — tests must not touch the shared probe cache or the
    process-wide singleton's state."""
    led = UtilizationLedger(window_s=1.0, history=4,
                            probe_file=str(tmp_path / "probe.json"))
    led.ensure_ceilings({"link_h2d_MBps": 1.0,
                         "link_d2h_MBps": 1.0, "source": "test"})
    return led


def _bump(decode=0.0, compute=0.0, serve=0.0, wait=0.0, mb=0.0):
    reg = default_registry()
    if decode:
        reg.counter("engine.busy_seconds").add(decode)
    if compute:
        reg.counter("device.run_seconds").add(compute)
    if serve:
        reg.counter("serve.coalesce_wait_seconds").add(serve)
    if wait:
        reg.counter("ship.transfer_wait_seconds_total").add(wait)
    if mb:
        reg.counter("ship.bytes_shipped").add(mb * MB)


# ---------------------------------------------------------------------------
# attribute(): THE verdict


class TestAttribute:
    def test_argmax_stage_wins(self):
        v = attribute({"decode": 0.2, "link": 0.9, "compute": 0.3,
                       "serve": 0.0})
        assert v["bound_by"] == "link"
        assert v["headroom_pct"] == 10.0
        assert v["util"]["link"] == 0.9

    def test_ties_break_deterministically_alphabetical_first(self):
        v = attribute({"link": 0.5, "compute": 0.5, "decode": 0.5})
        assert v["bound_by"] == "compute"   # 'c' < 'd' < 'l'

    def test_headroom_floors_at_zero_above_ceiling(self):
        # a value measured above its ceiling (the link moved between
        # measurements) is zero headroom, never negative
        v = attribute({"link": 1.4})
        assert v["headroom_pct"] == 0.0

    def test_idle_when_empty_or_all_zero(self):
        assert attribute({})["bound_by"] == "idle"
        v = attribute({"decode": 0.0, "link": 0.0})
        assert v["bound_by"] == "idle"
        assert v["headroom_pct"] == 100.0


# ---------------------------------------------------------------------------
# windowed-rate edge cases


class TestWindowing:
    def test_first_tick_is_baseline_only(self, fresh_ledger):
        assert fresh_ledger.tick(now=10.0) is None
        assert fresh_ledger.history() == []

    def test_rates_divide_deltas_by_wall(self, fresh_ledger):
        fresh_ledger.baseline(now=100.0)
        _bump(decode=0.5, compute=0.25, serve=0.1, mb=0.25)
        w = fresh_ledger.tick(now=101.0)        # 1 s window
        assert w["util"]["decode"] == pytest.approx(0.5, abs=1e-6)
        assert w["util"]["compute"] == pytest.approx(0.25, abs=1e-6)
        assert w["util"]["serve"] == pytest.approx(0.1, abs=1e-6)
        # 0.25 MB over 1 s against the injected 1 MB/s ceiling
        assert w["util"]["link"] == pytest.approx(0.25, abs=1e-6)
        assert w["link_basis"] == "bytes/probed-bandwidth"
        assert w["bound_by"] == "decode"
        assert w["headroom_pct"] == pytest.approx(50.0)

    def test_zero_duration_window_is_noop(self, fresh_ledger):
        fresh_ledger.baseline(now=50.0)
        _bump(decode=0.3)
        assert fresh_ledger.tick(now=50.0) is None      # dt == 0
        assert fresh_ledger.tick(now=49.0) is None      # dt < 0
        assert fresh_ledger.history() == []
        # the baseline survived intact: the delta lands in the next
        # real window instead of being lost or double-divided
        w = fresh_ledger.tick(now=51.0)
        assert w is not None
        assert w["util"]["decode"] == pytest.approx(0.3, abs=1e-6)

    def test_utilization_clamps_to_unit_interval(self, fresh_ledger):
        fresh_ledger.baseline(now=0.0)
        _bump(decode=5.0, mb=50.0)      # 5 s busy in a 1 s window
        w = fresh_ledger.tick(now=1.0)
        assert w["util"]["decode"] == 1.0
        assert w["util"]["link"] == 1.0
        assert all(0.0 <= w["util"][s] <= 1.0 for s in STAGES)

    def test_counter_reset_reads_as_empty_delta(self, fresh_ledger):
        """Registry re-publish/clear moves a feed counter backwards;
        the window must read an empty delta (counted), never a
        negative rate."""
        fresh_ledger.baseline(now=0.0)
        _bump(decode=1.0)
        fresh_ledger.tick(now=1.0)
        # simulate the reset: a fresh registry object re-created the
        # counters at zero
        reg = default_registry()
        before = reg.counter("ledger.counter_resets").value
        reg.counter("engine.busy_seconds").value = 0.0
        w = fresh_ledger.tick(now=2.0)
        assert w["util"]["decode"] == 0.0
        assert w["counter_resets"] >= 1
        assert reg.counter("ledger.counter_resets").value > before

    def test_ring_evicts_with_accounting_never_silent(self, fresh_ledger):
        reg = default_registry()
        before = reg.counter("ledger.windows_evicted").value
        fresh_ledger.baseline(now=0.0)
        for i in range(7):
            _bump(compute=0.1)
            assert fresh_ledger.tick(now=float(i + 1)) is not None
        assert len(fresh_ledger.history()) == 4     # capacity
        assert fresh_ledger.windows == 7
        assert fresh_ledger.evicted == 3
        assert reg.counter("ledger.windows_evicted").value \
            - before == 3
        st = fresh_ledger.status()
        assert st["evicted"] == 3 and st["history_len"] == 4

    def test_link_degrades_to_transfer_wait_without_probe(self, tmp_path):
        led = UtilizationLedger(window_s=1.0, history=4,
                                probe_file=str(tmp_path / "p.json"))
        led.ensure_ceilings({"error": "no backend"})
        led.baseline(now=0.0)
        _bump(wait=0.4, mb=10.0)
        w = led.tick(now=1.0)
        assert w["link_basis"] == "transfer-wait"
        assert w["util"]["link"] == pytest.approx(0.4, abs=1e-6)

    def test_tick_due_respects_window_length(self, fresh_ledger):
        fresh_ledger.baseline(now=0.0)
        assert fresh_ledger.tick_due(now=0.5) is None   # not due
        _bump(compute=0.2)
        w = fresh_ledger.tick_due(now=1.5)
        assert w is not None
        assert fresh_ledger.tick_due(now=1.6) is None

    def test_racing_readers_cannot_close_duplicate_windows(
            self, fresh_ledger):
        """Two readers that both observed 'due' race into tick():
        min_dt makes the loser re-verify under the lock and back off
        — no junk microsecond window overwrites the real one, no
        double-counted ledger.windows."""
        fresh_ledger.baseline(now=0.0)
        _bump(compute=0.5)
        # both racers captured now≈1.5 at the due check; the winner
        # closes the real window, the loser's dt collapses to ~0
        w1 = fresh_ledger.tick(now=1.5, min_dt=1.0)
        w2 = fresh_ledger.tick(now=1.5000002, min_dt=1.0)
        assert w1 is not None
        assert w2 is None
        assert fresh_ledger.windows == 1
        assert len(fresh_ledger.history()) == 1
        # and a sub-min_dt tick leaves the baseline intact: the delta
        # lands in the next full window
        _bump(compute=0.25)
        assert fresh_ledger.tick(now=2.0, min_dt=1.0) is None
        w3 = fresh_ledger.tick(now=2.5, min_dt=1.0)
        assert w3 is not None
        assert w3["util"]["compute"] == pytest.approx(0.25, abs=1e-6)

    def test_tick_never_runs_a_measured_probe(self, tmp_path,
                                              monkeypatch):
        """Ticks ride scrape handlers, flight dumps, and the hot-path
        poll — where the device may be exactly what is wedged. A
        ceilings-less ledger must tick on the transfer-wait fallback
        without ever touching probe machinery."""
        import importlib
        # the package exports a ledger() accessor that shadows the
        # submodule attribute (the request_log precedent) — resolve
        # the MODULE explicitly
        ledger_mod = importlib.import_module("sparkdl_tpu.obs.ledger")

        def boom(*a, **k):
            raise AssertionError("tick ran a measured probe")

        monkeypatch.setattr(ledger_mod, "probe_ceilings", boom)
        led = UtilizationLedger(window_s=1.0, history=4,
                                probe_file=str(tmp_path / "absent.json"))
        led.baseline(now=0.0)
        _bump(wait=0.3)
        w = led.tick(now=1.0)
        assert w["link_basis"] == "transfer-wait"

    def test_tick_reads_probe_cache_file_without_measuring(
            self, tmp_path, monkeypatch):
        import importlib
        # the package exports a ledger() accessor that shadows the
        # submodule attribute (the request_log precedent) — resolve
        # the MODULE explicitly
        ledger_mod = importlib.import_module("sparkdl_tpu.obs.ledger")

        path = tmp_path / "probe.json"
        path.write_text(json.dumps({"schema": PROBE_SCHEMA,
                                    "link_h2d_MBps": 2.0}))
        monkeypatch.setattr(
            ledger_mod, "probe_ceilings",
            lambda *a, **k: (_ for _ in ()).throw(
                AssertionError("measured probe on tick path")))
        led = UtilizationLedger(window_s=1.0, history=4,
                                probe_file=str(path))
        led.baseline(now=0.0)
        _bump(mb=1.0)
        w = led.tick(now=1.0)
        assert w["link_basis"] == "bytes/probed-bandwidth"
        assert w["util"]["link"] == pytest.approx(0.5, abs=1e-6)


# ---------------------------------------------------------------------------
# the ceilings probe cache


class TestProbeCeilings:
    def _measure(self, calls):
        def measure(n_mb):
            calls.append(n_mb)
            return {"h2d_MBps": 123.0, "d2h_MBps": 45.0}
        return measure

    def test_missing_file_probes_fresh_and_caches(self, tmp_path):
        path = str(tmp_path / "probe.json")
        calls = []
        p = probe_ceilings(path=path, measure=self._measure(calls))
        assert p["link_h2d_MBps"] == 123.0
        assert p["schema"] == PROBE_SCHEMA
        assert len(calls) == 1
        # second call: steady state never re-pays the probe
        p2 = probe_ceilings(path=path, measure=self._measure(calls))
        assert p2["link_h2d_MBps"] == 123.0
        assert len(calls) == 1

    def test_corrupt_file_degrades_to_fresh_probe(self, tmp_path):
        path = tmp_path / "probe.json"
        path.write_text("{definitely not json")
        reg = default_registry()
        before = reg.counter("ledger.probe_errors").value
        calls = []
        p = probe_ceilings(path=str(path), measure=self._measure(calls))
        assert p["link_h2d_MBps"] == 123.0
        assert len(calls) == 1
        assert reg.counter("ledger.probe_errors").value > before
        # the cache was repaired: the next read hits it
        assert json.loads(path.read_text())["link_h2d_MBps"] == 123.0

    def test_wrong_schema_or_shape_degrades(self, tmp_path):
        path = tmp_path / "probe.json"
        path.write_text(json.dumps({"schema": "other/9",
                                    "link_h2d_MBps": 1.0}))
        calls = []
        p = probe_ceilings(path=str(path), measure=self._measure(calls))
        assert len(calls) == 1 and p["link_h2d_MBps"] == 123.0

    def test_failing_probe_returns_error_not_raise(self, tmp_path):
        def broken(n_mb):
            raise RuntimeError("no backend")
        p = probe_ceilings(path=str(tmp_path / "p.json"),
                           measure=broken)
        assert "error" in p
        assert not (tmp_path / "p.json").exists()

    def test_fractional_history_env_degrades_not_crashes(
            self, monkeypatch):
        """The module-level singleton parses these at import: a config
        typo must degrade to the default with one warning, never make
        `import sparkdl_tpu` fail."""
        from sparkdl_tpu.obs.ledger import DEFAULT_HISTORY, DEFAULT_WINDOW_S
        monkeypatch.setenv("SPARKDL_TPU_LEDGER_HISTORY", "0.5")
        monkeypatch.setenv("SPARKDL_TPU_LEDGER_WINDOW_S", "nope")
        led = UtilizationLedger()
        assert led.history_capacity == DEFAULT_HISTORY
        assert led.window_s == DEFAULT_WINDOW_S
        monkeypatch.setenv("SPARKDL_TPU_LEDGER_HISTORY", "-3")
        assert UtilizationLedger().history_capacity == DEFAULT_HISTORY


# ---------------------------------------------------------------------------
# the disarmed hot-path poll (the tracer's shared-no-op regime)


class TestPollOverhead:
    def test_disarmed_poll_under_10us(self, monkeypatch):
        monkeypatch.delenv("SPARKDL_TPU_LEDGER", raising=False)
        led = ledger()
        monkeypatch.setattr(led, "_override", None)
        n = 20_000
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(n):
                ledger_poll()
            best = min(best, (time.perf_counter() - t0) / n)
        assert best < 10e-6, f"disarmed poll costs {best * 1e6:.2f} µs"

    def test_armed_poll_advances_windows(self, monkeypatch, tmp_path):
        led = ledger()
        monkeypatch.setattr(led, "probe_file",
                            str(tmp_path / "p.json"))
        monkeypatch.setattr(led, "window_s", 0.0)
        monkeypatch.setattr(led, "_override", True)
        monkeypatch.setattr(
            led, "_ceilings",
            {"schema": PROBE_SCHEMA, "link_h2d_MBps": 100.0})
        before = led.windows
        ledger_poll()       # baseline
        _bump(compute=0.01)
        time.sleep(0.002)
        ledger_poll()       # closes a window
        assert led.windows > before


# ---------------------------------------------------------------------------
# pickle discipline (StageMetrics precedent)


class TestPickle:
    def test_ring_dropped_config_travels(self, fresh_ledger):
        cloudpickle = pytest.importorskip("cloudpickle")
        fresh_ledger.baseline(now=0.0)
        _bump(compute=0.5)
        assert fresh_ledger.tick(now=1.0) is not None
        assert fresh_ledger.history()
        clone = cloudpickle.loads(cloudpickle.dumps(fresh_ledger))
        # windows measured here are this process's record
        assert clone.history() == []
        assert clone.windows == 0 and clone.evicted == 0
        # configuration travels
        assert clone.window_s == fresh_ledger.window_s
        assert clone.history_capacity == fresh_ledger.history_capacity
        assert clone.status()["ceilings"]["link_h2d_MBps"] == 1.0
        # and the clone still windows correctly on arrival
        clone.baseline(now=0.0)
        _bump(compute=0.25)
        w = clone.tick(now=1.0)
        assert w["util"]["compute"] >= 0.25 - 1e-6


# ---------------------------------------------------------------------------
# the hot paths actually feed the ledger


class TestFeeds:
    def test_runner_feeds_compute_and_link_lanes(self):
        reg = default_registry()
        run_before = reg.counter("device.run_seconds").value
        bytes_before = reg.counter("ship.bytes_shipped").value
        mf = ModelFunction.fromSingle(lambda x: x * 2.0, None,
                                      input_shape=(4,))
        runner_inputs = np.ones((32, 4), np.float32)
        from sparkdl_tpu.runtime.runner import BatchRunner
        BatchRunner(mf, batch_size=8).run({"input": runner_inputs})
        assert reg.counter("device.run_seconds").value > run_before
        assert reg.counter("ship.bytes_shipped").value \
            - bytes_before == runner_inputs.nbytes

    def test_host_backend_counts_compute_but_ships_nothing(self):
        reg = default_registry()
        run_before = reg.counter("device.run_seconds").value
        bytes_before = reg.counter("ship.bytes_shipped").value

        def apply(params, inputs):
            return {"y": np.asarray(inputs["x"], np.float32) * 2.0}

        mf = ModelFunction(apply, None,
                           input_signature={"x": ((2,), np.float32)},
                           output_names=["y"], backend="host")
        from sparkdl_tpu.runtime.runner import BatchRunner
        BatchRunner(mf, batch_size=4).run(
            {"x": np.ones((8, 2), np.float32)})
        assert reg.counter("device.run_seconds").value > run_before
        assert reg.counter("ship.bytes_shipped").value == bytes_before

    def test_engine_feeds_decode_lane(self):
        from sparkdl_tpu.data import DataFrame
        from sparkdl_tpu.data.engine import LocalEngine
        reg = default_registry()
        before = reg.counter("engine.busy_seconds").value
        df = DataFrame.from_pylist(
            [{"x": float(i)} for i in range(8)], num_partitions=2,
            engine=LocalEngine(num_workers=1))
        df.map_batches(lambda b: b, name="noop").collect()
        assert reg.counter("engine.busy_seconds").value > before


# ---------------------------------------------------------------------------
# surfaces: Prometheus HELP pairing, throughput_report, report --bound


class TestSurfaces:
    def test_every_type_line_has_its_help_line(self):
        reg = MetricsRegistry()
        reg.counter("ledger.windows").add()
        reg.gauge("ledger.util.link").set(0.5)
        reg.reservoir("serve.latency_seconds").observe(0.01)
        text = render_prometheus(reg)
        helps, types, samples = set(), set(), set()
        for line in text.strip().splitlines():
            if line.startswith("# HELP "):
                helps.add(line.split(" ")[2])
            elif line.startswith("# TYPE "):
                name = line.split(" ")[2]
                types.add(name)
                # the HELP must already have been emitted for it
                assert name in helps, line
            else:
                samples.add(line.split(" ")[0])
        assert samples <= types
        assert types == helps

    def test_throughput_report_prints_bound_line(self):
        from sparkdl_tpu.runtime.runner import RunnerMetrics
        from sparkdl_tpu.utils import StageMetrics, throughput_report
        sm = StageMetrics()
        sm.add("decode", 1.0, 100)
        rm = RunnerMetrics()
        rm.add(100, 2, 0.5)
        rep = throughput_report(sm, rm)
        assert "bound by: " in rep
        assert "headroom" in rep
        # the no-input shape keeps its contract
        assert throughput_report() == "(no metrics)"

    def _trace(self):
        return [
            {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
             "args": {"name": "engine"}},
            {"name": "process_name", "ph": "M", "pid": 2, "tid": 0,
             "args": {"name": "ship"}},
            {"name": "process_name", "ph": "M", "pid": 3, "tid": 0,
             "args": {"name": "device"}},
            {"name": "stage:decode", "ph": "X", "ts": 0.0,
             "dur": 400.0, "pid": 1, "tid": 1},
            {"name": "dispatch", "ph": "X", "ts": 0.0, "dur": 100.0,
             "pid": 2, "tid": 1},
            {"name": "device_get", "ph": "X", "ts": 100.0,
             "dur": 900.0, "pid": 3, "tid": 1},
        ]

    def test_report_bound_reads_lanes_and_verdicts(self):
        b = bound_summary(self._trace())
        assert b["util"]["decode"] == pytest.approx(0.4, abs=1e-3)
        assert b["util"]["link"] == pytest.approx(0.9, abs=1e-3)
        assert b["util"]["compute"] == pytest.approx(0.1, abs=1e-3)
        assert b["bound_by"] == "link"
        text = summarize_bound(self._trace())
        assert "bound by: link" in text
        assert "live roofline" in text

    def test_report_bound_empty_trace_degrades(self):
        assert bound_summary([]) is None
        assert "no spans" in summarize_bound([])

    def test_bound_cli_flag(self, tmp_path, capsys):
        from sparkdl_tpu.obs.report import main
        path = tmp_path / "trace.json"
        path.write_text(json.dumps(self._trace()))
        assert main(["report", "--bound", str(path)]) == 0
        out = capsys.readouterr().out
        assert "bound by: link" in out
