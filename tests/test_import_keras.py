"""Keras-applications → Flax zoo weight conversion oracle tests.

The strongest architecture-fidelity check in the suite: build the
keras.applications model with random weights, convert with
``import_keras_weights``, and require numerically identical outputs.
Any divergence between a Flax zoo architecture and its Keras
counterpart (layer order, padding, BN epsilon, biases) fails here.
All five reference zoo architectures have an oracle.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

# keras warns when predict() gets a bare array instead of its named
# input structure — the standard calling convention for single-input
# models; pure noise in the oracle comparisons
pytestmark = pytest.mark.filterwarnings(
    "ignore:The structure of `inputs` doesn't match")

from sparkdl_tpu.models.import_keras import (
    import_keras_weights,
    import_named_model,
)


def _oracle(name, keras_builder, module, size, tol, feat_layer,
            feat_tol):
    import keras
    keras.utils.set_random_seed(7)
    kmodel = keras_builder(weights=None)
    variables = import_keras_weights(module, kmodel, (size, size, 3))
    rng = np.random.default_rng(1)
    x = rng.normal(0, 1, (2, size, size, 3)).astype(np.float32)
    ours = jax.nn.softmax(
        module.apply(variables, jnp.asarray(x), train=False), axis=-1)
    theirs = np.asarray(kmodel(x))
    diff = float(np.abs(np.asarray(ours) - theirs).max())
    assert diff <= tol, f"{name}: max prob diff {diff} > {tol}"

    # FEATURIZE-layer equivalence, not just softmax: the penultimate
    # vector is what DeepImageFeaturizer actually serves (transfer
    # learning, BASELINE config #1) — a head-only match could hide a
    # divergent trunk behind softmax saturation
    feats_ours = np.asarray(module.apply(variables, jnp.asarray(x),
                                         train=False, features_only=True))
    feat_extractor = keras.Model(kmodel.inputs,
                                 kmodel.get_layer(feat_layer).output)
    feats_theirs = np.asarray(feat_extractor(x))
    assert feats_ours.shape == feats_theirs.shape, \
        f"{name}: featurize shape {feats_ours.shape} != " \
        f"{feats_theirs.shape}"
    scale = max(1.0, float(np.abs(feats_theirs).max()))
    fdiff = float(np.abs(feats_ours - feats_theirs).max()) / scale
    assert fdiff <= feat_tol, \
        f"{name}: featurize relative diff {fdiff} > {feat_tol}"
    return variables


class TestConversionOracles:
    def test_inception_v3(self):
        import keras
        from sparkdl_tpu.models.inception import InceptionV3
        _oracle("InceptionV3", keras.applications.inception_v3.InceptionV3,
                InceptionV3(dtype=jnp.float32), 299, 1e-4,
                "avg_pool", 1e-4)

    def test_vgg16(self):
        import keras
        from sparkdl_tpu.models.vgg import VGG16
        _oracle("VGG16", keras.applications.vgg16.VGG16,
                VGG16(dtype=jnp.float32), 224, 1e-5, "fc2", 1e-5)

    def test_vgg19(self):
        """VERDICT r3 missing #5: the one zoo architecture without a
        fidelity proof — same tolerance as VGG16."""
        import keras
        from sparkdl_tpu.models.vgg import VGG19
        _oracle("VGG19", keras.applications.vgg19.VGG19,
                VGG19(dtype=jnp.float32), 224, 1e-5, "fc2", 1e-5)

    def test_resnet50(self):
        import keras
        from sparkdl_tpu.models.resnet import ResNet50
        _oracle("ResNet50", keras.applications.resnet50.ResNet50,
                ResNet50(dtype=jnp.float32), 224, 1e-5,
                "avg_pool", 1e-5)

    def test_xception(self):
        import keras
        from sparkdl_tpu.models.xception import Xception
        _oracle("Xception", keras.applications.xception.Xception,
                Xception(dtype=jnp.float32), 299, 1e-4,
                "avg_pool", 1e-4)


class TestZooIntegration:
    def test_import_named_model_feeds_zoo_cache(self, tmp_path,
                                                monkeypatch):
        """Converted weights land in the ModelFetcher cache and
        zoo.getModelFunction serves them instead of seeded init."""
        import keras
        from sparkdl_tpu.models import zoo
        from sparkdl_tpu.models.fetcher import ModelFetcher

        monkeypatch.setenv("SPARKDL_TPU_MODEL_CACHE", str(tmp_path))
        keras.utils.set_random_seed(3)
        kmodel = keras.applications.vgg16.VGG16(weights=None)
        fetcher = ModelFetcher()
        imported = import_named_model("VGG16", keras_model=kmodel,
                                      fetcher=fetcher)
        assert fetcher.has("VGG16.msgpack")

        mf = zoo.getModelFunction("VGG16", featurize=False,
                                  fetcher=fetcher)
        rng = np.random.default_rng(2)
        x = rng.integers(0, 255, (1, 224, 224, 3), dtype=np.uint8)
        # predict path emits PROBABILITIES (keras classifier heads end
        # in softmax; decode_predictions scores match reference scale)
        ours = np.asarray(mf({"image": x})["predictions"])
        # oracle: keras on the same caffe-preprocessed input
        pre = x.astype(np.float32)[..., ::-1] - np.array(
            [103.939, 116.779, 123.68], np.float32)
        expected = np.asarray(kmodel(pre))
        np.testing.assert_allclose(ours, expected, atol=1e-3)

    def test_count_mismatch_fails_loudly(self):
        import keras
        from sparkdl_tpu.models.testnet import TestNet
        kmodel = keras.applications.vgg16.VGG16(weights=None)
        with pytest.raises(ValueError, match="count mismatch"):
            import_keras_weights(TestNet(dtype=jnp.float32), kmodel,
                                 (32, 32, 3))
