"""Native C++ host shim tests: build, correctness vs the Python path,
fallback behavior. (The reference's native host path — JVM resize +
TensorFrames — was likewise tested against golden/PIL images,
``ImageUtilsSuite.scala``.)"""

import numpy as np
import pytest

from sparkdl_tpu import native
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.transformers.utils import packImageBatch


@pytest.fixture(scope="module")
def built():
    ok = native.available()
    assert ok, "native shim failed to build (g++ is expected in this env)"
    return ok


def _structs_column(arrays):
    import pyarrow as pa
    structs = [imageIO.imageArrayToStruct(a) if a is not None else None
               for a in arrays]
    return pa.array(structs, type=imageIO.imageType)


class TestNativeShim:
    def test_same_size_pack_is_exact(self, built):
        rng = np.random.default_rng(0)
        imgs = [rng.integers(0, 255, (16, 12, 3), dtype=np.uint8)
                for _ in range(5)]
        out = native.resize_pack_batch(imgs, 16, 12, 3)
        np.testing.assert_array_equal(out, np.stack(imgs))

    def test_resize_close_to_pil_on_smooth_images(self, built):
        # smooth gradients: bilinear and PIL's triangle filter agree
        # to within a few counts
        y = np.linspace(0, 255, 64)[:, None, None]
        x = np.linspace(0, 255, 48)[None, :, None]
        img = np.clip((y + x) / 2, 0, 255).astype(np.uint8)
        img = np.repeat(img, 3, axis=2)
        got = native.resize_pack_batch([img], 32, 24, 3)[0]
        exp = imageIO.resizeImageArray(img, 32, 24, 3)
        assert np.abs(got.astype(int) - exp.astype(int)).max() <= 4

    def test_upscale_close_to_pil(self, built):
        y = np.linspace(0, 255, 10)[:, None, None]
        img = np.repeat(np.repeat(y, 8, axis=1), 3, axis=2).astype(np.uint8)
        got = native.resize_pack_batch([img], 20, 16, 3)[0]
        exp = imageIO.resizeImageArray(img, 20, 16, 3)
        assert np.abs(got.astype(int) - exp.astype(int)).max() <= 4

    def test_channel_conversions(self, built):
        rng = np.random.default_rng(1)
        gray = rng.integers(0, 255, (10, 10, 1), dtype=np.uint8)
        out = native.resize_pack_batch([gray], 10, 10, 3)[0]
        np.testing.assert_array_equal(out, np.repeat(gray, 3, axis=2))

        rgba = rng.integers(0, 255, (10, 10, 4), dtype=np.uint8)
        out = native.resize_pack_batch([rgba], 10, 10, 3)[0]
        np.testing.assert_array_equal(out, rgba[:, :, :3])

        rgb = rng.integers(0, 255, (10, 10, 3), dtype=np.uint8)
        out = native.resize_pack_batch([rgb], 10, 10, 1)[0]
        # ITU-R 601-2 luma, same formula as PIL "L" (rounding ±1)
        rgbf = rgb.astype(np.float64)
        exp = (rgbf[..., 0] * 299 + rgbf[..., 1] * 587
               + rgbf[..., 2] * 114) / 1000.0
        assert np.abs(out[..., 0].astype(float) - exp).max() <= 1.0

    def test_rgba_to_gray_both_paths(self, built):
        """4→1 must be supported identically with and without the shim
        (regression: native accepted it, the PIL fallback rejected it)."""
        rng = np.random.default_rng(9)
        rgba = rng.integers(0, 255, (6, 6, 4), dtype=np.uint8)
        nat = native.resize_pack_batch([rgba], 6, 6, 1)[0]
        py = imageIO.resizeImageArray(rgba, 6, 6, 1)
        assert nat.shape == py.shape == (6, 6, 1)
        assert np.abs(nat.astype(int) - py.astype(int)).max() <= 1

    def test_unsupported_conversion_raises(self, built):
        gray = np.zeros((4, 4, 1), dtype=np.uint8)
        with pytest.raises(ValueError, match="channel conversion"):
            native.resize_pack_batch([gray], 4, 4, 4)

    def test_mixed_sizes_batch(self, built):
        rng = np.random.default_rng(2)
        imgs = [rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
                for h, w in [(8, 8), (20, 30), (15, 7)]]
        out = native.resize_pack_batch(imgs, 12, 12, 3)
        assert out.shape == (3, 12, 12, 3)
        np.testing.assert_array_equal(
            out[0], native.resize_pack_batch([imgs[0]], 12, 12, 3)[0])

    def test_empty_batch(self, built):
        out = native.resize_pack_batch([], 8, 8, 3)
        assert out.shape == (0, 8, 8, 3)


class TestPackImageBatchIntegration:
    def test_pack_uses_native_and_matches_python(self, built):
        rng = np.random.default_rng(3)
        smooth = np.repeat(np.repeat(
            np.linspace(0, 255, 18)[:, None, None], 20, axis=1),
            3, axis=2).astype(np.uint8)
        imgs = [rng.integers(0, 255, (14, 14, 3), dtype=np.uint8),
                rng.integers(0, 255, (14, 14, 3), dtype=np.uint8),
                smooth]
        col = _structs_column(imgs)
        got = packImageBatch(col, 14, 14, 3)
        # same-size rows are exact; the smooth resized row is close to
        # PIL (resamplers differ: bilinear vs triangle filter)
        np.testing.assert_array_equal(got[0], imgs[0])
        np.testing.assert_array_equal(got[1], imgs[1])
        exp2 = imageIO.resizeImageArray(imgs[2], 14, 14, 3)
        assert np.abs(got[2].astype(int) - exp2.astype(int)).max() <= 6

    def test_null_image_raises(self, built):
        col = _structs_column(
            [np.zeros((4, 4, 3), np.uint8), None])
        with pytest.raises(ValueError, match="null image"):
            packImageBatch(col, 4, 4, 3)

    def test_python_fallback_env_flag(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_TPU_NO_NATIVE", "1")
        assert native.resize_pack_batch(
            [np.zeros((4, 4, 3), np.uint8)], 4, 4, 3) is None
        rng = np.random.default_rng(4)
        imgs = [rng.integers(0, 255, (6, 9, 3), dtype=np.uint8)]
        col = _structs_column(imgs)
        out = packImageBatch(col, 8, 8, 3)
        np.testing.assert_array_equal(
            out[0], imageIO.resizeImageArray(imgs[0], 8, 8, 3))
