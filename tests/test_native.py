"""Native C++ host shim tests: build, correctness vs the Python path,
fallback behavior. (The reference's native host path — JVM resize +
TensorFrames — was likewise tested against golden/PIL images,
``ImageUtilsSuite.scala``.)"""

import numpy as np
import pytest

from sparkdl_tpu import native
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.transformers.utils import packImageBatch


@pytest.fixture(scope="module")
def built():
    if native.disabled_by_env():
        pytest.skip("native shim explicitly disabled via "
                    "SPARKDL_TPU_NO_NATIVE (fallback-path suite run)")
    ok = native.available()
    assert ok, "native shim failed to build (g++ is expected in this env)"
    return ok


def _structs_column(arrays):
    import pyarrow as pa
    structs = [imageIO.imageArrayToStruct(a) if a is not None else None
               for a in arrays]
    return pa.array(structs, type=imageIO.imageType)


class TestNativeShim:
    def test_same_size_pack_is_exact(self, built):
        rng = np.random.default_rng(0)
        imgs = [rng.integers(0, 255, (16, 12, 3), dtype=np.uint8)
                for _ in range(5)]
        out = native.resize_pack_batch(imgs, 16, 12, 3)
        np.testing.assert_array_equal(out, np.stack(imgs))

    def test_resize_close_to_pil_on_smooth_images(self, built):
        # smooth gradients: bilinear and PIL's triangle filter agree
        # to within a few counts
        y = np.linspace(0, 255, 64)[:, None, None]
        x = np.linspace(0, 255, 48)[None, :, None]
        img = np.clip((y + x) / 2, 0, 255).astype(np.uint8)
        img = np.repeat(img, 3, axis=2)
        got = native.resize_pack_batch([img], 32, 24, 3)[0]
        exp = imageIO.resizeImageArray(img, 32, 24, 3)
        assert np.abs(got.astype(int) - exp.astype(int)).max() <= 4

    def test_upscale_close_to_pil(self, built):
        y = np.linspace(0, 255, 10)[:, None, None]
        img = np.repeat(np.repeat(y, 8, axis=1), 3, axis=2).astype(np.uint8)
        got = native.resize_pack_batch([img], 20, 16, 3)[0]
        exp = imageIO.resizeImageArray(img, 20, 16, 3)
        assert np.abs(got.astype(int) - exp.astype(int)).max() <= 4

    def test_channel_conversions(self, built):
        rng = np.random.default_rng(1)
        gray = rng.integers(0, 255, (10, 10, 1), dtype=np.uint8)
        out = native.resize_pack_batch([gray], 10, 10, 3)[0]
        np.testing.assert_array_equal(out, np.repeat(gray, 3, axis=2))

        rgba = rng.integers(0, 255, (10, 10, 4), dtype=np.uint8)
        out = native.resize_pack_batch([rgba], 10, 10, 3)[0]
        np.testing.assert_array_equal(out, rgba[:, :, :3])

        rgb = rng.integers(0, 255, (10, 10, 3), dtype=np.uint8)
        out = native.resize_pack_batch([rgb], 10, 10, 1)[0]
        # ITU-R 601-2 luma, same formula as PIL "L" (rounding ±1)
        rgbf = rgb.astype(np.float64)
        exp = (rgbf[..., 0] * 299 + rgbf[..., 1] * 587
               + rgbf[..., 2] * 114) / 1000.0
        assert np.abs(out[..., 0].astype(float) - exp).max() <= 1.0

    def test_rgba_to_gray_both_paths(self, built):
        """4→1 must be supported identically with and without the shim
        (regression: native accepted it, the PIL fallback rejected it)."""
        rng = np.random.default_rng(9)
        rgba = rng.integers(0, 255, (6, 6, 4), dtype=np.uint8)
        nat = native.resize_pack_batch([rgba], 6, 6, 1)[0]
        py = imageIO.resizeImageArray(rgba, 6, 6, 1)
        assert nat.shape == py.shape == (6, 6, 1)
        assert np.abs(nat.astype(int) - py.astype(int)).max() <= 1

    def test_unsupported_conversion_raises(self, built):
        gray = np.zeros((4, 4, 1), dtype=np.uint8)
        with pytest.raises(ValueError, match="channel conversion"):
            native.resize_pack_batch([gray], 4, 4, 4)

    def test_mixed_sizes_batch(self, built):
        rng = np.random.default_rng(2)
        imgs = [rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
                for h, w in [(8, 8), (20, 30), (15, 7)]]
        out = native.resize_pack_batch(imgs, 12, 12, 3)
        assert out.shape == (3, 12, 12, 3)
        np.testing.assert_array_equal(
            out[0], native.resize_pack_batch([imgs[0]], 12, 12, 3)[0])

    def test_empty_batch(self, built):
        out = native.resize_pack_batch([], 8, 8, 3)
        assert out.shape == (0, 8, 8, 3)


class TestPackImageBatchIntegration:
    def test_pack_uses_native_and_matches_python(self, built):
        rng = np.random.default_rng(3)
        smooth = np.repeat(np.repeat(
            np.linspace(0, 255, 18)[:, None, None], 20, axis=1),
            3, axis=2).astype(np.uint8)
        imgs = [rng.integers(0, 255, (14, 14, 3), dtype=np.uint8),
                rng.integers(0, 255, (14, 14, 3), dtype=np.uint8),
                smooth]
        col = _structs_column(imgs)
        got = packImageBatch(col, 14, 14, 3)
        # same-size rows are exact; the smooth resized row is close to
        # PIL (resamplers differ: bilinear vs triangle filter)
        np.testing.assert_array_equal(got[0], imgs[0])
        np.testing.assert_array_equal(got[1], imgs[1])
        exp2 = imageIO.resizeImageArray(imgs[2], 14, 14, 3)
        assert np.abs(got[2].astype(int) - exp2.astype(int)).max() <= 6

    def test_null_image_raises(self, built):
        col = _structs_column(
            [np.zeros((4, 4, 3), np.uint8), None])
        with pytest.raises(ValueError, match="null image"):
            packImageBatch(col, 4, 4, 3)

    def test_same_size_batch_is_zero_copy_view(self, built):
        """An all-target-size batch must come back as a VIEW over the
        Arrow data buffer — no per-row Python, no memcpy (VERDICT r1
        weak #5: the featurize hot path must not round-trip through
        to_pylist)."""
        rng = np.random.default_rng(5)
        imgs = [rng.integers(0, 255, (6, 7, 3), dtype=np.uint8)
                for _ in range(4)]
        col = _structs_column(imgs)
        out = imageIO.imageColumnToNHWC(col, 6, 7, 3)
        for i, img in enumerate(imgs):
            np.testing.assert_array_equal(out[i], img)
        # view, not copy: walking .base reaches a buffer whose memory
        # contains out's data pointer
        assert out.base is not None
        # packImageBatch takes the same zero-copy path for uniform sizes
        out2 = packImageBatch(col, 6, 7, 3)
        assert out2.base is not None
        np.testing.assert_array_equal(np.asarray(out2), np.asarray(out))

    def test_views_on_sliced_batch(self, built):
        """Buffer views must respect Arrow slice offsets (a sliced
        RecordBatch shares buffers with the parent)."""
        rng = np.random.default_rng(6)
        imgs = [rng.integers(0, 255, (5, 5, 3), dtype=np.uint8)
                for _ in range(6)]
        col = _structs_column(imgs).slice(2, 3)
        out = imageIO.imageColumnToNHWC(col, 5, 5, 3)
        assert out.shape == (3, 5, 5, 3)
        for i in range(3):
            np.testing.assert_array_equal(out[i], imgs[2 + i])
        # mixed-size native path on the sliced column too
        mixed = imgs[:3] + [rng.integers(0, 255, (9, 4, 3),
                                         dtype=np.uint8)]
        col2 = _structs_column(mixed).slice(1, 3)
        out2 = packImageBatch(col2, 5, 5, 3)
        assert out2.shape == (3, 5, 5, 3)
        np.testing.assert_array_equal(out2[0], imgs[1])
        np.testing.assert_array_equal(out2[1], imgs[2])

    def test_python_fallback_env_flag(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_TPU_NO_NATIVE", "1")
        assert native.resize_pack_batch(
            [np.zeros((4, 4, 3), np.uint8)], 4, 4, 3) is None
        rng = np.random.default_rng(4)
        imgs = [rng.integers(0, 255, (6, 9, 3), dtype=np.uint8)]
        col = _structs_column(imgs)
        out = packImageBatch(col, 8, 8, 3)
        np.testing.assert_array_equal(
            out[0], imageIO.resizeImageArray(imgs[0], 8, 8, 3))


class TestNativeJpeg:
    def _jpeg_bytes(self, arr, quality=95):
        import io
        from PIL import Image
        buf = io.BytesIO()
        Image.fromarray(arr, "RGB").save(buf, format="JPEG",
                                         quality=quality)
        return buf.getvalue()

    def test_decode_matches_pil(self, built):
        if not native.has_jpeg():
            pytest.skip("libjpeg not available at build time")
        import io
        from PIL import Image
        rng = np.random.default_rng(0)
        arrs = [rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
                for h, w in [(24, 32), (17, 9)]]
        blobs = [self._jpeg_bytes(a) for a in arrs]
        got = native.decode_jpeg_batch(blobs)
        for blob, out in zip(blobs, got):
            pil = np.asarray(Image.open(io.BytesIO(blob)).convert("RGB"))
            assert out.shape == pil.shape
            # both decode through libjpeg; tiny IDCT variations allowed
            assert np.abs(out.astype(int) - pil.astype(int)).max() <= 1

    def test_corrupt_jpeg_returns_none(self, built):
        if not native.has_jpeg():
            pytest.skip("libjpeg not available at build time")
        good = self._jpeg_bytes(
            np.zeros((8, 8, 3), np.uint8))
        out = native.decode_jpeg_batch(
            [b"\xff\xd8\xffgarbage", good])
        assert out[0] is None
        assert out[1] is not None

    def test_fused_decode_resize_pack(self, built):
        if not native.has_jpeg():
            pytest.skip("libjpeg not available at build time")
        rng = np.random.default_rng(1)
        arrs = [rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
                for h, w in [(40, 40), (20, 28)]]
        blobs = [self._jpeg_bytes(a) for a in arrs]
        batch, ok = native.decode_resize_pack(blobs, 16, 16, 3)
        assert batch.shape == (2, 16, 16, 3) and ok.all()
        # oracle: two-step native decode then resize
        two_step = native.resize_pack_batch(
            native.decode_jpeg_batch(blobs), 16, 16, 3)
        np.testing.assert_array_equal(batch, two_step)

    def test_fused_marks_failures(self, built):
        if not native.has_jpeg():
            pytest.skip("libjpeg not available at build time")
        good = self._jpeg_bytes(np.zeros((8, 8, 3), np.uint8))
        batch, ok = native.decode_resize_pack(
            [good, b"\xff\xd8\xffbroken"], 8, 8, 3)
        assert ok.tolist() == [True, False]
        assert (batch[1] == 0).all()

    def test_read_images_jpeg_native_path(self, built, tmp_path):
        """readImages over JPEGs decodes through the native batch call
        and matches the PIL fallback exactly enough to be
        interchangeable."""
        if not native.has_jpeg():
            pytest.skip("libjpeg not available at build time")
        from PIL import Image
        rng = np.random.default_rng(2)
        for i in range(4):
            arr = rng.integers(0, 255, (30, 22, 3), dtype=np.uint8)
            Image.fromarray(arr, "RGB").save(tmp_path / f"j{i}.jpg",
                                             quality=92)
        df = imageIO.readImages(str(tmp_path), numPartitions=2)
        rows = df.collect_rows()
        assert len(rows) == 4
        for r in rows:
            arr = imageIO.imageStructToArray(r["image"])
            assert arr.shape == (30, 22, 3)

    def test_grayscale_jpeg_schema_matches_pil_path(self, built,
                                                    tmp_path):
        """Grayscale JPEGs must produce the SAME nChannels with and
        without the shim (regression: native forced RGB while PIL kept
        1 channel)."""
        import os
        from PIL import Image
        arr = np.linspace(0, 255, 12 * 12).reshape(12, 12).astype(
            np.uint8)
        Image.fromarray(arr, "L").save(tmp_path / "g.jpg", quality=95)
        df = imageIO.readImages(str(tmp_path))
        row_native = df.collect_rows()[0]["image"]
        os.environ["SPARKDL_TPU_NO_NATIVE"] = "1"
        try:
            row_pil = imageIO.readImages(
                str(tmp_path)).collect_rows()[0]["image"]
        finally:
            del os.environ["SPARKDL_TPU_NO_NATIVE"]
        assert row_native["nChannels"] == row_pil["nChannels"]

    def test_oversized_header_rejected(self, built):
        if not native.has_jpeg():
            pytest.skip("libjpeg not available at build time")
        # hand-build a JPEG SOI+SOF0 claiming absurd dimensions
        import struct
        sof = (b"\xff\xd8"                       # SOI
               b"\xff\xc0" + struct.pack(">HBHHB", 11, 8, 65000, 65000, 3)
               + b"\x01\x11\x00\x02\x11\x00\x03\x11\x00")
        out = native.decode_jpeg_batch([sof])
        assert out == [None]


class TestReadImagesPacked:
    def test_packed_reader_matches_general_reader(self, built, tmp_path):
        from PIL import Image
        rng = np.random.default_rng(5)
        for i in range(4):
            arr = rng.integers(0, 255, (30, 26, 3), dtype=np.uint8)
            Image.fromarray(arr, "RGB").save(tmp_path / f"p{i}.jpg",
                                             quality=92)
        # smooth PNG: its fallback resize is PIL (triangle filter) while
        # the oracle resizes natively — only close on smooth content
        smooth = np.repeat(np.repeat(
            np.linspace(0, 255, 18)[:, None, None], 18, axis=1),
            3, axis=2).astype(np.uint8)
        Image.fromarray(smooth, "RGB").save(tmp_path / "x.png")

        # scaledDecode=False: this is the exact-pixel oracle comparison
        # (the scaled path's deliberate few-count difference is covered
        # by TestScaledDecode)
        df = imageIO.readImagesPacked(str(tmp_path), (16, 16),
                                      numPartitions=2,
                                      scaledDecode=False)
        packed = df.tensor("image")
        assert packed.shape == (5, 16, 16, 3)

        # oracle: general reader + per-row resize
        from sparkdl_tpu.transformers.utils import packImageBatch
        gen = imageIO.readImages(str(tmp_path), numPartitions=2)
        expected = packImageBatch(gen.collect().column("image"),
                                  16, 16, 3)
        assert np.abs(packed.astype(int)
                      - expected.astype(int)).max() <= 2

    def test_packed_reader_failure_handling(self, built, tmp_path):
        from PIL import Image
        Image.fromarray(np.zeros((8, 8, 3), np.uint8), "RGB").save(
            tmp_path / "good.jpg")
        (tmp_path / "bad.jpg").write_bytes(b"\xff\xd8\xffnope")
        df = imageIO.readImagesPacked(str(tmp_path), (8, 8))
        assert df.tensor("image").shape == (1, 8, 8, 3)

        kept = imageIO.readImagesPacked(str(tmp_path), (8, 8),
                                        dropImageFailures=False)
        rows = kept.collect_rows()
        assert len(rows) == 2
        ok_by_name = {r["filePath"].rsplit("/", 1)[-1]: r["imageOk"]
                      for r in rows}
        assert ok_by_name == {"good.jpg": True, "bad.jpg": False}


class TestYuv420:
    """The 4:2:0 link-payload path (VERDICT r4 next #1): native packer
    vs the Python codec oracle, raw-vs-fallback source handling, and the
    packed reader."""

    def _jpeg(self, arr, subsampling, quality=92):
        import io
        from PIL import Image
        buf = io.BytesIO()
        Image.fromarray(arr, "RGB").save(buf, format="JPEG",
                                         quality=quality,
                                         subsampling=subsampling)
        return buf.getvalue()

    def test_fallback_444_matches_python_codec_exactly(self, built):
        """A 4:4:4 source takes the native RGB-decode fallback, whose
        pipeline (decode → resize_one → rgb_to_yuv420) is algorithm-
        identical to rgbToYuv420 over the native RGB pack — so the two
        agree to float-rounding (≤1 count)."""
        if not native.has_jpeg():
            pytest.skip("libjpeg not available at build time")
        rng = np.random.default_rng(0)
        arr = rng.integers(0, 255, (37, 45, 3), dtype=np.uint8)
        blob = self._jpeg(arr, subsampling=0)
        packed, ok = native.decode_resize_pack_420([blob], 20, 24)
        assert ok.all()
        rgb, ok2 = native.decode_resize_pack([blob], 20, 24, 3)
        assert ok2.all()
        oracle = imageIO.rgbToYuv420(rgb[0])
        assert np.abs(packed[0].astype(int)
                      - oracle.astype(int)).max() <= 1

    def test_raw_420_path_close_to_rgb_route(self, built):
        """A standard 4:2:0 source takes the raw libjpeg path (chroma
        never upsampled on host). Its planes must stay close to the
        RGB route's re-subsampled ones — they differ only by libjpeg's
        fancy upsample vs our bilinear handling of the SAME stored
        chroma (tolerance: mean ≤2, max ≤32 counts on textured data)."""
        if not native.has_jpeg():
            pytest.skip("libjpeg not available at build time")
        from sparkdl_tpu.utils.synth import textured_image
        rng = np.random.default_rng(1)
        arr = textured_image(rng, 90, 120)
        blob = self._jpeg(arr, subsampling=2)
        packed, ok = native.decode_resize_pack_420([blob], 48, 64)
        assert ok.all()
        rgb, _ = native.decode_resize_pack([blob], 48, 64, 3)
        oracle = imageIO.rgbToYuv420(rgb[0])
        d = np.abs(packed[0].astype(int) - oracle.astype(int))
        assert d.mean() <= 2.0, d.mean()
        assert d.max() <= 32, d.max()

    def test_grayscale_source_neutral_chroma(self, built):
        if not native.has_jpeg():
            pytest.skip("libjpeg not available at build time")
        import io
        from PIL import Image
        g = np.linspace(0, 255, 32 * 32).reshape(32, 32).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(g, "L").save(buf, format="JPEG", quality=95)
        packed, ok = native.decode_resize_pack_420([buf.getvalue()],
                                                   16, 16)
        assert ok.all()
        y = packed[0][:16 * 16]
        chroma = packed[0][16 * 16:]
        np.testing.assert_array_equal(chroma,
                                      np.full(2 * 64, 128, np.uint8))
        assert y.std() > 10  # real luma content survived

    def test_odd_dims_rejected(self, built):
        if not native.has_jpeg():
            pytest.skip("libjpeg not available at build time")
        with pytest.raises(ValueError, match="even dims"):
            native.decode_resize_pack_420([b""], 299, 299)

    def test_corrupt_rows_marked(self, built):
        if not native.has_jpeg():
            pytest.skip("libjpeg not available at build time")
        rng = np.random.default_rng(2)
        good = self._jpeg(
            rng.integers(0, 255, (20, 20, 3), dtype=np.uint8),
            subsampling=2)
        packed, ok = native.decode_resize_pack_420(
            [good, b"\xff\xd8\xffnope"], 10, 10)
        assert list(ok) == [True, False]
        assert packed[1].max() == 0

    def test_packed_reader_yuv420(self, built, tmp_path):
        """readImagesPacked(packedFormat='yuv420') ships h*w*3/2-byte
        rows whose host-side reconstruction stays within chroma-
        interpolation tolerance of the RGB reader's rows."""
        if not native.has_jpeg():
            pytest.skip("libjpeg not available at build time")
        from PIL import Image
        from sparkdl_tpu.utils.synth import textured_image
        rng = np.random.default_rng(3)
        for i in range(4):
            Image.fromarray(textured_image(rng, 60, 80), "RGB").save(
                tmp_path / f"t{i}.jpg", quality=90)
        df = imageIO.readImagesPacked(str(tmp_path), (32, 40),
                                      packedFormat="yuv420",
                                      numPartitions=2)
        packed = df.tensor("image")
        assert packed.shape == (4, 32 * 40 * 3 // 2)
        rgb = imageIO.readImagesPacked(str(tmp_path), (32, 40),
                                       numPartitions=2).tensor("image")
        for i in range(4):
            # yuv420ToRgb replicates chroma (nearest) — the crude host
            # inverse; precise parity with the bilinear device inverse
            # is test_ops.py::TestYuv420DeviceOp's job
            rec = imageIO.yuv420ToRgb(packed[i], 32, 40)
            d = np.abs(rec.astype(int) - rgb[i].astype(int))
            assert d.mean() <= 7.0, d.mean()

    def test_packed_reader_yuv420_pil_fallback(self, built, tmp_path,
                                               monkeypatch):
        """With the native 420 packer unavailable the reader's PIL
        fallback (decode → resize → rgbToYuv420) produces rows close to
        the native ones (resampler difference only)."""
        if not native.has_jpeg():
            pytest.skip("libjpeg not available at build time")
        from PIL import Image
        smooth = np.repeat(np.repeat(
            np.linspace(0, 255, 24)[:, None, None], 24, axis=1),
            3, axis=2).astype(np.uint8)
        Image.fromarray(smooth, "RGB").save(tmp_path / "s.jpg",
                                            quality=90)
        native_rows = imageIO.readImagesPacked(
            str(tmp_path), (12, 12),
            packedFormat="yuv420").tensor("image")
        monkeypatch.setattr(native, "decode_resize_pack_420",
                            lambda *a, **k: None)
        pil_rows = imageIO.readImagesPacked(
            str(tmp_path), (12, 12),
            packedFormat="yuv420").tensor("image")
        assert pil_rows.shape == native_rows.shape
        assert np.abs(pil_rows.astype(int)
                      - native_rows.astype(int)).max() <= 6

    def test_reader_validates_format_args(self, built, tmp_path):
        with pytest.raises(ValueError, match="packedFormat"):
            imageIO.readImagesPacked(str(tmp_path), (16, 16),
                                     packedFormat="bgr")
        with pytest.raises(ValueError, match="nChannels=3"):
            imageIO.readImagesPacked(str(tmp_path), (16, 16),
                                     nChannels=1, packedFormat="yuv420")
        with pytest.raises(ValueError, match="even"):
            imageIO.readImagesPacked(str(tmp_path), (15, 16),
                                     packedFormat="yuv420")


class TestScaledDecode:
    """DCT-domain prescaled decode (shim v3): libjpeg decodes at the
    smallest M/8 covering the target, the bilinear step shrinks <2x.
    Pins (a) bit-parity with PIL's draft mode where the scale factors
    coincide, (b) closeness to the unscaled path on photo-like content,
    (c) exactness when no shrink is possible, and (d) geometry safety
    across scale factors and odd dims on the raw 4:2:0 path."""

    def _jpeg(self, arr, quality=90, subsampling=2):
        import io

        from PIL import Image
        buf = io.BytesIO()
        Image.fromarray(arr, "RGB").save(buf, format="JPEG",
                                         quality=quality,
                                         subsampling=subsampling)
        return buf.getvalue()

    def test_matches_pil_draft_exactly_at_power_of_two(self, built):
        """600² → 150² picks scale 1/4 — the same factor PIL's draft
        mode picks — and the remaining resize is the identity, so the
        two DCT prescales must agree bit-for-bit."""
        if not native.has_jpeg():
            pytest.skip("libjpeg not available at build time")
        import io

        from PIL import Image

        from sparkdl_tpu.utils.synth import textured_image
        rng = np.random.default_rng(11)
        blob = self._jpeg(textured_image(rng, 600, 600))
        got, ok = native.decode_resize_pack([blob], 150, 150, 3,
                                            scaled_decode=True)
        assert ok.all()
        im = Image.open(io.BytesIO(blob))
        im.draft("RGB", (150, 150))
        pil = np.asarray(im.convert("RGB"))
        assert pil.shape == (150, 150, 3)
        np.testing.assert_array_equal(got[0], pil)

    def test_scaled_close_to_unscaled_on_photos(self, built):
        if not native.has_jpeg():
            pytest.skip("libjpeg not available at build time")
        from sparkdl_tpu.utils.synth import textured_image
        rng = np.random.default_rng(12)
        blobs = [self._jpeg(textured_image(rng, 375, 500))
                 for _ in range(4)]
        for fn in (lambda s: native.decode_resize_pack(
                       blobs, 150, 150, 3, scaled_decode=s)[0],
                   lambda s: native.decode_resize_pack_420(
                       blobs, 150, 150, scaled_decode=s)[0]):
            a = fn(False).astype(int)
            b = fn(True).astype(int)
            d = np.abs(a - b)
            assert d.mean() <= 4.0, d.mean()
            assert d.max() <= 48, d.max()

    def test_no_shrink_means_identical_output(self, built):
        """Upscale targets leave M=8 (no prescale): scaled and unscaled
        paths must agree exactly."""
        if not native.has_jpeg():
            pytest.skip("libjpeg not available at build time")
        from sparkdl_tpu.utils.synth import textured_image
        rng = np.random.default_rng(13)
        blob = self._jpeg(textured_image(rng, 40, 48))
        a, _ = native.decode_resize_pack([blob], 64, 64, 3,
                                         scaled_decode=False)
        b, ok = native.decode_resize_pack([blob], 64, 64, 3,
                                          scaled_decode=True)
        assert ok.all()
        np.testing.assert_array_equal(a, b)
        a4, _ = native.decode_resize_pack_420([blob], 64, 64,
                                              scaled_decode=False)
        b4, ok4 = native.decode_resize_pack_420([blob], 64, 64,
                                                scaled_decode=True)
        assert ok4.all()
        np.testing.assert_array_equal(a4, b4)

    @pytest.mark.parametrize("src_hw,dst", [
        ((375, 500), 150),   # 1/2 on the raw path
        ((375, 501), 150),   # odd width: iMCU edge handling
        ((1200, 1600), 150),  # 1/8: smallest scaled IDCT
        ((301, 400), 150),   # 1/2 engages just above the floor
                             # boundary (301 >= 2*150; Y lands at 151)
        ((299, 400), 150),   # just BELOW it (299 < 2*150): no
                             # prescale on the raw420 path, M=8
    ])
    def test_raw420_scaled_geometry(self, built, src_hw, dst):
        """The raw-420 prescale derives per-component strides/rows from
        comp_info (Y scales, stored chroma doesn't); every factor and
        odd-dim edge must produce valid planes close to the unscaled
        route's."""
        if not native.has_jpeg():
            pytest.skip("libjpeg not available at build time")
        from sparkdl_tpu.utils.synth import textured_image
        rng = np.random.default_rng(14)
        blob = self._jpeg(textured_image(rng, *src_hw))
        a, oka = native.decode_resize_pack_420([blob], dst, dst,
                                               scaled_decode=False)
        b, okb = native.decode_resize_pack_420([blob], dst, dst,
                                               scaled_decode=True)
        assert oka.all() and okb.all()
        d = np.abs(a.astype(int) - b.astype(int))
        assert d.mean() <= 4.0, (src_hw, d.mean())

    def test_gray_and_444_fallback_scaled(self, built):
        if not native.has_jpeg():
            pytest.skip("libjpeg not available at build time")
        import io

        from PIL import Image

        from sparkdl_tpu.utils.synth import textured_image
        rng = np.random.default_rng(15)
        # 4:4:4 source takes the RGB-decode fallback
        blob444 = self._jpeg(textured_image(rng, 200, 200),
                             subsampling=0)
        a, oka = native.decode_resize_pack_420([blob444], 64, 64,
                                               scaled_decode=True)
        assert oka.all()
        # grayscale source: scaled luma decode, neutral chroma
        g = np.clip(rng.normal(128, 40, (200, 200)), 0,
                    255).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(g, "L").save(buf, format="JPEG", quality=90)
        b, okb = native.decode_resize_pack_420([buf.getvalue()], 64, 64,
                                               scaled_decode=True)
        assert okb.all()
        chroma = b[0][64 * 64:]
        assert chroma.min() == chroma.max() == 128

    def test_scaled_reader_close_to_unscaled_reader(self, built,
                                                    tmp_path):
        """readImagesPacked's default (scaledDecode=True) stays within
        a few counts of the scaledDecode=False rows on photo content —
        the documented fidelity statement for the default."""
        if not native.has_jpeg():
            pytest.skip("libjpeg not available at build time")
        from PIL import Image

        from sparkdl_tpu.utils.synth import textured_image
        rng = np.random.default_rng(16)
        for i in range(3):
            Image.fromarray(textured_image(rng, 120, 160), "RGB").save(
                tmp_path / f"s{i}.jpg", quality=90)
        scaled = imageIO.readImagesPacked(
            str(tmp_path), (48, 64), numPartitions=2).tensor("image")
        unscaled = imageIO.readImagesPacked(
            str(tmp_path), (48, 64), numPartitions=2,
            scaledDecode=False).tensor("image")
        d = np.abs(scaled.astype(int) - unscaled.astype(int))
        assert d.mean() <= 4.0, d.mean()

    def test_pil_fallback_draft_matches_native_scaled(self, built,
                                                      tmp_path,
                                                      monkeypatch):
        """With the native packer unavailable, scaledDecode=True routes
        JPEG fallbacks through PIL's draft mode — the same pow2 DCT
        prescale — so no-toolchain hosts keep the semantics (and most
        of the speed) of the native scaled path."""
        if not native.has_jpeg():
            pytest.skip("libjpeg not available at build time")
        from PIL import Image

        from sparkdl_tpu.utils.synth import textured_image
        rng = np.random.default_rng(17)
        for i in range(3):
            Image.fromarray(textured_image(rng, 128, 128), "RGB").save(
                tmp_path / f"d{i}.jpg", quality=90)
        nat = imageIO.readImagesPacked(
            str(tmp_path), (32, 32), numPartitions=2).tensor("image")
        monkeypatch.setattr(native, "decode_resize_pack",
                            lambda *a, **k: None)
        pil = imageIO.readImagesPacked(
            str(tmp_path), (32, 32), numPartitions=2).tensor("image")
        # both took the same 1/4 DCT prescale; only the final <2x
        # bilinear differs (shim vs PIL filter)
        d = np.abs(nat.astype(int) - pil.astype(int))
        assert d.mean() <= 4.0, d.mean()
        # scaledDecode=False falls back through the general full-res
        # route: decode + resizeImageArray per row — pin against that
        # exact oracle (packImageBatch would resize with the SHIM here,
        # a different resampler)
        unscaled_pil = imageIO.readImagesPacked(
            str(tmp_path), (32, 32), numPartitions=2,
            scaledDecode=False).tensor("image")
        gen = imageIO.readImages(str(tmp_path), numPartitions=2)
        oracle = np.stack([
            imageIO.resizeImageArray(
                imageIO.imageStructToArray(s), 32, 32, 3)
            for s in gen.collect().column("image").to_pylist()])
        np.testing.assert_array_equal(unscaled_pil, oracle)

    def test_engage_rule_matches_pil_draft_across_geometries(self,
                                                             built):
        """Property: the native prescale and PIL's draft engage on
        IDENTICAL (source, target) pairs — the floor rule src >= 2^k *
        dst on both axes (sparkdl_host.cpp::choose_scale_num was
        deliberately matched to PIL). Random geometries either side of
        the boundary, plus the exact 2*dst-1 band where a ceil rule
        would diverge."""
        if not native.has_jpeg():
            pytest.skip("libjpeg not available at build time")
        import io

        from PIL import Image

        from sparkdl_tpu.utils.synth import textured_image
        rng = np.random.default_rng(20)
        cases = [(int(h), int(w), int(t)) for h, w, t in zip(
            rng.integers(40, 700, 8), rng.integers(40, 700, 8),
            rng.integers(20, 200, 8))]
        cases += [(2 * 64 - 1, 400, 64),   # ceil-vs-floor band
                  (2 * 64, 400, 64),       # exactly at the boundary
                  (8 * 30, 8 * 30, 30)]    # deepest scale, exact
        for h, w, t in cases:
            blob_buf = io.BytesIO()
            Image.fromarray(textured_image(rng, h, w), "RGB").save(
                blob_buf, format="JPEG", quality=90, subsampling=2)
            blob = blob_buf.getvalue()
            te = t - t % 2 or 2  # even target for the 420 packer
            im = Image.open(io.BytesIO(blob))
            im.draft("RGB", (te, te))
            pil_engaged = im.size != (w, h)
            a, _ = native.decode_resize_pack([blob], te, te, 3,
                                             scaled_decode=False)
            b, ok = native.decode_resize_pack([blob], te, te, 3,
                                              scaled_decode=True)
            assert ok.all(), (h, w, te)
            native_engaged = not np.array_equal(a, b)
            assert native_engaged == pil_engaged, \
                (h, w, te, native_engaged, pil_engaged)

    def test_mixed_source_zoo_routes_every_row(self, built, tmp_path):
        """Robustness fuzz for the fused+fallback routing: a directory
        mixing baseline/progressive/4:4:4/grayscale JPEGs, a PNG, and
        a corrupt file must come back with every decodable row present
        (in both packed formats, scaled and not) and the corrupt row
        dropped — no silent zero-tensors, no misrouted rows."""
        if not native.has_jpeg():
            pytest.skip("libjpeg not available at build time")
        from PIL import Image

        from sparkdl_tpu.utils.synth import textured_image
        rng = np.random.default_rng(21)
        mk = lambda: textured_image(rng, 40, 48)
        Image.fromarray(mk(), "RGB").save(tmp_path / "a_base.jpg",
                                          quality=90, subsampling=2)
        Image.fromarray(mk(), "RGB").save(tmp_path / "b_prog.jpg",
                                          quality=90, subsampling=2,
                                          progressive=True)
        Image.fromarray(mk(), "RGB").save(tmp_path / "c_444.jpg",
                                          quality=92, subsampling=0)
        Image.fromarray(mk()[:, :, 0], "L").save(tmp_path / "d_gray.jpg",
                                                 quality=90)
        Image.fromarray(mk(), "RGB").save(tmp_path / "e_png.png")
        (tmp_path / "f_corrupt.jpg").write_bytes(b"\xff\xd8\xff\x00junk")

        for fmt in ("rgb", "yuv420"):
            for scaled in (True, False):
                df = imageIO.readImagesPacked(
                    str(tmp_path), (16, 16), numPartitions=2,
                    packedFormat=fmt, scaledDecode=scaled,
                    dropImageFailures=False)
                rows = df.collect_rows()
                ok = {r["filePath"].rsplit("/", 1)[-1]: r["imageOk"]
                      for r in rows}
                assert len(rows) == 6, (fmt, scaled, len(rows))
                expect = {"a_base.jpg": True, "b_prog.jpg": True,
                          "c_444.jpg": True, "d_gray.jpg": True,
                          "e_png.png": True, "f_corrupt.jpg": False}
                assert ok == expect, (fmt, scaled, ok)
                # decoded rows carry real data, not zeroed slots
                for r in rows:
                    if r["imageOk"]:
                        assert np.asarray(r["image"]).max() > 0, \
                            (fmt, scaled, r["filePath"])
