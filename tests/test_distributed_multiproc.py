"""Two-process ``jax.distributed`` test (VERDICT r1 missing #3).

The reference delegated inter-host behavior to Spark and never tested it
beyond local-mode; this build owns its DCN layer, so multi-process is
exercised for real: two coordinator-joined CPU processes with 4 virtual
devices each form one 8-device global mesh, run a cross-process
collective, and shard one logical DataFrame's partitions disjointly
(reference role: SURVEY §2.5 Spark RPC between hosts).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "_distmp_worker.py")
NUM_PARTITIONS = 5


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _clean_env() -> dict:
    """Strip the axon TPU-tunnel sitecustomize and device overrides so
    the workers get a plain multi-process CPU runtime (shared helper —
    the same sanitization the driver's multichip dryrun uses)."""
    from sparkdl_tpu.utils.hostenv import sanitized_cpu_env
    return sanitized_cpu_env(pythonpath=REPO_ROOT, n_devices=4)


@pytest.fixture(scope="module")
def worker_results():
    port = _free_port()
    env = _clean_env()
    procs = [subprocess.Popen(
        [sys.executable, WORKER, str(i), str(port), str(NUM_PARTITIONS)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env, cwd=REPO_ROOT) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
            assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    results = []
    for out in outs:
        lines = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert lines, f"no RESULT line in worker output:\n{out[-3000:]}"
        results.append(json.loads(lines[0][len("RESULT "):]))
    return sorted(results, key=lambda r: r["pid"])


def test_global_runtime_topology(worker_results):
    for r in worker_results:
        assert r["process_count"] == 2
        assert r["local_devices"] == 4
        assert r["global_devices"] == 8


def test_cross_process_collective(worker_results):
    # process 0 contributes 0+1+2+3, process 1 contributes 10+11+12+13;
    # both observe the same global sum — proof the psum crossed processes.
    for r in worker_results:
        assert r["psum_total"] == pytest.approx(52.0)


def test_host_shard_indices_disjoint_covering(worker_results):
    a, b = (set(r["shard_indices"]) for r in worker_results)
    assert a.isdisjoint(b)
    assert a | b == set(range(NUM_PARTITIONS))


@pytest.fixture(scope="module", params=[4, 3, "resume"],
                ids=["even-shards", "uneven-shards", "ckpt-resume"])
def streaming_fit_results(request, tmp_path_factory):
    """2-process multi-host STREAMING estimator fit over shared images:
    each host decodes only its shard; gradient sync crosses hosts.
    With 3 partitions over 2 hosts the shards are UNEVEN, so the
    smaller host must cycle its shard to meet the global step quota —
    the collective-alignment path."""
    import keras
    import numpy as np
    from PIL import Image

    resume = request.param == "resume"
    num_partitions = 4 if resume else request.param
    d = tmp_path_factory.mktemp("mhimgs")
    rng = np.random.default_rng(9)
    for i in range(16):
        base = 40 if i % 2 == 0 else 210
        arr = np.clip(rng.normal(base, 15, (8, 8, 3)), 0, 255) \
            .astype(np.uint8)
        Image.fromarray(arr, "RGB").save(d / f"i_{i}.png")

    keras.utils.set_random_seed(7)
    m = keras.Sequential([
        keras.layers.Input((8, 8, 3)),
        keras.layers.Flatten(),
        keras.layers.Dense(2, activation="softmax")])
    model_file = str(d / "m.keras")
    m.save(model_file)

    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_distmp_train_worker.py")
    port = _free_port()
    env = _clean_env()
    argv = [str(port), str(d), model_file, str(num_partitions)]
    if resume:
        argv.append(str(tmp_path_factory.mktemp("mhckpt")))
    procs = [subprocess.Popen(
        [sys.executable, worker, str(i)] + argv,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env, cwd=REPO_ROOT) for i in range(2)]
    results = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
            line = [l for l in out.splitlines()
                    if l.startswith("RESULT ")][0]
            results.append(json.loads(line[len("RESULT "):]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return num_partitions, sorted(results, key=lambda r: r["pid"])


def test_multihost_streaming_fit_identical_models(streaming_fit_results):
    num_partitions, (a, b) = streaming_fit_results
    # round-robin shard sizes (uneven when partitions don't divide)
    assert a["local_partitions"] == (num_partitions + 1) // 2
    assert b["local_partitions"] == num_partitions // 2
    # replicated state stayed in lockstep: same loss history, same
    # final weights on both hosts
    assert len(a["history"]) == 2
    assert a["history"] == pytest.approx(b["history"], rel=1e-6)
    assert np.isfinite(a["weight_digest"])
    assert a["weight_digest"] == pytest.approx(b["weight_digest"],
                                               rel=1e-6)


def test_multihost_cache_decoded_matches_uncached(streaming_fit_results):
    """cacheDecoded multi-host: each host spills only its shard and
    later epochs stream the per-host cache — the replicated state must
    end exactly where the uncached fit ends, on every host."""
    _, results = streaming_fit_results
    a, b = results
    if "cached_history" not in a:
        pytest.skip("cached scenario runs in the non-ckpt params")
    for r in results:
        assert r["cached_history"] == pytest.approx(r["history"],
                                                    rel=1e-6)
        assert r["cached_digest"] == pytest.approx(r["weight_digest"],
                                                   rel=1e-6)
    assert a["cached_digest"] == pytest.approx(b["cached_digest"],
                                               rel=1e-6)


def test_multihost_checkpoint_resume(streaming_fit_results):
    """Interrupted multi-host streaming training (1 epoch saved, budget
    extended to 2) must resume from the per-host checkpoints — resume
    step agreed over DCN — and reproduce the uninterrupted 2-epoch run
    exactly, with identical state on every host."""
    _, results = streaming_fit_results
    a, b = results
    if "resumed_history" not in a:
        pytest.skip("checkpoint scenario runs in the ckpt-resume param")
    for r in results:
        # a silent from-scratch retrain reproduces identical
        # history/weights here (fully deterministic seeds), so the
        # restore itself must be asserted: resumedFrom distinguishes it
        assert r["short_resumed_from"] == 0
        assert r["resumed_from"] == 1
        assert len(r["short_history"]) == 1
        assert len(r["resumed_history"]) == 2
        # epoch 0 was NOT retrained: its loss is the restored history
        assert r["resumed_history"][0] == pytest.approx(
            r["short_history"][0], rel=1e-6)
        # the resumed run ends exactly where the uninterrupted run does
        assert r["resumed_history"] == pytest.approx(r["history"],
                                                     rel=1e-6)
        assert r["resumed_digest"] == pytest.approx(r["weight_digest"],
                                                    rel=1e-6)
    assert a["resumed_digest"] == pytest.approx(b["resumed_digest"],
                                                rel=1e-6)


def test_global_mesh_train_step(worker_results):
    """One DP train step over the pod-wide mesh: the gradient all-reduce
    crossed processes, so both report the identical finite loss."""
    a, b = (r["train_loss"] for r in worker_results)
    assert np.isfinite(a)
    assert a == pytest.approx(b, rel=1e-6)


def test_host_shard_dataframe_partitions_rows(worker_results):
    n_rows = 4 * NUM_PARTITIONS - 1
    a, b = (set(r["rows"]) for r in worker_results)
    assert a and b
    assert a.isdisjoint(b)
    assert a | b == set(range(n_rows))


def test_multihost_dp_inference_matches_single_process(worker_results):
    """Multi-host DP inference (SURVEY §2.4's core strategy at the
    inter-host level): each host featurizes only its shard on its local
    mesh; the union must cover every row exactly once and match a
    single-process run of the same frame bit-for-bit (TestNet's seeded
    params are identical everywhere)."""
    import _distmp_worker as worker

    a, b = worker_results
    got = sorted(tuple(p) for r in (a, b) for p in r["features"])
    xs = [x for x, _ in got]
    n_rows = 4 * NUM_PARTITIONS - 1
    assert xs == list(range(n_rows))  # disjoint, covering, no dupes

    ref = worker.featurize_rows(
        worker.build_image_frame(n_rows, NUM_PARTITIONS))
    for (x, s), (rx, rs) in zip(got, ref):
        assert x == rx
        assert s == pytest.approx(rs, rel=1e-5)
