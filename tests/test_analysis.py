"""sparkdl-lint (sparkdl_tpu.analysis) + runtime sanitizer tests.

Per rule: a positive fixture (deliberately broken code trips it), a
negative fixture (idiomatic clean code passes), and a suppressed
fixture (inline annotation downgrades without hiding). Plus the
meta-test: the shipped package itself must analyze to ZERO unsuppressed
findings — the gate tools/ci.sh step [10/11] enforces, pinned here so a
regressing module fails the suite before it fails CI.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import sparkdl_tpu
from sparkdl_tpu.analysis import (
    DEFAULT_ALLOWLIST,
    analyze_paths,
    analyze_source,
    format_findings,
)

PKG_DIR = os.path.dirname(os.path.abspath(sparkdl_tpu.__file__))


def _hits(source, rule, path="fixture.py"):
    return [f for f in analyze_source(source, path)
            if f.rule == rule and not f.suppressed]


def _suppressed(source, rule, path="fixture.py"):
    return [f for f in analyze_source(source, path)
            if f.rule == rule and f.suppressed]


# ---------------------------------------------------------------------------
# H1 — implicit host transfers


class TestH1Transfers:
    def test_device_get_trips(self):
        hits = _hits("import jax\n"
                     "def ship(res):\n"
                     "    return jax.device_get(res)\n", "H1")
        assert len(hits) == 1
        assert hits[0].line == 3
        assert "device_get" in hits[0].message
        assert hits[0].qualname == "ship"

    def test_block_until_ready_trips(self):
        hits = _hits("def wait(arr):\n"
                     "    arr.block_until_ready()\n", "H1")
        assert len(hits) == 1

    def test_np_asarray_on_jnp_call_trips(self):
        hits = _hits("import numpy as np\n"
                     "import jax.numpy as jnp\n"
                     "def f(x):\n"
                     "    return np.asarray(jnp.dot(x, x))\n", "H1")
        assert len(hits) == 1

    def test_np_asarray_on_host_value_clean(self):
        assert _hits("import numpy as np\n"
                     "def f(rows):\n"
                     "    return np.asarray(rows)\n", "H1") == []

    def test_trailing_suppression(self):
        src = ("import jax\n"
               "def drain(res):\n"
               "    return jax.device_get(res)"
               "  # sparkdl-lint: allow[H1] -- test drain\n")
        assert _hits(src, "H1") == []
        sup = _suppressed(src, "H1")
        assert len(sup) == 1
        assert "test drain" in sup[0].suppression

    def test_standalone_suppression_covers_next_line(self):
        src = ("import jax\n"
               "def drain(res):\n"
               "    # sparkdl-lint: allow[H1] -- standalone note\n"
               "    return jax.device_get(res)\n")
        assert _hits(src, "H1") == []
        assert len(_suppressed(src, "H1")) == 1

    def test_wrong_rule_suppression_does_not_apply(self):
        src = ("import jax\n"
               "def drain(res):\n"
               "    return jax.device_get(res)"
               "  # sparkdl-lint: allow[H2] -- wrong rule\n")
        assert len(_hits(src, "H1")) == 1

    def test_allowlist_scopes_by_qualname(self):
        src = ("import jax\n"
               "def timed_device_get(value):\n"
               "    return jax.device_get(value)\n"
               "def other(res):\n"
               "    return jax.device_get(res)\n")
        found = analyze_source(
            src, "sparkdl_tpu/obs/trace.py",
            allowlist=DEFAULT_ALLOWLIST)
        by_qual = {f.qualname: f.suppressed for f in found
                   if f.rule == "H1"}
        assert by_qual["timed_device_get"] is True
        assert by_qual["other"] is False


# ---------------------------------------------------------------------------
# H2 — jit/retrace hazards


class TestH2Retrace:
    def test_time_call_in_jitted_decorator(self):
        hits = _hits("import jax, time\n"
                     "@jax.jit\n"
                     "def step(x):\n"
                     "    t = time.perf_counter()\n"
                     "    return x * t\n", "H2")
        assert len(hits) == 1
        assert "trace" in hits[0].message.lower()

    def test_print_in_jit_call_form_named_fn(self):
        hits = _hits("import jax\n"
                     "def step(x):\n"
                     "    print(x)\n"
                     "    return x\n"
                     "jitted = jax.jit(step)\n", "H2")
        assert len(hits) == 1

    def test_np_random_in_partial_jit(self):
        hits = _hits("import jax\n"
                     "import numpy as np\n"
                     "from functools import partial\n"
                     "@partial(jax.jit, donate_argnums=(0,))\n"
                     "def step(x):\n"
                     "    return x + np.random.rand()\n", "H2")
        assert len(hits) == 1

    def test_jax_random_is_clean(self):
        assert _hits("import jax\n"
                     "@jax.jit\n"
                     "def step(key, x):\n"
                     "    return x + jax.random.normal(key, x.shape)\n",
                     "H2") == []

    def test_unjitted_time_is_clean(self):
        assert _hits("import time\n"
                     "def outer():\n"
                     "    return time.perf_counter()\n", "H2") == []

    def test_unhashable_static_argnums(self):
        hits = _hits("import jax\n"
                     "def f(x, n):\n"
                     "    return x\n"
                     "jitted = jax.jit(f, static_argnums=[1])\n", "H2")
        assert len(hits) == 1
        assert "static" in hits[0].message

    def test_tuple_static_argnums_clean(self):
        assert _hits("import jax\n"
                     "def f(x, n):\n"
                     "    return x\n"
                     "jitted = jax.jit(f, static_argnums=(1,))\n",
                     "H2") == []

    def test_suppressed(self):
        src = ("import jax, time\n"
               "@jax.jit\n"
               "def step(x):\n"
               "    t = time.time()"
               "  # sparkdl-lint: allow[H2] -- trace-time stamp wanted\n"
               "    return x * t\n")
        assert _hits(src, "H2") == []
        assert len(_suppressed(src, "H2")) == 1


# ---------------------------------------------------------------------------
# H3 — concurrency discipline


class TestH3Concurrency:
    def test_lock_without_getstate_trips(self):
        hits = _hits("import threading\n"
                     "class Runner:\n"
                     "    def __init__(self):\n"
                     "        self._lock = threading.Lock()\n", "H3")
        assert len(hits) == 1
        assert "__getstate__" in hits[0].message

    def test_dataclass_field_lock_trips(self):
        hits = _hits("import threading\n"
                     "from dataclasses import dataclass, field\n"
                     "@dataclass\n"
                     "class Metrics:\n"
                     "    rows: int = 0\n"
                     "    _lock: threading.Lock = field(\n"
                     "        default_factory=threading.Lock)\n", "H3")
        assert len(hits) == 1

    def test_lock_with_getstate_clean(self):
        assert _hits("import threading\n"
                     "class Runner:\n"
                     "    def __init__(self):\n"
                     "        self._lock = threading.Lock()\n"
                     "    def __getstate__(self):\n"
                     "        s = self.__dict__.copy()\n"
                     "        del s['_lock']\n"
                     "        return s\n", "H3") == []

    def test_class_body_lock_exempt(self):
        # class attributes aren't pickled per-instance
        assert _hits("import threading\n"
                     "class Manifest:\n"
                     "    _lock = threading.Lock()\n", "H3") == []

    def test_guarded_write_outside_lock_trips(self):
        src = ("import threading\n"
               "class Metrics:\n"
               "    _lock_guards = ('rows',)\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self.rows = 0\n"          # __init__ exempt
               "    def __getstate__(self):\n"
               "        return {}\n"
               "    def add(self, n):\n"
               "        self.rows += n\n")        # unlocked write
        hits = _hits(src, "H3")
        assert len(hits) == 1
        assert hits[0].line == 10
        assert "_lock_guards" in hits[0].message

    def test_guarded_write_inside_lock_clean(self):
        assert _hits("import threading\n"
                     "class Metrics:\n"
                     "    _lock_guards = ('rows',)\n"
                     "    def __init__(self):\n"
                     "        self._lock = threading.Lock()\n"
                     "        self.rows = 0\n"
                     "    def __getstate__(self):\n"
                     "        return {}\n"
                     "    def add(self, n):\n"
                     "        with self._lock:\n"
                     "            self.rows += n\n", "H3") == []

    def test_suppressed(self):
        src = ("import threading\n"
               "# sparkdl-lint: allow[H3] -- never ships to executors\n"
               "class Local:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n")
        assert _hits(src, "H3") == []
        assert len(_suppressed(src, "H3")) == 1

    def test_condition_holding_server_class_trips(self):
        """A Condition wraps (or owns) a mutex — a server-shaped class
        keeping one per instance has exactly the raw-Lock pickle
        problem (the serve layer's RequestQueue shape), and must not
        slip past H3 because it never says the word Lock."""
        src = ("import threading\n"
               "class RequestQueue:\n"
               "    def __init__(self):\n"
               "        self._cond = threading.Condition()\n"
               "    def offer(self, req):\n"
               "        with self._cond:\n"
               "            self._cond.notify()\n")
        hits = _hits(src, "H3")
        assert len(hits) == 1
        assert "_cond" in hits[0].message

    def test_condition_with_getstate_clean(self):
        """The serve queue's own discipline: drop-and-recreate hooks
        make a Condition-holding class clean."""
        assert _hits("import threading\n"
                     "class RequestQueue:\n"
                     "    def __init__(self):\n"
                     "        self._lock = threading.Lock()\n"
                     "        self._cond = threading.Condition("
                     "self._lock)\n"
                     "    def __getstate__(self):\n"
                     "        s = self.__dict__.copy()\n"
                     "        del s['_lock']\n"
                     "        del s['_cond']\n"
                     "        return s\n", "H3") == []


# ---------------------------------------------------------------------------
# H4 — quiesce hygiene


class TestH4Quiesce:
    def test_bare_except_trips(self):
        hits = _hits("def load():\n"
                     "    try:\n"
                     "        return open('x')\n"
                     "    except:\n"
                     "        return None\n", "H4")
        assert len(hits) == 1
        assert "bare" in hits[0].message

    def test_swallow_in_finally_trips(self):
        hits = _hits("def run(pending):\n"
                     "    try:\n"
                     "        yield 1\n"
                     "    finally:\n"
                     "        for fut in pending:\n"
                     "            try:\n"
                     "                fut.result()\n"
                     "            except Exception:\n"
                     "                pass\n", "H4")
        assert len(hits) == 1
        assert "swallow" in hits[0].message

    def test_swallow_in_close_trips(self):
        hits = _hits("class Src:\n"
                     "    def close(self):\n"
                     "        try:\n"
                     "            self.f.close()\n"
                     "        except OSError:\n"
                     "            pass\n", "H4")
        assert len(hits) == 1

    def test_logged_handler_clean(self):
        assert _hits("import logging\n"
                     "def close(f):\n"
                     "    try:\n"
                     "        f.close()\n"
                     "    except OSError as e:\n"
                     "        logging.debug('close: %s', e)\n",
                     "H4") == []

    def test_swallow_outside_cleanup_clean(self):
        # a probe in a hot-path helper may legitimately swallow
        assert _hits("def probe(x):\n"
                     "    try:\n"
                     "        return x.copy_to_host_async()\n"
                     "    except NotImplementedError:\n"
                     "        pass\n", "H4") == []

    def test_suppressed(self):
        src = ("def close(f):\n"
               "    try:\n"
               "        f.close()\n"
               "    # sparkdl-lint: allow[H4] -- double-close is fine\n"
               "    except OSError:\n"
               "        pass\n")
        assert _hits(src, "H4") == []
        assert len(_suppressed(src, "H4")) == 1


# ---------------------------------------------------------------------------
# H5 — clock discipline in obs/serve


class TestH5Clock:
    """Span/latency math in sparkdl_tpu/obs/ and sparkdl_tpu/serve/
    must share the tracer's perf_counter clock — wall-clock reads there
    are flagged; the same code anywhere else is not (path-scoped)."""

    def test_time_time_in_obs_trips(self):
        hits = _hits("import time\n"
                     "def span_end():\n"
                     "    return time.time()\n", "H5",
                     path="sparkdl_tpu/obs/fixture.py")
        assert len(hits) == 1
        assert "perf_counter" in hits[0].message
        assert hits[0].qualname == "span_end"

    def test_datetime_now_in_serve_trips(self):
        hits = _hits("from datetime import datetime\n"
                     "def deadline():\n"
                     "    return datetime.now()\n", "H5",
                     path="sparkdl_tpu/serve/fixture.py")
        assert len(hits) == 1

    def test_datetime_module_form_trips(self):
        hits = _hits("import datetime\n"
                     "def stamp():\n"
                     "    return datetime.datetime.utcnow()\n", "H5",
                     path="sparkdl_tpu/obs/fixture.py")
        assert len(hits) == 1

    def test_perf_counter_is_clean(self):
        assert _hits("import time\n"
                     "def now():\n"
                     "    return time.perf_counter()\n", "H5",
                     path="sparkdl_tpu/obs/fixture.py") == []

    def test_wall_clock_outside_obs_serve_is_clean(self):
        src = ("import time\n"
               "def bench_stamp():\n"
               "    return time.time()\n")
        assert _hits(src, "H5", path="fixture.py") == []
        assert _hits(src, "H5",
                     path="sparkdl_tpu/runtime/fixture.py") == []

    def test_suppressed(self):
        src = ("import time\n"
               "def stamp():\n"
               "    return time.time()"
               "  # sparkdl-lint: allow[H5] -- artifact stamp\n")
        path = "sparkdl_tpu/obs/fixture.py"
        assert _hits(src, "H5", path=path) == []
        sup = _suppressed(src, "H5", path=path)
        assert len(sup) == 1
        assert "artifact stamp" in sup[0].suppression

    def test_meta_flight_bundle_stamp_is_suppressed_not_invisible(self):
        """The one legitimate wall-clock read in obs/ — the flight
        bundle's written_unix stamp — must APPEAR as a suppressed H5
        finding (the allowlist-not-skipped discipline, H1 precedent)."""
        found = analyze_paths([os.path.join(PKG_DIR, "obs")])
        h5 = [f for f in found if f.rule == "H5"]
        assert h5, "expected the flight.py bundle stamp to be flagged"
        assert all(f.suppressed for f in h5), format_findings(
            [f for f in h5 if not f.suppressed])
        assert any("flight.py" in f.path for f in h5)


# ---------------------------------------------------------------------------
# H6 — metric-name cardinality (request ids must never become keys)


class TestH6Cardinality:
    """A registry metric name interpolating a request id grows one
    eternal registry entry + Prometheus series per request — flagged
    anywhere; bounded dynamic names (configured knobs) and constant
    names are not."""

    def test_fstring_request_id_name_trips(self):
        hits = _hits("def publish(reg, request_id):\n"
                     "    reg.counter(\n"
                     "        f'serve.req.{request_id}.rows').add()\n",
                     "H6")
        assert len(hits) == 1
        assert "cardinality" in hits[0].message
        assert hits[0].qualname == "publish"

    def test_concat_and_attribute_forms_trip(self):
        src = ("def publish(reg, req):\n"
               "    reg.gauge('serve.' + req.rid).set(1)\n"
               "    reg.reservoir('lat.' + req.request_id)\n")
        hits = _hits(src, "H6")
        assert len(hits) == 2

    def test_format_call_trips(self):
        hits = _hits("def publish(reg, rid):\n"
                     "    reg.gauge('serve.{}.depth'.format(rid))\n",
                     "H6")
        assert len(hits) == 1

    def test_keyword_name_form_trips(self):
        # the name= kwarg spelling is just as legal a call form — it
        # must not be a loophole
        hits = _hits("def publish(reg, request_id):\n"
                     "    reg.counter(\n"
                     "        name=f'req.{request_id}.rows').add()\n",
                     "H6")
        assert len(hits) == 1

    def test_constant_and_bounded_dynamic_names_are_clean(self):
        # constant names, and dynamic names over bounded key sets (the
        # autotune knob-gauge idiom) must NOT trip — the rule is about
        # request-shaped identifiers, not dynamism per se
        src = ("def publish(reg, target, knob):\n"
               "    reg.counter('obs.request_log.dropped').add()\n"
               "    reg.gauge(f'autotune.knob.{target}.{knob}')\n")
        assert _hits(src, "H6") == []

    def test_request_id_outside_metric_name_is_clean(self):
        # ids in exemplars / span args / log records are exactly where
        # they belong — only metric NAMES are the hazard
        src = ("def observe(res, rid, lat):\n"
               "    res.observe(lat, exemplar={'request_id': rid})\n")
        assert _hits(src, "H6") == []

    def test_suppressed_with_justification(self):
        """The worked inline-suppression fixture: a variable that only
        SOUNDS request-shaped but draws from a bounded set suppresses
        with the reason the key set is bounded."""
        src = ("def count_findings(reg, rid):\n"
               "    # rid here is a LINT RULE id (H1..H6), six values\n"
               "    reg.counter(f'lint.{rid}.findings').add()"
               "  # sparkdl-lint: allow[H6] -- rid is a lint rule id "
               "(H1..H6, a bounded set), not a request id\n")
        assert _hits(src, "H6") == []
        sup = _suppressed(src, "H6")
        assert len(sup) == 1
        assert "bounded set" in sup[0].suppression

    def test_meta_obs_and_serve_are_h6_clean(self):
        """The layers that actually handle request ids ship H6-clean:
        ids flow through the RequestLog/exemplars/span args, never
        into registry keys."""
        found = analyze_paths([os.path.join(PKG_DIR, "obs"),
                               os.path.join(PKG_DIR, "serve")])
        h6 = [f for f in found if f.rule == "H6" and not f.suppressed]
        assert h6 == [], format_findings(h6)


# ---------------------------------------------------------------------------
# walker / CLI / formatter


class TestHarness:
    def test_syntax_error_reports_parse_finding(self):
        found = analyze_source("def broken(:\n", "bad.py")
        assert [f.rule for f in found] == ["PARSE"]
        assert not found[0].suppressed

    def test_format_text_has_path_line_col(self):
        found = analyze_source(
            "import jax\nx = jax.device_get(1)\n", "mod.py")
        text = format_findings(found)
        assert text.startswith("mod.py:2:")

    def test_format_json(self):
        found = analyze_source(
            "import jax\nx = jax.device_get(1)\n", "mod.py")
        d = json.loads(format_findings(found, fmt="json"))
        assert d["unsuppressed"] == 1
        assert d["findings"][0]["rule"] == "H1"

    def test_cli_exit_codes(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("import jax\nx = jax.device_get(1)\n")
        ok = tmp_path / "ok.py"
        ok.write_text("x = 1\n")
        env = {**os.environ,
               "PYTHONPATH": os.path.dirname(PKG_DIR)}
        r = subprocess.run(
            [sys.executable, "-m", "sparkdl_tpu.analysis", str(bad)],
            capture_output=True, text=True, env=env)
        assert r.returncode == 1
        assert "H1" in r.stdout
        r = subprocess.run(
            [sys.executable, "-m", "sparkdl_tpu.analysis", str(ok)],
            capture_output=True, text=True, env=env)
        assert r.returncode == 0

    def test_meta_package_is_clean(self):
        """THE gate: the shipped package analyzes to zero unsuppressed
        findings — every legitimate drain/swallow carries an inline
        justification or a scoped allowlist entry."""
        found = analyze_paths([PKG_DIR])
        unsuppressed = [f for f in found if not f.suppressed]
        assert unsuppressed == [], format_findings(unsuppressed)
        # and the suppressions that exist all carry a justification
        for f in found:
            if f.suppressed:
                assert f.suppression, f.render()

    def test_meta_serve_package_is_clean(self):
        """The serve layer is the newest lock-heavy subsystem — pin it
        by name (zero unsuppressed H1–H4) so a refactor that breaks its
        lock-pickle/quiesce discipline names the right package instead
        of hiding in the whole-tree gate above."""
        found = analyze_paths([os.path.join(PKG_DIR, "serve")])
        unsuppressed = [f for f in found if not f.suppressed]
        assert unsuppressed == [], format_findings(unsuppressed)

    def test_meta_autotune_package_is_clean(self):
        """The autotune layer writes to knobs other threads' hot loops
        read and keeps its own lock-guarded counters — pin it by name
        (zero unsuppressed H1–H5) so a controller refactor that breaks
        the lock/clock discipline names the right package instead of
        hiding in the whole-tree gate above."""
        found = analyze_paths([os.path.join(PKG_DIR, "autotune")])
        unsuppressed = [f for f in found if not f.suppressed]
        assert unsuppressed == [], format_findings(unsuppressed)

    def test_meta_known_drains_are_suppressed_not_invisible(self):
        """The drain path is allowlisted, not skipped: the single
        blessed device_get — obs/trace.py::timed_device_get, where
        SlabSink.write's drain moved so it could be spanned — must
        APPEAR as a suppressed finding."""
        found = analyze_paths([PKG_DIR])
        quals = {f.qualname for f in found
                 if f.rule == "H1" and f.suppressed}
        assert "timed_device_get" in quals


# ---------------------------------------------------------------------------
# the real findings the first analyzer run surfaced — pinned fixed


class TestFirstRunFindingsFixed:
    """H3 hits from the analyzer's first pass over the repo: three
    lock-holding classes with no pickle hooks. Spark ships stage
    closures with cloudpickle; each must survive the wire."""

    def test_sharded_runner_ships(self):
        import cloudpickle as cp
        from sparkdl_tpu.graph.function import ModelFunction
        from sparkdl_tpu.parallel.inference import ShardedBatchRunner
        mf = ModelFunction.fromSingle(lambda x: x * 2.0, None,
                                      input_shape=(3,))
        r = cp.loads(cp.dumps(ShardedBatchRunner(mf, batch_size=1)))
        n = r.preferred_chunk  # re-derived from local devices
        x = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
        np.testing.assert_allclose(r.run({"input": x})["output"], x * 2)

    def test_local_engine_ships(self):
        import cloudpickle as cp
        from sparkdl_tpu.data.engine import LocalEngine
        e = cp.loads(cp.dumps(LocalEngine(num_workers=2)))
        assert list(e.execute([], [])) == []
        e.shutdown()

    def test_stage_metrics_ships(self):
        import cloudpickle as cp
        from sparkdl_tpu.utils.profiling import StageMetrics
        m = StageMetrics()
        m.add("decode", 0.5, 10)
        m2 = cp.loads(cp.dumps(m))
        m2.add("decode", 0.5, 10)
        assert m2.as_dict()["decode"]["rows"] == 20


# ---------------------------------------------------------------------------
# runtime sanitizer


class TestSanitizer:
    def _model_and_input(self):
        from sparkdl_tpu.graph.function import ModelFunction
        mf = ModelFunction.fromSingle(lambda x: x * 2.0, None,
                                      input_shape=(3,))
        x = np.arange(24, dtype=np.float32).reshape(8, 3)
        return mf, x

    def test_aligned_run_sanitized_matches_unsanitized(self, monkeypatch):
        from sparkdl_tpu.runtime.runner import BatchRunner, RunnerMetrics
        mf, x = self._model_and_input()
        monkeypatch.delenv("SPARKDL_TPU_SANITIZE", raising=False)
        base = BatchRunner(mf, batch_size=4).run({"input": x})["output"]
        monkeypatch.setenv("SPARKDL_TPU_SANITIZE", "1")
        m = RunnerMetrics()
        out = BatchRunner(mf, batch_size=4, metrics=m).run(
            {"input": x})["output"]
        np.testing.assert_array_equal(base, out)
        # the aligned zero-copy contract holds under the guard
        assert m.bytes_staged == 0
        assert m.bytes_copied == 0

    @pytest.mark.parametrize("strategy", ["immediate", "deferred",
                                          "host_async", "prefetch"])
    def test_every_strategy_completes_sanitized(self, monkeypatch,
                                                strategy):
        from sparkdl_tpu.runtime.runner import BatchRunner
        mf, x = self._model_and_input()
        monkeypatch.setenv("SPARKDL_TPU_SANITIZE", "1")
        out = BatchRunner(mf, batch_size=4, strategy=strategy).run(
            {"input": x})["output"]
        np.testing.assert_allclose(out, x * 2)

    def test_tail_run_sanitized(self, monkeypatch):
        from sparkdl_tpu.runtime.runner import BatchRunner
        mf, x = self._model_and_input()
        monkeypatch.setenv("SPARKDL_TPU_SANITIZE", "1")
        out = BatchRunner(mf, batch_size=4).run(
            {"input": x[:7]})["output"]
        np.testing.assert_allclose(out, x[:7] * 2)

    def test_sharded_runner_sanitized(self, monkeypatch):
        import jax
        if len(jax.local_devices()) < 2:
            pytest.skip("needs >1 device (ci.sh forces 8 virtual)")
        from sparkdl_tpu.parallel.inference import ShardedBatchRunner
        mf, x = self._model_and_input()
        monkeypatch.setenv("SPARKDL_TPU_SANITIZE", "1")
        runner = ShardedBatchRunner(mf, batch_size=1)
        n = runner.preferred_chunk
        xs = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
        out = runner.run({"input": xs})["output"]
        np.testing.assert_allclose(out, xs * 2)

    def test_guard_arms_or_degrades_once(self, monkeypatch):
        from sparkdl_tpu.runtime import sanitize
        monkeypatch.setenv("SPARKDL_TPU_SANITIZE", "1")
        before = sanitize.armed_run_count()
        with sanitize.ship_guard() as armed:
            # jax>=0.4 has the API: the guard must actually arm
            assert armed is True
        # the armed counter is what bench.py's "sanitize" key reports —
        # env-on alone must not count (degraded guard ≠ enforced)
        assert sanitize.armed_run_count() == before + 1

    def test_guard_off_by_default(self, monkeypatch):
        from sparkdl_tpu.runtime import sanitize
        monkeypatch.delenv("SPARKDL_TPU_SANITIZE", raising=False)
        with sanitize.ship_guard() as armed:
            assert armed is False

    def test_degrades_with_single_warning_when_api_missing(
            self, monkeypatch, caplog):
        import jax
        from sparkdl_tpu.runtime import sanitize
        monkeypatch.setenv("SPARKDL_TPU_SANITIZE", "1")
        monkeypatch.setattr(sanitize, "_warned_no_guard", False)
        monkeypatch.delattr(jax, "transfer_guard_device_to_host")
        with caplog.at_level("WARNING",
                             logger="sparkdl_tpu.runtime.sanitize"):
            with sanitize.ship_guard() as armed:
                assert armed is False
            with sanitize.ship_guard() as armed:
                assert armed is False
        warnings = [r for r in caplog.records
                    if "unguarded" in r.getMessage()]
        assert len(warnings) == 1  # probe-and-degrade warns ONCE

    def test_guard_blocks_implicit_transfer_when_backend_supports(
            self, monkeypatch):
        """On CPU, arrays are host-resident and a d2h guard has nothing
        to catch — but the guard plumbing must still reject implicit
        transfers wherever jax reports them. Exercise the context
        directly: entering must not swallow real errors raised inside."""
        from sparkdl_tpu.runtime import sanitize
        monkeypatch.setenv("SPARKDL_TPU_SANITIZE", "1")
        with pytest.raises(RuntimeError, match="boom"):
            with sanitize.ship_guard():
                raise RuntimeError("boom")


# ---------------------------------------------------------------------------
# H13 — unbounded retry loops (serve/runtime/data/resilience paths)


class TestH13RetryLoops:
    PATH = "sparkdl_tpu/serve/fixture.py"

    def test_bare_while_true_swallow_flagged(self):
        src = ("def pump(q):\n"
               "    while True:\n"
               "        try:\n"
               "            q.dispatch()\n"
               "        except Exception:\n"
               "            pass\n")
        found = _hits(src, "H13", self.PATH)
        assert len(found) == 1
        assert "bounded and backed-off" in found[0].message

    def test_while_one_log_and_continue_flagged(self):
        src = ("import logging\n"
               "def pump(q):\n"
               "    while 1:\n"
               "        try:\n"
               "            q.dispatch()\n"
               "        except Exception as e:\n"
               "            logging.warning('retrying: %s', e)\n"
               "            continue\n")
        assert len(_hits(src, "H13", self.PATH)) == 1

    def test_handler_that_reraises_clean(self):
        # the RetryPolicy.call shape: the handler re-raises when the
        # grant is refused — bounded by construction
        src = ("def call(fn, policy):\n"
               "    attempt = 0\n"
               "    while True:\n"
               "        try:\n"
               "            return fn()\n"
               "        except Exception as exc:\n"
               "            attempt += 1\n"
               "            delay = policy.grant(attempt, exc)\n"
               "            if delay is None:\n"
               "                raise\n"
               "            policy.sleep(delay)\n")
        assert _hits(src, "H13", self.PATH) == []

    def test_handler_that_breaks_clean(self):
        src = ("def pump(q):\n"
               "    while True:\n"
               "        try:\n"
               "            q.dispatch()\n"
               "        except Exception:\n"
               "            break\n")
        assert _hits(src, "H13", self.PATH) == []

    def test_try_inside_nested_for_still_flagged(self):
        # a per-iteration-bounded inner loop does not bound the OUTER
        # while True: the swallow re-enters it forever
        src = ("def pump(q):\n"
               "    while True:\n"
               "        for item in q.batch():\n"
               "            try:\n"
               "                q.dispatch(item)\n"
               "            except Exception:\n"
               "                pass\n")
        assert len(_hits(src, "H13", self.PATH)) == 1

    def test_break_of_inner_loop_is_not_an_escape(self):
        # the break exits the handler's own for, not the while True —
        # the outer loop still spins forever on sustained failure
        src = ("def pump(q):\n"
               "    while True:\n"
               "        try:\n"
               "            q.dispatch()\n"
               "        except Exception:\n"
               "            for h in q.hooks:\n"
               "                break\n")
        assert len(_hits(src, "H13", self.PATH)) == 1

    def test_nested_unbounded_while_flagged_once_at_its_own_loop(self):
        src = ("def pump(q):\n"
               "    while True:\n"
               "        while True:\n"
               "            try:\n"
               "                q.dispatch()\n"
               "            except Exception:\n"
               "                pass\n"
               "        return\n")
        assert len(_hits(src, "H13", self.PATH)) == 1

    def test_bounded_for_loop_not_flagged(self):
        src = ("def pump(q):\n"
               "    for attempt in range(3):\n"
               "        try:\n"
               "            return q.dispatch()\n"
               "        except Exception:\n"
               "            pass\n")
        assert _hits(src, "H13", self.PATH) == []

    def test_nested_def_handlers_not_attributed_to_outer_loop(self):
        # a callback defined inside the loop owns its own handlers
        src = ("def pump(q):\n"
               "    while True:\n"
               "        def cb():\n"
               "            try:\n"
               "                q.poke()\n"
               "            except Exception:\n"
               "                pass\n"
               "        if not q.step(cb):\n"
               "            return\n")
        assert _hits(src, "H13", self.PATH) == []

    def test_out_of_scope_path_ignored(self):
        src = ("def pump(q):\n"
               "    while True:\n"
               "        try:\n"
               "            q.dispatch()\n"
               "        except Exception:\n"
               "            pass\n")
        assert _hits(src, "H13", "sparkdl_tpu/models/fixture.py") == []

    def test_suppressed_with_justification(self):
        src = ("def pump(q):\n"
               "    while True:\n"
               "        try:\n"
               "            q.dispatch()\n"
               "        # sparkdl-lint: allow[H13] -- paced by q's blocking wait; exits via q.closed\n"
               "        except Exception:\n"
               "            q.note_failure()\n")
        assert _hits(src, "H13", self.PATH) == []
        assert len(_suppressed(src, "H13", self.PATH)) == 1

    def test_serve_loop_suppression_is_visible_not_invisible(self):
        """The package's one real H13 — the dispatcher's serve loop —
        must APPEAR as a suppressed finding with its justification."""
        found = analyze_paths(
            [os.path.join(PKG_DIR, "serve")], cache_path=None)
        h13 = [f for f in found if f.rule == "H13"]
        assert any(f.suppressed and "RetryPolicy" in f.suppression
                   for f in h13), [f.render() for f in h13]
        assert not any(not f.suppressed for f in h13)
