"""Multi-device tests on the 8-virtual-CPU-device mesh (conftest sets
XLA_FLAGS) — the SURVEY §4.1 substrate: single host, simulated chips.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.models.zoo import getKerasApplicationModel, getModelFunction
from sparkdl_tpu.parallel import (
    MeshSpec,
    ShardedBatchRunner,
    create_train_state,
    make_eval_step,
    make_mesh,
    make_train_step,
    param_shardings,
    shard_train_step,
)
from sparkdl_tpu.parallel.checkpoint import (
    latest_step,
    restore_checkpoint,
    save_checkpoint,
)


def test_mesh_shapes():
    mesh = make_mesh()
    assert mesh.shape["data"] == 8 and mesh.shape["model"] == 1
    mesh2 = make_mesh(MeshSpec(data=-1, model=2))
    assert mesh2.shape["data"] == 4 and mesh2.shape["model"] == 2
    with pytest.raises(ValueError):
        MeshSpec(data=3, model=2).resolve(8)


def test_collective_launch_lock_scoping():
    """Multi-device mesh programs can carry collectives, so concurrent
    in-process launchers must share ONE launch lock (interleaved
    per-device enqueues from two threads deadlock the all-reduce —
    the hang test_fit_multiple_parallel_trials used to hit); no mesh
    or a 1-device mesh needs no lock at all. Since the obs PR the
    multi-device case returns the instrumented wrapper around THE
    process lock (parallel/mesh.py::_CollectiveLaunch) — entering it
    must still hold the real lock."""
    from sparkdl_tpu.parallel import mesh as mesh_mod
    from sparkdl_tpu.parallel.mesh import collective_launch

    multi = collective_launch(make_mesh())
    # one process-wide instrumented lock, not one per call
    assert multi is mesh_mod._COLLECTIVE_LAUNCH
    assert collective_launch(make_mesh()) is multi
    single = collective_launch(
        make_mesh(devices=jax.devices()[:1]))
    assert single is not multi
    none = collective_launch(None)
    with none:
        # the 1-device/no-mesh paths never touch the launch lock
        assert not mesh_mod._COLLECTIVE_LAUNCH_LOCK.locked()
    with single:
        assert not mesh_mod._COLLECTIVE_LAUNCH_LOCK.locked()
    # entering the wrapper takes the REAL process lock; it is
    # reusable across steps and releases on exit
    with multi:
        assert mesh_mod._COLLECTIVE_LAUNCH_LOCK.locked()
    assert not mesh_mod._COLLECTIVE_LAUNCH_LOCK.locked()
    with multi:
        assert mesh_mod._COLLECTIVE_LAUNCH_LOCK.locked()
    assert not mesh_mod._COLLECTIVE_LAUNCH_LOCK.locked()


def test_sharded_runner_pickle_keeps_model_axis():
    """Shipping a model-parallel runner must preserve the parallelism
    LAYOUT: devices are re-derived on the receiving host, but the
    model-axis width travels (a silent collapse to pure DP would
    recompile the program against the wrong sharding)."""
    import cloudpickle as cp

    from sparkdl_tpu.graph.function import ModelFunction

    mf = ModelFunction.fromSingle(lambda x: x * 2.0, None,
                                  input_shape=(4,))
    r = ShardedBatchRunner(mf, mesh=make_mesh(MeshSpec(data=-1, model=2)),
                           batch_size=1)
    r2 = cp.loads(cp.dumps(r))
    assert r2.mesh.shape["model"] == 2
    assert r2.mesh.shape["data"] == 4
    n = r2.preferred_chunk
    x = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
    np.testing.assert_allclose(r2.run({"input": x})["output"], x * 2)


def test_param_shardings_model_axis():
    mesh = make_mesh(MeshSpec(data=-1, model=2))
    params = {"w": jnp.zeros((6, 4)), "b": jnp.zeros((3,)),
              "scalar": jnp.zeros(())}
    sh = param_shardings(params, mesh)
    assert sh["w"].spec == jax.sharding.PartitionSpec("model", None)
    assert sh["b"].spec == jax.sharding.PartitionSpec()
    assert sh["scalar"].spec == jax.sharding.PartitionSpec()


class TestShardedInference:

    def test_matches_single_device(self):
        mesh = make_mesh()
        mf = getModelFunction("TestNet", featurize=True)
        runner = ShardedBatchRunner(mf, mesh, batch_size=4)
        rng = np.random.default_rng(0)
        x = rng.integers(0, 255, size=(70, 32, 32, 3), dtype=np.uint8)
        out = runner.run({"image": x})["features"]
        assert out.shape == (70, 16)
        ref = np.asarray(mf({"image": x[:70]})["features"])
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)
        assert runner.metrics.rows == 70

    def test_rejects_host_backend(self):
        mf = ModelFunction(lambda p, d: d, backend="host",
                           input_signature={"x": ((2,), np.float32)})
        with pytest.raises(ValueError, match="jax backend"):
            ShardedBatchRunner(mf)

    def test_strategy_validated_like_batch_runner(self):
        """The sharded runner shares BatchRunner's strategy contract:
        typos raise, and the choice is introspectable."""
        mf = getModelFunction("TestNet", featurize=True)
        with pytest.raises(ValueError, match="immediate"):
            ShardedBatchRunner(mf, strategy="immedaite")
        r = ShardedBatchRunner(mf, strategy="immediate")
        assert r.strategy == "immediate" and r.max_inflight == 0

    def test_prefetch_matches_and_aligned_is_zero_copy(self):
        """The prefetch strategy (sharded device_put of chunk i+1
        during chunk i) is a pure dispatch policy: exact parity with
        the unsharded reference for aligned, tail-padded, and N=0
        inputs — and a batch-ALIGNED contiguous run reports ZERO bytes
        staged/copied (the read-only input pins that nothing writes
        it), while the tail stages exactly the tail rows."""
        mesh = make_mesh()
        mf = getModelFunction("TestNet", featurize=True)
        runner = ShardedBatchRunner(mf, mesh, batch_size=4,
                                    strategy="prefetch")
        gb = 4 * mesh.shape["data"]  # 32-row global batches
        rng = np.random.default_rng(6)

        x = rng.integers(0, 255, size=(2 * gb, 32, 32, 3),
                         dtype=np.uint8)
        x.setflags(write=False)
        out = runner.run({"image": x})["features"]
        ref = np.asarray(mf({"image": x})["features"])
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)
        assert runner.metrics.bytes_staged == 0
        assert runner.metrics.bytes_copied == 0

        y = rng.integers(0, 255, size=(2 * gb + 6, 32, 32, 3),
                         dtype=np.uint8)
        y.setflags(write=False)
        out = runner.run({"image": y})["features"]
        ref = np.asarray(mf({"image": y})["features"])
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)
        assert runner.metrics.bytes_staged == y[2 * gb:].nbytes
        assert runner.metrics.bytes_copied == 0

        empty = runner.run(
            {"image": np.zeros((0, 32, 32, 3), np.uint8)})
        assert empty["features"].shape[0] == 0

    def test_sharded_all_strategies_identical(self):
        """immediate / deferred / host_async / prefetch agree exactly
        through the sharded runner (slab-output parity pin)."""
        mesh = make_mesh()
        mf = getModelFunction("TestNet", featurize=True)
        rng = np.random.default_rng(8)
        x = rng.integers(0, 255, size=(70, 32, 32, 3), dtype=np.uint8)
        expected = None
        for strategy in ("immediate", "deferred", "host_async",
                         "prefetch"):
            r = ShardedBatchRunner(mf, mesh, batch_size=4,
                                   strategy=strategy)
            out = r.run({"image": x})["features"]
            assert out.shape == (70, 16), strategy
            if expected is None:
                expected = out
            else:
                np.testing.assert_array_equal(out, expected)


class TestDPTraining:

    def _setup(self, mesh):
        spec = getKerasApplicationModel("TestNet")
        module = spec.module_fn()
        x = jnp.zeros((1, 32, 32, 3), jnp.uint8)
        variables = module.init(jax.random.PRNGKey(0), spec.preprocess(x))
        state = create_train_state(module, variables,
                                   optax.sgd(1e-2, momentum=0.9))
        step = make_train_step(module, spec.preprocess, spec.num_classes)
        return spec, module, state, step

    def test_loss_decreases_and_stats_update(self):
        mesh = make_mesh()
        spec, module, state, step = self._setup(mesh)
        jitted, state = shard_train_step(step, mesh, state)
        rng = np.random.default_rng(1)
        batch = {
            "image": jnp.asarray(rng.integers(
                0, 255, size=(16, 32, 32, 3), dtype=np.uint8)),
            "label": jnp.asarray(rng.integers(0, 10, size=(16,))),
        }
        first = None
        for _ in range(8):
            state, metrics = jitted(state, batch)
            if first is None:
                first = float(metrics["loss"])
        assert float(metrics["loss"]) < first
        assert int(state.step) == 8

    def test_dp_matches_single_device_step(self):
        """One sharded DP step == the same step unsharded (grads psum
        over the data axis must be numerically equivalent)."""
        mesh = make_mesh()
        spec, module, state0, step = self._setup(mesh)
        rng = np.random.default_rng(2)
        batch = {
            "image": jnp.asarray(rng.integers(
                0, 255, size=(16, 32, 32, 3), dtype=np.uint8)),
            "label": jnp.asarray(rng.integers(0, 10, size=(16,))),
        }
        ref_state, ref_metrics = jax.jit(step)(state0, batch)

        jitted, sharded = shard_train_step(step, mesh, state0)
        new_state, metrics = jitted(sharded, batch)
        np.testing.assert_allclose(float(metrics["loss"]),
                                   float(ref_metrics["loss"]),
                                   rtol=1e-5)
        for a, b in zip(jax.tree.leaves(ref_state.params),
                        jax.tree.leaves(new_state.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_model_axis_sharding_compiles(self):
        mesh = make_mesh(MeshSpec(data=-1, model=2))
        spec, module, state, step = self._setup(mesh)
        jitted, state = shard_train_step(step, mesh, state,
                                         shard_model_axis=True)
        rng = np.random.default_rng(3)
        batch = {
            "image": jnp.asarray(rng.integers(
                0, 255, size=(8, 32, 32, 3), dtype=np.uint8)),
            "label": jnp.asarray(rng.integers(0, 10, size=(8,))),
        }
        state, metrics = jitted(state, batch)
        assert np.isfinite(float(metrics["loss"]))

    def test_eval_step(self):
        mesh = make_mesh()
        spec, module, state, _ = self._setup(mesh)
        ev = jax.jit(make_eval_step(module, spec.preprocess,
                                    spec.num_classes))
        rng = np.random.default_rng(4)
        batch = {
            "image": jnp.asarray(rng.integers(
                0, 255, size=(8, 32, 32, 3), dtype=np.uint8)),
            "label": jnp.asarray(rng.integers(0, 10, size=(8,))),
        }
        m = ev(state, batch)
        assert 0.0 <= float(m["accuracy"]) <= 1.0


class TestCheckpoint:

    def test_save_restore_roundtrip(self, tmp_path):
        spec = getKerasApplicationModel("TestNet")
        module = spec.module_fn()
        x = jnp.zeros((1, 32, 32, 3), jnp.uint8)
        variables = module.init(jax.random.PRNGKey(0), spec.preprocess(x))
        state = create_train_state(module, variables, optax.adam(1e-3))
        step = make_train_step(module, spec.preprocess, spec.num_classes)
        rng = np.random.default_rng(5)
        batch = {
            "image": jnp.asarray(rng.integers(
                0, 255, size=(4, 32, 32, 3), dtype=np.uint8)),
            "label": jnp.asarray(rng.integers(0, 10, size=(4,))),
        }
        state, _ = jax.jit(step)(state, batch)
        ckdir = str(tmp_path / "ck")
        save_checkpoint(ckdir, state, step=1)
        assert latest_step(ckdir) == 1

        fresh = create_train_state(module, variables, optax.adam(1e-3))
        restored = restore_checkpoint(ckdir, fresh)
        assert int(restored.step) == 1
        for a, b in zip(jax.tree.leaves(state.params),
                        jax.tree.leaves(restored.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))


class TestAgreeResumeStep:
    """Single-process simulation of the multi-host resume-step descent:
    a scripted agree function plays the global-min rounds of a 2-host
    cluster, asserting each host proposes the right values and both
    converge on max(intersection) with the same collective count."""

    @staticmethod
    def _simulate(hosts):
        """hosts: list of (local_best, available). Runs every host's
        agree_resume_step in lockstep with a real cross-host min."""
        from sparkdl_tpu.parallel.distributed import agree_resume_step

        proposals = [[] for _ in hosts]
        results = [None] * len(hosts)

        # threads: each host runs the real function; a barrier computes
        # the min per round
        import threading
        n = len(hosts)
        lock = threading.Condition()
        round_vals: dict = {}

        def agree_factory(i):
            my_round = [0]

            def agree(value):
                r = my_round[0]
                my_round[0] += 1
                with lock:
                    round_vals.setdefault(r, {})[i] = int(value)
                    lock.notify_all()
                    while len(round_vals[r]) < n:
                        lock.wait(timeout=10)
                    proposals[i].append(int(value))
                    return min(round_vals[r].values())
            return agree

        threads = []
        for i, (best, avail) in enumerate(hosts):
            def run(i=i, best=best, avail=avail):
                results[i] = agree_resume_step(best, avail,
                                               _agree=agree_factory(i))
            t = threading.Thread(target=run)
            threads.append(t)
            t.start()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive(), "agreement deadlocked"
        return results, proposals

    def test_diverged_views_find_common_step(self):
        # host A holds {1,3} (step-2 save failed), host B holds {1,2}
        # (crashed mid-save of 3): the newest COMMON step is 1
        results, proposals = self._simulate([(3, [1, 3]), (2, [1, 2])])
        assert results == [1, 1]
        # rounds: bests (3,2)->2; best<=2: (1,2)->1; best<=1: (1,1)->1
        assert proposals[0] == [3, 1, 1]
        assert proposals[1] == [2, 2, 1]

    def test_identical_views_resume_newest(self):
        results, _ = self._simulate([(4, [2, 3, 4]), (4, [2, 3, 4])])
        assert results == [4, 4]

    def test_one_host_empty_starts_fresh(self):
        results, _ = self._simulate([(3, [1, 2, 3]), (0, [])])
        assert results == [0, 0]

    def test_single_process_identity(self):
        from sparkdl_tpu.parallel.distributed import (
            agree_min,
            agree_resume_step,
        )
        assert agree_min(7) == 7  # process_count == 1 → identity
        assert agree_resume_step(5, [3, 5]) == 5
        assert agree_resume_step(0, []) == 0
