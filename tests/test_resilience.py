"""Engine retry + multi-host sharding + graph utils tests (SURVEY §5:
failure detection via task retry; §2.5 DCN host sharding; §2.1 tfx)."""

import threading

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.data.engine import LocalEngine
from sparkdl_tpu.data.frame import DataFrame, Source, Stage
from sparkdl_tpu.graph import utils as tfx
from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.parallel import (
    global_mesh,
    host_info,
    host_shard_dataframe,
    host_shard_indices,
    initialize,
)


def _batch(vals):
    return pa.RecordBatch.from_pydict({"x": pa.array(vals)})


class TestEngineRetry:
    def test_transient_failure_retried(self):
        engine = LocalEngine(num_workers=2, max_retries=2)
        fails = {"n": 0}
        lock = threading.Lock()

        def flaky_load():
            with lock:
                fails["n"] += 1
                if fails["n"] == 1:
                    raise IOError("transient read error")
            return _batch([1, 2, 3])

        sources = [Source(flaky_load, 3)]
        out = list(engine.execute(sources, []))
        assert out[0].num_rows == 3
        assert fails["n"] == 2  # one failure + one success

    def test_flaky_stage_retried(self):
        engine = LocalEngine(num_workers=2, max_retries=1)
        attempts = {"n": 0}
        lock = threading.Lock()

        def flaky_stage(batch):
            with lock:
                attempts["n"] += 1
                if attempts["n"] == 1:
                    raise IOError("decode read hiccup")
            return batch

        sources = [Source(lambda: _batch([1]), 1)]
        out = list(engine.execute(sources, [Stage(flaky_stage)]))
        assert out[0].num_rows == 1

    def test_permanent_failure_raises_after_attempts(self):
        engine = LocalEngine(num_workers=1, max_retries=2)
        calls = {"n": 0}

        def always_fails():
            calls["n"] += 1
            raise IOError("disk gone")

        sources = [Source(always_fails, 1)]
        with pytest.raises(IOError, match="disk gone"):
            list(engine.execute(sources, []))
        assert calls["n"] == 3

    def test_zero_retries(self):
        engine = LocalEngine(num_workers=1, max_retries=0)
        calls = {"n": 0}

        def fails():
            calls["n"] += 1
            raise IOError("nope")

        with pytest.raises(IOError, match="nope"):
            list(engine.execute([Source(fails, 1)], []))
        assert calls["n"] == 1

    def test_transient_device_error_retried(self):
        """A PJRT/jax runtime failure mid-partition (e.g. the tunnel
        connection dropping in this very env) must be retried like an IO
        error — the partition re-runs cleanly from its source (VERDICT
        r2 weak #6: the old retry set was OSError-only)."""
        from jax.errors import JaxRuntimeError

        engine = LocalEngine(num_workers=2, max_retries=2)
        attempts = {"n": 0}
        lock = threading.Lock()

        def flaky_device_stage(batch):
            with lock:
                attempts["n"] += 1
                if attempts["n"] == 1:
                    raise JaxRuntimeError(
                        "UNAVAILABLE: tunnel connection reset")
            return batch

        out = list(engine.execute(
            [Source(lambda: _batch([1, 2]), 2)],
            [Stage(flaky_device_stage, kind="device")]))
        assert out[0].num_rows == 2
        assert attempts["n"] == 2

    def test_deterministic_jax_status_not_retried(self):
        """A jax error whose status code means 'this will fail the same
        way again' (INVALID_ARGUMENT, a deterministic RESOURCE_EXHAUSTED
        allocation failure) must propagate on FIRST failure — re-running
        a decode-bearing partition 3x before the inevitable error would
        triple time-to-failure and mislabel it transient."""
        from jax.errors import JaxRuntimeError

        for status in ("INVALID_ARGUMENT: operand shapes",
                       "RESOURCE_EXHAUSTED: allocating 40G exceeds HBM",
                       # wrapping layers prefix context; the status
                       # token must still classify as deterministic
                       "Execution failed: INVALID_ARGUMENT: bad dims"):
            engine = LocalEngine(num_workers=1, max_retries=3)
            calls = {"n": 0}

            def stage(batch, status=status):
                calls["n"] += 1
                raise JaxRuntimeError(status)

            with pytest.raises(JaxRuntimeError):
                list(engine.execute([Source(lambda: _batch([1]), 1)],
                                    [Stage(stage, kind="device")]))
            assert calls["n"] == 1, status

    def test_classifier_tolerates_degenerate_messages(self):
        """Empty / whitespace-only jax error messages must classify
        (as non-deterministic), not crash the classifier and mask the
        original device error."""
        from jax.errors import JaxRuntimeError

        from sparkdl_tpu.data.engine import is_deterministic_jax_error

        for msg in ("", "\n", "   ", "\n\nINVALID_ARGUMENT: late"):
            assert is_deterministic_jax_error(JaxRuntimeError(msg)) \
                == ("INVALID_ARGUMENT" in msg)

    def test_custom_retryable_set(self):
        """retryable_exceptions is configurable; an exception outside
        the set propagates on first failure."""
        class Flaky(Exception):
            pass

        engine = LocalEngine(num_workers=1, max_retries=3,
                             retryable_exceptions=(Flaky,))
        calls = {"n": 0}

        def stage(batch):
            calls["n"] += 1
            if calls["n"] == 1:
                raise Flaky("once")
            return batch

        out = list(engine.execute([Source(lambda: _batch([1]), 1)],
                                  [Stage(stage)]))
        assert out[0].num_rows == 1 and calls["n"] == 2

        # OSError is now OUTSIDE the configured set → no retry
        calls2 = {"n": 0}

        def io_fails(batch):
            calls2["n"] += 1
            raise IOError("disk gone")

        with pytest.raises(IOError):
            list(engine.execute([Source(lambda: _batch([1]), 1)],
                                [Stage(io_fails)]))
        assert calls2["n"] == 1

    def test_deterministic_error_not_retried(self):
        engine = LocalEngine(num_workers=1, max_retries=3)
        calls = {"n": 0}

        def bad_stage(batch):
            calls["n"] += 1
            raise KeyError("column 'nope' not in batch")

        with pytest.raises(KeyError, match="nope"):
            list(engine.execute([Source(lambda: _batch([1]), 1)],
                                [Stage(bad_stage)]))
        assert calls["n"] == 1  # no pointless retries of user errors


class TestHostSharding:
    def test_single_process_owns_everything(self):
        initialize()  # no-op single process
        info = host_info()
        assert info.process_count == 1
        assert info.process_index == 0
        assert host_shard_indices(5) == [0, 1, 2, 3, 4]

    def test_initialize_attempts_join_with_explicit_args(self,
                                                         monkeypatch):
        """Explicit multi-process args must reach
        jax.distributed.initialize (regression: the old process_count
        guard initialized the backend itself, making real
        initialization unreachable)."""
        import jax
        calls = []
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda **kw: calls.append(kw))
        initialize(coordinator_address="10.0.0.1:1234",
                   num_processes=2, process_id=0)
        assert calls == [{"coordinator_address": "10.0.0.1:1234",
                          "num_processes": 2, "process_id": 0}]

    def test_initialize_auto_detect_env(self, monkeypatch):
        """A cluster env marker must trigger an initialize attempt even
        with no args (TPU pod auto-detection path; regression: the old
        all-None early return skipped it)."""
        import jax
        calls = []
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda **kw: calls.append(kw))
        monkeypatch.setenv("SLURM_JOB_ID", "12345")
        initialize()
        assert len(calls) == 1

    def test_initialize_plain_single_process_noop(self, monkeypatch):
        import jax
        calls = []
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda **kw: calls.append(kw))
        for v in ("JAX_COORDINATOR_ADDRESS", "MEGASCALE_COORDINATOR_ADDRESS",
                  "TPU_WORKER_HOSTNAMES", "SLURM_JOB_ID",
                  "OMPI_COMM_WORLD_SIZE"):
            monkeypatch.delenv(v, raising=False)
        initialize()
        assert calls == []

    def test_round_robin_explicit(self):
        assert host_shard_indices(10, process_index=0,
                                  process_count=4) == [0, 4, 8]
        assert host_shard_indices(10, process_index=3,
                                  process_count=4) == [3, 7]
        # every partition owned exactly once
        owned = sorted(sum((host_shard_indices(10, i, 4)
                            for i in range(4)), []))
        assert owned == list(range(10))

    def test_invalid_process(self):
        with pytest.raises(ValueError, match="invalid process"):
            host_shard_indices(4, process_index=4, process_count=4)

    def test_host_shard_dataframe_lazy(self):
        loaded = []

        def make(i):
            def _load():
                loaded.append(i)
                return _batch([i])
            return Source(_load, 1)

        df = DataFrame([make(i) for i in range(6)])
        mine = host_shard_dataframe(df, process_index=1, process_count=3)
        assert mine.num_partitions == 2
        rows = mine.collect_rows()
        assert [r["x"] for r in rows] == [1, 4]
        assert sorted(loaded) == [1, 4]  # other hosts' sources untouched

    def test_global_mesh_shape(self):
        mesh = global_mesh()
        assert mesh.devices.size == 8  # conftest's virtual CPU devices
        assert mesh.axis_names == ("data", "model")


class TestGraphUtils:
    def _mf(self):
        return ModelFunction.fromSingle(
            lambda x: x * 2.0, None, input_shape=(3,),
            input_name="inp", output_name="out", name="m")

    def test_validated_io(self):
        mf = self._mf()
        assert tfx.validated_input(mf, "inp") == "inp"
        assert tfx.validated_output(mf, "out") == "out"
        with pytest.raises(ValueError, match="not in model"):
            tfx.validated_input(mf, "bogus")
        with pytest.raises(ValueError, match="not in model"):
            tfx.validated_output(mf, "bogus")
        with pytest.raises(TypeError, match="ModelFunction"):
            tfx.validated_model("not a model")

    def test_shapes_and_names(self):
        mf = self._mf()
        assert tfx.get_input_shape(mf, "inp") == (3,)
        assert tfx.get_output_shape(mf, "out") == (3,)
        assert tfx.input_names(mf) == ["inp"]
        assert tfx.output_names(mf) == ["out"]

    def test_freeze_roundtrip(self):
        mf = self._mf()
        blob = tfx.strip_and_freeze(mf)
        assert isinstance(blob, bytes) and len(blob) > 0
        back = tfx.load_frozen(blob)
        x = np.arange(6, dtype=np.float32).reshape(2, 3)
        np.testing.assert_allclose(
            np.asarray(back({"inp": x})["out"]), x * 2.0)

    def test_fixed_batch_freeze_roundtrip(self):
        """A fixed-batch export must deserialize with working output
        names (regression: the lazy name probe ran the program with
        batch 1, which a fixed-batch export rejects)."""
        mf = self._mf()
        back = tfx.load_frozen(mf.export(batch_size=3))
        assert back.output_names == ["out"]
        # output_signature must come from the exported avals, not an
        # eval_shape probe (which would call the program with batch 1)
        shape, dtype = back.output_signature()["out"]
        assert shape == (3,) and np.dtype(dtype) == np.float32
        assert tfx.get_output_shape(back, "out") == (3,)
        x = np.arange(9, dtype=np.float32).reshape(3, 3)
        np.testing.assert_allclose(
            np.asarray(back({"inp": x})["out"]), x * 2.0)

    def test_select_outputs_prunes(self):
        def two_headed(x):
            return {"a": x + 1.0, "b": x * 3.0}

        mf = ModelFunction(
            lambda p, d: two_headed(d["inp"]), None,
            input_signature={"inp": ((3,), np.dtype(np.float32))},
            output_names=["a", "b"], name="two")
        pruned = tfx.select_outputs(mf, ["b"])
        assert pruned.output_names == ["b"]
        x = np.ones((2, 3), np.float32)
        out = pruned({"inp": x})
        assert set(out) == {"b"}
        np.testing.assert_allclose(np.asarray(out["b"]), x * 3.0)
        with pytest.raises(ValueError, match="not in model"):
            tfx.select_outputs(mf, ["bogus"])
        with pytest.raises(ValueError, match="at least one"):
            tfx.select_outputs(mf, [])

    def test_with_preprocessor_fuses(self):
        mf = self._mf()
        pre = tfx.with_preprocessor(
            mf, lambda ins: {"inp": ins["inp"] + 10.0})
        x = np.arange(6, dtype=np.float32).reshape(2, 3)
        np.testing.assert_allclose(
            np.asarray(pre({"inp": x})["out"]), (x + 10.0) * 2.0)
        # composed program still exports to StableHLO (deploy form)
        blob = tfx.strip_and_freeze(pre)
        back = tfx.load_frozen(blob)
        np.testing.assert_allclose(
            np.asarray(back({"inp": x})["out"]), (x + 10.0) * 2.0)

    def test_with_postprocessor_infers_names(self):
        mf = self._mf()
        post = tfx.with_postprocessor(
            mf, lambda outs: {"flat": outs["out"].reshape(
                outs["out"].shape[0], -1).sum(axis=1)})
        assert post.output_names == ["flat"]
        x = np.arange(6, dtype=np.float32).reshape(2, 3)
        np.testing.assert_allclose(
            np.asarray(post({"inp": x})["flat"]), (x * 2.0).sum(axis=1))
