"""Engine retry + multi-host sharding + graph utils tests (SURVEY §5:
failure detection via task retry; §2.5 DCN host sharding; §2.1 tfx)."""

import threading

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.data.engine import LocalEngine
from sparkdl_tpu.data.frame import DataFrame, Source, Stage
from sparkdl_tpu.graph import utils as tfx
from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.parallel import (
    global_mesh,
    host_info,
    host_shard_dataframe,
    host_shard_indices,
    initialize,
)


def _batch(vals):
    return pa.RecordBatch.from_pydict({"x": pa.array(vals)})


class TestEngineRetry:
    def test_transient_failure_retried(self):
        engine = LocalEngine(num_workers=2, max_retries=2)
        fails = {"n": 0}
        lock = threading.Lock()

        def flaky_load():
            with lock:
                fails["n"] += 1
                if fails["n"] == 1:
                    raise IOError("transient read error")
            return _batch([1, 2, 3])

        sources = [Source(flaky_load, 3)]
        out = list(engine.execute(sources, []))
        assert out[0].num_rows == 3
        assert fails["n"] == 2  # one failure + one success

    def test_flaky_stage_retried(self):
        engine = LocalEngine(num_workers=2, max_retries=1)
        attempts = {"n": 0}
        lock = threading.Lock()

        def flaky_stage(batch):
            with lock:
                attempts["n"] += 1
                if attempts["n"] == 1:
                    raise IOError("decode read hiccup")
            return batch

        sources = [Source(lambda: _batch([1]), 1)]
        out = list(engine.execute(sources, [Stage(flaky_stage)]))
        assert out[0].num_rows == 1

    def test_permanent_failure_raises_after_attempts(self):
        engine = LocalEngine(num_workers=1, max_retries=2)
        calls = {"n": 0}

        def always_fails():
            calls["n"] += 1
            raise IOError("disk gone")

        sources = [Source(always_fails, 1)]
        with pytest.raises(IOError, match="disk gone"):
            list(engine.execute(sources, []))
        assert calls["n"] == 3

    def test_zero_retries(self):
        engine = LocalEngine(num_workers=1, max_retries=0)
        calls = {"n": 0}

        def fails():
            calls["n"] += 1
            raise IOError("nope")

        with pytest.raises(IOError, match="nope"):
            list(engine.execute([Source(fails, 1)], []))
        assert calls["n"] == 1

    def test_transient_device_error_retried(self):
        """A PJRT/jax runtime failure mid-partition (e.g. the tunnel
        connection dropping in this very env) must be retried like an IO
        error — the partition re-runs cleanly from its source (VERDICT
        r2 weak #6: the old retry set was OSError-only)."""
        from jax.errors import JaxRuntimeError

        engine = LocalEngine(num_workers=2, max_retries=2)
        attempts = {"n": 0}
        lock = threading.Lock()

        def flaky_device_stage(batch):
            with lock:
                attempts["n"] += 1
                if attempts["n"] == 1:
                    raise JaxRuntimeError(
                        "UNAVAILABLE: tunnel connection reset")
            return batch

        out = list(engine.execute(
            [Source(lambda: _batch([1, 2]), 2)],
            [Stage(flaky_device_stage, kind="device")]))
        assert out[0].num_rows == 2
        assert attempts["n"] == 2

    def test_deterministic_jax_status_not_retried(self):
        """A jax error whose status code means 'this will fail the same
        way again' (INVALID_ARGUMENT, a deterministic RESOURCE_EXHAUSTED
        allocation failure) must propagate on FIRST failure — re-running
        a decode-bearing partition 3x before the inevitable error would
        triple time-to-failure and mislabel it transient."""
        from jax.errors import JaxRuntimeError

        for status in ("INVALID_ARGUMENT: operand shapes",
                       "RESOURCE_EXHAUSTED: allocating 40G exceeds HBM",
                       # wrapping layers prefix context; the status
                       # token must still classify as deterministic
                       "Execution failed: INVALID_ARGUMENT: bad dims"):
            engine = LocalEngine(num_workers=1, max_retries=3)
            calls = {"n": 0}

            def stage(batch, status=status):
                calls["n"] += 1
                raise JaxRuntimeError(status)

            with pytest.raises(JaxRuntimeError):
                list(engine.execute([Source(lambda: _batch([1]), 1)],
                                    [Stage(stage, kind="device")]))
            assert calls["n"] == 1, status

    def test_classifier_tolerates_degenerate_messages(self):
        """Empty / whitespace-only jax error messages must classify
        (as non-deterministic), not crash the classifier and mask the
        original device error."""
        from jax.errors import JaxRuntimeError

        from sparkdl_tpu.data.engine import is_deterministic_jax_error

        for msg in ("", "\n", "   ", "\n\nINVALID_ARGUMENT: late"):
            assert is_deterministic_jax_error(JaxRuntimeError(msg)) \
                == ("INVALID_ARGUMENT" in msg)

    def test_custom_retryable_set(self):
        """retryable_exceptions is configurable; an exception outside
        the set propagates on first failure."""
        class Flaky(Exception):
            pass

        engine = LocalEngine(num_workers=1, max_retries=3,
                             retryable_exceptions=(Flaky,))
        calls = {"n": 0}

        def stage(batch):
            calls["n"] += 1
            if calls["n"] == 1:
                raise Flaky("once")
            return batch

        out = list(engine.execute([Source(lambda: _batch([1]), 1)],
                                  [Stage(stage)]))
        assert out[0].num_rows == 1 and calls["n"] == 2

        # OSError is now OUTSIDE the configured set → no retry
        calls2 = {"n": 0}

        def io_fails(batch):
            calls2["n"] += 1
            raise IOError("disk gone")

        with pytest.raises(IOError):
            list(engine.execute([Source(lambda: _batch([1]), 1)],
                                [Stage(io_fails)]))
        assert calls2["n"] == 1

    def test_deterministic_error_not_retried(self):
        engine = LocalEngine(num_workers=1, max_retries=3)
        calls = {"n": 0}

        def bad_stage(batch):
            calls["n"] += 1
            raise KeyError("column 'nope' not in batch")

        with pytest.raises(KeyError, match="nope"):
            list(engine.execute([Source(lambda: _batch([1]), 1)],
                                [Stage(bad_stage)]))
        assert calls["n"] == 1  # no pointless retries of user errors


class TestHostSharding:
    def test_single_process_owns_everything(self):
        initialize()  # no-op single process
        info = host_info()
        assert info.process_count == 1
        assert info.process_index == 0
        assert host_shard_indices(5) == [0, 1, 2, 3, 4]

    def test_initialize_attempts_join_with_explicit_args(self,
                                                         monkeypatch):
        """Explicit multi-process args must reach
        jax.distributed.initialize (regression: the old process_count
        guard initialized the backend itself, making real
        initialization unreachable)."""
        import jax
        calls = []
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda **kw: calls.append(kw))
        initialize(coordinator_address="10.0.0.1:1234",
                   num_processes=2, process_id=0)
        assert calls == [{"coordinator_address": "10.0.0.1:1234",
                          "num_processes": 2, "process_id": 0}]

    def test_initialize_auto_detect_env(self, monkeypatch):
        """A cluster env marker must trigger an initialize attempt even
        with no args (TPU pod auto-detection path; regression: the old
        all-None early return skipped it)."""
        import jax
        calls = []
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda **kw: calls.append(kw))
        monkeypatch.setenv("SLURM_JOB_ID", "12345")
        initialize()
        assert len(calls) == 1

    def test_initialize_plain_single_process_noop(self, monkeypatch):
        import jax
        calls = []
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda **kw: calls.append(kw))
        for v in ("JAX_COORDINATOR_ADDRESS", "MEGASCALE_COORDINATOR_ADDRESS",
                  "TPU_WORKER_HOSTNAMES", "SLURM_JOB_ID",
                  "OMPI_COMM_WORLD_SIZE"):
            monkeypatch.delenv(v, raising=False)
        initialize()
        assert calls == []

    def test_round_robin_explicit(self):
        assert host_shard_indices(10, process_index=0,
                                  process_count=4) == [0, 4, 8]
        assert host_shard_indices(10, process_index=3,
                                  process_count=4) == [3, 7]
        # every partition owned exactly once
        owned = sorted(sum((host_shard_indices(10, i, 4)
                            for i in range(4)), []))
        assert owned == list(range(10))

    def test_invalid_process(self):
        with pytest.raises(ValueError, match="invalid process"):
            host_shard_indices(4, process_index=4, process_count=4)

    def test_host_shard_dataframe_lazy(self):
        loaded = []

        def make(i):
            def _load():
                loaded.append(i)
                return _batch([i])
            return Source(_load, 1)

        df = DataFrame([make(i) for i in range(6)])
        mine = host_shard_dataframe(df, process_index=1, process_count=3)
        assert mine.num_partitions == 2
        rows = mine.collect_rows()
        assert [r["x"] for r in rows] == [1, 4]
        assert sorted(loaded) == [1, 4]  # other hosts' sources untouched

    def test_global_mesh_shape(self):
        mesh = global_mesh()
        assert mesh.devices.size == 8  # conftest's virtual CPU devices
        assert mesh.axis_names == ("data", "model")


class TestGraphUtils:
    def _mf(self):
        return ModelFunction.fromSingle(
            lambda x: x * 2.0, None, input_shape=(3,),
            input_name="inp", output_name="out", name="m")

    def test_validated_io(self):
        mf = self._mf()
        assert tfx.validated_input(mf, "inp") == "inp"
        assert tfx.validated_output(mf, "out") == "out"
        with pytest.raises(ValueError, match="not in model"):
            tfx.validated_input(mf, "bogus")
        with pytest.raises(ValueError, match="not in model"):
            tfx.validated_output(mf, "bogus")
        with pytest.raises(TypeError, match="ModelFunction"):
            tfx.validated_model("not a model")

    def test_shapes_and_names(self):
        mf = self._mf()
        assert tfx.get_input_shape(mf, "inp") == (3,)
        assert tfx.get_output_shape(mf, "out") == (3,)
        assert tfx.input_names(mf) == ["inp"]
        assert tfx.output_names(mf) == ["out"]

    def test_freeze_roundtrip(self):
        mf = self._mf()
        blob = tfx.strip_and_freeze(mf)
        assert isinstance(blob, bytes) and len(blob) > 0
        back = tfx.load_frozen(blob)
        x = np.arange(6, dtype=np.float32).reshape(2, 3)
        np.testing.assert_allclose(
            np.asarray(back({"inp": x})["out"]), x * 2.0)

    def test_fixed_batch_freeze_roundtrip(self):
        """A fixed-batch export must deserialize with working output
        names (regression: the lazy name probe ran the program with
        batch 1, which a fixed-batch export rejects)."""
        mf = self._mf()
        back = tfx.load_frozen(mf.export(batch_size=3))
        assert back.output_names == ["out"]
        # output_signature must come from the exported avals, not an
        # eval_shape probe (which would call the program with batch 1)
        shape, dtype = back.output_signature()["out"]
        assert shape == (3,) and np.dtype(dtype) == np.float32
        assert tfx.get_output_shape(back, "out") == (3,)
        x = np.arange(9, dtype=np.float32).reshape(3, 3)
        np.testing.assert_allclose(
            np.asarray(back({"inp": x})["out"]), x * 2.0)

    def test_select_outputs_prunes(self):
        def two_headed(x):
            return {"a": x + 1.0, "b": x * 3.0}

        mf = ModelFunction(
            lambda p, d: two_headed(d["inp"]), None,
            input_signature={"inp": ((3,), np.dtype(np.float32))},
            output_names=["a", "b"], name="two")
        pruned = tfx.select_outputs(mf, ["b"])
        assert pruned.output_names == ["b"]
        x = np.ones((2, 3), np.float32)
        out = pruned({"inp": x})
        assert set(out) == {"b"}
        np.testing.assert_allclose(np.asarray(out["b"]), x * 3.0)
        with pytest.raises(ValueError, match="not in model"):
            tfx.select_outputs(mf, ["bogus"])
        with pytest.raises(ValueError, match="at least one"):
            tfx.select_outputs(mf, [])

    def test_with_preprocessor_fuses(self):
        mf = self._mf()
        pre = tfx.with_preprocessor(
            mf, lambda ins: {"inp": ins["inp"] + 10.0})
        x = np.arange(6, dtype=np.float32).reshape(2, 3)
        np.testing.assert_allclose(
            np.asarray(pre({"inp": x})["out"]), (x + 10.0) * 2.0)
        # composed program still exports to StableHLO (deploy form)
        blob = tfx.strip_and_freeze(pre)
        back = tfx.load_frozen(blob)
        np.testing.assert_allclose(
            np.asarray(back({"inp": x})["out"]), (x + 10.0) * 2.0)

    def test_with_postprocessor_infers_names(self):
        mf = self._mf()
        post = tfx.with_postprocessor(
            mf, lambda outs: {"flat": outs["out"].reshape(
                outs["out"].shape[0], -1).sum(axis=1)})
        assert post.output_names == ["flat"]
        x = np.arange(6, dtype=np.float32).reshape(2, 3)
        np.testing.assert_allclose(
            np.asarray(post({"inp": x})["flat"]), (x * 2.0).sum(axis=1))


# ---------------------------------------------------------------------------
# ISSUE 11: the resilience layer — taxonomy, fault harness, retry policy,
# circuit breaking, serve re-dispatch, SLO-aware priority shedding.

import time

from sparkdl_tpu import resilience
from sparkdl_tpu.data.frame import Source as _Source, Stage as _Stage
from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.obs import default_registry
from sparkdl_tpu.obs.slo import slo_tracker
from sparkdl_tpu.resilience import faults as rfaults
from sparkdl_tpu.resilience.errors import (
    PermanentError,
    TransientError,
    classify,
    is_transient,
)
from sparkdl_tpu.resilience.faults import (
    FaultSpecError,
    InjectedFault,
    InjectedPermanentFault,
)
from sparkdl_tpu.resilience.policy import (
    CircuitBreaker,
    CircuitOpen,
    RetryBudgetExhausted,
    RetryPolicy,
)
from sparkdl_tpu.serve import (
    ModelServer,
    Request,
    RequestQueue,
    ServeConfig,
    ServerOverloaded,
    ShedForPriority,
)


@pytest.fixture(autouse=True)
def _disarm_faults():
    """Every test in this file starts and ends with the harness
    disarmed — injection is per-test, never ambient."""
    rfaults.disarm()
    yield
    rfaults.disarm()


def _echo_mf(row=(2,), factor=2.0):
    def apply(params, inputs):
        return {"y": np.asarray(inputs["x"], np.float32) * factor}
    return ModelFunction(apply, None, {"x": (tuple(row), np.float32)},
                         output_names=["y"], backend="host")


def _counter(name):
    return default_registry().snapshot().get(name, 0.0)


class TestErrorTaxonomy:
    def test_typed_markers_win(self):
        class Weird(OSError, PermanentError):
            pass
        assert is_transient(TransientError("x"))
        assert not is_transient(PermanentError("x"))
        # PermanentError beats the otherwise-retryable OSError family
        assert not is_transient(Weird("x"))

    def test_heuristic_families(self):
        from jax.errors import JaxRuntimeError
        assert classify(IOError("disk")) == "transient"
        assert classify(KeyError("col")) == "permanent"
        assert classify(JaxRuntimeError(
            "UNAVAILABLE: tunnel reset")) == "transient"
        assert classify(JaxRuntimeError(
            "INVALID_ARGUMENT: bad dims")) == "permanent"

    def test_injected_faults_classify(self):
        assert classify(InjectedFault("drill")) == "transient"
        assert classify(InjectedPermanentFault("drill")) == "permanent"

    def test_engine_reexports_survive_the_move(self):
        # the taxonomy moved to resilience/; the engine names are API
        from sparkdl_tpu.data.engine import (
            default_retryable_exceptions as engine_dre,
        )
        from sparkdl_tpu.resilience.errors import (
            default_retryable_exceptions as res_dre,
        )
        assert engine_dre() == res_dre()
        assert TransientError in engine_dre()


class TestFaultHarness:
    def test_inject_validates_loudly(self):
        with pytest.raises(FaultSpecError, match="unknown fault site"):
            resilience.inject("nope.site")
        with pytest.raises(FaultSpecError, match="unknown fault kind"):
            resilience.inject("serve.dispatch", kind="flaky")
        with pytest.raises(FaultSpecError, match="rate"):
            resilience.inject("serve.dispatch", rate=0.0)
        with pytest.raises(FaultSpecError, match="rate"):
            resilience.inject("serve.dispatch", rate=1.5)

    def test_deterministic_sequence_per_seed(self):
        def pattern():
            fired = []
            for _ in range(24):
                try:
                    rfaults.maybe_fail("model.fetch")
                    fired.append(False)
                except InjectedFault:
                    fired.append(True)
            return fired

        resilience.inject("model.fetch", rate=0.5, seed=3)
        first = pattern()
        rfaults.disarm()
        resilience.inject("model.fetch", rate=0.5, seed=3)
        assert pattern() == first
        assert any(first) and not all(first)

    def test_registry_family_counts(self):
        before_total = _counter("faults.injected")
        before_site = _counter("faults.model.fetch.injected")
        resilience.inject("model.fetch", rate=1.0)
        for _ in range(3):
            with pytest.raises(InjectedFault):
                rfaults.maybe_fail("model.fetch")
        assert _counter("faults.injected") == before_total + 3
        assert _counter("faults.model.fetch.injected") == \
            before_site + 3
        st = rfaults.state()
        assert st["armed"] and \
            st["sites"]["model.fetch"]["injected"] == 3

    def test_env_spec_arms(self, monkeypatch):
        monkeypatch.setenv(
            "SPARKDL_TPU_FAULTS",
            "serve.dispatch:transient:0.25:7,model.fetch:permanent:1.0")
        assert rfaults.arm_from_env()
        st = rfaults.state()
        assert st["sites"]["serve.dispatch"] == {
            "kind": "transient", "rate": 0.25, "seed": 7,
            "checks": 0, "injected": 0}
        assert st["sites"]["model.fetch"]["kind"] == "permanent"

    def test_env_typo_degrades_disarmed(self, monkeypatch, caplog):
        for bad in ("serve.dispatch", "serve.dispatch:transient:2.0",
                    "bogus.site:transient:0.5",
                    "serve.dispatch:transient:zero"):
            monkeypatch.setenv("SPARKDL_TPU_FAULTS", bad)
            with caplog.at_level("WARNING",
                                 logger="sparkdl_tpu.resilience.faults"):
                assert not rfaults.arm_from_env(), bad
            assert not rfaults.state()["armed"], bad
        assert any("not a valid fault spec" in r.getMessage()
                   for r in caplog.records)

    def test_disarmed_overhead_every_site(self):
        """The acceptance bound: a disarmed site check rides the
        tracer's <10 µs shared no-op regime (min over repeats —
        noise only ever adds time)."""
        n = 4_000
        for site in rfaults.SITES:
            best = float("inf")
            for _ in range(5):
                t0 = time.perf_counter()
                for _ in range(n):
                    rfaults.maybe_fail(site)
                best = min(best, (time.perf_counter() - t0) / n)
            assert best < 10e-6, \
                f"disarmed {site} costs {best * 1e6:.2f} µs"

    def test_partial_arm_keeps_other_sites_noop(self):
        resilience.inject("model.fetch", rate=1.0)
        # an armed plan must not start firing at un-armed sites
        rfaults.maybe_fail("serve.dispatch")
        rfaults.maybe_fail("engine.source_load")
        rfaults.disarm("model.fetch")
        assert not rfaults.state()["armed"]


class TestFaultSitesThreaded:
    """Each named site actually fires from its real hot path."""

    def test_engine_source_load_retries_injected_transient(self):
        # seed 1, rate 0.5: first draw fires, second passes — the
        # partition retry recovers and the data is intact
        resilience.inject("engine.source_load", rate=0.5, seed=1)
        before = _counter("engine.retries")
        engine = LocalEngine(num_workers=1, max_retries=2)
        out = list(engine.execute([Source(lambda: _batch([1, 2]), 2)],
                                  []))
        assert out[0].num_rows == 2
        assert _counter("engine.retries") == before + 1
        assert rfaults.state()["sites"]["engine.source_load"][
            "injected"] == 1

    def test_engine_stage_apply_permanent_fails_fast(self):
        resilience.inject("engine.stage_apply", kind="permanent",
                          rate=1.0)
        engine = LocalEngine(num_workers=1, max_retries=3)
        with pytest.raises(InjectedPermanentFault):
            list(engine.execute([Source(lambda: _batch([1]), 1)],
                                [_Stage(lambda b: b)]))
        # permanent = classified non-retryable: exactly ONE attempt
        assert rfaults.state()["sites"]["engine.stage_apply"][
            "checks"] == 1

    def test_ship_sites_fire_from_dispatch_chunks(self):
        from sparkdl_tpu.runtime.runner import BatchRunner
        mf = ModelFunction.fromSingle(
            lambda x: x * 2.0, None, input_shape=(3,),
            input_name="x", output_name="y", name="m")
        runner = BatchRunner(mf, batch_size=4)
        x = np.ones((8, 3), np.float32)
        for site in ("ship.device_put", "ship.drain"):
            rfaults.disarm()
            resilience.inject(site, rate=1.0)
            with pytest.raises(InjectedFault):
                runner.run({"x": x})
            assert rfaults.state()["sites"][site]["injected"] >= 1
        rfaults.disarm()
        out = runner.run({"x": x})     # disarmed: clean run after
        np.testing.assert_allclose(out["y"], 2.0)

    def test_collective_launch_site_never_leaks_the_lock(self):
        from sparkdl_tpu.parallel.mesh import (
            _COLLECTIVE_LAUNCH_LOCK,
            collective_launch,
        )
        mesh = global_mesh()
        resilience.inject("collective.launch", rate=1.0)
        with pytest.raises(InjectedFault):
            with collective_launch(mesh):
                pass
        assert not _COLLECTIVE_LAUNCH_LOCK.locked()
        rfaults.disarm()
        with collective_launch(mesh):   # clean entry after the drill
            assert _COLLECTIVE_LAUNCH_LOCK.locked()
        assert not _COLLECTIVE_LAUNCH_LOCK.locked()

    def test_model_fetch_site(self, tmp_path):
        from sparkdl_tpu.models.fetcher import ModelFetcher
        f = ModelFetcher(cache_dir=str(tmp_path))
        params = {"w": np.ones((2,), np.float32)}
        f.put("m.msgpack", params)
        resilience.inject("model.fetch", rate=1.0)
        with pytest.raises(InjectedFault):
            f.get("m.msgpack", params)
        rfaults.disarm()
        back = f.get("m.msgpack", params)
        np.testing.assert_allclose(back["w"], 1.0)


class TestRetryPolicy:
    def test_bounded_attempts_reraise_original(self):
        p = RetryPolicy(attempts=3, base_backoff_s=0.0,
                        sleep=lambda s: None)
        calls = []

        def fails():
            calls.append(1)
            raise InjectedFault("always")

        with pytest.raises(InjectedFault):
            p.call(fails)
        assert len(calls) == 3

    def test_non_retryable_propagates_first(self):
        p = RetryPolicy(attempts=5, base_backoff_s=0.0,
                        sleep=lambda s: None)
        calls = []

        def fails():
            calls.append(1)
            raise KeyError("permanent user error")

        with pytest.raises(KeyError):
            p.call(fails)
        assert len(calls) == 1

    def test_backoff_exponential_capped_deterministic(self):
        p = RetryPolicy(attempts=8, base_backoff_s=0.1,
                        max_backoff_s=0.4, jitter_frac=0.25)
        d1, d2, d3 = (p.backoff_s(a, "k") for a in (1, 2, 3))
        assert 0.1 <= d1 <= 0.125
        assert 0.2 <= d2 <= 0.25
        assert 0.4 <= d3 <= 0.5       # capped at max, jitter on top
        assert p.backoff_s(2, "k") == d2          # deterministic
        assert p.backoff_s(2, "other") != d2      # de-synchronized

    def test_budget_bounds_amplification_typed(self):
        p = RetryPolicy(attempts=2, base_backoff_s=0.0,
                        budget_ratio=0.2, budget_cap=1.0,
                        sleep=lambda s: None)
        before = _counter("resilience.budget_denied")

        def fails():
            raise InjectedFault("dependency down")

        with pytest.raises(InjectedFault):
            p.call(fails)               # spends the one token
        with pytest.raises(RetryBudgetExhausted) as ei:
            p.call(fails)               # bucket empty -> typed refusal
        assert isinstance(ei.value.__cause__, InjectedFault)
        assert isinstance(ei.value, PermanentError)  # outer no-retry
        assert _counter("resilience.budget_denied") == before + 1

    def test_deposits_refill_the_bucket(self):
        p = RetryPolicy(attempts=2, base_backoff_s=0.0,
                        budget_ratio=1.0, budget_cap=1.0,
                        sleep=lambda s: None)
        for _ in range(4):      # ratio 1.0: every call earns a retry
            calls = []

            def flaky():
                calls.append(1)
                if len(calls) == 1:
                    raise InjectedFault("once")
                return "ok"

            assert p.call(flaky) == "ok"

    def test_deadline_blocks_late_retry(self):
        p = RetryPolicy(attempts=5, base_backoff_s=0.2,
                        sleep=lambda s: None)
        calls = []

        def fails():
            calls.append(1)
            raise InjectedFault("x")

        with pytest.raises(InjectedFault):
            p.call(fails, deadline=time.perf_counter() + 0.01)
        assert len(calls) == 1  # backoff 0.2s cannot fit in 10ms

    def test_pickle_round_trip(self):
        import cloudpickle
        import pickle
        p = RetryPolicy(attempts=4, base_backoff_s=0.03, seed=9)
        p2 = pickle.loads(cloudpickle.dumps(p))
        assert p2.attempts == 4
        assert p2.backoff_s(2, "k") == p.backoff_s(2, "k")
        assert p2.call(lambda: 11) == 11


class TestCircuitBreaker:
    def test_transitions(self):
        clock = [0.0]
        cb = CircuitBreaker(failure_threshold=3, reset_timeout_s=5.0,
                            half_open_probes=1,
                            clock=lambda: clock[0])
        assert cb.state == "closed" and cb.allow()
        cb.record_failure(); cb.record_failure()
        assert cb.state == "closed"     # below threshold
        cb.record_success()
        cb.record_failure(); cb.record_failure(); cb.record_failure()
        assert cb.state == "open" and cb.opens == 1
        assert not cb.allow()
        clock[0] = 4.9
        assert not cb.allow()           # still inside the timeout
        clock[0] = 5.1
        assert cb.allow()               # half-open: the one probe
        assert cb.state == "half_open"
        assert not cb.allow()           # probe budget spent
        cb.record_failure()             # probe failed -> open again
        assert cb.state == "open" and cb.opens == 2
        clock[0] = 11.0
        assert cb.allow()
        cb.record_success()
        assert cb.state == "closed" and cb.allow()
        assert cb.state_code == 0

    def test_lost_probe_self_heals_the_half_open_window(self):
        """A half-open probe that dies BEFORE dispatch (rejected at
        the queue, expired, shed, abandoned by shutdown) produces no
        record_* outcome — the breaker must re-open its probe window
        after reset_timeout_s instead of wedging every future submit
        on a long-recovered model."""
        clock = [0.0]
        cb = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0,
                            half_open_probes=1,
                            clock=lambda: clock[0])
        cb.record_failure()
        clock[0] = 5.1
        assert cb.allow()               # the probe slot
        assert not cb.allow()           # spent; probe then dies silently
        clock[0] = 10.0
        assert not cb.allow()           # probe window not yet stale
        clock[0] = 10.2
        assert cb.allow()               # self-healed: fresh probe
        cb.record_success()
        assert cb.state == "closed"

    def test_validation(self):
        with pytest.raises(ValueError):
            CircuitBreaker(failure_threshold=0)
        with pytest.raises(ValueError):
            CircuitBreaker(reset_timeout_s=0)
        with pytest.raises(ValueError):
            CircuitBreaker(half_open_probes=0)

    def test_pickle_reanchors_open_timestamp(self):
        import cloudpickle
        import pickle
        cb = CircuitBreaker(failure_threshold=1, reset_timeout_s=60.0)
        cb.record_failure()
        assert cb.state == "open"
        cb2 = pickle.loads(cloudpickle.dumps(cb))
        assert cb2.state == "open"
        assert not cb2.allow()   # waits a FULL timeout in its process


class TestServeResilience:
    def test_injected_soak_zero_lost_zero_duplicated(self):
        """THE acceptance drill: 10% transient faults at the serve
        dispatch site under a concurrent soak — every admitted request
        resolves (success or typed failure), row identity exact, and
        the re-dispatch path demonstrably engaged."""
        import threading as th
        resilience.inject("serve.dispatch", rate=0.1, seed=1234)
        retries_before = _counter("serve.retries")
        server = ModelServer(ServeConfig(
            max_wait_s=0.001, max_queue_rows=4096,
            dispatch_retries=3, retry_base_backoff_s=0.001))
        server.register("drill", _echo_mf(row=(4,)), batch_size=16)
        futures, lock = [], th.Lock()

        def fire(tid):
            for i in range(30):
                val = float(tid * 100 + i)
                f = server.submit(
                    {"x": np.full((8, 4), val, np.float32)})
                with lock:
                    futures.append((val, f))

        workers = [th.Thread(target=fire, args=(t,)) for t in range(4)]
        for w in workers:
            w.start()
        for w in workers:
            w.join()
        ok = typed = 0
        for val, f in futures:
            try:
                out = f.result(timeout=60)
                # row identity: the value IS the request id — a lost,
                # duplicated, or cross-wired row shows up here
                assert out["y"].shape == (8, 4)
                np.testing.assert_allclose(out["y"], 2.0 * val)
                ok += 1
            except (InjectedFault, RetryBudgetExhausted):
                typed += 1
        server.close()
        assert ok + typed == len(futures) == 120
        assert ok > 0
        assert _counter("serve.retries") > retries_before
        assert rfaults.state()["sites"]["serve.dispatch"][
            "injected"] > 0

    def test_surviving_requests_redispatch_not_whole_batch(self):
        """Two coalesced requests, one dispatch failure: the batch
        re-dispatches and BOTH resolve — the pre-resilience behavior
        (one transient failure fails every coalesced request) is
        gone. Deterministic: seed 1 / rate 0.5 fires on the first
        check only."""
        resilience.inject("serve.dispatch", rate=0.5, seed=1)
        server = ModelServer(ServeConfig(
            max_wait_s=0.05, dispatch_retries=2,
            retry_base_backoff_s=0.001))
        session = server.register("m", _echo_mf(), batch_size=8)
        session._ensure_worker = lambda: None       # hold the queue
        f1 = server.submit({"x": np.full((4, 2), 1.0, np.float32)})
        f2 = server.submit({"x": np.full((4, 2), 2.0, np.float32)})
        del session.__dict__["_ensure_worker"]      # restore + kick
        session._ensure_worker()
        np.testing.assert_allclose(f1.result(timeout=30)["y"], 2.0)
        np.testing.assert_allclose(f2.result(timeout=30)["y"], 4.0)
        assert session.metrics.retries >= 1
        server.close()

    def test_permanent_fault_never_retries(self):
        resilience.inject("serve.dispatch", kind="permanent", rate=1.0)
        server = ModelServer(ServeConfig(
            max_wait_s=0.0, dispatch_retries=3,
            retry_base_backoff_s=0.001))
        fut = server.register("m", _echo_mf(), batch_size=4).submit(
            {"x": np.zeros((2, 2), np.float32)})
        with pytest.raises(InjectedPermanentFault):
            fut.result(timeout=30)
        # exactly one dispatch attempt: permanent = no re-dispatch
        assert rfaults.state()["sites"]["serve.dispatch"]["checks"] == 1
        assert server.metrics.retries == 0
        server.close()

    def test_retry_budget_exhaustion_stays_typed(self):
        resilience.inject("serve.dispatch", rate=1.0)
        server = ModelServer(ServeConfig(
            max_wait_s=0.0, dispatch_retries=3,
            retry_base_backoff_s=0.0005, retry_budget_ratio=0.1,
            circuit_failure_threshold=1000))
        session = server.register("m", _echo_mf(), batch_size=4)
        outcomes = []
        for i in range(8):
            fut = session.submit({"x": np.zeros((2, 2), np.float32)})
            try:
                fut.result(timeout=30)
                outcomes.append("ok")
            except Exception as e:
                outcomes.append(type(e).__name__)
        # the bucket (cap 8, ratio 0.1) drains; refusals are TYPED
        assert "RetryBudgetExhausted" in outcomes, outcomes
        assert set(outcomes) <= {"InjectedFault",
                                 "RetryBudgetExhausted"}, outcomes
        server.close()

    def test_circuit_open_half_open_close(self):
        resilience.inject("serve.dispatch", kind="permanent", rate=1.0)
        server = ModelServer(ServeConfig(
            max_wait_s=0.0, circuit_failure_threshold=2,
            circuit_reset_s=0.15))
        session = server.register("m", _echo_mf(), batch_size=4)
        for _ in range(2):
            with pytest.raises(InjectedPermanentFault):
                session.submit(
                    {"x": np.zeros((2, 2), np.float32)}).result(
                        timeout=30)
        assert session.circuit.state == "open"
        with pytest.raises(CircuitOpen, match="circuit is open"):
            session.submit({"x": np.zeros((2, 2), np.float32)})
        assert session.metrics.circuit_rejections == 1
        # heal the model, wait out the reset, probe through
        rfaults.disarm()
        time.sleep(0.2)
        probe = session.submit({"x": np.ones((2, 2), np.float32)})
        np.testing.assert_allclose(probe.result(timeout=30)["y"], 2.0)
        assert session.circuit.state == "closed"
        server.close()
        snap = default_registry().snapshot()
        assert snap["serve.circuit_state"] == 0.0
        assert snap["serve.circuit_rejections"] >= 1.0

    def test_statusz_carries_circuit_and_resilience(self):
        server = ModelServer(ServeConfig(max_wait_s=0.0))
        server.register("m", _echo_mf(), batch_size=4)
        st = server.telemetry_status()
        assert st["models"]["m"]["circuit"]["state"] == "closed"
        assert st["models"]["m"]["retry"]["attempts"] == 3
        from sparkdl_tpu.obs.flight import recorder
        bundle = recorder().bundle(reason="test")
        assert "faults" in bundle["resilience"]
        assert bundle["resilience"]["circuits"]["m"][
            "state"] == "closed"
        server.close()


class TestPriorityShedding:
    def _req(self, rows, priority, deadline=None):
        return Request({"x": np.zeros((rows, 2), np.float32)}, rows,
                       deadline, priority=priority)

    def test_displacement_lowest_newest_first(self):
        q = RequestQueue()
        p0_old = self._req(4, 0)
        p0_new = self._req(4, 0)
        p1 = self._req(8, 1)
        for r in (p0_old, p0_new, p1):
            q.offer(r, 16)
        assert q.depth() == 16
        high = self._req(8, 2)
        depth, victims = q.offer(high, 16)
        # sheds the lowest class, newest first: both p0s (8 rows
        # needed), never the p1 (4 rows would not have sufficed from
        # p0_new alone, and p1 outranks p0)
        assert victims == [p0_new, p0_old]
        assert depth == 16 and q.depth() == 16

    def test_equal_priority_never_displaces(self):
        q = RequestQueue()
        q.offer(self._req(16, 0), 16)
        with pytest.raises(ServerOverloaded,
                           match="no lower-priority rows"):
            q.offer(self._req(4, 0), 16)

    def test_insufficient_shed_rejects_arrival(self):
        q = RequestQueue()
        q.offer(self._req(2, 0), 16)    # only 2 sheddable rows: the
        q.offer(self._req(14, 10), 16)  # 14-row request OUTRANKS the
        with pytest.raises(ServerOverloaded):   # priority-9 arrival
            q.offer(self._req(8, 9), 16)
        assert q.depth() == 16          # nothing was shed on refusal

    def test_burn_shed_below_highest_queued_class(self):
        q = RequestQueue()
        q.offer(self._req(4, 1), 64)
        # budget burning + queue past the watermark: lower class sheds
        with pytest.raises(ShedForPriority, match="burning"):
            q.offer(self._req(4, 0), 64, burn_rate=2.0,
                    watermark_rows=4)
        # same class rides through regardless of burn
        depth, victims = q.offer(self._req(4, 1), 64, burn_rate=2.0,
                                 watermark_rows=4)
        assert depth == 8 and victims == []
        # healthy budget: low class admits fine past the watermark
        depth, _ = q.offer(self._req(4, 0), 64, burn_rate=0.5,
                           watermark_rows=4)
        assert depth == 12

    def test_saturation_keeps_highest_class_green(self):
        """The ISSUE's drill: under hard saturation, priority-1
        traffic stays at 100% availability while priority-0 sheds —
        lowest class first, typed."""
        server = ModelServer(ServeConfig(max_wait_s=0.0,
                                         max_queue_rows=32))
        session = server.register("m", _echo_mf(), batch_size=8)
        session._ensure_worker = lambda: None   # saturate the queue
        p0_futs = [session.submit(
            {"x": np.zeros((8, 2), np.float32)}, priority=0)
            for _ in range(4)]                  # 32 rows: FULL
        shed_before = session.metrics.shed
        p1_futs = [session.submit(
            {"x": np.full((8, 2), 7.0, np.float32)}, priority=1)
            for _ in range(2)]                  # displaces 2x p0
        shed_now = [f for f in p0_futs if f.done()]
        assert len(shed_now) == 2
        for f in shed_now:
            with pytest.raises(ServerOverloaded, match="shed"):
                f.result(timeout=1)
        assert session.metrics.shed == shed_before + 2
        assert session.metrics.shed_rows >= 16
        del session.__dict__["_ensure_worker"]  # drain what remains
        session._ensure_worker()
        for f in p1_futs:       # the highest class: 100% availability
            np.testing.assert_allclose(f.result(timeout=30)["y"], 14.0)
        for f in p0_futs:
            if f not in shed_now:
                np.testing.assert_allclose(
                    f.result(timeout=30)["y"], 0.0)
        server.close()
        assert default_registry().snapshot()["serve.shed"] >= 2

    def test_negative_priority_rejected_at_submit(self):
        server = ModelServer(ServeConfig(max_wait_s=0.0))
        server.register("m", _echo_mf(), batch_size=4)
        with pytest.raises(ValueError, match="priority"):
            server.submit({"x": np.zeros((2, 2), np.float32)},
                          priority=-1)
        server.close()

    def test_default_priority_behavior_unchanged(self):
        """With every caller at the default class there is no
        displacement and no burn shed — the pre-priority contract."""
        q = RequestQueue()
        q.offer(self._req(8, 0), 8)
        with pytest.raises(ServerOverloaded):
            q.offer(self._req(8, 0), 8, burn_rate=5.0,
                    watermark_rows=2)
