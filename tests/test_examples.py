"""Examples must actually run (the reference's README examples were its
user API spec — these are that spec, kept executable)."""

import runpy
import sys

import pytest


@pytest.mark.parametrize("script", [
    "examples/transfer_learning.py",
    "examples/keras_udf.py",
    "examples/multi_chip.py",
    "examples/fast_infeed.py",
    "examples/export_deploy.py",
    "examples/save_load_pipeline.py",
    "examples/out_of_core_tuning.py",
])
def test_example_runs(script, capsys):
    runpy.run_path(script, run_name="__main__")
    out = capsys.readouterr().out
    assert out.strip()  # each example prints its result


def test_hpo_example_runs(capsys):
    runpy.run_path("examples/hyperparameter_search.py",
                   run_name="__main__")
    assert "accuracies" in capsys.readouterr().out


def test_export_deploy_example_serves_online(capsys):
    """The deploy example's last act (docs/SERVING.md): the exported
    bytes behind a ModelServer under concurrent clients — the printed
    serve counters prove the requests really went through the
    micro-batcher (full batches, nothing rejected) rather than a
    per-request fallback path. The compile log (obs/compile_log.py)
    additionally pins the warm-start contract: the served program
    compiles EXACTLY once (during warmup, never on a request), and
    the example measures first-request latency with vs without
    warmup() — ROADMAP item 4's AOT warm-start case, as a number."""
    runpy.run_path("examples/export_deploy.py", run_name="__main__")
    out = capsys.readouterr().out
    assert "serve: 12 concurrent requests" in out, out
    assert "micro-batches" in out and "fill" in out, out
    assert "rejections 0" in out, out
    # the zero-retrace pin: exactly one compile for the served path
    # (the example asserts the compile-log counts internally; this
    # pins the printed contract line)
    assert "served-path compiles 1 (exactly once" in out, out
    assert "first-request latency:" in out, out
    assert "cold (compile on the hot path)" in out, out
    assert "after warmup()" in out, out


def test_migration_guide_api_claims():
    """Every API shape docs/MIGRATION.md shows must exist as written —
    a stale migration guide misleads exactly the user it exists for."""
    import inspect

    import sparkdl_tpu
    from sparkdl_tpu.estimators.keras_image_file_estimator import (
        KerasImageFileEstimator,
    )
    from sparkdl_tpu.graph.function import ModelFunction
    from sparkdl_tpu.graph.ingest import ModelIngest, TFInputGraph
    from sparkdl_tpu.image.imageIO import readImagesPacked
    from sparkdl_tpu.params.tuning import CrossValidator
    from sparkdl_tpu.transformers.image_transform import ImageTransformer
    from sparkdl_tpu.transformers.tensor_transform import TensorTransformer

    assert TFInputGraph is ModelIngest
    for src in ("fromGraph", "fromGraphDef", "fromSavedModel",
                "fromSavedModelWithSignature", "fromCheckpoint",
                "fromCheckpointWithSignature", "fromFunction",
                "fromExport"):
        assert hasattr(ModelIngest, src), src
    assert hasattr(ModelFunction, "fromList")
    assert sparkdl_tpu.TFImageTransformer is ImageTransformer
    assert sparkdl_tpu.TFTransformer is TensorTransformer

    def has_params(fn, *names):
        sig = inspect.signature(fn)
        for n in names:
            assert n in sig.parameters, (fn, n)

    has_params(ImageTransformer.__init__, "modelFunction", "outputMode",
               "deviceResizeFrom", "useMesh")
    has_params(TensorTransformer.__init__, "modelFunction",
               "inputMapping", "outputMapping", "tfHParams")
    has_params(sparkdl_tpu.LogisticRegression.__init__, "batchSize",
               "streaming", "memoryBudgetBytes")
    has_params(KerasImageFileEstimator.__init__, "parallelism",
               "useMesh", "checkpointDir", "streaming")
    has_params(CrossValidator.__init__, "cacheDir")
    has_params(sparkdl_tpu.registerKerasImageUDF, "preprocessor",
               "session")
    has_params(readImagesPacked, "packedFormat", "scaledDecode",
               "dropImageFailures")
    # the eight reference names + readImages all resolve
    for name in ("imageSchema", "readImages", "DeepImageFeaturizer",
                 "DeepImagePredictor", "TFImageTransformer",
                 "TFTransformer", "KerasImageFileTransformer",
                 "KerasTransformer", "KerasImageFileEstimator",
                 "registerKerasImageUDF"):
        assert getattr(sparkdl_tpu, name) is not None
