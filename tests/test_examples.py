"""Examples must actually run (the reference's README examples were its
user API spec — these are that spec, kept executable)."""

import runpy
import sys

import pytest


@pytest.mark.parametrize("script", [
    "examples/transfer_learning.py",
    "examples/keras_udf.py",
    "examples/multi_chip.py",
    "examples/fast_infeed.py",
    "examples/export_deploy.py",
    "examples/save_load_pipeline.py",
    "examples/out_of_core_tuning.py",
])
def test_example_runs(script, capsys):
    runpy.run_path(script, run_name="__main__")
    out = capsys.readouterr().out
    assert out.strip()  # each example prints its result


def test_hpo_example_runs(capsys):
    runpy.run_path("examples/hyperparameter_search.py",
                   run_name="__main__")
    assert "accuracies" in capsys.readouterr().out
