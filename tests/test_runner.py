"""BatchRunner tests (L1: static-shape chunking, padding, async gather)."""

import numpy as np
import pytest

from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.runtime.runner import BatchRunner, RunnerMetrics


def _double_fn():
    return ModelFunction.fromSingle(lambda x: x * 2.0, None,
                                    input_shape=(3,))


class TestBatchRunner:
    def test_exact_multiple(self):
        r = BatchRunner(_double_fn(), batch_size=4)
        x = np.arange(24, dtype=np.float32).reshape(8, 3)
        out = r.run({"input": x})["output"]
        np.testing.assert_allclose(out, x * 2)

    def test_padding_last_chunk(self):
        r = BatchRunner(_double_fn(), batch_size=4)
        x = np.arange(21, dtype=np.float32).reshape(7, 3)
        out = r.run({"input": x})["output"]
        assert out.shape == (7, 3)
        np.testing.assert_allclose(out, x * 2)

    def test_smaller_than_batch(self):
        r = BatchRunner(_double_fn(), batch_size=64)
        x = np.ones((2, 3), np.float32)
        np.testing.assert_allclose(r.run({"input": x})["output"], 2.0)

    def test_empty_input(self):
        r = BatchRunner(_double_fn(), batch_size=4)
        out = r.run({"input": np.zeros((0, 3), np.float32)})
        assert out["output"].shape == (0, 3)

    def test_metrics(self):
        m = RunnerMetrics()
        r = BatchRunner(_double_fn(), batch_size=4, metrics=m)
        r.run({"input": np.zeros((10, 3), np.float32)})
        assert m.rows == 10
        assert m.batches == 3
        assert m.seconds > 0
        assert m.rows_per_second > 0

    def test_row_count_mismatch(self):
        def two_in(params, inputs):
            return {"out": inputs["a"] + inputs["b"]}
        mf = ModelFunction(two_in, None,
                           {"a": ((2,), np.float32),
                            "b": ((2,), np.float32)})
        r = BatchRunner(mf, batch_size=4)
        with pytest.raises(ValueError, match="rows"):
            r.run({"a": np.zeros((3, 2), np.float32),
                   "b": np.zeros((4, 2), np.float32)})

    def test_signature_validation_names_both_sides(self):
        """A missing/mis-shaped input raises HERE with both names —
        not a bare KeyError or a flax shape error from inside the
        traced program (review r5 probe)."""
        r = BatchRunner(_double_fn(), batch_size=4)
        with pytest.raises(ValueError, match="missing from"):
            r.run({"wrong": np.zeros((4, 3), np.float32)})
        with pytest.raises(ValueError, match="expects"):
            r.run({"input": np.zeros((4, 7), np.float32)})
        # extra keys are tolerated (the model ignores them)
        out = r.run({"input": np.ones((2, 3), np.float32),
                     "extra": np.zeros((2, 1), np.float32)})
        np.testing.assert_allclose(out["output"], 2.0)
        # zero-row inputs keep their empty-batch tolerance even when
        # FLAT (empty variable-list columns arrive as (0,))
        empty = r.run({"input": np.zeros((0,), np.float32)})
        assert empty["output"].shape == (0, 3)
        # jax models with scalar rows () ARE enforced ((4,3) into a
        # scalar-input model must not sail into an XLA error)
        scal = BatchRunner(ModelFunction.fromSingle(
            lambda x: x * 2.0, None, input_shape=()), batch_size=4)
        with pytest.raises(ValueError, match="expects"):
            scal.run({"input": np.zeros((4, 3), np.float32)})

    def test_deserialize_garbage_raises_clearly(self):
        from sparkdl_tpu.graph.ingest import ModelIngest

        with pytest.raises(ValueError, match="StableHLO"):
            ModelIngest.fromExport(b"definitely not an export")

    def test_bad_batch_size(self):
        with pytest.raises(ValueError):
            BatchRunner(_double_fn(), batch_size=0)

    def test_strategy_resolution(self, monkeypatch):
        from sparkdl_tpu.runtime.runner import resolve_strategy

        # isolate from the documented env override: a developer running
        # the suite with SPARKDL_TPU_RUNNER_STRATEGY exported must not
        # see spurious failures here
        monkeypatch.delenv("SPARKDL_TPU_RUNNER_STRATEGY", raising=False)
        assert resolve_strategy("immediate", None) == ("immediate", 0)
        assert resolve_strategy("deferred", 5) == ("deferred", 5)
        from sparkdl_tpu.runtime.runner import MAX_INFLIGHT_HOST_ASYNC
        assert resolve_strategy("host_async", None) == \
            ("host_async", MAX_INFLIGHT_HOST_ASYNC)
        assert resolve_strategy("host_async", 3) == ("host_async", 3)
        # an explicit queue depth means the caller wants a queue — it
        # must select deferred, not be silently dropped by the
        # tunnel-env auto-default
        assert resolve_strategy(None, 8) == ("deferred", 8)
        # contradictions and typos are loud
        with pytest.raises(ValueError, match="contradicts"):
            resolve_strategy("immediate", 8)
        with pytest.raises(ValueError, match="immediate"):
            resolve_strategy("immedaite", None)
        r = BatchRunner(_double_fn(), strategy="immediate")
        assert r.strategy == "immediate" and r.max_inflight == 0

    def test_start_host_copies_reports_missing_api(self):
        """A backend without copy_to_host_async must report False so
        runners fall back to the SHALLOW deferred queue — a deep queue
        of never-copied buffers is the round-1 stale-buffer collapse."""
        import jax.numpy as jnp

        from sparkdl_tpu.runtime.runner import start_host_copies

        class _NoAPI:
            pass

        assert start_host_copies({"y": _NoAPI()}) is False
        assert start_host_copies({"y": jnp.zeros(3)}) is True

    def test_start_host_copies_propagates_internal_bugs(self):
        """An AttributeError raised INSIDE a working copy_to_host_async
        is a genuine bug — it must propagate, not be misread as
        'API missing' and silently degrade the strategy (ADVICE r2 #2).
        NotImplementedError still means 'backend can't' → False."""
        from sparkdl_tpu.runtime.runner import start_host_copies

        class _Buggy:
            def copy_to_host_async(self):
                raise AttributeError("'NoneType' has no attribute 'buf'")

        class _CannotDo:
            def copy_to_host_async(self):
                raise NotImplementedError

        import pytest
        with pytest.raises(AttributeError, match="buf"):
            start_host_copies({"y": _Buggy()})
        assert start_host_copies({"y": _CannotDo()}) is False

    def test_all_strategies_produce_identical_outputs(self):
        """immediate / deferred / host_async / prefetch are pure
        dispatch policies — same results, same order, for aligned,
        tail-padded, and N=0 inputs (the slab-output parity pin)."""
        cases = {
            "tail": np.arange(22 * 3, dtype=np.float32).reshape(22, 3),
            "aligned": np.arange(8 * 3, dtype=np.float32).reshape(8, 3),
            "empty": np.zeros((0, 3), np.float32),
        }
        for name, x in cases.items():
            expected = None
            for strategy in ("immediate", "deferred", "host_async",
                             "prefetch"):
                r = BatchRunner(_double_fn(), batch_size=4,
                                strategy=strategy)
                out = r.run({"input": x})["output"]
                assert out.shape == x.shape, (name, strategy)
                if expected is None:
                    expected = out
                else:
                    np.testing.assert_array_equal(out, expected)
            np.testing.assert_allclose(expected, x * 2.0)

    def test_host_backend(self):
        def host_apply(params, inputs):
            return {"y": np.asarray(inputs["x"]) + 1.0}
        mf = ModelFunction(host_apply, None, {"x": ((3,), np.float32)},
                           output_names=["y"], backend="host")
        r = BatchRunner(mf, batch_size=4)
        x = np.zeros((6, 3), np.float32)
        np.testing.assert_allclose(r.run({"x": x})["y"], 1.0)

    def test_device_params_cached_and_invalidated(self):
        """Params transfer to the device once per params object and the
        cache invalidates when .params is reassigned (regression: a
        runner-level cache served stale weights after reassignment)."""
        mf = ModelFunction.fromSingle(
            lambda p, x: x * p["scale"], {"scale": np.float32(2.0)},
            input_shape=(2,))
        r = BatchRunner(mf, batch_size=4)
        x = np.ones((3, 2), np.float32)
        np.testing.assert_allclose(r.run({"input": x})["output"], 2.0)
        assert mf.device_params() is mf.device_params()  # cached

        mf.params = {"scale": np.float32(5.0)}
        np.testing.assert_allclose(r.run({"input": x})["output"], 5.0)

    def test_aligned_run_is_zero_copy(self):
        """The zero-copy hot path pinned by counters: a batch-aligned
        contiguous input ships as plain views — RunnerMetrics reports
        ZERO bytes staged and ZERO bytes copied. The input is marked
        read-only so any staging write into it would raise."""
        m = RunnerMetrics()
        r = BatchRunner(_double_fn(), batch_size=4, metrics=m)
        x = np.arange(24, dtype=np.float32).reshape(8, 3)
        x.setflags(write=False)
        np.testing.assert_allclose(r.run({"input": x})["output"], x * 2)
        assert m.bytes_staged == 0 and m.bytes_copied == 0, m
        # a tail-padded run stages EXACTLY the tail rows, nothing more
        y = np.arange(30, dtype=np.float32).reshape(10, 3)
        y.setflags(write=False)
        np.testing.assert_allclose(r.run({"input": y})["output"], y * 2)
        assert m.bytes_staged == y[8:].nbytes, m
        assert m.bytes_copied == 0, m

    def test_non_contiguous_input_counts_copies(self):
        """Non-contiguous rows (e.g. a strided column view) can't ship
        as views — they are copied, and the copy is COUNTED: the
        counters must not claim zero-copy for a path that copies."""
        m = RunnerMetrics()
        r = BatchRunner(_double_fn(), batch_size=4, metrics=m)
        x = np.arange(8 * 6, dtype=np.float32).reshape(8, 6)[:, ::2]
        assert not x.flags.c_contiguous
        np.testing.assert_allclose(r.run({"input": x})["output"], x * 2)
        assert m.bytes_copied == x.nbytes, m
        assert m.bytes_staged == 0, m

    def test_iter_padded_chunks_views_and_persistent_staging(self):
        """Full chunks are VIEWS of the input (zero host copies); the
        tail stages through ONE persistent buffer reused across calls,
        with the pad region re-zeroed when the next tail is shorter."""
        from sparkdl_tpu.runtime.runner import (
            CopyCounters,
            PadStaging,
            iter_padded_chunks,
        )

        x = np.arange(33, dtype=np.float32).reshape(11, 3)
        x.setflags(write=False)
        staging, counters = PadStaging(), CopyCounters()
        chunks = list(iter_padded_chunks({"x": x}, 11, 4,
                                         staging, counters))
        assert [v for v, _ in chunks] == [4, 4, 3]
        assert np.shares_memory(chunks[0][1]["x"], x)
        assert np.shares_memory(chunks[1][1]["x"], x)
        tail = chunks[2][1]["x"]
        assert not np.shares_memory(tail, x)
        assert tail.shape == (4, 3)
        np.testing.assert_array_equal(tail[:3], x[8:])
        np.testing.assert_array_equal(tail[3:], 0.0)
        assert counters.bytes_copied == 0
        assert counters.bytes_staged == x[8:].nbytes
        # second call, shorter tail: SAME buffer object, stale rows
        # from the previous tail re-zeroed
        y = np.ones((6, 3), np.float32)
        c2 = list(iter_padded_chunks({"x": y}, 6, 4, staging,
                                     CopyCounters()))
        assert c2[1][1]["x"] is tail  # persistent buffer reused
        np.testing.assert_array_equal(tail[:2], 1.0)
        np.testing.assert_array_equal(tail[2:], 0.0)

    def test_prefetch_degrades_once_with_warning(self, monkeypatch,
                                                 caplog):
        """A backend whose device_put can't place ahead of dispatch
        (NotImplementedError) degrades prefetch → host_async dispatch
        EXACTLY ONCE per run, with the documented warning exactly once
        per process; real runtime errors propagate instead."""
        import logging

        import sparkdl_tpu.runtime.runner as rmod

        monkeypatch.setattr(rmod, "_WARNED_REASONS", set())
        calls = []

        def no_async_put(v, *a, **k):
            calls.append(1)
            raise NotImplementedError("no async placement")

        monkeypatch.setattr(rmod.jax, "device_put", no_async_put)
        x = np.arange(36, dtype=np.float32).reshape(12, 3)
        with caplog.at_level(logging.WARNING,
                             logger="sparkdl_tpu.runtime.runner"):
            for _ in range(2):  # second run: no second warning
                r = BatchRunner(_double_fn(), batch_size=4,
                                strategy="prefetch")
                out = r.run({"input": x})["output"]
                np.testing.assert_allclose(out, x * 2.0)
        # one probe per run — after the first NotImplementedError the
        # run never retries device_put for its remaining chunks
        assert len(calls) == 2, calls
        warns = [r for r in caplog.records
                 if "prefetch degrades" in r.getMessage()]
        assert len(warns) == 1, caplog.records

    def test_prefetch_propagates_real_device_put_errors(self,
                                                        monkeypatch):
        """Only NotImplementedError means 'backend can't' — a genuine
        runtime failure inside device_put must surface, not silently
        degrade the strategy (the start_host_copies discipline)."""
        import sparkdl_tpu.runtime.runner as rmod

        def broken_put(v, *a, **k):
            raise RuntimeError("device OOM")

        monkeypatch.setattr(rmod.jax, "device_put", broken_put)
        r = BatchRunner(_double_fn(), batch_size=4,
                        strategy="prefetch")
        with pytest.raises(RuntimeError, match="device OOM"):
            r.run({"input": np.zeros((8, 3), np.float32)})

    def test_runner_pickles_without_lock_state(self):
        """Device stage closures holding a runner ship to Spark
        executors — the staging lock/buffers must drop on pickle and
        come back fresh (the RunnerMetrics discipline)."""
        cloudpickle = pytest.importorskip("cloudpickle")

        r = BatchRunner(_double_fn(), batch_size=4)
        x = np.arange(30, dtype=np.float32).reshape(10, 3)
        r.run({"input": x})  # warm staging so there IS state to drop
        r2 = cloudpickle.loads(cloudpickle.dumps(r))
        np.testing.assert_allclose(r2.run({"input": x})["output"],
                                   x * 2.0)

    def test_params_cache_purges_all_placements(self):
        """Reassigning .params purges every cached placement, not just
        the next-accessed key (regression: dead replicated copies held
        device memory)."""
        from sparkdl_tpu.parallel.mesh import make_mesh
        mf = ModelFunction.fromSingle(
            lambda p, x: x * p["s"], {"s": np.float32(2.0)},
            input_shape=(2,))
        mesh = make_mesh()
        mf.device_params()
        mf.replicated_params(mesh)
        assert len(mf._params_cache) == 2
        mf.params = {"s": np.float32(3.0)}
        mf.device_params()   # triggers purge of the stale replicated copy
        assert len(mf._params_cache) == 1
        np.testing.assert_allclose(
            np.asarray(mf.replicated_params(mesh)["s"]), 3.0)
