"""Worker for test_distributed_multiproc: one process of a 2-process
``jax.distributed`` CPU cluster (4 virtual devices each → 8 global).

Spawned with a sanitized environment (the parent strips the axon
sitecustomize and TPU tunnel vars) so jax initializes a plain CPU
backend; cross-process collectives ride Gloo. Prints one ``RESULT {...}``
JSON line the parent asserts on.
"""

import json
import sys


def build_image_frame(num_rows: int, num_partitions: int):
    """A deterministic image frame every process (and the test's
    reference run) can rebuild identically: row ``i`` carries a seeded
    32x32 uint8 image and key column ``x = i``."""
    import numpy as np
    import pyarrow as pa

    from sparkdl_tpu.data.frame import DataFrame
    from sparkdl_tpu.image import imageIO

    structs = []
    for i in range(num_rows):
        arr = np.random.default_rng(1000 + i).integers(
            0, 255, (32, 32, 3), dtype=np.uint8)
        structs.append(imageIO.imageArrayToStruct(arr, origin=str(i)))
    batch = imageIO.structsToBatch(
        structs, extra_columns={"x": pa.array(list(range(num_rows)))})
    return DataFrame.from_table(
        pa.Table.from_batches([batch]), num_partitions)


def featurize_rows(df):
    """(x, sum(features)) per row through DeepImageFeaturizer(TestNet)
    on the local-device mesh — multi-host DP inference is exactly
    'every host runs its shard on its own chips', no collectives."""
    import numpy as np

    from sparkdl_tpu.transformers.named_image import DeepImageFeaturizer

    out = DeepImageFeaturizer(modelName="TestNet", inputCol="image",
                              outputCol="f", useMesh=True).transform(df)
    table = out.collect()
    xs = table.column("x").to_pylist()
    sums = [float(np.sum(v)) for v in table.column("f").to_pylist()]
    return sorted(zip(xs, sums))


def main() -> None:
    pid = int(sys.argv[1])
    port = sys.argv[2]
    num_partitions = int(sys.argv[3])

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparkdl_tpu.parallel import distributed as dist
    from sparkdl_tpu.parallel.mesh import DATA_AXIS, MeshSpec

    # Explicit join (the TPU-pod path auto-detects; tests pass params).
    dist.initialize(coordinator_address=f"127.0.0.1:{port}",
                    num_processes=2, process_id=pid)
    info = dist.host_info()

    # Global-mesh psum: every process contributes its local shard of a
    # global ("data",)-sharded array; the jitted sum needs a
    # cross-process collective (Gloo here, ICI/DCN on a pod).
    mesh = dist.global_mesh(MeshSpec(data=-1, model=1))
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    local = np.arange(info.local_device_count, dtype=np.float64) + 10 * pid
    garr = jax.make_array_from_process_local_data(
        sharding, local, (info.global_device_count,))
    total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(garr)

    # host_shard_dataframe end-to-end: each host materializes only its
    # own partitions of the same logical frame.
    from sparkdl_tpu.data.frame import DataFrame
    rows = [{"x": i} for i in range(4 * num_partitions - 1)]
    df = DataFrame.from_pylist(rows, num_partitions=num_partitions)
    mine = dist.host_shard_dataframe(df)
    xs = sorted(r["x"] for r in mine.collect_rows())

    # one full DP train step over the GLOBAL mesh: per-process local
    # batch shards assemble into one global batch; the gradient
    # all-reduce crosses processes (both must see the same loss)
    import optax

    from sparkdl_tpu.models.testnet import TestNet
    from sparkdl_tpu.models.zoo import getKerasApplicationModel
    from sparkdl_tpu.parallel.train import (
        create_train_state,
        make_train_step,
        shard_train_step,
    )

    spec = getKerasApplicationModel("TestNet")
    module = TestNet()
    x0 = spec.preprocess(jnp.zeros((1, 32, 32, 3), jnp.uint8))
    variables = module.init(jax.random.PRNGKey(0), x0)
    state = create_train_state(module, variables, optax.sgd(1e-2, 0.9))
    train_step = make_train_step(module, spec.preprocess,
                                 num_classes=spec.num_classes)
    jitted, state = shard_train_step(train_step, mesh, state)

    per_proc = 2 * info.local_device_count
    brng = np.random.default_rng(pid)
    imgs = brng.integers(0, 255, (per_proc, 32, 32, 3), np.uint8)
    labels = ((np.arange(per_proc) + pid)
              % spec.num_classes).astype(np.int32)
    gb = 2 * info.global_device_count
    batch = {
        "image": jax.make_array_from_process_local_data(
            NamedSharding(mesh, P(DATA_AXIS)), imgs, (gb, 32, 32, 3)),
        "label": jax.make_array_from_process_local_data(
            NamedSharding(mesh, P(DATA_AXIS)), labels, (gb,)),
    }
    state, metrics = jitted(state, batch)
    train_loss = float(metrics["loss"])

    # multi-host DP inference: featurize ONLY this host's shard of a
    # shared logical frame on this host's local mesh
    img_df = build_image_frame(4 * num_partitions - 1, num_partitions)
    feats = featurize_rows(dist.host_shard_dataframe(img_df))

    print("RESULT " + json.dumps({
        "pid": pid,
        "process_count": info.process_count,
        "local_devices": info.local_device_count,
        "global_devices": info.global_device_count,
        "shard_indices": dist.host_shard_indices(num_partitions),
        "psum_total": float(total),
        "rows": xs,
        "train_loss": train_loss,
        "features": feats,
    }), flush=True)


if __name__ == "__main__":
    main()
