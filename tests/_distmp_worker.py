"""Worker for test_distributed_multiproc: one process of a 2-process
``jax.distributed`` CPU cluster (4 virtual devices each → 8 global).

Spawned with a sanitized environment (the parent strips the axon
sitecustomize and TPU tunnel vars) so jax initializes a plain CPU
backend; cross-process collectives ride Gloo. Prints one ``RESULT {...}``
JSON line the parent asserts on.
"""

import json
import sys


def main() -> None:
    pid = int(sys.argv[1])
    port = sys.argv[2]
    num_partitions = int(sys.argv[3])

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparkdl_tpu.parallel import distributed as dist
    from sparkdl_tpu.parallel.mesh import DATA_AXIS, MeshSpec

    # Explicit join (the TPU-pod path auto-detects; tests pass params).
    dist.initialize(coordinator_address=f"127.0.0.1:{port}",
                    num_processes=2, process_id=pid)
    info = dist.host_info()

    # Global-mesh psum: every process contributes its local shard of a
    # global ("data",)-sharded array; the jitted sum needs a
    # cross-process collective (Gloo here, ICI/DCN on a pod).
    mesh = dist.global_mesh(MeshSpec(data=-1, model=1))
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    local = np.arange(info.local_device_count, dtype=np.float64) + 10 * pid
    garr = jax.make_array_from_process_local_data(
        sharding, local, (info.global_device_count,))
    total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(garr)

    # host_shard_dataframe end-to-end: each host materializes only its
    # own partitions of the same logical frame.
    from sparkdl_tpu.data.frame import DataFrame
    rows = [{"x": i} for i in range(4 * num_partitions - 1)]
    df = DataFrame.from_pylist(rows, num_partitions=num_partitions)
    mine = dist.host_shard_dataframe(df)
    xs = sorted(r["x"] for r in mine.collect_rows())

    print("RESULT " + json.dumps({
        "pid": pid,
        "process_count": info.process_count,
        "local_devices": info.local_device_count,
        "global_devices": info.global_device_count,
        "shard_indices": dist.host_shard_indices(num_partitions),
        "psum_total": float(total),
        "rows": xs,
    }), flush=True)


if __name__ == "__main__":
    main()
