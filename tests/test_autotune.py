"""Closed-loop infeed autotuner tests (docs/PERFORMANCE.md).

The contract under test:

* depth-N prefetch — ``dispatch_chunks`` keeps up to ``prefetch_depth``
  chunks ``device_put`` ahead of the dispatching one (bounded
  look-ahead), outputs identical across depths, the
  prefetch→host_async degrade ladder preserved at any depth;
* controller hysteresis — bounded single-step applies, cooldown after
  every change, a quick direction flip is REFUSED and counted as an
  oscillation, clamped proposals count clamps, trial reverts bypass
  cooldown;
* targets — RunnerTarget deepens overlap while transfer waits
  dominate and reverts-and-freezes a trial that didn't pay;
  ServeTarget shrinks a saturated coalesce window / grows an
  underfilled one inside its p99 budget; RechunkTarget moves only
  along its pre-warmed ladder with ZERO cold retraces
  (trace-count-pinned);
* live apply points — the engine's re-chunk cut follows a
  ``LiveBatchHint`` mid-stream with row identity and order exact
  (the satellite the autotuner's engine knob rides on);
* disarmed regime — ``poll()`` is a single armed-check, pinned <10µs
  alongside the tracer bound;
* observability — decisions/oscillations/clamps in the registry,
  controller state in flight bundles, pickle discipline.
"""

import logging
import time

import numpy as np
import pyarrow as pa
import pytest

import sparkdl_tpu.runtime.runner as rmod
from sparkdl_tpu.autotune import (
    AutotuneController,
    Knob,
    Proposal,
    RechunkTarget,
    RunnerTarget,
    ServeTarget,
    controller,
    poll,
)
from sparkdl_tpu.data import DataFrame
from sparkdl_tpu.data.frame import LiveBatchHint
from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.obs import default_registry
from sparkdl_tpu.runtime.runner import (
    BatchRunner,
    RunnerMetrics,
    SlabSink,
    dispatch_chunks,
)
from sparkdl_tpu.serve import ModelServer, ServeConfig
from sparkdl_tpu.serve.metrics import ServeMetrics


def _double_fn(shape=(3,)):
    return ModelFunction.fromSingle(lambda x: x * 2.0, None,
                                    input_shape=shape)


def _ctl(**over) -> AutotuneController:
    """A standalone armed controller with no warmup window (tests
    drive deterministic step sequences)."""
    c = AutotuneController(interval_s=0.0)
    c.arm()
    c.warmup_steps = over.pop("warmup_steps", 0)
    for k, v in over.items():
        setattr(c, k, v)
    return c


# ---------------------------------------------------------------------------
# depth-N prefetch in dispatch_chunks


class TestDepthNPrefetch:
    def test_lookahead_runs_depth_chunks_ahead(self, monkeypatch):
        """White-box ordering pin: with prefetch_depth=3 the first
        three chunks are placed BEFORE the first dispatch, and the
        look-ahead stays ≥1 / ≤depth ahead until the generator dries
        up — the bounded-queue semantics the tentpole names."""
        events = []

        def fake_place(chunk, sharding=None, interleave=0):
            events.append(("place", chunk["i"]))
            return chunk

        monkeypatch.setattr(rmod, "start_device_prefetch", fake_place)

        def fn(params, chunk):
            events.append(("dispatch", chunk["i"]))
            return {"y": np.full((4, 2), chunk["i"], np.float32)}

        chunks = iter((4, {"i": i, "x": np.zeros((4, 2), np.float32)})
                      for i in range(6))
        sink = SlabSink(24)
        n = dispatch_chunks(fn, None, chunks, "prefetch", 8, sink,
                            prefetch_depth=3)
        assert n == 6
        out = sink.result()["y"]
        np.testing.assert_array_equal(out[:, 0],
                                      np.repeat(np.arange(6.0), 4))
        # chunks 0..2 placed before anything dispatched (depth 3)
        assert events[:4] == [("place", 0), ("place", 1), ("place", 2),
                              ("dispatch", 0)]
        # every chunk was placed exactly once, none dispatched before
        # its own placement
        placed_at = {i: events.index(("place", i)) for i in range(6)}
        for i in range(6):
            assert placed_at[i] < events.index(("dispatch", i))

    def test_outputs_identical_across_depths(self):
        mf = _double_fn()
        x = np.arange(60, dtype=np.float32).reshape(20, 3)
        expect = x * 2.0
        for depth in (1, 2, 4, 8):
            r = BatchRunner(mf, batch_size=4, strategy="prefetch",
                            prefetch_depth=depth)
            np.testing.assert_allclose(r.run({"input": x})["output"],
                                       expect)

    def test_degrade_ladder_preserved_at_depth(self, monkeypatch,
                                               caplog):
        """A backend that cannot place ahead degrades prefetch →
        host_async dispatch at ANY depth: one probe per run, outputs
        exact, and the once-per-process-per-reason warning."""
        monkeypatch.setattr(rmod, "_WARNED_REASONS", set())
        calls = []

        def no_async_put(v, *a, **k):
            calls.append(1)
            raise NotImplementedError("no async placement")

        monkeypatch.setattr(rmod.jax, "device_put", no_async_put)
        x = np.arange(36, dtype=np.float32).reshape(12, 3)
        with caplog.at_level(logging.WARNING,
                             logger="sparkdl_tpu.runtime.runner"):
            for _ in range(2):
                r = BatchRunner(_double_fn(), batch_size=4,
                                strategy="prefetch", prefetch_depth=4)
                np.testing.assert_allclose(
                    r.run({"input": x})["output"], x * 2.0)
        assert len(calls) == 2, calls   # one probe per run, any depth
        warns = [rec for rec in caplog.records
                 if "prefetch degrades" in rec.getMessage()]
        assert len(warns) == 1, caplog.records

    def test_depth_resolution_ctor_env_default(self, monkeypatch):
        mf = _double_fn()
        monkeypatch.delenv("SPARKDL_TPU_PREFETCH_DEPTH", raising=False)
        assert BatchRunner(mf).prefetch_depth == 1
        assert BatchRunner(mf, prefetch_depth=5).prefetch_depth == 5
        monkeypatch.setenv("SPARKDL_TPU_PREFETCH_DEPTH", "4")
        assert BatchRunner(mf).prefetch_depth == 4
        assert BatchRunner(mf, prefetch_depth=2).prefetch_depth == 2
        monkeypatch.setenv("SPARKDL_TPU_PREFETCH_DEPTH", "nope")
        with pytest.raises(ValueError, match="PREFETCH_DEPTH"):
            BatchRunner(mf)
        with pytest.raises(ValueError, match=">= 1"):
            BatchRunner(mf, prefetch_depth=0)

    def test_warn_once_dedupes_per_reason(self, monkeypatch, caplog):
        monkeypatch.setattr(rmod, "_WARNED_REASONS", set())
        with caplog.at_level(logging.WARNING,
                             logger="sparkdl_tpu.runtime.runner"):
            rmod.warn_once("r1", "first %s", "reason")
            rmod.warn_once("r1", "first %s", "again")
            rmod.warn_once("r2", "second reason")
        msgs = [r.getMessage() for r in caplog.records]
        assert msgs == ["first reason", "second reason"]


# ---------------------------------------------------------------------------
# controller core


class _BoxTarget:
    """A scriptable target: pops one proposal list per step."""

    def __init__(self, lo=0, hi=10, start=5):
        self.name = "box"
        self.box = {"v": start}
        self.knob = Knob("v", lambda: self.box["v"],
                         lambda x: self.box.__setitem__("v", x),
                         lo, hi)
        self.script = []

    def knobs(self):
        return [self.knob]

    def propose(self, warming):
        return self.script.pop(0) if self.script else []

    def describe(self):
        return {"name": self.name, "knobs": [self.knob.describe()]}


class TestControllerCore:
    def test_apply_cooldown_and_counters(self):
        ctl = _ctl()
        t = ctl.attach(_BoxTarget())
        t.script = [[Proposal(t.knob, 6, "up")],
                    [Proposal(t.knob, 7, "up again")]]
        ctl.step()
        assert t.box["v"] == 6 and ctl.decisions_applied == 1
        ctl.step()     # cooldown: the second proposal is held
        assert t.box["v"] == 6 and ctl.decisions_applied == 1
        snap = default_registry().snapshot()
        assert snap.get("autotune.knob.box.v") == 6.0

    def test_quick_direction_flip_is_refused_and_counted(self):
        ctl = _ctl()
        t = ctl.attach(_BoxTarget())
        t.script = [[Proposal(t.knob, 6, "up")], [], [],
                    [Proposal(t.knob, 5, "down")]]
        before = default_registry().counter(
            "autotune.oscillations").value
        for _ in range(4):
            ctl.step()
        # the flip at step 4 (3 steps after the up) is hunting: refused
        assert t.box["v"] == 6
        assert ctl.oscillations == 1
        assert default_registry().counter(
            "autotune.oscillations").value == before + 1
        assert t.knob.frozen_for > 0

    def test_slow_reversal_is_legitimate_control(self):
        ctl = _ctl()
        t = ctl.attach(_BoxTarget())
        t.script = [[Proposal(t.knob, 6, "up")], [], [], [], [],
                    [Proposal(t.knob, 5, "down")]]
        for _ in range(6):
            ctl.step()
        assert t.box["v"] == 5          # reversal outside osc_window
        assert ctl.oscillations == 0

    def test_clamps_counted_and_bounds_hold(self):
        ctl = _ctl()
        t = ctl.attach(_BoxTarget(lo=0, hi=10, start=5))
        t.script = [[Proposal(t.knob, 20, "way up")], [], [],
                    [Proposal(t.knob, 15, "still past the bound")]]
        for _ in range(4):
            ctl.step()
        assert t.box["v"] == 10         # clamped apply
        assert ctl.clamps == 2          # moved-clamp + held-clamp
        assert ctl.decisions_applied == 1

    def test_force_revert_bypasses_cooldown(self):
        ctl = _ctl()
        t = ctl.attach(_BoxTarget())
        t.script = [[Proposal(t.knob, 6, "up")],
                    [Proposal(t.knob, 5, "revert", force=True)]]
        ctl.step()
        ctl.step()
        assert t.box["v"] == 5
        assert ctl.oscillations == 0    # reverts never count

    def test_warmup_steps_measure_only(self):
        ctl = _ctl(warmup_steps=2)
        seen = []

        class _T(_BoxTarget):
            def propose(self, warming):
                seen.append(warming)
                return ([] if warming
                        else [Proposal(self.knob, 6, "up")])

        t = ctl.attach(_T())
        for _ in range(3):
            ctl.step()
        assert seen == [True, True, False]
        assert t.box["v"] == 6

    def test_interval_paces_poll_driven_steps(self):
        ctl = AutotuneController(interval_s=3600.0)
        ctl.arm()
        ctl.attach(_BoxTarget())
        ctl.maybe_step()
        ctl.maybe_step()
        assert ctl.steps == 1           # second poll inside interval

    def test_broken_target_is_skipped_loudly(self, caplog):
        ctl = _ctl()

        class _Boom:
            name = "boom"

            def knobs(self):
                return []

            def propose(self, warming):
                raise RuntimeError("target bug")

            def describe(self):
                return {"name": "boom"}

        ctl.attach(_Boom())
        good = ctl.attach(_BoxTarget())
        good.script = [[Proposal(good.knob, 6, "up")]]
        with caplog.at_level(logging.ERROR):
            ctl.step()
        assert good.box["v"] == 6       # the healthy target still ran
        assert any("propose failed" in r.getMessage()
                   for r in caplog.records)

    def test_disarmed_poll_is_noop(self, monkeypatch):
        monkeypatch.delenv("SPARKDL_TPU_AUTOTUNE", raising=False)
        ctl = controller()
        monkeypatch.setattr(ctl, "_armed_override", None)
        steps = ctl.steps
        for _ in range(50):
            poll()
        assert ctl.steps == steps

    def test_disarmed_poll_overhead(self, monkeypatch):
        """The shared-no-op contract alongside the tracer bound: the
        hot-loop hook must cost well under 10 µs disarmed (min over
        repeats — noise only ever adds time)."""
        monkeypatch.delenv("SPARKDL_TPU_AUTOTUNE", raising=False)
        monkeypatch.setattr(controller(), "_armed_override", None)
        n = 20_000
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(n):
                poll()
            best = min(best, (time.perf_counter() - t0) / n)
        assert best < 10e-6, f"disarmed poll costs {best * 1e6:.2f} µs"

    def test_env_arming_and_override(self, monkeypatch):
        ctl = AutotuneController()
        monkeypatch.delenv("SPARKDL_TPU_AUTOTUNE", raising=False)
        assert not ctl.armed
        monkeypatch.setenv("SPARKDL_TPU_AUTOTUNE", "1")
        assert ctl.armed
        ctl.disarm()
        assert not ctl.armed            # override beats the env
        ctl.arm_from_env()
        assert ctl.armed
        monkeypatch.setenv("SPARKDL_TPU_AUTOTUNE_INTERVAL_S", "bogus")
        import importlib
        cmod = importlib.import_module("sparkdl_tpu.autotune.core")
        monkeypatch.setattr(cmod, "_env_interval_cache", None)
        assert ctl.interval_s == cmod.DEFAULT_INTERVAL_S  # typo degrades

    def test_controller_pickles_without_lock_or_targets(self):
        import cloudpickle

        ctl = _ctl()
        ctl.attach(_BoxTarget())
        clone = cloudpickle.loads(cloudpickle.dumps(ctl))
        assert clone.armed
        assert clone.targets() == []    # live handles are process-local
        clone.step()                    # fresh locks work


# ---------------------------------------------------------------------------
# RunnerTarget


class _StubRunner:
    def __init__(self, strategy="prefetch", max_inflight=8,
                 prefetch_depth=1):
        self.strategy = strategy
        self.max_inflight = max_inflight
        self.prefetch_depth = prefetch_depth
        self.batch_size = 8
        self.metrics = RunnerMetrics()


class TestRunnerTarget:
    def test_deepens_prefetch_while_transfer_wait_dominates(self):
        ctl = _ctl()
        r = _StubRunner()
        ctl.attach(RunnerTarget(r))
        r.metrics.add(1000, 10, 1.0, transfer_wait_seconds=0.5)
        ctl.step()                      # baseline window
        r.metrics.add(1000, 10, 1.0, transfer_wait_seconds=0.5)
        ctl.step()                      # wait_frac 0.5 → trial up
        assert r.prefetch_depth == 2
        assert ctl.decisions_applied == 1

    def test_trial_without_gain_reverts_and_freezes(self):
        ctl = _ctl()
        r = _StubRunner()
        t = ctl.attach(RunnerTarget(r))
        r.metrics.add(1000, 10, 1.0, transfer_wait_seconds=0.5)
        ctl.step()
        r.metrics.add(1000, 10, 1.0, transfer_wait_seconds=0.5)
        ctl.step()                      # trial: depth 1 → 2
        assert r.prefetch_depth == 2
        r.metrics.add(1000, 10, 1.0, transfer_wait_seconds=0.5)
        ctl.step()                      # same tput → no gain → revert
        assert r.prefetch_depth == 1
        assert t._depth.frozen_for > 0
        # frozen: the same signal no longer moves the knob
        r.metrics.add(1000, 10, 1.0, transfer_wait_seconds=0.5)
        ctl.step()
        assert r.prefetch_depth == 1
        assert ctl.oscillations == 0    # the revert is not hunting

    def test_trial_with_gain_is_kept(self):
        ctl = _ctl()
        r = _StubRunner()
        ctl.attach(RunnerTarget(r))
        r.metrics.add(1000, 10, 1.0, transfer_wait_seconds=0.5)
        ctl.step()
        r.metrics.add(1000, 10, 1.0, transfer_wait_seconds=0.5)
        ctl.step()                      # trial up
        r.metrics.add(2000, 20, 1.0, transfer_wait_seconds=0.5)
        ctl.step()                      # 2x tput → kept
        assert r.prefetch_depth == 2

    def test_non_prefetch_strategy_tunes_inflight(self):
        ctl = _ctl()
        r = _StubRunner(strategy="host_async")
        ctl.attach(RunnerTarget(r))
        r.metrics.add(1000, 10, 1.0, transfer_wait_seconds=0.5)
        ctl.step()
        r.metrics.add(1000, 10, 1.0, transfer_wait_seconds=0.5)
        ctl.step()
        assert r.max_inflight == 9 and r.prefetch_depth == 1

    def test_backpressure_sheds_one_step(self):
        ctl = _ctl()
        r = _StubRunner(prefetch_depth=4)
        ctl.attach(RunnerTarget(r))
        r.metrics.add(1000, 10, 1.0)
        ctl.step()
        default_registry().counter("ship.prefetch_degrade_events").add()
        r.metrics.add(1000, 10, 1.0)
        ctl.step()
        assert r.prefetch_depth == 3    # shed toward the floor

    def test_permanent_degrade_never_walks_inflight_down(self):
        """A backend that degrades EVERY window (the re-probe-per-run
        shape) sheds depth to its floor and stops — max_inflight is
        never shed on degrades, and the wait_frac signal can still
        RAISE it (armed must not be worse than disarmed on a degraded
        backend)."""
        ctl = _ctl()
        r = _StubRunner(strategy="prefetch", max_inflight=8,
                        prefetch_depth=2)
        ctl.attach(RunnerTarget(r))
        deg = default_registry().counter("ship.prefetch_degrade_events")
        r.metrics.add(1000, 10, 1.0, transfer_wait_seconds=0.5)
        ctl.step()
        for _ in range(8):
            deg.add()                   # a degrade event every window
            r.metrics.add(1000, 10, 1.0, transfer_wait_seconds=0.5)
            ctl.step()
        assert r.prefetch_depth == 1    # shed to the floor, then held
        assert r.max_inflight >= 8, \
            "degrade events must never walk the result queue down"

    def test_host_copy_degrades_do_not_touch_the_depth_knob(self):
        """The mixed ship.degrade_events total also counts missing
        copy_to_host_async — which says nothing about look-ahead. Only
        the placement-specific counter may shed depth or block its
        up-trials (a backend whose device_put works must keep tuning
        depth while host copies degrade every run)."""
        ctl = _ctl()
        r = _StubRunner(strategy="prefetch", prefetch_depth=2)
        ctl.attach(RunnerTarget(r))
        deg = default_registry().counter("ship.degrade_events")
        r.metrics.add(1000, 10, 1.0, transfer_wait_seconds=0.5)
        ctl.step()
        deg.add()                       # host-copy degrade, per run
        r.metrics.add(1000, 10, 1.0, transfer_wait_seconds=0.5)
        ctl.step()
        assert r.prefetch_depth == 3, \
            "a host-copy degrade must not disable depth tuning"

    def test_low_wait_holds_instead_of_hunting(self):
        """Idle queue slots are not a signal: a window with negligible
        transfer wait and no backpressure moves NOTHING (lowering on
        'unused' depth is how static experts oscillate)."""
        ctl = _ctl()
        r = _StubRunner(max_inflight=8, prefetch_depth=4)
        ctl.attach(RunnerTarget(r))
        for _ in range(4):
            r.metrics.add(1000, 10, 1.0, transfer_wait_seconds=0.001)
            ctl.step()
        assert (r.max_inflight, r.prefetch_depth) == (8, 4)
        assert ctl.decisions_applied == 0


# ---------------------------------------------------------------------------
# ServeTarget


class _StubSession:
    def __init__(self, max_wait_s=0.002, default_deadline_s=None):
        self.name = "m"
        self.max_wait_s = max_wait_s
        self.metrics = ServeMetrics()
        self.config = ServeConfig(max_wait_s=max_wait_s,
                                  default_deadline_s=default_deadline_s)


class TestServeTarget:
    def _window(self, s, valid, cap, n=4):
        for _ in range(n):
            s.metrics.add_batch(valid, cap)

    def test_saturated_fill_shrinks_the_window(self):
        ctl = _ctl()
        s = _StubSession(max_wait_s=0.008)
        ctl.attach(ServeTarget(s))
        self._window(s, 8, 8)
        ctl.step()                      # baseline
        self._window(s, 8, 8)
        ctl.step()
        assert s.max_wait_s == pytest.approx(0.004)

    def test_poor_fill_grows_the_window(self):
        ctl = _ctl()
        s = _StubSession(max_wait_s=0.002)
        ctl.attach(ServeTarget(s))
        self._window(s, 2, 8)
        ctl.step()
        self._window(s, 2, 8)
        ctl.step()
        assert s.max_wait_s == pytest.approx(0.003)

    def test_deadband_holds(self):
        ctl = _ctl()
        s = _StubSession(max_wait_s=0.002)
        ctl.attach(ServeTarget(s))
        for _ in range(3):
            self._window(s, 6, 8)       # fill 0.75: inside the band
            ctl.step()
        assert s.max_wait_s == pytest.approx(0.002)
        assert ctl.decisions_applied == 0

    def test_p99_budget_blocks_growth(self):
        ctl = _ctl()
        s = _StubSession(max_wait_s=0.002, default_deadline_s=0.1)
        for _ in range(10):
            s.metrics.observe_latency(0.0499)
        ctl.attach(ServeTarget(s))
        self._window(s, 2, 8)
        ctl.step()
        self._window(s, 2, 8)
        ctl.step()                      # p99 + growth > budget/2
        assert s.max_wait_s == pytest.approx(0.002)

    def test_live_session_knob_reaches_the_dispatcher(self):
        """End-to-end: a ServeTarget shrink on a REAL session changes
        what the dispatcher passes to collect(), and /statusz reports
        the live value, not the frozen config."""
        mf = _double_fn()
        server = ModelServer(ServeConfig(max_wait_s=0.008))
        server.register("m", mf, batch_size=4, prefetch_depth=2)
        session = server.session()
        assert session.runner.prefetch_depth == 2
        ctl = _ctl()
        ctl.attach(ServeTarget(session))
        self._window(session, 4, 4)
        ctl.step()
        self._window(session, 4, 4)
        ctl.step()
        assert session.max_wait_s == pytest.approx(0.004)
        st = server.telemetry_status()
        assert st["models"]["m"]["max_wait_s"] == pytest.approx(0.004)
        assert st["models"]["m"]["runner"]["prefetch_depth"] == 2
        out = server.submit(
            {"input": np.ones((2, 3), np.float32)}).result(timeout=30)
        np.testing.assert_allclose(out["output"], 2.0)
        server.close()


# ---------------------------------------------------------------------------
# RechunkTarget: the pre-warmed shape ladder


class TestRechunkTarget:
    def test_prewarm_traces_every_rung_then_zero_retraces(self):
        """THE ladder contract: prewarm compiles each rung once (the
        jit traces the Python fn once per shape — count those calls);
        afterwards rung moves and real runs at any warmed rung perform
        ZERO new traces."""
        traces = []

        def fn(x):
            traces.append(np.shape(x))
            return x * 2.0

        mf = ModelFunction.fromSingle(fn, None, input_shape=(3,))
        r = BatchRunner(mf, batch_size=4)
        t = RechunkTarget(r, ladder=(2, 4, 8))
        warmed = t.prewarm()
        assert warmed == 3
        assert len(traces) == 3         # one per rung
        assert t.prewarm() == 0         # idempotent
        for rung in (0, 2, 1):
            t._rung.set(rung)
            x = np.ones((10, 3), np.float32)
            np.testing.assert_allclose(r.run({"input": x})["output"],
                                       2.0)
        assert len(traces) == 3, "a rung move cold-retraced"

    def test_padding_tax_steps_the_ladder_down(self):
        ctl = _ctl()
        traces = []

        def fn(x):
            traces.append(np.shape(x))
            return x * 2.0

        mf = ModelFunction.fromSingle(fn, None, input_shape=(3,))
        r = BatchRunner(mf, batch_size=8)
        t = ctl.attach(RechunkTarget(r, ladder=(4, 8)))
        t.prewarm()
        n_warm = len(traces)
        x = np.ones((2, 3), np.float32)     # fill 2/8 < 0.5
        r.run({"input": x})
        ctl.step()                          # baseline window
        r.run({"input": x})
        ctl.step()                          # fill 0.25 → step down
        assert r.batch_size == 4
        r.run({"input": x})                 # runs at the new rung
        assert len(traces) == n_warm, "the down-rung cold-retraced"

    def test_prewarm_never_touches_the_live_batch_size(self):
        """Prewarm compiles rungs through the jit cache directly — a
        concurrent run() on another thread must never observe a
        transient rung. The traced fn itself asserts the live knob is
        untouched at every compile."""
        observed = []

        r_box = {}

        def fn(x):
            observed.append(r_box["r"].batch_size)
            return x * 2.0

        mf = ModelFunction.fromSingle(fn, None, input_shape=(3,))
        r = BatchRunner(mf, batch_size=4)
        r_box["r"] = r
        t = RechunkTarget(r, ladder=(2, 4, 8))
        assert t.prewarm() == 3
        assert observed == [4, 4, 4], observed
        assert r.batch_size == 4

    def test_attach_while_armed_prewarns_on_the_setup_thread(self):
        """controller().attach runs the ladder compile immediately
        (the on_attach hook) so it never lands inside a hot loop's
        first controller step."""
        traces = []
        mf = ModelFunction.fromSingle(
            lambda x: (traces.append(1), x * 2.0)[1], None,
            input_shape=(3,))
        r = BatchRunner(mf, batch_size=4)
        ctl = _ctl()
        t = ctl.attach(RechunkTarget(r, ladder=(2, 4)))
        assert t.warmed and len(traces) == 2

    def test_off_ladder_batch_size_rejected_at_ctor(self):
        r = BatchRunner(_double_fn(), batch_size=6)
        with pytest.raises(ValueError, match="ladder"):
            RechunkTarget(r, ladder=(4, 8))


# ---------------------------------------------------------------------------
# mid-stream hint changes through the engine (the apply point)


class _Chunky:
    """A preferred_chunk carrier for LiveBatchHint (stands in for the
    runner whose batch_size the controller moves)."""

    def __init__(self, n):
        self.batch_size = n

    @property
    def preferred_chunk(self):
        return self.batch_size


class TestMidStreamHintChange:
    def test_live_hint_moves_between_blocks_rows_exact(self):
        """The satellite pin: when the hint moves between blocks the
        partition-spanning re-slice stays row-exact and ordered — and
        the cut actually follows the new hint."""
        chunky = _Chunky(8)
        hint = LiveBatchHint(chunky)
        assert int(hint) == 8 and bool(hint)
        seen = []

        def fn(batch):
            seen.append(batch.num_rows)
            if len(seen) == 1:
                chunky.batch_size = 4   # the controller's apply point
            return batch

        ids = np.arange(30)
        df = DataFrame.from_table(pa.table({"id": ids}), 6)
        out = df.map_batches(fn, kind="device", name="dev",
                             batch_hint=hint).collect()
        np.testing.assert_array_equal(
            out.column("id").to_numpy(zero_copy_only=False), ids)
        # the first cut honored hint 8; later cuts honored hint 4
        assert seen[0] == 8, seen
        assert any(n == 4 for n in seen[1:]), seen
        # every dispatched block after the move is ≤ the larger hint
        assert sum(seen) == 30

    def test_hint_shrink_and_regrow_stays_ordered(self):
        """Hint moves in BOTH directions mid-stream (shrink then grow
        back) keep row order across partition-spanning blocks."""
        chunky = _Chunky(6)
        seen = []

        def fn(batch):
            seen.append(batch.num_rows)
            if len(seen) == 1:
                chunky.batch_size = 3
            elif len(seen) == 3:
                chunky.batch_size = 12
            return batch

        ids = np.arange(40)
        df = DataFrame.from_table(pa.table({"id": ids}), 8)
        out = df.map_batches(fn, kind="device", name="dev",
                             batch_hint=LiveBatchHint(chunky)).collect()
        np.testing.assert_array_equal(
            out.column("id").to_numpy(zero_copy_only=False), ids)
        assert sum(seen) == 40

    def test_live_hint_pickles_with_its_runner(self):
        import cloudpickle

        hint = LiveBatchHint(_Chunky(16))
        clone = cloudpickle.loads(cloudpickle.dumps(hint))
        assert int(clone) == 16

    def test_tensor_transformer_publishes_live_hint(self):
        """The production path: TensorTransformer's device stage hint
        follows the runner's batch size live."""
        from sparkdl_tpu.transformers.tensor_transform import (
            TensorTransformer,
        )

        mf = _double_fn((4,))
        t = TensorTransformer(modelFunction=mf,
                              inputMapping={"x": "input"},
                              outputMapping={"output": "y"},
                              batchSize=8)
        x = np.ones((12, 4), np.float32)
        df = DataFrame.from_table(pa.table({"i": np.arange(12)}), 2) \
            .with_column("x", lambda b, x=x: x[:b.num_rows])
        plan_df = t.transform(df)
        stage = next(st for st in plan_df._plan if st.kind == "device")
        assert isinstance(stage.batch_hint, LiveBatchHint)
        assert int(stage.batch_hint) == 8
        out = plan_df.collect()
        assert out.num_rows == 12


# ---------------------------------------------------------------------------
# observability plumbing


class TestObservability:
    def test_flight_bundle_carries_controller_state(self):
        from sparkdl_tpu.obs.flight import FlightRecorder

        ctl = controller()
        try:
            ctl.attach(_BoxTarget())
            bundle = FlightRecorder().bundle(reason="test")
            at = bundle["autotune"]
            assert "armed" in at and "decisions" in at
            assert any(t.get("name") == "box" for t in at["targets"])
        finally:
            ctl.reset()

    def test_apply_lands_on_the_autotune_lane(self):
        from sparkdl_tpu.obs import Tracer

        t = Tracer(capacity=64)
        ctl = _ctl()
        box = ctl.attach(_BoxTarget())
        box.script = [[Proposal(box.knob, 6, "up")]]
        import importlib
        cmod = importlib.import_module("sparkdl_tpu.autotune.core")
        real_span = cmod.span

        def spy_span(name, lane="host", **attrs):
            return t.span(name, lane=lane, **attrs)

        cmod.span = spy_span
        try:
            t.arm()
            ctl.step()
        finally:
            cmod.span = real_span
        lanes = {s.lane for s in t.spans()}
        names = {s.name for s in t.spans()}
        assert lanes == {"autotune"}
        assert {"autotune.step", "autotune.apply"} <= names

    def test_state_reports_knobs_and_counters(self):
        ctl = _ctl()
        box = ctl.attach(_BoxTarget())
        box.script = [[Proposal(box.knob, 6, "up")]]
        ctl.step()
        st = ctl.state()
        assert st["decisions"] == 1 and st["oscillations"] == 0
        (tgt,) = st["targets"]
        assert tgt["knobs"][0]["value"] == 6
