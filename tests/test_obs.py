"""Unified pipeline tracing (sparkdl_tpu/obs): span tracer, metrics
registry, Perfetto export, instrumentation, lint + pickle discipline.

The contracts pinned here, in ISSUE order: a disarmed tracer is a
true no-op (no ring growth, per-call cost far under 1% of a tight
stage call), an armed 2-thread concurrent transform yields properly
nested same-thread spans and a valid Perfetto export, the
collective-launch counters move under racing fitMultiple trials, the
ring buffer caps with a visible drop counter, arming introduces zero
new unsuppressed lint findings, and tracer/registry survive
cloudpickle with remote-side spans staying remote."""

import json
import threading
import time

import numpy as np
import pytest

from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.obs import (
    MetricsRegistry,
    Tracer,
    default_registry,
    span,
    tracer,
)
from sparkdl_tpu.obs.report import load_events, summarize
from sparkdl_tpu.runtime.runner import BatchRunner, RunnerMetrics

# fixtures reused from the estimator suite (tiny keras model + the
# brightness-labeled image frame); `tests` resolves as a namespace
# package from the repo root
from tests.test_estimators import (  # noqa: F401
    keras_cls_file,
    uri_label_df,
)


def _mf(width=3):
    return ModelFunction.fromSingle(lambda x: x * 2.0, None,
                                    input_shape=(width,))


@pytest.fixture()
def armed_tracer(monkeypatch):
    """The global tracer, armed via the env (as production would) and
    cleared before/after so tests don't see each other's spans."""
    t = tracer()
    monkeypatch.setenv("SPARKDL_TPU_TRACE", "1")
    t.clear()
    yield t
    t.clear()


# ---------------------------------------------------------------------------
# tracer core


class TestTracerCore:
    def test_disarmed_is_noop_no_ring_growth(self, monkeypatch):
        monkeypatch.delenv("SPARKDL_TPU_TRACE", raising=False)
        t = Tracer(capacity=16)
        for _ in range(100):
            with t.span("work", lane="engine", rows=1):
                pass
        assert t.spans() == []
        assert t.dropped == 0
        # the module-level fast path allocates nothing: one shared
        # no-op object comes back for every disarmed call
        tracer().clear()
        assert span("a") is span("b")

    def test_disarmed_span_overhead(self, monkeypatch):
        """The <1%-on-a-tight-stage-loop contract: engine stage calls
        are ≥ 1 ms (decode/resize/device dispatch granularity), so the
        disarmed span wrapping each one must cost well under 10 µs.
        Measured as the min over repeats (robust to CI noise — noise
        only ever adds time)."""
        monkeypatch.delenv("SPARKDL_TPU_TRACE", raising=False)
        n = 20_000
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(n):
                with span("s", lane="engine"):
                    pass
            best = min(best, (time.perf_counter() - t0) / n)
        assert best < 10e-6, f"disarmed span costs {best * 1e6:.2f} µs"

    def test_armed_records_thread_and_attrs(self):
        t = Tracer(capacity=16)
        t.arm()
        with t.span("work", lane="ship", rows=4):
            time.sleep(0.001)
        (rec,) = t.spans()
        assert rec.name == "work"
        assert rec.lane == "ship"
        assert rec.attrs == {"rows": 4}
        assert rec.thread_id == threading.get_ident()
        assert rec.end - rec.start >= 0.001

    def test_env_arming_and_override(self, monkeypatch):
        t = Tracer(capacity=4)
        monkeypatch.delenv("SPARKDL_TPU_TRACE", raising=False)
        assert not t.armed
        monkeypatch.setenv("SPARKDL_TPU_TRACE", "1")
        assert t.armed
        t.disarm()  # programmatic override beats the env
        assert not t.armed
        t.arm_from_env()
        assert t.armed
        monkeypatch.delenv("SPARKDL_TPU_TRACE", raising=False)
        t.arm()
        assert t.armed

    def test_ring_buffer_caps_and_notes_drop(self):
        """Old spans evict, the drop counter says so, and the export
        carries a visible note — no silent truncation."""
        t = Tracer(capacity=8)
        t.arm()
        for i in range(20):
            with t.span(f"s{i}", lane="engine"):
                pass
        recs = t.spans()
        assert len(recs) == 8
        assert [r.name for r in recs] == [f"s{i}" for i in range(12, 20)]
        assert t.dropped == 12
        note = [e for e in t.trace_events()
                if "dropped" in str(e.get("name", ""))]
        assert note and note[0]["args"]["dropped"] == 12

    def test_exception_exit_still_records(self):
        t = Tracer(capacity=4)
        t.arm()
        with pytest.raises(ValueError):
            with t.span("boom", lane="engine"):
                raise ValueError("x")
        (rec,) = t.spans()
        assert rec.attrs["error"] == "ValueError"

    def test_garbage_buffer_env_degrades_to_default(self, monkeypatch):
        """A tracing-config typo must not make the library
        unimportable (the singleton parses the env at import time) —
        it falls back to the default capacity with a warning."""
        from sparkdl_tpu.obs.trace import DEFAULT_CAPACITY
        for bad in ("0", "-5", "64k", "  "):
            monkeypatch.setenv("SPARKDL_TPU_TRACE_BUFFER", bad)
            assert Tracer().capacity == DEFAULT_CAPACITY, bad
        monkeypatch.setenv("SPARKDL_TPU_TRACE_BUFFER", "128")
        assert Tracer().capacity == 128
        # an EXPLICIT bad ctor arg still fails loudly
        with pytest.raises(ValueError):
            Tracer(capacity=0)

    def test_clear_resets_spans_and_drop_counter(self):
        t = Tracer(capacity=2)
        t.arm()
        for _ in range(5):
            with t.span("s"):
                pass
        assert t.dropped == 3
        t.clear()
        assert t.spans() == [] and t.dropped == 0


# ---------------------------------------------------------------------------
# armed concurrent transform → nested spans + valid Perfetto export


class TestConcurrentTransform:
    def test_two_thread_transform_spans_and_export(self, armed_tracer,
                                                   tmp_path):
        runner = BatchRunner(_mf(), batch_size=4, strategy="deferred")
        x = np.arange(48, dtype=np.float32).reshape(16, 3)
        errs = []

        def work():
            try:
                out = runner.run({"input": x})
                np.testing.assert_allclose(out["output"], x * 2)
            except Exception as e:  # pragma: no cover - assertion aid
                errs.append(e)

        threads = [threading.Thread(target=work) for _ in range(2)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not errs
        recs = armed_tracer.spans()
        assert {r.lane for r in recs} >= {"ship", "device"}
        # both worker threads recorded
        assert len({r.thread_id for r in recs}) >= 2
        # same-thread spans follow stack discipline: any two either
        # don't overlap or one contains the other (never a partial
        # overlap — that would mean a corrupted/racing timeline)
        by_thread = {}
        for r in recs:
            by_thread.setdefault(r.thread_id, []).append(r)
        for spans_ in by_thread.values():
            spans_.sort(key=lambda r: (r.start, -r.end))
            for a, b in zip(spans_, spans_[1:]):
                assert b.start >= a.end or b.end <= a.end + 1e-9, \
                    (a, b)

        path = tmp_path / "trace.json"
        n = armed_tracer.export(str(path))
        events = json.loads(path.read_text())
        assert isinstance(events, list)
        xs = [e for e in events if e["ph"] == "X"]
        assert len(xs) == n == len(recs)
        for e in xs:
            for k in ("ts", "dur", "pid", "tid", "name", "args"):
                assert k in e
        # every span's pid resolves to a named lane process
        named = {e["pid"] for e in events
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert {e["pid"] for e in xs} <= named

    def test_engine_lane_from_dataframe_pipeline(self, armed_tracer):
        from sparkdl_tpu.data import DataFrame
        df = DataFrame.from_pylist(
            [{"x": float(i)} for i in range(12)], num_partitions=3)
        df.map_batches(lambda b: b, name="noop").collect()
        recs = armed_tracer.spans()
        assert any(r.lane == "engine" and r.name == "stage:noop"
                   for r in recs)
        assert any(r.name == "source.load" for r in recs)


# ---------------------------------------------------------------------------
# registry


class TestRegistry:
    def test_counter_is_thread_safe(self):
        reg = MetricsRegistry()
        c = reg.counter("t.hits")

        def bump():
            for _ in range(10_000):
                c.add()

        threads = [threading.Thread(target=bump) for _ in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert reg.snapshot()["t.hits"] == 40_000

    def test_gauge_set_and_set_max(self):
        reg = MetricsRegistry()
        g = reg.gauge("t.depth")
        g.set(3)
        g.set(1)
        assert reg.snapshot()["t.depth"] == 1.0
        g.set_max(5)
        g.set_max(2)
        assert reg.snapshot()["t.depth"] == 5.0

    def test_kind_collision_raises(self):
        reg = MetricsRegistry()
        reg.counter("t.x")
        with pytest.raises(TypeError, match="Counter"):
            reg.gauge("t.x")

    def test_snapshot_is_flat_and_sorted(self):
        reg = MetricsRegistry()
        reg.counter("b").add(2)
        reg.gauge("a").set(1)
        assert list(reg.snapshot()) == ["a", "b"]

    def test_default_registry_is_process_wide(self):
        assert default_registry() is default_registry()

    def test_queue_depth_gauges_from_runner(self):
        BatchRunner(_mf(), batch_size=4, strategy="deferred").run(
            {"input": np.arange(36, dtype=np.float32).reshape(12, 3)})
        snap = default_registry().snapshot()
        assert snap["ship.inflight"] == 0.0  # fully drained
        assert snap["ship.inflight_peak"] >= 1.0

    def test_reservoir_quantiles_and_snapshot_keys(self):
        reg = MetricsRegistry()
        r = reg.reservoir("t.latency")
        assert r.quantile(0.5) == 0.0    # empty never raises
        for v in range(1, 101):
            r.observe(float(v))
        assert r.quantile(0.5) == 50.0
        assert r.quantile(0.99) == 99.0
        assert r.quantile(1.0) == 100.0
        snap = reg.snapshot()
        # reservoirs flatten to derived keys, one level deep
        assert snap["t.latency.count"] == 100.0
        assert snap["t.latency.p50"] == 50.0
        assert snap["t.latency.p99"] == 99.0
        with pytest.raises(ValueError, match="quantile"):
            r.quantile(1.5)

    def test_reservoir_window_bounded_count_lifetime(self):
        from sparkdl_tpu.obs import Reservoir
        r = Reservoir("t.win", capacity=4)
        for v in range(10):
            r.observe(float(v))
        assert r.count == 10               # lifetime total
        assert r.quantile(0.0) == 6.0      # window kept the newest 4
        with pytest.raises(ValueError, match="capacity"):
            Reservoir("t.bad", capacity=0)

    def test_reservoir_kind_collision_raises(self):
        reg = MetricsRegistry()
        reg.reservoir("t.r")
        with pytest.raises(TypeError, match="Reservoir"):
            reg.counter("t.r")
        reg.gauge("t.g")
        with pytest.raises(TypeError, match="Gauge"):
            reg.reservoir("t.g")

    def test_reservoir_round_trip_keeps_window(self):
        import pickle

        from sparkdl_tpu.obs import Reservoir
        r = Reservoir("t.p")
        r.observe(1.0)
        r.observe(3.0)
        r2 = pickle.loads(pickle.dumps(r))
        assert r2.count == 2 and r2.quantile(1.0) == 3.0
        r2.observe(5.0)                    # lock recreated, still works
        assert r2.quantile(1.0) == 5.0


# ---------------------------------------------------------------------------
# collective launch observability


class TestCollectiveLaunchObservability:
    def test_contended_acquire_counts_and_spans(self, armed_tracer):
        import jax

        from sparkdl_tpu.parallel import mesh as mesh_mod
        from sparkdl_tpu.parallel.mesh import collective_launch, make_mesh
        if len(jax.devices()) < 2:
            pytest.skip("needs >1 device")
        launch = collective_launch(make_mesh())
        reg = default_registry()
        waits0 = reg.counter("collective.lock_waits").value
        wait_s0 = reg.counter("collective.lock_wait_seconds").value

        # deterministic contention: hold the real lock while a second
        # thread enters the instrumented wrapper
        mesh_mod._COLLECTIVE_LAUNCH_LOCK.acquire()
        entered = threading.Event()

        def contend():
            entered.set()
            with launch:
                pass

        th = threading.Thread(target=contend)
        th.start()
        entered.wait()
        time.sleep(0.05)
        mesh_mod._COLLECTIVE_LAUNCH_LOCK.release()
        th.join()

        assert reg.counter("collective.lock_waits").value == waits0 + 1
        assert reg.counter("collective.lock_wait_seconds").value \
            >= wait_s0 + 0.04
        recs = [r for r in armed_tracer.spans()
                if r.name == "collective_lock_wait"]
        assert recs and recs[-1].attrs["contended"] is True
        assert recs[-1].end - recs[-1].start >= 0.04

    def test_enter_failure_releases_the_launch_lock(self, monkeypatch):
        """An exception inside __enter__ AFTER the lock is acquired
        (e.g. a registry kind collision) must release it — __exit__
        never runs when __enter__ raises, and a leaked hold would
        deadlock every future collective launch."""
        import jax

        from sparkdl_tpu.parallel import mesh as mesh_mod
        from sparkdl_tpu.parallel.mesh import collective_launch, make_mesh
        if len(jax.devices()) < 2:
            pytest.skip("needs >1 device")

        def boom():
            raise RuntimeError("registry unavailable")

        monkeypatch.setattr(mesh_mod, "default_registry", boom)
        with pytest.raises(RuntimeError, match="registry unavailable"):
            with collective_launch(make_mesh()):
                pass  # pragma: no cover - never reached
        assert not mesh_mod._COLLECTIVE_LAUNCH_LOCK.locked()
        monkeypatch.undo()
        with collective_launch(make_mesh()):  # still usable afterwards
            assert mesh_mod._COLLECTIVE_LAUNCH_LOCK.locked()

    def test_racing_fit_multiple_trials_increment_counters(
            self, keras_cls_file, uri_label_df):
        """Two fitMultiple trials racing their mesh-jitted train steps
        must leave their launch serialization visible in the registry:
        every step's dispatch counts a launch and its acquire time
        lands in collective.lock_wait_seconds."""
        from tests.test_estimators import make_estimator

        reg = default_registry()
        launches0 = reg.counter("collective.launches").value
        wait0 = reg.counter("collective.lock_wait_seconds").value
        est = make_estimator(keras_cls_file, parallelism=2)
        grid = [
            {est.getParam("kerasFitParams"):
             {"epochs": 1, "batch_size": 8, "learning_rate": 1e-4,
              "seed": 1}},
            {est.getParam("kerasFitParams"):
             {"epochs": 2, "batch_size": 8, "learning_rate": 0.05,
              "seed": 1}},
        ]
        got = dict(est.fitMultiple(uri_label_df, grid))
        assert set(got) == {0, 1}
        # 20 images, global batch rounded to the 8-device data axis →
        # ≥1 step per epoch per trial, 3 epochs total
        assert reg.counter("collective.launches").value >= launches0 + 3
        assert reg.counter("collective.lock_wait_seconds").value > wait0


# ---------------------------------------------------------------------------
# estimator + sanitizer instrumentation


class TestEstimatorAndSanitizerInstrumentation:
    def test_logistic_regression_estimator_lane(self, armed_tracer):
        import pyarrow as pa

        from sparkdl_tpu.data import DataFrame
        from sparkdl_tpu.data.tensors import append_tensor_column
        from sparkdl_tpu.estimators import LogisticRegression
        rng = np.random.default_rng(0)
        y = rng.integers(0, 2, 24)
        X = rng.normal(0, 1, (24, 4)).astype(np.float32) \
            + 3.0 * y[:, None]
        b = pa.RecordBatch.from_pylist([{"label": int(v)} for v in y])
        b = append_tensor_column(b, "features", X)
        LogisticRegression(maxIter=3).fit(DataFrame.from_batches([b]))
        recs = armed_tracer.spans()
        assert any(r.lane == "estimator" and r.name == "step"
                   for r in recs)

    def test_sanitizer_arm_counts_into_registry(self, monkeypatch):
        from sparkdl_tpu.runtime import sanitize
        reg = default_registry()
        armed0 = reg.counter("sanitize.armed_runs").value
        monkeypatch.setenv("SPARKDL_TPU_SANITIZE", "1")
        BatchRunner(_mf(), batch_size=4).run(
            {"input": np.arange(24, dtype=np.float32).reshape(8, 3)})
        if sanitize.armed_run_count() == 0:
            pytest.skip("backend lacks transfer_guard")
        assert reg.counter("sanitize.armed_runs").value > armed0


# ---------------------------------------------------------------------------
# throughput_report routes through the registry (PR-1 counters included)


class TestThroughputReportRouting:
    def test_device_line_carries_copy_counters(self):
        from sparkdl_tpu.utils import StageMetrics, throughput_report
        sm = StageMetrics()
        sm.add("decode", 1.0, 100)
        rm = RunnerMetrics()
        rm.add(100, 2, 0.5, bytes_staged=4096, bytes_copied=128,
               transfer_wait_seconds=0.25)
        rep = throughput_report(sm, rm)
        assert "decode" in rep
        assert "4096 B staged" in rep
        assert "128 B copied" in rep
        assert "0.250s transfer wait" in rep

    def test_report_renders_from_registry_snapshot(self):
        from sparkdl_tpu.utils import StageMetrics, throughput_report
        sm = StageMetrics()
        sm.add("resize", 2.0, 10)
        rm = RunnerMetrics()
        rm.add(10, 1, 1.0, bytes_staged=7)
        reg = MetricsRegistry()
        rep = throughput_report(sm, rm, registry=reg)
        snap = reg.snapshot()
        assert snap["engine.stage.resize.rows"] == 10
        assert snap["ship.bytes_staged"] == 7
        assert "resize" in rep and "7 B staged" in rep

    def test_reused_registry_does_not_leak_stale_stages(self):
        """A reused registry (the default_registry routing) keeps
        gauges from earlier runs — a later report must list only the
        stages ITS StageMetrics actually ran."""
        from sparkdl_tpu.utils import StageMetrics, throughput_report
        reg = MetricsRegistry()
        run1 = StageMetrics()
        run1.add("decode", 1.0, 5)
        throughput_report(run1, registry=reg)
        run2 = StageMetrics()
        run2.add("pack", 1.0, 5)
        rep2 = throughput_report(run2, registry=reg)
        assert "pack" in rep2
        assert "decode" not in rep2


# ---------------------------------------------------------------------------
# lint discipline


class TestLintDiscipline:
    def test_armed_tracer_zero_new_unsuppressed_findings(self,
                                                         monkeypatch):
        """Arming is a runtime switch; the instrumented code is always
        there — the analyzer must stay at zero unsuppressed either
        way."""
        import os

        from sparkdl_tpu.analysis.walker import analyze_paths
        monkeypatch.setenv("SPARKDL_TPU_TRACE", "1")
        import sparkdl_tpu
        pkg = os.path.dirname(sparkdl_tpu.__file__)
        unsuppressed = [f for f in analyze_paths([pkg])
                        if not f.suppressed]
        assert unsuppressed == [], [f.render() for f in unsuppressed]

    def test_obs_drain_is_allowlisted_not_invisible(self):
        import os

        import sparkdl_tpu
        from sparkdl_tpu.analysis.walker import analyze_paths
        pkg = os.path.dirname(sparkdl_tpu.__file__)
        found = analyze_paths([os.path.join(pkg, "obs")])
        h1 = [f for f in found if f.rule == "H1"]
        assert any(f.suppressed and f.qualname == "timed_device_get"
                   for f in h1)

    def test_h2_flags_span_inside_jit(self):
        """Spans read the host wall clock — inside a jit-traced
        function that happens once, at trace time (H2)."""
        from sparkdl_tpu.analysis.walker import analyze_source
        src = (
            "import jax\n"
            "from sparkdl_tpu.obs import span\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    with span('bad', lane='ship'):\n"
            "        return x * 2\n")
        findings = analyze_source(src, "fixture.py", rules=["H2"])
        assert any("span" in f.message and not f.suppressed
                   for f in findings)
        # outside the jit: clean
        ok = (
            "from sparkdl_tpu.obs import span\n"
            "def g(x):\n"
            "    with span('fine'):\n"
            "        return x * 2\n")
        assert analyze_source(ok, "fixture.py", rules=["H2"]) == []


# ---------------------------------------------------------------------------
# pickle discipline (StageMetrics precedent)


class TestPickleDiscipline:
    def test_tracer_round_trip_drops_spans_keeps_config(self):
        import cloudpickle as cp
        t = Tracer(capacity=32)
        t.arm()
        with t.span("local", lane="engine"):
            pass
        t2 = cp.loads(cp.dumps(t))
        # remote-side spans stay remote: the buffer does not travel
        assert t2.spans() == []
        assert t2.dropped == 0
        assert t2.capacity == 32
        assert t2.armed  # the programmatic arm travels
        with t2.span("remote", lane="engine"):
            pass
        assert [r.name for r in t2.spans()] == ["remote"]
        # and the original is untouched
        assert [r.name for r in t.spans()] == ["local"]
        # the clock origin is per-process (perf_counter): the restored
        # tracer re-anchors its epoch, so exported timestamps are
        # sane relative offsets, not sender-minus-receiver garbage
        (ev,) = [e for e in t2.trace_events() if e["ph"] == "X"]
        assert 0 <= ev["ts"] < 60 * 1e6

    def test_registry_round_trip_keeps_values(self):
        import cloudpickle as cp
        reg = MetricsRegistry()
        reg.counter("c").add(5)
        reg.gauge("g").set(2)
        reg2 = cp.loads(cp.dumps(reg))
        assert reg2.snapshot() == {"c": 5.0, "g": 2.0}
        reg2.counter("c").add(1)  # lock recreated, still usable
        assert reg2.snapshot()["c"] == 6.0

    def test_collective_launch_wrapper_ships_as_singleton(self):
        """A closure capturing the launch wrapper must survive the
        wire: the wrapped lock doesn't pickle, so __reduce__ re-binds
        to the receiving process's singleton (H3 discipline in
        identity-preserving form)."""
        import cloudpickle as cp
        import jax

        from sparkdl_tpu.parallel import mesh as mesh_mod
        from sparkdl_tpu.parallel.mesh import collective_launch, make_mesh
        if len(jax.devices()) < 2:
            pytest.skip("needs >1 device")
        launch = collective_launch(make_mesh())
        launch2 = cp.loads(cp.dumps(launch))
        assert launch2 is mesh_mod._COLLECTIVE_LAUNCH
        with launch2:
            assert mesh_mod._COLLECTIVE_LAUNCH_LOCK.locked()
        assert not mesh_mod._COLLECTIVE_LAUNCH_LOCK.locked()

    def test_instrumented_runner_still_ships(self):
        """The obs imports must not break the runner's existing wire
        discipline (H3: stage closures ship with cloudpickle)."""
        import cloudpickle as cp
        r = cp.loads(cp.dumps(BatchRunner(_mf(), batch_size=4)))
        x = np.arange(12, dtype=np.float32).reshape(4, 3)
        np.testing.assert_allclose(r.run({"input": x})["output"], x * 2)


# ---------------------------------------------------------------------------
# report CLI


class TestReportCLI:
    def _export(self, tmp_path):
        t = Tracer(capacity=64)
        t.arm()
        with t.span("stage:decode", lane="engine", rows=8):
            time.sleep(0.002)
        with t.span("dispatch", lane="ship", rows=8):
            time.sleep(0.001)
        with t.span("device_get", lane="device"):
            time.sleep(0.001)
        path = str(tmp_path / "t.json")
        t.export(path)
        return path

    def test_summary_has_lanes_and_stalls(self, tmp_path):
        out = summarize(load_events(self._export(tmp_path)))
        for needle in ("engine", "ship", "device", "busy%",
                       "device/device_get"):
            assert needle in out, out

    def test_cli_entry_point(self, tmp_path):
        import subprocess
        import sys
        path = self._export(tmp_path)
        proc = subprocess.run(
            [sys.executable, "-m", "sparkdl_tpu.obs", "report", path],
            capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr
        assert "busy%" in proc.stdout

    def test_cli_rejects_garbage(self, tmp_path):
        from sparkdl_tpu.obs.report import main
        bad = tmp_path / "bad.json"
        bad.write_text("{\"notTraceEvents\": 1}")
        assert main(["report", str(bad)]) == 2
        assert main(["wrong"]) == 2


# ---------------------------------------------------------------------------
# report forward-compat: lanes are data, not a schema


class TestReportForwardCompat:
    """An older report invocation must summarize traces carrying lanes
    it has never heard of, and a newer report must tolerate traces
    from before those lanes existed — the lane set grows every obs PR
    (serve in PR 4, obs/flight in this one) and neither direction may
    crash."""

    def test_unknown_lane_summarizes(self):
        events = [
            {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
             "args": {"name": "lane-from-the-future"}},
            {"name": "mystery", "cat": "lane-from-the-future",
             "ph": "X", "ts": 0.0, "dur": 50.0, "pid": 1, "tid": 7,
             "args": {}},
        ]
        out = summarize(events)
        assert "lane-from-the-future" in out
        assert "mystery" in out

    def test_span_without_lane_metadata_falls_back_to_cat(self):
        events = [{"name": "orphan", "cat": "obs", "ph": "X",
                   "ts": 0.0, "dur": 10.0, "pid": 99, "tid": 1,
                   "args": {}}]
        out = summarize(events)
        assert "obs/orphan" in out

    def test_zero_span_lane_does_not_crash_or_render_busy(self):
        """Lane metadata with no spans (an armed run that never
        exercised a subsystem) must not crash the report or appear as
        a busy lane."""
        events = [
            {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
             "args": {"name": "serve"}},
            {"name": "process_name", "ph": "M", "pid": 2, "tid": 0,
             "args": {"name": "engine"}},
            {"name": "stage:decode", "cat": "engine", "ph": "X",
             "ts": 0.0, "dur": 25.0, "pid": 2, "tid": 1, "args": {}},
        ]
        out = summarize(events)
        assert "engine" in out
        # the empty lane contributes no busy line
        assert "serve  " not in out.split("top spans")[0].replace(
            "lanes", "")

    def test_malformed_metadata_and_missing_dur_tolerated(self):
        events = [
            {"name": "process_name", "ph": "M", "pid": 1, "tid": 0},
            {"name": "short", "ph": "X", "ts": 1.0, "pid": 1,
             "tid": 1},
        ]
        out = summarize(events)
        assert "short" in out

    def test_all_metadata_no_spans(self):
        events = [{"name": "process_name", "ph": "M", "pid": 1,
                   "tid": 0, "args": {"name": "engine"}}]
        assert summarize(events) == "(no spans in trace)"

    def test_new_obs_lane_flows_through_report(self, tmp_path):
        """The flight recorder's own dump span (obs lane, new in this
        PR) must ride the generic machinery like every other lane."""
        from sparkdl_tpu.obs import flight
        t = tracer()
        t.arm()
        try:
            rec = flight.FlightRecorder()
            # a dump's own span records at its END — the SECOND
            # bundle carries the first dump's span
            rec.dump(path=str(tmp_path / "a.json"), reason="first")
            path = rec.dump(path=str(tmp_path / "b.json"),
                            reason="report test")
        finally:
            t.disarm()
            t.arm_from_env()
        with open(path) as f:
            events = json.load(f)["spans"]
        t.clear()
        out = summarize(events)
        assert "obs" in out
        assert "flight.dump" in out
