"""Fitted-model persistence (VERDICT r2 missing #4): save(dir)/load(dir)
for LogisticRegressionModel, KerasImageFileModel, PipelineModel, and the
tuning models — pyspark ML persistence semantics the reference inherited
(SURVEY §2.1 param-system row). The headline test reloads in a FRESH
process and asserts identical transform output."""

import json
import os
import subprocess
import sys

import numpy as np
import pyarrow as pa
import pytest

import sparkdl_tpu
from sparkdl_tpu.data.frame import DataFrame
from sparkdl_tpu.data.tensors import append_tensor_column
from sparkdl_tpu.estimators.logistic_regression import (
    LogisticRegression,
    LogisticRegressionModel,
)
from sparkdl_tpu.params.pipeline import Pipeline, PipelineModel


def _feature_df(n=40, d=6, seed=3):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 2, n)
    X = rng.normal(0, 1, (n, d)).astype(np.float32) + 2.5 * y[:, None]
    batch = pa.RecordBatch.from_pylist([{"label": int(v)} for v in y])
    batch = append_tensor_column(batch, "features", X)
    return DataFrame.from_batches([batch]), X, y


class TestLogisticRegressionPersistence:
    def test_round_trip_identical_transform(self, tmp_path):
        df, X, y = _feature_df()
        model = LogisticRegression(maxIter=60, learningRate=0.2).fit(df)
        path = str(tmp_path / "lr")
        model.save(path)

        back = sparkdl_tpu.load_model(path)
        assert isinstance(back, LogisticRegressionModel)
        np.testing.assert_array_equal(back.coefficients,
                                      model.coefficients)
        np.testing.assert_array_equal(back.intercept, model.intercept)
        assert back.objectiveHistory == pytest.approx(
            model.objectiveHistory)
        a = model.transform(df).tensor("probability")
        b = back.transform(df).tensor("probability")
        np.testing.assert_array_equal(a, b)

    def test_no_silent_overwrite(self, tmp_path):
        df, _, _ = _feature_df(n=10)
        model = LogisticRegression(maxIter=2).fit(df)
        path = str(tmp_path / "lr")
        model.save(path)
        with pytest.raises(FileExistsError, match="fresh"):
            model.save(path)

    def test_load_rejects_non_stage_dir(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="metadata"):
            sparkdl_tpu.load_model(str(tmp_path))
        bogus = tmp_path / "bogus"
        bogus.mkdir()
        (bogus / "metadata.json").write_text(json.dumps({"format": "x"}))
        with pytest.raises(ValueError, match="not written"):
            sparkdl_tpu.load_model(str(bogus))


class TestPipelinePersistence:
    def test_featurizer_pipeline_round_trip(self, tmp_path, image_dir):
        """The reference's headline flow — DeepImageFeaturizer →
        LogisticRegression — saved and reloaded as ONE PipelineModel."""
        from sparkdl_tpu.image import imageIO

        table = imageIO.readImages(image_dir, numPartitions=2,
                                   dropImageFailures=True).collect()
        labels = pa.array([i % 2 for i in range(table.num_rows)],
                          type=pa.int64())
        df = DataFrame.from_table(table.append_column("label", labels), 2)
        pipe = Pipeline(stages=[
            sparkdl_tpu.DeepImageFeaturizer(
                inputCol="image", outputCol="features",
                modelName="TestNet"),
            LogisticRegression(maxIter=20, learningRate=0.2),
        ])
        fitted = pipe.fit(df)
        path = str(tmp_path / "pipe")
        fitted.save(path)

        back = sparkdl_tpu.load_model(path)
        assert isinstance(back, PipelineModel)
        assert [type(s).__name__ for s in back.stages] == \
            ["DeepImageFeaturizer", "LogisticRegressionModel"]
        a = fitted.transform(df).tensor("probability")
        b = back.transform(df).tensor("probability")
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)

    def test_unfitted_pipeline_round_trip(self, tmp_path):
        """A configured-but-unfitted Pipeline saves its stages as child
        saves and reloads ready to fit."""
        pipe = Pipeline(stages=[
            LogisticRegression(maxIter=15, learningRate=0.2)])
        path = str(tmp_path / "est")
        pipe.save(path)
        back = sparkdl_tpu.load_model(path)
        assert [type(s).__name__ for s in back.getStages()] == \
            ["LogisticRegression"]
        assert back.getStages()[0].getOrDefault("maxIter") == 15

    def test_pipeline_loads_legacy_stages_param_layout(self, tmp_path):
        """Artifacts saved before stages nested as children pickled the
        stage list into params['stages'] — they must still load with
        their stages, not silently come back empty."""
        import json

        from sparkdl_tpu.params import persistence

        path = str(tmp_path / "legacy")
        import os
        os.makedirs(path)
        stages = [LogisticRegression(maxIter=7)]
        desc = persistence._encode_value("param_stages", stages, path)
        meta = {"format": persistence.FORMAT, "version": 1,
                "class": "sparkdl_tpu.params.pipeline.Pipeline",
                "params": {"stages": desc}, "extra": {}, "children": []}
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(meta, f)

        back = sparkdl_tpu.load_model(path)
        assert [type(s).__name__ for s in back.getStages()] == \
            ["LogisticRegression"]
        assert back.getStages()[0].getOrDefault("maxIter") == 7

    def test_fresh_process_round_trip(self, tmp_path):
        """fit → save → load in a NEW python process → identical
        output (the round-trip bar VERDICT set)."""
        df, X, y = _feature_df()
        model = LogisticRegression(maxIter=40, learningRate=0.2).fit(df)
        pm = PipelineModel([model])
        path = str(tmp_path / "pm")
        pm.save(path)
        expected = pm.transform(df).tensor("probability")
        np.save(tmp_path / "X.npy", X)
        np.save(tmp_path / "expected.npy", expected)

        script = f"""
import numpy as np, pyarrow as pa
import sparkdl_tpu
from sparkdl_tpu.data.frame import DataFrame
from sparkdl_tpu.data.tensors import append_tensor_column

X = np.load({str(tmp_path / 'X.npy')!r})
expected = np.load({str(tmp_path / 'expected.npy')!r})
batch = pa.RecordBatch.from_pylist([{{"i": int(i)}} for i in range(len(X))])
batch = append_tensor_column(batch, "features", X)
df = DataFrame.from_batches([batch])
model = sparkdl_tpu.load_model({path!r})
got = model.transform(df).tensor("probability")
np.testing.assert_array_equal(got, expected)
print("FRESH_PROCESS_OK")
"""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = "/root/repo"
        out = subprocess.run([sys.executable, "-c", script],
                             capture_output=True, text=True, env=env,
                             timeout=300)
        assert out.returncode == 0, out.stderr
        assert "FRESH_PROCESS_OK" in out.stdout


class TestTransformerPersistence:
    def test_tensor_transformer_with_model_fn_param(self, tmp_path):
        """A ModelFunction-valued param persists as StableHLO and the
        reloaded stage produces identical output."""
        from sparkdl_tpu.graph.function import ModelFunction
        from sparkdl_tpu.transformers.tensor_transform import (
            TensorTransformer,
        )

        rng = np.random.default_rng(0)
        W = rng.normal(size=(4, 3)).astype(np.float32)
        mf = ModelFunction(
            lambda p, d: {"out": d["x"] @ p["W"]}, {"W": W},
            {"x": ((4,), np.float32)}, output_names=["out"], name="lin")
        t = TensorTransformer(modelFunction=mf,
                              inputMapping={"x": "x"},
                              outputMapping={"out": "y"}, batchSize=8)
        path = str(tmp_path / "tt")
        t.save(path)

        back = sparkdl_tpu.load_model(path)
        X = rng.normal(size=(10, 4)).astype(np.float32)
        batch = pa.RecordBatch.from_pylist(
            [{"i": int(i)} for i in range(10)])
        batch = append_tensor_column(batch, "x", X)
        df = DataFrame.from_batches([batch])
        a = t.transform(df).tensor("y")
        b = back.transform(df).tensor("y")
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


class TestTuningPersistence:
    def test_cross_validator_model_round_trip(self, tmp_path):
        from sparkdl_tpu.estimators.evaluators import (
            ClassificationEvaluator,
        )
        from sparkdl_tpu.params.tuning import CrossValidator

        df, X, y = _feature_df()
        lr = LogisticRegression(maxIter=30, learningRate=0.2)
        cv = CrossValidator(
            estimator=lr,
            estimatorParamMaps=[{lr.regParam: 0.0},
                                {lr.regParam: 0.1}],
            evaluator=ClassificationEvaluator(
                predictionCol="prediction"),
            numFolds=2)
        cvm = cv.fit(df)
        path = str(tmp_path / "cvm")
        cvm.save(path)

        back = sparkdl_tpu.load_model(path)
        assert back.avgMetrics == pytest.approx(cvm.avgMetrics)
        a = cvm.transform(df).tensor("probability")
        b = back.transform(df).tensor("probability")
        np.testing.assert_array_equal(a, b)


class TestDefaultsPersistence:
    def test_saved_defaults_pin_behavior(self, tmp_path):
        """Defaults are persisted alongside explicit params (pyspark
        DefaultParamsWriter): a reload must use the defaults as they
        were AT SAVE TIME, not whatever this library version's
        constructor sets — proven by tampering the saved default and
        observing the loaded stage follow it."""
        import json
        import os

        from sparkdl_tpu.transformers.tensor_transform import (
            TensorTransformer,
        )

        t = TensorTransformer()  # tfHParams stays a pure default (None)
        path = str(tmp_path / "tt")
        t.save(path)
        meta_path = os.path.join(path, "metadata.json")
        with open(meta_path) as f:
            meta = json.load(f)
        assert meta["defaults"]["tfHParams"]["value"] is None

        # simulate "the library default changed since the save": the
        # artifact's recorded defaults must win on reload
        meta["defaults"]["tfHParams"]["value"] = {"gain": 2.5}
        with open(meta_path, "w") as f:
            json.dump(meta, f)
        back = sparkdl_tpu.load_model(path)
        assert back.getTFHParams() == {"gain": 2.5}
        # explicitly-set-at-construction params are unaffected
        assert back.getBatchSize() == 64


class _Widget(sparkdl_tpu.params.base.Params):
    """keyword_only stage whose constructor explicitly _sets every
    kwarg — the pattern that used to shadow restored saved defaults."""

    from sparkdl_tpu.params.base import (
        Param as _P,
        TypeConverters as _TC,
    )
    gain = _P("_Widget", "gain", "gain", _TC.toFloat)
    mode = _P("_Widget", "mode", "mode", _TC.toString)

    @sparkdl_tpu.params.base.keyword_only
    def __init__(self, *, gain=1.0, mode="auto"):
        super().__init__()
        self._setDefault(gain=1.0, mode="auto")
        self._set(gain=gain, mode=mode)


class TestDefaultsNotShadowed:
    def test_load_restricts_class_resolution(self, tmp_path):
        """Classes outside sparkdl_tpu refuse to load unless their
        module prefix is explicitly trusted (pickle-loader hygiene)."""
        w = _Widget(gain=3.0)
        path = str(tmp_path / "w")
        w.save(path)
        with pytest.raises(ValueError, match="trusted"):
            sparkdl_tpu.load_model(path)
        back = sparkdl_tpu.load_model(
            path, trusted_modules=[type(w).__module__.split(".")[0]])
        assert back.getOrDefault("gain") == 3.0

    def test_reloaded_stage_reports_saved_set_state(self, tmp_path):
        """ADVICE r3 (persistence.py:194): the keyword_only constructor
        _sets every kwarg explicitly, so without the post-construction
        clear a reloaded stage (a) reported isSet() for never-set params
        and (b) resolved constructor values over the SAVED defaults."""
        w = _Widget(gain=3.0)
        w.clear("mode")            # mode governed by its default
        assert not w.isSet("mode")
        path = str(tmp_path / "w")
        w.save(path)

        meta_path = os.path.join(path, "metadata.json")
        with open(meta_path) as f:
            meta = json.load(f)
        assert "mode" in meta["defaults"] and "mode" not in meta["params"]
        # simulate "library default changed since the save": the saved
        # default must govern the reloaded stage
        meta["defaults"]["mode"]["value"] = "fancy"
        with open(meta_path, "w") as f:
            json.dump(meta, f)

        trusted = [type(w).__module__.split(".")[0]]
        back = sparkdl_tpu.load_model(path, trusted_modules=trusted)
        assert back.isSet("gain") and back.getOrDefault("gain") == 3.0
        assert not back.isSet("mode")          # as saved
        assert back.getOrDefault("mode") == "fancy"  # saved default wins


class TestEstimatorPersistence:
    def test_configured_cross_validator_round_trip(self, tmp_path):
        """An unfitted CrossValidator (estimator + grid + evaluator as
        params) saves and reloads ready to fit — enabled by stages
        being picklable."""
        from sparkdl_tpu.estimators.evaluators import (
            ClassificationEvaluator,
        )
        from sparkdl_tpu.params.tuning import CrossValidator

        lr = LogisticRegression(maxIter=25, learningRate=0.2)
        cv = CrossValidator(
            estimator=lr,
            estimatorParamMaps=[{lr.regParam: 0.0},
                                {lr.regParam: 0.1}],
            evaluator=ClassificationEvaluator(
                predictionCol="prediction"),
            numFolds=2, seed=5)
        path = str(tmp_path / "cv_est")
        cv.save(path)

        back = sparkdl_tpu.load_model(path)
        assert back.getOrDefault("numFolds") == 2
        assert back.getOrDefault("seed") == 5
        df, X, y = _feature_df()
        model = back.fit(df)
        probs = model.transform(df).tensor("probability")
        assert np.mean(probs.argmax(-1) == y) >= 0.9


class TestKerasModelPersistence:
    def test_keras_image_file_model_round_trip(self, tmp_path):
        """The fitted Keras model (trained weights inside a
        ModelFunction) survives save/load with identical predictions."""
        import keras
        from PIL import Image

        from sparkdl_tpu.estimators import KerasImageFileEstimator

        keras.utils.set_random_seed(7)
        m = keras.Sequential([
            keras.layers.Input((8, 8, 3)),
            keras.layers.Flatten(),
            keras.layers.Dense(2, activation="softmax"),
        ])
        model_file = str(tmp_path / "m.keras")
        m.save(model_file)

        def loader(uri):
            from PIL import Image as PILImage
            img = PILImage.open(uri).convert("RGB").resize((8, 8))
            return np.asarray(img, dtype=np.float32) / 255.0

        rng = np.random.default_rng(11)
        rows = []
        for i in range(8):
            label = i % 2
            base = 50 if label == 0 else 200
            arr = np.clip(rng.normal(base, 10, (8, 8, 3)),
                          0, 255).astype(np.uint8)
            p = str(tmp_path / f"img{i}.png")
            Image.fromarray(arr, "RGB").save(p)
            rows.append({"uri": p, "label": label})
        df = DataFrame.from_pylist(rows, num_partitions=2)
        est = KerasImageFileEstimator(
            inputCol="uri", outputCol="pred", labelCol="label",
            modelFile=model_file, imageLoader=loader,
            kerasFitParams={"epochs": 1, "batch_size": 4,
                            "learning_rate": 0.01, "seed": 0},
            batchSize=4, useMesh=False)
        fitted = est.fit(df)
        path = str(tmp_path / "kifm")
        fitted.save(path)

        back = sparkdl_tpu.load_model(path)
        assert back.history == pytest.approx(fitted.history)
        a = fitted.transform(df).tensor("pred")
        b = back.transform(df).tensor("pred")
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
