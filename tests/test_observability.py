"""StageMetrics / profiling + LogisticRegression (transfer-learning
pipeline parity: the reference's headline flow was DeepImageFeaturizer →
MLlib LogisticRegression, upstream README)."""

import numpy as np
import pytest

from sparkdl_tpu.data import DataFrame
from sparkdl_tpu.data.engine import LocalEngine
from sparkdl_tpu.estimators import (
    ClassificationEvaluator,
    LogisticRegression,
)
from sparkdl_tpu.params.pipeline import Pipeline
from sparkdl_tpu.utils import StageMetrics, throughput_report


class TestStageMetrics:
    def test_engine_records_stage_timings(self):
        sm = StageMetrics()
        engine = LocalEngine(num_workers=2, stage_metrics=sm)
        df = DataFrame.from_pylist(
            [{"x": float(i)} for i in range(20)], num_partitions=4,
            engine=engine)

        def double(batch):
            import pyarrow as pa
            return batch.set_column(
                0, "x", pa.array([v * 2 for v in
                                  batch.column(0).to_pylist()]))

        df.map_batches(double, name="double").collect()
        stats = sm.as_dict()
        assert "double" in stats
        assert stats["double"]["calls"] == 4
        assert stats["double"]["rows"] == 20
        assert stats["double"]["seconds"] >= 0
        assert "double" in sm.report()

    def test_retried_partition_not_double_counted(self):
        """Stage timings flush only when a partition succeeds, so
        retries don't inflate totals (regression)."""
        import threading
        import pyarrow as pa
        from sparkdl_tpu.data.frame import Source, Stage
        sm = StageMetrics()
        engine = LocalEngine(num_workers=1, max_retries=2,
                             stage_metrics=sm)
        state = {"n": 0}
        lock = threading.Lock()

        def ok_stage(batch):
            return batch

        def flaky_stage(batch):
            with lock:
                state["n"] += 1
                if state["n"] == 1:
                    raise IOError("blip")
            return batch

        src = Source(lambda: pa.RecordBatch.from_pydict(
            {"x": pa.array([1, 2, 3])}), 3)
        list(engine.execute([src], [Stage(ok_stage, name="ok"),
                                    Stage(flaky_stage, name="flaky")]))
        stats = sm.as_dict()
        assert stats["ok"]["rows"] == 3      # counted once, not twice
        assert stats["ok"]["calls"] == 1

    def test_no_metrics_attached_is_fine(self):
        engine = LocalEngine(num_workers=1)
        df = DataFrame.from_pylist([{"x": 1.0}], engine=engine)
        assert df.map_batches(lambda b: b).count() == 1

    def test_throughput_report(self):
        from sparkdl_tpu.runtime.runner import RunnerMetrics
        sm = StageMetrics()
        sm.add("decode", 1.0, 100)
        rm = RunnerMetrics()
        rm.add(100, 2, 0.5)
        rep = throughput_report(sm, rm)
        assert "decode" in rep and "device:" in rep
        assert throughput_report() == "(no metrics)"


class TestLogisticRegression:
    def _df(self, n=120, d=5, seed=0):
        rng = np.random.default_rng(seed)
        import pyarrow as pa
        from sparkdl_tpu.data.tensors import append_tensor_column
        # two gaussian blobs, linearly separable-ish
        y = rng.integers(0, 2, n)
        X = rng.normal(0, 1, (n, d)).astype(np.float32) + 3.0 * y[:, None]
        batch = pa.RecordBatch.from_pylist(
            [{"label": int(v)} for v in y])
        batch = append_tensor_column(batch, "features", X)
        return DataFrame.from_batches([batch]), X, y

    def test_fit_learns_separable_blobs(self):
        df, X, y = self._df()
        lr = LogisticRegression(featuresCol="features", labelCol="label",
                                maxIter=200, learningRate=0.2)
        model = lr.fit(df)
        assert model.numClasses == 2
        assert model.objectiveHistory[-1] < model.objectiveHistory[0]
        out = model.transform(df)
        probs = out.tensor("probability")
        acc = np.mean(probs.argmax(-1) == y)
        assert acc >= 0.95
        assert np.allclose(probs.sum(-1), 1.0, atol=1e-5)
        # predictionCol is the class label as float64 (Spark convention)
        preds = np.asarray([r["prediction"] for r in out.collect_rows()])
        assert preds.dtype == np.float64
        np.testing.assert_array_equal(preds, probs.argmax(-1))
        # pyspark model-inspection surface: BINOMIAL layout for 2
        # classes — one signed-margin row — exactly like MLlib, so
        # migration code reading coefficientMatrix[0] gets the margin
        assert model.numFeatures == 5
        assert model.coefficientMatrix.shape == (1, 5)
        assert model.interceptVector.shape == (1,)
        # the margin must separate the blobs in the right DIRECTION:
        # features are shifted +3 for class 1, so margin weights sum > 0
        assert float(model.coefficientMatrix[0].sum()) > 0
        # detached copies: mutation cannot corrupt the model
        model.coefficientMatrix[0, 0] = 1e9
        model.interceptVector[0] = 1e9
        assert abs(model.coefficients).max() < 1e8
        assert abs(model.intercept).max() < 1e8

    def test_minibatch_matches_full_batch_quality(self):
        """batchSize>0 streams shuffled minibatches through a
        fixed-shape jitted step (HBM never holds the table — VERDICT r2
        weak #3); quality must match the full-batch path, including a
        ragged tail batch (120 % 32 != 0)."""
        df, X, y = self._df()
        mb = LogisticRegression(maxIter=40, learningRate=0.2,
                                batchSize=32).fit(df)
        probs = mb.transform(df).tensor("probability")
        assert np.mean(probs.argmax(-1) == y) >= 0.95
        # per-epoch mean loss decreases
        assert mb.objectiveHistory[-1] < mb.objectiveHistory[0]

    def test_minibatch_step_never_traces_full_table(self):
        """The compiled train step's feature operand must be
        (batchSize, D)-shaped — tracing with the whole table resident
        would defeat the point of minibatching."""
        import jax

        df, X, y = self._df(n=100, d=4)
        traced_shapes = []
        orig_jit = jax.jit

        def spy_jit(fn, *a, **k):
            def wrapper(*args, **kwargs):
                # operand 2 is xb in the minibatch step; record every
                # call's shape (compiled calls included — shapes are
                # what matter)
                if len(args) >= 3 and hasattr(args[2], "shape"):
                    traced_shapes.append(args[2].shape)
                return orig_jit(fn)(*args, **kwargs)
            return wrapper

        jax.jit = spy_jit
        try:
            LogisticRegression(maxIter=2, batchSize=16).fit(df)
        finally:
            jax.jit = orig_jit
        assert traced_shapes and all(s[0] == 16 for s in traced_shapes)

    def _multi_part_df(self, n=160, d=5, seed=0, parts=4):
        import pyarrow as pa

        from sparkdl_tpu.data.tensors import append_tensor_column
        rng = np.random.default_rng(seed)
        y = rng.integers(0, 2, n)
        X = rng.normal(0, 1, (n, d)).astype(np.float32) + 3.0 * y[:, None]
        batches = []
        for lo in range(0, n, n // parts):
            hi = min(n, lo + n // parts)
            b = pa.RecordBatch.from_pylist(
                [{"label": int(v)} for v in y[lo:hi]])
            batches.append(append_tensor_column(b, "features", X[lo:hi]))
        return DataFrame.from_batches(batches), X, y

    def test_streaming_fit_never_collects(self, monkeypatch):
        """VERDICT r3 #5: streaming=True assembles minibatches from the
        partition stream — the feature table is NEVER collected into
        driver memory, across the label pass, every epoch, AND the
        streaming evaluators scoring the result."""
        from sparkdl_tpu.estimators import (
            BinaryClassificationEvaluator,
            ClassificationEvaluator,
        )

        df, X, y = self._multi_part_df()
        lr = LogisticRegression(maxIter=30, learningRate=0.2,
                                batchSize=32, streaming=True)

        def no_collect(self):
            raise AssertionError("streaming LR path collected a table")

        monkeypatch.setattr(DataFrame, "collect", no_collect)
        try:
            model = lr.fit(df)
            scored = model.transform(df)
            acc = ClassificationEvaluator(
                predictionCol="prediction").evaluate(scored)
            auc = BinaryClassificationEvaluator().evaluate(scored)
        finally:
            monkeypatch.undo()
        assert acc >= 0.95
        assert auc >= 0.95
        assert model.objectiveHistory[-1] < model.objectiveHistory[0]
        assert len(model.objectiveHistory) == 30  # epochs

    def test_streaming_matches_inmemory_quality(self):
        """Same data through streaming and in-memory minibatch paths:
        both learn the separable blobs (batch composition differs, so
        weights aren't bit-identical — quality is the contract)."""
        df, X, y = self._multi_part_df()
        for kw in ({"batchSize": 32, "streaming": True},
                   {"batchSize": 32}):
            m = LogisticRegression(maxIter=30, learningRate=0.2,
                                   **kw).fit(df)
            probs = m.transform(df).tensor("probability")
            assert np.mean(probs.argmax(-1) == y) >= 0.95, kw

    def test_streaming_requires_batch_size(self):
        df, _, _ = self._multi_part_df(n=16, parts=2)
        with pytest.raises(ValueError, match="batchSize"):
            LogisticRegression(streaming=True).fit(df)

    def test_streaming_num_classes_param_skips_label_pass(self):
        """numClasses set: no labels-only pre-pass (the upstream plan
        runs exactly maxIter times, once per epoch)."""
        runs = {"n": 0}

        def counting(batch):
            if batch.num_rows:
                runs["n"] += 1
            return batch

        df, X, y = self._multi_part_df(n=80, parts=2)
        dfc = df.map_batches(counting, name="featurize")
        m = LogisticRegression(maxIter=3, learningRate=0.2, batchSize=16,
                               streaming=True, numClasses=2).fit(dfc)
        assert runs["n"] == 3 * dfc.num_partitions  # epochs only
        runs["n"] = 0
        LogisticRegression(maxIter=3, learningRate=0.2, batchSize=16,
                           streaming=True).fit(dfc)
        assert runs["n"] == 4 * dfc.num_partitions  # + label pass
        # out-of-range label vs declared numClasses fails loudly
        with pytest.raises(ValueError, match="out of range"):
            LogisticRegression(maxIter=2, batchSize=16, streaming=True,
                               numClasses=1).fit(df)

    def test_streaming_num_classes_one_widens_like_inmemory(self):
        """numClasses=1 over single-class data: both paths widen to a
        2-class head (1-class softmax is constant — zero gradient,
        silent no-op training) instead of diverging."""
        import pyarrow as pa

        from sparkdl_tpu.data.tensors import append_tensor_column
        rng = np.random.default_rng(0)
        X = rng.normal(0, 1, (24, 3)).astype(np.float32)
        b = pa.RecordBatch.from_pylist([{"label": 0}] * 24)
        b = append_tensor_column(b, "features", X)
        df = DataFrame.from_batches([b])
        for kw in ({"streaming": True, "batchSize": 8}, {}):
            m = LogisticRegression(maxIter=2, numClasses=1, **kw).fit(df)
            assert m.coefficients.shape == (3, 2), kw

    def test_batchsize_geq_n_falls_back_to_full_batch(self):
        df, X, y = self._df(n=30)
        m = LogisticRegression(maxIter=50, learningRate=0.2,
                               batchSize=1000).fit(df)
        probs = m.transform(df).tensor("probability")
        assert np.mean(probs.argmax(-1) == y) >= 0.9
        # full-batch history counts STEPS (50), not epochs
        assert len(m.objectiveHistory) == 50

    def test_transform_time_param_override(self):
        """model.transform(df, {param: value}) must honor the override
        (regression: copy() dropped the extra map)."""
        df, X, y = self._df(n=16)
        model = LogisticRegression(maxIter=5).fit(df)
        out = model.transform(df, {"predictionCol": "p2"})
        assert "p2" in out.columns
        # and the original model is unchanged
        assert model.getOrDefault("predictionCol") == "prediction"

    def test_regularization_shrinks_weights(self):
        df, _, _ = self._df()
        free = LogisticRegression(maxIter=150).fit(df)
        reg = LogisticRegression(maxIter=150, regParam=0.5).fit(df)
        assert (np.linalg.norm(reg.coefficients)
                < np.linalg.norm(free.coefficients))

    def test_double_labels_spark_convention(self):
        """Spark ML label columns are float64 holding integral class ids
        (0.0, 1.0) — accept those identically to ints; reject true
        fractions loudly. In particular a LogisticRegressionModel's own
        predictionCol (float64 class label) must be usable as a label."""
        import pyarrow as pa
        from sparkdl_tpu.data.tensors import append_tensor_column
        rng = np.random.default_rng(1)
        y = rng.integers(0, 2, 60)
        X = rng.normal(0, 1, (60, 4)).astype(np.float32) + 3.0 * y[:, None]
        batch = pa.RecordBatch.from_pylist(
            [{"label": float(v)} for v in y])
        batch = append_tensor_column(batch, "features", X)
        df = DataFrame.from_batches([batch])
        model = LogisticRegression(maxIter=100, learningRate=0.2).fit(df)
        assert model.numClasses == 2
        # (fractional labels stay rejected: test_bad_labels_rejected)

    def test_negative_labels_rejected(self):
        """{-1, 1} labels must error, not silently wrap through np.eye
        fancy-indexing (regression)."""
        import pyarrow as pa
        from sparkdl_tpu.data.tensors import append_tensor_column
        batch = pa.RecordBatch.from_pylist(
            [{"label": -1}, {"label": 1}])
        batch = append_tensor_column(
            batch, "features", np.zeros((2, 3), np.float32))
        df = DataFrame.from_batches([batch])
        with pytest.raises(ValueError, match="re-encode"):
            LogisticRegression().fit(df)

    def test_fit_materializes_plan_once(self):
        """LR._fit must run the upstream plan once, not once per column
        read (regression: tensor() + select().collect() doubled the
        featurization cost). Row-bearing calls only: the memory-budget
        estimate adds one ZERO-row schema probe, which costs nothing
        (runners short-circuit N=0)."""
        runs = {"n": 0}
        zero_rows = {"n": 0}
        df, X, y = self._df(n=8)

        def counting(batch):
            if batch.num_rows:
                runs["n"] += 1
            else:
                zero_rows["n"] += 1
            return batch

        counted = df.map_batches(counting, name="count")
        LogisticRegression(maxIter=2).fit(counted)
        assert runs["n"] == counted.num_partitions
        assert zero_rows["n"] <= 1  # the budget estimate's schema probe

    def test_fit_budget_probe_never_loads_partition0(self):
        """review r5: the default-budget sizing estimate's schema probe
        must ride the leaf schema_hint — partition 0's SOURCE must load
        exactly once (the collect pass), not once more for the probe."""
        import pyarrow as pa

        from sparkdl_tpu.data.frame import Source
        from sparkdl_tpu.data.tensors import append_tensor_column

        rng = np.random.default_rng(0)
        batch = pa.RecordBatch.from_pylist(
            [{"label": int(i % 2)} for i in range(8)])
        batch = append_tensor_column(
            batch, "features",
            rng.normal(size=(8, 3)).astype(np.float32))
        loads = {"n": 0}

        def load():
            loads["n"] += 1
            return batch

        df = DataFrame([Source(load, batch.num_rows,
                               schema_hint=batch.schema)])
        LogisticRegression(maxIter=2).fit(df)
        assert loads["n"] == 1, loads
        # HINT-LESS sources: the estimate must bail (None) rather than
        # load partition 0 just to read a column width — still exactly
        # one load (the collect), with the mid-collect watchdog
        # covering the budget instead (review r5 high #3)
        loads["n"] = 0
        df2 = DataFrame([Source(load, batch.num_rows)])
        LogisticRegression(maxIter=2).fit(df2)
        assert loads["n"] == 1, loads

    def test_bad_labels_rejected(self):
        import pyarrow as pa
        from sparkdl_tpu.data.tensors import append_tensor_column
        batch = pa.RecordBatch.from_pylist(
            [{"label": 0.5}, {"label": 1.0}])
        batch = append_tensor_column(
            batch, "features", np.zeros((2, 3), np.float32))
        df = DataFrame.from_batches([batch])
        with pytest.raises(ValueError, match="integral class ids"):
            LogisticRegression().fit(df)

    def test_empty_dataset_rejected(self):
        import pyarrow as pa
        from sparkdl_tpu.data.tensors import append_tensor_column
        batch = pa.RecordBatch.from_pylist([{"label": 0}])
        batch = append_tensor_column(
            batch, "features", np.zeros((1, 3), np.float32))
        df = DataFrame.from_batches([batch]).filter_rows(
            np.zeros(1, dtype=bool))
        with pytest.raises(ValueError, match="empty"):
            LogisticRegression().fit(df)


class TestTransferLearningPipeline:
    def test_featurizer_plus_logreg(self, image_dir):
        """The reference's README headline: readImages →
        DeepImageFeaturizer → LogisticRegression, as one Pipeline."""
        from sparkdl_tpu.image import imageIO
        from sparkdl_tpu.transformers import DeepImageFeaturizer

        df = imageIO.readImages(image_dir, numPartitions=2)
        n = df.count()
        labels = np.arange(n) % 2

        # attach labels by row order
        table = df.collect()
        import pyarrow as pa
        table = table.append_column("label",
                                    pa.array(labels, type=pa.int64()))
        labeled = DataFrame.from_table(table, num_partitions=2)

        pipe = Pipeline(stages=[
            DeepImageFeaturizer(modelName="TestNet", inputCol="image",
                                outputCol="features"),
            LogisticRegression(featuresCol="features", labelCol="label",
                               maxIter=60, learningRate=0.2),
        ])
        model = pipe.fit(labeled)
        out = model.transform(labeled)
        probs = out.tensor("probability")
        assert probs.shape == (n, 2)
        ev = ClassificationEvaluator(predictionCol="prediction",
                                     labelCol="label")
        assert 0.0 <= ev.evaluate(out) <= 1.0

    def test_transfer_learning_reaches_accuracy(self, tmp_path):
        """The accuracy story, end-to-end at small scale (VERDICT r2
        missing #1 / next #4): the committed TRAINED TestNet artifact
        featurizes generated two-class images, a LogisticRegression
        head fits on the features, and train accuracy clears a real
        threshold — the semantic counterpart of BASELINE config #1
        (DeepImageFeaturizer → LogisticRegression), which random
        weights could only exercise mechanically."""
        from PIL import Image

        from sparkdl_tpu.image import imageIO
        from sparkdl_tpu.transformers import DeepImageFeaturizer

        rng = np.random.default_rng(21)
        labels = []
        for i in range(24):
            label = i % 2
            base = 45 if label == 0 else 205
            arr = np.clip(rng.normal(base, 14, (32, 32, 3)),
                          0, 255).astype(np.uint8)
            Image.fromarray(arr, "RGB").save(tmp_path / f"c{i:02d}.png")
            labels.append(label)

        table = imageIO.readImages(str(tmp_path), numPartitions=3) \
            .collect()
        # readImages globs in sorted order; labels follow the filename
        # index
        import pyarrow as pa
        order = [int(p[-6:-4]) for p in
                 table.column("filePath").to_pylist()]
        y = np.array([labels[i] for i in order])
        labeled = DataFrame.from_table(
            table.append_column("label", pa.array(y, type=pa.int64())),
            num_partitions=3)

        model = Pipeline(stages=[
            DeepImageFeaturizer(modelName="TestNet", inputCol="image",
                                outputCol="features"),
            LogisticRegression(featuresCol="features", labelCol="label",
                               maxIter=80, learningRate=0.2),
        ]).fit(labeled)
        out = model.transform(labeled)
        acc = ClassificationEvaluator(predictionCol="prediction",
                                      labelCol="label").evaluate(out)
        assert acc >= 0.9, f"transfer-learning accuracy {acc} < 0.9"

    def test_predictor_semantics_on_trained_artifact(self, tmp_path):
        """VERDICT r3 missing #3 / next #6: the PREDICTOR analogue of
        the featurizer pin above. DeepImagePredictor(decodePredictions=
        True) over the committed trained TestNet artifact must put the
        TRUE class first, with names resolved from the artifact's
        class-index metadata — semantics, not just top-K mechanics."""
        from PIL import Image

        from sparkdl_tpu.image import imageIO
        from sparkdl_tpu.models.testnet import synthetic_testnet_dataset
        from sparkdl_tpu.transformers import DeepImagePredictor

        # a FRESH eval split (seed differs from both training splits in
        # the provenance sidecar) over the same prototype classes; PNG
        # is lossless, so the frame sees the exact generated pixels
        imgs, labels = synthetic_testnet_dataset(48, seed=7)
        for i, arr in enumerate(imgs):
            Image.fromarray(arr, "RGB").save(tmp_path / f"e{i:02d}.png")

        df = imageIO.readImages(str(tmp_path), numPartitions=3)
        out = DeepImagePredictor(
            modelName="TestNet", inputCol="image", outputCol="preds",
            decodePredictions=True, topK=3).transform(df)
        table = out.collect()
        order = [int(p[-6:-4])
                 for p in table.column("filePath").to_pylist()]
        rows = table.column("preds").to_pylist()
        hits = 0
        for row, img_i in zip(rows, order):
            assert len(row) == 3
            assert row[0]["score"] >= row[1]["score"] >= row[2]["score"]
            if row[0]["class"] == f"proto_{labels[img_i]}":
                hits += 1
        top1 = hits / len(rows)
        assert top1 >= 0.95, f"predictor top-1 accuracy {top1} < 0.95"
        # names came from the artifact's class-index sidecar, not the
        # ImageNet fallback
        assert rows[0][0]["description"].startswith("prototype_")

    def test_predictor_class_index_file_override(self, tmp_path):
        """classIndexFile: user-supplied class metadata wins over the
        model's own sidecar (the reference's decode_predictions index
        mechanism, made explicit)."""
        import json

        from PIL import Image

        from sparkdl_tpu.image import imageIO
        from sparkdl_tpu.models.testnet import synthetic_testnet_dataset
        from sparkdl_tpu.transformers import DeepImagePredictor

        imgs, labels = synthetic_testnet_dataset(6, seed=9)
        for i, arr in enumerate(imgs):
            Image.fromarray(arr, "RGB").save(tmp_path / f"o{i}.png")
        index_file = tmp_path / "index.json"
        index_file.write_text(json.dumps(
            {str(i): [f"id{i}", f"species_{i}"] for i in range(10)}))

        df = imageIO.readImages(str(tmp_path), numPartitions=1)
        out = DeepImagePredictor(
            modelName="TestNet", inputCol="image", outputCol="preds",
            decodePredictions=True, topK=1,
            classIndexFile=str(index_file)).transform(df)
        for row in out.collect().column("preds").to_pylist():
            assert row[0]["class"].startswith("id")
            assert row[0]["description"].startswith("species_")
