"""Worker for test_distributed_multiproc's multi-host STREAMING
estimator fit: one process of a 2-process CPU cluster training one
Keras model data-parallel over the pod-wide mesh, each host streaming
only its own partition shard."""

import json
import sys


def main() -> None:
    pid = int(sys.argv[1])
    port = sys.argv[2]
    images_dir = sys.argv[3]
    model_file = sys.argv[4]
    num_partitions = int(sys.argv[5]) if len(sys.argv) > 5 else 4

    import numpy as np

    from sparkdl_tpu.parallel import distributed as dist

    dist.initialize(coordinator_address=f"127.0.0.1:{port}",
                    num_processes=2, process_id=pid)

    import glob
    import os

    from sparkdl_tpu.data import DataFrame
    from sparkdl_tpu.estimators import KerasImageFileEstimator

    rows = []
    for p in sorted(glob.glob(os.path.join(images_dir, "*.png"))):
        label = int(os.path.basename(p).split("_")[1].split(".")[0]) % 2
        rows.append({"uri": p, "label": label})
    df = DataFrame.from_pylist(rows, num_partitions=num_partitions)

    def loader(uri):
        from PIL import Image
        return np.asarray(Image.open(uri).convert("RGB"),
                          dtype=np.float32) / 255.0

    est = KerasImageFileEstimator(
        inputCol="uri", outputCol="pred", labelCol="label",
        imageLoader=loader, modelFile=model_file,
        kerasOptimizer="adam", kerasLoss="categorical_crossentropy",
        kerasFitParams={"epochs": 2, "batch_size": 8,
                        "learning_rate": 0.05, "seed": 3},
        streaming=True, useMesh=True)
    model = est.fit(df)

    # weight digest proves every host converged to identical params
    leaves = [np.asarray(v) for v in
              model.modelFunction.params["trainable"]]
    digest = float(sum(np.abs(a).sum() for a in leaves))

    mine = dist.host_shard_dataframe(df)
    print("RESULT " + json.dumps({
        "pid": pid,
        "history": model.history,
        "weight_digest": digest,
        "local_partitions": mine.num_partitions,
    }), flush=True)


if __name__ == "__main__":
    main()
