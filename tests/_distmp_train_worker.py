"""Worker for test_distributed_multiproc's multi-host STREAMING
estimator fit: one process of a 2-process CPU cluster training one
Keras model data-parallel over the pod-wide mesh, each host streaming
only its own partition shard."""

import json
import sys


def main() -> None:
    pid = int(sys.argv[1])
    port = sys.argv[2]
    images_dir = sys.argv[3]
    model_file = sys.argv[4]
    num_partitions = int(sys.argv[5]) if len(sys.argv) > 5 else 4
    # optional: a checkpoint dir triggers the interrupted-run scenario
    # (fit 1 epoch with checkpoints, then extend to 2 — must resume and
    # land exactly where the uninterrupted 2-epoch fit lands)
    ckpt_dir = sys.argv[6] if len(sys.argv) > 6 else None

    import numpy as np

    from sparkdl_tpu.parallel import distributed as dist

    dist.initialize(coordinator_address=f"127.0.0.1:{port}",
                    num_processes=2, process_id=pid)

    import jax

    # persistent compile cache: the checkpoint scenario runs THREE fits
    # of the same program shapes — compile once (concurrent-safe:
    # atomic renames)
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/sparkdl_tpu_jax_cache_mp")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)

    import glob
    import os

    from sparkdl_tpu.data import DataFrame
    from sparkdl_tpu.estimators import KerasImageFileEstimator

    rows = []
    for p in sorted(glob.glob(os.path.join(images_dir, "*.png"))):
        label = int(os.path.basename(p).split("_")[1].split(".")[0]) % 2
        rows.append({"uri": p, "label": label})
    df = DataFrame.from_pylist(rows, num_partitions=num_partitions)

    def loader(uri):
        from PIL import Image
        return np.asarray(Image.open(uri).convert("RGB"),
                          dtype=np.float32) / 255.0

    def make_est(epochs, checkpointDir=None, cacheDecoded=False):
        kw = dict(
            inputCol="uri", outputCol="pred", labelCol="label",
            imageLoader=loader, modelFile=model_file,
            kerasOptimizer="adam", kerasLoss="categorical_crossentropy",
            kerasFitParams={"epochs": epochs, "batch_size": 8,
                            "learning_rate": 0.05, "seed": 3},
            streaming=True, useMesh=True, cacheDecoded=cacheDecoded)
        if checkpointDir:
            kw["checkpointDir"] = checkpointDir
        return KerasImageFileEstimator(**kw)

    def digest_of(model):
        # weight digest proves every host holds identical params
        leaves = [np.asarray(v) for v in
                  model.modelFunction.params["trainable"]]
        return float(sum(np.abs(a).sum() for a in leaves))

    model = make_est(epochs=2).fit(df)

    result = {
        "pid": pid,
        "history": model.history,
        "weight_digest": digest_of(model),
        "local_partitions": dist.host_shard_dataframe(df).num_partitions,
    }

    if not ckpt_dir:
        # cacheDecoded in the multi-host path: each host spills only
        # ITS shard; epoch 2 streams the cache. Must land on the exact
        # same replicated state as the uncached fit above.
        cached = make_est(epochs=2, cacheDecoded=True).fit(df)
        result["cached_history"] = cached.history
        result["cached_digest"] = digest_of(cached)

    if ckpt_dir:
        # interrupted: 1 epoch saved, then the same config extended to
        # 2 epochs resumes from the per-host checkpoint (every host
        # agrees on the resume step over DCN) and must match the
        # uninterrupted run above bit-for-bit in history and weights
        short = make_est(epochs=1, checkpointDir=ckpt_dir).fit(df)
        resumed = make_est(epochs=2, checkpointDir=ckpt_dir).fit(df)
        result["short_history"] = short.history
        result["resumed_history"] = resumed.history
        result["resumed_digest"] = digest_of(resumed)
        # observable resume proof: a silent from-scratch retrain would
        # reproduce identical history/weights (deterministic seeds), so
        # assert the restore actually happened via resumedFrom
        result["short_resumed_from"] = short.resumedFrom
        result["resumed_from"] = resumed.resumedFrom

    print("RESULT " + json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
