"""Static race detector tests: thread-topology inference + the
H17/H18/H19 guarded-by consistency rules, plus the runtime
cross-check (``assert_lock_owned`` under ``SPARKDL_TPU_SANITIZE=1``).

Fixture style mirrors tests/test_callgraph.py: deliberately racy
multi-module trees under tmp_path trip the rules WITH their full
witnesses (both thread roots, the lock identity, the guarded-by
evidence); the locked/atomic/double-checked clean forms stay silent;
inline suppressions downgrade without hiding. The real package is
pinned twice: its known concurrent loops must be IN the thread-root
inventory (a moved spawn site must not silently drop them) and the
whole package must be clean under the three rules — including the
three real fixes this sweep landed (server close, ledger verdict,
policy state code), each pinned by a source regression test.
"""

import os

import pytest

import sparkdl_tpu
from sparkdl_tpu.analysis import analyze_paths, build_graph
from sparkdl_tpu.analysis import cache as cache_mod
from sparkdl_tpu.analysis import iter_python_files
from sparkdl_tpu.analysis.races import _guard_model
from sparkdl_tpu.analysis.threads import thread_topology
from sparkdl_tpu.analysis.walker import ALL_RULES

PKG_DIR = os.path.dirname(os.path.abspath(sparkdl_tpu.__file__))
REPO_ROOT = os.path.dirname(PKG_DIR)

RACE_RULES = ["H17", "H18", "H19"]


def _tree(tmp_path, files: dict) -> str:
    for name, src in files.items():
        (tmp_path / name).write_text(src)
    return str(tmp_path)


def _unsup(findings, rule):
    return [f for f in findings if f.rule == rule and not f.suppressed]


def _sup(findings, rule):
    return [f for f in findings if f.rule == rule and f.suppressed]


_package_graph_cache = {}


def _package_graph():
    """The full-package CallGraph, built once per test run (the
    topology + guard model memoize onto it)."""
    if "g" not in _package_graph_cache:
        _package_graph_cache["g"] = build_graph(
            list(iter_python_files(PKG_DIR)))
    return _package_graph_cache["g"]


# ---------------------------------------------------------------------------
# H17 — unguarded access to a guarded attribute


H17_RACY = (
    "import threading\n"
    "\n"
    "class Buf:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self.items = []\n"
    "\n"
    "    def start(self):\n"
    "        t = threading.Thread(target=self.worker)\n"
    "        t.start()\n"
    "\n"
    "    def worker(self):\n"
    "        with self._lock:\n"
    "            self.items.append(1)\n"
    "\n"
    "    def size(self):\n"
    "        with self._lock:\n"
    "            return len(self.items)\n"
    "\n"
    "    def clear(self):\n"
    "        with self._lock:\n"
    "            self.items.clear()\n"
    "\n"
    "    def peek(self):\n"
    "        return self.items[0]\n")


class TestH17:
    def test_unguarded_read_fires_with_full_witness(self, tmp_path):
        root = _tree(tmp_path, {"m.py": H17_RACY})
        found = analyze_paths([root], rules=RACE_RULES,
                              cache_path=None)
        hits = _unsup(found, "H17")
        assert len(hits) == 1, [f.render() for f in hits]
        f = hits[0]
        assert f.qualname == "Buf.peek"
        # the witness: lock identity + majority evidence + BOTH
        # thread roots (the spawned worker and the implicit main)
        assert "m:Buf._lock" in f.message
        assert "majority evidence" in f.message
        assert "held at 5 of 6 accesses" in f.message
        assert "the main thread" in f.message
        assert "shares" in f.message and "instance state" in f.message

    def test_fully_locked_class_is_silent(self, tmp_path):
        src = H17_RACY.replace(
            "    def peek(self):\n"
            "        return self.items[0]\n",
            "    def peek(self):\n"
            "        with self._lock:\n"
            "            return self.items[0]\n")
        root = _tree(tmp_path, {"m.py": src})
        found = analyze_paths([root], rules=RACE_RULES,
                              cache_path=None)
        assert _unsup(found, "H17") == []

    def test_single_threaded_class_is_exempt(self, tmp_path):
        # same racy shape, but NO spawn anywhere: one thread, no race
        src = H17_RACY.replace(
            "    def start(self):\n"
            "        t = threading.Thread(target=self.worker)\n"
            "        t.start()\n", "")
        root = _tree(tmp_path, {"m.py": src})
        found = analyze_paths([root], rules=RACE_RULES,
                              cache_path=None)
        assert _unsup(found, "H17") == []

    def test_inline_suppression_downgrades_without_hiding(
            self, tmp_path):
        src = H17_RACY.replace(
            "        return self.items[0]\n",
            "        return self.items[0]  "
            "# sparkdl-lint: allow[H17] -- reader tolerates staleness\n")
        root = _tree(tmp_path, {"m.py": src})
        found = analyze_paths([root], rules=RACE_RULES,
                              cache_path=None)
        assert _unsup(found, "H17") == []
        sup = _sup(found, "H17")
        assert len(sup) == 1
        assert "reader tolerates staleness" in sup[0].suppression

    def test_init_never_votes_and_is_never_flagged(self, tmp_path):
        # __init__ assigns without the lock at two sites; they must
        # neither dilute the vote nor be flagged themselves
        src = H17_RACY.replace(
            "        self.items = []\n",
            "        self.items = []\n"
            "        self.items.append(0)\n")
        root = _tree(tmp_path, {"m.py": src})
        found = analyze_paths([root], rules=RACE_RULES,
                              cache_path=None)
        hits = _unsup(found, "H17")
        assert len(hits) == 1
        assert hits[0].qualname == "Buf.peek"
        assert "held at 5 of 6 accesses" in hits[0].message

    def test_two_module_witness_chain(self, tmp_path):
        root = _tree(tmp_path, {
            "w.py": (
                "import threading\n"
                "\n"
                "class Shared:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n"
                "        self.n = 0\n"
                "    def bump(self):\n"
                "        with self._lock:\n"
                "            self.n += 1\n"
                "    def sync_read(self):\n"
                "        with self._lock:\n"
                "            return self.n\n"
                "    def racy_read(self):\n"
                "        return self.n\n"
                "\n"
                "def run(obj):\n"
                "    obj.bump()\n"),
            "s.py": (
                "import threading\n"
                "from w import run\n"
                "\n"
                "def launch(obj):\n"
                "    t = threading.Thread(target=run, args=(obj,))\n"
                "    t.start()\n")})
        found = analyze_paths([root], rules=RACE_RULES,
                              cache_path=None)
        hits = _unsup(found, "H17")
        assert len(hits) == 1, [f.render() for f in hits]
        f = hits[0]
        assert f.qualname == "Shared.racy_read"
        # the chain crosses the module boundary: spawned in s.py,
        # runs w.run -> Shared.bump, shares the instance with
        # racy_read
        assert "w:run" in f.message
        assert "w:Shared.bump" in f.message
        assert "shares" in f.message and "instance state" in f.message

    def test_lock_guards_declaration_is_authoritative(self, tmp_path):
        # the vote alone would NOT guard `state` (held at 1 of 3
        # accesses) — the class-body declaration overrides it
        root = _tree(tmp_path, {"m.py": (
            "import threading\n"
            "\n"
            "class S:\n"
            "    _lock_guards = (\"state\",)\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.state = \"idle\"\n"
            "    def start(self):\n"
            "        threading.Thread(target=self.run).start()\n"
            "    def run(self):\n"
            "        with self._lock:\n"
            "            self.state = \"running\"\n"
            "    def status(self):\n"
            "        return self.state\n"
            "    def reset(self):\n"
            "        self.state = \"idle\"\n")})
        found = analyze_paths([root], rules=RACE_RULES,
                              cache_path=None)
        hits = _unsup(found, "H17")
        # the read in status() fires on the declaration's authority;
        # the plain WRITE in reset() is H3's beat — H17 skips it so
        # one decision never needs two suppressions
        assert len(hits) == 1, [f.render() for f in hits]
        assert hits[0].qualname == "S.status"
        assert "declared by `_lock_guards`" in hits[0].message
        assert all(h.qualname != "S.reset" for h in hits)


# ---------------------------------------------------------------------------
# H18 — unsafe publication of mutable state


class TestH18:
    def test_argument_handoff_mutated_both_sides(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "import threading\n"
            "\n"
            "def worker(buf):\n"
            "    buf.append(1)\n"
            "\n"
            "def main():\n"
            "    buf = []\n"
            "    t = threading.Thread(target=worker, args=(buf,))\n"
            "    t.start()\n"
            "    buf.append(2)\n")})
        found = analyze_paths([root], rules=RACE_RULES,
                              cache_path=None)
        hits = _unsup(found, "H18")
        assert len(hits) == 1, [f.render() for f in hits]
        f = hits[0]
        assert f.qualname == "main"
        assert "mutable local `buf`" in f.message
        assert "a thread target" in f.message
        assert "m:worker" in f.message
        assert "`buf` parameter" in f.message

    def test_closure_capture_mutated_both_sides(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "import threading\n"
            "\n"
            "def main():\n"
            "    buf = []\n"
            "    def worker():\n"
            "        buf.append(1)\n"
            "    t = threading.Thread(target=worker)\n"
            "    t.start()\n"
            "    buf.append(2)\n")})
        found = analyze_paths([root], rules=RACE_RULES,
                              cache_path=None)
        hits = _unsup(found, "H18")
        assert len(hits) == 1, [f.render() for f in hits]
        assert "captured by" in hits[0].message

    def test_common_lock_on_both_sides_is_silent(self, tmp_path):
        # the SAME lexical lock seen from the spawner and from the
        # nested target carries two function-scoped ids but one name
        # — the token comparison must recognize it as common
        root = _tree(tmp_path, {"m.py": (
            "import threading\n"
            "\n"
            "def main():\n"
            "    lock = threading.Lock()\n"
            "    buf = []\n"
            "    def worker():\n"
            "        with lock:\n"
            "            buf.append(1)\n"
            "    t = threading.Thread(target=worker)\n"
            "    t.start()\n"
            "    with lock:\n"
            "        buf.append(2)\n")})
        found = analyze_paths([root], rules=RACE_RULES,
                              cache_path=None)
        assert _unsup(found, "H18") == []

    def test_handoff_without_spawner_mutation_is_silent(
            self, tmp_path):
        # publishing and then never touching it again is the
        # immutable-snapshot discipline — no finding
        root = _tree(tmp_path, {"m.py": (
            "import threading\n"
            "\n"
            "def worker(buf):\n"
            "    buf.append(1)\n"
            "\n"
            "def main():\n"
            "    buf = []\n"
            "    buf.append(0)\n"
            "    t = threading.Thread(target=worker, args=(buf,))\n"
            "    t.start()\n")})
        found = analyze_paths([root], rules=RACE_RULES,
                              cache_path=None)
        assert _unsup(found, "H18") == []

    def test_inline_suppression_downgrades(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "import threading\n"
            "\n"
            "def worker(buf):\n"
            "    buf.append(1)\n"
            "\n"
            "def main():\n"
            "    buf = []\n"
            "    t = threading.Thread(target=worker, args=(buf,))  "
            "# sparkdl-lint: allow[H18] -- join() below serializes\n"
            "    t.start()\n"
            "    t.join()\n"
            "    buf.append(2)\n")})
        found = analyze_paths([root], rules=RACE_RULES,
                              cache_path=None)
        assert _unsup(found, "H18") == []
        sup = _sup(found, "H18")
        assert len(sup) == 1
        assert "join() below serializes" in sup[0].suppression


# ---------------------------------------------------------------------------
# H19 — atomicity split (check-then-act across separate holds)


H19_SPLIT = (
    "import threading\n"
    "\n"
    "class Q:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self.rows = []\n"
    "        self.cap = 4\n"
    "\n"
    "    def start(self):\n"
    "        threading.Thread(target=self.drain).start()\n"
    "\n"
    "    def drain(self):\n"
    "        with self._lock:\n"
    "            if self.rows:\n"
    "                self.rows.pop()\n"
    "\n"
    "    def offer(self, row):\n"
    "        with self._lock:\n"
    "            if len(self.rows) >= self.cap:\n"
    "                return False\n"
    "        with self._lock:\n"
    "            self.rows.append(row)\n"
    "        return True\n")


class TestH19:
    def test_split_check_then_act_fires(self, tmp_path):
        root = _tree(tmp_path, {"m.py": H19_SPLIT})
        found = analyze_paths([root], rules=RACE_RULES,
                              cache_path=None)
        hits = _unsup(found, "H19")
        assert len(hits) == 1, [f.render() for f in hits]
        f = hits[0]
        assert f.qualname == "Q.offer"
        assert "check-then-act split on `self.rows`" in f.message
        assert "Q._lock at line 19" in f.message
        assert "SEPARATE hold at line 22" in f.message
        assert "TOCTOU" in f.message
        assert "the main thread" in f.message

    def test_single_hold_is_atomic_and_silent(self, tmp_path):
        src = H19_SPLIT.replace(
            "    def offer(self, row):\n"
            "        with self._lock:\n"
            "            if len(self.rows) >= self.cap:\n"
            "                return False\n"
            "        with self._lock:\n"
            "            self.rows.append(row)\n",
            "    def offer(self, row):\n"
            "        with self._lock:\n"
            "            if len(self.rows) >= self.cap:\n"
            "                return False\n"
            "            self.rows.append(row)\n")
        root = _tree(tmp_path, {"m.py": src})
        found = analyze_paths([root], rules=RACE_RULES,
                              cache_path=None)
        assert _unsup(found, "H19") == []

    def test_double_checked_locking_is_the_remedy_not_the_hazard(
            self, tmp_path):
        src = H19_SPLIT.replace(
            "        with self._lock:\n"
            "            self.rows.append(row)\n",
            "        with self._lock:\n"
            "            if len(self.rows) < self.cap:\n"
            "                self.rows.append(row)\n")
        root = _tree(tmp_path, {"m.py": src})
        found = analyze_paths([root], rules=RACE_RULES,
                              cache_path=None)
        assert _unsup(found, "H19") == []

    def test_inline_suppression_downgrades(self, tmp_path):
        src = H19_SPLIT.replace(
            "            self.rows.append(row)\n",
            "            self.rows.append(row)  "
            "# sparkdl-lint: allow[H19] -- overshoot by one row is "
            "acceptable here\n")
        root = _tree(tmp_path, {"m.py": src})
        found = analyze_paths([root], rules=RACE_RULES,
                              cache_path=None)
        assert _unsup(found, "H19") == []
        sup = _sup(found, "H19")
        assert len(sup) == 1
        assert "overshoot by one row" in sup[0].suppression


# ---------------------------------------------------------------------------
# the real package: thread-root inventory + guarded-by pins


class TestRealPackageTopology:
    def test_known_concurrent_loops_are_roots(self):
        topo = thread_topology(_package_graph())
        roots = set(topo.roots)
        assert ("sparkdl_tpu.serve.server::"
                "ModelSession._serve_loop") in roots
        assert ("sparkdl_tpu.obs.watchdog::"
                "StallWatchdog._monitor") in roots
        assert ("sparkdl_tpu.autotune.core::"
                "AutotuneController.step") in roots
        # the pipeline worker pool + the flight recorder's signal
        # handler arrive via spawn-site detection, not the table
        assert ("sparkdl_tpu.data.pipeline::"
                "_pooled_partition_task") in roots
        assert ("sparkdl_tpu.obs.flight::"
                "FlightRecorder._install_signal._on_sigusr2") in roots

    def test_autotune_apply_path_is_multi_worker(self):
        topo = thread_topology(_package_graph())
        root = topo.roots[
            "sparkdl_tpu.autotune.core::AutotuneController.step"]
        assert root.multi

    def test_hot_structures_are_concurrent(self):
        topo = thread_topology(_package_graph())
        for key in (
                "sparkdl_tpu.serve.batching::RequestQueue.offer",
                "sparkdl_tpu.serve.batching::RequestQueue.collect",
                "sparkdl_tpu.obs.watchdog::StallWatchdog.pulse",
                "sparkdl_tpu.obs.registry::Reservoir.observe",
                "sparkdl_tpu.data.pipeline::"
                "HostPipeline._retire_locked"):
            assert topo.is_concurrent(key), key

    def test_single_threaded_helpers_stay_out(self):
        # the analyzer's own code and the jit-cache accessor run on
        # whatever single thread calls them — no spawn root reaches
        # them, so the race rules must leave them alone
        topo = thread_topology(_package_graph())
        for key in (
                "sparkdl_tpu.analysis.suppress::"
                "SuppressionIndex.lookup",
                "sparkdl_tpu.graph.function::ModelFunction.jitted"):
            assert not topo.is_concurrent(key), key

    def test_request_queue_guards_are_declared(self):
        model = _guard_model(_package_graph())
        gi = model.guards.get(
            ("sparkdl_tpu.serve.batching::RequestQueue", "rows"))
        assert gi is not None and gi.declared
        assert gi.lock == \
            "sparkdl_tpu.serve.batching::RequestQueue._lock"


# ---------------------------------------------------------------------------
# the sweep's fixes + the acceptance gate


class TestRealPackageClean:
    def test_package_tools_examples_clean_under_race_rules(self):
        targets = [PKG_DIR]
        for extra in ("tools", "examples"):
            d = os.path.join(REPO_ROOT, extra)
            if os.path.isdir(d):
                targets.append(d)
        found = analyze_paths(targets, rules=RACE_RULES,
                              cache_path=None)
        unsup = [f for f in found if not f.suppressed]
        assert unsup == [], "\n".join(f.render() for f in unsup)

    def test_server_close_reads_worker_under_lock(self):
        """Regression pin for the sweep's serve fix: close() must
        read the dispatcher handle under the session lock (a racing
        submit() may be swapping a fresh worker in)."""
        with open(os.path.join(PKG_DIR, "serve", "server.py")) as f:
            src = f.read()
        assert "with self._lock:\n            worker = self._worker" \
            in src

    def test_ledger_verdict_reads_ceilings_under_lock(self):
        with open(os.path.join(PKG_DIR, "obs", "ledger.py")) as f:
            src = f.read()
        assert "with self._lock:\n" \
               "            ceilings = self._ceilings or {}" in src

    def test_policy_state_code_reads_under_lock(self):
        with open(os.path.join(PKG_DIR, "resilience",
                               "policy.py")) as f:
            src = f.read()
        assert "with self._lock:\n" \
               "            return _STATE_CODES[self.state]" in src


# ---------------------------------------------------------------------------
# serialization: the facts ride the cache (ANALYZER_VERSION 8)


class TestRaceFactsCache:
    def test_analyzer_version_is_eight(self):
        """The thread/race facts changed the ModuleFacts schema; v8
        is what forces every v7 cache entry cold. A future schema
        change must bump again — update this pin when it does."""
        assert cache_mod.ANALYZER_VERSION == 8

    def test_race_findings_survive_the_cache_round_trip(
            self, tmp_path):
        root = _tree(tmp_path, {"m.py": H17_RACY,
                                "q.py": H19_SPLIT})
        cache = str(tmp_path / "cache.json")
        cold = analyze_paths([root], rules=RACE_RULES,
                             cache_path=cache)
        stats: dict = {}
        warm = analyze_paths([root], rules=RACE_RULES,
                             cache_path=cache, cache_stats=stats)
        assert stats["hits"] == 2 and stats["misses"] == 0
        assert [f.render() for f in cold] == \
            [f.render() for f in warm]
        assert _unsup(warm, "H17") and _unsup(warm, "H19")

    def test_all_rules_has_nineteen_entries(self):
        assert len(ALL_RULES) == 19
        assert {"H17", "H18", "H19"} <= set(ALL_RULES)


# ---------------------------------------------------------------------------
# the runtime cross-check: assert_lock_owned under SPARKDL_TPU_SANITIZE


class TestAssertLockOwned:
    def test_noop_when_sanitize_is_off(self, monkeypatch):
        import threading
        from sparkdl_tpu.runtime.sanitize import assert_lock_owned
        monkeypatch.delenv("SPARKDL_TPU_SANITIZE", raising=False)
        assert_lock_owned(threading.Lock(), "x")     # held or not
        assert_lock_owned(None, "x")                 # even None

    def test_armed_raises_on_unheld_and_none(self, monkeypatch):
        import threading
        from sparkdl_tpu.runtime.sanitize import assert_lock_owned
        monkeypatch.setenv("SPARKDL_TPU_SANITIZE", "1")
        lock = threading.Lock()
        with pytest.raises(AssertionError, match="caller-holds"):
            assert_lock_owned(lock, "helper")
        with pytest.raises(AssertionError, match="no guard"):
            assert_lock_owned(None, "helper")
        with lock:
            assert_lock_owned(lock, "helper")        # held: fine
        rlock = threading.RLock()
        with pytest.raises(AssertionError):
            assert_lock_owned(rlock, "helper")
        with rlock:
            assert_lock_owned(rlock, "helper")

    def test_serve_queue_helpers_assert_their_contract(
            self, monkeypatch):
        from sparkdl_tpu.serve.batching import RequestQueue
        monkeypatch.setenv("SPARKDL_TPU_SANITIZE", "1")
        q = RequestQueue()
        with pytest.raises(AssertionError):
            q._max_queued_priority()
        with pytest.raises(AssertionError):
            q._pick_victims(priority=1, overflow=1)
        with q._lock:
            assert q._max_queued_priority() == -1
            assert q._pick_victims(priority=1, overflow=0) == []

    def test_infeed_ring_asserts_once_checked_out(self, monkeypatch):
        import threading
        from sparkdl_tpu.runtime.runner import InfeedRing
        monkeypatch.setenv("SPARKDL_TPU_SANITIZE", "1")
        bare = InfeedRing(depth=2)
        assert bare.get(b"x" * 16) is None   # no guard: check stays off
        ring = InfeedRing(depth=2)
        guard = threading.Lock()
        ring._guard = guard
        with pytest.raises(AssertionError):
            ring.get(b"x" * 16)
        with guard:
            assert ring.get(b"x" * 16) is None
            ring.note_donated(b"x" * 16)

    def test_pool_registry_retire_asserts(self, monkeypatch):
        from sparkdl_tpu.data.pipeline import HostPipeline
        monkeypatch.setenv("SPARKDL_TPU_SANITIZE", "1")
        p = HostPipeline(mode="thread")
        with pytest.raises(AssertionError):
            p._retire_locked(None)
        with p._lock:
            assert p._retire_locked(None) is None

    def test_violations_are_counted(self, monkeypatch):
        import threading
        from sparkdl_tpu.obs import default_registry
        from sparkdl_tpu.runtime.sanitize import assert_lock_owned
        monkeypatch.setenv("SPARKDL_TPU_SANITIZE", "1")
        before = default_registry().counter(
            "sanitize.lock_violations").value
        with pytest.raises(AssertionError):
            assert_lock_owned(threading.Lock(), "counted")
        after = default_registry().counter(
            "sanitize.lock_violations").value
        assert after == before + 1
