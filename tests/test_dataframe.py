"""DataFrame/engine tests (the engine seam that replaces Spark local-mode
in the reference's test harness, SURVEY §4.1)."""

import threading

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.data import DataFrame, LocalEngine, arrow_to_tensor
from sparkdl_tpu.data.frame import Source
from sparkdl_tpu.data.tensors import append_tensor_column, tensor_shape_of


def _df(n=100, parts=7):
    return DataFrame.from_table(
        pa.table({"x": np.arange(n, dtype=np.float64),
                  "s": [f"r{i}" for i in range(n)]}), parts)


class TestConstruction:
    def test_partition_count(self):
        assert _df(100, 7).num_partitions == 7
        assert _df(3, 8).num_partitions == 3  # capped at rows

    def test_order_preserved(self):
        tab = _df(100, 7).collect()
        np.testing.assert_array_equal(tab.column("x").to_numpy(),
                                      np.arange(100))

    def test_from_pylist(self):
        df = DataFrame.from_pylist([{"a": 1}, {"a": 2}], 2)
        assert df.count() == 2

    def test_schema_and_columns(self):
        df = _df()
        assert df.columns == ["x", "s"]


class TestOps:
    def test_with_column_numpy_tensor(self):
        df = _df(10, 2).with_column(
            "t", lambda b: np.ones((b.num_rows, 2, 3), np.float32))
        t = df.tensor("t")
        assert t.shape == (10, 2, 3)

    def test_tensor_shape_metadata(self):
        batch = pa.RecordBatch.from_pydict({"x": pa.array([1.0, 2.0])})
        batch = append_tensor_column(batch, "t",
                                     np.zeros((2, 4, 5), np.float32))
        assert tensor_shape_of(batch.schema.field("t")) == (4, 5)
        back = arrow_to_tensor(batch.column(1), batch.schema.field("t"))
        assert back.shape == (2, 4, 5)

    def test_select_drop_rename(self):
        df = _df()
        assert df.select("x").columns == ["x"]
        assert df.drop("s").columns == ["x"]
        assert df.rename({"x": "y"}).columns == ["y", "s"]

    def test_filter(self):
        df = _df(100, 5).filter(
            lambda b: b.column(0).to_numpy(zero_copy_only=False) < 10)
        assert df.count() == 10

    def test_filter_rows_global_mask(self):
        mask = np.zeros(100, dtype=bool)
        mask[::2] = True
        df = _df(100, 5).filter_rows(mask)
        assert df.count() == 50
        np.testing.assert_array_equal(
            df.collect().column("x").to_numpy(), np.arange(0, 100, 2))

    def test_count_fast_path_and_slow_path(self):
        df = _df(100, 5)
        assert df.count() == 100
        assert df.filter(lambda b: b.column(0).to_numpy(
            zero_copy_only=False) >= 0).count() == 100

    def test_take_first(self):
        df = _df(100, 5)
        assert df.first()["x"] == 0.0
        assert [r["x"] for r in df.take(3)] == [0.0, 1.0, 2.0]

    def test_chained_lazy_plan(self):
        calls = []

        def stage(b):
            calls.append(1)
            return b

        df = _df(10, 2).map_batches(stage)
        assert not calls  # lazy until materialized
        df.collect()
        assert len(calls) == 2  # once per partition


class TestEngine:
    def test_host_stages_parallel(self):
        """Host stages run on multiple threads."""
        seen = set()

        def stage(b):
            seen.add(threading.current_thread().name)
            return b

        engine = LocalEngine(num_workers=4)
        df = DataFrame.from_table(
            pa.table({"x": np.arange(64.0)}), 16, engine) \
            .map_batches(stage)
        df.collect()
        assert len(seen) >= 2

    def test_with_index_stages_survive_partition_reorder(self):
        """with_index stages (sample's per-partition determinism) must
        see each partition's LOGICAL index, so reordering partitions —
        per-epoch shuffles, host sharding — keeps the same rows
        (regression: the engine passed the positional index)."""
        df = DataFrame.from_table(pa.table({"x": np.arange(400.0)}), 8)
        sampled = df.sample(0.3, seed=5)
        baseline = sorted(r["x"] for r in sampled.collect_rows())

        reordered = sampled.with_partition_order([5, 2, 7, 0, 1, 6, 3, 4])
        got = sorted(r["x"] for r in reordered.collect_rows())
        assert got == baseline

        subset = sampled.with_partition_order([3, 1])
        sub_rows = set(r["x"] for r in subset.collect_rows())
        assert sub_rows <= set(baseline)
        # nested reorder keeps the original identity pinned
        nested = sampled.with_partition_order([3, 1]) \
            .with_partition_order([1, 0])
        assert set(r["x"] for r in nested.collect_rows()) == sub_rows

        # a one-shot iterable must be read once, not consumed by the
        # bounds check and then silently produce a 0-partition frame
        gen = (i for i in [5, 2, 7, 0, 1, 6, 3, 4])
        from_gen = sorted(
            r["x"] for r in sampled.with_partition_order(gen)
            .collect_rows())
        assert from_gen == baseline

        # limit's partially-taken source keeps the pinned identity too:
        # the limited rows must be a prefix of the reordered frame's
        n_lim = 7
        tag = df.with_partition_order([5, 2, 7, 0, 1, 6, 3, 4]) \
            .map_batches(lambda b, i: b.append_column(
                "pid", pa.array([i] * b.num_rows)), with_index=True)
        full_rows = tag.collect_rows()
        lim_rows = tag.limit(n_lim).collect_rows()
        assert lim_rows == full_rows[:n_lim]

    def test_concurrent_frames_share_engine_safely(self):
        """Two frames materializing concurrently on ONE engine (the
        default-engine reality: every transformer shares it) must each
        stream their own partitions in order with no cross-talk, and
        device stages must stay globally serialized across frames."""
        active = [0]
        max_active = [0]
        lock = threading.Lock()

        def dev_stage(b):
            with lock:
                active[0] += 1
                max_active[0] = max(max_active[0], active[0])
            import time
            time.sleep(0.002)
            with lock:
                active[0] -= 1
            return b

        engine = LocalEngine(num_workers=4)
        a = DataFrame.from_table(
            pa.table({"x": np.arange(40.0)}), 8, engine) \
            .map_batches(dev_stage, kind="device")
        b = DataFrame.from_table(
            pa.table({"x": np.arange(100.0, 140.0)}), 8, engine) \
            .map_batches(dev_stage, kind="device")

        results = {}

        def run(name, df):
            results[name] = [r["x"] for r in df.collect_rows()]

        ta = threading.Thread(target=run, args=("a", a))
        tb = threading.Thread(target=run, args=("b", b))
        ta.start(); tb.start(); ta.join(); tb.join()

        assert results["a"] == list(np.arange(40.0))
        assert results["b"] == list(np.arange(100.0, 140.0))
        assert max_active[0] == 1  # device serialization held across frames

    def test_device_stage_serialized(self):
        """Device stages never overlap."""
        active = [0]
        max_active = [0]
        lock = threading.Lock()

        def dev_stage(b):
            with lock:
                active[0] += 1
                max_active[0] = max(max_active[0], active[0])
            import time
            time.sleep(0.005)
            with lock:
                active[0] -= 1
            return b

        engine = LocalEngine(num_workers=8)
        df = DataFrame.from_table(
            pa.table({"x": np.arange(64.0)}), 16, engine) \
            .map_batches(dev_stage, kind="device")
        df.collect()
        assert max_active[0] == 1

    def test_stream_order(self):
        df = _df(50, 10)
        batches = list(df.stream())
        xs = np.concatenate(
            [b.column(0).to_numpy(zero_copy_only=False) for b in batches])
        np.testing.assert_array_equal(xs, np.arange(50))


class TestJoin:
    def _frames(self):
        left = DataFrame.from_table(
            pa.table({"path": [f"p{i}" for i in range(8)],
                      "x": np.arange(8.0)}), 3)
        right = DataFrame.from_table(
            pa.table({"path": [f"p{i}" for i in range(0, 8, 2)],
                      "label": [10, 12, 14, 16]}), 2)
        return left, right

    def test_inner_join_attaches_and_drops(self):
        left, right = self._frames()
        out = left.join(right, on="path").collect()
        assert out.column("path").to_pylist() == \
            ["p0", "p2", "p4", "p6"]
        assert out.column("label").to_pylist() == [10, 12, 14, 16]
        assert out.column("x").to_pylist() == [0.0, 2.0, 4.0, 6.0]

    def test_left_join_keeps_unmatched_with_nulls(self):
        left, right = self._frames()
        out = left.join(right, on="path", how="left").collect()
        assert out.num_rows == 8
        labels = out.column("label").to_pylist()
        assert labels[0::2] == [10, 12, 14, 16]
        assert labels[1::2] == [None] * 4

    def test_join_preserves_tensor_columns(self):
        feats = np.arange(12, dtype=np.float32).reshape(4, 3)
        rb = pa.RecordBatch.from_pylist(
            [{"path": f"p{i}"} for i in range(4)])
        rb = append_tensor_column(rb, "feat", feats)
        right = DataFrame.from_batches([rb])
        left = DataFrame.from_table(
            pa.table({"path": [f"p{i}" for i in range(4)]}), 2)
        out = left.join(right, on="path")
        np.testing.assert_array_equal(out.tensor("feat"), feats)

    def test_join_validation(self):
        left, right = self._frames()
        with pytest.raises(KeyError):
            left.join(right, on="nope")
        with pytest.raises(ValueError, match="how"):
            left.join(right, on="path", how="outer")
        with pytest.raises(ValueError, match="at least one"):
            left.join(right, on=[])
        dup = DataFrame.from_table(
            pa.table({"path": ["p0", "p0"], "label": [1, 2]}), 1)
        with pytest.raises(ValueError, match="duplicate join key"):
            left.join(dup, on="path").collect()
        clash = DataFrame.from_table(
            pa.table({"path": ["p0"], "x": [9.0]}), 1)
        with pytest.raises(ValueError, match="both"):
            left.join(clash, on="path")

    def test_broadcast_size_guard(self):
        """VERDICT r3 weak #7: a right side over the broadcast contract
        raises a named error (not an OOM), before full materialization
        for the row guard; limits are explicitly raisable."""
        left, right = self._frames()
        with pytest.raises(ValueError, match="broadcast_limit_rows"):
            left.join(right, on="path", broadcast_limit_rows=2)
        with pytest.raises(ValueError, match="broadcast_limit_bytes"):
            left.join(right, on="path", broadcast_limit_bytes=16)
        # raising the limit explicitly lets the join through
        out = left.join(right, on="path",
                        broadcast_limit_rows=4).collect()
        assert out.num_rows == 4

    def test_multi_key_separator_safety(self):
        """Key values containing the composite separator must neither
        collide (('x\\x1fy','z') vs ('x','y\\x1fz')) nor mis-match."""
        left = DataFrame.from_table(
            pa.table({"a": ["x\x1fy", "x"], "b": ["z", "y\x1fz"],
                      "v": [1.0, 2.0]}), 1)
        right = DataFrame.from_table(
            pa.table({"a": ["x\x1fy", "x"], "b": ["z", "y\x1fz"],
                      "tag": ["first", "second"]}), 1)
        out = left.join(right, on=["a", "b"]).collect()
        assert out.column("tag").to_pylist() == ["first", "second"]

    def test_join_schema_probe_and_empty_partitions(self):
        """.schema / .columns on a joined frame probes the stage with a
        zero-row batch — the inner-join mask must stay boolean-typed
        there (regression: empty pa.array infers type null, which
        filter() rejects)."""
        left, right = self._frames()
        joined = left.join(right, on="path")
        assert joined.columns == ["path", "x", "label"]
        assert joined.limit(2).collect().num_rows == 2

    def test_multi_key_join(self):
        left = DataFrame.from_table(
            pa.table({"a": [1, 1, 2], "b": ["x", "y", "x"],
                      "v": [1.0, 2.0, 3.0]}), 2)
        right = DataFrame.from_table(
            pa.table({"a": [1, 2], "b": ["y", "x"],
                      "tag": ["one-y", "two-x"]}), 1)
        out = left.join(right, on=["a", "b"]).collect()
        assert out.column("v").to_pylist() == [2.0, 3.0]
        assert out.column("tag").to_pylist() == ["one-y", "two-x"]


class TestCoalesce:
    def test_merges_preserving_order_and_plan(self):
        calls = {"n": 0}

        def counting(batch):
            if batch.num_rows:
                calls["n"] += 1
            return batch

        df = _df(40, 8).map_batches(counting, name="decode")
        c = df.coalesce(3)
        assert c.num_partitions == 3
        assert c.count() == 40  # num_rows survives (row-preserving plan)
        got = c.collect().column("x").to_pylist()
        assert got == df.collect().column("x").to_pylist()
        # the plan ran once per INPUT partition per materialization —
        # coalescing composes, it doesn't re-run or collect globally
        assert calls["n"] == 8 * 2  # c.collect() + df.collect()

    def test_bounded_memory_no_global_collect(self, monkeypatch):
        """Each output partition materializes only its own group —
        streaming a coalesced frame never collects the whole table."""
        df = _df(60, 6)
        c = df.coalesce(2)
        monkeypatch.setattr(DataFrame, "collect", lambda self: (_ for _ in ()).throw(
            AssertionError("coalesce materialized the frame")))
        try:
            seen = [b.num_rows for b in c.stream()]
        finally:
            monkeypatch.undo()
        assert sum(seen) == 60 and len(seen) == 2

    def test_with_index_keeps_input_identity(self):
        """sample() must draw identically coalesced or not — the plan
        runs per INPUT partition with its logical index."""
        df = _df(80, 8).sample(0.5, seed=9)
        a = df.collect().column("x").to_pylist()
        b = df.coalesce(3).collect().column("x").to_pylist()
        assert a == b

    def test_noop_and_clamp(self):
        df = _df(10, 4)
        assert df.coalesce(4) is df
        assert df.coalesce(99) is df
        assert df.coalesce(1).num_partitions == 1
        assert df.coalesce(1).collect().column("x").to_pylist() == \
            df.collect().column("x").to_pylist()

    def test_schema_probe_decodes_nothing(self):
        """.columns on a coalesced frame must come from the pre-seeded
        schema — the load IS the baked plan over a whole group."""
        loads = {"n": 0}

        def counting(batch):
            if batch.num_rows:
                loads["n"] += 1
            return batch

        df = _df(20, 4).map_batches(counting, name="decode")
        df.schema  # probe once on the UNcoalesced frame (zero-row)
        loads["n"] = 0
        c = df.coalesce(2)
        assert c.columns == ["x", "s"]
        assert loads["n"] == 0  # no group decoded to answer .columns

    def test_ships_through_spark_engine(self):
        """A coalesced frame's sources must survive Spark task
        serialization (the group helper drops its engine on the wire)."""
        from tests.test_spark_binding import _FakeSparkSession

        from sparkdl_tpu.data.spark_binding import SparkEngine

        df = _df(24, 6).filter_rows(np.arange(24.0) >= 4)
        c = df.coalesce(2)
        engine = SparkEngine(spark=_FakeSparkSession())
        got = pa.Table.from_batches(
            list(engine.execute(c._sources, c._plan)))
        assert got.column("x").to_pylist() == \
            df.collect().column("x").to_pylist()


class TestParquetIO:
    def test_round_trip_with_tensor_columns(self, tmp_path):
        X = np.arange(40, dtype=np.float32).reshape(10, 4)
        batch = pa.RecordBatch.from_pylist(
            [{"i": int(i)} for i in range(10)])
        batch = append_tensor_column(batch, "feat", X)
        df = DataFrame.from_batches([batch, batch])
        out = str(tmp_path / "pq")
        df.write_parquet(out)

        back = DataFrame.read_parquet(out)
        assert back.num_partitions == 2
        assert back.columns == ["i", "feat"]
        np.testing.assert_array_equal(back.tensor("feat"),
                                      np.concatenate([X, X]))
        # shape metadata survived (multi-dim reshaping still works)
        assert tensor_shape_of(back.collect().schema.field("feat")) \
            == (4,)

    def test_count_reads_footers_not_data(self, tmp_path):
        df = _df(100, 4)
        out = str(tmp_path / "pq")
        df.write_parquet(out)
        back = DataFrame.read_parquet(out)
        assert back.count() == 100  # from parquet metadata (num_rows)

    def test_image_struct_round_trip(self, tmp_path, image_dir):
        from sparkdl_tpu.image import imageIO

        df = imageIO.readImages(image_dir, numPartitions=2)
        out = str(tmp_path / "imgs_pq")
        df.write_parquet(out)
        back = DataFrame.read_parquet(out)
        a = df.collect()
        b = back.collect()
        assert a.column("filePath").to_pylist() == \
            b.column("filePath").to_pylist()
        assert a.column("image").to_pylist() == \
            b.column("image").to_pylist()

    def test_no_silent_overwrite_and_missing_path(self, tmp_path):
        df = _df(10, 2)
        out = str(tmp_path / "pq")
        df.write_parquet(out)
        with pytest.raises(FileExistsError, match="fresh"):
            df.write_parquet(out)
        with pytest.raises(FileNotFoundError):
            DataFrame.read_parquet(str(tmp_path / "empty_dir"))

    def test_success_marker_gates_reads(self, tmp_path, caplog):
        import logging
        import os

        df = _df(10, 2)
        out = str(tmp_path / "pq")
        df.write_parquet(out)
        assert os.path.exists(os.path.join(out, "_SUCCESS"))
        with caplog.at_level(logging.WARNING):
            DataFrame.read_parquet(out)
        assert "partial" not in caplog.text.lower()

        os.remove(os.path.join(out, "_SUCCESS"))
        # marker-less with NO staging remnant = a foreign writer
        # (pyarrow/pandas, Spark with the marker suppressed — none
        # require _SUCCESS on read): warn-and-serve
        with caplog.at_level(logging.WARNING):
            back = DataFrame.read_parquet(out)
        assert "did not commit" in caplog.text
        assert back.count() == 10

        # a _tmp.* staging remnant is a DEFINITIVE interrupted
        # write_parquet commit: refused without explicit opt-in
        os.mkdir(os.path.join(out, "_tmp.123"))
        with pytest.raises(FileNotFoundError, match="PARTIAL"):
            DataFrame.read_parquet(out)
        back = DataFrame.read_parquet(out, allow_uncommitted=True)
        assert back.count() == 10

    def test_failed_write_leaves_no_partial_dataset(self, tmp_path):
        """A crash mid-stream must not leave part files a later
        read_parquet would silently serve as a complete dataset — parts
        stage in a temp subdir and only rename into place on success."""
        import glob
        import os

        boom = {"n": 0}

        def failing(batch):
            boom["n"] += 1
            if boom["n"] == 2:
                raise RuntimeError("decode exploded on partition 2")
            return batch

        df = _df(30, 3).map_batches(failing)
        out = str(tmp_path / "pq")
        with pytest.raises(RuntimeError, match="exploded"):
            df.write_parquet(out)
        assert glob.glob(os.path.join(out, "*.parquet")) == []
        assert not glob.glob(os.path.join(out, "_tmp*"))
        # the directory is reusable after the failure
        boom["n"] = -100
        df.write_parquet(out)
        assert DataFrame.read_parquet(out).count() == 30

    def test_schema_from_footer_not_data(self, tmp_path):
        """Reading .columns on a read_parquet frame must come from the
        parquet footer, not a full read of part 0."""
        df = _df(10, 2)
        out = str(tmp_path / "pq")
        df.write_parquet(out)
        import pyarrow.parquet as pq
        orig = pq.read_table
        reads = []
        pq.read_table = lambda *a, **k: (reads.append(a),
                                         orig(*a, **k))[1]
        try:
            back = DataFrame.read_parquet(out)
            assert back.columns == ["x", "s"]
        finally:
            pq.read_table = orig
        assert reads == []  # schema answered without touching data


class TestCacheToDisk:
    def test_spills_once_and_rereads_identically(self, tmp_path):
        calls = {"n": 0}

        def expensive(batch):
            if batch.num_rows:  # zero-row schema probes are free
                calls["n"] += 1
            return batch.append_column(
                "y", pa.array(np.asarray(batch.column("x")) * 2.0))

        df = DataFrame.from_table(
            pa.table({"x": np.arange(12.0)}), 3).map_batches(expensive)
        cached = df.cache_to_disk(str(tmp_path / "spill"))
        first = cached.collect()
        assert calls["n"] == 3  # one plan run per partition
        second = cached.collect()
        assert calls["n"] == 3  # later passes stream the Arrow files
        assert first.equals(second)
        assert second.column("y").to_pylist() == \
            list(np.arange(12.0) * 2.0)

    def test_preserves_partition_identity_for_shuffles(self, tmp_path):
        df = DataFrame.from_table(pa.table({"x": np.arange(9.0)}), 3)
        cached = df.cache_to_disk(str(tmp_path / "spill"))
        cached.collect()  # spill
        reordered = cached.with_partition_order([2, 0, 1])
        got = reordered.collect().column("x").to_pylist()
        assert got == [6.0, 7.0, 8.0, 0.0, 1.0, 2.0, 3.0, 4.0, 5.0]

    def test_rejects_foreign_or_unmanifested_directory(self, tmp_path):
        """A populated cache dir is reused only when its manifest
        matches this frame — never silently serving another frame's
        spilled rows."""
        d = str(tmp_path / "spill")
        df1 = DataFrame.from_table(pa.table({"x": np.arange(6.0)}), 2)
        df1.cache_to_disk(d).collect()

        # same schema + partitions → warm reuse is allowed
        again = DataFrame.from_table(
            pa.table({"x": np.arange(6.0)}), 2).cache_to_disk(d)
        assert again.collect().column("x").to_pylist() == \
            list(np.arange(6.0))

        # different schema → refuse
        df2 = DataFrame.from_table(pa.table({"y": np.arange(6.0)}), 2)
        with pytest.raises(ValueError, match="DIFFERENT frame"):
            df2.cache_to_disk(d)
        # different partition count → refuse
        df3 = DataFrame.from_table(pa.table({"x": np.arange(6.0)}), 3)
        with pytest.raises(ValueError, match="DIFFERENT frame"):
            df3.cache_to_disk(d)

        # non-empty dir without a manifest → refuse
        stray = tmp_path / "stray"
        stray.mkdir()
        (stray / "junk.bin").write_bytes(b"x")
        with pytest.raises(ValueError, match="no spill manifest"):
            df1.cache_to_disk(str(stray))

    def test_concurrent_callers_share_a_spill_dir(self, tmp_path):
        """fitMultiple's trials call cache_to_disk on the SAME dir from
        threads; the manifest check-then-act must not race into
        spurious 'not empty' errors."""
        import concurrent.futures

        d = str(tmp_path / "spill")
        table = pa.table({"x": np.arange(30.0)})

        def run(_):
            df = DataFrame.from_table(table, 3)
            return df.cache_to_disk(d).collect().num_rows

        with concurrent.futures.ThreadPoolExecutor(8) as ex:
            got = list(ex.map(run, range(8)))
        assert got == [30] * 8

    def test_pre_fingerprint_manifest_still_reusable(self, tmp_path):
        """Manifests written before the fingerprint field existed must
        count as the default fingerprint, not as a mismatch."""
        import json
        import os

        d = str(tmp_path / "spill")
        df = DataFrame.from_table(pa.table({"x": np.arange(6.0)}), 2)
        df.cache_to_disk(d).collect()
        mp = os.path.join(d, "_manifest.json")
        with open(mp) as f:
            manifest = json.load(f)
        del manifest["fingerprint"]  # simulate an old-version spill
        with open(mp, "w") as f:
            json.dump(manifest, f)
        warm = DataFrame.from_table(
            pa.table({"x": np.arange(6.0)}), 2).cache_to_disk(d)
        assert warm.collect().column("x").to_pylist() == \
            list(np.arange(6.0))

    def test_fingerprint_distinguishes_same_shape_content(self, tmp_path):
        """Same schema + partition count but a different caller
        fingerprint must refuse the warm cache (shape alone cannot see
        content)."""
        d = str(tmp_path / "spill")
        df1 = DataFrame.from_table(pa.table({"x": np.arange(6.0)}), 2)
        df1.cache_to_disk(d, fingerprint="day1").collect()
        df2 = DataFrame.from_table(pa.table({"x": np.arange(6.0) * 9}),
                                   2)
        with pytest.raises(ValueError, match="fingerprint"):
            df2.cache_to_disk(d, fingerprint="day2")

    def test_schema_probe_does_not_spill(self, tmp_path):
        """.columns / union schema checks must come from the underlying
        frame's zero-row probe, not a full decode+spill of partition 0."""
        calls = {"n": 0}

        def expensive(batch):
            if batch.num_rows:  # zero-row probes are free
                calls["n"] += 1
            return batch

        df = DataFrame.from_table(
            pa.table({"x": np.arange(6.0)}), 2).map_batches(expensive)
        cached = df.cache_to_disk(str(tmp_path / "spill"))
        assert cached.columns == ["x"]
        assert calls["n"] == 0  # schema answered without materializing

    def test_tensor_columns_round_trip(self, tmp_path):
        X = np.arange(24, dtype=np.float32).reshape(6, 4)
        batch = pa.RecordBatch.from_pylist(
            [{"i": int(i)} for i in range(6)])
        batch = append_tensor_column(batch, "t", X)
        df = DataFrame.from_batches([batch])
        cached = df.cache_to_disk(str(tmp_path / "spill"))
        cached.collect()
        np.testing.assert_array_equal(cached.tensor("t"), X)


class TestFrameUsability:
    def _df(self, n=20, parts=4):
        return DataFrame.from_pylist(
            [{"x": i} for i in range(n)], num_partitions=parts)

    def test_limit_lazy(self):
        loaded = []

        def make(i):
            def _load():
                loaded.append(i)
                return pa.RecordBatch.from_pydict(
                    {"x": pa.array([i * 10, i * 10 + 1])})
            return Source(_load, 2)

        df = DataFrame([make(i) for i in range(5)])
        out = df.limit(3).collect_rows()
        assert [r["x"] for r in out] == [0, 1, 10]
        assert sorted(loaded) == [0, 1]  # partitions 2..4 never loaded

    def test_limit_after_filter_counts_final_rows(self):
        df = self._df(20, 4).filter(
            lambda b: np.asarray([v % 2 == 0 for v in
                                  b.column(0).to_pylist()], dtype=bool))
        out = df.limit(5).collect_rows()
        assert [r["x"] for r in out] == [0, 2, 4, 6, 8]

    def test_limit_zero_and_over(self):
        assert self._df(5).limit(0).count() == 0
        assert self._df(5).limit(99).count() == 5

    def test_union(self):
        a = self._df(3).with_column(
            "y", lambda b: np.asarray(b.column(0).to_pylist(),
                                      np.float32))
        b = self._df(2).with_column(
            "y", lambda b: np.asarray(b.column(0).to_pylist(),
                                      np.float32))
        u = a.union(b)
        assert u.count() == 5
        assert [r["x"] for r in u.collect_rows()] == [0, 1, 2, 0, 1]

    def test_limit_over_unknown_count_partitions(self):
        """limit(n) must return exactly n rows even when partition row
        counts are unknown — union of different-plan frames produces
        deferred sources with num_rows=None, and a lazy prefix that
        stops at the first unknown source silently under-returns
        (regression: limit(5) over 6+6 rows returned 3)."""
        a = self._df(6, 2).filter_rows(np.ones(6, bool))  # non-preserving
        b = self._df(6, 2)
        u = a.union(b)
        assert u.count() == 12
        got = [r["x"] for r in u.limit(5).collect_rows()]
        assert got == [0, 1, 2, 3, 4]
        assert u.limit(0).count() == 0
        assert u.limit(12).count() == 12
        assert u.limit(50).count() == 12

    def test_sample(self):
        df = self._df(200, 4)
        kept = df.sample(0.3, seed=7).count()
        assert 30 <= kept <= 90  # loose Bernoulli bounds
        assert df.sample(0.0).count() == 0
        assert df.sample(1.0).count() == 200

    def test_show_renders(self, capsys):
        self._df(3).show()
        out = capsys.readouterr().out
        assert "| x" in out and "| 2" in out

    def test_schema_cached_across_accesses(self):
        """Repeated schema accesses (limit/union/show all consult it)
        must not re-load partition 0 or re-run plan stages."""
        loads, stage_runs = [], []

        def _load():
            loads.append(1)
            return pa.RecordBatch.from_pydict({"x": pa.array([1, 2])})

        def _probe(batch):
            stage_runs.append(1)
            return batch

        df = DataFrame([Source(_load, 2)]).map_batches(_probe, name="probe")
        for _ in range(5):
            _ = df.schema
            _ = df.columns
        assert len(loads) == 1
        assert len(stage_runs) == 1
        # materialization still runs the stage (on the real batch)
        assert df.count() == 2

    def test_sample_partition_index_determinism(self):
        """sample() must see the true partition index on every engine
        path: same frame re-materialized gives identical rows, and
        distinct partitions don't all reuse index 0's coin flips."""
        df = self._df(400, 4)
        s = df.sample(0.5, seed=11)
        first = [r["x"] for r in s.collect_rows()]
        second = [r["x"] for r in s.collect_rows()]
        assert first == second
        # partitions hold disjoint value ranges (0-99, 100-199, ...); if
        # every partition were sampled with the same rng the kept row
        # *offsets* within each partition would coincide — astronomically
        # unlikely with per-index seeding.
        offsets = [sorted(v % 100 for v in first if v // 100 == p)
                   for p in range(4)]
        assert not all(o == offsets[0] for o in offsets[1:])


class TestEngineScale:
    def test_many_partitions_stream_bounded(self):
        """64 partitions stream through the engine in order with bounded
        in-flight load (backpressure: peak concurrent loads stays near
        max_inflight, far below the partition count)."""
        import threading
        engine = LocalEngine(num_workers=4, max_inflight=4)
        live = {"now": 0, "peak": 0}
        lock = threading.Lock()

        def make(i):
            def _load():
                with lock:
                    live["now"] += 1
                    live["peak"] = max(live["peak"], live["now"])
                batch = pa.RecordBatch.from_pydict(
                    {"x": pa.array(np.full(100, i))})
                with lock:
                    live["now"] -= 1
                return batch
            return Source(_load, 100)

        df = DataFrame([make(i) for i in range(64)], engine=engine)
        total = 0
        last = -1
        for batch in df.map_batches(lambda b: b).stream():
            v = batch.column(0)[0].as_py()
            assert v == last + 1  # partition order preserved
            last = v
            total += batch.num_rows
        assert total == 6400
        assert live["peak"] <= 8  # bounded, not 64


class TestCrossPartitionRechunk:
    """Engine-level device-batch alignment (VERDICT r4 next #3): a
    row-preserving device stage with a batch_hint is fed hint-aligned
    row blocks spanning partition boundaries, so partitions smaller
    than the device batch stop padding the static shape (the measured
    2.4× tax, BASELINE.md). Chunk count is the deterministic proxy for
    the throughput criterion: 32-row partitions at batch 128 must
    dispatch exactly ceil(N/128) device chunks — identical to the
    batch-aligned layout — instead of one padded chunk per partition."""

    def _frame_and_transformer(self, n_rows, n_parts, batch_size,
                               width=6):
        from sparkdl_tpu.graph.function import ModelFunction
        from sparkdl_tpu.transformers.tensor_transform import (
            TensorTransformer,
        )

        rng = np.random.default_rng(42)
        feats = rng.normal(size=(n_rows, width)).astype(np.float32)
        tbl = pa.table({"rid": pa.array(np.arange(n_rows))})
        batch = pa.RecordBatch.from_pydict({"rid": tbl.column("rid")
                                            .combine_chunks()})
        batch = append_tensor_column(batch, "x", feats)
        df = DataFrame.from_table(pa.Table.from_batches([batch]),
                                  num_partitions=n_parts)

        def apply_fn(params, inputs):
            import jax.numpy as jnp
            return {"y": jnp.tanh(inputs["x"]) * 2.0}

        mf = ModelFunction(apply_fn, params={},
                           input_signature={"x": ((width,), np.float32)},
                           output_names=["y"])
        t = TensorTransformer(modelFunction=mf,
                              inputMapping={"x": "x"},
                              outputMapping={"y": "y"},
                              batchSize=batch_size)
        return df, t, feats

    def test_small_partitions_dispatch_aligned_chunks(self):
        df, t, feats = self._frame_and_transformer(512, 16, 128)
        out = t.transform(df)
        got = out.tensor("y")
        # exactly ceil(512/128)=4 device chunks, not 16 padded ones
        assert t.metrics.batches == 4, t.metrics.batches
        np.testing.assert_allclose(got, np.tanh(feats) * 2.0,
                                   atol=1e-6)
        # row identity: rid column still pairs with its own row's output
        rids = out.collect().column("rid").to_numpy()
        np.testing.assert_array_equal(rids, np.arange(512))

    def test_uneven_partitions_and_tail_flush(self):
        # 19 rows over 4 uneven partitions, batch 4: greedy dispatch
        # still totals ceil(19/4)=5 chunks, tail padded once at flush
        from sparkdl_tpu.graph.function import ModelFunction
        from sparkdl_tpu.transformers.tensor_transform import (
            TensorTransformer,
        )
        rng = np.random.default_rng(1)
        sizes = [5, 3, 9, 2]
        batches = []
        offset = 0
        for s in sizes:
            b = pa.RecordBatch.from_pydict(
                {"rid": pa.array(np.arange(offset, offset + s))})
            b = append_tensor_column(
                b, "x", rng.normal(size=(s, 3)).astype(np.float32))
            batches.append(b)
            offset += s
        sources = [Source((lambda bb=bb: bb), bb.num_rows)
                   for bb in batches]
        df = DataFrame(sources)

        def apply_fn(params, inputs):
            return {"y": inputs["x"] + 1.0}

        mf = ModelFunction(apply_fn, params={},
                           input_signature={"x": ((3,), np.float32)},
                           output_names=["y"])
        t = TensorTransformer(modelFunction=mf, inputMapping={"x": "x"},
                              outputMapping={"y": "y"}, batchSize=4)
        out = t.transform(df)
        table = out.collect()
        assert t.metrics.batches == 5, t.metrics.batches
        np.testing.assert_array_equal(
            table.column("rid").to_numpy(), np.arange(19))
        x = arrow_to_tensor(table.column("x"))
        y = arrow_to_tensor(table.column("y"))
        np.testing.assert_allclose(y, x + 1.0, atol=1e-6)

    def test_empty_partition_mid_stream(self):
        from sparkdl_tpu.graph.function import ModelFunction
        from sparkdl_tpu.transformers.tensor_transform import (
            TensorTransformer,
        )
        mk = lambda lo, n: append_tensor_column(  # noqa: E731
            pa.RecordBatch.from_pydict(
                {"rid": pa.array(np.arange(lo, lo + n))}),
            "x", np.full((n, 2), 1.5, np.float32))
        batches = [mk(0, 3), mk(3, 0), mk(3, 4)]
        df = DataFrame([Source((lambda bb=bb: bb), bb.num_rows)
                        for bb in batches])

        def apply_fn(params, inputs):
            return {"y": inputs["x"] * 3.0}

        mf = ModelFunction(apply_fn, params={},
                           input_signature={"x": ((2,), np.float32)},
                           output_names=["y"])
        t = TensorTransformer(modelFunction=mf, inputMapping={"x": "x"},
                              outputMapping={"y": "y"}, batchSize=4)
        table = t.transform(df).collect()
        np.testing.assert_array_equal(table.column("rid").to_numpy(),
                                      np.arange(7))
        np.testing.assert_allclose(arrow_to_tensor(table.column("y")),
                                   np.full((7, 2), 4.5), atol=1e-6)

    def test_downstream_host_stage_and_filter(self):
        df, t, feats = self._frame_and_transformer(40, 10, 16)
        out = t.transform(df)
        out = out.with_column(
            "norm", lambda b: np.linalg.norm(
                arrow_to_tensor(b.column(b.schema.get_field_index("y"))),
                axis=1).astype(np.float32))
        out = out.filter(lambda b: pa.array(
            b.column(b.schema.get_field_index("rid")).to_numpy() % 2
            == 0))
        table = out.collect()
        assert table.num_rows == 20
        np.testing.assert_array_equal(
            table.column("rid").to_numpy() % 2, 0)

    def test_stream_stage_retries_transient_errors(self):
        calls = {"n": 0}

        def flaky(batch):
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("transient")
            return batch

        b = pa.RecordBatch.from_pydict({"v": pa.array([1, 2, 3])})
        df = DataFrame([Source(lambda: b, 3)])
        df = df.map_batches(flaky, kind="device", batch_hint=2,
                            name="flaky")
        table = df.collect()
        assert table.num_rows == 3
        assert calls["n"] >= 2

    def test_row_nonpreserving_device_stage_not_rechunked(self):
        """A device stage that drops rows must keep per-partition
        execution (the re-chunk path requires 1:1 rows)."""
        def drop_first(batch):
            return batch.slice(1)

        batches = [pa.RecordBatch.from_pydict({"v": pa.array([1, 2])}),
                   pa.RecordBatch.from_pydict({"v": pa.array([3, 4])})]
        df = DataFrame([Source((lambda bb=bb: bb), 2)
                        for bb in batches])
        df = df.map_batches(drop_first, kind="device",
                            row_preserving=False, batch_hint=64,
                            name="drop")
        assert df.collect().column("v").to_pylist() == [2, 4]

    def test_misaligned_throughput_parity_cpu(self):
        """The VERDICT r4 #3 criterion: 32-row partitions at batch 128
        reach ≥90% of batch-aligned throughput on CPU. Both layouts now
        dispatch identical device chunks (the deterministic guarantee
        asserted above); the wall-clock ratio check uses a model heavy
        enough that chunk count dominates scheduling noise."""
        import time

        from sparkdl_tpu.graph.function import ModelFunction
        from sparkdl_tpu.transformers.tensor_transform import (
            TensorTransformer,
        )
        rng = np.random.default_rng(7)
        n, width = 512, 256
        feats = rng.normal(size=(n, width)).astype(np.float32)
        w = rng.normal(size=(width, width)).astype(np.float32) * 0.05

        def apply_fn(params, inputs):
            import jax.numpy as jnp
            x = inputs["x"]
            for _ in range(8):
                x = jnp.tanh(x @ params["w"])
            return {"y": x}

        mf = ModelFunction(apply_fn, params={"w": w},
                           input_signature={"x": ((width,), np.float32)},
                           output_names=["y"])

        def make_layout(n_parts):
            base = pa.RecordBatch.from_pydict(
                {"rid": pa.array(np.arange(n))})
            base = append_tensor_column(base, "x", feats)
            df = DataFrame.from_table(pa.Table.from_batches([base]),
                                      num_partitions=n_parts)
            t = TensorTransformer(modelFunction=mf,
                                  inputMapping={"x": "x"},
                                  outputMapping={"y": "y"},
                                  batchSize=128)
            t.transform(df).collect()  # warm the jit
            return df, t

        def one_pass(df, t):
            t0 = time.perf_counter()
            out = t.transform(df).collect()
            dt = time.perf_counter() - t0
            assert out.num_rows == n
            return dt

        # chunk parity (asserted above, exact) is the hard ≥90%
        # guarantee — identical device dispatches; this wall-clock
        # check is a smoke bound with slack for CI scheduler noise.
        # Passes ALTERNATE layouts so a load spike on a small shared
        # runner degrades both bests instead of tanking whichever
        # layout it happened to land on.
        aligned = make_layout(4)    # 128-row partitions
        small = make_layout(16)     # 32-row partitions
        t_aligned = t_small = float("inf")
        for _ in range(5):
            t_aligned = min(t_aligned, one_pass(*aligned))
            t_small = min(t_small, one_pass(*small))
        batches = small[1].metrics.batches
        assert batches % 4 == 0  # ceil(512/128) per pass, no extras
        ratio = t_aligned / t_small
        assert ratio >= 0.6, (t_small, t_aligned, ratio)


class TestOutOfCoreRepartition:
    """VERDICT r4 #6: repartition(cacheDir=...) must re-cut a frame
    UPWARD in partition count without ever materializing it whole."""

    def _frame(self, n=96, parts=4):
        rng = np.random.default_rng(5)
        tbl = pa.table({"rid": np.arange(n),
                        "v": rng.normal(size=n)})
        df = DataFrame.from_table(tbl, parts)
        # a plan stage proves the spill runs the full plan, not raw
        # sources
        return df.map_batches(lambda b: b.append_column(
            "v2", pa.array(np.asarray(b.column(1)) * 2.0)))

    def test_upward_repartition_spill_backed(self, tmp_path,
                                             monkeypatch):
        df = self._frame()
        # the memory-bounded proof pattern (cf. CV cacheDir): global
        # collect is FORBIDDEN for the whole operation
        monkeypatch.setattr(
            DataFrame, "collect",
            lambda self: (_ for _ in ()).throw(
                AssertionError("repartition(cacheDir) must not "
                               "collect the frame")))
        out = df.repartition(12, cacheDir=str(tmp_path))
        assert out.num_partitions == 12
        rows = 0
        rids = []
        for b in out.stream():
            assert b.num_rows == 8  # 96/12, contiguous even ranges
            rows += b.num_rows
            rids.extend(b.column(b.schema.get_field_index("rid"))
                        .to_pylist())
        assert rows == 96
        assert rids == list(range(96))  # row order preserved

    def test_plan_applied_before_spill(self, tmp_path):
        df = self._frame()
        out = df.repartition(6, cacheDir=str(tmp_path))
        t = out.collect()
        np.testing.assert_allclose(
            np.asarray(t.column("v2")), np.asarray(t.column("v")) * 2.0)

    def test_count_uses_footers_not_data(self, tmp_path):
        df = self._frame()
        out = df.repartition(10, cacheDir=str(tmp_path))
        assert out.count() == 96
        # each source advertises its exact range size, near-even split
        sizes = [s.num_rows for s in out._sources]
        assert sum(sizes) == 96 and len(sizes) == 10
        assert set(sizes) <= {9, 10}

    def test_in_memory_path_unchanged(self):
        df = self._frame()
        out = df.repartition(3)
        assert out.num_partitions == 3
        assert out.count() == 96


class TestColumnCollisions:
    """Arrow happily stores duplicate column names, and every by-name
    lookup then silently serves the FIRST (stale) one — so name
    collisions follow pyspark: with_column REPLACES in place
    (withColumn semantics); transformer/model output columns RAISE
    (Spark ML's 'output column already exists'); joins keep Spark's
    duplicate-name behavior."""

    def test_with_column_replaces_in_place(self):
        df = _df(10, 2).with_column(
            "x", lambda b: pa.array(np.full(b.num_rows, 7.5)))
        table = df.collect()
        assert table.schema.names == ["x", "s"]  # position preserved
        np.testing.assert_array_equal(table.column("x").to_numpy(), 7.5)
        # tensor-valued replacement too
        df2 = _df(6, 2).with_column(
            "x", lambda b: np.ones((b.num_rows, 2), np.float32))
        t2 = df2.collect()
        assert t2.schema.names == ["x", "s"]
        assert arrow_to_tensor(t2.column("x")).shape == (6, 2)

    def test_transformer_output_collision_raises(self):
        from sparkdl_tpu.graph.function import ModelFunction
        from sparkdl_tpu.transformers.tensor_transform import (
            TensorTransformer,
        )

        b = pa.RecordBatch.from_pydict({"rid": pa.array([0, 1])})
        b = append_tensor_column(b, "x", np.ones((2, 3), np.float32))
        df = DataFrame.from_batches([b])
        mf = ModelFunction(lambda p, i: {"y": i["x"] * 2}, params={},
                           input_signature={"x": ((3,), np.float32)},
                           output_names=["y"])
        t = TensorTransformer(modelFunction=mf, inputMapping={"x": "x"},
                              outputMapping={"y": "x"}, batchSize=2)
        with pytest.raises(ValueError, match="already exists"):
            t.transform(df).collect()

    def test_rename_collision_raises(self):
        # EAGER when the schema is free: the error fires at rename()
        with pytest.raises(ValueError, match="duplicate"):
            _df(6, 2).rename({"x": "s"})
        # hint-less sources must NOT load a partition at rename() —
        # validation defers to execution, same error
        loads = {"n": 0}
        b = pa.RecordBatch.from_pydict(
            {"x": pa.array([1.0]), "s": pa.array(["a"])})

        def load():
            loads["n"] += 1
            return b

        df = DataFrame([Source(load, 1)])
        renamed = df.rename({"x": "s"})  # no raise, no load
        assert loads["n"] == 0
        with pytest.raises(ValueError, match="duplicate"):
            renamed.collect()

    def test_rename_tolerates_preexisting_duplicates(self):
        # only count INCREASES are the mapping's fault: a frame already
        # carrying duplicate names may rename its OTHER columns
        b = pa.RecordBatch.from_arrays(
            [pa.array([1.0]), pa.array([2.0]), pa.array([3.0])],
            names=["x", "x", "y"])
        df = DataFrame.from_batches([b])
        out = df.rename({"y": "z"}).collect()
        assert out.schema.names == ["x", "x", "z"]

    def test_nonpositive_partition_counts_raise(self):
        # Spark raises for repartition/coalesce(<=0); clamping hid typos
        df = _df(10, 2)
        with pytest.raises(ValueError, match="positive"):
            df.repartition(0)
        with pytest.raises(ValueError, match="positive"):
            df.repartition(-3)
        with pytest.raises(ValueError, match="positive"):
            df.coalesce(0)

    def test_ambiguous_column_message(self):
        # duplicated names read as -1 from get_field_index; the lookup
        # error must say AMBIGUOUS, not missing
        from sparkdl_tpu.data.frame import column_index
        b = pa.RecordBatch.from_arrays(
            [pa.array([1.0]), pa.array([2.0])], names=["x", "x"])
        with pytest.raises(KeyError, match="ambiguous"):
            column_index(b, "x")

    def test_lr_output_collision_raises(self):
        from sparkdl_tpu.estimators import LogisticRegression

        b = pa.RecordBatch.from_pylist(
            [{"label": 0, "prediction": 9.0},
             {"label": 1, "prediction": 9.0}])
        b = append_tensor_column(
            b, "features", np.eye(2, dtype=np.float32))
        df = DataFrame.from_batches([b])
        model = LogisticRegression(maxIter=2).fit(df)
        with pytest.raises(ValueError, match="already exists"):
            model.transform(df).collect()


class TestCollectSeam:
    def test_on_batch_observes_every_batch(self):
        seen = []
        table = _df(40, 4).collect(on_batch=lambda b: seen.append(
            b.num_rows))
        assert table.num_rows == 40
        assert sum(seen) == 40 and len(seen) == 4

    def test_all_empty_keeps_one_schema_carrier(self):
        # every partition emptied: sibling empty batches may carry
        # imprecise computed-column types that disagree — collect keeps
        # one as the schema carrier instead of failing the concat
        df = _df(40, 4).filter(lambda b: np.zeros(b.num_rows, bool))
        table = df.collect()
        assert table.num_rows == 0
        assert table.schema.names == ["x", "s"]


class TestSchemaHint:
    """Leaf sources with a statically-known schema publish it as
    ``Source.schema_hint`` so the zero-row schema probe never
    materializes partition 0 (review r5: LR's free sizing estimate was
    decoding a whole image partition just to read the feature width)."""

    def test_schema_probe_does_not_load_partition(self):
        loads = {"n": 0}
        batch = pa.RecordBatch.from_pydict(
            {"x": pa.array([1.0, 2.0]), "s": pa.array(["a", "b"])})

        def load():
            loads["n"] += 1
            return batch

        df = DataFrame([Source(load, batch.num_rows,
                               schema_hint=batch.schema)])
        assert df.columns == ["x", "s"]
        assert loads["n"] == 0  # hint answered the probe
        assert df.collect().num_rows == 2
        assert loads["n"] == 1

    def test_plan_stages_run_on_hint_prototype(self):
        # the probe still runs the plan (on a zero-row prototype), so
        # plan-added columns appear in .columns without a load
        loads = {"n": 0}
        batch = pa.RecordBatch.from_pydict({"x": pa.array([1.0, 2.0])})

        def load():
            loads["n"] += 1
            return batch

        df = DataFrame([Source(load, 2, schema_hint=batch.schema)])
        df = df.with_column(
            "y", lambda b: np.zeros((b.num_rows, 3), np.float32))
        assert df.columns == ["x", "y"]
        assert loads["n"] == 0

    def test_files_frame_schema_without_reading_files(self):
        from sparkdl_tpu.image.imageIO import filesToDF

        df = filesToDF(["/nonexistent/zzz.bin"], numPartitions=1)
        assert df.columns == ["filePath", "fileData"]  # no open()
        with pytest.raises(Exception):
            df.collect()

    def test_reader_hint_schema_matches_loaded(self, tmp_path):
        # the hint path must produce EXACTLY the loaded path's schema,
        # through the full decode plans of both readers
        from PIL import Image

        from sparkdl_tpu.image import imageIO

        rng = np.random.default_rng(0)
        for i in range(2):
            Image.fromarray(
                rng.integers(0, 255, (16, 20, 3), dtype=np.uint8),
                "RGB").save(tmp_path / f"i{i}.png")
        for df in (imageIO.readImages(str(tmp_path), numPartitions=2),
                   imageIO.readImagesPacked(str(tmp_path), (8, 8),
                                            numPartitions=2)):
            assert df.schema == df.collect().schema


class TestRechunkChaos:
    """Interaction coverage: the re-chunk stream phase composed with
    TRANSIENT failures injected into every stage kind at once — random
    partition layouts (empties included), an upstream host stage, the
    re-chunked device stage, and a pooled downstream host stage, all
    failing intermittently with retryable errors. Row identity, order,
    and values must come out exact; retries must not double-apply."""

    def test_random_layouts_with_transient_failures(self):
        import pyarrow as pa

        from sparkdl_tpu.data.engine import LocalEngine
        from sparkdl_tpu.data.frame import Source, Stage

        rng = np.random.default_rng(7)
        for trial in range(4):
            sizes = [int(s) for s in
                     rng.integers(0, 9, size=int(rng.integers(3, 9)))]
            n = sum(sizes)
            if n == 0:
                sizes.append(5)
                n = 5
            batches, lo = [], 0
            for s in sizes:
                batches.append(pa.RecordBatch.from_pydict(
                    {"rid": pa.array(np.arange(lo, lo + s))}))
                lo += s
            # failure schedule keyed on batch CONTENT (first rid), not
            # call order — pool interleaving must not shift which call
            # fails, and a retried batch recomputes the same key so it
            # fails exactly ONCE per (stage, batch) and then succeeds
            # within max_retries. Guarded: concurrent first attempts of
            # different batches share the set.
            lock = threading.Lock()
            failed_once: set = set()

            def flaky(kind, batch, transform):
                key = (kind, batch.column(0)[0].as_py()
                       if batch.num_rows else -1)
                with lock:
                    fresh = key not in failed_once
                    failed_once.add(key)
                if fresh:
                    raise OSError(f"transient {kind} {key}")
                return transform(batch)

            def add_col(b, name, fn):
                vals = fn(np.asarray(b.column(0).to_pylist(),
                                     np.float64))
                return b.append_column(name, pa.array(vals))

            plan = [
                Stage(lambda b: flaky(
                    "pre", b, lambda x: add_col(x, "a",
                                                lambda v: v * 2.0)),
                      kind="host", name="pre"),
                Stage(lambda b: flaky(
                    "dev", b, lambda x: add_col(x, "d",
                                                lambda v: v + 0.5)),
                      kind="device", name="dev", batch_hint=4),
                Stage(lambda b: flaky(
                    "post", b, lambda x: add_col(x, "p",
                                                 lambda v: -v)),
                      kind="host", name="post"),
            ]
            sources = [Source((lambda bb=bb: bb), bb.num_rows)
                       for bb in batches]
            eng = LocalEngine(num_workers=3, max_retries=2)
            out = list(eng.execute(sources, plan))
            table = pa.Table.from_batches(
                [b for b in out if b.num_rows] or out[:1])
            assert table.num_rows == n, (trial, sizes)
            rid = np.asarray(table.column("rid").to_pylist(), np.float64)
            np.testing.assert_array_equal(rid, np.arange(n))
            np.testing.assert_allclose(
                np.asarray(table.column("a").to_pylist()), rid * 2.0)
            np.testing.assert_allclose(
                np.asarray(table.column("d").to_pylist()), rid + 0.5)
            np.testing.assert_allclose(
                np.asarray(table.column("p").to_pylist()), -rid)
            assert failed_once, "schedule never injected a failure"


def test_pooled_downstream_quiesces_on_error():
    """review r5: a failing pooled EFFECTFUL stage downstream of a
    re-chunked device stage must DRAIN its in-flight siblings before
    the error reaches the caller — a straggler completing after the
    caller's cleanup (write_parquet sweeping its staging dir) corrupts
    the cleanup's outcome."""
    import time

    from sparkdl_tpu.data.engine import LocalEngine
    from sparkdl_tpu.data.frame import Stage

    eng = LocalEngine(num_workers=4, max_inflight=2, max_retries=0)
    batches = []
    for lo in range(0, 24, 4):
        batches.append(pa.RecordBatch.from_pydict(
            {"rid": pa.array(np.arange(lo, lo + 4))}))
    effects = []

    def host_fn(batch):
        chunk = int(batch.column(0)[0].as_py()) // 4
        if chunk == 0:
            raise ValueError("boom")
        time.sleep(0.2)
        effects.append(time.perf_counter())
        return batch

    plan = [Stage(lambda b: b, kind="device", name="dev", batch_hint=4),
            Stage(host_fn, kind="host", name="fx", effectful=True)]
    sources = [Source((lambda bb=bb: bb), bb.num_rows)
               for bb in batches]
    with pytest.raises(ValueError, match="boom"):
        for _ in eng.execute(sources, plan):
            pass
    t_err = time.perf_counter()
    time.sleep(0.5)  # stragglers would land in this window
    assert all(t <= t_err for t in effects), (effects, t_err)


def test_effectful_source_load_quiesces_on_error():
    """ADVICE r5: cache_to_disk spill sources WRITE IPC files inside
    Source.load — the quiesce gate must consider SOURCE effectfulness,
    not just stage effectfulness, so an error drains in-flight sibling
    loads before control returns (a straggler load completing after
    the tuning-cleanup rmtree would re-create spill files)."""
    import time

    from sparkdl_tpu.data.engine import LocalEngine
    from sparkdl_tpu.data.frame import Source, Stage

    eng = LocalEngine(num_workers=4, max_inflight=8, max_retries=0)
    effects = []

    def make_load(lo, fail=False):
        def _load():
            if fail:
                raise ValueError("boom")
            time.sleep(0.2)
            effects.append(time.perf_counter())  # the spill write
            return pa.RecordBatch.from_pydict(
                {"rid": pa.array(np.arange(lo, lo + 2))})
        return _load

    sources = [Source(make_load(0, fail=True), 2, effectful=True)] + [
        Source(make_load(i * 2), 2, effectful=True)
        for i in range(1, 6)]
    plan = [Stage(lambda b: b, kind="host", name="id")]
    with pytest.raises(ValueError, match="boom"):
        for _ in eng.execute(sources, plan):
            pass
    t_err = time.perf_counter()
    time.sleep(0.5)  # stragglers would land in this window
    assert all(t <= t_err for t in effects), (effects, t_err)


def test_cache_to_disk_sources_marked_effectful():
    """cache_to_disk's spill sources must carry the effectful flag —
    it is what routes them through the drain above."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        df = DataFrame.from_table(
            pa.table({"x": np.arange(8.0)}), 2).cache_to_disk(d)
        assert all(s.effectful for s in df._sources)


def test_concurrent_transforms_of_one_frame():
    """Spark delegated concurrent-job safety to its scheduler; here the
    engine owns it: several threads transforming the SAME frame through
    the SAME ModelFunction (shared jit cache, shared device lock,
    per-call re-chunk bookkeeping) must all get exact, order-preserved
    results."""
    from sparkdl_tpu.graph.function import ModelFunction
    from sparkdl_tpu.transformers.tensor_transform import (
        TensorTransformer,
    )

    rng = np.random.default_rng(0)
    X = rng.normal(size=(200, 4)).astype(np.float32)
    b = pa.RecordBatch.from_pydict({"rid": pa.array(np.arange(200))})
    b = append_tensor_column(b, "x", X)
    df = DataFrame.from_table(pa.Table.from_batches([b]), 8)
    mf = ModelFunction(lambda p, i: {"y": i["x"] * 3.0}, params={},
                       input_signature={"x": ((4,), np.float32)},
                       output_names=["y"])
    t = TensorTransformer(modelFunction=mf, inputMapping={"x": "x"},
                          outputMapping={"y": "y"}, batchSize=16)
    results: dict = {}
    errors: list = []
    # barrier: without it the millisecond transforms can run serially
    # and the test would pass without ever overlapping
    gate = threading.Barrier(4)

    def work(i):
        try:
            gate.wait(timeout=10)
            out = t.transform(df).collect()
            results[i] = (np.asarray(out.column("rid").to_pylist()),
                          arrow_to_tensor(out.column("y")))
        except Exception as e:  # pragma: no cover - failure reporting
            errors.append(e)

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errors, errors
    assert len(results) == 4
    for rid, y in results.values():
        np.testing.assert_array_equal(rid, np.arange(200))
        np.testing.assert_allclose(y, X * 3.0, atol=1e-6)


def test_zero_max_inflight_is_not_explicit():
    """max_inflight=0 is a falsy sentinel, not an explicit window:
    treating it as explicit disabled the adaptive load-ahead widening
    while the 0 itself was discarded (review r5 high #5)."""
    from sparkdl_tpu.data.engine import LocalEngine

    eng = LocalEngine(num_workers=4, max_inflight=0)
    assert eng.max_inflight == 8  # the default window
    assert not eng._explicit_inflight
    explicit = LocalEngine(num_workers=4, max_inflight=3)
    assert explicit.max_inflight == 3 and explicit._explicit_inflight


def test_pure_plan_abandonment_does_not_drain():
    """The drain is gated on effectful stages: take(1) on a pure
    decode-heavy plan must return without waiting for the in-flight
    wave of sibling partitions (review r5 high #2). Structural proof:
    partition 0 is fast, siblings slow — siblings must still be
    RUNNING when take returns (with the old unconditional drain, no
    load ever completes after the return)."""
    import time

    from sparkdl_tpu.data.engine import LocalEngine
    from sparkdl_tpu.data.frame import DataFrame, Source

    done = []

    def make_load(lo, seconds):
        def _load():
            time.sleep(seconds)
            done.append(time.perf_counter())
            return pa.RecordBatch.from_pydict(
                {"rid": pa.array(np.arange(lo, lo + 2))})
        return _load

    eng = LocalEngine(num_workers=4, max_inflight=8)
    sources = [Source(make_load(0, 0.05), 2)] + [
        Source(make_load(i * 2, 0.6), 2) for i in range(1, 6)]
    df = DataFrame(sources, engine=eng)
    rows = df.take(1)
    t_ret = time.perf_counter()
    assert len(rows) == 1
    time.sleep(1.0)  # let the abandoned siblings finish
    late = [t for t in done if t > t_ret]
    assert late, "take(1) blocked until every sibling load finished"


def test_interrupted_commit_keeps_refusal_evidence(tmp_path,
                                                   monkeypatch):
    """A write_parquet that fails mid-commit (after some parts moved
    into place) must leave the _tmp.* staging remnant so read_parquet
    refuses the PARTIAL dataset — sweeping it would downgrade the
    failure to 'foreign writer, warn-and-serve' (review r5 finding)."""
    import os

    import sparkdl_tpu.data.frame as fmod

    df = _df(40, 4)
    out = str(tmp_path / "pq")
    orig = os.replace
    calls = {"n": 0}

    def flaky(src, dst, *a, **k):
        if dst.endswith(".parquet") and "_tmp." not in dst:
            calls["n"] += 1
            if calls["n"] == 2:
                raise OSError("simulated commit failure")
        return orig(src, dst, *a, **k)

    monkeypatch.setattr(fmod.os, "replace", flaky)
    with pytest.raises(OSError, match="simulated"):
        df.write_parquet(out)
    assert calls["n"] == 2
    # one part landed, no _SUCCESS, staging remnant kept as evidence
    import glob
    assert glob.glob(os.path.join(out, "*.parquet"))
    assert glob.glob(os.path.join(out, "_tmp.*"))
    with pytest.raises(FileNotFoundError, match="PARTIAL"):
        DataFrame.read_parquet(out)


def test_write_parquet_row_group_cap(tmp_path):
    """row_group_rows caps parquet row-group size so range readers
    (repartition's spill) fetch only overlapping groups, not files."""
    import pyarrow.parquet as pq

    df = _df(40, 2)  # 20 rows per part
    out = str(tmp_path / "pq")
    df.write_parquet(out, row_group_rows=8)
    import glob
    files = sorted(glob.glob(out + "/*.parquet"))
    assert files
    for f in files:
        md = pq.ParquetFile(f).metadata
        assert md.num_row_groups == 3  # ceil(20/8)
        assert max(md.row_group(g).num_rows
                   for g in range(md.num_row_groups)) <= 8


class TestRechunkComposition:
    """Plans with several device stages / interleaved host stages all
    flow through the stream phase correctly."""

    def _mf(self, width, k):
        from sparkdl_tpu.graph.function import ModelFunction

        def apply_fn(params, inputs):
            return {"y": inputs["x"] * k}

        return ModelFunction(apply_fn, params={},
                             input_signature={"x": ((width,),
                                                    np.float32)},
                             output_names=["y"])

    def test_two_chained_device_stages_different_batches(self):
        from sparkdl_tpu.transformers.tensor_transform import (
            TensorTransformer,
        )
        rng = np.random.default_rng(11)
        n = 60
        feats = rng.normal(size=(n, 3)).astype(np.float32)
        b = pa.RecordBatch.from_pydict({"rid": pa.array(np.arange(n))})
        b = append_tensor_column(b, "x", feats)
        df = DataFrame.from_table(pa.Table.from_batches([b]), 12)

        t1 = TensorTransformer(modelFunction=self._mf(3, 2.0),
                               inputMapping={"x": "x"},
                               outputMapping={"y": "x2"}, batchSize=16)
        t2 = TensorTransformer(modelFunction=self._mf(3, -1.0),
                               inputMapping={"x2": "x"},
                               outputMapping={"y": "x3"}, batchSize=7)
        out = t2.transform(t1.transform(df)).collect()
        np.testing.assert_array_equal(out.column("rid").to_numpy(),
                                      np.arange(n))
        np.testing.assert_allclose(arrow_to_tensor(out.column("x3")),
                                   feats * -2.0, atol=1e-6)
        assert t1.metrics.batches == 4   # ceil(60/16)
        assert t2.metrics.batches == 9   # ceil(60/7)

    def test_device_stage_after_filter_after_device_stage(self):
        from sparkdl_tpu.transformers.tensor_transform import (
            TensorTransformer,
        )
        n = 40
        feats = np.arange(n * 2, dtype=np.float32).reshape(n, 2)
        b = pa.RecordBatch.from_pydict({"rid": pa.array(np.arange(n))})
        b = append_tensor_column(b, "x", feats)
        df = DataFrame.from_table(pa.Table.from_batches([b]), 8)

        t1 = TensorTransformer(modelFunction=self._mf(2, 3.0),
                               inputMapping={"x": "x"},
                               outputMapping={"y": "x3"}, batchSize=16)
        stage1 = t1.transform(df)
        kept = stage1.filter(lambda bb: pa.array(
            bb.column(bb.schema.get_field_index("rid")).to_numpy() % 4
            == 0))
        t2 = TensorTransformer(modelFunction=self._mf(2, 10.0),
                               inputMapping={"x3": "x"},
                               outputMapping={"y": "x30"}, batchSize=4)
        out = t2.transform(kept).collect()
        assert out.num_rows == 10
        np.testing.assert_allclose(
            arrow_to_tensor(out.column("x30")),
            feats[::4] * 30.0, atol=1e-5)


class TestRechunkFuzz:
    """Randomized layouts through the re-chunker: any partition-size
    mix × any batch hint must preserve row identity and order and
    dispatch ceil(N/hint) chunks."""

    def test_random_layouts(self):
        from sparkdl_tpu.graph.function import ModelFunction
        from sparkdl_tpu.transformers.tensor_transform import (
            TensorTransformer,
        )
        rng = np.random.default_rng(123)
        for trial in range(6):
            sizes = rng.integers(0, 9, size=rng.integers(2, 9)).tolist()
            n = int(sum(sizes))
            if n == 0:
                sizes.append(3)
                n = 3
            hint = int(rng.integers(2, 12))
            feats = rng.normal(size=(n, 2)).astype(np.float32)
            batches, off = [], 0
            for s in sizes:
                b = pa.RecordBatch.from_pydict(
                    {"rid": pa.array(np.arange(off, off + s))})
                b = append_tensor_column(b, "x", feats[off:off + s])
                batches.append(b)
                off += s
            df = DataFrame([Source((lambda bb=bb: bb), bb.num_rows)
                            for bb in batches])

            def apply_fn(params, inputs):
                return {"y": inputs["x"] * 0.5}

            mf = ModelFunction(apply_fn, params={},
                               input_signature={"x": ((2,), np.float32)},
                               output_names=["y"])
            t = TensorTransformer(modelFunction=mf,
                                  inputMapping={"x": "x"},
                                  outputMapping={"y": "y"},
                                  batchSize=hint)
            table = t.transform(df).collect()
            ctx = (trial, sizes, hint)
            assert table.num_rows == n, ctx
            np.testing.assert_array_equal(
                table.column("rid").to_numpy(), np.arange(n), err_msg=str(ctx))
            np.testing.assert_allclose(
                arrow_to_tensor(table.column("y")), feats * 0.5,
                atol=1e-6, err_msg=str(ctx))
            assert t.metrics.batches == -(-n // hint), ctx

    def test_pooled_downstream_stage_preserves_order_under_jitter(self):
        """Host stages after the device stage run pooled; ordered
        emission must hold even when later partitions finish first."""
        import time

        from sparkdl_tpu.graph.function import ModelFunction
        from sparkdl_tpu.transformers.tensor_transform import (
            TensorTransformer,
        )
        n = 24
        b = pa.RecordBatch.from_pydict({"rid": pa.array(np.arange(n))})
        b = append_tensor_column(b, "x",
                                 np.ones((n, 2), np.float32))
        df = DataFrame.from_table(pa.Table.from_batches([b]), 8)

        def apply_fn(params, inputs):
            return {"y": inputs["x"]}

        mf = ModelFunction(apply_fn, params={},
                           input_signature={"x": ((2,), np.float32)},
                           output_names=["y"])
        t = TensorTransformer(modelFunction=mf, inputMapping={"x": "x"},
                              outputMapping={"y": "y"}, batchSize=5)
        rng = np.random.default_rng(0)

        def jitter(batch):
            time.sleep(float(rng.uniform(0, 0.01)))
            return batch.append_column(
                "tag", pa.array([1] * batch.num_rows))

        out = t.transform(df).map_batches(jitter, name="jitter")
        rids = []
        for bb in out.stream():
            rids.extend(bb.column(0).to_pylist())
        assert rids == list(range(n))
