"""DataFrame/engine tests (the engine seam that replaces Spark local-mode
in the reference's test harness, SURVEY §4.1)."""

import threading

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.data import DataFrame, LocalEngine, arrow_to_tensor
from sparkdl_tpu.data.tensors import append_tensor_column, tensor_shape_of


def _df(n=100, parts=7):
    return DataFrame.from_table(
        pa.table({"x": np.arange(n, dtype=np.float64),
                  "s": [f"r{i}" for i in range(n)]}), parts)


class TestConstruction:
    def test_partition_count(self):
        assert _df(100, 7).num_partitions == 7
        assert _df(3, 8).num_partitions == 3  # capped at rows

    def test_order_preserved(self):
        tab = _df(100, 7).collect()
        np.testing.assert_array_equal(tab.column("x").to_numpy(),
                                      np.arange(100))

    def test_from_pylist(self):
        df = DataFrame.from_pylist([{"a": 1}, {"a": 2}], 2)
        assert df.count() == 2

    def test_schema_and_columns(self):
        df = _df()
        assert df.columns == ["x", "s"]


class TestOps:
    def test_with_column_numpy_tensor(self):
        df = _df(10, 2).with_column(
            "t", lambda b: np.ones((b.num_rows, 2, 3), np.float32))
        t = df.tensor("t")
        assert t.shape == (10, 2, 3)

    def test_tensor_shape_metadata(self):
        batch = pa.RecordBatch.from_pydict({"x": pa.array([1.0, 2.0])})
        batch = append_tensor_column(batch, "t",
                                     np.zeros((2, 4, 5), np.float32))
        assert tensor_shape_of(batch.schema.field("t")) == (4, 5)
        back = arrow_to_tensor(batch.column(1), batch.schema.field("t"))
        assert back.shape == (2, 4, 5)

    def test_select_drop_rename(self):
        df = _df()
        assert df.select("x").columns == ["x"]
        assert df.drop("s").columns == ["x"]
        assert df.rename({"x": "y"}).columns == ["y", "s"]

    def test_filter(self):
        df = _df(100, 5).filter(
            lambda b: b.column(0).to_numpy(zero_copy_only=False) < 10)
        assert df.count() == 10

    def test_filter_rows_global_mask(self):
        mask = np.zeros(100, dtype=bool)
        mask[::2] = True
        df = _df(100, 5).filter_rows(mask)
        assert df.count() == 50
        np.testing.assert_array_equal(
            df.collect().column("x").to_numpy(), np.arange(0, 100, 2))

    def test_count_fast_path_and_slow_path(self):
        df = _df(100, 5)
        assert df.count() == 100
        assert df.filter(lambda b: b.column(0).to_numpy(
            zero_copy_only=False) >= 0).count() == 100

    def test_take_first(self):
        df = _df(100, 5)
        assert df.first()["x"] == 0.0
        assert [r["x"] for r in df.take(3)] == [0.0, 1.0, 2.0]

    def test_chained_lazy_plan(self):
        calls = []

        def stage(b):
            calls.append(1)
            return b

        df = _df(10, 2).map_batches(stage)
        assert not calls  # lazy until materialized
        df.collect()
        assert len(calls) == 2  # once per partition


class TestEngine:
    def test_host_stages_parallel(self):
        """Host stages run on multiple threads."""
        seen = set()

        def stage(b):
            seen.add(threading.current_thread().name)
            return b

        engine = LocalEngine(num_workers=4)
        df = DataFrame.from_table(
            pa.table({"x": np.arange(64.0)}), 16, engine) \
            .map_batches(stage)
        df.collect()
        assert len(seen) >= 2

    def test_device_stage_serialized(self):
        """Device stages never overlap."""
        active = [0]
        max_active = [0]
        lock = threading.Lock()

        def dev_stage(b):
            with lock:
                active[0] += 1
                max_active[0] = max(max_active[0], active[0])
            import time
            time.sleep(0.005)
            with lock:
                active[0] -= 1
            return b

        engine = LocalEngine(num_workers=8)
        df = DataFrame.from_table(
            pa.table({"x": np.arange(64.0)}), 16, engine) \
            .map_batches(dev_stage, kind="device")
        df.collect()
        assert max_active[0] == 1

    def test_stream_order(self):
        df = _df(50, 10)
        batches = list(df.stream())
        xs = np.concatenate(
            [b.column(0).to_numpy(zero_copy_only=False) for b in batches])
        np.testing.assert_array_equal(xs, np.arange(50))
