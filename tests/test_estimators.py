"""Estimator tests — the reference's pattern (SURVEY §4.7): one-epoch
fits on a few images, assert a model comes back, transform works, and
CrossValidator integration doesn't crash; plus loss-decrease and
evaluator unit checks."""

import numpy as np
import pytest

from sparkdl_tpu.data import DataFrame
from sparkdl_tpu.estimators import (
    ClassificationEvaluator,
    KerasImageFileEstimator,
    LossEvaluator,
)
from sparkdl_tpu.params.tuning import CrossValidator, ParamGridBuilder

H = W = 8


@pytest.fixture(scope="module")
def keras_cls_file(tmp_path_factory):
    """Tiny 2-class softmax classifier saved as a .keras file."""
    import keras
    keras.utils.set_random_seed(123)  # init must not depend on test order
    m = keras.Sequential([
        keras.layers.Input((H, W, 3)),
        keras.layers.Flatten(),
        keras.layers.Dense(8, activation="relu"),
        keras.layers.Dense(2, activation="softmax"),
    ])
    path = str(tmp_path_factory.mktemp("est") / "cls.keras")
    m.save(path)
    return path


@pytest.fixture(scope="module")
def uri_label_df(tmp_path_factory):
    """20 images whose mean brightness encodes the class label —
    learnable by a linear model in a few steps."""
    from PIL import Image
    rng = np.random.default_rng(7)
    d = tmp_path_factory.mktemp("estimgs")
    rows = []
    for i in range(20):
        label = i % 2
        base = 40 if label == 0 else 210
        arr = np.clip(rng.normal(base, 15, (H, W, 3)), 0, 255).astype(
            np.uint8)
        p = str(d / f"i{i}.png")
        Image.fromarray(arr, "RGB").save(p)
        rows.append({"uri": p, "label": label})
    return DataFrame.from_pylist(rows, num_partitions=3)


def loader(uri):
    # centered like the real zoo preprocessors (inception x/127.5-1):
    # all-positive near-colinear inputs make the tiny fixture net's
    # ReLUs die wholesale for unlucky fold compositions — centering
    # removes that bistability so learning assertions are stable for
    # ANY fold/seed draw
    from PIL import Image
    return np.asarray(Image.open(uri).convert("RGB"),
                      dtype=np.float32) / 255.0 - 0.5


def make_estimator(model_file, **over):
    kw = dict(inputCol="uri", outputCol="prediction", labelCol="label",
              modelFile=model_file, imageLoader=loader,
              kerasOptimizer="adam", kerasLoss="categorical_crossentropy",
              kerasFitParams={"epochs": 6, "batch_size": 8,
                              "learning_rate": 0.05, "seed": 1},
              batchSize=8)
    kw.update(over)
    return KerasImageFileEstimator(**kw)


class TestKerasImageFileEstimator:
    def test_fit_returns_working_model(self, keras_cls_file, uri_label_df):
        est = make_estimator(keras_cls_file)
        model = est.fit(uri_label_df)
        assert len(model.history) == 6
        # training loss must actually decrease on the separable data
        assert model.history[-1] < model.history[0]

        out = model.transform(uri_label_df)
        preds = out.tensor("prediction")
        assert preds.shape == (20, 2)
        labels = np.array([r["label"]
                           for r in uri_label_df.collect_rows()])
        acc = float(np.mean(preds.argmax(-1) == labels))
        assert acc >= 0.8

    def test_fit_multiple_parallel_trials(self, keras_cls_file,
                                          uri_label_df):
        est = make_estimator(keras_cls_file, parallelism=2)
        grid = [
            {est.getParam("kerasFitParams"):
             {"epochs": 1, "batch_size": 8, "learning_rate": 1e-4,
              "seed": 1}},
            {est.getParam("kerasFitParams"):
             {"epochs": 5, "batch_size": 8, "learning_rate": 0.05,
              "seed": 1}},
        ]
        got = dict(est.fitMultiple(uri_label_df, grid))
        assert set(got) == {0, 1}
        assert len(got[0].history) == 1
        assert len(got[1].history) == 5

    def test_cache_decoded_matches_uncached_exactly(self, keras_cls_file,
                                                    uri_label_df):
        """cacheDecoded=True (epoch 1 spills decoded tensors, later
        epochs stream the Arrow cache) must train to the SAME weights as
        plain streaming — the cache changes where bytes come from, not
        what the steps see (VERDICT r2 weak #5)."""
        fit_params = {"epochs": 3, "batch_size": 8,
                      "learning_rate": 0.05, "shuffle": False, "seed": 1}
        plain = make_estimator(keras_cls_file, kerasFitParams=fit_params,
                               streaming=True).fit(uri_label_df)
        cached = make_estimator(keras_cls_file, kerasFitParams=fit_params,
                                streaming=True,
                                cacheDecoded=True).fit(uri_label_df)
        np.testing.assert_allclose(np.asarray(cached.history),
                                   np.asarray(plain.history),
                                   rtol=1e-5, atol=1e-6)
        for a, b in zip(cached.modelFunction.params["trainable"],
                        plain.modelFunction.params["trainable"]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_cache_decoded_decodes_once(self, keras_cls_file,
                                        uri_label_df):
        """With the cache, imageLoader runs exactly once per image per
        fit; without it, once per image per EPOCH."""
        calls = {"n": 0}

        def counting_loader(uri):
            calls["n"] += 1
            return loader(uri)

        n_img = uri_label_df.count()
        fit_params = {"epochs": 3, "batch_size": 8,
                      "learning_rate": 0.05, "shuffle": False, "seed": 1}
        make_estimator(keras_cls_file, kerasFitParams=fit_params,
                       imageLoader=counting_loader, streaming=True,
                       cacheDecoded=True).fit(uri_label_df)
        assert calls["n"] == n_img  # one decode per image, ever

        calls["n"] = 0
        make_estimator(keras_cls_file, kerasFitParams=fit_params,
                       imageLoader=counting_loader,
                       streaming=True).fit(uri_label_df)
        assert calls["n"] == 3 * n_img  # the documented re-decode cost

    def test_cache_decoded_spill_dir_removed(self, keras_cls_file,
                                             uri_label_df, monkeypatch):
        """The per-fit spill directory is deleted when training ends —
        on success AND when the fit fails before the epoch loop."""
        import os
        import tempfile
        made = []
        orig = tempfile.mkdtemp

        def spy_mkdtemp(*a, **k):
            d = orig(*a, **k)
            if k.get("prefix", "").startswith("sparkdl_tpu_decoded"):
                made.append(d)
            return d

        monkeypatch.setattr(tempfile, "mkdtemp", spy_mkdtemp)
        fit_params = {"epochs": 2, "batch_size": 8,
                      "learning_rate": 0.05, "shuffle": False, "seed": 1}
        make_estimator(keras_cls_file, kerasFitParams=fit_params,
                       streaming=True,
                       cacheDecoded=True).fit(uri_label_df)
        assert made and not any(os.path.exists(d) for d in made)

        # early-failure path: empty dataset raises before any epoch —
        # the spill dir must still be cleaned up (review r3 finding)
        made.clear()
        import pyarrow as pa

        from sparkdl_tpu.data import DataFrame
        empty = DataFrame.from_table(
            pa.table({"uri": pa.array([], type=pa.string()),
                      "label": pa.array([], type=pa.int64())}), 1)
        est = make_estimator(keras_cls_file, kerasFitParams=fit_params,
                             streaming=True, cacheDecoded=True)
        with pytest.raises(ValueError, match="empty"):
            est.fit(empty)
        assert made and not any(os.path.exists(d) for d in made)

    def test_cache_decoded_shared_across_trials(self, keras_cls_file,
                                                uri_label_df):
        """fitMultiple's trials share ONE decoded spill cache when the
        paramMaps leave the data params untouched — k trials decode the
        dataset once, not k times."""
        calls = {"n": 0}

        def counting_loader(uri):
            calls["n"] += 1
            return loader(uri)

        n_img = uri_label_df.count()
        est = make_estimator(
            keras_cls_file, imageLoader=counting_loader, streaming=True,
            cacheDecoded=True, parallelism=1,
            kerasFitParams={"epochs": 2, "batch_size": 8,
                            "learning_rate": 0.05, "shuffle": False,
                            "seed": 1})
        grid = [
            {est.getParam("kerasFitParams"):
             {"epochs": 2, "batch_size": 8, "learning_rate": 0.01,
              "shuffle": False, "seed": 1}},
            {est.getParam("kerasFitParams"):
             {"epochs": 2, "batch_size": 8, "learning_rate": 0.05,
              "shuffle": False, "seed": 1}},
        ]
        got = dict(est.fitMultiple(uri_label_df, grid))
        assert set(got) == {0, 1}
        assert calls["n"] == n_img  # one decode pass for BOTH trials

    def test_streaming_matches_inmemory_exactly(self, keras_cls_file,
                                                uri_label_df):
        """streaming=True with shuffle=False feeds the identical batch
        sequence as the collect-to-memory path (partition order, wrap
        policy), so the trained weights must match."""
        fit_params = {"epochs": 2, "batch_size": 8,
                      "learning_rate": 0.05, "shuffle": False, "seed": 1}
        mem = make_estimator(keras_cls_file, kerasFitParams=fit_params) \
            .fit(uri_label_df)
        stream = make_estimator(keras_cls_file, kerasFitParams=fit_params,
                                streaming=True).fit(uri_label_df)
        np.testing.assert_allclose(np.asarray(stream.history),
                                   np.asarray(mem.history),
                                   rtol=1e-5, atol=1e-6)
        for a, b in zip(stream.modelFunction.params["trainable"],
                        mem.modelFunction.params["trainable"]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_streaming_shuffled_trains(self, keras_cls_file,
                                       uri_label_df):
        est = make_estimator(keras_cls_file, streaming=True)
        model = est.fit(uri_label_df)
        assert len(model.history) == 6
        assert model.history[-1] < model.history[0]
        preds = model.transform(uri_label_df).tensor("prediction")
        labels = np.array([r["label"]
                           for r in uri_label_df.collect_rows()])
        assert float(np.mean(preds.argmax(-1) == labels)) >= 0.8

    def test_streaming_checkpoint_resume(self, keras_cls_file,
                                         uri_label_df, tmp_path):
        """A resumed streaming fit must land on the same weights as an
        uninterrupted one (epoch seeds are burned for skipped epochs)."""
        fit_params = {"epochs": 3, "batch_size": 8,
                      "learning_rate": 0.05, "seed": 2}
        full = make_estimator(keras_cls_file, kerasFitParams=fit_params,
                              streaming=True).fit(uri_label_df)

        ckpt = str(tmp_path / "stream_ck")
        short = dict(fit_params, epochs=2)
        make_estimator(keras_cls_file, kerasFitParams=short,
                       streaming=True, checkpointDir=ckpt) \
            .fit(uri_label_df)
        resumed = make_estimator(keras_cls_file, kerasFitParams=fit_params,
                                 streaming=True, checkpointDir=ckpt) \
            .fit(uri_label_df)
        # the restore actually happened (a deterministic retrain would
        # produce identical weights, so equality alone can't prove it)
        assert resumed.resumedFrom == 2
        np.testing.assert_allclose(np.asarray(resumed.history),
                                   np.asarray(full.history),
                                   rtol=1e-5, atol=1e-6)
        for a, b in zip(resumed.modelFunction.params["trainable"],
                        full.modelFunction.params["trainable"]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_batch_size_larger_than_dataset(self, keras_cls_file,
                                            uri_label_df):
        """batch_size > 2n must still produce full static batches on the
        mesh (regression: the wrap pad truncated at 2n, yielding a short
        batch the data-axis sharding rejects)."""
        est = make_estimator(
            keras_cls_file,
            kerasFitParams={"epochs": 2, "batch_size": 64,
                            "learning_rate": 0.01, "seed": 1})
        model = est.fit(uri_label_df)  # n=20, batch 64
        assert len(model.history) == 2

    def test_fitmultiple_imageloader_override_retrains_data(
            self, keras_cls_file, uri_label_df):
        """A paramMap overriding imageLoader must re-localize with that
        loader (regression: all trials trained on self's decode)."""
        est = make_estimator(keras_cls_file, parallelism=1,
                             kerasFitParams={"epochs": 1, "batch_size": 8,
                                             "seed": 1})
        seen = []

        def tagged_loader(uri):
            seen.append(uri)
            return loader(uri)

        grid = [{est.getParam("imageLoader"): tagged_loader}]
        got = dict(est.fitMultiple(uri_label_df, grid))
        assert len(seen) == 20  # override decoded the trial's data
        assert got[0].getImageLoader() is tagged_loader

    def test_checkpoint_resume_matches_uninterrupted(self, keras_cls_file,
                                                     uri_label_df,
                                                     tmp_path):
        """A 2-epoch run + resumed 4-epoch run must equal one
        uninterrupted 4-epoch run (weights and loss history)."""
        fit = {"epochs": 4, "batch_size": 8, "learning_rate": 0.01,
               "seed": 3}
        full = make_estimator(keras_cls_file,
                              kerasFitParams=fit).fit(uri_label_df)

        ckpt = str(tmp_path / "ckpt")
        part = dict(fit, epochs=2)
        make_estimator(keras_cls_file, kerasFitParams=part,
                       checkpointDir=ckpt).fit(uri_label_df)
        resumed = make_estimator(keras_cls_file, kerasFitParams=fit,
                                 checkpointDir=ckpt).fit(uri_label_df)

        # resume must actually have happened: the extended run shares
        # the partial run's trial directory (epochs is a budget, not an
        # identity — regression: epochs in the fingerprint made every
        # extension train from scratch in a fresh dir)
        import os
        assert len(os.listdir(ckpt)) == 1
        assert resumed.resumedFrom == 2
        assert resumed.history == pytest.approx(full.history, rel=1e-5)
        import jax
        for a, b in zip(jax.tree.leaves(resumed.modelFunction.params),
                        jax.tree.leaves(full.modelFunction.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_checkpoint_not_shared_across_data(self, keras_cls_file,
                                               uri_label_df, tmp_path):
        """Different data (e.g. CV folds) must never adopt each other's
        checkpoints (regression: identity was only dir+trial index)."""
        fit = {"epochs": 2, "batch_size": 8, "learning_rate": 0.01,
               "seed": 3}
        ckpt = str(tmp_path / "shared")
        est = make_estimator(keras_cls_file, kerasFitParams=fit,
                             checkpointDir=ckpt)
        est.fit(uri_label_df)

        half = uri_label_df.filter_rows(
            np.arange(20) < 10)  # a "fold": different data
        m = est.fit(half)
        # must have actually trained 2 epochs on the fold, not resumed
        # the full-data run's final state
        assert len(m.history) == 2

    def test_checkpoint_config_change_trains_fresh(self, keras_cls_file,
                                                   uri_label_df, tmp_path):
        """Changing the config (here: epochs) changes the fingerprint,
        so the run trains fresh instead of restoring a state from a
        different configuration (and can never hit a pruned step of the
        old run — regression: min(last, epochs) restored a GC'd step)."""
        ckpt = str(tmp_path / "prune")
        base = {"batch_size": 8, "learning_rate": 0.01, "seed": 3}
        make_estimator(keras_cls_file,
                       kerasFitParams=dict(base, epochs=6),
                       checkpointDir=ckpt).fit(uri_label_df)
        m = make_estimator(keras_cls_file,
                           kerasFitParams=dict(base, epochs=2),
                           checkpointDir=ckpt).fit(uri_label_df)
        assert len(m.history) == 2

    def test_missing_required_param_raises(self, keras_cls_file,
                                           uri_label_df):
        est = KerasImageFileEstimator(inputCol="uri", outputCol="p",
                                      modelFile=keras_cls_file,
                                      imageLoader=loader)
        with pytest.raises(ValueError, match="labelCol"):
            est.fit(uri_label_df)

    def test_crossvalidator_integration(self, keras_cls_file, uri_label_df):
        est = make_estimator(keras_cls_file, parallelism=2)
        grid = (ParamGridBuilder()
                .addGrid(est.getParam("kerasFitParams"),
                         [{"epochs": 1, "batch_size": 8,
                           "learning_rate": 1e-4, "seed": 1},
                          {"epochs": 4, "batch_size": 8,
                           "learning_rate": 0.05, "seed": 1}])
                .build())
        cv = CrossValidator(
            estimator=est, estimatorParamMaps=grid,
            evaluator=ClassificationEvaluator(predictionCol="prediction",
                                              labelCol="label"),
            numFolds=2, seed=0)
        cv_model = cv.fit(uri_label_df)
        assert len(cv_model.avgMetrics) == 2
        assert all(0.0 <= m <= 1.0 for m in cv_model.avgMetrics)
        out = cv_model.transform(uri_label_df)
        assert out.tensor("prediction").shape == (20, 2)

    def test_crossvalidator_with_streaming(self, keras_cls_file,
                                           uri_label_df):
        """CV folds compose with streaming training: each trial streams
        its fold's partitions, nothing is localized."""
        est = make_estimator(
            keras_cls_file, streaming=True, parallelism=1,
            kerasFitParams={"epochs": 2, "batch_size": 8,
                            "learning_rate": 0.05, "seed": 1})
        # streaming shuffles partition-then-rows (coarser than the
        # in-memory global permutation), so tiny folds need a few more
        # epochs for the strong config to separate cleanly
        grid = (ParamGridBuilder()
                .addGrid(est.getParam("kerasFitParams"),
                         [{"epochs": 1, "batch_size": 8,
                           "learning_rate": 1e-4, "seed": 1},
                          {"epochs": 6, "batch_size": 8,
                           "learning_rate": 0.05, "seed": 1}])
                .build())
        cv = CrossValidator(
            estimator=est, estimatorParamMaps=grid,
            evaluator=ClassificationEvaluator(predictionCol="prediction",
                                              labelCol="label"),
            numFolds=2, seed=0)
        cv_model = cv.fit(uri_label_df)
        assert len(cv_model.avgMetrics) == 2
        # the higher-lr/6-epoch config must win on separable data
        assert int(np.argmax(cv_model.avgMetrics)) == 1
        assert len(cv_model.bestModel.history) == 6


class TestEvaluators:
    def _df(self):
        import pyarrow as pa
        from sparkdl_tpu.data.tensors import append_tensor_column
        preds = np.array([[0.9, 0.1], [0.2, 0.8], [0.6, 0.4]],
                         dtype=np.float32)
        batch = pa.RecordBatch.from_pylist(
            [{"label": 0}, {"label": 1}, {"label": 1}])
        batch = append_tensor_column(batch, "prediction", preds)
        return DataFrame.from_batches([batch])

    def test_classification_accuracy(self):
        ev = ClassificationEvaluator(predictionCol="prediction",
                                     labelCol="label")
        assert ev.evaluate(self._df()) == pytest.approx(2.0 / 3.0)
        assert ev.isLargerBetter()

    def test_weighted_metrics_match_hand_computation(self):
        """metricName f1 / weightedPrecision / weightedRecall follow
        pyspark MulticlassClassificationEvaluator semantics: per-class
        values weighted by true-class support."""
        import pyarrow as pa

        # labels: 0,0,0,1,1,2 — preds: 0,0,1,1,2,2
        labels = [0, 0, 0, 1, 1, 2]
        pred = [0.0, 0.0, 1.0, 1.0, 2.0, 2.0]
        batch = pa.RecordBatch.from_pylist(
            [{"label": l, "prediction": p}
             for l, p in zip(labels, pred)])
        df = DataFrame.from_batches([batch])

        # class 0: tp=2 fp=0 fn=1 → P=1, R=2/3, F1=0.8 (support 3)
        # class 1: tp=1 fp=1 fn=1 → P=.5, R=.5, F1=.5 (support 2)
        # class 2: tp=1 fp=1 fn=0 → P=.5, R=1, F1=2/3 (support 1)
        exp = {
            "accuracy": 4 / 6,
            "weightedPrecision": (1.0 * 3 + 0.5 * 2 + 0.5 * 1) / 6,
            "weightedRecall": (2 / 3 * 3 + 0.5 * 2 + 1.0 * 1) / 6,
            "f1": (0.8 * 3 + 0.5 * 2 + (2 / 3) * 1) / 6,
        }
        for name, want in exp.items():
            ev = ClassificationEvaluator(predictionCol="prediction",
                                         labelCol="label",
                                         metricName=name)
            assert ev.evaluate(df) == pytest.approx(want), name
        with pytest.raises(ValueError, match="metricName"):
            ClassificationEvaluator(metricName="bogus")
        # set() bypasses __init__ validation — evaluate must re-check
        ev = ClassificationEvaluator(predictionCol="prediction",
                                     labelCol="label")
        ev.set(ev.metricName, "precisionByLabel")
        with pytest.raises(ValueError, match="metricName"):
            ev.evaluate(df)

    def test_streaming_accumulation_matches_single_batch(self):
        """VERDICT r3 weak #4: evaluators stream per-batch sufficient
        statistics. The SAME rows split across many partitions (ties
        straddling batch boundaries included) must give exactly the
        single-batch metric, with no full-table collect anywhere."""
        import pyarrow as pa

        from sparkdl_tpu.data.tensors import append_tensor_column
        from sparkdl_tpu.estimators import (
            BinaryClassificationEvaluator,
            LossEvaluator,
        )

        rng = np.random.default_rng(4)
        n = 60
        labels = rng.integers(0, 3, n)
        probs = rng.dirichlet([1.0] * 3, n).astype(np.float32)
        scores = np.round(rng.random(n), 1)  # heavy score ties
        blabels = rng.integers(0, 2, n)

        def frame(parts):
            batches = []
            for lo in range(0, n, n // parts):
                hi = min(n, lo + n // parts)
                b = pa.RecordBatch.from_pylist(
                    [{"label": int(l), "blabel": int(bl),
                      "score": float(s)}
                     for l, bl, s in zip(labels[lo:hi], blabels[lo:hi],
                                         scores[lo:hi])])
                batches.append(append_tensor_column(
                    b, "prediction", probs[lo:hi]))
            return DataFrame.from_batches(batches)

        single, multi = frame(1), frame(6)
        for metric in ("accuracy", "f1", "weightedPrecision",
                       "weightedRecall"):
            ev = ClassificationEvaluator(predictionCol="prediction",
                                         labelCol="label",
                                         metricName=metric)
            assert ev.evaluate(multi) == pytest.approx(
                ev.evaluate(single)), metric
        for metric in ("areaUnderROC", "areaUnderPR"):
            ev = BinaryClassificationEvaluator(rawPredictionCol="score",
                                               labelCol="blabel",
                                               metricName=metric)
            assert ev.evaluate(multi) == pytest.approx(
                ev.evaluate(single)), metric
        loss = LossEvaluator(predictionCol="prediction",
                             labelCol="label")
        assert loss.evaluate(multi) == pytest.approx(
            loss.evaluate(single))

    def test_sparse_large_class_ids(self):
        """Confusion statistics are SPARSE: metrics on un-reindexed ids
        (e.g. raw entity ids in the millions) must compute in
        O(distinct), not allocate a dense max_id² matrix."""
        import pyarrow as pa

        labels = [0, 1_000_000, 1_000_000, 0]
        preds = [0.0, 1_000_000.0, 0.0, 0.0]
        df = DataFrame.from_batches([pa.RecordBatch.from_pylist(
            [{"label": l, "prediction": p}
             for l, p in zip(labels, preds)])])
        ev = ClassificationEvaluator(predictionCol="prediction",
                                     labelCol="label")
        assert ev.evaluate(df) == pytest.approx(3 / 4)
        f1 = ClassificationEvaluator(predictionCol="prediction",
                                     labelCol="label",
                                     metricName="f1").evaluate(df)
        # class 0: tp=2 fp=1 fn=0 → P=2/3 R=1 F1=0.8 (support 2)
        # class 1e6: tp=1 fp=0 fn=1 → P=1 R=.5 F1=2/3 (support 2)
        assert f1 == pytest.approx((0.8 * 2 + (2 / 3) * 2) / 4)

    def test_loss_evaluator_rejects_negative_vector_labels(self):
        """{-1,1}-convention labels against an (N,C) probability column
        must raise, not wrap to the last class (the scalar branch's
        twin guard)."""
        import pyarrow as pa

        from sparkdl_tpu.data.tensors import append_tensor_column

        probs = np.array([[0.7, 0.3], [0.2, 0.8]], np.float32)
        b = pa.RecordBatch.from_pylist([{"label": -1}, {"label": 1}])
        b = append_tensor_column(b, "probability", probs)
        with pytest.raises(ValueError, match="re-encode"):
            LossEvaluator().evaluate(DataFrame.from_batches([b]))

    def test_evaluators_never_collect(self, monkeypatch):
        """Scoring streams partition batches — a full-table collect of
        the scored frame (prediction vectors + every column) is the
        driver-memory cliff the streaming rewrite removed."""
        import pyarrow as pa

        from sparkdl_tpu.data.tensors import append_tensor_column
        from sparkdl_tpu.estimators import BinaryClassificationEvaluator

        rng = np.random.default_rng(1)
        batches = []
        for _ in range(3):
            b = pa.RecordBatch.from_pylist(
                [{"label": int(v)} for v in rng.integers(0, 2, 20)])
            batches.append(append_tensor_column(
                b, "prediction",
                rng.dirichlet([1.0, 1.0], 20).astype(np.float32)))
        df = DataFrame.from_batches(batches)

        def no_collect(self):
            raise AssertionError("evaluator collected the scored table")

        monkeypatch.setattr(DataFrame, "collect", no_collect)
        try:
            acc = ClassificationEvaluator(
                predictionCol="prediction").evaluate(df)
            auc = BinaryClassificationEvaluator(
                rawPredictionCol="prediction").evaluate(df)
        finally:
            monkeypatch.undo()
        assert 0.0 <= acc <= 1.0 and 0.0 <= auc <= 1.0

    def _scalar_df(self, values, labels, parts=3):
        import pyarrow as pa
        batches = []
        step = -(-len(values) // parts)
        for lo in range(0, len(values), step):
            batches.append(pa.RecordBatch.from_pylist(
                [{"label": int(l), "prediction": float(v)}
                 for v, l in zip(values[lo:lo + step],
                                 labels[lo:lo + step])]))
        return DataFrame.from_batches(batches)

    def test_prediction_semantics_streams_scalars(self, monkeypatch):
        """VERDICT r4 weak #7: the scalar 'labels or probabilities?'
        disambiguation is whole-column, so 'auto' gathers two scalar
        arrays. Declaring predictionSemantics removes the gather — with
        the module's one gather seam forbidden, declared-semantic
        scoring still works (and matches auto), while auto visibly
        needs the gather."""
        from sparkdl_tpu.estimators import evaluators as ev_mod

        probs = [0.9, 0.2, 0.8, 0.4, 0.7, 0.1]
        plabels = [1, 0, 1, 1, 1, 0]
        ids = [0.0, 1.0, 1.0, 2.0, 2.0, 0.0]
        ilabels = [0, 1, 2, 2, 2, 1]
        df_p = self._scalar_df(probs, plabels)
        df_i = self._scalar_df(ids, ilabels)
        want_p = ClassificationEvaluator(
            predictionCol="prediction").evaluate(df_p)
        want_i = ClassificationEvaluator(
            predictionCol="prediction").evaluate(df_i)

        def no_concat(*a, **k):
            raise AssertionError("declared-semantic path gathered")

        monkeypatch.setattr(ev_mod, "_gather_deferred", no_concat)
        try:
            got_p = ClassificationEvaluator(
                predictionCol="prediction",
                predictionSemantics="probabilities").evaluate(df_p)
            got_i = ClassificationEvaluator(
                predictionCol="prediction",
                predictionSemantics="labels").evaluate(df_i)
            loss = LossEvaluator(
                predictionCol="prediction",
                predictionSemantics="probabilities").evaluate(df_p)
            with pytest.raises(AssertionError, match="gathered"):
                ClassificationEvaluator(
                    predictionCol="prediction").evaluate(df_p)
        finally:
            monkeypatch.undo()
        assert got_p == pytest.approx(want_p)
        assert got_i == pytest.approx(want_i)
        picked = [p if l else 1.0 - p for p, l in zip(probs, plabels)]
        assert loss == pytest.approx(-np.mean(np.log(picked)), rel=1e-6)

    def test_prediction_semantics_declares_saturated_probabilities(self):
        """All-0.0/1.0 scalars are the ambiguous case auto resolves as
        labels; a declared 'probabilities' scores them as a saturated
        sigmoid (legal), and LossEvaluator accepts them WITHOUT the
        class-label rejection."""
        vals = [1.0, 0.0, 1.0, 0.0]
        labels = [1, 0, 0, 1]
        df = self._scalar_df(vals, labels, parts=2)
        acc = ClassificationEvaluator(
            predictionCol="prediction",
            predictionSemantics="probabilities").evaluate(df)
        assert acc == pytest.approx(0.5)
        loss = LossEvaluator(
            predictionCol="prediction",
            predictionSemantics="probabilities").evaluate(df)
        assert loss > 0.0  # clipped log(1e-7) terms, finite

    def test_auto_semantics_warns_on_saturated_01_column(self, caplog):
        """ADVICE r5 medium: the all-0.0/1.0 warning block was dead
        code — unreachable under the raw-scores raise it sat below.
        Under predictionSemantics='auto' an all-0.0/1.0 scalar column
        must SCORE (a fully saturated sigmoid is legitimate) but WARN
        that the values may be class labels."""
        import logging

        vals = [1.0, 0.0, 1.0, 0.0]
        labels = [1, 0, 0, 1]
        df = self._scalar_df(vals, labels, parts=2)
        with caplog.at_level(logging.WARNING,
                             logger="sparkdl_tpu.estimators.evaluators"):
            loss = LossEvaluator(predictionCol="prediction").evaluate(df)
        assert np.isfinite(loss) and loss > 0.0
        saturated = [r for r in caplog.records
                     if "0.0/1.0" in r.getMessage()]
        assert len(saturated) == 1, caplog.records
        # a genuinely fractional column must NOT warn
        caplog.clear()
        df_frac = self._scalar_df([0.9, 0.2, 0.8, 0.4], [1, 0, 1, 1],
                                  parts=2)
        with caplog.at_level(logging.WARNING,
                             logger="sparkdl_tpu.estimators.evaluators"):
            LossEvaluator(predictionCol="prediction").evaluate(df_frac)
        assert not [r for r in caplog.records
                    if "0.0/1.0" in r.getMessage()]

    def test_auto_semantics_rejects_raw_scores(self):
        """review r5 high #1: non-integral scalars OUTSIDE [0,1] are
        neither labels nor probabilities (raw margins mistakenly wired
        in) — auto must refuse like the declared and vector paths, for
        both the classifier and the loss."""
        df = self._scalar_df([0.3, 2.7, 5.1, 1.4], [0, 1, 1, 0],
                             parts=2)
        with pytest.raises(ValueError, match="raw scores"):
            ClassificationEvaluator(
                predictionCol="prediction").evaluate(df)
        with pytest.raises(ValueError, match="raw scores"):
            LossEvaluator(predictionCol="prediction").evaluate(df)

    def test_prediction_semantics_contradiction_raises(self):
        """Values contradicting the declared semantic raise instead of
        silently scoring a mis-wired column."""
        df_ids = self._scalar_df([0.0, 2.0], [0, 2], parts=1)
        with pytest.raises(ValueError, match="outside"):
            ClassificationEvaluator(
                predictionCol="prediction",
                predictionSemantics="probabilities").evaluate(df_ids)
        df_frac = self._scalar_df([0.3, 0.7], [0, 1], parts=1)
        with pytest.raises(ValueError, match="non-integral"):
            ClassificationEvaluator(
                predictionCol="prediction",
                predictionSemantics="labels").evaluate(df_frac)
        with pytest.raises(ValueError, match="outside"):
            LossEvaluator(
                predictionCol="prediction",
                predictionSemantics="probabilities").evaluate(df_ids)

    def test_prediction_semantics_validation(self):
        with pytest.raises(ValueError, match="predictionSemantics"):
            ClassificationEvaluator(predictionSemantics="scores")
        with pytest.raises(ValueError, match="predictionSemantics"):
            LossEvaluator(predictionSemantics="labels")
        # set() bypasses __init__ validation — evaluate must re-check
        ev = ClassificationEvaluator(predictionCol="prediction")
        ev.set(ev.predictionSemantics, "scores")
        with pytest.raises(ValueError, match="predictionSemantics"):
            ev.evaluate(self._scalar_df([0.0, 1.0], [0, 1], parts=1))
        lv = LossEvaluator(predictionCol="prediction")
        lv.set(lv.predictionSemantics, "labels")
        with pytest.raises(ValueError, match="predictionSemantics"):
            lv.evaluate(self._scalar_df([0.5, 0.5], [0, 1], parts=1))

    def _binary_df(self):
        import pyarrow as pa
        from sparkdl_tpu.data.tensors import append_tensor_column
        preds = np.array([[0.9], [0.2], [0.8]], dtype=np.float32)
        batch = pa.RecordBatch.from_pylist(
            [{"label": 1}, {"label": 0}, {"label": 1}])
        batch = append_tensor_column(batch, "prediction", preds)
        return DataFrame.from_batches([batch])

    def test_binary_sigmoid_accuracy(self):
        """(N,1) sigmoid outputs must threshold, not argmax (regression:
        argmax(-1) over width-1 vectors is always 0)."""
        ev = ClassificationEvaluator(predictionCol="prediction",
                                     labelCol="label")
        assert ev.evaluate(self._binary_df()) == pytest.approx(1.0)

    def test_binary_auc_metrics(self):
        """areaUnderROC / areaUnderPR against hand-computed values,
        including score ties (average-rank handling) and the (N,2)
        probability-vector input shape."""
        import pyarrow as pa

        from sparkdl_tpu.data.tensors import append_tensor_column
        from sparkdl_tpu.estimators import BinaryClassificationEvaluator

        # scores: .9 .8 .8 .4 .2 — labels: 1 1 0 0 1 (tie at .8)
        scores = np.array([0.9, 0.8, 0.8, 0.4, 0.2], np.float64)
        labels = [1, 1, 0, 0, 1]
        batch = pa.RecordBatch.from_pylist(
            [{"label": l, "probability": s}
             for l, s in zip(labels, scores)])
        df = DataFrame.from_batches([batch])

        # ranks asc: .2→1, .4→2, .8→(3+4)/2=3.5 each, .9→5
        # pos rank sum = 5 + 3.5 + 1 = 9.5 → AUC = (9.5 - 6) / (3*2)
        ev = BinaryClassificationEvaluator()
        assert ev.evaluate(df) == pytest.approx((9.5 - 6.0) / 6.0)
        assert ev.isLargerBetter()

        # AP with the .8 tie grouped into ONE threshold:
        # .9 → tp 1, prec 1/1; .8 → tp 1, prec 2/3; .2 → tp 1, prec 3/5
        # AP = (1·1 + 1·(2/3) + 1·0.6) / 3
        ap = BinaryClassificationEvaluator(metricName="areaUnderPR")
        assert ap.evaluate(df) == pytest.approx(
            (1.0 + 2.0 / 3.0 + 0.6) / 3.0)

        # tie handling must be row-order invariant: the same
        # (score, label) multiset in any order gives one value
        for labs in ([1, 0], [0, 1]):
            bt = pa.RecordBatch.from_pylist(
                [{"label": l, "probability": 0.8} for l in labs]
                + [{"label": 0, "probability": 0.1}])
            v = ap.evaluate(DataFrame.from_batches([bt]))
            assert v == pytest.approx(0.5), labs

        # (N,2) probability vectors: class-1 column is the score
        probs = np.stack([1.0 - scores, scores], axis=1) \
            .astype(np.float32)
        b2 = pa.RecordBatch.from_pylist([{"label": l} for l in labels])
        b2 = append_tensor_column(b2, "probability", probs)
        df2 = DataFrame.from_batches([b2])
        assert BinaryClassificationEvaluator().evaluate(df2) == \
            pytest.approx((9.5 - 6.0) / 6.0)

    def test_binary_default_col_matches_pyspark(self):
        """ADVICE r3: default rawPredictionCol is 'rawPrediction'
        (pyspark parity); 'probability' is only a fallback when that
        column is absent, and never shadows a real 'rawPrediction'."""
        import pyarrow as pa

        from sparkdl_tpu.estimators import BinaryClassificationEvaluator

        ev = BinaryClassificationEvaluator()
        assert ev.getOrDefault("rawPredictionCol") == "rawPrediction"
        # margins in rawPrediction rank opposite to the decoy column:
        # the default must read rawPrediction, not probability
        both = pa.RecordBatch.from_pylist(
            [{"label": 1, "rawPrediction": 2.0, "probability": 0.1},
             {"label": 0, "rawPrediction": -1.0, "probability": 0.9}])
        assert ev.evaluate(DataFrame.from_batches([both])) == 1.0
        only_prob = pa.RecordBatch.from_pylist(
            [{"label": 1, "probability": 0.9},
             {"label": 0, "probability": 0.2}])
        assert ev.evaluate(DataFrame.from_batches([only_prob])) == 1.0

    def test_binary_auc_validation(self):
        import pyarrow as pa

        from sparkdl_tpu.estimators import BinaryClassificationEvaluator

        with pytest.raises(ValueError, match="metricName"):
            BinaryClassificationEvaluator(metricName="rocCurve")
        multi = pa.RecordBatch.from_pylist(
            [{"label": 2, "probability": 0.5},
             {"label": 0, "probability": 0.1}])
        with pytest.raises(ValueError, match="binary"):
            BinaryClassificationEvaluator().evaluate(
                DataFrame.from_batches([multi]))
        one_class = pa.RecordBatch.from_pylist(
            [{"label": 1, "probability": 0.5},
             {"label": 1, "probability": 0.1}])
        with pytest.raises(ValueError, match="single class"):
            BinaryClassificationEvaluator().evaluate(
                DataFrame.from_batches([one_class]))

    def test_binary_sigmoid_loss(self):
        ev = LossEvaluator(predictionCol="prediction", labelCol="label")
        expected = -np.mean(np.log([0.9, 0.8, 0.8]))
        assert ev.evaluate(self._binary_df()) == pytest.approx(
            expected, rel=1e-5)

    def test_loss_evaluator(self):
        ev = LossEvaluator(predictionCol="prediction", labelCol="label")
        expected = -np.mean(np.log([0.9, 0.8, 0.4]))
        assert ev.evaluate(self._df()) == pytest.approx(expected, rel=1e-5)
        assert not ev.isLargerBetter()

    def test_loss_evaluator_defaults_to_probability_column(self):
        """The default predictionCol must be 'probability' — with
        LogisticRegressionModel, 'prediction' holds the float64 CLASS
        LABEL, and for a binary model cross-entropy on labels is
        undetectable from values alone (all 0.0/1.0 looks like a
        saturated sigmoid). Wiring LossEvaluator() to an LR pipeline
        must score the model's probabilities by default."""
        assert LossEvaluator().getOrDefault("predictionCol") \
            == "probability"

    def test_loss_evaluator_rejects_class_label_column(self):
        """Pointing LossEvaluator at a class-label column (e.g.
        LogisticRegressionModel's predictionCol) must error, not return
        a plausible-looking garbage loss."""
        import pyarrow as pa

        from sparkdl_tpu.data.frame import DataFrame
        batch = pa.RecordBatch.from_pylist(
            [{"prediction": 2.0, "label": 2},
             {"prediction": 0.0, "label": 0},
             {"prediction": 1.0, "label": 2}])
        df = DataFrame.from_batches([batch])
        ev = LossEvaluator(predictionCol="prediction", labelCol="label")
        with pytest.raises(ValueError, match="class labels"):
            ev.evaluate(df)

    def test_loss_evaluator_rejects_negative_values(self):
        """Negative values are as definitively not-probabilities as
        values above 1 (e.g. a {-1,1} label column) — clipping them
        returned a near-perfect garbage loss (regression)."""
        import pyarrow as pa

        from sparkdl_tpu.data.frame import DataFrame
        batch = pa.RecordBatch.from_pylist(
            [{"prediction": -1.0, "label": 0},
             {"prediction": 1.0, "label": 1}])
        df = DataFrame.from_batches([batch])
        ev = LossEvaluator(predictionCol="prediction", labelCol="label")
        with pytest.raises(ValueError, match="negative"):
            ev.evaluate(df)

    def test_loss_evaluator_rejects_logits_vector_column(self):
        """A 2-D prediction column holding raw logits (negatives or
        values above 1) must raise like the 1-D guards do, not be
        silently clipped into a plausible loss (ADVICE r2 #3)."""
        import pyarrow as pa

        from sparkdl_tpu.data.frame import DataFrame
        from sparkdl_tpu.data.tensors import append_tensor_column

        batch = pa.RecordBatch.from_pylist([{"label": 0}, {"label": 1}])
        logits = np.array([[2.5, -1.3], [-0.2, 4.1]], dtype=np.float32)
        batch = append_tensor_column(batch, "probability", logits)
        ev = LossEvaluator(labelCol="label")
        with pytest.raises(ValueError, match="outside"):
            ev.evaluate(DataFrame.from_batches([batch]))

    def test_loss_evaluator_rejects_n1_label_tensor_column(self):
        """The same mistake stored as an (N,1) tensor column must hit
        the guard too (regression: the squeeze ran after it)."""
        import pyarrow as pa

        from sparkdl_tpu.data.frame import DataFrame
        from sparkdl_tpu.data.tensors import append_tensor_column
        batch = pa.RecordBatch.from_pylist(
            [{"label": 2}, {"label": 0}, {"label": 2}])
        batch = append_tensor_column(
            batch, "prediction",
            np.array([[2.0], [0.0], [1.0]], np.float32))
        df = DataFrame.from_batches([batch])
        ev = LossEvaluator(predictionCol="prediction", labelCol="label")
        with pytest.raises(ValueError, match="class labels"):
            ev.evaluate(df)


class TestTargetPrep:
    def test_int_labels_one_hot(self):
        y = np.array([0, 2, 1])
        out = KerasImageFileEstimator._prepare_targets(
            y, "categorical_crossentropy", 3)
        np.testing.assert_array_equal(
            out, np.eye(3, dtype=np.float32)[[0, 2, 1]])

    def test_float_passthrough(self):
        y = np.array([[0.0, 1.0], [1.0, 0.0]])
        out = KerasImageFileEstimator._prepare_targets(y, "mse", 2)
        assert out.dtype == np.float32
        np.testing.assert_array_equal(out, y)

    def test_double_class_labels_one_hot(self):
        """Spark-convention float64 integral class ids must one-hot for
        categorical losses exactly like ints (regression: they fell
        into the 1-D lift and raised for any multi-wide head)."""
        y = np.array([0.0, 1.0, 1.0, 0.0])
        out = KerasImageFileEstimator._prepare_targets(
            y, "categorical_crossentropy", 2)
        np.testing.assert_array_equal(
            out, np.eye(2, dtype=np.float32)[[0, 1, 1, 0]])
        # fractional labels stay out of the one-hot path: they lift and
        # raise against a multi-wide head rather than round silently
        with pytest.raises(ValueError, match="1-D targets"):
            KerasImageFileEstimator._prepare_targets(
                np.array([0.5, 1.0]), "categorical_crossentropy", 2)
        # out-of-range ids raise instead of np.eye silently WRAPPING
        # -1 to the last class (regression)
        for bad in ([-1.0, 1.0], [0, 3]):
            with pytest.raises(ValueError, match="re-encode|class ids"):
                KerasImageFileEstimator._prepare_targets(
                    np.array(bad), "categorical_crossentropy", 2)


def test_evaluators_raise_on_empty_scored_frame():
    """One convention across all three evaluators (advisor r4 #4): an
    empty scored frame raises — the TYPED EmptyScoredFrameError (a
    ValueError), so tuning can nan-skip a degenerate fold while
    standalone calls still fail loudly."""
    import pyarrow as pa

    from sparkdl_tpu.estimators import EmptyScoredFrameError
    from sparkdl_tpu.estimators.evaluators import (
        BinaryClassificationEvaluator,
        ClassificationEvaluator,
        LossEvaluator,
    )
    assert issubclass(EmptyScoredFrameError, ValueError)
    empty = DataFrame.from_table(pa.table({
        "prediction": pa.array([], pa.float64()),
        "label": pa.array([], pa.float64())}))
    for ev in (ClassificationEvaluator(), LossEvaluator(),
               BinaryClassificationEvaluator(
                   rawPredictionCol="prediction")):
        with pytest.raises(EmptyScoredFrameError,
                           match="empty|no rows|0 rows"):
            ev.evaluate(empty)


def test_evaluators_refuse_non_finite_scores():
    """NaN predictions measured accuracy 0.5 and AUC 0.5 before the
    guard — plausible numbers a CV could SELECT on from a diverged
    model. All three evaluators must refuse NaN/Inf loudly."""
    import pyarrow as pa

    from sparkdl_tpu.data.tensors import append_tensor_column
    from sparkdl_tpu.estimators import (
        BinaryClassificationEvaluator,
        ClassificationEvaluator,
        LossEvaluator,
    )

    rows = [{"label": i % 2, "prediction": float("nan")}
            for i in range(4)]
    df = DataFrame.from_batches([pa.RecordBatch.from_pylist(rows)])
    for ev in (ClassificationEvaluator(predictionCol="prediction"),
               BinaryClassificationEvaluator(
                   rawPredictionCol="prediction"),
               LossEvaluator(predictionCol="prediction")):
        with pytest.raises(ValueError, match="non-finite"):
            ev.evaluate(df)
    # vector predictions too
    b = pa.RecordBatch.from_pylist([{"label": i % 2} for i in range(4)])
    b = append_tensor_column(
        b, "prediction", np.full((4, 2), np.inf, np.float32))
    df2 = DataFrame.from_batches([b])
    with pytest.raises(ValueError, match="non-finite"):
        ClassificationEvaluator(predictionCol="prediction").evaluate(df2)


class TestEmptyFoldHandling:
    """review r5: one degenerate CV fold (validation side emptied by
    upstream filters) must not crash the whole search after N-1 folds
    of work — the fold nan-skips with a loud warning. TVS's single
    validation side is shared by every candidate, so there it stays a
    hard error, with attribution."""

    def _stub(self):
        from sparkdl_tpu.params.pipeline import Estimator, Model

        class _M(Model):
            def _transform(self, dataset):
                return dataset

        class _E(Estimator):
            def _fit(self, dataset):
                return _M()

        return _E()

    def _flaky_ev(self, fail_calls):
        from sparkdl_tpu.params.pipeline import (
            EmptyScoredFrameError,
            Evaluator,
        )

        class _Ev(Evaluator):
            calls = 0

            def evaluate(self, dataset):
                _Ev.calls += 1
                if _Ev.calls in fail_calls:
                    raise EmptyScoredFrameError("0 rows")
                return float(_Ev.calls)

        return _Ev()

    def _df(self):
        import pyarrow as pa
        return DataFrame.from_table(
            pa.table({"x": np.arange(24.0), "label": [0, 1] * 12}), 4)

    def test_cv_nan_skips_empty_fold(self, caplog):
        import logging

        from sparkdl_tpu.params.tuning import CrossValidator

        # call order: fold0 cand0 (empty -> skipped), fold0 cand1 = 2,
        # fold1 cand0 = 3, fold1 cand1 = 4. fold0 is excluded from
        # EVERY candidate's average (common-subset comparison): cand0
        # averages {fold1}=3, cand1 averages {fold1}=4 — NOT (2+4)/2,
        # which would score cand1 on a fold cand0 never saw.
        cv = CrossValidator(estimator=self._stub(),
                            estimatorParamMaps=[{}, {}],
                            evaluator=self._flaky_ev({1}), numFolds=2)
        with caplog.at_level(logging.WARNING):
            m = cv.fit(self._df())
        assert m.avgMetrics == pytest.approx([3.0, 4.0])
        assert any("scored 0 rows" in r.message for r in caplog.records)
        assert any("common" in r.message for r in caplog.records)

    def test_cv_all_empty_raises(self):
        from sparkdl_tpu.params.tuning import CrossValidator

        cv = CrossValidator(estimator=self._stub(),
                            estimatorParamMaps=[{}, {}],
                            evaluator=self._flaky_ev(set(range(1, 20))),
                            numFolds=2)
        with pytest.raises(ValueError, match="no fold"):
            cv.fit(self._df())

    def test_tvs_empty_validation_raises_with_context(self):
        from sparkdl_tpu.params.tuning import TrainValidationSplit

        tvs = TrainValidationSplit(
            estimator=self._stub(), estimatorParamMaps=[{}],
            evaluator=self._flaky_ev({1}))
        with pytest.raises(ValueError, match="validation side"):
            tvs.fit(self._df())

    def test_collect_sub_models(self):
        """pyspark 2.3 parity: collectSubModels=True keeps every
        (fold, candidate) fitted model — [fold][candidate] for CV,
        [candidate] for TVS; the default result carries None."""
        from sparkdl_tpu.params.pipeline import Model
        from sparkdl_tpu.params.tuning import (
            CrossValidator,
            TrainValidationSplit,
        )

        df = self._df()
        cv = CrossValidator(estimator=self._stub(),
                            estimatorParamMaps=[{}, {}, {}],
                            evaluator=self._flaky_ev(set()),
                            numFolds=2, collectSubModels=True)
        m = cv.fit(df)
        assert len(m.subModels) == 2  # folds
        assert all(len(fold) == 3 for fold in m.subModels)
        assert all(isinstance(s, Model)
                   for fold in m.subModels for s in fold)
        # sub-models are usable transformers
        assert m.subModels[0][0].transform(df).count() == 24
        assert CrossValidator(
            estimator=self._stub(), estimatorParamMaps=[{}],
            evaluator=self._flaky_ev(set()),
            numFolds=2).fit(df).subModels is None

        tvs = TrainValidationSplit(estimator=self._stub(),
                                   estimatorParamMaps=[{}, {}],
                                   evaluator=self._flaky_ev(set()),
                                   collectSubModels=True)
        tm = tvs.fit(df)
        assert len(tm.subModels) == 2
        assert all(isinstance(s, Model) for s in tm.subModels)
        assert TrainValidationSplit(
            estimator=self._stub(), estimatorParamMaps=[{}],
            evaluator=self._flaky_ev(set())).fit(df).subModels is None


class TestLRMemoryBudget:
    """VERDICT r4 #4: streaming-safe defaults — a larger-than-budget
    feature table never materializes in driver RAM."""

    @property
    def LR(self):
        from sparkdl_tpu.estimators.logistic_regression import (
            LogisticRegression,
        )
        return LogisticRegression

    def _frame(self, n=64, width=8, parts=4):
        import pyarrow as pa

        from sparkdl_tpu.data.tensors import append_tensor_column
        rng = np.random.default_rng(3)
        X = rng.normal(size=(n, width)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float64)
        b = pa.RecordBatch.from_pydict({"label": pa.array(y)})
        b = append_tensor_column(b, "features", X)
        import pyarrow as pa2
        return DataFrame.from_table(pa2.Table.from_batches([b]), parts)

    def test_auto_switch_never_collects(self, monkeypatch, caplog):
        import logging

        df = self._frame()
        # tiny budget: 64×8×4 = 2 KiB > 1 KiB → must auto-stream
        lr = self.LR(maxIter=3, memoryBudgetBytes=1024)
        monkeypatch.setattr(
            DataFrame, "collect",
            lambda self: (_ for _ in ()).throw(
                AssertionError("budget auto-switch must not collect")))
        with caplog.at_level(logging.WARNING):
            model = lr.fit(df)
        assert "auto-switching to the streaming fit" in caplog.text
        assert model.numClasses == 2
        # inference needed no extra args: numClasses came from the
        # labels-only first pass
        scored = model.transform(df)
        assert "prediction" in scored.columns

    def test_under_budget_keeps_collected_path(self, caplog):
        import logging

        df = self._frame()
        lr = self.LR(maxIter=3)  # default 1 GiB budget
        with caplog.at_level(logging.WARNING):
            model = lr.fit(df)
        assert "auto-switching" not in caplog.text
        assert model.numClasses == 2

    def test_mid_collect_watchdog_warns_on_unknown_counts(self, caplog):
        import logging

        # a filter makes the row count unknowable for free → the
        # pre-collect estimate is None; the mid-collect watchdog warns
        df = self._frame().filter(
            lambda b: np.ones(b.num_rows, bool))
        assert df.known_count() is None
        lr = self.LR(maxIter=2, memoryBudgetBytes=512)
        with caplog.at_level(logging.WARNING):
            lr.fit(df)
        assert "buffered" in caplog.text

    def test_budget_zero_disables(self, caplog):
        import logging

        df = self._frame()
        lr = self.LR(maxIter=2, memoryBudgetBytes=0)
        with caplog.at_level(logging.WARNING):
            lr.fit(df)
        assert "auto-switching" not in caplog.text

    def test_misspelled_features_col_fails_clearly(self, caplog):
        """review r5: schema.field(get_field_index('typo')) == -1
        negative-indexes the LAST field — the estimate must not be
        computed from the wrong column (which could trigger a bogus
        auto-switch before the real missing-column error)."""
        import logging

        # big tensor column LAST in the schema: the buggy lookup would
        # estimate from it and cross the tiny budget
        df = self._frame(n=64, width=64)
        lr = self.LR(maxIter=2, featuresCol="featurs",
                     memoryBudgetBytes=1024)
        with caplog.at_level(logging.WARNING):
            with pytest.raises(KeyError, match="featurs"):
                lr.fit(df)
        assert "auto-switching" not in caplog.text
