"""useMesh pipeline-surface tests (multi-chip DP inference through the
transformers; tests run on the 8 simulated CPU devices) and the Spark
binding seam."""

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.data import DataFrame
from sparkdl_tpu.data.frame import Stage
from sparkdl_tpu.data.spark_binding import (
    SparkEngine,
    plan_to_map_in_arrow,
)
from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.parallel.inference import ShardedBatchRunner
from sparkdl_tpu.runtime.runner import BatchRunner
from sparkdl_tpu.transformers import (
    DeepImageFeaturizer,
    ImageTransformer,
    TensorTransformer,
)


@pytest.fixture(scope="module")
def image_df(tmp_path_factory):
    from PIL import Image
    rng = np.random.default_rng(21)
    d = tmp_path_factory.mktemp("meshimgs")
    for i in range(7):
        arr = rng.integers(0, 255, (20, 24, 3), dtype=np.uint8)
        Image.fromarray(arr, "RGB").save(d / f"m{i}.png")
    return imageIO.readImages(str(d), numPartitions=2)


class TestUseMesh:
    def test_featurizer_mesh_matches_single_device(self, image_df):
        single = DeepImageFeaturizer(modelName="TestNet", inputCol="image",
                                     outputCol="f", batchSize=2)
        sharded = DeepImageFeaturizer(modelName="TestNet", inputCol="image",
                                      outputCol="f", batchSize=2,
                                      useMesh=True)
        a = single.transform(image_df).tensor("f")
        b = sharded.transform(image_df).tensor("f")
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

    def test_device_resize_mesh_matches_single_device(self, image_df):
        """deviceResizeFrom + useMesh: the fused resize+model program
        shards over the data axis like any other model program."""
        kw = dict(modelName="TestNet", inputCol="image", outputCol="f",
                  batchSize=2, deviceResizeFrom=(20, 24))
        a = DeepImageFeaturizer(**kw).transform(image_df).tensor("f")
        b = DeepImageFeaturizer(useMesh=True, **kw) \
            .transform(image_df).tensor("f")
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

    def test_tensor_transformer_mesh(self):
        mf = ModelFunction.fromSingle(
            lambda x: x * 3.0, None, input_shape=(4,), name="triple")
        rows = [{"x": [float(i)] * 4} for i in range(10)]
        df = DataFrame.from_pylist(rows, num_partitions=2)
        t = TensorTransformer(modelFunction=mf,
                              inputMapping={"x": "input"},
                              outputMapping={"output": "y"},
                              batchSize=2, useMesh=True)
        got = t.transform(df).tensor("y")
        np.testing.assert_allclose(got[:, 0], np.arange(10) * 3.0,
                                   rtol=1e-6)

    def test_make_runner_selects_sharded(self):
        from sparkdl_tpu.transformers.utils import make_runner
        mf = ModelFunction.fromSingle(lambda x: x, None, input_shape=(2,))
        assert isinstance(make_runner(mf, 4, use_mesh=True),
                          ShardedBatchRunner)
        assert isinstance(make_runner(mf, 4, use_mesh=False), BatchRunner)

    def test_host_backend_falls_back_with_warning(self, caplog):
        import logging
        from sparkdl_tpu.transformers.utils import make_runner
        mf = ModelFunction(lambda p, i: i, None, {"x": ((2,), np.float32)},
                           output_names=["x"], backend="host")
        with caplog.at_level(logging.WARNING):
            r = make_runner(mf, 4, use_mesh=True)
        assert isinstance(r, BatchRunner)
        assert any("useMesh" in rec.message for rec in caplog.records)

    def test_sharded_program_cached_across_runners(self):
        """Two sharded runners over one model share the compiled program
        and the replicated weights (regression: per-runner re-jit and
        re-transfer)."""
        mf = ModelFunction.fromSingle(lambda x: x + 1.0, None,
                                      input_shape=(2,))
        r1 = ShardedBatchRunner(mf, batch_size=2)
        r2 = ShardedBatchRunner(mf, batch_size=4)
        x = np.zeros((8, 2), np.float32)
        r1.run({"input": x})
        r2.run({"input": x})
        assert r1.mesh == r2.mesh
        assert mf.sharded_jitted(r1.mesh) is mf.sharded_jitted(r2.mesh)


class TestSparkBinding:
    def test_plan_compiles_and_applies_without_spark(self):
        """plan_to_map_in_arrow is pure: it must run the stage chain
        over an Arrow batch iterator with no pyspark present."""
        def add_one(batch):
            vals = [v + 1 for v in batch.column(0).to_pylist()]
            return pa.RecordBatch.from_pydict({"x": pa.array(vals)})

        fn = plan_to_map_in_arrow([Stage(add_one, name="inc"),
                                   Stage(add_one, name="inc2")])
        batches = [pa.RecordBatch.from_pydict({"x": pa.array([1, 2])}),
                   pa.RecordBatch.from_pydict({"x": pa.array([10])})]
        out = list(fn(iter(batches)))
        assert [b.column(0).to_pylist() for b in out] == [[3, 4], [12]]

    def test_spark_engine_requires_pyspark(self):
        with pytest.raises(RuntimeError, match="pyspark"):
            SparkEngine()

    def test_executor_contract_real_plan_matches_local_engine(
            self, tmp_path_factory):
        """The full executor calling convention: a hand-built
        iterator-of-RecordBatches loop (what Spark's mapInArrow does on
        each task) over a REAL decode→resize/pack→model-apply plan must
        produce exactly what LocalEngine produces."""
        from PIL import Image
        rng = np.random.default_rng(33)
        d = tmp_path_factory.mktemp("bindimgs")
        for i in range(6):
            arr = rng.integers(0, 255, (16 + i, 20, 3), dtype=np.uint8)
            Image.fromarray(arr, "RGB").save(d / f"b{i}.png")

        df = imageIO.readImagesPacked(str(d), size=(8, 8),
                                      numPartitions=3)
        mf = ModelFunction.fromSingle(
            lambda x: x.reshape(x.shape[0], -1).astype("float32").sum(
                axis=1, keepdims=True),
            None, input_shape=(8, 8, 3), input_dtype=np.uint8,
            name="sum")
        out_df = TensorTransformer(modelFunction=mf,
                                   inputMapping={"image": "input"},
                                   outputMapping={"output": "s"},
                                   batchSize=4).transform(df)

        expected = out_df.collect()  # LocalEngine path

        # fake-executor loop: one task per partition source, each task
        # streams its batches through the compiled plan fn
        fn = plan_to_map_in_arrow(out_df._plan)
        got_batches = []
        for source in out_df._sources:
            got_batches.extend(fn(iter([source.load()])))
        got = pa.Table.from_batches(got_batches)

        assert got.schema == expected.schema
        assert got.column("filePath").to_pylist() == \
            expected.column("filePath").to_pylist()
        np.testing.assert_array_equal(
            np.asarray(got.column("s").combine_chunks().flatten()),
            np.asarray(expected.column("s").combine_chunks().flatten()))

    def test_executor_contract_with_index_stage(self):
        """with_index stages get the partition id (0 without a Spark
        TaskContext) — same convention LocalEngine now follows."""
        seen = []

        def probe(batch, index):
            seen.append(index)
            return batch

        fn = plan_to_map_in_arrow(
            [Stage(probe, name="probe", with_index=True)])
        batch = pa.RecordBatch.from_pydict({"x": pa.array([1])})
        list(fn(iter([batch])))
        assert seen == [0]


def test_yuv420_model_shards_on_mesh(tmp_path):
    """The 4:2:0 reconstruction op claims GSPMD-shardability (XLA-only
    einsum chain) — prove it: the same yuv420-wrapped model through the
    8-device ShardedBatchRunner must equal the single-device runner,
    through the full packed-reader flow, with a tail that pads."""
    from PIL import Image

    from sparkdl_tpu.models.zoo import getModelFunction
    from sparkdl_tpu.transformers.utils import (
        deviceResizeModel,
        single_io,
    )
    from sparkdl_tpu.utils.synth import textured_image

    rng = np.random.default_rng(9)
    for i in range(11):  # deliberately ragged vs 8-device global batch
        Image.fromarray(textured_image(rng, 40, 48), "RGB").save(
            tmp_path / f"m{i}.jpg", quality=90)
    mf = getModelFunction("TestNet", featurize=True)
    mfp = deviceResizeModel(mf, (24, 24), packedFormat="yuv420")
    in_name, out_name = single_io(mfp)
    packed = imageIO.readImagesPacked(str(tmp_path), (24, 24),
                                      numPartitions=3,
                                      packedFormat="yuv420")
    x = packed.tensor("image")

    single = BatchRunner(mfp, batch_size=4).run({in_name: x})[out_name]
    sharded = ShardedBatchRunner(mfp, batch_size=2).run(
        {in_name: x})[out_name]
    np.testing.assert_allclose(sharded, single, rtol=2e-4, atol=2e-5)
