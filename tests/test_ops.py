"""Pallas infeed kernel tests (interpret mode on CPU) + parity with
jax.image.resize — the op must be a drop-in for the resize+normalize
the image pipelines do on-device."""

import numpy as np
import pytest

from sparkdl_tpu.ops import bilinear_weight_matrix, fused_resize_normalize


@pytest.fixture(scope="module")
def batch(rng):
    return rng.integers(0, 255, (3, 40, 56, 3), dtype=np.uint8)


class TestWeights:
    def test_identity_when_same_size(self):
        np.testing.assert_array_equal(bilinear_weight_matrix(32, 32),
                                      np.eye(32, dtype=np.float32))

    def test_rows_normalized(self):
        for src, dst in [(40, 299), (299, 40), (17, 23), (64, 8)]:
            w = bilinear_weight_matrix(src, dst)
            assert w.shape == (dst, src)
            np.testing.assert_allclose(w.sum(axis=1), 1.0, atol=1e-6)

    @pytest.mark.parametrize("src,dst", [(40, 64), (64, 24), (56, 299)])
    def test_matches_jax_image_resize(self, batch, src, dst):
        """The separable-matmul resize must equal jax.image.resize's
        anti-aliased bilinear (same triangle kernel)."""
        import jax
        import jax.numpy as jnp

        x = batch.astype(np.float32)
        got = fused_resize_normalize(x, (dst, dst), use_pallas=False)
        ref = jax.image.resize(jnp.asarray(x),
                               (x.shape[0], dst, dst, x.shape[3]),
                               method="bilinear")
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-4, atol=1e-3)


class TestFusedOp:
    def test_pallas_interpret_matches_xla(self, batch):
        a = fused_resize_normalize(batch, (24, 32), scale=1 / 127.5,
                                   offset=-1.0, use_pallas=False)
        b = fused_resize_normalize(batch, (24, 32), scale=1 / 127.5,
                                   offset=-1.0, use_pallas=True,
                                   interpret=True)
        assert np.asarray(a).shape == (3, 24, 32, 3)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)

    def test_normalize_applied(self, batch):
        plain = fused_resize_normalize(batch, (20, 20), use_pallas=False)
        scaled = fused_resize_normalize(batch, (20, 20), scale=2.0,
                                        offset=5.0, use_pallas=False)
        np.testing.assert_allclose(np.asarray(scaled),
                                   np.asarray(plain) * 2.0 + 5.0,
                                   rtol=1e-5, atol=1e-4)

    def test_output_dtype(self, batch):
        import jax.numpy as jnp
        out = fused_resize_normalize(batch, (16, 16), dtype=jnp.bfloat16,
                                     use_pallas=False)
        assert np.asarray(out).dtype == jnp.bfloat16

    def test_jittable_inside_program(self, batch):
        """The op composes under jit (how deviceResizeModel embeds it:
        one XLA program with the model)."""
        import jax

        f = jax.jit(lambda x: fused_resize_normalize(
            x, (16, 16), scale=1 / 255.0, use_pallas=False).sum())
        assert np.isfinite(float(f(batch)))
