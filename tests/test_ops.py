"""Pallas infeed kernel tests (interpret mode on CPU) + parity with
jax.image.resize — the op must be a drop-in for the resize+normalize
the image pipelines do on-device."""

import numpy as np
import pytest

from sparkdl_tpu.ops import bilinear_weight_matrix, fused_resize_normalize


@pytest.fixture(scope="module")
def batch(rng):
    return rng.integers(0, 255, (3, 40, 56, 3), dtype=np.uint8)


class TestWeights:
    def test_identity_when_same_size(self):
        np.testing.assert_array_equal(bilinear_weight_matrix(32, 32),
                                      np.eye(32, dtype=np.float32))

    def test_rows_normalized(self):
        for src, dst in [(40, 299), (299, 40), (17, 23), (64, 8)]:
            w = bilinear_weight_matrix(src, dst)
            assert w.shape == (dst, src)
            np.testing.assert_allclose(w.sum(axis=1), 1.0, atol=1e-6)

    @pytest.mark.parametrize("src,dst", [(40, 64), (64, 24), (56, 299)])
    def test_matches_jax_image_resize(self, batch, src, dst):
        """The separable-matmul resize must equal jax.image.resize's
        anti-aliased bilinear (same triangle kernel)."""
        import jax
        import jax.numpy as jnp

        x = batch.astype(np.float32)
        got = fused_resize_normalize(x, (dst, dst), use_pallas=False)
        ref = jax.image.resize(jnp.asarray(x),
                               (x.shape[0], dst, dst, x.shape[3]),
                               method="bilinear")
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-4, atol=1e-3)


class TestFusedOp:
    def test_pallas_interpret_matches_xla(self, batch):
        a = fused_resize_normalize(batch, (24, 32), scale=1 / 127.5,
                                   offset=-1.0, use_pallas=False)
        b = fused_resize_normalize(batch, (24, 32), scale=1 / 127.5,
                                   offset=-1.0, use_pallas=True,
                                   interpret=True)
        assert np.asarray(a).shape == (3, 24, 32, 3)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)

    def test_normalize_applied(self, batch):
        plain = fused_resize_normalize(batch, (20, 20), use_pallas=False)
        scaled = fused_resize_normalize(batch, (20, 20), scale=2.0,
                                        offset=5.0, use_pallas=False)
        np.testing.assert_allclose(np.asarray(scaled),
                                   np.asarray(plain) * 2.0 + 5.0,
                                   rtol=1e-5, atol=1e-4)

    def test_output_dtype(self, batch):
        import jax.numpy as jnp
        out = fused_resize_normalize(batch, (16, 16), dtype=jnp.bfloat16,
                                     use_pallas=False)
        assert np.asarray(out).dtype == jnp.bfloat16

    def test_jittable_inside_program(self, batch):
        """The op composes under jit (how deviceResizeModel embeds it:
        one XLA program with the model)."""
        import jax

        f = jax.jit(lambda x: fused_resize_normalize(
            x, (16, 16), scale=1 / 255.0, use_pallas=False).sum())
        assert np.isfinite(float(f(batch)))


class TestYuv420DeviceOp:
    """Device half of the 4:2:0 payload path: fused chroma-upsample +
    BT.601 reconstruction + resize (ops.fused_yuv420_resize_normalize)."""

    def test_constant_chroma_matches_rgb_path(self):
        """With spatially constant chroma the 2×2 subsample is lossless,
        so the 420 route must equal the RGB route up to the codec's
        uint8 rounding (≤2 counts after resize)."""
        from sparkdl_tpu.image.imageIO import rgbToYuv420
        from sparkdl_tpu.ops import fused_yuv420_resize_normalize
        # constant color per image -> constant chroma planes
        colors = np.array([[200, 40, 90], [10, 250, 128]], np.uint8)
        rgb = np.broadcast_to(colors[:, None, None, :],
                              (2, 24, 32, 3)).copy()
        packed = np.stack([rgbToYuv420(im) for im in rgb])
        got = np.asarray(fused_yuv420_resize_normalize(
            packed, (24, 32), (48, 64)))
        exp = np.asarray(fused_resize_normalize(rgb, (48, 64)))
        assert np.abs(got - exp).max() <= 2.0

    def test_textured_within_chroma_tolerance(self, rng):
        """On textured data the only divergence from the RGB route is
        the 2×2 chroma subsample itself (synthetic textures carry
        full-bandwidth chroma, unlike JPEG sources whose chroma the
        encoder already band-limited — those measure ~0.8 mean, see
        test_native.py): mean ≤2.5 counts, p99 ≤12."""
        from sparkdl_tpu.image.imageIO import rgbToYuv420
        from sparkdl_tpu.ops import fused_yuv420_resize_normalize
        from sparkdl_tpu.utils.synth import textured_image
        rgb = np.stack([textured_image(rng, 40, 56) for _ in range(3)])
        packed = np.stack([rgbToYuv420(im) for im in rgb])
        got = np.asarray(fused_yuv420_resize_normalize(
            packed, (40, 56), (30, 42)))
        exp = np.asarray(fused_resize_normalize(rgb, (30, 42)))
        d = np.abs(got - exp)
        assert d.mean() <= 2.5, d.mean()
        assert np.percentile(d, 99) <= 12.0, np.percentile(d, 99)

    def test_one_pixel_upscale_matches_rgb_path(self, rng):
        """The no-resolution-loss packed shape ships even dims one
        pixel under an odd model size (bench: 298² planes → 299²
        program). At near-identity sizes the RGB route passes pixels
        through almost sharp, so the comparison exposes the BARE 2×2
        chroma-subsample cost (a downscale low-passes both routes and
        shrinks it — measured mean 6.5 at identity vs 2.8 at half
        size on full-bandwidth synthetic chroma). Luma must stay
        essentially exact — that's the op's own accuracy; chroma gets
        the format's inherent tolerance."""
        from sparkdl_tpu.image.imageIO import rgbToYuv420
        from sparkdl_tpu.ops import fused_yuv420_resize_normalize
        from sparkdl_tpu.utils.synth import textured_image
        rgb = np.stack([textured_image(rng, 28, 28) for _ in range(2)])
        packed = np.stack([rgbToYuv420(im) for im in rgb])
        got = np.asarray(fused_yuv420_resize_normalize(
            packed, (28, 28), (29, 29)))
        exp = np.asarray(fused_resize_normalize(rgb, (29, 29)))
        wy = np.array([0.299, 0.587, 0.114])
        luma_d = np.abs((got * wy).sum(-1) - (exp * wy).sum(-1))
        assert luma_d.mean() <= 0.5, luma_d.mean()
        d = np.abs(got - exp)
        assert d.mean() <= 6.0, d.mean()

    def test_scale_offset_dtype(self):
        from sparkdl_tpu.image.imageIO import rgbToYuv420
        from sparkdl_tpu.ops import fused_yuv420_resize_normalize
        rgb = np.full((1, 8, 8, 3), 255, np.uint8)
        packed = np.stack([rgbToYuv420(im) for im in rgb])
        out = np.asarray(fused_yuv420_resize_normalize(
            packed, (8, 8), (8, 8), scale=1 / 127.5, offset=-1.0,
            dtype=np.float32))
        assert out.dtype == np.float32
        np.testing.assert_allclose(out, 1.0, atol=0.03)

    def test_validation(self):
        from sparkdl_tpu.ops import fused_yuv420_resize_normalize
        with pytest.raises(ValueError, match="even"):
            fused_yuv420_resize_normalize(
                np.zeros((1, 10), np.uint8), (3, 3), (4, 4))
        with pytest.raises(ValueError, match="expected"):
            fused_yuv420_resize_normalize(
                np.zeros((1, 10), np.uint8), (4, 4), (4, 4))

    def test_nonpositive_dims_rejected(self):
        """(0, 0) is even — zero/negative dims must raise everywhere
        instead of silently producing empty tensors (review r5 probe)."""
        from sparkdl_tpu.image import imageIO
        from sparkdl_tpu.ops import fused_yuv420_resize_normalize
        from sparkdl_tpu.ops.infeed import bilinear_weight_matrix

        with pytest.raises(ValueError, match="positive"):
            bilinear_weight_matrix(0, 8)
        with pytest.raises(ValueError, match="positive"):
            bilinear_weight_matrix(8, 0)
        with pytest.raises(ValueError, match="positive"):
            fused_yuv420_resize_normalize(
                np.zeros((1, 0), np.uint8), (0, 0), (4, 4))
        with pytest.raises(ValueError, match="positive"):
            imageIO.readImagesPacked("/nonexistent", (0, 0))
        with pytest.raises(ValueError, match="positive"):
            imageIO.readImagesPacked("/nonexistent", (-4, 8))
        with pytest.raises(ValueError, match="positive"):
            imageIO.createResizeImageUDF((0, 8))
        with pytest.raises(ValueError, match="positive"):
            imageIO.rgbToYuv420(np.zeros((0, 0, 3), np.uint8))
        from sparkdl_tpu import native
        with pytest.raises(ValueError, match="positive"):
            native.yuv420_packed_size(0, 0)

    def test_jittable_and_device_resize_model(self):
        """deviceResizeModel(packedFormat='yuv420') embeds the op in one
        jitted program and reproduces the RGB-input model's output on a
        lossless (constant-chroma) batch."""
        import jax
        import jax.numpy as jnp

        from sparkdl_tpu.graph.function import ModelFunction
        from sparkdl_tpu.image.imageIO import rgbToYuv420
        from sparkdl_tpu.transformers.utils import deviceResizeModel

        def apply_fn(params, inputs):
            x = inputs["image"].astype(jnp.float32)
            return {"out": x.mean(axis=(1, 2))}

        mf = ModelFunction(
            apply_fn, params={},
            input_signature={"image": ((16, 16, 3), np.uint8)},
            output_names=["out"])
        wrapped = deviceResizeModel(mf, (24, 24), packedFormat="yuv420")
        assert wrapped.input_signature["image"] == \
            ((24 * 24 * 3 // 2,), np.uint8)
        colors = np.array([[130, 60, 200]], np.uint8)
        rgb = np.broadcast_to(colors[:, None, None, :],
                              (1, 24, 24, 3)).copy()
        packed = np.stack([rgbToYuv420(im) for im in rgb])
        out = jax.jit(wrapped.apply_fn)(wrapped.params,
                                        {"image": packed})
        np.testing.assert_allclose(np.asarray(out["out"])[0],
                                   colors[0].astype(np.float32),
                                   atol=2.5)
