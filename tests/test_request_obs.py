"""Per-request tracing, tail attribution, and SLO tracking
(docs/OBSERVABILITY.md "Per-request timelines" / "SLO tracking",
docs/SERVING.md runbook).

The contract under test:

* disarmed, the per-request path stays in the tracer's shared no-op
  regime (<10µs/submit-hook, alongside the span bound);
* armed, every submit mints a unique request_id; a concurrent
  saturation soak (with split requests) yields records and exemplars
  whose phase durations SUM to the end-to-end latency within clock
  tolerance, and every exemplar's request_id resolves to spans (and a
  connected flow) in the exported trace;
* ``report --tails`` attributes ≥95% of the measured p99 across the
  named phases, and ignores event types it has never seen
  (forward-compat);
* the RequestLog ring and exemplar retention are hard-bounded with
  drop counters;
* failed/expired requests land in the SLO availability stream and
  NEVER in the latency reservoir — each population is correct;
* pickle follows the StageMetrics drop-and-recreate discipline.
"""

import json
import threading
import time

import numpy as np
import pytest

from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.obs import default_registry, request_log, tracer
from sparkdl_tpu.obs.registry import EXEMPLAR_CAPACITY, Reservoir
from sparkdl_tpu.obs.report import (
    main as report_main,
    summarize,
    summarize_tails,
    tails_summary,
)
from sparkdl_tpu.obs.request_log import PHASES, RequestLog
from sparkdl_tpu.obs.slo import SLObjective, SLOTracker, slo_tracker
from sparkdl_tpu.serve import (
    DeadlineExceeded,
    ModelServer,
    ServeConfig,
)


def _double_fn():
    return ModelFunction.fromSingle(lambda x: x * 2.0, None,
                                    input_shape=(3,))


def _slow_host_fn(delay_s):
    def apply(params, inputs):
        time.sleep(delay_s)
        return {"y": np.asarray(inputs["x"], np.float32) + 1.0}
    return ModelFunction(apply, None, {"x": ((3,), np.float32)},
                         output_names=["y"], backend="host")


@pytest.fixture()
def armed(monkeypatch):
    """Tracer + request log armed via the env (as production would),
    everything cleared before/after so tests don't see each other."""
    monkeypatch.setenv("SPARKDL_TPU_TRACE", "1")
    t = tracer()
    t.clear()
    rlog = request_log()
    rlog.clear()
    slo_tracker().clear()
    yield t, rlog
    t.clear()
    rlog.clear()
    slo_tracker().clear()


# ---------------------------------------------------------------------------
# the disarmed no-op regime


class TestDisarmedRegime:
    def test_disarmed_timeline_is_none_and_cheap(self, monkeypatch):
        """The per-request submit hook disarmed: one armed-check
        returning None — pinned <10µs alongside the tracer's span
        bound (min over repeats; noise only adds time)."""
        monkeypatch.delenv("SPARKDL_TPU_TRACE", raising=False)
        monkeypatch.delenv("SPARKDL_TPU_REQUEST_LOG", raising=False)
        rlog = RequestLog(capacity=16)
        assert rlog.timeline("m", 4, time.perf_counter()) is None
        n = 20_000
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(n):
                rlog.timeline("m", 4, 0.0)
            best = min(best, (time.perf_counter() - t0) / n)
        assert best < 10e-6, f"disarmed timeline costs {best * 1e6:.2f} µs"

    def test_disarmed_submit_records_nothing(self, monkeypatch):
        monkeypatch.delenv("SPARKDL_TPU_TRACE", raising=False)
        monkeypatch.delenv("SPARKDL_TPU_REQUEST_LOG", raising=False)
        rlog = request_log()
        rlog.clear()
        before = rlog.appended
        with ModelServer(ServeConfig(max_wait_s=0.0)) as server:
            server.register("m", _double_fn(), batch_size=4)
            x = np.zeros((4, 3), np.float32)
            server.submit({"input": x}).result(timeout=30)
        assert rlog.appended == before
        assert rlog.records() == []

    def test_request_log_arms_alone_and_with_tracer(self, monkeypatch):
        monkeypatch.delenv("SPARKDL_TPU_TRACE", raising=False)
        monkeypatch.delenv("SPARKDL_TPU_REQUEST_LOG", raising=False)
        rlog = RequestLog(capacity=4)
        assert not rlog.armed
        monkeypatch.setenv("SPARKDL_TPU_REQUEST_LOG", "1")
        assert rlog.armed                  # its own env switch
        monkeypatch.delenv("SPARKDL_TPU_REQUEST_LOG")
        monkeypatch.setenv("SPARKDL_TPU_TRACE", "1")
        assert rlog.armed                  # follows the armed tracer
        monkeypatch.delenv("SPARKDL_TPU_TRACE")
        rlog.arm()
        assert rlog.armed                  # override wins
        rlog.disarm()
        monkeypatch.setenv("SPARKDL_TPU_REQUEST_LOG", "1")
        assert not rlog.armed              # pinned off beats the env


# ---------------------------------------------------------------------------
# the armed soak: exemplar fidelity + trace resolution


class TestArmedSoak:
    def test_saturation_soak_exemplars_sum_and_resolve(self, armed,
                                                       tmp_path):
        """Concurrent saturation soak with split requests: every
        record's (and exemplar's) phase durations sum to its
        end-to-end latency within clock tolerance, request ids are
        unique, and every exemplar's request_id resolves to spans +
        one connected flow in the exported trace."""
        t, rlog = armed
        server = ModelServer(ServeConfig(max_wait_s=0.005,
                                         max_queue_rows=4096))
        server.register("m", _double_fn(), batch_size=8)
        server.warmup()

        futures, lock = [], threading.Lock()

        def fire(tid):
            rng = np.random.default_rng(tid)
            for i in range(8):
                # mixed shapes: sub-batch (coalesce path) and
                # oversized (split-and-reassemble path)
                rows = 20 if (tid + i) % 4 == 0 else 3
                x = rng.normal(size=(rows, 3)).astype(np.float32)
                f = server.submit({"input": x})
                with lock:
                    futures.append((f, x))

        workers = [threading.Thread(target=fire, args=(k,))
                   for k in range(4)]
        for w in workers:
            w.start()
        for w in workers:
            w.join()
        for f, x in futures:
            np.testing.assert_allclose(
                f.result(timeout=30)["output"], x * 2, rtol=1e-6)
        server.close()

        recs = rlog.records()
        assert len(recs) == 32
        rids = [r.request_id for r in recs]
        assert len(set(rids)) == len(rids)          # unique ids
        assert any(r.batches > 1 for r in recs)     # splits happened
        for r in recs:
            assert r.status == "ok"
            assert set(r.phases) == set(PHASES)
            attributed = sum(r.phases.values())
            # phase sums match end-to-end within clock tolerance: the
            # coalesce remainder construction makes this exact up to
            # float rounding
            assert attributed == pytest.approx(r.total_s, abs=1e-6)

        exemplars = server.metrics.latency_exemplars()
        assert exemplars, "saturated soak must retain exemplars"
        by_rid = {r.request_id: r for r in recs}
        for ex in exemplars:
            assert set(ex["phases"]) == set(PHASES)
            assert sum(ex["phases"].values()) == pytest.approx(
                ex["value"], abs=1e-6)
            assert ex["request_id"] in by_rid

        # every exemplar resolves into the exported trace: a request
        # span carrying the id, and a connected flow (start on the
        # enqueue span, ≥1 step on dispatch slices, an end)
        path = tmp_path / "trace.json"
        t.export(str(path))
        events = json.loads(path.read_text())
        req_spans = {e["args"]["request_id"]: e for e in events
                     if e.get("ph") == "X"
                     and e.get("name") == "request"}
        flows = [e for e in events if e.get("cat") == "request_flow"]
        for ex in exemplars:
            rid = ex["request_id"]
            assert rid in req_spans
            span_phases = req_spans[rid]["args"]["phases_s"]
            assert set(span_phases) == set(PHASES)
            kinds = {e["ph"] for e in flows if e["id"] == rid}
            assert kinds == {"s", "t", "f"}, (rid, kinds)
        # a split request's flow steps through EVERY micro-batch
        split = next(r for r in recs if r.batches > 1)
        steps = [e for e in flows
                 if e["id"] == split.request_id and e["ph"] == "t"]
        assert len(steps) == split.batches

    def test_flow_attrs_consumed_not_leaked(self, armed, tmp_path):
        """The reserved flow_* attrs drive flow-event emission and
        must NOT appear in the exported slice args (request_id, a
        visible arg, stays)."""
        t, rlog = armed
        with ModelServer(ServeConfig(max_wait_s=0.0)) as server:
            server.register("m", _double_fn(), batch_size=4)
            x = np.zeros((4, 3), np.float32)
            server.submit({"input": x}).result(timeout=30)
        events = t.trace_events()
        for e in events:
            args = e.get("args") or {}
            assert "flow_id" not in args and "flow_ph" not in args \
                and "flow_ids" not in args, e
        enq = next(e for e in events
                   if e.get("ph") == "X" and e.get("name") == "enqueue")
        assert enq["args"]["request_id"].startswith("r")

    def test_no_dangling_flow_end_for_never_enqueued_requests(
            self, armed):
        """Dead-at-submit / precheck-rejected requests never opened
        the enqueue span (the flow's 's' start): their records must
        not emit a flow END — every 'f' in an export needs a matching
        's' or Perfetto renders dangling arrows."""
        from sparkdl_tpu.serve import ServerOverloaded

        t, rlog = armed
        server = ModelServer(ServeConfig(max_wait_s=0.0,
                                         max_queue_rows=8))
        server.register("m", _double_fn(), batch_size=4)
        with pytest.raises(DeadlineExceeded):
            server.submit({"input": np.zeros((2, 3), np.float32)},
                          deadline=-1.0).result(timeout=1)
        with pytest.raises(ServerOverloaded):
            server.submit({"input": np.zeros((64, 3), np.float32)})
        server.close()
        assert len(rlog.records()) == 2     # both outcomes recorded
        events = t.trace_events()
        ends = {e["id"] for e in events
                if e.get("cat") == "request_flow" and e["ph"] == "f"}
        starts = {e["id"] for e in events
                  if e.get("cat") == "request_flow" and e["ph"] == "s"}
        assert ends <= starts, (ends, starts)

    def test_device_phase_detail_from_chunk_phases(self, armed):
        """jax-backed sessions subdivide the device phase through the
        runner's ChunkPhases accumulator (runtime/runner.py): the
        record carries placement/enqueue/drain detail whose parts
        don't exceed the device phase they subdivide."""
        _t, rlog = armed
        with ModelServer(ServeConfig(max_wait_s=0.0)) as server:
            server.register("m", _double_fn(), batch_size=4)
            x = np.arange(12, dtype=np.float32).reshape(4, 3)
            server.submit({"input": x}).result(timeout=30)
        (rec,) = rlog.records()
        assert rec.device_detail is not None
        assert rec.device_detail["enqueue_s"] >= 0.0
        assert rec.device_detail["drain_s"] >= 0.0
        detail_sum = sum(rec.device_detail.values())
        assert detail_sum <= rec.phases["device"] + 1e-3


# ---------------------------------------------------------------------------
# report --tails


class TestReportTails:
    def _request_event(self, rid, dur_us, phases_us, status="ok",
                       batches=1):
        return {"name": "request", "cat": "request", "ph": "X",
                "ts": 0.0, "dur": dur_us, "pid": 9, "tid": 1,
                "args": {"request_id": rid, "status": status,
                         "rows": 4, "batches": batches,
                         "phases_s": {k: v / 1e6
                                      for k, v in phases_us.items()}}}

    def test_tails_summary_attributes_p99(self):
        events = [self._request_event(
            f"r-{i}", 1000.0 + i,
            {"queue": 300.0, "coalesce": 400.0 + i, "staging": 50.0,
             "device": 200.0, "reassembly": 50.0})
            for i in range(10)]
        s = tails_summary(events)
        assert s["requests"] == 10
        assert s["p99_request_id"] == "r-9"
        assert s["attributed_pct"] == pytest.approx(100.0, abs=0.5)
        assert s["attributed_pct"] >= 95.0
        text = summarize_tails(events)
        assert "p99 attribution" in text and "coalesce" in text

    def test_failed_requests_excluded_from_latency_population(self):
        events = [self._request_event("ok-1", 1000.0,
                                      {"queue": 1000.0})]
        dead = self._request_event(
            "dead-1", 9_000_000.0, {"queue": 9_000_000.0},
            status="deadline_exceeded")
        events.append(dead)
        s = tails_summary(events)
        assert s["requests"] == 1
        assert s["failed_excluded"] == 1
        assert s["p99_request_id"] == "ok-1"
        # an all-failures trace has NO latency population: the summary
        # must say so, not quietly compute percentiles from the
        # excluded population
        s = tails_summary([dead])
        assert s["requests"] == 0 and s["failed_excluded"] == 1
        assert s["p99_ms"] is None and s["p99_request_id"] is None
        assert "no successes" in summarize_tails([dead])

    def test_report_ignores_unknown_event_types(self):
        """Forward-compat: flow events (s/t/f), counter events, and
        ph values this report has never heard of must be skipped by
        BOTH modes, never crashed on."""
        events = [
            self._request_event("r-1", 1000.0, {"queue": 1000.0}),
            {"name": "request", "ph": "s", "id": "r-1", "ts": 0.0,
             "pid": 9, "tid": 1, "cat": "request_flow"},
            {"name": "request", "ph": "f", "id": "r-1", "ts": 5.0,
             "pid": 9, "tid": 1, "cat": "request_flow", "bp": "e"},
            {"name": "ctr", "ph": "C", "ts": 0.0, "pid": 9, "tid": 1,
             "args": {"v": 1}},
            {"name": "mystery", "ph": "Q"},         # unknown type
            {"ph": "X"},                            # degenerate span
        ]
        assert "request" in summarize(events)       # no crash
        s = tails_summary(events)
        assert s is not None and s["requests"] == 1
        assert "p99 attribution" in summarize_tails(events)

    def test_no_request_spans_degrades_with_guidance(self):
        assert tails_summary([{"name": "x", "ph": "X", "ts": 0.0,
                               "dur": 1.0, "pid": 1, "tid": 1}]) is None
        assert "no request spans" in summarize_tails([])

    def test_cli_smoke(self, armed, tmp_path, capsys):
        t, _rlog = armed
        with ModelServer(ServeConfig(max_wait_s=0.0)) as server:
            server.register("m", _double_fn(), batch_size=4)
            x = np.zeros((8, 3), np.float32)
            server.submit({"input": x}).result(timeout=30)
        path = tmp_path / "trace.json"
        t.export(str(path))
        assert report_main(["report", "--tails", str(path)]) == 0
        out = capsys.readouterr().out
        assert "request tails" in out
        assert "attributed:" in out

    def test_cli_usage_error(self, capsys):
        assert report_main(["report", "--tails"]) == 2


# ---------------------------------------------------------------------------
# cardinality bounds: the ring + exemplar retention


class TestBoundedRetention:
    def test_request_log_ring_bounds_and_counts_drops(self, armed):
        _t, _ = armed
        reg = default_registry()
        before = reg.counter("obs.request_log.dropped").value
        small = RequestLog(capacity=4)
        for i in range(10):
            tl = small.timeline("m", 1, time.perf_counter())
            small.record(tl.finish(time.perf_counter(), "ok"),
                         submitted=tl.submitted)
        assert len(small.records()) == 4
        assert small.dropped == 6
        assert reg.counter("obs.request_log.dropped").value \
            == before + 6
        st = small.status()
        assert st["retained"] == 4 and st["dropped"] == 6

    def test_capacity_env_typo_degrades(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_TPU_REQUEST_LOG_CAPACITY", "lots")
        rlog = RequestLog()
        assert rlog.capacity == 1024      # default, not a crash

    def test_exemplar_retention_bounded_keeps_worst(self):
        res = Reservoir("t.lat", capacity=1024)
        for i in range(100):
            res.observe(float(i), exemplar={"request_id": f"r-{i}"})
        ex = res.exemplars()
        assert len(ex) == EXEMPLAR_CAPACITY
        # the K LARGEST survive, largest first
        assert [e["value"] for e in ex] == \
            [float(v) for v in range(99, 99 - EXEMPLAR_CAPACITY, -1)]
        assert res.exemplars_dropped == 100 - EXEMPLAR_CAPACITY

    def test_exemplars_age_out_of_the_window(self):
        res = Reservoir("t.lat", capacity=8)
        res.observe(1e9, exemplar={"request_id": "ancient"})
        for i in range(20):                 # push it out of the window
            res.observe(1.0 + i * 1e-3,
                        exemplar={"request_id": f"r-{i}"})
        rids = {e["request_id"] for e in res.exemplars()}
        assert "ancient" not in rids        # a stale worst case must
        # not shadow the current tail

    def test_exemplars_age_out_without_new_exemplar_offers(self):
        """Plain observe() calls advance the window too: once a
        specimen's observation leaves it, the readout must stop
        naming it — even if no exemplar-carrying observe ever runs
        again (e.g. the request log was disarmed)."""
        res = Reservoir("t.lat", capacity=8)
        res.observe(1e9, exemplar={"request_id": "ancient"})
        dropped_before = res.exemplars_dropped
        for i in range(20):
            res.observe(1.0 + i * 1e-3)     # no exemplars offered
        assert res.exemplars() == []
        assert res.exemplars_dropped == dropped_before + 1

    def test_h6_meta_no_per_request_metric_names(self):
        """The registry never grows request-keyed metric names under
        load — snapshot keys stay a bounded vocabulary."""
        reg = default_registry()
        for key in reg.snapshot():
            assert "r-" not in key and "request_id" not in key, key


# ---------------------------------------------------------------------------
# SLO tracking: populations + burn rate


class TestSLOTracker:
    def _tracker(self, window_s=60.0):
        return SLOTracker([
            SLObjective(name="latency", kind="latency", target=0.9,
                        threshold_s=0.1, window_s=window_s),
            SLObjective(name="availability", kind="availability",
                        target=0.9, window_s=window_s),
        ])

    def test_burn_rate_math(self):
        st = self._tracker()
        for _ in range(8):
            st.record(latency_s=0.01, ok=True)
        ob = st.status()["objectives"]
        assert ob["availability"]["burn_rate"] == 0.0
        assert ob["availability"]["budget_remaining"] == 1.0
        st.record(ok=False)                  # 1 bad of 9 ≈ 11.1% bad
        st.record(ok=False)                  # 2 bad of 10 = 20% bad
        ob = st.status()["objectives"]
        # 20% bad / 10% budget = burn 2.0 — burning twice the
        # sustainable rate; remaining clamps at -1
        assert ob["availability"]["burn_rate"] == pytest.approx(2.0)
        assert ob["availability"]["budget_remaining"] == -1.0
        assert not ob["availability"]["healthy"]

    def test_latency_objective_counts_slow_and_failed_as_bad(self):
        st = self._tracker()
        st.record(latency_s=0.01, ok=True)   # good
        st.record(latency_s=0.5, ok=True)    # slow: bad for latency
        st.record(ok=False)                  # failed: bad for both
        ob = st.status()["objectives"]
        assert ob["latency"]["bad"] == 2
        assert ob["availability"]["bad"] == 1

    def test_window_rolls_off(self):
        st = self._tracker(window_s=0.05)
        st.record(ok=False)
        time.sleep(0.08)
        st.record(latency_s=0.01, ok=True)
        ob = st.status()["objectives"]
        assert ob["availability"]["events"] == 1     # the miss aged out
        assert ob["availability"]["burn_rate"] == 0.0

    def test_env_typo_degrades_to_defaults(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_TPU_SLO_LATENCY_TARGET", "huge")
        monkeypatch.setenv("SPARKDL_TPU_SLO_WINDOW_S", "-3")
        st = SLOTracker()
        (lat, avail) = st.objectives
        assert lat.target == 0.99 and lat.window_s == 300.0
        assert avail.kind == "availability"

    def test_objective_validation(self):
        with pytest.raises(ValueError, match="kind"):
            SLObjective(name="x", kind="speed", target=0.5)
        with pytest.raises(ValueError, match="fraction"):
            SLObjective(name="x", kind="availability", target=1.5)
        with pytest.raises(ValueError, match="threshold"):
            SLObjective(name="x", kind="latency", target=0.9)

    def test_publish_gauges(self):
        st = self._tracker()
        st.record(ok=False)
        reg = default_registry()
        st.publish(reg)
        snap = reg.snapshot()
        assert snap["slo.availability.burn_rate"] > 0.0
        assert "slo.availability.budget_remaining" in snap
        assert "slo.latency.burn_rate" in snap

    def test_publish_due_rate_limits_but_force_wins(self):
        """The dispatcher-loop publish is rate-limited (status() scans
        the whole outcome window — not a per-micro-batch cost); the
        lifecycle edges force through."""
        st = self._tracker()
        st.record(ok=False)
        reg = default_registry()
        assert st.publish_due(reg) is True     # first: due
        assert st.publish_due(reg) is False    # immediately after: not
        assert st.publish_due(reg, force=True) is True
        st.clear()
        assert st.publish_due(reg) is True     # clear resets the clock


class TestSeparatePopulations:
    """THE fix pinned: deadline-expired / failed requests are recorded
    in the availability stream, and the latency reservoir's percentile
    population holds ONLY successes."""

    def test_deadline_misses_never_enter_latency_reservoir(self):
        slo_tracker().clear()
        server = ModelServer(ServeConfig(max_wait_s=0.0))
        server.register("m", _slow_host_fn(0.05), batch_size=4)
        x = np.zeros((2, 3), np.float32)
        # the burst: the first dispatch holds the lane ~50 ms, so
        # these 1 ms deadlines expire queued
        futs = [server.submit({"x": x}, deadline=0.001)
                for _ in range(8)]
        missed = 0
        for f in futs:
            try:
                f.result(timeout=30)
            except DeadlineExceeded:
                missed += 1
        assert missed >= 1
        ok = [server.submit({"x": x}) for _ in range(3)]
        for f in ok:
            f.result(timeout=30)
        server.close()
        m = server.metrics
        assert m.deadline_misses == missed
        # the latency population: exactly the successes — a polluted
        # population would also show p50 far below the 50 ms dispatch
        # floor
        successes = (8 - missed) + 3
        assert m._latency.count == successes
        assert m.latency_seconds(0.5) >= 0.04
        # the availability stream saw every outcome
        avail = slo_tracker().status()["objectives"]["availability"]
        assert avail["events"] == 11
        assert avail["bad"] == missed
        assert avail["burn_rate"] > 0.0
        slo_tracker().clear()

    def test_dispatch_failures_count_availability_and_failures(self):
        slo_tracker().clear()

        def broken(params, inputs):
            raise RuntimeError("boom")

        mf = ModelFunction(broken, None, {"x": ((3,), np.float32)},
                           output_names=["y"], backend="host")
        server = ModelServer(ServeConfig(max_wait_s=0.0))
        server.register("m", mf, batch_size=4)
        fut = server.submit({"x": np.zeros((2, 3), np.float32)})
        with pytest.raises(RuntimeError, match="boom"):
            fut.result(timeout=30)
        server.close()
        m = server.metrics
        assert m.failures == 1
        assert m._latency.count == 0
        avail = slo_tracker().status()["objectives"]["availability"]
        assert avail["bad"] >= 1
        slo_tracker().clear()

    def test_zero_row_fast_path_is_an_outcome_too(self):
        """The inline N=0 path must not be a metrics hole: a broken
        runner hammered with empty probes shows up as failures +
        availability burn, and successful empties count as good."""
        slo_tracker().clear()

        def broken(params, inputs):
            raise RuntimeError("empty-probe boom")

        mf = ModelFunction(broken, None, {"x": ((3,), np.float32)},
                           output_names=["y"], backend="host")
        server = ModelServer(ServeConfig(max_wait_s=0.0))
        server.register("m", mf, batch_size=4)
        with pytest.raises(ValueError, match="empty"):
            # the N=0 probe-batch contract wraps the runner error
            server.submit({"x": np.zeros((0, 3), np.float32)})
        m = server.metrics
        assert m.failures == 1
        avail = slo_tracker().status()["objectives"]["availability"]
        assert avail["bad"] == 1
        server.close()
        slo_tracker().clear()
        with ModelServer(ServeConfig(max_wait_s=0.0)) as ok_server:
            ok_server.register("m", _double_fn(), batch_size=4)
            out = ok_server.submit(
                {"input": np.zeros((0, 3), np.float32)}).result(1)
            assert out["output"].shape == (0, 3)
        avail = slo_tracker().status()["objectives"]["availability"]
        assert avail["events"] >= 1 and avail["bad"] == 0
        slo_tracker().clear()

    def test_failed_requests_close_their_timelines(self, armed):
        _t, rlog = armed
        server = ModelServer(ServeConfig(max_wait_s=0.0))
        server.register("m", _slow_host_fn(0.05), batch_size=4)
        x = np.zeros((2, 3), np.float32)
        futs = [server.submit({"x": x}, deadline=0.001)
                for _ in range(6)]
        outcomes = []
        for f in futs:
            try:
                f.result(timeout=30)
                outcomes.append("ok")
            except DeadlineExceeded:
                outcomes.append("deadline_exceeded")
        server.close()
        recs = rlog.records()
        assert len(recs) == 6
        assert sorted(r.status for r in recs) == sorted(outcomes)
        for r in recs:
            assert sum(r.phases.values()) == pytest.approx(
                r.total_s, abs=1e-6)


# ---------------------------------------------------------------------------
# surfaces: /statusz + flight bundle


class TestSurfaces:
    def test_statusz_carries_slo_request_log_and_exemplars(self,
                                                           armed):
        import urllib.request

        _t, _rlog = armed
        slo_tracker().clear()
        server = ModelServer(ServeConfig(max_wait_s=0.0))
        server.register("m", _double_fn(), batch_size=4)
        tel = server.serve_telemetry()
        try:
            x = np.zeros((4, 3), np.float32)
            server.submit({"input": x}).result(timeout=30)
            with urllib.request.urlopen(tel.url("/statusz"),
                                        timeout=5) as r:
                st = json.load(r)
            assert "latency" in st["slo"]["objectives"]
            assert st["request_log"]["capacity"] > 0
            (ex,) = st["servers"]
            assert ex["latency_exemplars"], ex
            assert ex["latency_exemplars"][0]["request_id"]
            with urllib.request.urlopen(tel.url("/metricsz"),
                                        timeout=5) as r:
                body = r.read().decode()
            assert "sparkdl_slo_latency_burn_rate" in body
            assert "sparkdl_slo_availability_budget_remaining" in body
        finally:
            server.close()
            slo_tracker().clear()

    def test_metricsz_refreshes_slo_at_scrape_time(self):
        """The serve loop's gauge publish is rate-limited; the scrape
        must never see that throttle — /metricsz re-publishes the SLO
        verdicts at request time, so an outcome recorded with NO
        publish at all still reads back fresh."""
        import re
        import urllib.request

        from sparkdl_tpu.obs.export import TelemetryServer

        slo_tracker().clear()
        slo_tracker().record(ok=False)       # never published
        with TelemetryServer() as tel:
            with urllib.request.urlopen(tel.url("/metricsz"),
                                        timeout=5) as r:
                body = r.read().decode()
        burn = float(re.search(
            r"^sparkdl_slo_availability_burn_rate ([-+0-9.e]+)",
            body, re.M).group(1))
        assert burn > 0.0
        slo_tracker().clear()

    def test_flight_bundle_carries_slo_and_requests(self, armed,
                                                    tmp_path,
                                                    monkeypatch):
        from sparkdl_tpu.obs import flight

        _t, rlog = armed
        monkeypatch.setenv("SPARKDL_TPU_FLIGHT_DIR", str(tmp_path))
        with ModelServer(ServeConfig(max_wait_s=0.0)) as server:
            server.register("m", _double_fn(), batch_size=4)
            x = np.zeros((4, 3), np.float32)
            server.submit({"input": x}).result(timeout=30)
            path = flight.recorder().dump(reason="test")
        bundle = json.loads(open(path).read())
        assert "objectives" in bundle["slo"]
        reqs = bundle["requests"]
        assert reqs["retained"] >= 1
        assert reqs["recent"][0]["request_id"]
        assert set(reqs["recent"][0]["phases"]) == set(PHASES)


# ---------------------------------------------------------------------------
# pickle discipline


class TestPickle:
    def test_request_log_roundtrip(self):
        cloudpickle = pytest.importorskip("cloudpickle")
        import pickle

        rlog = RequestLog(capacity=7)
        rlog.arm()
        tl = rlog.timeline("m", 2, time.perf_counter())
        rlog.record(tl.finish(time.perf_counter(), "ok"),
                    submitted=tl.submitted)
        clone = pickle.loads(cloudpickle.dumps(rlog))
        assert clone.capacity == 7
        assert clone.armed                  # armed-ness travels
        assert clone.records() == []        # records stay local
        assert clone.dropped == 0
        tl2 = clone.timeline("m", 2, time.perf_counter())
        clone.record(tl2.finish(time.perf_counter(), "ok"))
        assert len(clone.records()) == 1    # usable on arrival

    def test_slo_tracker_roundtrip(self):
        cloudpickle = pytest.importorskip("cloudpickle")
        import pickle

        st = SLOTracker([SLObjective(
            name="availability", kind="availability", target=0.5,
            window_s=9.0)])
        st.record(ok=False)
        clone = pickle.loads(cloudpickle.dumps(st))
        (obj,) = clone.objectives           # config travels
        assert obj.target == 0.5 and obj.window_s == 9.0
        # events are per-process perf_counter instants: dropped
        assert clone.status()["objectives"]["availability"][
            "events"] == 0
        clone.record(ok=True)               # usable on arrival
        assert clone.status()["objectives"]["availability"][
            "events"] == 1
