"""Contract test for the Spark binding's pure half (VERDICT r1 missing #4).

pyspark is absent in this environment, but ``plan_to_map_in_arrow`` is
pure: it compiles a stage plan into the exact
``iterator[RecordBatch] → iterator[RecordBatch]`` function Spark's
``DataFrame.mapInArrow`` calls on each executor. These tests drive that
function with a hand-built iterator — the executor's calling
convention — over a real decode→pack→apply plan and assert row-level
parity with ``LocalEngine`` output (reference role: the whole upstream
repo WAS this binding; SURVEY §7 "the seam must be clean enough that the
Spark binding is mechanical").
"""

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.data.frame import DataFrame, Stage
from sparkdl_tpu.data.spark_binding import SparkEngine, plan_to_map_in_arrow
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.transformers.image_transform import ImageTransformer


@pytest.fixture(scope="module")
def featurized(image_dir):
    """An images frame with the full production plan: decode (host) →
    pack/resize (host) → jitted model apply (device)."""
    from sparkdl_tpu.models.zoo import getModelFunction

    df = imageIO.readImages(image_dir, numPartitions=3,
                            dropImageFailures=True)
    mf = getModelFunction("TestNet", featurize=True)
    out = ImageTransformer(
        inputCol="image", outputCol="features",
        modelFunction=mf).transform(df)
    return out


def _executor_outputs(df: DataFrame) -> list:
    """Run df's plan the way a Spark executor would: one mapInArrow
    function instance per task, fed an iterator of the task's batches."""
    fn = plan_to_map_in_arrow(df._plan)
    outs = []
    for source in df._sources:
        outs.extend(fn(iter([source.load()])))
    return outs


def test_binding_matches_local_engine(featurized):
    expected = featurized.collect()
    got = pa.Table.from_batches(_executor_outputs(featurized))
    assert got.schema.equals(expected.schema)
    a = np.stack(got.column("features").to_pylist())
    b = np.stack(expected.column("features").to_pylist())
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)
    assert got.column("filePath").to_pylist() == \
        expected.column("filePath").to_pylist()


def test_binding_streams_multiple_batches_per_task(featurized):
    """Spark hands mapInArrow MANY batches per task; the compiled fn must
    apply the plan per batch and preserve order within the iterator."""
    fn = plan_to_map_in_arrow(featurized._plan)
    batches = [s.load() for s in featurized._sources]
    outs = list(fn(iter(batches)))
    assert len(outs) == len(batches)
    expected = featurized.collect()
    got = pa.Table.from_batches(outs)
    assert got.column("filePath").to_pylist() == \
        expected.column("filePath").to_pylist()


def test_binding_honors_with_index_stages():
    """with_index stages receive the Spark partition id (0 without a
    TaskContext — exactly what a driver-local plan sees)."""
    batch = pa.RecordBatch.from_pylist([{"x": 1}, {"x": 2}])

    seen = []

    def tag(b, index):
        seen.append(index)
        return b

    fn = plan_to_map_in_arrow([Stage(tag, with_index=True, name="tag")])
    list(fn(iter([batch])))
    assert seen == [0]


def test_spark_engine_requires_pyspark():
    with pytest.raises(RuntimeError, match="pyspark"):
        SparkEngine()


class _FakeRDD:
    """Mimics the exact slice of the RDD API SparkEngine.execute uses:
    parallelize(seq, n).map(fn).collect(). ``map`` runs the task
    function on every element — like an executor would, outside the
    driver's engine — and round-trips each task through pickle the way
    Spark's closure serializer does."""

    def __init__(self, items):
        self.items = list(items)

    def map(self, fn):
        # Spark ships task closures with cloudpickle (stdlib pickle
        # cannot serialize the local closures Sources use) — round-trip
        # through it so un-shippable closures fail here, not on a real
        # cluster
        import cloudpickle

        out = []
        for item in self.items:
            task_fn, task_item = cloudpickle.loads(
                cloudpickle.dumps((fn, item)))
            out.append((task_fn, task_item))
        return _FakeRDD(out)

    def collect(self):
        return [f(i) for f, i in self.items]


class _FakeContext:
    def parallelize(self, seq, n):
        assert n == len(list(seq))  # one partition per task, like execute()
        return _FakeRDD(seq)


class _FakeSparkSession:
    sparkContext = _FakeContext()


def test_spark_engine_execute_contract(featurized):
    """SparkEngine.execute end-to-end against a duck-typed session:
    partition loads ship as tasks, results come back as Arrow IPC bytes,
    and the rows match LocalEngine exactly (same plan, same order)."""
    engine = SparkEngine(spark=_FakeSparkSession())
    got = pa.Table.from_batches(
        list(engine.execute(featurized._sources, featurized._plan)))
    expected = featurized.collect()
    assert got.schema.equals(expected.schema)
    assert got.column("filePath").to_pylist() == \
        expected.column("filePath").to_pylist()
    a = np.stack(got.column("features").to_pylist())
    b = np.stack(expected.column("features").to_pylist())
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


def test_spark_engine_union_of_different_plans():
    """A different-plan union's deferred sides must survive the Spark
    task boundary (regression: the deferred loader reached into
    LocalEngine privates and captured an unpicklable lock)."""
    a = DataFrame.from_table(pa.table({"x": np.arange(6.0)}), 2) \
        .filter_rows(np.arange(6.0) >= 1)  # non-preserving plan
    b = DataFrame.from_table(pa.table({"x": np.arange(6.0, 10.0)}), 2)
    u = a.union(b)
    expected = [r["x"] for r in u.collect_rows()]
    assert expected == [1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0, 9.0]

    engine = SparkEngine(spark=_FakeSparkSession())
    got = pa.Table.from_batches(
        list(engine.execute(u._sources, u._plan)))
    assert got.column("x").to_pylist() == expected


def test_spark_engine_with_index_uses_logical_identity():
    """A reordered frame's with_index stages must see each partition's
    pinned LOGICAL index on the Spark engine too, not the task position
    (same contract LocalEngine honors)."""
    base = DataFrame.from_table(
        pa.table({"x": np.arange(40.0)}), 4)
    tagged = base.with_partition_order([3, 1]).map_batches(
        lambda b, i: b.append_column("pid", pa.array([i] * b.num_rows)),
        with_index=True)
    engine = SparkEngine(spark=_FakeSparkSession())
    got = pa.Table.from_batches(
        list(engine.execute(tagged._sources, tagged._plan)))
    assert sorted(set(got.column("pid").to_pylist())) == [1, 3]
    expected = tagged.collect()
    assert got.column("pid").to_pylist() == \
        expected.column("pid").to_pylist()
