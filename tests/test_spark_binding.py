"""Contract test for the Spark binding's pure half (VERDICT r1 missing #4).

pyspark is absent in this environment, but ``plan_to_map_in_arrow`` is
pure: it compiles a stage plan into the exact
``iterator[RecordBatch] → iterator[RecordBatch]`` function Spark's
``DataFrame.mapInArrow`` calls on each executor. These tests drive that
function with a hand-built iterator — the executor's calling
convention — over a real decode→pack→apply plan and assert row-level
parity with ``LocalEngine`` output (reference role: the whole upstream
repo WAS this binding; SURVEY §7 "the seam must be clean enough that the
Spark binding is mechanical").
"""

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.data.frame import DataFrame, Stage
from sparkdl_tpu.data.spark_binding import SparkEngine, plan_to_map_in_arrow
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.transformers.image_transform import ImageTransformer


@pytest.fixture(scope="module")
def featurized(image_dir):
    """An images frame with the full production plan: decode (host) →
    pack/resize (host) → jitted model apply (device)."""
    from sparkdl_tpu.models.zoo import getModelFunction

    df = imageIO.readImages(image_dir, numPartitions=3,
                            dropImageFailures=True)
    mf = getModelFunction("TestNet", featurize=True)
    out = ImageTransformer(
        inputCol="image", outputCol="features",
        modelFunction=mf).transform(df)
    return out


def _executor_outputs(df: DataFrame) -> list:
    """Run df's plan the way a Spark executor would: one mapInArrow
    function instance per task, fed an iterator of the task's batches."""
    fn = plan_to_map_in_arrow(df._plan)
    outs = []
    for source in df._sources:
        outs.extend(fn(iter([source.load()])))
    return outs


def test_binding_matches_local_engine(featurized):
    expected = featurized.collect()
    got = pa.Table.from_batches(_executor_outputs(featurized))
    assert got.schema.equals(expected.schema)
    a = np.stack(got.column("features").to_pylist())
    b = np.stack(expected.column("features").to_pylist())
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)
    assert got.column("filePath").to_pylist() == \
        expected.column("filePath").to_pylist()


def test_binding_streams_multiple_batches_per_task(featurized):
    """Spark hands mapInArrow MANY batches per task; the compiled fn must
    apply the plan per batch and preserve order within the iterator."""
    fn = plan_to_map_in_arrow(featurized._plan)
    batches = [s.load() for s in featurized._sources]
    outs = list(fn(iter(batches)))
    assert len(outs) == len(batches)
    expected = featurized.collect()
    got = pa.Table.from_batches(outs)
    assert got.column("filePath").to_pylist() == \
        expected.column("filePath").to_pylist()


def test_binding_honors_with_index_stages():
    """with_index stages receive the Spark partition id (0 without a
    TaskContext — exactly what a driver-local plan sees)."""
    batch = pa.RecordBatch.from_pylist([{"x": 1}, {"x": 2}])

    seen = []

    def tag(b, index):
        seen.append(index)
        return b

    fn = plan_to_map_in_arrow([Stage(tag, with_index=True, name="tag")])
    list(fn(iter([batch])))
    assert seen == [0]


def test_spark_engine_requires_pyspark():
    with pytest.raises(RuntimeError, match="pyspark"):
        SparkEngine()


class _FakeRDD:
    """Mimics the exact slice of the RDD API SparkEngine.execute uses:
    parallelize(seq, n).map(fn).collect(). ``map`` runs the task
    function on every element — like an executor would, outside the
    driver's engine — and round-trips each task through pickle the way
    Spark's closure serializer does."""

    def __init__(self, items):
        self.items = list(items)

    def map(self, fn):
        # Spark ships task closures with cloudpickle (stdlib pickle
        # cannot serialize the local closures Sources use) — round-trip
        # through it so un-shippable closures fail here, not on a real
        # cluster
        import cloudpickle

        out = []
        for item in self.items:
            task_fn, task_item = cloudpickle.loads(
                cloudpickle.dumps((fn, item)))
            out.append((task_fn, task_item))
        return _FakeRDD(out)

    def collect(self):
        return [f(i) for f, i in self.items]


class _FakeContext:
    def parallelize(self, seq, n):
        assert n == len(list(seq))  # one partition per task, like execute()
        return _FakeRDD(seq)


class _FakeSparkSession:
    sparkContext = _FakeContext()


class _LazyRDD(_FakeRDD):
    """Adds real pyspark's ``toLocalIterator``: partition-ordered LAZY
    fetch — each task runs only when the driver consumes its result, and
    the log records when, so tests can assert driver memory stays
    O(partition)."""

    def __init__(self, items, log):
        super().__init__(items)
        self.log = log

    def map(self, fn):
        mapped = super().map(fn)
        return _LazyRDD(mapped.items, self.log)

    def toLocalIterator(self):
        for f, i in self.items:
            self.log.append("ran")
            yield f(i)


class _LazySparkSession:
    def __init__(self):
        self.task_log = []
        outer = self

        class Ctx:
            def parallelize(self, seq, n):
                assert n == len(list(seq))
                return _LazyRDD(seq, outer.task_log)

        self.sparkContext = Ctx()


class _RunJobSparkSession:
    """Mimics pyspark's ``sc.runJob(rdd, fn, partitions)``: one job per
    WINDOW of partitions (all of a window's tasks run together — the
    parallelism collect() had), recording each job's partition set so
    tests can assert windows, ordering, and that no job runs before its
    window is consumed."""

    def __init__(self):
        self.jobs = []
        outer = self

        class Ctx:
            def parallelize(self, seq, n):
                assert n == len(list(seq))
                return _FakeRDD(seq)

            def runJob(self, rdd, fn, partitions):
                outer.jobs.append(list(partitions))
                out = []
                for p in partitions:
                    f, item = rdd.items[p]
                    out.extend(fn(iter([f(item)])))
                return out

        self.sparkContext = Ctx()


def test_spark_engine_execute_contract(featurized):
    """SparkEngine.execute end-to-end against a duck-typed session:
    partition loads ship as tasks, results come back as Arrow IPC bytes,
    and the rows match LocalEngine exactly (same plan, same order)."""
    engine = SparkEngine(spark=_FakeSparkSession())
    got = pa.Table.from_batches(
        list(engine.execute(featurized._sources, featurized._plan)))
    expected = featurized.collect()
    assert got.schema.equals(expected.schema)
    assert got.column("filePath").to_pylist() == \
        expected.column("filePath").to_pylist()
    a = np.stack(got.column("features").to_pylist())
    b = np.stack(expected.column("features").to_pylist())
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


def test_spark_engine_union_of_different_plans():
    """A different-plan union's deferred sides must survive the Spark
    task boundary (regression: the deferred loader reached into
    LocalEngine privates and captured an unpicklable lock)."""
    a = DataFrame.from_table(pa.table({"x": np.arange(6.0)}), 2) \
        .filter_rows(np.arange(6.0) >= 1)  # non-preserving plan
    b = DataFrame.from_table(pa.table({"x": np.arange(6.0, 10.0)}), 2)
    u = a.union(b)
    expected = [r["x"] for r in u.collect_rows()]
    assert expected == [1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0, 9.0]

    engine = SparkEngine(spark=_FakeSparkSession())
    got = pa.Table.from_batches(
        list(engine.execute(u._sources, u._plan)))
    assert got.column("x").to_pylist() == expected


def test_deferred_union_side_computes_single_partition_per_task():
    """A shipped different-plan union side must compute ONLY the side
    partition its task asks for — pool-mapping the whole side per task
    would cost O(P²) partition decodes cluster-wide (ADVICE r2 #1)."""
    import cloudpickle

    from sparkdl_tpu.data.frame import Source, Stage, _DeferredSide

    def make(i):
        def _load():
            # closures cross the wire by value, so count by poisoning:
            # any OTHER partition's load blowing up proves the remote
            # copy materialized more than the one it needed
            if i != 3:
                raise AssertionError(
                    f"side partition {i} computed for a task that only "
                    f"needs partition 3")
            return pa.RecordBatch.from_pydict({"x": pa.array([float(i)])})
        return Source(_load, 1, logical_index=i)

    side = _DeferredSide(
        engine=object(),  # any process-local engine; dropped on the wire
        plan=[Stage(lambda b: b, name="identity")],
        sources=[make(i) for i in range(5)])

    remote = cloudpickle.loads(cloudpickle.dumps(side))
    batch = remote.get(3)
    assert batch.column(0).to_pylist() == [3.0]


def test_spark_engine_streams_bounded_memory_at_scale():
    """The north-star dataset does not fit on the driver; execute() must
    fetch results in bounded windows, never materializing all partitions
    at once (VERDICT r2 weak #1) — while keeping cluster parallelism: a
    window's tasks run as ONE job (sequential one-job-per-partition
    would degrade a wide cluster to sum(partition times)). A 100k-row
    20-partition frame with chunk 5 must produce exactly 4 window jobs,
    scheduled only as the consumer reaches them."""
    n_rows, n_parts, chunk = 100_000, 20, 5
    table = pa.table({"x": np.arange(float(n_rows)),
                      "y": np.arange(float(n_rows)) * 2.0})
    df = DataFrame.from_table(table, n_parts).map_batches(
        lambda b: b.set_column(0, "x", pa.array(
            np.asarray(b.column("x")) + 1.0)))

    session = _RunJobSparkSession()
    engine = SparkEngine(spark=session, stream_chunk_size=chunk)
    it = engine.execute(df._sources, df._plan)

    got_batches = []
    jobs_when_consumed = []
    for k in range(n_parts):
        got_batches.append(next(it))
        jobs_when_consumed.append(len(session.jobs))
    assert next(it, None) is None

    # windowed fetch: consuming partition k needs only ceil((k+1)/5)
    # jobs — collect() semantics would materialize everything upfront;
    # one-job-per-partition (plain toLocalIterator) would show k+1 jobs
    assert jobs_when_consumed == [(k // chunk) + 1 for k in range(n_parts)]
    assert session.jobs == [list(range(lo, lo + chunk))
                            for lo in range(0, n_parts, chunk)]

    got = pa.Table.from_batches(got_batches)
    expected = df.collect()
    assert got.num_rows == n_rows
    assert got.column("x").to_pylist() == expected.column("x").to_pylist()


def test_spark_engine_tolocaliterator_fallback_is_lazy():
    """A duck-typed session without runJob but with toLocalIterator
    still streams lazily, one partition per consume."""
    n_parts = 6
    table = pa.table({"x": np.arange(60.0)})
    df = DataFrame.from_table(table, n_parts)
    session = _LazySparkSession()
    engine = SparkEngine(spark=session)
    it = engine.execute(df._sources, df._plan)
    ran_when_consumed = []
    got = []
    for _ in range(n_parts):
        got.append(next(it))
        ran_when_consumed.append(len(session.task_log))
    assert ran_when_consumed == list(range(1, n_parts + 1))
    assert pa.Table.from_batches(got).column("x").to_pylist() == \
        list(np.arange(60.0))


def test_spark_engine_prefers_toLocalIterator_over_collect():
    """When the session offers both, streaming wins: collect must not be
    called at all."""
    table = pa.table({"x": np.arange(8.0)})
    df = DataFrame.from_table(table, 2)
    session = _LazySparkSession()
    collected = []
    orig_collect = _FakeRDD.collect

    def spy_collect(self):
        collected.append(True)
        return orig_collect(self)

    _FakeRDD.collect = spy_collect
    try:
        engine = SparkEngine(spark=session)
        out = pa.Table.from_batches(
            list(engine.execute(df._sources, df._plan)))
    finally:
        _FakeRDD.collect = orig_collect
    assert not collected
    assert out.column("x").to_pylist() == list(np.arange(8.0))


class _SizeRecordingSession:
    """Duck session that records each task's result-payload size — the
    observable proof of WHERE data was produced: a task that writes its
    part inside the executor returns a tiny summary, one that ships its
    batch to the driver returns megabytes."""

    def __init__(self):
        self.result_sizes = []
        outer = self

        class _RDD(_FakeRDD):
            def map(self, fn):
                return _RDD(super().map(fn).items)

            def collect(self):
                out = [f(i) for f, i in self.items]
                outer.result_sizes.extend(len(r) for r in out)
                return out

        class Ctx:
            def parallelize(self, seq, n):
                assert n == len(list(seq))
                return _RDD(seq)

        self.sparkContext = Ctx()


class TestExecutorSideParquetWrite:
    """VERDICT r3 #8: part files are written inside tasks (executors on
    SparkEngine); the driver only commits summaries + _SUCCESS."""

    def test_parts_written_inside_tasks(self, tmp_path):
        n_rows = 20_000
        table = pa.table({"x": np.arange(float(n_rows)),
                          "s": ["wide-payload-" * 8] * n_rows})
        session = _SizeRecordingSession()
        df = DataFrame.from_table(table, 4,
                                  engine=SparkEngine(spark=session))
        out = str(tmp_path / "pq")
        df.write_parquet(out)

        # every task's result is a summary, not the partition data
        assert len(session.result_sizes) == 4
        assert all(sz < 2_000 for sz in session.result_sizes), \
            session.result_sizes
        # the dataset itself is complete and ordered
        back = DataFrame.read_parquet(out)
        assert back.count() == n_rows
        assert back.collect().column("x").to_pylist() == \
            table.column("x").to_pylist()
        import glob
        import os
        assert len(glob.glob(os.path.join(out, "*.parquet"))) == 4
        assert not glob.glob(os.path.join(out, "_tmp*"))

    def test_repeated_partitions_write_distinct_parts(self, tmp_path):
        """with_partition_order repeats are legal; each occurrence must
        commit its own part (identical logical index notwithstanding)."""
        df = DataFrame.from_table(pa.table({"x": np.arange(6.0)}), 2)
        rep = df.with_partition_order([1, 1, 0])
        out = str(tmp_path / "pq")
        rep.write_parquet(out)
        back = DataFrame.read_parquet(out)
        assert back.collect().column("x").to_pylist() == \
            [3.0, 4.0, 5.0, 3.0, 4.0, 5.0, 0.0, 1.0, 2.0]


class _FakeUDFRegistrar:
    """The udf.register(name, fn) seam of a SparkSession, with a
    SELECT-shaped invocation helper: sql_select pulls the named column
    off an Arrow table and calls the registered function on it — the
    shape of ``spark.sql(f"SELECT {name}(col) FROM t")`` — after
    round-tripping the function through cloudpickle, the way Spark
    ships a registered python UDF to its executors."""

    def __init__(self):
        self.registered = {}

    def register(self, name, fn):
        self.registered[name] = fn
        return fn

    def sql_select(self, name, table: pa.Table, col: str):
        import cloudpickle
        fn = cloudpickle.loads(cloudpickle.dumps(self.registered[name]))
        return fn(table.column(col))


class _FakeUDFSession:
    def __init__(self):
        self.udf = _FakeUDFRegistrar()


class TestSqlUdfRegistration:
    """VERDICT r3 missing #1: the reference's makeGraphUDF registered a
    named Spark SQL function (SURVEY §3.5); register_udf is that seam —
    contract-tested against the duck-typed session like SparkEngine."""

    def _tensor_udf(self):
        from sparkdl_tpu.graph.function import ModelFunction
        from sparkdl_tpu.udf.registry import makeModelUDF
        mf = ModelFunction.fromSingle(
            lambda x: x.astype("float32") * 3.0, None,
            input_shape=(4,), input_dtype=np.float32, name="triple")
        return makeModelUDF(mf, "triple", kind="tensor", register=False)

    def test_select_matches_model_udf_apply(self):
        from sparkdl_tpu.data.spark_binding import register_udf

        udf = self._tensor_udf()
        session = _FakeUDFSession()
        register_udf(session, udf)
        assert "triple" in session.udf.registered

        rows = [{"x": [float(i), 1.0, 2.0, 3.0]} for i in range(7)]
        table = pa.table({"x": [r["x"] for r in rows]})
        got = session.udf.sql_select("triple", table, "x")

        frame = DataFrame.from_pylist(rows, num_partitions=2)
        expected = udf.apply(frame, "x", "y").collect().column("y")
        assert got.to_pylist() == expected.combine_chunks().to_pylist()

    def test_pandas_series_convention(self):
        """pandas_udf hands the function a pandas Series and expects a
        Series back — the calling convention pyspark uses when the real
        pandas_udf wrapper is unavailable in-env."""
        import pandas as pd

        from sparkdl_tpu.data.spark_binding import udf_to_column_fn

        fn = udf_to_column_fn(self._tensor_udf())
        s = pd.Series([[1.0, 2.0, 3.0, 4.0], [0.0, 0.0, 0.0, 0.5]])
        out = fn(s)
        assert isinstance(out, pd.Series)
        np.testing.assert_allclose(out.iloc[0], [3.0, 6.0, 9.0, 12.0])
        np.testing.assert_allclose(out.iloc[1], [0.0, 0.0, 0.0, 1.5])

    def test_pandas_dataframe_struct_convention(self, image_dir):
        """Real pyspark hands a STRUCT column (the image struct) to a
        scalar pandas_udf as a pandas DataFrame (one column per field)
        — the column fn must rebuild the struct array from it."""
        import keras
        import pandas as pd

        from sparkdl_tpu.data.spark_binding import udf_to_column_fn
        from sparkdl_tpu.image import imageIO
        from sparkdl_tpu.udf import registerKerasImageUDF, unregisterUDF

        keras.utils.set_random_seed(6)
        m = keras.Sequential([
            keras.layers.Input((10, 10, 3)),
            keras.layers.Flatten(),
            keras.layers.Dense(2, activation="softmax"),
        ])
        udf = registerKerasImageUDF("pd_struct_udf", m)
        try:
            df = imageIO.readImages(image_dir, numPartitions=2,
                                    dropImageFailures=True)
            table = df.collect()
            img = table.column("image").combine_chunks()
            pdf = pd.DataFrame(img.to_pylist())  # pyspark's shape
            fn = udf_to_column_fn(udf)
            out = fn(pdf)
            assert isinstance(out, pd.Series)
            expected = udf.apply(df, "image", "p") \
                .collect().column("p").combine_chunks()
            np.testing.assert_allclose(
                np.stack(out.tolist()),
                np.stack(expected.to_pylist()), rtol=1e-5, atol=1e-6)
        finally:
            unregisterUDF("pd_struct_udf")

    def test_image_udf_over_sql_seam(self, image_dir):
        """The reference's headline flow: register a Keras image model,
        SELECT it over an image-struct column — rows must equal the
        pipeline transformer's output."""
        import keras

        from sparkdl_tpu.data.spark_binding import register_udf
        from sparkdl_tpu.image import imageIO
        from sparkdl_tpu.udf import registerKerasImageUDF, unregisterUDF

        keras.utils.set_random_seed(5)
        m = keras.Sequential([
            keras.layers.Input((12, 12, 3)),
            keras.layers.Flatten(),
            keras.layers.Dense(3, activation="softmax"),
        ])
        session = _FakeUDFSession()
        udf = registerKerasImageUDF("sql_img_udf", m, session=session)
        try:
            df = imageIO.readImages(image_dir, numPartitions=2,
                                    dropImageFailures=True)
            table = df.collect()
            got = session.udf.sql_select("sql_img_udf", table, "image")
            expected = udf.apply(df, "image", "probs") \
                .collect().column("probs")
            np.testing.assert_allclose(
                np.stack(got.to_pylist()),
                np.stack(expected.combine_chunks().to_pylist()),
                rtol=1e-5, atol=1e-6)
        finally:
            unregisterUDF("sql_img_udf")

    def test_register_validates_session_and_mode(self):
        from sparkdl_tpu.data.spark_binding import (
            register_udf,
            udf_to_column_fn,
        )

        udf = self._tensor_udf()
        with pytest.raises(TypeError, match="udf.register"):
            register_udf(object(), udf)
        with pytest.raises(ValueError, match="vector"):
            udf_to_column_fn(udf, outputMode="image")


def test_spark_engine_with_index_uses_logical_identity():
    """A reordered frame's with_index stages must see each partition's
    pinned LOGICAL index on the Spark engine too, not the task position
    (same contract LocalEngine honors)."""
    base = DataFrame.from_table(
        pa.table({"x": np.arange(40.0)}), 4)
    tagged = base.with_partition_order([3, 1]).map_batches(
        lambda b, i: b.append_column("pid", pa.array([i] * b.num_rows)),
        with_index=True)
    engine = SparkEngine(spark=_FakeSparkSession())
    got = pa.Table.from_batches(
        list(engine.execute(tagged._sources, tagged._plan)))
    assert sorted(set(got.column("pid").to_pylist())) == [1, 3]
    expected = tagged.collect()
    assert got.column("pid").to_pylist() == \
        expected.column("pid").to_pylist()


def test_null_struct_rows_from_pandas_surface_as_null_images():
    """pyspark hands a struct column to a pandas_udf as a DataFrame with
    NULL rows flattened to all-null fields; the rebuilt StructArray must
    carry row-level validity so the failure is imageColumnViews' clear
    'null image' message, not a NaN cast error (advisor r4 #3)."""
    import pandas as pd

    from sparkdl_tpu.image import imageIO

    good = imageIO.imageArrayToStruct(
        np.zeros((4, 5, 3), np.uint8), origin="g")
    frame = pd.DataFrame([good,
                          {k: None for k in good}])  # null image row
    tbl = pa.Table.from_pandas(frame, preserve_index=False)
    children = [tbl.column(i).combine_chunks()
                for i in range(tbl.num_columns)]
    all_null = np.logical_and.reduce(
        [np.asarray(pa.compute.is_null(c)) for c in children])
    arr = pa.StructArray.from_arrays(
        children, names=list(tbl.column_names),
        mask=pa.array(all_null))
    # the binding's own path builds the same mask — drive it end to end
    from sparkdl_tpu.data.spark_binding import udf_to_column_fn
    from sparkdl_tpu.udf.registry import makeModelUDF
    from sparkdl_tpu.models.zoo import getModelFunction
    udf = makeModelUDF(getModelFunction("TestNet", featurize=True),
                       "nulltest_udf", kind="image", register=False)
    fn = udf_to_column_fn(udf, outputMode="vector")
    with pytest.raises(ValueError, match="null image"):
        fn(frame)
