"""Device-dataflow layer tests: hot-path classification over the real
package, the H14 hot-sync rule (witness chains through resolved call
edges), the H15 donation rule's dead-vs-escaping argument matrix, the
H16 widening rule, cache round-trip of the dataflow facts, the
analyzer's per-rule cost accounting, and the ISSUE-12 fix-on-find
regressions (the estimator's donated batch, the LR estimators'
epoch-boundary loss drains).

Fixture style mirrors tests/test_callgraph.py / test_effects.py:
deliberately hazardous multi-module trees under tmp_path trip the
rules; the idiomatic clean forms don't; inline suppressions downgrade
without hiding. Hot fixtures mark their loops the same way the repo
does — a ``sparkdl_tpu.obs.watchdog`` watch/pulse import + call — so
hotness is detected lexically, never by executing fixture code.
"""

import os

import numpy as np
import pytest

import sparkdl_tpu
from sparkdl_tpu.analysis import analyze_paths, build_graph
from sparkdl_tpu.analysis.callgraph import ModuleFacts, scan_module
from sparkdl_tpu.analysis.dataflow import DeviceFlow, _flow_state
from sparkdl_tpu.analysis.walker import analyze_source
import ast

PKG_DIR = os.path.dirname(os.path.abspath(sparkdl_tpu.__file__))
REPO_ROOT = os.path.dirname(PKG_DIR)

WATCH_IMPORT = \
    "from sparkdl_tpu.obs.watchdog import watch as watchdog_watch\n"


def _tree(tmp_path, files: dict) -> str:
    tmp_path.mkdir(parents=True, exist_ok=True)
    for name, src in files.items():
        (tmp_path / name).write_text(src)
    return str(tmp_path)


def _unsup(findings, rule):
    return [f for f in findings if f.rule == rule and not f.suppressed]


# ---------------------------------------------------------------------------
# hot-path classification


class TestHotPathClassification:
    def test_watchdog_marker_roots_a_function(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "import jax.numpy as jnp\n" + WATCH_IMPORT +
            "def loop(xs):\n"
            "    for x in xs:\n"
            "        with watchdog_watch('m.loop'):\n"
            "            pass\n"
            "def cold(xs):\n"
            "    return xs\n")})
        g = build_graph([os.path.join(root, "m.py")])
        state = _flow_state(g)
        [loop_key] = [k for k in g.functions if k.endswith("::loop")]
        [cold_key] = [k for k in g.functions if k.endswith("::cold")]
        assert state.hot.is_hot(loop_key)
        assert not state.hot.is_hot(cold_key)

    def test_hotness_flows_down_not_up(self, tmp_path):
        """Callees of a hot loop are hot (with a recorded chain);
        the loop's own CALLERS are not."""
        root = _tree(tmp_path, {"m.py": (
            WATCH_IMPORT +
            "def helper(x):\n"
            "    return x\n"
            "def loop(xs):\n"
            "    with watchdog_watch('m'):\n"
            "        for x in xs:\n"
            "            helper(x)\n"
            "def caller(xs):\n"
            "    loop(xs)\n")})
        g = build_graph([os.path.join(root, "m.py")])
        state = _flow_state(g)
        key = {k.rsplit("::", 1)[1]: k for k in g.functions}
        assert state.hot.is_hot(key["helper"])
        assert not state.hot.is_hot(key["caller"])
        chain = state.hot.chain(key["helper"])
        assert chain[0] == key["loop"] and chain[-1] == key["helper"]
        assert "loop -> " in state.hot.why(key["helper"])

    def test_real_package_roots_are_hot(self):
        """The runner dispatch/drain state machine, the serve
        dispatcher, the engine stream/re-chunk path, and the
        estimator step loops all classify hot on the real package."""
        from sparkdl_tpu.analysis import iter_python_files
        g = build_graph(list(iter_python_files(PKG_DIR)))
        state = _flow_state(g)
        hot = {k for k in g.functions if state.hot.is_hot(k)}

        def has(qual):
            return any(k.endswith("::" + qual) for k in hot), \
                sorted(q for q in hot if qual.split(".")[-1] in q)

        for qual in ("dispatch_chunks", "drain_bounded",
                     "SlabSink.write",
                     "ModelSession._serve_loop",
                     "LocalEngine._stream_rechunk",
                     "KerasImageFileEstimator._trainOne",
                     "LogisticRegression._run_minibatch"):
            ok, near = has(qual)
            assert ok, (qual, near)

    def test_tools_examples_and_config_paths_are_cold(self):
        """Hotness must not leak UP into the CLIs that call the hot
        paths, nor into cold config/constructor code."""
        from sparkdl_tpu.analysis import iter_python_files
        paths = list(iter_python_files(PKG_DIR))
        for extra in ("tools", "examples"):
            d = os.path.join(REPO_ROOT, extra)
            if os.path.isdir(d):
                paths.extend(iter_python_files(d))
        g = build_graph(paths)
        state = _flow_state(g)
        for key in g.functions:
            mod = key.partition("::")[0]
            if mod.startswith(("tools.", "examples.")) \
                    or ".serve.config" in mod:
                assert not state.hot.is_hot(key), \
                    (key, state.hot.why(key))


# ---------------------------------------------------------------------------
# H14 — hot-path host sync


class TestH14HotPathSync:
    def _analyze(self, root):
        return analyze_paths([root], cache_path=None)

    def test_item_sync_in_hot_loop_caught(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "import jax.numpy as jnp\n" + WATCH_IMPORT +
            "def loop(xs, out):\n"
            "    for x in xs:\n"
            "        with watchdog_watch('m'):\n"
            "            v = jnp.asarray(x)\n"
            "            out.append(v.item())\n")})
        h14 = _unsup(self._analyze(root), "H14")
        assert len(h14) == 1 and "`.item()`" in h14[0].message, \
            [f.render() for f in h14]

    def test_witness_chain_through_two_modules(self, tmp_path):
        """The sync sits two resolved call edges from the watchdog
        root, with the device value crossing as an ARGUMENT — the
        finding anchors in the leaf module and prints the full hot
        chain module-by-module."""
        root = _tree(tmp_path, {
            "sink.py": ("def record(loss, out):\n"
                        "    out.append(float(loss))\n"),
            "mid.py": ("from sink import record\n"
                       "def forward(loss, out):\n"
                       "    record(loss, out)\n"),
            "hot.py": ("import jax.numpy as jnp\n" + WATCH_IMPORT +
                       "from mid import forward\n"
                       "def drive(xs, out):\n"
                       "    for x in xs:\n"
                       "        with watchdog_watch('hot'):\n"
                       "            loss = jnp.asarray(x)\n"
                       "            forward(loss, out)\n")})
        h14 = _unsup(self._analyze(root), "H14")
        assert len(h14) == 1, [f.render() for f in h14]
        f = h14[0]
        assert f.path.endswith("sink.py")
        # the chain prints module-by-module, root first (module names
        # carry the fixture dir prefix)
        assert "hot:drive -> " in f.message, f.message
        assert "mid:forward -> " in f.message, f.message
        assert "sink:record" in f.message, f.message
        assert f.message.index("hot:drive") \
            < f.message.index("mid:forward") \
            < f.message.index("sink:record")
        assert "`float(...)`" in f.message

    @pytest.mark.parametrize("sync", [
        "float(v)", "int(v)", "len(v)", "np.asarray(v)",
        "v.tolist()"])
    def test_materialization_forms_caught(self, tmp_path, sync):
        root = _tree(tmp_path, {"m.py": (
            "import numpy as np\n"
            "import jax.numpy as jnp\n" + WATCH_IMPORT +
            "def loop(xs, out):\n"
            "    for x in xs:\n"
            "        with watchdog_watch('m'):\n"
            "            v = jnp.asarray(x)\n"
            f"            out.append({sync})\n")})
        h14 = _unsup(self._analyze(root), "H14")
        assert len(h14) == 1, (sync, [f.render() for f in h14])

    def test_truthiness_and_iteration_caught(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "import jax.numpy as jnp\n" + WATCH_IMPORT +
            "def loop(xs, out):\n"
            "    with watchdog_watch('m'):\n"
            "        v = jnp.asarray(xs)\n"
            "        if v:\n"
            "            out.append(1)\n"
            "        for row in v:\n"
            "            out.append(row)\n")})
        h14 = _unsup(self._analyze(root), "H14")
        forms = {f.message.split(" over ")[0] for f in h14}
        assert len(h14) == 2, [f.render() for f in h14]
        assert any("truth" in m for m in forms), forms
        assert any("for ... in" in m for m in forms), forms

    def test_aliased_module_import_resolves(self, tmp_path):
        """Review regression: device-ness must cross `import mod as
        alias` calls — the dotted qualifier carries the IMPORT SOURCE
        (the locks.py contract), not the local alias."""
        root = _tree(tmp_path, {
            "helpers_mod.py": ("import jax.numpy as jnp\n"
                               "def make(x):\n"
                               "    return jnp.asarray(x)\n"),
            "main_mod.py": ("import helpers_mod as hm\n"
                            + WATCH_IMPORT +
                            "def loop(xs, out):\n"
                            "    for x in xs:\n"
                            "        with watchdog_watch('m'):\n"
                            "            v = hm.make(x)\n"
                            "            out.append(v.item())\n")})
        h14 = _unsup(self._analyze(root), "H14")
        assert len(h14) == 1 and "`v`" in h14[0].message, \
            [f.render() for f in h14]

    def test_self_call_resolves_despite_ambiguous_method_name(
            self, tmp_path):
        """Review regression: `self.make()` binds to the ENCLOSING
        class even when another class defines a same-named method —
        the qualifier carries the class, not the unique-method
        fallback."""
        root = _tree(tmp_path, {"m.py": (
            "import jax.numpy as jnp\n" + WATCH_IMPORT +
            "class A:\n"
            "    def make(self, x):\n"
            "        return jnp.asarray(x)\n"
            "    def drive(self, xs, out):\n"
            "        for x in xs:\n"
            "            with watchdog_watch('m'):\n"
            "                v = self.make(x)\n"
            "                out.append(v.item())\n"
            "class B:\n"
            "    def make(self, x):\n"
            "        return x\n")})
        h14 = _unsup(self._analyze(root), "H14")
        assert len(h14) == 1 and "`v`" in h14[0].message, \
            [f.render() for f in h14]

    def test_cold_function_not_flagged(self, tmp_path):
        """The same sync OFF the hot set is fine — draining at a
        boundary is exactly what the fix-on-find sweep installed."""
        root = _tree(tmp_path, {"m.py": (
            "import jax.numpy as jnp\n"
            "def summarize(xs):\n"
            "    v = jnp.asarray(xs)\n"
            "    return float(v)\n")})
        assert _unsup(self._analyze(root), "H14") == []

    def test_container_of_device_arrays_not_flagged(self, tmp_path):
        """Review regression: a host LIST of device arrays is a plain
        python container — len()/iteration over it are free host ops,
        exactly the pre-staging pattern the rule should encourage."""
        root = _tree(tmp_path, {"m.py": (
            "import jax.numpy as jnp\n" + WATCH_IMPORT +
            "def loop(data, step):\n"
            "    with watchdog_watch('m'):\n"
            "        batches = [jnp.asarray(b) for b in data]\n"
            "        if len(batches) > 1:\n"
            "            pass\n"
            "        for xb in batches:\n"
            "            step(xb)\n")})
        assert _unsup(self._analyze(root), "H14") == []

    def test_len_message_is_honest_about_metadata(self, tmp_path):
        """len() on a jax array reads static shape — the finding must
        not claim the thread blocks."""
        root = _tree(tmp_path, {"m.py": (
            "import jax.numpy as jnp\n" + WATCH_IMPORT +
            "def loop(xs, out):\n"
            "    with watchdog_watch('m'):\n"
            "        v = jnp.asarray(xs)\n"
            "        out.append(len(v))\n")})
        h14 = _unsup(self._analyze(root), "H14")
        assert len(h14) == 1, [f.render() for f in h14]
        assert "static metadata" in h14[0].message
        assert "blocks until the device" not in h14[0].message

    def test_arithmetic_propagates_device_ness(self, tmp_path):
        """Review regression: `y = dev * dev` is a device array — the
        per-step `.item()` on the DERIVED value must still flag."""
        root = _tree(tmp_path, {"m.py": (
            "import jax.numpy as jnp\n" + WATCH_IMPORT +
            "def loop(xs, out):\n"
            "    for x in xs:\n"
            "        with watchdog_watch('m'):\n"
            "            dev = jnp.asarray(x)\n"
            "            y = dev * dev\n"
            "            out.append(y.item())\n")})
        h14 = _unsup(self._analyze(root), "H14")
        assert len(h14) == 1 and "`y`" in h14[0].message, \
            [f.render() for f in h14]

    def test_host_values_not_flagged(self, tmp_path):
        """np/host values materialize freely — only device-tracked
        values count."""
        root = _tree(tmp_path, {"m.py": (
            "import numpy as np\n" + WATCH_IMPORT +
            "def loop(xs, out):\n"
            "    for x in xs:\n"
            "        with watchdog_watch('m'):\n"
            "            v = np.square(x)\n"
            "            out.append(float(v))\n")})
        assert _unsup(self._analyze(root), "H14") == []

    def test_inline_suppression_downgrades_not_hides(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "import jax.numpy as jnp\n" + WATCH_IMPORT +
            "def loop(xs, out):\n"
            "    for x in xs:\n"
            "        with watchdog_watch('m'):\n"
            "            v = jnp.asarray(x)\n"
            "            out.append(v.item())  "
            "# sparkdl-lint: allow[H14] -- convergence check needs "
            "the scalar per step\n")})
        found = [f for f in self._analyze(root) if f.rule == "H14"]
        assert len(found) == 1 and found[0].suppressed
        assert "convergence" in found[0].suppression

    def test_sanctioned_drain_is_allowlisted_not_invisible(self):
        """timed_device_get's own scope may materialize — via the
        DEFAULT_ALLOWLIST H14 entry, reported suppressed."""
        found = analyze_source(
            "import jax.numpy as jnp\n" + WATCH_IMPORT +
            "def timed_device_get(res):\n"
            "    with watchdog_watch('drain'):\n"
            "        v = jnp.asarray(res)\n"
            "        return v.item()\n",
            "sparkdl_tpu/obs/trace.py", rules=["H14"])
        h14 = [f for f in found if f.rule == "H14"]
        assert h14 and all(f.suppressed for f in h14)
        assert "allowlist" in h14[0].suppression


# ---------------------------------------------------------------------------
# H15 — missing buffer donation: the dead-vs-escaping matrix


_H15_HEADER = (
    "import jax\n"
    "import jax.numpy as jnp\n"
    "def run(step, X, keep):\n"
    "    jitted = jax.jit(step)\n"
    "    state = jnp.zeros((4,), jnp.float32)\n")


class TestH15Donation:
    def _h15(self, tmp_path, body, header=_H15_HEADER):
        root = _tree(tmp_path, {"m.py": header + body})
        return _unsup(analyze_paths([root], cache_path=None), "H15")

    def test_dead_argument_caught_with_index(self, tmp_path):
        h15 = self._h15(tmp_path,
                        "    for i in range(8):\n"
                        "        xb = jnp.asarray(X[i])\n"
                        "        state = jitted(state, xb)\n"
                        "    return state\n")
        assert len(h15) == 1, [f.render() for f in h15]
        assert "`xb`" in h15[0].message
        assert "donate_argnums=(1,)" in h15[0].message

    def test_result_carrying_state_not_flagged(self, tmp_path):
        """``state`` is read after the call (returned, re-fed) — its
        buffer is NOT dead, donation analysis must skip it."""
        h15 = self._h15(tmp_path,
                        "    for i in range(8):\n"
                        "        xb = jnp.asarray(X[i])\n"
                        "        state = jitted(state, xb)\n"
                        "    return state\n")
        assert not any("`state`" in f.message for f in h15)

    @pytest.mark.parametrize("escape,why", [
        ("        keep.append(xb)\n", "passed to another call"),
        ("        keep.attr = xb\n", "stored on an attribute"),
        ("        keep[i] = xb\n", "stored in a container"),
    ], ids=["arg-pass", "attr-store", "subscript-store"])
    def test_escaping_argument_not_flagged(self, tmp_path, escape,
                                           why):
        h15 = self._h15(tmp_path,
                        "    for i in range(8):\n"
                        "        xb = jnp.asarray(X[i])\n"
                        + escape +
                        "        state = jitted(state, xb)\n"
                        "    return state\n")
        assert h15 == [], (why, [f.render() for f in h15])

    def test_read_after_call_not_flagged(self, tmp_path):
        h15 = self._h15(tmp_path,
                        "    for i in range(8):\n"
                        "        xb = jnp.asarray(X[i])\n"
                        "        state = jitted(state, xb)\n"
                        "        last = xb\n"
                        "    return state, last\n")
        assert h15 == [], [f.render() for f in h15]

    def test_loop_carried_argument_not_flagged(self, tmp_path):
        """A buffer placed BEFORE the loop and re-fed every iteration
        is loop-carried — donating it would poison iteration 2."""
        h15 = self._h15(tmp_path,
                        "    xb = jnp.asarray(X)\n"
                        "    for i in range(8):\n"
                        "        state = jitted(state, xb)\n"
                        "    return state\n")
        assert h15 == [], [f.render() for f in h15]

    def test_parameter_argument_not_flagged(self, tmp_path):
        """A function PARAMETER's lifetime belongs to the caller —
        never dead from this scope's view."""
        root = _tree(tmp_path, {"m.py": (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "def run_one(step, xb):\n"
            "    jitted = jax.jit(step)\n"
            "    return jitted(xb)\n")})
        assert _unsup(analyze_paths([root], cache_path=None),
                      "H15") == []

    def test_donated_compile_not_flagged(self, tmp_path):
        h15 = self._h15(tmp_path,
                        "    for i in range(8):\n"
                        "        xb = jnp.asarray(X[i])\n"
                        "        state = jitted(state, xb)\n"
                        "    return state\n",
                        header=_H15_HEADER.replace(
                            "jax.jit(step)",
                            "jax.jit(step, donate_argnums=(1,))"))
        assert h15 == [], [f.render() for f in h15]

    def test_jit_compiled_in_resolved_helper_caught(self, tmp_path):
        """The estimator shape: the jit is compiled by a helper and
        returned; the call site is where donation analysis runs — the
        finding names the compiling call."""
        root = _tree(tmp_path, {
            "compiler.py": ("import jax\n"
                            "def compile_step(step):\n"
                            "    jitted = jax.jit(step)\n"
                            "    return jitted, 32\n"),
            "trainer.py": ("import jax.numpy as jnp\n"
                           "from compiler import compile_step\n"
                           "def train(step, X):\n"
                           "    jitted, bs = compile_step(step)\n"
                           "    for i in range(8):\n"
                           "        xb = jnp.asarray(X[i])\n"
                           "        out = jitted(xb)\n"
                           "    return out\n")})
        h15 = _unsup(analyze_paths([root], cache_path=None), "H15")
        assert len(h15) == 1, [f.render() for f in h15]
        assert h15[0].path.endswith("trainer.py")
        assert "compile_step" in h15[0].message
        assert "donate_argnums=(0,)" in h15[0].message

    def test_model_function_jitted_form(self, tmp_path):
        """`mf.jitted()` without donate_inputs flags a dead batch;
        with donate_inputs=True it is silent."""
        src = ("import jax.numpy as jnp\n"
               "def apply(mf, rows):\n"
               "    fn = mf.jitted({})\n"
               "    d = jnp.asarray(rows)\n"
               "    return fn(d)\n")
        root = _tree(tmp_path, {"m.py": src.format("")})
        h15 = _unsup(analyze_paths([root], cache_path=None), "H15")
        assert len(h15) == 1 and "`d`" in h15[0].message, \
            [f.render() for f in h15]
        root2 = _tree(tmp_path / "b",
                      {"m.py": src.format("donate_inputs=True")})
        assert _unsup(analyze_paths([root2], cache_path=None),
                      "H15") == []

    def test_inline_suppression(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            _H15_HEADER +
            "    for i in range(8):\n"
            "        xb = jnp.asarray(X[i])\n"
            "        # sparkdl-lint: allow[H15] -- xb aliases a "
            "caller-owned staging buffer\n"
            "        state = jitted(state, xb)\n"
            "    return state\n")})
        found = [f for f in analyze_paths([root], cache_path=None)
                 if f.rule == "H15"]
        assert len(found) == 1 and found[0].suppressed
        assert "staging buffer" in found[0].suppression

    def test_nonlocal_rebinding_closure_is_an_escape(self, tmp_path):
        """Review regression: a nested def that rebinds the buffer
        via `nonlocal` both reads and writes the OUTER binding — the
        buffer is captured, not dead, and donating it would be a
        use-after-donate when the closure later runs."""
        h15 = self._h15(tmp_path,
                        "    xb = jnp.asarray(X)\n"
                        "    def reset():\n"
                        "        nonlocal xb\n"
                        "        xb = jnp.zeros_like(xb)\n"
                        "    keep.append(reset)\n"
                        "    state = jitted(state, xb)\n"
                        "    return state\n")
        assert h15 == [], [f.render() for f in h15]

    def test_conditionally_assigned_loop_buffer_not_flagged(
            self, tmp_path):
        """Review regression: an arg assigned on a maybe-skipped
        branch inside the loop is reused across the back-edge by the
        iterations that skip it — loop-carried, never dead."""
        h15 = self._h15(tmp_path,
                        "    xb = jnp.asarray(X[0])\n"
                        "    for i in range(8):\n"
                        "        if i % 2 == 0:\n"
                        "            xb = jnp.asarray(X[i])\n"
                        "        state = jitted(state, xb)\n"
                        "    return state\n")
        assert h15 == [], [f.render() for f in h15]

    def test_reassignment_after_the_call_keeps_the_finding(
            self, tmp_path):
        """Review regression: deadness is judged against the
        assignment REACHING the call (snapshotted at call time) — a
        later conditional reassignment of the same name must not
        launder the verdict about the buffer fed into the call."""
        h15 = self._h15(tmp_path,
                        "    for i in range(8):\n"
                        "        xb = jnp.asarray(X[i])\n"
                        "        state = jitted(state, xb)\n"
                        "        if i == 7:\n"
                        "            xb = jnp.asarray(X[0])\n"
                        "    return state\n")
        assert any("`xb`" in f.message for f in h15), \
            [f.render() for f in h15]

    def test_back_edge_read_above_the_assignment_not_flagged(
            self, tmp_path):
        """Review regression: a read at the loop TOP, lexically above
        the reaching assignment, runs on the next iteration against
        this iteration's buffer — donating it would crash iteration
        2 with a use-after-donate."""
        h15 = self._h15(tmp_path,
                        "    xb = jnp.asarray(X[0])\n"
                        "    delta = jnp.zeros((4,), jnp.float32)\n"
                        "    for i in range(8):\n"
                        "        delta = delta + xb\n"
                        "        xb = jnp.asarray(X[i])\n"
                        "        state = jitted(state, xb)\n"
                        "    return state, delta\n")
        assert not any("`xb`" in f.message for f in h15), \
            [f.render() for f in h15]

    def test_device_container_arg_still_flagged(self, tmp_path):
        """A dict comprehension of device arrays is a donatable
        pytree — the ModelFunction.__call__ shape."""
        root = _tree(tmp_path, {"m.py": (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "def apply_once(step, rows):\n"
            "    jitted = jax.jit(step)\n"
            "    d = {k: jnp.asarray(v) for k, v in rows.items()}\n"
            "    return jitted(d)\n")})
        h15 = _unsup(analyze_paths([root], cache_path=None), "H15")
        assert len(h15) == 1 and "`d`" in h15[0].message, \
            [f.render() for f in h15]


# ---------------------------------------------------------------------------
# H16 — dtype widening


class TestH16Widening:
    def _h16(self, tmp_path, line):
        root = _tree(tmp_path, {"m.py": (
            "import numpy as np\n"
            "import jax.numpy as jnp\n" + WATCH_IMPORT +
            "def ship(chunks, out):\n"
            "    for c in chunks:\n"
            "        with watchdog_watch('m'):\n"
            "            dev = jnp.asarray(c)\n"
            f"            {line}\n"
            "            out.append(dev)\n")})
        return _unsup(analyze_paths([root], cache_path=None), "H16")

    def test_dtypeless_zeros_caught(self, tmp_path):
        h16 = self._h16(tmp_path, "dev = dev + np.zeros(4)")
        assert len(h16) == 1 and "np.zeros" in h16[0].message, \
            [f.render() for f in h16]
        assert "hot witness" in h16[0].message

    def test_float64_scalar_caught(self, tmp_path):
        h16 = self._h16(tmp_path, "dev = dev * np.float64(0.5)")
        assert len(h16) == 1, [f.render() for f in h16]

    def test_dtypeless_full_caught(self, tmp_path):
        """Review regression: np.full's dtype is the THIRD positional
        — the two-arg form is dtype-less and must flag; the
        dtype-pinned form must not."""
        h16 = self._h16(tmp_path, "dev = dev + np.full((4,), 0.5)")
        assert len(h16) == 1, [f.render() for f in h16]
        clean = self._h16(
            tmp_path / "b",
            "dev = dev + np.full((4,), 0.5, np.float32)")
        assert clean == [], [f.render() for f in clean]

    def test_float_literal_caught(self, tmp_path):
        h16 = self._h16(tmp_path, "dev = dev * 2.5")
        assert len(h16) == 1, [f.render() for f in h16]

    def test_pinned_dtype_not_flagged(self, tmp_path):
        h16 = self._h16(tmp_path,
                        "dev = dev + np.zeros(4, dtype=np.float32)")
        assert h16 == [], [f.render() for f in h16]

    def test_cold_function_not_flagged(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "import numpy as np\n"
            "import jax.numpy as jnp\n"
            "def summarize(c):\n"
            "    dev = jnp.asarray(c)\n"
            "    return dev + np.zeros(4)\n")})
        assert _unsup(analyze_paths([root], cache_path=None),
                      "H16") == []

    def test_inline_suppression(self, tmp_path):
        h16 = [f for f in analyze_paths([_tree(tmp_path, {"m.py": (
            "import numpy as np\n"
            "import jax.numpy as jnp\n" + WATCH_IMPORT +
            "def ship(chunks, out):\n"
            "    for c in chunks:\n"
            "        with watchdog_watch('m'):\n"
            "            dev = jnp.asarray(c)\n"
            "            dev = dev + np.zeros(4)  "
            "# sparkdl-lint: allow[H16] -- f64 accumulator is the "
            "numerically-required reduction dtype\n"
            "            out.append(dev)\n")})], cache_path=None)
            if f.rule == "H16"]
        assert len(h16) == 1 and h16[0].suppressed


# ---------------------------------------------------------------------------
# facts serialization + cache + cost accounting


class TestFactsAndCost:
    def test_device_flow_round_trips_through_module_facts(self):
        src = ("import jax\n"
               "import jax.numpy as jnp\n" + WATCH_IMPORT +
               "def loop(xs, out):\n"
               "    jitted = jax.jit(len)\n"
               "    for x in xs:\n"
               "        with watchdog_watch('m'):\n"
               "            v = jnp.asarray(x)\n"
               "            out.append(v.item())\n")
        mf = scan_module(ast.parse(src), "m.py")
        back = ModuleFacts.from_dict(mf.to_dict())
        assert set(back.flows) == set(mf.flows)
        for key, flow in mf.flows.items():
            b = back.flows[key]
            assert isinstance(b, DeviceFlow)
            assert b.hot_root == flow.hot_root
            assert b.params == flow.params
            assert b.last_load == flow.last_load
            assert [(e.kind, e.line, e.loops, e.data)
                    for e in b.events] == \
                [(e.kind, e.line, e.loops, e.data)
                 for e in flow.events]

    def test_cached_rerun_reports_identical_h14(self, tmp_path):
        """The dataflow facts ride the per-file cache: a warm run
        replays them without re-scanning and reaches the same
        verdicts."""
        root = _tree(tmp_path / "t", {"m.py": (
            "import jax.numpy as jnp\n" + WATCH_IMPORT +
            "def loop(xs, out):\n"
            "    for x in xs:\n"
            "        with watchdog_watch('m'):\n"
            "            v = jnp.asarray(x)\n"
            "            out.append(v.item())\n")})
        cache = str(tmp_path / "cache.json")
        stats_cold: dict = {}
        cold = analyze_paths([root], cache_path=cache,
                             cache_stats=stats_cold)
        stats_warm: dict = {}
        warm = analyze_paths([root], cache_path=cache,
                             cache_stats=stats_warm)
        assert stats_cold["misses"] == 1 and stats_cold["hits"] == 0
        assert stats_warm["hits"] == 1 and stats_warm["misses"] == 0
        assert [f.message for f in _unsup(cold, "H14")] == \
            [f.message for f in _unsup(warm, "H14")]
        assert _unsup(warm, "H14"), "warm run lost the finding"

    def test_rule_stats_cover_the_dataflow_rules(self, tmp_path):
        root = _tree(tmp_path, {"m.py": "def f():\n    return 1\n"})
        rule_stats: dict = {}
        analyze_paths([root], cache_path=None, rule_stats=rule_stats)
        per_rule = rule_stats["per_rule_s"]
        for rule in ("H14", "H15", "H16", "H7", "H10", "scan"):
            assert rule in per_rule, (rule, sorted(per_rule))
            assert per_rule[rule] >= 0.0
        assert rule_stats["total_s"] > 0.0


# ---------------------------------------------------------------------------
# ISSUE-12 fix-on-find regressions


class TestFixOnFindRegressions:
    def test_estimator_step_donates_the_batch(self):
        """Both _compile_step branches must donate the batch args
        (3, 4) — the H15 finding this PR fixed; a refactor dropping
        the donation re-opens it (and the analyzer would flag it
        again, pinned below)."""
        path = os.path.join(PKG_DIR, "estimators",
                            "keras_image_file_estimator.py")
        with open(path) as f:
            src = f.read()
        assert src.count("donate_argnums=(3, 4)") == 2, \
            "both _compile_step branches must donate (xb, yb)"

    def test_logistic_regression_drains_at_the_boundary(self):
        """The three per-step float(loss) syncs are gone: losses
        accumulate device-side and drain once per epoch/fit."""
        path = os.path.join(PKG_DIR, "estimators",
                            "logistic_regression.py")
        with open(path) as f:
            src = f.read()
        assert ".append(float(loss))" not in src, \
            "a per-step float(loss) sync came back"
        assert src.count("jax.device_get(losses)") >= 2

    def test_estimators_package_is_h14_h15_clean(self):
        found = analyze_paths([os.path.join(PKG_DIR, "estimators")],
                              cache_path=None)
        for rule in ("H14", "H15", "H16"):
            assert _unsup(found, rule) == [], \
                [f.render() for f in _unsup(found, rule)]

    def test_logistic_regression_history_still_floats(self):
        """Behavior pin for the drain refactor: objectiveHistory is
        plain python floats, one per iteration, finite."""
        import pyarrow as pa

        from sparkdl_tpu.data import DataFrame
        from sparkdl_tpu.data.tensors import append_tensor_column
        from sparkdl_tpu.estimators import LogisticRegression

        rng = np.random.default_rng(0)
        y = np.arange(16) % 2
        x = rng.normal(size=(16, 4)).astype(np.float32) \
            + 3.0 * y[:, None].astype(np.float32)
        b = pa.RecordBatch.from_pylist(
            [{"label": int(v)} for v in y])
        b = append_tensor_column(b, "features", x)
        model = LogisticRegression(maxIter=3).fit(
            DataFrame.from_batches([b]))
        hist = model.objectiveHistory
        assert len(hist) == 3
        assert all(isinstance(v, float) and np.isfinite(v)
                   for v in hist), hist
        assert hist[-1] <= hist[0], hist

    def test_model_function_call_suppression_is_visible(self):
        """The __call__ aliasing suppression must stay a REPORTED
        H15 suppression, never silently disappear."""
        found = analyze_paths(
            [os.path.join(PKG_DIR, "graph", "function.py")],
            cache_path=None)
        h15 = [f for f in found if f.rule == "H15"]
        assert any(f.suppressed and "alias" in f.suppression.lower()
                   for f in h15), [f.render() for f in h15]
