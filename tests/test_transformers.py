"""Transformer tests — the reference's strongest pattern: pipeline output
vs in-process model oracle (``named_image_test.py``, SURVEY §4.2)."""

import numpy as np
import pyarrow as pa
import pytest

import jax

from sparkdl_tpu.data import DataFrame
from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.models import zoo
from sparkdl_tpu.transformers import (
    DeepImageFeaturizer,
    DeepImagePredictor,
    ImageTransformer,
    KerasImageFileTransformer,
    KerasTransformer,
    TensorTransformer,
)
from sparkdl_tpu.transformers.utils import packImageBatch


@pytest.fixture(scope="module")
def image_df(tmp_path_factory):
    """Mixed-size images on disk, read through readImages."""
    from PIL import Image
    rng = np.random.default_rng(5)
    d = tmp_path_factory.mktemp("tximgs")
    for i, (h, w) in enumerate([(40, 50), (32, 32), (64, 48), (20, 30),
                                (55, 21)]):
        arr = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
        Image.fromarray(arr, "RGB").save(d / f"t{i}.png")
    return imageIO.readImages(str(d), numPartitions=2)


class TestImageTransformer:
    def test_matches_direct_model_oracle(self, image_df):
        mf = zoo.getModelFunction("TestNet")
        t = ImageTransformer(inputCol="image", outputCol="features",
                             modelFunction=mf, batchSize=3)
        got = t.transform(image_df).tensor("features")

        packed = packImageBatch(
            image_df.collect().column("image"), 32, 32, 3)
        expected = np.asarray(mf(packed))
        np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)
        assert t.metrics.rows == 5

    def test_device_resize_matches_device_oracle(self, tmp_path):
        """deviceResizeFrom packs at the native size and resizes inside
        the model's XLA program; output must equal applying the model to
        the fused resize op's output computed directly (the op itself is
        oracle-tested against jax.image.resize in tests/test_ops.py)."""
        import jax.numpy as jnp
        from PIL import Image

        from sparkdl_tpu.ops import fused_resize_normalize

        rng = np.random.default_rng(11)
        d = tmp_path / "uniform"
        d.mkdir()
        native = rng.integers(0, 255, (6, 48, 64, 3), dtype=np.uint8)
        for i, arr in enumerate(native):
            Image.fromarray(arr, "RGB").save(d / f"u{i}.png")
        df = imageIO.readImages(str(d), numPartitions=2)

        mf = zoo.getModelFunction("TestNet")
        t = ImageTransformer(inputCol="image", outputCol="features",
                             modelFunction=mf, batchSize=3,
                             deviceResizeFrom=(48, 64))
        got = t.transform(df).tensor("features")

        resized = fused_resize_normalize(native, (32, 32))
        resized = np.asarray(
            jnp.clip(jnp.round(resized), 0, 255).astype(jnp.uint8))
        expected = np.asarray(mf(resized))
        np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)

    def test_device_resize_rejects_mixed_sizes(self, image_df):
        mf = zoo.getModelFunction("TestNet")
        t = ImageTransformer(inputCol="image", outputCol="features",
                             modelFunction=mf,
                             deviceResizeFrom=(48, 64))
        with pytest.raises(ValueError, match="48, 64"):
            t.transform(image_df).collect()

    def test_device_resize_noop_when_sizes_match(self, image_df):
        """(h, w) equal to the model input size degrades to the plain
        host-packed path (still works on mixed-size input)."""
        mf = zoo.getModelFunction("TestNet")
        t = ImageTransformer(inputCol="image", outputCol="features",
                             modelFunction=mf, deviceResizeFrom=(32, 32))
        base = ImageTransformer(inputCol="image", outputCol="features",
                                modelFunction=mf)
        np.testing.assert_allclose(
            t.transform(image_df).tensor("features"),
            base.transform(image_df).tensor("features"),
            rtol=1e-5, atol=1e-6)

    def test_image_output_mode(self, image_df):
        def invert(x):
            return 255.0 - x.astype("float32")
        mf = ModelFunction.fromSingle(
            invert, None, input_shape=(8, 8, 3), input_dtype=np.uint8,
            input_name="image")
        t = ImageTransformer(inputCol="image", outputCol="inverted",
                             modelFunction=mf, outputMode="image",
                             batchSize=2)
        rows = t.transform(image_df).collect_rows()
        for r in rows:
            out = imageIO.imageStructToArray(r["inverted"])
            assert out.shape == (8, 8, 3)

    def test_empty_partition(self, image_df):
        """A partition whose rows were all filtered out must flow through
        the device stage (regression: reshape(0, -1) crash)."""
        empty = image_df.filter(lambda b: np.zeros(b.num_rows, bool))
        t = ImageTransformer(inputCol="image", outputCol="f",
                             modelFunction=zoo.getModelFunction("TestNet"),
                             batchSize=2)
        out = t.transform(empty).collect()
        assert out.num_rows == 0
        assert "f" in out.schema.names

    def test_non_hwc_model_rejected(self, image_df):
        mf = ModelFunction.fromSingle(lambda x: x, None, input_shape=(4,))
        t = ImageTransformer(inputCol="image", outputCol="o",
                             modelFunction=mf)
        with pytest.raises(ValueError, match="HWC"):
            t.transform(image_df)


class TestNamedImage:
    def test_featurizer_oracle(self, image_df):
        f = DeepImageFeaturizer(inputCol="image", outputCol="features",
                                modelName="TestNet", batchSize=2)
        got = f.transform(image_df).tensor("features")
        assert got.shape == (5, 16)
        mf = zoo.getModelFunction("TestNet")
        packed = packImageBatch(image_df.collect().column("image"),
                                32, 32, 3)
        np.testing.assert_allclose(got, np.asarray(mf(packed)),
                                   rtol=1e-4, atol=1e-5)

    def test_featurizer_unknown_model(self, image_df):
        f = DeepImageFeaturizer(inputCol="image", outputCol="f",
                                modelName="NopeNet")
        with pytest.raises(ValueError, match="unsupported"):
            f.transform(image_df)

    def test_predictor_raw(self, image_df):
        p = DeepImagePredictor(inputCol="image", outputCol="preds",
                               modelName="TestNet", batchSize=2)
        out = p.transform(image_df).tensor("preds")
        assert out.shape == (5, 10)

    def test_predictor_decoded(self, image_df):
        p = DeepImagePredictor(inputCol="image", outputCol="preds",
                               modelName="TestNet",
                               decodePredictions=True, topK=3)
        rows = p.transform(image_df).collect_rows()
        for r in rows:
            assert len(r["preds"]) == 3
            scores = [e["score"] for e in r["preds"]]
            assert scores == sorted(scores, reverse=True)
            # probabilities (keras decode_predictions score scale)
            assert all(0.0 <= s <= 1.0 for s in scores)
            assert all(isinstance(e["description"], str)
                       for e in r["preds"])


def _mlp_model_fn():
    r = np.random.default_rng(3)
    params = {"W": r.normal(size=(4, 2)).astype(np.float32)}

    def apply_fn(p, inputs):
        return {"scores": inputs["feats"] @ p["W"]}

    return ModelFunction(apply_fn, params,
                         {"feats": ((4,), np.float32)},
                         output_names=["scores"])


class TestTensorTransformer:
    def _df(self, n=10):
        r = np.random.default_rng(4)
        x = r.normal(size=(n, 4)).astype(np.float32)
        df = DataFrame.from_table(pa.table({"id": np.arange(n)}), 3)
        return df.with_column("x", lambda b, x=x: x[
            b.column(0).to_numpy(zero_copy_only=False).astype(int)]), x

    def test_apply_and_oracle(self):
        df, x = self._df()
        mf = _mlp_model_fn()
        t = TensorTransformer(modelFunction=mf,
                              inputMapping={"x": "feats"},
                              outputMapping={"scores": "y"},
                              batchSize=4)
        got = t.transform(df).tensor("y")
        np.testing.assert_allclose(got, x @ np.asarray(mf.params["W"]),
                                   rtol=1e-5, atol=1e-6)

    def test_unknown_model_input(self):
        df, _ = self._df()
        t = TensorTransformer(modelFunction=_mlp_model_fn(),
                              inputMapping={"x": "bogus"},
                              outputMapping={"scores": "y"})
        with pytest.raises(ValueError, match="unknown model inputs"):
            t.transform(df)

    def test_tfhparams_feeds_constant_input(self):
        """tfHParams entries feed model inputs of the same name as
        row-broadcast constants (reference TFTransformer.tfHParams,
        SURVEY §2.1 tf_tensor.py)."""
        df, x = self._df()

        def apply_fn(p, inputs):
            return {"scores": inputs["feats"] * inputs["scale"][:, None]}

        mf = ModelFunction(apply_fn, None,
                           {"feats": ((4,), np.float32),
                            "scale": ((), np.float32)},
                           output_names=["scores"])
        t = TensorTransformer(modelFunction=mf,
                              inputMapping={"x": "feats"},
                              outputMapping={"scores": "y"},
                              tfHParams={"scale": 2.5}, batchSize=4)
        got = t.transform(df).tensor("y")
        np.testing.assert_allclose(got, x * 2.5, rtol=1e-5, atol=1e-6)

    def test_tfhparams_validation(self):
        df, _ = self._df()
        t = TensorTransformer(modelFunction=_mlp_model_fn(),
                              inputMapping={"x": "feats"},
                              outputMapping={"scores": "y"},
                              tfHParams={"bogus": 1.0})
        with pytest.raises(ValueError, match="tfHParams references"):
            t.transform(df)
        t2 = TensorTransformer(modelFunction=_mlp_model_fn(),
                               inputMapping={"x": "feats"},
                               outputMapping={"scores": "y"},
                               tfHParams={"feats": 1.0})
        with pytest.raises(ValueError, match="BOTH"):
            t2.transform(df)
        with pytest.raises(TypeError, match="numeric"):
            TensorTransformer(modelFunction=_mlp_model_fn(),
                              inputMapping={"x": "feats"},
                              outputMapping={"scores": "y"},
                              tfHParams={"scale": "not-a-number"})

    def test_tfhparams_shape_mismatch_front_loaded(self):
        """A wrong-shaped constant must fail at validation with the
        param name, not mid-transform as an opaque XLA error."""
        df, _ = self._df()

        def apply_fn(p, inputs):
            return {"scores": inputs["feats"] * inputs["scale"]}

        mf = ModelFunction(apply_fn, None,
                           {"feats": ((4,), np.float32),
                            "scale": ((4,), np.float32)},
                           output_names=["scores"])
        t = TensorTransformer(modelFunction=mf,
                              inputMapping={"x": "feats"},
                              outputMapping={"scores": "y"},
                              tfHParams={"scale": 2.0})  # scalar, not (4,)
        with pytest.raises(ValueError, match=r"tfHParams\['scale'\]"):
            t.transform(df)

    def test_unmapped_input(self):
        df, _ = self._df()
        t = TensorTransformer(modelFunction=_mlp_model_fn(),
                              inputMapping={},
                              outputMapping={"scores": "y"})
        with pytest.raises(ValueError, match="not mapped"):
            t.transform(df)

    def test_unknown_output(self):
        df, _ = self._df()
        t = TensorTransformer(modelFunction=_mlp_model_fn(),
                              inputMapping={"x": "feats"},
                              outputMapping={"bogus": "y"})
        with pytest.raises(ValueError, match="unknown model outputs"):
            t.transform(df)

    def test_missing_column(self):
        df, _ = self._df()
        t = TensorTransformer(modelFunction=_mlp_model_fn(),
                              inputMapping={"nope": "feats"},
                              outputMapping={"scores": "y"})
        with pytest.raises(KeyError):
            t.transform(df).collect()


@pytest.fixture(scope="module")
def keras_file(tmp_path_factory):
    import keras
    m = keras.Sequential([
        keras.layers.Input((6,)),
        keras.layers.Dense(4, activation="relu"),
        keras.layers.Dense(2),
    ])
    path = str(tmp_path_factory.mktemp("km") / "model.keras")
    m.save(path)
    x = np.random.default_rng(6).normal(size=(9, 6)).astype(np.float32)
    return path, x, m.predict(x, verbose=0)


class TestKerasTransformers:
    def test_keras_tensor_oracle(self, keras_file):
        path, x, expected = keras_file
        df = DataFrame.from_table(pa.table({"i": np.arange(len(x))}), 2) \
            .with_column("x", lambda b: x[
                b.column(0).to_numpy(zero_copy_only=False).astype(int)])
        t = KerasTransformer(inputCol="x", outputCol="y", modelFile=path,
                             batchSize=4)
        got = t.transform(df).tensor("y")
        np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)

    def test_keras_image_file_oracle(self, keras_file, tmp_path):
        import keras
        from PIL import Image
        rng = np.random.default_rng(8)
        paths = []
        for i in range(5):
            arr = rng.integers(0, 255, (10, 10, 3), dtype=np.uint8)
            p = tmp_path / f"k{i}.png"
            Image.fromarray(arr, "RGB").save(p)
            paths.append(str(p))

        m = keras.Sequential([
            keras.layers.Input((8, 8, 3)),
            keras.layers.Conv2D(2, 3, activation="relu"),
            keras.layers.GlobalAveragePooling2D(),
        ])
        mpath = str(tmp_path / "imgmodel.keras")
        m.save(mpath)

        def loader(uri):
            img = Image.open(uri).resize((8, 8), Image.BILINEAR)
            return np.asarray(img, np.float32) / 255.0

        df = DataFrame.from_table(pa.table({"uri": paths}), 2)
        t = KerasImageFileTransformer(
            inputCol="uri", outputCol="feats", modelFile=mpath,
            imageLoader=loader, batchSize=2)
        got = t.transform(df).tensor("feats")

        expected = m.predict(np.stack([loader(p) for p in paths]),
                             verbose=0)
        np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)

    def test_loader_mistakes_raise_attributably(self, tmp_path):
        """A loader emitting the wrong size (or ragged sizes) must
        raise errors naming the LOADER and the model — not numpy's
        bare reshape/stack messages (review r5 probe)."""
        import keras

        m = keras.Sequential([keras.layers.Input((8, 8, 3)),
                              keras.layers.Flatten(),
                              keras.layers.Dense(2)])
        mpath = str(tmp_path / "m.keras")
        m.save(mpath)
        df = DataFrame.from_table(pa.table({"uri": ["a", "b"]}), 1)

        wrong = KerasImageFileTransformer(
            inputCol="uri", outputCol="o", modelFile=mpath,
            imageLoader=lambda u: np.zeros((5, 5, 3), np.float32))
        with pytest.raises(ValueError, match="imageLoader.*expects"):
            wrong.transform(df).collect()

        shapes = {"a": (8, 8, 3), "b": (6, 6, 3)}
        ragged = KerasImageFileTransformer(
            inputCol="uri", outputCol="o", modelFile=mpath,
            imageLoader=lambda u: np.zeros(shapes[u], np.float32))
        with pytest.raises(ValueError, match="differing shapes"):
            ragged.transform(df).collect()


class TestTensorTransformerMultiIO:
    def test_multi_input_multi_output(self):
        """Explicit column↔tensor mappings over a 2-in/2-out model
        (reference TFTransformer's core contract)."""
        def apply_fn(params, inputs):
            return {"sum": inputs["a"] + inputs["b"],
                    "diff": inputs["a"] - inputs["b"]}

        mf = ModelFunction(apply_fn, None,
                           {"a": ((3,), np.float32),
                            "b": ((3,), np.float32)},
                           output_names=["sum", "diff"])
        rows = [{"left": [float(i)] * 3, "right": [1.0] * 3}
                for i in range(7)]
        df = DataFrame.from_pylist(rows, num_partitions=2)
        t = TensorTransformer(modelFunction=mf,
                              inputMapping={"left": "a", "right": "b"},
                              outputMapping={"sum": "s", "diff": "d"},
                              batchSize=3)
        out = t.transform(df)
        s = out.tensor("s")
        d = out.tensor("d")
        np.testing.assert_allclose(s[:, 0], np.arange(7) + 1.0)
        np.testing.assert_allclose(d[:, 0], np.arange(7) - 1.0)
        # inputs stay in the frame alongside outputs
        assert set(out.columns) == {"left", "right", "s", "d"}


class TestPayloadMismatchDiagnostics:
    """A frame whose packed payload disagrees with the model (wrong
    size or packedFormat) must fail with a message naming the column
    and both shapes — not a bare numpy reshape error (round-5 probe:
    'cannot reshape array of size 6144 into shape (8,384)')."""

    def _packed_frame(self, tmp_path, fmt):
        from PIL import Image

        from sparkdl_tpu.image import imageIO
        rng = np.random.default_rng(5)
        for i in range(4):
            arr = rng.integers(0, 255, (20, 20, 3), dtype=np.uint8)
            Image.fromarray(arr, "RGB").save(tmp_path / f"x{i}.jpg",
                                             quality=92)
        return imageIO.readImagesPacked(str(tmp_path), (16, 16),
                                        numPartitions=2,
                                        packedFormat=fmt)

    @pytest.mark.parametrize("frame_fmt,model_kw", [
        ("rgb", {"packedFormat": "yuv420"}),   # rgb rows, 420 model
        ("yuv420", {}),                        # 420 rows, rgb model
    ])
    def test_format_mismatch_names_column_and_shapes(self, tmp_path,
                                                     frame_fmt,
                                                     model_kw):
        from sparkdl_tpu.models.zoo import getModelFunction
        from sparkdl_tpu.transformers.tensor_transform import (
            TensorTransformer,
        )
        from sparkdl_tpu.transformers.utils import (
            deviceResizeModel,
            single_io,
        )
        mfp = deviceResizeModel(
            getModelFunction("TestNet", featurize=True), (16, 16),
            **model_kw)
        i_n, o_n = single_io(mfp)
        t = TensorTransformer(modelFunction=mfp,
                              inputMapping={"image": i_n},
                              outputMapping={o_n: "f"}, batchSize=4)
        df = self._packed_frame(tmp_path, frame_fmt)
        with pytest.raises(ValueError,
                           match="'image'.*does not match"):
            t.transform(df).collect()
