"""UDF registry + registerKerasImageUDF tests (reference
``udf/keras_image_model_test.py`` pattern: register, call through the
engine, compare against the in-process model oracle)."""

import numpy as np
import pytest

import sparkdl_tpu.udf as udf_mod
from sparkdl_tpu.data import DataFrame
from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.udf import (
    callUDF,
    getUDF,
    listUDFs,
    makeModelUDF,
    registerKerasImageUDF,
    unregisterUDF,
)


@pytest.fixture(autouse=True)
def _clean_registry():
    yield
    for name in listUDFs():
        unregisterUDF(name)


@pytest.fixture(scope="module")
def image_df(tmp_path_factory):
    from PIL import Image
    rng = np.random.default_rng(11)
    d = tmp_path_factory.mktemp("udfimgs")
    for i, (h, w) in enumerate([(16, 16), (24, 20), (10, 12)]):
        arr = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
        Image.fromarray(arr, "RGB").save(d / f"u{i}.png")
    return imageIO.readImages(str(d), numPartitions=2)


def _double_mf():
    return ModelFunction.fromSingle(
        lambda x: x.astype("float32") * 2.0, None,
        input_shape=(4,), input_dtype=np.float32, name="double")


class TestRegistry:
    def test_register_get_call(self):
        u = makeModelUDF(_double_mf(), "double", kind="tensor")
        assert "double" in listUDFs()
        assert getUDF("double") is u

        df = DataFrame.from_pylist(
            [{"x": [1.0, 2.0, 3.0, 4.0]}, {"x": [0.0, 0.5, 1.0, 1.5]}])
        out = callUDF("double", df, "x", "y").tensor("y")
        np.testing.assert_allclose(
            out, [[2, 4, 6, 8], [0, 1, 2, 3]], rtol=1e-6)

    def test_duplicate_rejected_unless_replace(self):
        makeModelUDF(_double_mf(), "dup")
        with pytest.raises(ValueError, match="already registered"):
            makeModelUDF(_double_mf(), "dup")
        makeModelUDF(_double_mf(), "dup", replace=True)

    def test_unknown_name(self):
        with pytest.raises(KeyError, match="no UDF named"):
            getUDF("nope")

    def test_direct_call(self):
        u = makeModelUDF(_double_mf(), "d2", register=False)
        out = u(np.ones((3, 4), np.float32))
        np.testing.assert_allclose(out, 2 * np.ones((3, 4)))

    def test_unregister(self):
        makeModelUDF(_double_mf(), "gone")
        assert unregisterUDF("gone")
        assert not unregisterUDF("gone")
        assert "gone" not in listUDFs()


@pytest.fixture(scope="module")
def keras_img_model():
    import keras
    m = keras.Sequential([
        keras.layers.Input((12, 12, 3)),
        keras.layers.Flatten(),
        keras.layers.Dense(5, activation="softmax"),
    ])
    return m


class TestRegisterKerasImageUDF:
    def test_matches_keras_oracle(self, keras_img_model, image_df):
        u = registerKerasImageUDF("kudf", keras_img_model)
        out = callUDF("kudf", image_df, "image", "probs")
        got = out.tensor("probs")
        assert got.shape == (3, 5)

        # oracle: pack/resize identically, call the Keras model directly
        from sparkdl_tpu.transformers.utils import packImageBatch
        packed = packImageBatch(image_df.collect().column("image"),
                                12, 12, 3).astype(np.float32)
        expected = np.asarray(keras_img_model(packed))
        np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)

    def test_with_preprocessor(self, keras_img_model, image_df):
        def pre(x):  # scale to [0,1] inside the device program
            return x / 255.0

        registerKerasImageUDF("kpre", keras_img_model, preprocessor=pre)
        got = callUDF("kpre", image_df, "image", "p").tensor("p")

        from sparkdl_tpu.transformers.utils import packImageBatch
        packed = packImageBatch(image_df.collect().column("image"),
                                12, 12, 3).astype(np.float32) / 255.0
        expected = np.asarray(keras_img_model(packed))
        np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)

    def test_from_file(self, keras_img_model, image_df, tmp_path):
        path = str(tmp_path / "m.keras")
        keras_img_model.save(path)
        registerKerasImageUDF("kfile", path)
        got = callUDF("kfile", image_df, "image", "o").tensor("o")
        assert got.shape == (3, 5)

    def test_non_image_model_rejected(self):
        import keras
        m = keras.Sequential([keras.layers.Input((7,)),
                              keras.layers.Dense(2)])
        with pytest.raises(ValueError, match="HWC"):
            registerKerasImageUDF("bad", m)
