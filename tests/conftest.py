"""Test harness config.

Mirrors the reference's test substrate choice (local-mode Spark ≈ SURVEY
§4.1): all "distributed" behavior is tested on a single host with 8
virtual CPU devices via XLA_FLAGS, so multi-chip sharding code paths run
anywhere. Must run before jax is first imported.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("KERAS_BACKEND", "jax")
# Keep TF (used only for reading TF-era artifacts) quiet and off any GPU.
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")

import jax

# The environment's sitecustomize registers the axon TPU plugin and calls
# jax.config.update("jax_platforms", "axon,cpu") at interpreter start,
# overriding JAX_PLATFORMS from the env — force CPU back explicitly.
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)


@pytest.fixture(scope="session")
def image_dir(tmp_path_factory, rng):
    """A directory of small real image files (the reference committed
    tests/resources/images/*.jpg; we synthesize equivalents)."""
    from PIL import Image
    d = tmp_path_factory.mktemp("images")
    sizes = [(32, 48), (64, 64), (21, 33), (128, 96)]
    for i, (h, w) in enumerate(sizes):
        arr = rng.integers(0, 255, size=(h, w, 3), dtype=np.uint8)
        Image.fromarray(arr, "RGB").save(d / f"img_{i}.png")
    # one jpeg and one grayscale png
    arr = rng.integers(0, 255, size=(40, 40, 3), dtype=np.uint8)
    Image.fromarray(arr, "RGB").save(d / "img_jpg.jpg", quality=95)
    arr = rng.integers(0, 255, size=(16, 16), dtype=np.uint8)
    Image.fromarray(arr, "L").save(d / "img_gray.png")
    # one non-image file that must be ignored
    (d / "notes.txt").write_text("not an image")
    return str(d)
