"""imageIO tests — oracle-vs-PIL pattern from the reference's
``python/tests/image/test_imageIO.py`` (SURVEY §4.4)."""

import io

import numpy as np
import pyarrow as pa
import pytest
from PIL import Image

from sparkdl_tpu.image import imageIO


class TestCodecs:
    def test_array_struct_roundtrip(self, rng):
        arr = rng.integers(0, 255, size=(7, 9, 3), dtype=np.uint8)
        s = imageIO.imageArrayToStruct(arr, origin="mem")
        assert (s["height"], s["width"], s["nChannels"]) == (7, 9, 3)
        assert s["mode"] == imageIO.ocvTypes["CV_8UC3"]
        back = imageIO.imageStructToArray(s)
        np.testing.assert_array_equal(back, arr)

    def test_grayscale_and_rgba(self, rng):
        for c in (1, 4):
            arr = rng.integers(0, 255, size=(5, 5, c), dtype=np.uint8)
            s = imageIO.imageArrayToStruct(arr)
            np.testing.assert_array_equal(imageIO.imageStructToArray(s), arr)

    def test_2d_array_promoted(self, rng):
        arr = rng.integers(0, 255, size=(5, 5), dtype=np.uint8)
        s = imageIO.imageArrayToStruct(arr)
        assert s["nChannels"] == 1

    def test_float01_rescaled(self):
        arr = np.full((4, 4, 3), 0.5, dtype=np.float32)
        s = imageIO.imageArrayToStruct(arr)
        assert imageIO.imageStructToArray(s)[0, 0, 0] == 128

    def test_decode_png_matches_pil(self, rng):
        arr = rng.integers(0, 255, size=(11, 13, 3), dtype=np.uint8)
        buf = io.BytesIO()
        Image.fromarray(arr, "RGB").save(buf, format="PNG")
        s = imageIO._decodeImage(buf.getvalue(), origin="x")
        np.testing.assert_array_equal(imageIO.imageStructToArray(s), arr)
        assert s["origin"] == "x"

    def test_decode_garbage_returns_none(self):
        assert imageIO._decodeImage(b"not an image") is None

    def test_size_mismatch_raises(self):
        s = {"height": 2, "width": 2, "nChannels": 3, "data": b"\x00" * 5,
             "mode": 16, "origin": ""}
        with pytest.raises(ValueError):
            imageIO.imageStructToArray(s)


class TestSlicedColumnViews:
    def test_zero_copy_views_respect_arrow_offsets(self):
        """A sliced struct column (non-zero Arrow offset — what
        ``batch.slice``/``limit`` produce) must view the right rows'
        dims and pixels, both per-row and on the same-size fast path."""
        import numpy as np
        import pyarrow as pa

        from sparkdl_tpu.transformers.utils import packImageBatch

        rng = np.random.default_rng(0)
        arrays = [rng.integers(0, 255, (4 + i, 5, 3), dtype=np.uint8)
                  for i in range(6)]
        col = pa.array([imageIO.imageArrayToStruct(a) for a in arrays],
                       type=imageIO.imageType)
        sl = col.slice(2, 3)

        h, w, c, off, vals = imageIO.imageColumnViews(sl)
        assert list(h) == [6, 7, 8]
        for i in range(3):
            np.testing.assert_array_equal(
                vals[off[i]:off[i + 1]].reshape(h[i], w[i], c[i]),
                arrays[2 + i])

        # same-size fast path on a sliced uniform column
        uni = pa.array([imageIO.imageArrayToStruct(a)
                        for a in arrays[:1] * 5], type=imageIO.imageType)
        batch = imageIO.imageColumnToNHWC(uni.slice(1, 3), 4, 5, 3)
        assert batch.shape == (3, 4, 5, 3)
        np.testing.assert_array_equal(batch[0], arrays[0])

        # and the resize pack path — pixel content must match packing
        # the full column and slicing the result (catches row pointers
        # computed from the unsliced buffer start)
        packed = packImageBatch(sl, 5, 5, 3)
        assert packed.shape == (3, 5, 5, 3)
        np.testing.assert_array_equal(packed,
                                      packImageBatch(col, 5, 5, 3)[2:5])


class TestExoticModes:
    """Non-RGB source files must decode to the struct schema's channel
    model (the reference leaned on PIL the same way: everything not
    L/RGB/RGBA converts to RGB)."""

    def _bytes(self, img, fmt):
        import io
        buf = io.BytesIO()
        img.save(buf, fmt)
        return buf.getvalue()

    def test_cmyk_jpeg_and_palette_png(self):
        import numpy as np
        from PIL import Image
        rng = np.random.default_rng(0)

        cmyk = self._bytes(Image.fromarray(
            rng.integers(0, 255, (20, 30, 4), dtype=np.uint8), "CMYK"),
            "JPEG")
        pal = self._bytes(Image.fromarray(
            rng.integers(0, 255, (16, 16), dtype=np.uint8), "L")
            .convert("P"), "PNG")
        # no mode override (deprecated for removal in Pillow 13):
        # fromarray's uint16 typemap already yields I;16
        i16 = self._bytes(Image.fromarray(
            rng.integers(0, 60000, (12, 14), dtype=np.uint16)), "PNG")

        structs = imageIO._decodeBatch(
            ["cmyk", "pal", "i16"], [cmyk, pal, i16])
        assert all(s is not None for s in structs)
        assert (structs[0]["height"], structs[0]["width"],
                structs[0]["nChannels"]) == (20, 30, 3)
        assert structs[1]["nChannels"] == 3   # palette expands to RGB
        assert structs[2]["nChannels"] == 3   # 16-bit converts to RGB

        # the batch (native-eligible) path and the pure-PIL path must
        # produce identical pixels for the CMYK JPEG
        pil = imageIO._decodeImage(cmyk, "cmyk")
        np.testing.assert_array_equal(
            np.frombuffer(structs[0]["data"], np.uint8),
            np.frombuffer(pil["data"], np.uint8))


class TestResize:
    def test_resize_matches_pil_oracle(self, rng):
        arr = rng.integers(0, 255, size=(30, 40, 3), dtype=np.uint8)
        ours = imageIO.resizeImageArray(arr, 15, 20)
        pil = np.asarray(Image.fromarray(arr, "RGB")
                         .resize((20, 15), Image.BILINEAR))
        np.testing.assert_array_equal(ours, pil)

    def test_resize_noop_same_size(self, rng):
        arr = rng.integers(0, 255, size=(8, 8, 3), dtype=np.uint8)
        assert imageIO.resizeImageArray(arr, 8, 8) is arr

    def test_channel_conversions(self, rng):
        gray = rng.integers(0, 255, size=(8, 8, 1), dtype=np.uint8)
        assert imageIO.resizeImageArray(gray, 8, 8, nChannels=3).shape \
            == (8, 8, 3)
        rgba = rng.integers(0, 255, size=(8, 8, 4), dtype=np.uint8)
        assert imageIO.resizeImageArray(rgba, 4, 4, nChannels=3).shape \
            == (4, 4, 3)

    def test_resize_udf_on_dataframe(self, image_dir):
        df = imageIO.readImages(image_dir, numPartitions=2)
        resized = df.with_column(
            "image2", imageIO.createResizeImageUDF((10, 12)))
        for row in resized.collect_rows():
            assert row["image2"]["height"] == 10
            assert row["image2"]["width"] == 12
            assert row["image2"]["nChannels"] == 3


class TestReadImages:
    def test_read_images(self, image_dir):
        df = imageIO.readImages(image_dir, numPartitions=3)
        rows = df.collect_rows()
        assert len(rows) == 6  # 6 images, txt file ignored
        for r in rows:
            img = r["image"]
            assert img["origin"] == r["filePath"]
            arr = imageIO.imageStructToArray(img)
            assert arr.shape == (img["height"], img["width"],
                                 img["nChannels"])

    def test_read_images_content_matches_pil(self, image_dir):
        df = imageIO.readImages(image_dir, numPartitions=2)
        for r in df.collect_rows():
            if not r["filePath"].endswith(".png"):
                continue
            pil = np.asarray(Image.open(r["filePath"]))
            if pil.ndim == 2:
                pil = pil[:, :, None]
            np.testing.assert_array_equal(
                imageIO.imageStructToArray(r["image"]), pil)

    def test_batch_nhwc_conversion(self, rng):
        arrs = [rng.integers(0, 255, (6, 7, 3), dtype=np.uint8)
                for _ in range(4)]
        structs = [imageIO.imageArrayToStruct(a) for a in arrs]
        batch = imageIO.structsToBatch(structs)
        nhwc = imageIO.imageColumnToNHWC(batch.column(0), 6, 7, 3)
        np.testing.assert_array_equal(nhwc, np.stack(arrs))
        # default is a zero-copy view (writability follows the Arrow
        # buffer's provenance — IPC/mmap buffers are read-only);
        # writable=True GUARANTEES a mutable copy that never aliases
        assert not nhwc.flags.owndata  # aliases the Arrow buffer
        w = imageIO.imageColumnToNHWC(batch.column(0), 6, 7, 3,
                                      writable=True)
        assert w.flags.writeable
        w[0, 0, 0, 0] += 1  # must not raise nor write through
        np.testing.assert_array_equal(nhwc, np.stack(arrs))

    def test_struct_to_pil_roundtrip(self, rng):
        for c, mode in ((1, "L"), (3, "RGB"), (4, "RGBA")):
            arr = rng.integers(0, 255, (5, 4, c), dtype=np.uint8)
            pil = imageIO.imageStructToPIL(imageIO.imageArrayToStruct(arr))
            assert pil.mode == mode
            back = np.asarray(pil)
            np.testing.assert_array_equal(
                back if c > 1 else back[:, :, None], arr)

    def test_nhwc_size_mismatch_raises(self, rng):
        structs = [imageIO.imageArrayToStruct(
            rng.integers(0, 255, (6, 7, 3), dtype=np.uint8))]
        batch = imageIO.structsToBatch(structs)
        with pytest.raises(ValueError):
            imageIO.imageColumnToNHWC(batch.column(0), 8, 8, 3)

    def test_files_to_df(self, image_dir):
        paths = imageIO.listImageFiles(image_dir)
        df = imageIO.filesToDF(paths, numPartitions=2)
        rows = df.collect_rows()
        assert len(rows) == len(paths)
        with open(rows[0]["filePath"], "rb") as f:
            assert rows[0]["fileData"] == f.read()
