"""Parallel host pipeline (data/pipeline.py): ordered re-merge under
adversarial scheduling, the shared-memory hand-off, H3 pickle
discipline, serial degrades, the PipelineTarget autotune knobs, and
the ledger's per-worker decode basis.

The ISSUE-15 pins: workers completing out of order, a mid-stream
``LiveBatchHint`` shrink/regrow while fragments are in flight, a
worker raising (typed error surfaces once, remaining rows drain, the
engine quiesces), and exact row-identity/order assertions in each
case.
"""

import os
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from sparkdl_tpu.data import DataFrame, LocalEngine
from sparkdl_tpu.data import pipeline as host_pipeline
from sparkdl_tpu.data.frame import LiveBatchHint, Source
from sparkdl_tpu.obs import default_registry


def _ids_df(ids, parts, engine):
    return DataFrame(
        DataFrame.from_table(pa.table({"id": ids}), parts)._sources,
        engine=engine)


def _collect_ids(table):
    return table.column("id").to_numpy(zero_copy_only=False)


@pytest.fixture
def thread_engine():
    eng = LocalEngine(pipeline_workers=3, pipeline_mode="thread")
    yield eng
    eng.shutdown()


@pytest.fixture
def process_engine():
    # fork context: pytest's __main__ survives spawn too, but fork is
    # the cheap deterministic choice for the suite (workers stay off
    # jax by design — module docstring)
    os.environ["SPARKDL_TPU_PIPELINE_MPCTX"] = "fork"
    eng = LocalEngine(pipeline_workers=2, pipeline_mode="process")
    yield eng
    eng.shutdown()
    os.environ.pop("SPARKDL_TPU_PIPELINE_MPCTX", None)


# ---------------------------------------------------------------------------
# config resolution + degrades
# ---------------------------------------------------------------------------

class TestConfig:
    def test_env_typo_degrades_to_serial(self, monkeypatch):
        monkeypatch.setenv(host_pipeline.ENV_WORKERS, "banana")
        before = default_registry().counter(
            "pipeline.config_errors").value
        assert host_pipeline.resolve_workers(None) == 0
        assert default_registry().counter(
            "pipeline.config_errors").value == before + 1

    def test_env_selects_pooled_mode(self, monkeypatch):
        monkeypatch.setenv(host_pipeline.ENV_WORKERS, "4")
        eng = LocalEngine()
        assert eng.pipeline_workers == 4
        assert eng.pipeline_read_ahead == 8  # 2x workers default
        eng.shutdown()

    def test_read_ahead_typo_degrades(self, monkeypatch):
        monkeypatch.setenv(host_pipeline.ENV_READ_AHEAD, "-3")
        assert host_pipeline.resolve_read_ahead(None, 2) == 4

    def test_one_core_auto_degrades_serial(self, monkeypatch):
        monkeypatch.setattr(os, "cpu_count", lambda: 1)
        before = default_registry().counter(
            "pipeline.degrade_events").value
        assert host_pipeline.effective_workers(4, "auto") == 0
        assert default_registry().counter(
            "pipeline.degrade_events").value == before + 1
        # explicit modes trust the caller (CI correctness drills)
        assert host_pipeline.effective_workers(4, "thread") == 4
        assert host_pipeline.effective_workers(4, "process") == 4

    def test_under_two_workers_is_serial(self):
        assert host_pipeline.effective_workers(0, "thread") == 0
        assert host_pipeline.effective_workers(1, "process") == 0

    def test_serial_engine_never_builds_a_pool(self):
        eng = LocalEngine(pipeline_workers=0)
        ids = np.arange(20)
        out = _ids_df(ids, 4, eng).map_batches(lambda b: b).collect()
        np.testing.assert_array_equal(_collect_ids(out), ids)
        assert eng._pipeline is None
        eng.shutdown()


# ---------------------------------------------------------------------------
# ordered re-merge under adversarial scheduling
# ---------------------------------------------------------------------------

class TestOrderedRemerge:
    def test_out_of_order_completion_stays_ordered(self, thread_engine):
        """Later partitions finish FIRST (sleeps shrink with index);
        the reorder buffer must still yield strict partition order
        with exact row identity."""
        ids = np.arange(90)

        def slow(batch, idx):
            time.sleep(0.03 * (8 - idx) / 8)
            return batch

        out = _ids_df(ids, 9, thread_engine).map_batches(
            slow, with_index=True, name="slow").collect()
        np.testing.assert_array_equal(_collect_ids(out), ids)

    def test_process_mode_roundtrip_exact(self, process_engine):
        ids = np.arange(64)
        out = _ids_df(ids, 5, process_engine).map_batches(
            lambda b: b, name="ident").collect()
        np.testing.assert_array_equal(_collect_ids(out), ids)

    def test_process_mode_shm_handoff_exercised(self, process_engine):
        """Forcing the shared-memory threshold to 0 routes every
        fragment through a segment; identity stays exact and the
        hand-off counters move."""
        process_engine._host_pipeline().shm_min_bytes = 0
        reg = default_registry()
        segs0 = reg.counter("pipeline.shm_segments").value
        bytes0 = reg.counter("pipeline.handoff_bytes").value
        ids = np.arange(48)
        out = _ids_df(ids, 4, process_engine).map_batches(
            lambda b: b).collect()
        np.testing.assert_array_equal(_collect_ids(out), ids)
        assert reg.counter("pipeline.shm_segments").value == segs0 + 4
        assert reg.counter("pipeline.handoff_bytes").value > bytes0

    def test_small_fragments_ride_the_pipe(self, process_engine):
        process_engine._host_pipeline().shm_min_bytes = 1 << 30
        reg = default_registry()
        segs0 = reg.counter("pipeline.shm_segments").value
        ids = np.arange(30)
        out = _ids_df(ids, 3, process_engine).map_batches(
            lambda b: b).collect()
        np.testing.assert_array_equal(_collect_ids(out), ids)
        assert reg.counter("pipeline.shm_segments").value == segs0

    def test_with_index_sees_logical_identity(self, thread_engine):
        """Reordered partitions keep their logical index through the
        pooled path (the with_index determinism contract)."""
        seen = {}

        def record(batch, idx):
            seen[idx] = batch.num_rows
            return batch

        ids = np.arange(40)
        df = _ids_df(ids, 4, thread_engine) \
            .with_partition_order([3, 1, 2, 0]) \
            .map_batches(record, with_index=True)
        out = df.collect()
        assert sorted(seen) == [0, 1, 2, 3]
        expect = np.concatenate([ids[30:], ids[10:20], ids[20:30],
                                 ids[:10]])
        np.testing.assert_array_equal(_collect_ids(out), expect)

    def test_empty_partitions_keep_schema(self, thread_engine):
        df = DataFrame.from_table(pa.table({"id": np.arange(3)}), 1)
        empty = DataFrame(
            [Source(lambda: pa.RecordBatch.from_pylist(
                [], schema=pa.schema([("id", pa.int64())])), 0)]
            + df._sources, engine=thread_engine)
        out = empty.map_batches(lambda b: b).collect()
        assert out.num_rows == 3

    def test_device_stage_rechunk_through_pooled_prefix(
            self, thread_engine):
        """A batch-hinted device stage downstream of the pooled prefix
        still gets hint-aligned blocks spanning partitions, outputs
        re-sliced row-exact."""
        blocks = []

        def dev(batch):
            blocks.append(batch.num_rows)
            return batch

        ids = np.arange(50)
        out = _ids_df(ids, 7, thread_engine).map_batches(
            dev, kind="device", name="dev", batch_hint=16).collect()
        np.testing.assert_array_equal(_collect_ids(out), ids)
        assert all(n == 16 for n in blocks[:-1]), blocks
        assert sum(blocks) == 50


class _Chunky:
    """Duck-typed LiveBatchHint runner stub (the test_autotune
    idiom)."""

    def __init__(self, n):
        self.batch_size = n

    @property
    def preferred_chunk(self):
        return self.batch_size


class TestMidStreamHintThroughPool:
    def test_hint_shrink_regrow_with_fragments_in_flight(
            self, thread_engine):
        """The ISSUE pin: a LiveBatchHint shrink then regrow while
        pooled fragments are still in flight keeps row identity and
        order exact, and the cut follows the moved hint."""
        chunky = _Chunky(8)
        seen = []

        def dev(batch):
            seen.append(batch.num_rows)
            if len(seen) == 1:
                chunky.batch_size = 4       # shrink mid-stream
            elif len(seen) == 3:
                chunky.batch_size = 12      # regrow mid-stream
            return batch

        def slow(batch, idx):
            # out-of-order completion underneath the hint changes
            time.sleep(0.02 * ((idx + 3) % 6) / 6)
            return batch

        ids = np.arange(64)
        out = _ids_df(ids, 8, thread_engine) \
            .map_batches(slow, with_index=True, name="slow") \
            .map_batches(dev, kind="device", name="dev",
                         batch_hint=LiveBatchHint(chunky)).collect()
        np.testing.assert_array_equal(_collect_ids(out), ids)
        assert seen[0] == 8, seen
        assert any(n == 4 for n in seen[1:]), seen
        assert sum(seen) == 64

    def test_hint_change_process_mode(self, process_engine):
        chunky = _Chunky(8)
        seen = []

        def dev(batch):
            seen.append(batch.num_rows)
            if len(seen) == 1:
                chunky.batch_size = 4
            return batch

        ids = np.arange(40)
        out = _ids_df(ids, 5, process_engine).map_batches(
            dev, kind="device", name="dev",
            batch_hint=LiveBatchHint(chunky)).collect()
        np.testing.assert_array_equal(_collect_ids(out), ids)
        assert sum(seen) == 40


# ---------------------------------------------------------------------------
# failure semantics
# ---------------------------------------------------------------------------

class TestWorkerFailure:
    def test_typed_error_surfaces_once_thread(self, thread_engine):
        calls = []

        def boom(batch, idx):
            calls.append(idx)
            if idx == 2:
                raise KeyError("bad column xyz")
            return batch

        df = _ids_df(np.arange(40), 4, thread_engine).map_batches(
            boom, with_index=True)
        with pytest.raises(KeyError, match="bad column xyz"):
            df.collect()

    def test_typed_error_survives_the_process_wire(self, process_engine):
        def boom(batch):
            raise ValueError("decode exploded on purpose")

        df = _ids_df(np.arange(20), 4, process_engine).map_batches(boom)
        with pytest.raises(ValueError, match="decode exploded"):
            df.collect()

    def test_engine_quiesces_and_stays_usable_after_error(
            self, process_engine):
        def boom(batch):
            raise ValueError("boom")

        ids = np.arange(30)
        with pytest.raises(ValueError):
            _ids_df(ids, 3, process_engine).map_batches(boom).collect()
        out = _ids_df(ids, 3, process_engine).map_batches(
            lambda b: b).collect()
        np.testing.assert_array_equal(_collect_ids(out), ids)

    def test_effectful_plan_drains_stragglers(self, thread_engine):
        """The quiesce discipline: an EFFECTFUL plan's in-flight
        siblings complete before control returns after an error — a
        straggler must not produce side effects after the caller's
        cleanup ran."""
        done = []
        started = threading.Semaphore(0)
        release = threading.Event()

        def effectful(batch, idx):
            if idx == 0:
                # raise only once BOTH siblings are genuinely running:
                # a merely-queued future would be cancelled (itself a
                # fine quiesce outcome — no effect at all) and the
                # drain-wait path under test would never exercise
                started.acquire(timeout=5.0)
                started.acquire(timeout=5.0)
                raise ValueError("primary failure")
            started.release()
            release.wait(5.0)
            done.append(idx)
            return batch

        df = _ids_df(np.arange(30), 3, thread_engine).map_batches(
            effectful, with_index=True, effectful=True)

        t = threading.Thread(
            target=lambda: pytest.raises(ValueError, df.collect))
        t.start()
        time.sleep(0.1)
        release.set()
        t.join(10.0)
        assert not t.is_alive()
        # every in-flight sibling drained (read_ahead covered both)
        assert sorted(done) == [1, 2]

    def test_handoff_error_is_typed_transient(self):
        """A vanished shm segment must actually reach the parent-side
        retry: the class docstring promises transient classification,
        so the type has to carry it (resilience/errors.py)."""
        from sparkdl_tpu.resilience.errors import (
            TransientError,
            is_transient,
        )
        assert issubclass(host_pipeline.PipelineHandoffError,
                          TransientError)
        assert is_transient(host_pipeline.PipelineHandoffError("gone"))

    def test_transient_worker_failure_retries_parent_side(
            self, process_engine, tmp_path):
        """A transient error in a pooled worker re-runs through the
        engine's shared RetryPolicy (parent-side re-submit) and the
        partition completes."""
        from sparkdl_tpu.resilience.errors import TransientError

        marker = tmp_path / "fail_once"

        def flaky(batch, idx):
            # cross-process once-latch: the file system is the only
            # state the worker processes share
            if idx == 1 and not marker.exists():
                marker.write_text("failed")
                raise TransientError("transient decode hiccup")
            return batch

        retries0 = default_registry().counter("engine.retries").value
        ids = np.arange(30)
        out = _ids_df(ids, 3, process_engine).map_batches(
            flaky, with_index=True).collect()
        np.testing.assert_array_equal(_collect_ids(out), ids)
        assert default_registry().counter(
            "engine.retries").value > retries0


# ---------------------------------------------------------------------------
# watchdog + obs
# ---------------------------------------------------------------------------

class TestWatchdogAndObs:
    def test_stalled_worker_fires_named_stall_and_recovers(
            self, thread_engine):
        from sparkdl_tpu.obs.watchdog import watchdog

        wd = watchdog()
        wd.arm(threshold_s=0.15)
        reg = default_registry()
        stalls0 = reg.counter("watchdog.stalls").value
        try:
            def wedge(batch, idx):
                if idx == 1:
                    time.sleep(0.6)     # > threshold: a stalled worker
                return batch

            stalled_names = []

            def sample():
                deadline = time.perf_counter() + 5.0
                while time.perf_counter() < deadline:
                    v = wd.verdict()
                    if v["stalled_sources"]:
                        stalled_names.extend(v["stalled_sources"])
                        return
                    time.sleep(0.02)

            sampler = threading.Thread(target=sample)
            sampler.start()
            ids = np.arange(30)
            out = _ids_df(ids, 3, thread_engine).map_batches(
                wedge, with_index=True).collect()
            sampler.join(6.0)
            np.testing.assert_array_equal(_collect_ids(out), ids)
            assert reg.counter("watchdog.stalls").value > stalls0
            # the stall names EXACTLY the wedged partition: queued
            # siblings are unwatched until they run, finished ones
            # unwatch at completion — neither can mis-fire
            assert set(stalled_names) == {"pipeline.decode:1"}, \
                stalled_names
            # completion recovers: nothing left active or stalled
            assert wd.healthy()
        finally:
            wd.disarm()
            wd.arm_from_env()

    def test_pipeline_gauges_and_spans(self, thread_engine):
        from sparkdl_tpu.obs import tracer

        trc = tracer()
        trc.arm()
        try:
            reg = default_registry()
            tasks0 = reg.counter("pipeline.tasks").value
            rows0 = reg.counter("pipeline.rows").value
            ids = np.arange(40)
            out = _ids_df(ids, 4, thread_engine).map_batches(
                lambda b: b).collect()
            assert out.num_rows == 40
            assert reg.counter("pipeline.tasks").value == tasks0 + 4
            assert reg.counter("pipeline.rows").value == rows0 + 40
            assert reg.gauge("pipeline.inflight_peak").value >= 1
            # the merged fragments land on the engine lane
            frags = [s for s in trc.spans()
                     if s.name == "pipeline.fragment"]
            assert len(frags) >= 4
            assert all(s.lane == "engine" for s in frags)
        finally:
            trc.disarm()
            trc.arm_from_env()

    def test_workers_gauge_live_during_stream_and_zero_after(
            self, thread_engine):
        reg = default_registry()
        seen = []

        def probe(batch):
            seen.append(reg.gauge("pipeline.workers").value)
            return batch

        _ids_df(np.arange(20), 2, thread_engine).map_batches(
            probe).collect()
        assert seen and all(v == 3 for v in seen), seen
        assert reg.gauge("pipeline.workers").value == 0

    def test_state_rides_statusz_shape(self, thread_engine):
        _ids_df(np.arange(10), 2, thread_engine).map_batches(
            lambda b: b).collect()
        st = host_pipeline.state()
        for k in ("mode", "workers", "read_ahead", "streams_active",
                  "counters"):
            assert k in st, sorted(st)
        assert st["mode"] == "thread"
        assert st["workers"] == 3
        from sparkdl_tpu.obs import flight
        assert flight.pipeline_state()["mode"] == "thread"


# ---------------------------------------------------------------------------
# H3 pickle discipline
# ---------------------------------------------------------------------------

class TestPickleDiscipline:
    def test_engine_cloudpickle_roundtrip_drops_pools(
            self, process_engine):
        import cloudpickle

        # warm the pool so there is live state to drop
        _ids_df(np.arange(20), 2, process_engine).map_batches(
            lambda b: b).collect()
        assert process_engine._pipeline is not None
        clone = cloudpickle.loads(cloudpickle.dumps(process_engine))
        # config travels ...
        assert clone.pipeline_workers == 2
        assert clone.pipeline_read_ahead == \
            process_engine.pipeline_read_ahead
        assert clone.pipeline_mode == "process"
        # ... pools and locks do not (fresh on arrival)
        assert clone._pipeline is None
        ids = np.arange(20)
        out = _ids_df(ids, 2, clone).map_batches(lambda b: b).collect()
        np.testing.assert_array_equal(_collect_ids(out), ids)
        clone.shutdown()

    def test_host_pipeline_pickle_drops_pools(self, thread_engine):
        import cloudpickle

        _ids_df(np.arange(10), 2, thread_engine).map_batches(
            lambda b: b).collect()
        hp = thread_engine._host_pipeline()
        clone = cloudpickle.loads(cloudpickle.dumps(hp))
        assert clone.mode == "thread"
        assert clone._thread_handle is None
        assert clone._proc_handle is None

    def test_unpicklable_plan_falls_back_to_threads(self):
        """The H3 fallback: a plan that cannot survive the wire (a
        closure over a lock) runs on the THREAD pool — counted, not
        silent, and still ordered-exact."""
        eng = LocalEngine(pipeline_workers=2, pipeline_mode="process")
        lock = threading.Lock()

        def locked(batch):
            with lock:
                return batch

        reg = default_registry()
        fb0 = reg.counter("pipeline.fallbacks").value
        ids = np.arange(30)
        out = _ids_df(ids, 3, eng).map_batches(locked).collect()
        np.testing.assert_array_equal(_collect_ids(out), ids)
        assert reg.counter("pipeline.fallbacks").value == fb0 + 1
        assert host_pipeline.state()["mode"] == "thread"
        eng.shutdown()


# ---------------------------------------------------------------------------
# the ledger's per-worker decode basis
# ---------------------------------------------------------------------------

class TestLedgerDecodeBasis:
    def test_pooled_workers_raise_the_decode_ceiling(self):
        from sparkdl_tpu.obs.ledger import UtilizationLedger

        led = UtilizationLedger(window_s=0.01, probe_file="/dev/null")
        reg = default_registry()
        reg.gauge("pipeline.workers").set(4)
        try:
            led.baseline(now=100.0)
            # 2 busy-seconds in a 1-second window: serial basis would
            # clamp to 1.0; the 4-worker ceiling reads 0.5
            reg.counter("engine.busy_seconds").add(2.0)
            w = led.tick(now=101.0)
            assert w is not None
            assert w["decode_basis"] == "busy/pooled-workers"
            assert w["decode_workers"] == 4
            assert abs(w["util"]["decode"] - 0.5) < 1e-6
        finally:
            reg.gauge("pipeline.workers").set(0)

    def test_stream_ending_mid_window_keeps_its_pooled_basis(self):
        """A pooled stream that finished before the tick already
        banked its N busy-seconds: the window divides by the WINDOW
        PEAK of the worker gauge, not the instantaneous (now 0) read —
        otherwise the window fabricates a saturated serial decode
        verdict right as PipelineTarget reads it as the deepen
        prior."""
        from sparkdl_tpu.obs.ledger import UtilizationLedger

        led = UtilizationLedger(window_s=0.01, probe_file="/dev/null")
        reg = default_registry()
        host_pipeline.consume_workers_peak()   # drain prior history
        led.baseline(now=300.0)
        sid = host_pipeline._enter_stream(4)
        reg.counter("engine.busy_seconds").add(2.0)
        host_pipeline._exit_stream(sid)        # gauge back to 0
        assert reg.gauge("pipeline.workers").value == 0
        w = led.tick(now=301.0)
        assert w is not None
        assert w["decode_basis"] == "busy/pooled-workers"
        assert w["decode_workers"] == 4
        assert abs(w["util"]["decode"] - 0.5) < 1e-6

    def test_baseline_drains_stale_worker_history(self):
        """A pooled experiment that finished BEFORE baseline() must
        not leak its worker count into the next window: a serial
        decode-saturated pass divided by stale workers would
        under-read and hide the decode-bound prior."""
        from sparkdl_tpu.obs.ledger import UtilizationLedger

        led = UtilizationLedger(window_s=0.01, probe_file="/dev/null")
        reg = default_registry()
        sid = host_pipeline._enter_stream(4)
        host_pipeline._exit_stream(sid)        # history pre-baseline
        led.baseline(now=400.0)
        reg.counter("engine.busy_seconds").add(0.9)
        w = led.tick(now=401.0)
        assert w is not None
        assert w["decode_basis"] == "busy-time"
        assert w["decode_workers"] == 1
        assert w["util"]["decode"] >= 0.85

    def test_serial_keeps_busy_time_basis(self):
        from sparkdl_tpu.obs.ledger import UtilizationLedger

        led = UtilizationLedger(window_s=0.01, probe_file="/dev/null")
        reg = default_registry()
        reg.gauge("pipeline.workers").set(0)
        led.baseline(now=200.0)
        reg.counter("engine.busy_seconds").add(0.5)
        w = led.tick(now=201.0)
        assert w is not None
        assert w["decode_basis"] == "busy-time"
        assert w["decode_workers"] == 1
        assert w["util"]["decode"] >= 0.45


# ---------------------------------------------------------------------------
# the PipelineTarget autotune knobs
# ---------------------------------------------------------------------------

class TestPipelineTarget:
    def _target(self, engine, **kw):
        from sparkdl_tpu.autotune import PipelineTarget
        return PipelineTarget(engine, **kw)

    def _feed(self, rows=100):
        default_registry().counter("pipeline.rows").add(rows)
        default_registry().counter("pipeline.stream_seconds").add(1.0)

    def test_knobs_move_engine_attributes(self):
        eng = LocalEngine(pipeline_workers=2)
        t = self._target(eng, max_workers=8)
        workers, read_ahead = t.knobs()
        workers.set(4)
        read_ahead.set(6)
        assert eng.pipeline_workers == 4
        assert eng.pipeline_read_ahead == 6
        eng.shutdown()

    def test_deepens_only_on_decode_prior(self, monkeypatch):
        eng = LocalEngine(pipeline_workers=2)
        t = self._target(eng, max_workers=8)
        monkeypatch.setattr(t, "_ledger_prior", lambda: "link")
        self._feed()
        assert t.propose(False) == []       # first window = baseline
        self._feed()
        assert t.propose(False) == []       # link-bound: vetoed
        monkeypatch.setattr(t, "_ledger_prior", lambda: "decode")
        self._feed()
        props = t.propose(False)
        assert len(props) == 1
        assert props[0].knob.name == "pipeline_workers"
        assert props[0].value == 3
        eng.shutdown()

    def test_trial_reverts_when_gain_does_not_pay(self, monkeypatch):
        eng = LocalEngine(pipeline_workers=2)
        t = self._target(eng, max_workers=8)
        monkeypatch.setattr(t, "_ledger_prior", lambda: "decode")
        self._feed(1000)
        t.propose(False)
        self._feed(1000)
        [p] = t.propose(False)
        p.knob.set(p.value)                 # the controller's apply
        # the next window does NOT pay min_gain -> revert + freeze
        self._feed(1000)
        out = t.propose(False)
        assert any(pr.force and pr.value == 2 for pr in out), \
            [(pr.knob.name, pr.value, pr.force) for pr in out]
        assert t._workers.frozen_for > 0
        eng.shutdown()

    def test_memory_pressure_sheds_read_ahead_then_workers(self):
        eng = LocalEngine(pipeline_workers=3, pipeline_read_ahead=4)
        t = self._target(eng, memory_pressure=lambda: True)
        self._feed()
        t.propose(False)
        self._feed()
        [p] = t.propose(False)
        assert p.knob.name == "pipeline_read_ahead"
        assert p.value == 3
        eng.pipeline_read_ahead = 1
        self._feed()
        [p] = t.propose(False)
        assert p.knob.name == "pipeline_workers"
        assert p.value == 2
        eng.shutdown()

    def test_controller_convergence_zero_oscillations(self, monkeypatch):
        """The CI convergence shape: an armed controller driving the
        target over steady traffic settles without a single refused
        direction flip."""
        from sparkdl_tpu.autotune.core import AutotuneController

        eng = LocalEngine(pipeline_workers=2)
        ctl = AutotuneController(interval_s=0.0)
        ctl.arm(interval_s=0.0)
        t = self._target(eng, max_workers=4)
        monkeypatch.setattr(t, "_ledger_prior", lambda: "decode")
        ctl.attach(t)
        for _ in range(12):
            self._feed(500)
            ctl.step()
        assert ctl.oscillations == 0
        assert 1 <= eng.pipeline_workers <= 4
        assert t._workers.lo <= t._workers.value <= t._workers.hi
        ctl.reset()
        eng.shutdown()

    def test_describe_shape(self):
        eng = LocalEngine(pipeline_workers=2)
        d = self._target(eng).describe()
        assert d["kind"] == "pipeline"
        assert {k["name"] for k in d["knobs"]} == \
            {"pipeline_workers", "pipeline_read_ahead"}
        eng.shutdown()


# ---------------------------------------------------------------------------
# early-stop hygiene
# ---------------------------------------------------------------------------

class TestLiveResize:
    def test_resize_mid_stream_keeps_the_old_generation_alive(self):
        """The autotuner moving ``pipeline_workers`` while a stream is
        mid-flight must not cancel that stream's queued tasks: the
        stream pinned its _PoolHandle generation; the resized pool is
        a NEW generation and the old one only shuts down when its last
        holder releases it."""
        eng = LocalEngine(pipeline_workers=2, pipeline_mode="thread",
                          pipeline_read_ahead=2)
        try:
            ids = np.arange(60)
            it = _ids_df(ids, 6, eng).map_batches(
                lambda b: b, name="slowish").stream()
            first = next(it)          # stream A mid-flight, tasks queued
            hp = eng._host_pipeline()
            gen_a = hp._thread_handle
            assert gen_a is not None and gen_a.refs >= 1

            # the knob moves; stream B runs to completion on the NEW
            # generation while A is still open
            eng.pipeline_workers = 3
            out_b = _ids_df(np.arange(30), 3, eng).map_batches(
                lambda b: b).collect()
            assert out_b.num_rows == 30
            assert hp._thread_handle is not gen_a
            assert gen_a.retired and gen_a.refs >= 1

            # stream A drains its remaining rows intact — nothing was
            # cancelled out from under it
            got = [first] + list(it)
            merged = np.concatenate(
                [_collect_ids(b) for b in got])
            np.testing.assert_array_equal(merged, ids)
            # A's release shut the retired generation down
            assert gen_a.refs == 0
        finally:
            eng.shutdown()


class TestEarlyStop:
    def test_take_abandons_stream_without_leaking_segments(
            self, process_engine):
        """take(1) on a pooled frame abandons in-flight fragments;
        completed-but-unconsumed shared-memory segments must be
        released (pipeline.fragments_discarded counts them)."""
        process_engine._host_pipeline().shm_min_bytes = 0
        ids = np.arange(80)
        rows = _ids_df(ids, 8, process_engine).map_batches(
            lambda b: b).take(1)
        assert rows[0]["id"] == 0
        # the stream generator closed; give abandoned futures a beat
        deadline = time.perf_counter() + 5.0
        reg = default_registry()
        while time.perf_counter() < deadline:
            if reg.gauge("pipeline.inflight").value == 0:
                break
            time.sleep(0.02)
        assert reg.gauge("pipeline.inflight").value == 0
        assert default_registry().gauge("pipeline.workers").value == 0
