"""Infeed-ring tests (L2: device-resident slabs, donation safety,
per-device transfer interleave, warmup/autotune integration).

Pins the PR-16 contracts: content hits dispatch resident slabs and
ship zero bytes; a donated slot can never be re-read
(use-after-donate raises); every degrade — bad env knob, donation
no-op backend, unservable interleave — is counted and warned, never
silent; warmup warms every ring slot at exactly two traced programs;
and the RunnerTarget grows the ring only behind a link-bound ledger
prior."""

import logging

import numpy as np
import pytest

import jax

import sparkdl_tpu.runtime.runner as rmod
from sparkdl_tpu.autotune.targets import RunnerTarget
from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.obs.registry import default_registry
from sparkdl_tpu.runtime.runner import (
    BatchRunner,
    InfeedRing,
    RunnerMetrics,
    dispatch_donated,
    interleaved_device_put,
    resolve_infeed_ring,
    resolve_transfer_interleave,
    warmup_runner,
)

LOGGER = "sparkdl_tpu.runtime.runner"


def _double_fn():
    return ModelFunction.fromSingle(lambda x: x * 2.0, None,
                                    input_shape=(3,))


def _c(name: str) -> float:
    return default_registry().counter(name).value


def _chunk(seed: float, rows: int = 4):
    return {"x": np.full((rows, 3), seed, np.float32)}


# ---------------------------------------------------------------------------
# InfeedRing unit: fingerprint, hit/admit/donate policy, LRU history


class TestInfeedRingUnit:
    def test_depth_floor_raises(self):
        for bad in (0, 1, -2):
            with pytest.raises(ValueError, match="depth"):
                InfeedRing(bad)
        with pytest.raises(ValueError, match="depth"):
            InfeedRing(4).resize(1)

    def test_fingerprint_is_content_addressed(self):
        ring = InfeedRing(2)
        a = {"x": np.arange(12, dtype=np.float32).reshape(4, 3)}
        same = {"x": np.array(a["x"])}           # copy, same content
        assert ring.fingerprint(a) == ring.fingerprint(same)
        # name, dtype, shape, and bytes each break the match
        assert ring.fingerprint(a) != ring.fingerprint(
            {"y": a["x"]})
        assert ring.fingerprint(a) != ring.fingerprint(
            {"x": a["x"].astype(np.float64)})
        assert ring.fingerprint(a) != ring.fingerprint(
            {"x": a["x"].reshape(3, 4)})
        assert ring.fingerprint(a) != ring.fingerprint(
            {"x": a["x"] + 1})
        # non-contiguous views hash like their contiguous copy
        t = np.asfortranarray(a["x"])
        assert ring.fingerprint({"x": t}) == ring.fingerprint(a)

    def test_hit_returns_resident_slab(self):
        ring = InfeedRing(2)
        fp = ring.fingerprint(_chunk(1.0))
        assert ring.get(fp) is None
        assert ring.admit(fp, {"x": "slab"}, 48) is True
        assert ring.get(fp) == {"x": "slab"}
        st = ring.state()
        assert st["depth"] == 2 and st["live"] == 1
        assert st["hits"] == 1 and st["resident_bytes"] == 48

    def test_use_after_donate_raises(self):
        ring = InfeedRing(2)
        fp = ring.fingerprint(_chunk(1.0))
        ring.admit(fp, {"x": "slab"}, 48)
        ring.note_donated(fp)
        with pytest.raises(RuntimeError, match="use-after-donate"):
            ring.get(fp)
        assert ring.state()["donated"] == 1

    def test_admit_capacity_then_donate_through(self):
        ring = InfeedRing(2)
        fps = [ring.fingerprint(_chunk(float(i))) for i in range(3)]
        ring.tick()
        assert ring.admit(fps[0], {"x": 0}, 8) is True
        ring.tick()
        assert ring.admit(fps[1], {"x": 1}, 8) is True
        # every slot recently useful: the third chunk must NOT evict a
        # hot slab — it streams through
        ring.tick()
        assert ring.admit(fps[2], {"x": 2}, 8) is False
        assert ring.get(fps[0]) == {"x": 0}

    def test_admit_reclaims_donated_slot_first(self):
        ring = InfeedRing(2)
        fps = [ring.fingerprint(_chunk(float(i))) for i in range(3)]
        ring.admit(fps[0], {"x": 0}, 8)
        ring.admit(fps[1], {"x": 1}, 8)
        ring.note_donated(fps[0])
        assert ring.admit(fps[2], {"x": 2}, 8) is True
        # the dead slab's index entry is gone (no use-after-donate
        # left to trip) and the newcomer serves hits
        assert ring.get(fps[0]) is None
        assert ring.get(fps[2]) == {"x": 2}

    def test_admit_evicts_stale_slot(self):
        ring = InfeedRing(2)
        fps = [ring.fingerprint(_chunk(float(i))) for i in range(3)]
        ring.admit(fps[0], {"x": 0}, 8)
        ring.admit(fps[1], {"x": 1}, 8)
        for _ in range(2 * ring.depth):
            ring.tick()                  # both slots idle past 2*depth
        assert ring.admit(fps[2], {"x": 2}, 8) is True
        assert ring.get(fps[2]) == {"x": 2}

    def test_retire_all_makes_slots_reclaimable(self):
        ring = InfeedRing(2)
        fps = [ring.fingerprint(_chunk(float(i))) for i in range(3)]
        ring.admit(fps[0], {"x": 0}, 8)
        ring.admit(fps[1], {"x": 1}, 8)
        ring.retire_all()
        # retired slots still serve hits until actually evicted...
        assert ring.get(fps[0]) == {"x": 0}
        ring.retire_all()
        ring.tick()
        # ...but a miss claims one immediately, no 2*depth wait
        assert ring.admit(fps[2], {"x": 2}, 8) is True

    def test_note_shipped_detects_reship_with_bounded_history(self):
        ring = InfeedRing(2)
        fps = [ring.fingerprint(_chunk(float(i)))
               for i in range(70)]
        assert ring.note_shipped(fps[0]) is False
        assert ring.note_shipped(fps[0]) is True      # the re-ship
        for fp in fps[1:]:
            ring.note_shipped(fp)
        # cap = max(64, 8*depth) = 64: fps[0] has been LRU-evicted
        # from the history, so it no longer reads as a re-ship
        assert ring.note_shipped(fps[0]) is False

    def test_resize_grow_keeps_slabs_shrink_drops(self):
        ring = InfeedRing(2)
        fps = [ring.fingerprint(_chunk(float(i))) for i in range(3)]
        ring.admit(fps[0], {"x": 0}, 8)
        ring.admit(fps[1], {"x": 1}, 8)
        ring.resize(4)
        assert ring.depth == 4
        assert ring.get(fps[0]) == {"x": 0}           # grow keeps
        assert ring.admit(fps[2], {"x": 2}, 8) is True
        ring.resize(2)
        assert ring.get(fps[0]) == {"x": 0}
        assert ring.get(fps[2]) is None               # shrink drops


# ---------------------------------------------------------------------------
# Env/ctor resolvers: typos degrade loudly, never raise


class TestRingResolvers:
    def test_env_typo_degrades_loudly(self, monkeypatch, caplog):
        monkeypatch.setattr(rmod, "_WARNED_REASONS", set())
        monkeypatch.setenv("SPARKDL_TPU_INFEED_RING", "bananas")
        c0 = _c("ship.ring_config_errors")
        with caplog.at_level(logging.WARNING, logger=LOGGER):
            assert resolve_infeed_ring(None) == rmod.DEFAULT_INFEED_RING
        assert _c("ship.ring_config_errors") == c0 + 1
        assert any("integer" in r.message for r in caplog.records)

    def test_env_valid_and_ctor_wins(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_TPU_INFEED_RING", "4")
        assert resolve_infeed_ring(None) == 4
        assert resolve_infeed_ring(3) == 3            # ctor beats env
        r = BatchRunner(_double_fn(), batch_size=4)
        assert r.infeed_ring == 4                     # env engages

    def test_negative_depth_degrades_to_off(self, monkeypatch, caplog):
        monkeypatch.setattr(rmod, "_WARNED_REASONS", set())
        c0 = _c("ship.ring_config_errors")
        with caplog.at_level(logging.WARNING, logger=LOGGER):
            assert resolve_infeed_ring(-3) == 0
        assert _c("ship.ring_config_errors") == c0 + 1

    def test_depth_one_clamps_to_floor(self, monkeypatch, caplog):
        monkeypatch.setattr(rmod, "_WARNED_REASONS", set())
        c0 = _c("ship.ring_config_errors")
        with caplog.at_level(logging.WARNING, logger=LOGGER):
            assert resolve_infeed_ring(1) == 2
        assert _c("ship.ring_config_errors") == c0 + 1
        assert any("double-buffer" in r.getMessage()
                   for r in caplog.records)

    def test_interleave_negative_degrades_width_one_is_serial(
            self, monkeypatch, caplog):
        monkeypatch.setattr(rmod, "_WARNED_REASONS", set())
        c0 = _c("ship.ring_config_errors")
        with caplog.at_level(logging.WARNING, logger=LOGGER):
            assert resolve_transfer_interleave(-1) == 0
        assert _c("ship.ring_config_errors") == c0 + 1
        # width 1 IS the serial stream — a no-op, not a degrade
        c1 = _c("ship.ring_config_errors")
        with caplog.at_level(logging.WARNING, logger=LOGGER):
            assert resolve_transfer_interleave(1) == 0
        assert _c("ship.ring_config_errors") == c1
        assert resolve_transfer_interleave(4) == 4

    def test_warn_once_dedupes_log_not_counter(self, monkeypatch,
                                               caplog):
        monkeypatch.setattr(rmod, "_WARNED_REASONS", set())
        c0 = _c("ship.ring_config_errors")
        with caplog.at_level(logging.WARNING, logger=LOGGER):
            resolve_infeed_ring(-1)
            resolve_infeed_ring(-1)
        assert _c("ship.ring_config_errors") == c0 + 2
        assert sum("negative" in r.getMessage()
                   for r in caplog.records) == 1


# ---------------------------------------------------------------------------
# Donation probe: the no-op-backend degrade is counted, never silent


class TestDonationProbe:
    def test_noop_warning_degrades_to_undonated(self, monkeypatch,
                                                caplog):
        import warnings as wmod
        monkeypatch.setattr(rmod, "_DONATION_STATE",
                            {"probed": False, "supported": True})
        monkeypatch.setattr(rmod, "_WARNED_REASONS", set())

        def donate_fn(params, chunk):
            wmod.warn("Some donated buffers were not usable")
            return {"out": chunk["x"] * 2}

        def fn(params, chunk):
            return {"out": chunk["x"] * 3}

        c0 = _c("ship.ring_degrade_events")
        chunk = {"x": np.ones(3, np.float32)}
        with caplog.at_level(logging.WARNING, logger=LOGGER):
            res, donated = dispatch_donated(donate_fn, fn, None, chunk)
        # the probe call itself ran the donated program (semantics are
        # identical) but the verdict is NOT-donated
        assert donated is False
        np.testing.assert_allclose(res["out"], 2.0)
        assert _c("ship.ring_degrade_events") == c0 + 1
        assert any("cannot donate" in r.getMessage()
                   for r in caplog.records)
        # every later call dispatches the UNDONATED program, without
        # re-probing or re-counting
        res2, donated2 = dispatch_donated(donate_fn, fn, None, chunk)
        assert donated2 is False
        np.testing.assert_allclose(res2["out"], 3.0)
        assert _c("ship.ring_degrade_events") == c0 + 1

    def test_clean_probe_keeps_donating(self, monkeypatch):
        monkeypatch.setattr(rmod, "_DONATION_STATE",
                            {"probed": False, "supported": True})

        def donate_fn(params, chunk):
            return {"out": chunk["x"] * 2}

        def fn(params, chunk):          # pragma: no cover - must not run
            raise AssertionError("undonated fallback dispatched")

        c0 = _c("ship.ring_degrade_events")
        chunk = {"x": np.ones(3, np.float32)}
        for _ in range(2):
            res, donated = dispatch_donated(donate_fn, fn, None, chunk)
            assert donated is True
            np.testing.assert_allclose(res["out"], 2.0)
        assert _c("ship.ring_degrade_events") == c0


# ---------------------------------------------------------------------------
# End-to-end: zero re-ship on a steady repeated corpus


class TestSteadyRepeatedCorpus:
    def test_second_pass_ships_zero_bytes_zero_retraces(self):
        r = BatchRunner(_double_fn(), batch_size=4, infeed_ring=2)
        assert r.warmup() is True
        x = np.arange(24, dtype=np.float32).reshape(8, 3)
        # pass 1 pays the placements (warmup retired its synthetic
        # slabs, so both real chunks are admitted immediately)
        np.testing.assert_allclose(r.run({"input": x})["output"], x * 2)
        hits0 = _c("ship.ring_hits")
        reship0 = _c("ship.bytes_reshipped")
        shipped0 = _c("ship.bytes_shipped")
        retrace0 = _c("compile.unexpected_retraces")
        # pass 2, same corpus: every chunk is a content hit — zero
        # bytes cross the link, zero re-ships, zero retraces
        np.testing.assert_allclose(r.run({"input": x})["output"], x * 2)
        assert _c("ship.ring_hits") == hits0 + 2
        assert _c("ship.bytes_reshipped") == reship0
        assert _c("ship.bytes_shipped") == shipped0
        assert _c("compile.unexpected_retraces") == retrace0
        st = r.ring_state()
        assert st is not None
        assert st["depth"] == 2 and st["live"] == 2 and st["hits"] >= 2

    def test_resident_slab_owns_its_bytes(self):
        """A retained slab must survive the host-side pad buffer being
        rewritten (CPU backends may zero-copy alias device_put): after
        running a DIFFERENT corpus through the same staging, the
        original corpus's hit must still return the original rows."""
        r = BatchRunner(_double_fn(), batch_size=4, infeed_ring=4)
        a = np.arange(12, dtype=np.float32).reshape(4, 3)
        b = a + 100.0
        np.testing.assert_allclose(r.run({"input": a})["output"], a * 2)
        np.testing.assert_allclose(r.run({"input": b})["output"], b * 2)
        hits0 = _c("ship.ring_hits")
        np.testing.assert_allclose(r.run({"input": a})["output"], a * 2)
        assert _c("ship.ring_hits") == hits0 + 1


# ---------------------------------------------------------------------------
# Ring wrap-around under mid-stream LiveBatchHint changes


class TestRingLiveBatchHints:
    def test_batch_size_change_between_runs_stays_exact(self):
        r = BatchRunner(_double_fn(), batch_size=4, infeed_ring=2)
        x = np.arange(24, dtype=np.float32).reshape(8, 3)
        np.testing.assert_allclose(r.run({"input": x})["output"], x * 2)
        # a live hint moves the chunk shape mid-stream: old-shape slots
        # can never hit again; the new chunks stream through (or evict
        # stale slots) — rows stay exact either way, nothing raises
        r.batch_size = 3
        np.testing.assert_allclose(r.run({"input": x})["output"], x * 2)
        # back to the original shape: slot turnover staggers across
        # passes (stale eviction is clocked in dispatches), but the
        # ring re-adapts — repeats of the restored corpus serve hits
        # again, and every pass stays row-exact
        r.batch_size = 4
        np.testing.assert_allclose(r.run({"input": x})["output"], x * 2)
        hits0 = _c("ship.ring_hits")
        np.testing.assert_allclose(r.run({"input": x})["output"], x * 2)
        assert _c("ship.ring_hits") >= hits0 + 1
        assert r.ring_state()["live"] == 2


# ---------------------------------------------------------------------------
# Warmup: every slot warmed, exactly two traced programs


class TestWarmupRing:
    def test_warmup_fills_every_slot_trace_count_pinned(
            self, monkeypatch):
        # pin the donation verdict so the overflow batch deterministically
        # dispatches the DONATED program (the natural probe's verdict is
        # platform-dependent)
        monkeypatch.setattr(rmod, "_DONATION_STATE",
                            {"probed": True, "supported": True})

        def _warm(depth):
            calls = {"n": 0}

            def f(x):
                calls["n"] += 1         # fires at TRACE time only
                return x * 2.0

            mf = ModelFunction.fromSingle(f, None, input_shape=(3,))
            r = BatchRunner(mf, batch_size=4, infeed_ring=depth)
            assert warmup_runner(r) is True
            st = r.ring_state()
            assert st["depth"] == depth and st["slots"] == depth
            assert st["live"] == depth  # every slot warmed
            donations = _c("ship.ring_donations")
            return calls["n"], donations

        d0 = _c("ship.ring_donations")
        traces_k2, after_k2 = _warm(2)
        traces_k4, after_k4 = _warm(4)
        # every warm batch shares ONE device shape: at most the
        # undonated + donated programs trace, and the count is pinned
        # INDEPENDENT of ring depth (jax may share the jaxpr between
        # the two — donation changes lowering, not tracing)
        assert traces_k2 == traces_k4 <= 2
        # each warmup's overflow batch streamed through donated —
        # compiled here, never at a steady-state request
        assert after_k2 == d0 + 1 and after_k4 == d0 + 2

    def test_warmup_retires_slots_so_real_corpus_admits(self):
        r = BatchRunner(_double_fn(), batch_size=4, infeed_ring=2)
        assert r.warmup() is True
        x = np.arange(12, dtype=np.float32).reshape(4, 3) + 7.0
        donations0 = _c("ship.ring_donations")
        np.testing.assert_allclose(r.run({"input": x})["output"], x * 2)
        hits0 = _c("ship.ring_hits")
        np.testing.assert_allclose(r.run({"input": x})["output"], x * 2)
        # the first real chunk was ADMITTED (warmup slabs retired), so
        # the repeat is a hit — it did not donate-through behind
        # synthetic warmth
        assert _c("ship.ring_hits") == hits0 + 1
        assert _c("ship.ring_donations") == donations0


# ---------------------------------------------------------------------------
# interleaved_device_put: row identity, serial no-op, loud degrade


class TestInterleavedDevicePut:
    def test_row_identity_across_devices(self):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec
        devs = jax.devices()
        assert len(devs) >= 2           # conftest forces 8 virtual
        mesh = Mesh(np.array(devs), ("d",))
        sh = NamedSharding(mesh, PartitionSpec("d"))
        x = np.arange(len(devs) * 4, dtype=np.float32).reshape(
            len(devs), 4)
        out = interleaved_device_put({"x": x}, sh, 4)
        assert out is not None
        np.testing.assert_array_equal(np.asarray(out["x"]), x)
        assert out["x"].sharding.is_equivalent_to(sh, x.ndim)

    def test_single_device_sharding_is_serial_not_a_degrade(
            self, monkeypatch, caplog):
        monkeypatch.setattr(rmod, "_WARNED_REASONS", set())
        sh = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        d0 = _c("ship.interleave_degrade_events")
        x = np.ones((4, 3), np.float32)
        with caplog.at_level(logging.WARNING, logger=LOGGER):
            out = interleaved_device_put({"x": x}, sh, 4)
        np.testing.assert_array_equal(np.asarray(out["x"]), x)
        assert _c("ship.interleave_degrade_events") == d0
        assert not caplog.records

    def test_unservable_sharding_degrades_loudly(self, monkeypatch,
                                                 caplog):
        monkeypatch.setattr(rmod, "_WARNED_REASONS", set())

        class _BadSharding:
            def addressable_devices_indices_map(self, shape):
                raise NotImplementedError("no shard map here")

        d0 = _c("ship.degrade_events")
        i0 = _c("ship.interleave_degrade_events")
        with caplog.at_level(logging.WARNING, logger=LOGGER):
            out = interleaved_device_put(
                {"x": np.ones((4, 3), np.float32)}, _BadSharding(), 2)
        assert out is None
        assert _c("ship.degrade_events") == d0 + 1
        assert _c("ship.interleave_degrade_events") == i0 + 1
        assert any("interleave" in r.getMessage()
                   for r in caplog.records)


# ---------------------------------------------------------------------------
# Sharded runner: the ring over placed sharded slabs


class TestShardedRunnerRing:
    def test_sharded_steady_pass_zero_reship(self):
        from sparkdl_tpu.parallel.inference import ShardedBatchRunner
        r = ShardedBatchRunner(_double_fn(), batch_size=1,
                               infeed_ring=2)
        n = 2 * r.preferred_chunk       # a corpus that fits the ring
        x = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
        np.testing.assert_allclose(r.run({"input": x})["output"], x * 2)
        hits0 = _c("ship.ring_hits")
        reship0 = _c("ship.bytes_reshipped")
        shipped0 = _c("ship.bytes_shipped")
        np.testing.assert_allclose(r.run({"input": x})["output"], x * 2)
        assert _c("ship.ring_hits") == hits0 + 2
        assert _c("ship.bytes_reshipped") == reship0
        assert _c("ship.bytes_shipped") == shipped0
        st = r.ring_state()
        assert st is not None and st["live"] == 2

    def test_record_run_feeds_shipped_override(self):
        mf = _double_fn()
        c0 = _c("ship.bytes_shipped")
        rmod.record_run_feeds(mf, {"input": np.ones((64, 3),
                                                    np.float32)},
                              0.01, 0.0, batches=1, shipped_bytes=123)
        # the override IS the link traffic — not the input-sum bytes
        assert _c("ship.bytes_shipped") == c0 + 123


# ---------------------------------------------------------------------------
# RunnerTarget: ring knobs behind a link-bound ledger prior


class _RingStubRunner:
    def __init__(self, **kw):
        self.strategy = "prefetch"
        self.max_inflight = 8
        self.prefetch_depth = 1
        self.infeed_ring = 0
        self.transfer_interleave = 0
        self.metrics = RunnerMetrics()
        self.__dict__.update(kw)


class _BareStubRunner:
    """The pre-ring runner surface (prebuilt custom runners, old
    pickles): no infeed_ring / transfer_interleave attributes."""

    def __init__(self):
        self.strategy = "prefetch"
        self.max_inflight = 8
        self.prefetch_depth = 1
        self.metrics = RunnerMetrics()


def _busy_window(t, wait=0.001):
    """One quiet traffic window: rows moved, negligible transfer wait
    (so the wait_frac path stays out of the way of the link prior)."""
    t.runner.metrics.add(1000, 10, 1.0, transfer_wait_seconds=wait)
    return t.propose(warming=False)


class TestRunnerTargetRingKnobs:
    def test_link_prior_grows_ring_to_the_k2_floor(self):
        t = RunnerTarget(_RingStubRunner())
        t._ledger_prior = lambda: "link"
        assert _busy_window(t) == []    # baseline window
        out = _busy_window(t)
        assert [p.knob.name for p in out] == ["infeed_ring"]
        assert out[0].value == 2        # 0 -> 2 jumps the K>=2 floor
        assert "link" in out[0].reason

    def test_ring_at_cap_widens_interleave(self):
        t = RunnerTarget(_RingStubRunner(infeed_ring=8))
        t._ledger_prior = lambda: "link"
        _busy_window(t)
        out = _busy_window(t)
        assert [p.knob.name for p in out] == ["transfer_interleave"]
        assert out[0].value == 2
        assert "transfer streams" in out[0].reason

    def test_no_link_prior_no_ring_move(self):
        for prior in ("decode", "compute", None):
            t = RunnerTarget(_RingStubRunner())
            t._ledger_prior = lambda p=prior: p
            _busy_window(t)
            assert _busy_window(t) == []

    def test_wait_frac_path_still_wins_the_window(self):
        """One move per window: while transfer waits dominate, the
        existing overlap trial fires and the ring stays untouched."""
        t = RunnerTarget(_RingStubRunner())
        t._ledger_prior = lambda: "link"
        _busy_window(t, wait=0.5)
        out = _busy_window(t, wait=0.5)
        assert [p.knob.name for p in out] == ["prefetch_depth"]

    def test_bare_runner_tunes_exactly_as_before(self):
        t = RunnerTarget(_BareStubRunner())
        assert [k.name for k in t.knobs()] == ["max_inflight",
                                               "prefetch_depth"]
        t._ledger_prior = lambda: "link"
        _busy_window(t)
        assert _busy_window(t) == []    # no ring knobs to move

    def test_ring_runner_exposes_four_knobs(self):
        t = RunnerTarget(_RingStubRunner())
        assert [k.name for k in t.knobs()] == [
            "max_inflight", "prefetch_depth", "infeed_ring",
            "transfer_interleave"]
