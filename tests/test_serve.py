"""Online serving layer tests (docs/SERVING.md).

The contract under test, per the serving spec:

* dynamic micro-batching — small concurrent requests coalesce into
  ``preferred_chunk``-aligned device batches; a request larger than
  the chunk splits across micro-batches and reassembles in order;
* admission control — a saturated bounded queue rejects with the
  typed ``ServerOverloaded`` (no unbounded growth, no deadlock), and
  requests whose deadline passes while queued fail with
  ``DeadlineExceeded`` BEFORE dispatch;
* warmup — after ``warmup()`` the first submit performs no new jit
  trace (pinned by a trace-count test);
* quiesce — graceful drain completes everything admitted; a
  non-draining close fails the queue with ``ServerClosed``;
* observability — ``serve``-lane spans + ``serve.*`` registry
  metrics that MATCH observed outcomes;
* pickle — the server follows the StageMetrics drop-and-recreate
  discipline (workers/locks/queues dropped; config/runners travel).
"""

import threading
import time

import numpy as np
import pytest

from sparkdl_tpu.graph.function import ModelFunction
from sparkdl_tpu.obs import default_registry, tracer
from sparkdl_tpu.runtime.runner import BatchRunner
from sparkdl_tpu.serve import (
    DeadlineExceeded,
    ModelServer,
    ServeConfig,
    ServerClosed,
    ServerOverloaded,
)


def _double_fn():
    return ModelFunction.fromSingle(lambda x: x * 2.0, None,
                                    input_shape=(3,))


def _slow_host_fn(delay_s):
    """Host-backend model sleeping per chunk — a deterministic
    capacity knob for saturation/deadline tests (no jit, no device)."""
    def apply(params, inputs):
        time.sleep(delay_s)
        return {"y": np.asarray(inputs["x"], np.float32) + 1.0}
    return ModelFunction(apply, None, {"x": ((3,), np.float32)},
                         output_names=["y"], backend="host")


def _server(mf=None, *, batch_size=8, **cfg):
    server = ModelServer(ServeConfig(**cfg))
    server.register("m", mf or _double_fn(), batch_size=batch_size)
    return server


class TestSubmitBasics:
    def test_roundtrip_single_full_chunk(self):
        with _server(batch_size=4) as server:
            x = np.arange(12, dtype=np.float32).reshape(4, 3)
            out = server.submit({"input": x}).result(timeout=30)
            np.testing.assert_allclose(out["output"], x * 2)

    def test_small_requests_coalesce_into_one_batch(self):
        # window generous vs. sub-ms submit spacing: all four 2-row
        # requests land in ONE 8-row micro-batch
        server = _server(batch_size=8, max_wait_s=0.5)
        futs = [server.submit(
            {"input": np.full((2, 3), i, np.float32)})
            for i in range(4)]
        for i, f in enumerate(futs):
            np.testing.assert_allclose(
                f.result(timeout=30)["output"], 2.0 * i)
        server.close()
        m = server.metrics
        assert m.batches == 1, m.as_dict()
        assert m.batch_fill_ratio == 1.0
        assert m.requests == 4 and m.rows == 8

    def test_large_request_splits_and_reassembles_in_order(self):
        server = _server(batch_size=4, max_wait_s=0.0)
        x = np.arange(30, dtype=np.float32).reshape(10, 3)
        out = server.submit({"input": x}).result(timeout=30)
        np.testing.assert_allclose(out["output"], x * 2)  # row order
        server.close()
        assert server.metrics.batches == 3  # 4 + 4 + 2
        assert server.metrics.rows == 10

    def test_zero_row_submission_resolves_immediately(self):
        with _server(batch_size=4) as server:
            fut = server.submit(
                {"input": np.zeros((0, 3), np.float32)})
            out = fut.result(timeout=1)
            # schema-correct empties via empty_jax_outputs: the output
            # row shape, zero rows
            assert out["output"].shape == (0, 3)
            assert out["output"].dtype == np.float32

    def test_zero_row_submission_honors_close_and_signature(self):
        """The N=0 fast path must not bypass the server contracts:
        closed is closed, and declared inputs must be present even
        when empty."""
        server = _server(batch_size=4)
        with pytest.raises(ValueError, match="missing"):
            server.submit({"bogus": np.zeros((0, 5), np.float32)})
        server.close()
        with pytest.raises(ServerClosed):
            server.submit({"input": np.zeros((0, 3), np.float32)})

    def test_signature_validated_at_submit(self):
        with _server() as server:
            with pytest.raises(ValueError, match="missing from"):
                server.submit({"wrong": np.zeros((2, 3), np.float32)})
            with pytest.raises(ValueError, match="expects"):
                server.submit({"input": np.zeros((2, 5), np.float32)})

    def test_float64_caller_does_not_invalidate_warmup(self):
        """Inputs cast to the signature dtype at admission: a sloppy
        float64 caller must reuse the warmed float32 program, not
        trigger a retrace (and get float32-typed results back)."""
        traces = []

        def fn(x):
            traces.append(1)
            return x * 2.0

        server = _server(ModelFunction.fromSingle(fn, None,
                                                  input_shape=(3,)),
                         batch_size=4)
        server.warmup()
        out = server.submit(
            {"input": np.ones((4, 3), np.float64)}).result(timeout=30)
        np.testing.assert_allclose(out["output"], 2.0)
        server.close()
        assert len(traces) == 1, "float64 submit re-traced the program"

    def test_multi_model_registry_routes_by_name(self):
        server = ModelServer(ServeConfig())
        server.register("double", _double_fn(), batch_size=4)
        server.register("halve", ModelFunction.fromSingle(
            lambda x: x / 2.0, None, input_shape=(3,)), batch_size=4)
        with pytest.raises(ValueError, match="pass model="):
            server.submit({"input": np.ones((1, 3), np.float32)})
        with pytest.raises(ValueError, match="unknown model"):
            server.submit({"input": np.ones((1, 3), np.float32)},
                          model="nope")
        x = np.ones((2, 3), np.float32)
        np.testing.assert_allclose(
            server.submit({"input": x}, model="double")
            .result(timeout=30)["output"], 2.0)
        np.testing.assert_allclose(
            server.submit({"input": x}, model="halve")
            .result(timeout=30)["output"], 0.5)
        with pytest.raises(ValueError, match="already registered"):
            server.register("double", _double_fn())
        server.close()


class TestWarmup:
    def test_first_submit_after_warmup_performs_no_new_trace(self):
        """THE warmup contract: jit traces call the Python fn once per
        compilation — count those calls. After warmup() the first
        submit must hit the compiled cache (every serve dispatch is
        one padded preferred_chunk shape, so one zeros run covers
        it)."""
        traces = []

        def fn(x):
            traces.append(threading.get_ident())
            return x * 2.0

        mf = ModelFunction.fromSingle(fn, None, input_shape=(3,))
        server = _server(mf, batch_size=8)
        assert server.warmup() == {"m": True}
        assert len(traces) == 1, "warmup should trace exactly once"
        out = server.submit(
            {"input": np.ones((3, 3), np.float32)}).result(timeout=30)
        np.testing.assert_allclose(out["output"], 2.0)
        server.close()
        assert len(traces) == 1, \
            "first submit after warmup re-traced the program"

    def test_host_backend_warmup_is_a_noop(self):
        server = _server(_slow_host_fn(0.0), batch_size=4)
        assert server.warmup() == {"m": False}
        out = server.submit(
            {"x": np.zeros((2, 3), np.float32)}).result(timeout=30)
        np.testing.assert_allclose(out["y"], 1.0)
        server.close()


class TestBackpressure:
    def test_oversized_request_rejected_outright(self):
        with _server(max_queue_rows=8) as server:
            with pytest.raises(ServerOverloaded, match="never"):
                server.submit(
                    {"input": np.zeros((9, 3), np.float32)})
        assert server.metrics.rejections == 1

    def test_saturated_queue_rejects_with_typed_error(self):
        # capacity ~4 rows/50ms; queue bounded at 8 rows — the third+
        # immediate 4-row submit must be rejected, not queued
        server = _server(_slow_host_fn(0.05), batch_size=4,
                         max_queue_rows=8, max_wait_s=0.0)
        accepted, rejected = [], 0
        for _ in range(8):
            try:
                accepted.append(server.submit(
                    {"x": np.zeros((4, 3), np.float32)}))
            except ServerOverloaded:
                rejected += 1
        assert rejected > 0
        for f in accepted:
            np.testing.assert_allclose(
                f.result(timeout=30)["y"], 1.0)
        server.close()
        assert server.metrics.rejections == rejected
        assert server.metrics.requests == len(accepted)

    def test_deadline_expired_request_fails_before_dispatch(self):
        # first request occupies the dispatcher ~0.2s; the second's
        # 10ms deadline passes while queued → DeadlineExceeded, and
        # the model never sees its rows
        seen_rows = []

        def apply(params, inputs):
            seen_rows.append(len(inputs["x"]))
            time.sleep(0.2)
            return {"y": np.asarray(inputs["x"], np.float32)}
        mf = ModelFunction(apply, None, {"x": ((3,), np.float32)},
                           output_names=["y"], backend="host")
        server = _server(mf, batch_size=4, max_wait_s=0.0)
        first = server.submit({"x": np.zeros((4, 3), np.float32)})
        time.sleep(0.05)        # first is now dispatching
        doomed = server.submit({"x": np.ones((4, 3), np.float32)},
                               deadline=0.01)
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=30)
        first.result(timeout=30)
        server.close()
        assert server.metrics.deadline_misses == 1
        assert sum(seen_rows) == 4, \
            "the expired request's rows reached the model"

    def test_expired_request_fails_promptly_not_after_the_window(self):
        """Once an expired request is detected, collect() must return
        at once — the dead request's failure (and any live parts
        already held, dispatched as a partial batch) must not sit out
        a long max_wait_s window."""
        server = _server(_slow_host_fn(0.2), batch_size=4,
                         max_wait_s=2.0)
        t0 = time.perf_counter()
        server.submit({"x": np.zeros((4, 3), np.float32)})
        time.sleep(0.05)        # dispatcher is now busy ~0.2s
        doomed = server.submit({"x": np.ones((2, 3), np.float32)},
                               deadline=0.01)
        live = server.submit({"x": np.full((1, 3), 7.0, np.float32)})
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=30)
        np.testing.assert_allclose(live.result(timeout=30)["y"], 8.0)
        elapsed = time.perf_counter() - t0
        server.close()
        assert elapsed < 1.0, \
            f"expired request held through the coalesce window " \
            f"({elapsed:.2f}s)"

    def test_nonpositive_deadline_fails_fast(self):
        with _server() as server:
            fut = server.submit({"input": np.ones((1, 3), np.float32)},
                                deadline=0.0)
            with pytest.raises(DeadlineExceeded, match="not in the"):
                fut.result(timeout=1)
        assert server.metrics.deadline_misses == 1


class TestSaturationSoak:
    def test_multithreaded_saturation_no_deadlock_counters_match(self):
        """The acceptance scenario: offered load > capacity against a
        bounded queue from many threads. Every submit must either be
        admitted (and then complete or fail with a deadline error) or
        be rejected with ServerOverloaded; the queue never grows past
        its bound; the serve.* counters match the observed outcomes;
        and the whole thing finishes (join timeouts are the deadlock
        canary)."""
        server = _server(_slow_host_fn(0.01), batch_size=8,
                         max_queue_rows=32, max_wait_s=0.005,
                         default_deadline_s=5.0)
        n_threads, per_thread, rows = 4, 30, 4
        futures, lock = [], threading.Lock()
        outcomes = {"rejected": 0}

        def fire(tid):
            x = np.full((rows, 3), float(tid), np.float32)
            for _ in range(per_thread):
                try:
                    f = server.submit({"x": x})
                except ServerOverloaded:
                    with lock:
                        outcomes["rejected"] += 1
                else:
                    with lock:
                        futures.append((tid, f))
        threads = [threading.Thread(target=fire, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "submitter deadlocked"

        completed = missed = 0
        for tid, f in futures:
            try:
                out = f.result(timeout=60)
            except DeadlineExceeded:
                missed += 1
            else:
                completed += 1
                np.testing.assert_allclose(out["y"], float(tid) + 1.0)
        server.close()

        assert outcomes["rejected"] > 0, \
            "offered load never saturated the queue"
        assert completed > 0
        m = server.metrics
        assert m.rejections == outcomes["rejected"]
        assert m.deadline_misses == missed
        assert m.requests == len(futures)
        assert m.rows == len(futures) * rows
        # the published registry view matches the per-server metrics
        snap = default_registry().snapshot()
        assert snap["serve.rejections"] == outcomes["rejected"]
        assert snap["serve.deadline_misses"] == missed
        assert snap["serve.queue_rows"] == 0.0
        assert 0.0 < m.batch_fill_ratio <= 1.0
        assert m.latency_seconds(0.99) >= m.latency_seconds(0.5) > 0.0


class TestQuiesce:
    def test_graceful_drain_completes_admitted_work(self):
        server = _server(_slow_host_fn(0.02), batch_size=4,
                         max_wait_s=0.0, max_queue_rows=64)
        futs = [server.submit({"x": np.zeros((2, 3), np.float32)})
                for _ in range(6)]
        server.close(drain=True)
        for f in futs:
            np.testing.assert_allclose(f.result(timeout=1)["y"], 1.0)
        with pytest.raises(ServerClosed):
            server.submit({"x": np.zeros((2, 3), np.float32)})
        server.close()  # idempotent

    def test_non_draining_close_fails_queued_requests(self):
        server = _server(_slow_host_fn(0.1), batch_size=4,
                         max_wait_s=0.0, max_queue_rows=64)
        futs = [server.submit({"x": np.zeros((4, 3), np.float32)})
                for _ in range(5)]
        server.close(drain=False)
        outcomes = {"ok": 0, "closed": 0}
        for f in futs:
            try:
                f.result(timeout=30)
                outcomes["ok"] += 1
            except ServerClosed:
                outcomes["closed"] += 1
        # whatever was already dispatched completes; the rest fail
        # with the typed shutdown error — nothing hangs, nothing lost
        assert outcomes["closed"] > 0
        assert outcomes["ok"] + outcomes["closed"] == 5

    def test_dispatch_failure_fails_its_requests_not_the_server(self):
        calls = []

        def apply(params, inputs):
            calls.append(1)
            if len(calls) == 1:
                raise RuntimeError("transient model failure")
            return {"y": np.asarray(inputs["x"], np.float32)}
        mf = ModelFunction(apply, None, {"x": ((3,), np.float32)},
                           output_names=["y"], backend="host")
        server = _server(mf, batch_size=4, max_wait_s=0.0)
        bad = server.submit({"x": np.zeros((4, 3), np.float32)})
        with pytest.raises(RuntimeError, match="transient"):
            bad.result(timeout=30)
        good = server.submit({"x": np.zeros((4, 3), np.float32)})
        np.testing.assert_allclose(good.result(timeout=30)["y"], 0.0)
        server.close()

    def test_close_publishes_the_final_partial_window(self):
        """Rows admitted after the dispatcher's last per-batch publish
        (here: admitted and never dispatched at all — the worker is
        pinned off and close(drain=False) abandons the queue) must
        still land in the registry via the close()-time publish;
        before it, the last window was simply lost."""
        server = _server(_double_fn(), batch_size=8, max_wait_s=0.0,
                         max_queue_rows=64)
        session = server.session("m")
        # deterministic "admitted but never dispatched": no worker
        session._ensure_worker = lambda: None
        fut = server.submit({"input": np.zeros((3, 3), np.float32)})
        snap = default_registry().snapshot()
        # nothing published yet for this window (only live gauges)
        assert snap["serve.queue_rows"] == 3.0
        server.close(drain=False)
        with pytest.raises(ServerClosed):
            fut.result(timeout=1)
        snap = default_registry().snapshot()
        assert snap["serve.requests"] == server.metrics.requests
        assert snap["serve.rows"] == server.metrics.rows
        assert server.metrics.rows >= 3


class TestMeshSessions:
    def test_sharded_session_serves_and_takes_collective_launch(self):
        """A model-parallel mesh session dispatches through
        ShardedBatchRunner.run, which takes the collective launch lock
        — the armed trace must show collective_lock_wait inside the
        serve dispatch, and the session must report itself
        collective."""
        from sparkdl_tpu.parallel.mesh import MeshSpec, make_mesh

        trc = tracer()
        trc.clear()
        trc.arm()
        try:
            server = ModelServer(ServeConfig(max_wait_s=0.0))
            session = server.register(
                "mesh", _double_fn(),
                mesh=make_mesh(MeshSpec(data=-1, model=2)),
                batch_size=1)
            assert session.collective is True
            server.warmup()
            n = session.chunk
            x = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
            out = server.submit({"input": x}).result(timeout=60)
            np.testing.assert_allclose(out["output"], x * 2)
            server.close()
            names = {s.name for s in trc.spans()}
            lanes = {s.lane for s in trc.spans()}
            assert "serve" in lanes
            assert "collective_lock_wait" in names
        finally:
            trc.arm_from_env()
            trc.clear()

    def test_pure_dp_session_is_not_collective(self):
        server = ModelServer(ServeConfig())
        session = server.register("dp", _double_fn(), mesh=None,
                                  batch_size=4)
        assert session.collective is False
        server.close()


class TestObservability:
    def test_serve_lane_spans_and_report(self):
        """An armed serve run records enqueue/coalesce/dispatch spans
        on the serve lane, and the report CLI summarizes them through
        the SAME per-lane machinery as the pipeline lanes — coalesce
        shows up as a wait-shaped stall."""
        import json

        from sparkdl_tpu.obs.report import summarize

        trc = tracer()
        trc.clear()
        trc.arm()
        try:
            with _server(batch_size=4, max_wait_s=0.01) as server:
                server.warmup()
                for _ in range(3):
                    server.submit(
                        {"input": np.ones((2, 3), np.float32)}
                    ).result(timeout=30)
            by_lane = {}
            for s in trc.spans():
                by_lane.setdefault(s.lane, set()).add(s.name)
            assert {"enqueue", "coalesce",
                    "dispatch"} <= by_lane["serve"], by_lane
            events = trc.trace_events()
            json.dumps(events)  # exportable
            text = summarize(events)
            assert "serve" in text
            assert "coalesce" in text.split("stalls")[1], \
                "coalesce missing from the stall breakdown"
        finally:
            trc.arm_from_env()
            trc.clear()

    def test_disarmed_serve_records_nothing(self):
        trc = tracer()
        trc.clear()
        before = len(trc.spans())
        with _server(batch_size=4) as server:
            server.submit(
                {"input": np.ones((2, 3), np.float32)}
            ).result(timeout=30)
        assert len(trc.spans()) == before

    def test_queue_depth_gauges(self):
        server = _server(_slow_host_fn(0.05), batch_size=4,
                         max_wait_s=0.0, max_queue_rows=64)
        futs = [server.submit({"x": np.zeros((4, 3), np.float32)})
                for _ in range(4)]
        snap = default_registry().snapshot()
        assert snap["serve.queue_rows_peak"] >= 4
        for f in futs:
            f.result(timeout=30)
        server.close()
        assert default_registry().snapshot()["serve.queue_rows"] == 0.0


class TestPickle:
    def test_server_round_trip_drops_workers_and_locks(self):
        """The StageMetrics precedent, server-shaped: config and
        registered runners travel, worker threads / locks / queued
        futures drop, and the arrived server serves."""
        cloudpickle = pytest.importorskip("cloudpickle")

        server = _server(batch_size=4, max_wait_s=0.01,
                         max_queue_rows=128)
        x = np.arange(12, dtype=np.float32).reshape(4, 3)
        server.submit({"input": x}).result(timeout=30)  # warm state

        server2 = cloudpickle.loads(cloudpickle.dumps(server))
        assert server2.config == server.config
        s2 = server2.session("m")
        assert s2._worker is None           # workers dropped
        assert s2._queue.depth() == 0       # queue arrives empty
        out = server2.submit({"input": x}).result(timeout=30)
        np.testing.assert_allclose(out["output"], x * 2)
        # cumulative metrics values traveled (the precedent: values
        # travel, locks drop) and keep counting on arrival
        assert server2.metrics.requests == server.metrics.requests + 1
        server2.close()
        server.close()

    def test_closed_server_stays_closed_across_the_wire(self):
        cloudpickle = pytest.importorskip("cloudpickle")

        server = _server(batch_size=4)
        server.close()
        server2 = cloudpickle.loads(cloudpickle.dumps(server))
        with pytest.raises(ServerClosed):
            server2.submit({"input": np.ones((1, 3), np.float32)})

    def test_config_validation(self):
        with pytest.raises(ValueError, match="max_wait_s"):
            ServeConfig(max_wait_s=-1.0)
        with pytest.raises(ValueError, match="max_queue_rows"):
            ServeConfig(max_queue_rows=0)
        with pytest.raises(ValueError, match="default_deadline_s"):
            ServeConfig(default_deadline_s=0.0)
        with pytest.raises(ValueError, match="drain_timeout_s"):
            ServeConfig(drain_timeout_s=0.0)


class TestStageParts:
    def test_stage_parts_reuses_one_buffer_and_zero_pads(self):
        from sparkdl_tpu.runtime.runner import CopyCounters, PadStaging

        staging, counters = PadStaging(), CopyCounters()
        a = np.ones((2, 3), np.float32)
        b = np.full((3, 3), 2.0, np.float32)
        buf = staging.stage_parts("x", [a, b], 8, counters)
        assert buf.shape == (8, 3)
        np.testing.assert_array_equal(buf[:2], 1.0)
        np.testing.assert_array_equal(buf[2:5], 2.0)
        np.testing.assert_array_equal(buf[5:], 0.0)
        assert counters.bytes_staged == a.nbytes + b.nbytes
        assert counters.bytes_copied == 0
        # second call: SAME buffer object, stale rows re-zeroed
        buf2 = staging.stage_parts("x", [np.full((1, 3), 9.0,
                                                 np.float32)], 8)
        assert buf2 is buf
        np.testing.assert_array_equal(buf[0], 9.0)
        np.testing.assert_array_equal(buf[1:], 0.0)

    def test_stage_parts_rejects_overflow(self):
        from sparkdl_tpu.runtime.runner import PadStaging

        with pytest.raises(ValueError, match="rows"):
            PadStaging().stage_parts(
                "x", [np.ones((5, 3), np.float32)], 4)

    def test_runner_warmup_traces_once(self):
        traces = []

        def fn(x):
            traces.append(1)
            return x * 2.0

        r = BatchRunner(ModelFunction.fromSingle(fn, None,
                                                 input_shape=(3,)),
                        batch_size=4)
        assert r.warmup() is True
        assert len(traces) == 1
        x = np.ones((4, 3), np.float32)
        np.testing.assert_allclose(r.run({"input": x})["output"], 2.0)
        assert len(traces) == 1, "post-warmup run re-traced"
