"""Effect-system tests: the H10 jit-purity closure (cross-module
witness chains + mutable-capture analysis), H11 resource-lifecycle
tracking (escape-analysis negatives pinned silent), H12 exception-flow
accounting, SARIF 2.1.0 output, ``--changed-only``, and the
facts-schema cache invalidation contract.

Fixture style mirrors tests/test_callgraph.py: deliberately impure /
leaky multi-module trees under tmp_path trip the rules; the idiomatic
clean forms don't; inline suppressions downgrade without hiding. The
acceptance bars from ISSUE 10: a jitted function transitively calling
a registry counter through two modules is caught WITH the full
witness chain; a mutable-instance-attr capture is caught; an unclosed
ModelServer is caught while every escape-analysis negative stays
silent; a swallowing serve handler is caught while the
counter-recording form is accepted; the real package + tools +
examples are lint-clean under all nineteen rules (H13 rode in with
ISSUE 11's resilience layer; H14-H16 with ISSUE 12's device-dataflow
layer; H17-H19 with ISSUE 17's static race detector).
"""

import json
import os
import subprocess
import sys
import time

import pytest

import sparkdl_tpu
from sparkdl_tpu.analysis import analyze_paths, build_graph, to_sarif
from sparkdl_tpu.analysis import cache as cache_mod
from sparkdl_tpu.analysis.effects import may_effect
from sparkdl_tpu.analysis.walker import ALL_RULES, analyze_source

PKG_DIR = os.path.dirname(os.path.abspath(sparkdl_tpu.__file__))
REPO_ROOT = os.path.dirname(PKG_DIR)


def _tree(tmp_path, files: dict) -> str:
    for name, src in files.items():
        (tmp_path / name).write_text(src)
    return str(tmp_path)


def _unsup(findings, rule):
    return [f for f in findings if f.rule == rule and not f.suppressed]


def _sup(findings, rule):
    return [f for f in findings if f.rule == rule and f.suppressed]


def _run_cli(*args, cwd=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + \
        env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "sparkdl_tpu.analysis", *args],
        capture_output=True, text=True, env=env,
        cwd=cwd or REPO_ROOT)


# ---------------------------------------------------------------------------
# H10 — effectful call reachable from jit


class TestH10JitPurity:
    def test_registry_counter_through_two_modules_with_witness(
            self, tmp_path):
        """THE acceptance fixture: a jitted step transitively calls a
        registry counter through two modules — the finding prints the
        full module-by-module witness chain."""
        root = _tree(tmp_path, {
            "metrics_mod.py": (
                "def bump(reg):\n"
                "    reg.counter('train.steps').add()\n"),
            "helper_mod.py": (
                "from metrics_mod import bump\n"
                "def helper(x, reg):\n"
                "    bump(reg)\n"
                "    return x\n"),
            "train_mod.py": (
                "import jax\n"
                "from helper_mod import helper\n"
                "@jax.jit\n"
                "def step(x, reg):\n"
                "    return helper(x, reg)\n")})
        found = analyze_paths([root], rules=["H10"], cache_path=None)
        hits = _unsup(found, "H10")
        assert len(hits) == 1, [f.render() for f in found]
        msg = hits[0].message
        assert "train_mod:step" in msg
        assert "helper_mod:helper" in msg
        assert "metrics_mod:bump" in msg
        assert "registry" in msg
        assert hits[0].path.endswith("train_mod.py")

    def test_mutable_instance_attr_capture(self, tmp_path):
        """THE second acceptance fixture: a jitted method capturing a
        mutable instance attr (the stale-value/retrace hazard)."""
        root = _tree(tmp_path, {"m.py": (
            "import jax\n"
            "class Trainer:\n"
            "    def __init__(self):\n"
            "        self.history = []\n"
            "    @jax.jit\n"
            "    def traced(self, x):\n"
            "        return x + len(self.history)\n")})
        found = analyze_paths([root], rules=["H10"], cache_path=None)
        hits = _unsup(found, "H10")
        assert len(hits) == 1, [f.render() for f in found]
        assert "self.history" in hits[0].message
        assert "mutable instance attribute" in hits[0].message

    def test_mutable_closure_capture(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "import jax\n"
            "def outer():\n"
            "    accum = []\n"
            "    @jax.jit\n"
            "    def inner(x):\n"
            "        return x + len(accum)\n"
            "    return inner\n")})
        found = analyze_paths([root], rules=["H10"], cache_path=None)
        hits = _unsup(found, "H10")
        assert len(hits) == 1, [f.render() for f in found]
        assert "`accum`" in hits[0].message
        assert "closure" in hits[0].message

    def test_param_shadowing_is_not_a_capture(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "import jax\n"
            "def outer():\n"
            "    accum = []\n"
            "    @jax.jit\n"
            "    def inner(accum):\n"      # param shadows the list
            "        return len(accum)\n"
            "    return inner\n")})
        found = analyze_paths([root], rules=["H10"], cache_path=None)
        assert _unsup(found, "H10") == []

    def test_nested_def_local_does_not_shadow_a_capture(
            self, tmp_path):
        """A NESTED helper's local `accum = ...` must not shadow the
        jitted function's genuine closure capture of the enclosing
        `accum` (scope-pruned locals collection)."""
        root = _tree(tmp_path, {"m.py": (
            "import jax\n"
            "def outer():\n"
            "    accum = []\n"
            "    @jax.jit\n"
            "    def step(x):\n"
            "        y = x + len(accum)\n"
            "        def helper():\n"
            "            accum = 1\n"
            "            return accum\n"
            "        return y\n"
            "    return step\n")})
        found = analyze_paths([root], rules=["H10"], cache_path=None)
        hits = _unsup(found, "H10")
        assert len(hits) == 1, [f.render() for f in found]
        assert "`accum`" in hits[0].message

    def test_pure_jit_fn_is_clean(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "def pure_helper(x):\n"
            "    return x * 2\n"
            "@jax.jit\n"
            "def step(x):\n"
            "    return pure_helper(x) + jnp.sum(x)\n")})
        found = analyze_paths([root], rules=["H10"], cache_path=None)
        assert _unsup(found, "H10") == []

    def test_effect_not_reachable_from_jit_is_clean(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "import jax\n"
            "def effectful(reg):\n"
            "    reg.counter('x.y').add()\n"
            "@jax.jit\n"
            "def step(x):\n"
            "    return x\n")})
        found = analyze_paths([root], rules=["H10"], cache_path=None)
        assert _unsup(found, "H10") == []

    def test_direct_registry_write_in_jit_body(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "import jax\n"
            "@jax.jit\n"
            "def step(x, reg):\n"
            "    reg.counter('steps').add()\n"
            "    return x\n")})
        found = analyze_paths([root], rules=["H10"], cache_path=None)
        hits = _unsup(found, "H10")
        assert len(hits) == 1
        assert "TRACE time" in hits[0].message

    def test_direct_clock_is_h2_territory_not_h10(self, tmp_path):
        """A literal time.time() inside the jit body is H2's lexical
        beat — H10 flagging the same line would demand two
        suppressions for one decision."""
        root = _tree(tmp_path, {"m.py": (
            "import jax, time\n"
            "@jax.jit\n"
            "def step(x):\n"
            "    t = time.time()\n"
            "    return x + t\n")})
        found = analyze_paths([root], rules=["H10"], cache_path=None)
        assert _unsup(found, "H10") == []
        found2 = analyze_paths([root], rules=["H2"], cache_path=None)
        assert len(_unsup(found2, "H2")) == 1

    def test_transitive_clock_IS_h10(self, tmp_path):
        """...but the same clock reached through a call chain is
        exactly what H2 cannot see and H10 exists for."""
        root = _tree(tmp_path, {"m.py": (
            "import jax, time\n"
            "def stamp():\n"
            "    return time.time()\n"
            "@jax.jit\n"
            "def step(x):\n"
            "    return x + stamp()\n")})
        found = analyze_paths([root], rules=["H10"], cache_path=None)
        hits = _unsup(found, "H10")
        assert len(hits) == 1
        assert "time.time" in hits[0].message
        found2 = analyze_paths([root], rules=["H2"], cache_path=None)
        assert _unsup(found2, "H2") == []

    def test_unique_method_edges_are_not_followed(self, tmp_path):
        """A jit body calling obj.update() must NOT bind to the one
        analyzed class defining `update` — optimizer objects live
        outside the analyzed set, and a guessed edge manufactures
        false impurity."""
        root = _tree(tmp_path, {"m.py": (
            "import jax\n"
            "class Registryish:\n"
            "    def update(self, reg):\n"
            "        reg.counter('x.y').add()\n"
            "@jax.jit\n"
            "def step(x, opt, state):\n"
            "    return opt.update(state)\n")})
        found = analyze_paths([root], rules=["H10"], cache_path=None)
        assert _unsup(found, "H10") == []

    def test_partial_jit_outer_call_form_marks_named_def(
            self, tmp_path):
        """`partial(jax.jit, ...)(step)`: the traced fn rides the
        OUTER call's args — it must still be marked a jit root."""
        root = _tree(tmp_path, {"m.py": (
            "import jax\n"
            "from functools import partial\n"
            "def make():\n"
            "    def step(x, reg):\n"
            "        reg.counter('steps').add()\n"
            "        return x\n"
            "    return partial(jax.jit, donate_argnums=(0,))(step)\n")})
        found = analyze_paths([root], rules=["H10"], cache_path=None)
        assert len(_unsup(found, "H10")) == 1, \
            [f.render() for f in found]

    def test_jit_root_inside_match_case_is_seen(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "import jax\n"
            "def fit(mode):\n"
            "    match mode:\n"
            "        case 'train':\n"
            "            @jax.jit\n"
            "            def step(x, reg):\n"
            "                reg.counter('steps').add()\n"
            "                return x\n"
            "            return step\n")})
        found = analyze_paths([root], rules=["H10"], cache_path=None)
        assert len(_unsup(found, "H10")) == 1, \
            [f.render() for f in found]

    def test_jit_call_form_marks_named_def(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "import jax\n"
            "def make():\n"
            "    def step(x, reg):\n"
            "        reg.gauge('depth').set(x)\n"
            "        return x\n"
            "    return jax.jit(step)\n")})
        found = analyze_paths([root], rules=["H10"], cache_path=None)
        assert len(_unsup(found, "H10")) == 1

    def test_jitted_step_inside_epoch_loop_is_seen(self, tmp_path):
        """The streaming-estimator idiom: the jitted def sits inside
        a for/if block, not at the function body's top level — the
        def walk must still find it (the PR-8 walk missed these)."""
        root = _tree(tmp_path, {"m.py": (
            "import jax\n"
            "def fit(first):\n"
            "    if first:\n"
            "        @jax.jit\n"
            "        def step(x, reg):\n"
            "            reg.counter('steps').add()\n"
            "            return x\n"
            "        return step\n")})
        found = analyze_paths([root], rules=["H10"], cache_path=None)
        assert len(_unsup(found, "H10")) == 1

    def test_suppressed_with_reason(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "import jax\n"
            "def log_shape(x):\n"
            "    print(x.shape)\n"
            "@jax.jit\n"
            "def step(x):\n"
            "    log_shape(x)  # sparkdl-lint: allow[H10] -- "
            "trace-time shape echo is the point (debug build only)\n"
            "    return x\n")})
        found = analyze_paths([root], rules=["H10"], cache_path=None)
        assert _unsup(found, "H10") == []
        sup = _sup(found, "H10")
        assert len(sup) == 1
        assert "shape echo" in sup[0].suppression

    def test_may_effect_closure_dedups_and_chains(self, tmp_path):
        root = _tree(tmp_path, {
            "a.py": ("from b import mid\n"
                     "def top(reg):\n"
                     "    mid(reg)\n"
                     "    mid(reg)\n"),
            "b.py": ("def mid(reg):\n"
                     "    reg.counter('k.v').add()\n")})
        g = build_graph([os.path.join(root, "a.py"),
                         os.path.join(root, "b.py")])
        key = next(k for k, f in g.functions.items()
                   if f.qualname == "top")
        eff = may_effect(g, key)
        regs = [(k, chain) for k, chain in eff.items()
                if k[0] == "registry"]
        assert len(regs) == 1
        (_, chain) = regs[0]
        assert chain[0].endswith("a:top") and chain[-1].endswith("b:mid")


# ---------------------------------------------------------------------------
# H11 — resource lifecycle


_SRV = ("class ModelServer:\n"
        "    def submit(self, x):\n"
        "        return x\n"
        "    def close(self):\n"
        "        pass\n")


class TestH11ResourceLifecycle:
    def test_unclosed_modelserver_is_caught(self, tmp_path):
        """THE acceptance fixture: a ModelServer constructed, used,
        and abandoned — cross-module ctor resolution included."""
        root = _tree(tmp_path, {
            "srv.py": _SRV,
            "use.py": ("from srv import ModelServer\n"
                       "def serve_once(x):\n"
                       "    s = ModelServer()\n"
                       "    return s.submit(x)\n")})
        found = analyze_paths([root], rules=["H11"], cache_path=None)
        hits = _unsup(found, "H11")
        assert len(hits) == 1, [f.render() for f in found]
        assert "ModelServer" in hits[0].message
        assert "close()" in hits[0].message
        assert hits[0].path.endswith("use.py")

    @pytest.mark.parametrize("body", [
        # returned
        "    s = ModelServer()\n    return s\n",
        # stored on self/attr
        "    s = ModelServer()\n    holder.srv = s\n",
        # stored in a container
        "    s = ModelServer()\n    holder['k'] = s\n",
        # weakly registered / passed to a function
        "    s = ModelServer()\n    reg.register(s)\n",
        # terminated
        "    s = ModelServer()\n    s.close()\n",
        # terminated in a finally
        "    s = ModelServer()\n    try:\n        s.submit(1)\n"
        "    finally:\n        s.close()\n",
        # used as a context manager
        "    s = ModelServer()\n    with s:\n        pass\n",
    ], ids=["returned", "stored-attr", "stored-subscript",
            "registered", "closed", "finally-closed", "with"])
    def test_escape_analysis_negatives_stay_silent(self, tmp_path,
                                                   body):
        root = _tree(tmp_path, {
            "srv.py": _SRV,
            "use.py": ("from srv import ModelServer\n"
                       "def f(holder, reg):\n" + body)})
        found = analyze_paths([root], rules=["H11"], cache_path=None)
        assert _unsup(found, "H11") == [], \
            [f.render() for f in _unsup(found, "H11")]

    def test_global_storage_escapes(self, tmp_path):
        root = _tree(tmp_path, {
            "srv.py": _SRV,
            "use.py": ("from srv import ModelServer\n"
                       "_default = None\n"
                       "def default_server():\n"
                       "    global _default\n"
                       "    _default = ModelServer()\n"
                       "    return _default\n")})
        found = analyze_paths([root], rules=["H11"], cache_path=None)
        assert _unsup(found, "H11") == []

    def test_open_handle_leak_and_with_form(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "def leaky(p):\n"
            "    f = open(p)\n"
            "    return f.read()\n"       # escape? no: f.read() is
            "def fine(p):\n"               # receiver use, not escape
            "    with open(p) as f:\n"
            "        return f.read()\n"
            "def closed(p):\n"
            "    f = open(p)\n"
            "    data = f.read()\n"
            "    f.close()\n"
            "    return data\n")})
        found = analyze_paths([root], rules=["H11"], cache_path=None)
        hits = _unsup(found, "H11")
        assert len(hits) == 1, [f.render() for f in hits]
        assert hits[0].qualname == "leaky"

    def test_arm_without_disarm_is_caught(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "from sparkdl_tpu.obs.watchdog import watchdog\n"
            "def measure():\n"
            "    wd = watchdog()\n"
            "    wd.arm(threshold_s=0.5)\n"
            "    run()\n")})
        found = analyze_paths([root], rules=["H11"], cache_path=None)
        hits = _unsup(found, "H11")
        assert len(hits) == 1
        assert "disarm" in hits[0].message

    def test_arm_with_disarm_is_clean(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "from sparkdl_tpu.obs.watchdog import watchdog\n"
            "def measure():\n"
            "    wd = watchdog()\n"
            "    wd.arm(threshold_s=0.5)\n"
            "    try:\n"
            "        run()\n"
            "    finally:\n"
            "        wd.disarm()\n")})
        found = analyze_paths([root], rules=["H11"], cache_path=None)
        assert _unsup(found, "H11") == []

    def test_direct_singleton_arm_form(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "from sparkdl_tpu.obs.trace import tracer\n"
            "def measure():\n"
            "    tracer().arm()\n"
            "    run()\n")})
        found = analyze_paths([root], rules=["H11"], cache_path=None)
        assert len(_unsup(found, "H11")) == 1
        (tmp_path / "ok").mkdir()
        root2 = _tree(tmp_path / "ok", {"m.py": (
            "from sparkdl_tpu.obs.trace import tracer\n"
            "def measure():\n"
            "    tracer().arm()\n"
            "    run()\n"
            "    tracer().disarm()\n")})
        found2 = analyze_paths([root2], rules=["H11"], cache_path=None)
        assert _unsup(found2, "H11") == []

    def test_arm_in_nested_def_belongs_to_the_nested_scope(
            self, tmp_path):
        """An arm inside a nested callback is the CALLBACK's
        lifecycle, not the enclosing function's — exactly one finding,
        anchored in the nested def (the scope-pruned walk)."""
        root = _tree(tmp_path, {"m.py": (
            "from sparkdl_tpu.obs.watchdog import watchdog\n"
            "def setup(register):\n"
            "    def cb():\n"
            "        watchdog().arm(threshold_s=1.0)\n"
            "        run()\n"
            "    register(cb)\n")})
        found = analyze_paths([root], rules=["H11"], cache_path=None)
        hits = _unsup(found, "H11")
        assert len(hits) == 1, [f.render() for f in hits]
        assert hits[0].qualname == "setup.cb"

    def test_terminator_inside_nested_def_does_not_silence(
            self, tmp_path):
        """A close() sitting inside a maybe-never-called nested def
        must NOT count as the outer scope's termination. (The ctor
        form escapes via nested-def capture instead; the arm form has
        no capturable name, so this pins the real hole.)"""
        root = _tree(tmp_path, {"m.py": (
            "from sparkdl_tpu.obs.watchdog import watchdog\n"
            "def measure(register):\n"
            "    watchdog().arm(threshold_s=1.0)\n"
            "    def later():\n"
            "        watchdog().disarm()\n"
            "    register(later)\n"
            "    run()\n")})
        found = analyze_paths([root], rules=["H11"], cache_path=None)
        hits = _unsup(found, "H11")
        assert len(hits) == 1, [f.render() for f in hits]
        assert hits[0].qualname == "measure"

    def test_unresolvable_ctor_is_silent(self, tmp_path):
        """A class the analyzer cannot see (third-party) gives no
        verdict — a guessed lifecycle would be a false positive."""
        root = _tree(tmp_path, {"m.py": (
            "from somewhere import Mystery\n"
            "def f():\n"
            "    m = Mystery()\n"
            "    m.use()\n")})
        found = analyze_paths([root], rules=["H11"], cache_path=None)
        assert _unsup(found, "H11") == []

    def test_ambiguous_class_name_is_silent(self, tmp_path):
        """Two analyzed modules define `Server` (one with close, one
        without): the unique-class fallback must refuse, like the
        unique-method heuristic does."""
        root = _tree(tmp_path, {
            "a.py": "class Server:\n    def close(self):\n        pass\n",
            "b.py": "class Server:\n    def ping(self):\n        pass\n",
            "use.py": ("def f(make):\n"
                       "    s = Server()\n"
                       "    s.ping()\n")})
        found = analyze_paths([root], rules=["H11"], cache_path=None)
        assert _unsup(found, "H11") == []

    def test_non_resource_class_is_silent(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "class Plain:\n"
            "    def work(self):\n"
            "        pass\n"
            "def f():\n"
            "    p = Plain()\n"
            "    p.work()\n")})
        found = analyze_paths([root], rules=["H11"], cache_path=None)
        assert _unsup(found, "H11") == []

    def test_suppressed_with_reason(self, tmp_path):
        root = _tree(tmp_path, {
            "srv.py": _SRV,
            "use.py": (
                "from srv import ModelServer\n"
                "def f(x):\n"
                "    s = ModelServer()  # sparkdl-lint: allow[H11] -- "
                "process-lifetime server; atexit hook closes it\n"
                "    return s.submit(x)\n")})
        found = analyze_paths([root], rules=["H11"], cache_path=None)
        assert _unsup(found, "H11") == []
        assert len(_sup(found, "H11")) == 1


# ---------------------------------------------------------------------------
# H12 — exception-flow accounting


_SERVE_PATH = "sparkdl_tpu/serve/fake_dispatch.py"


class TestH12ExceptionFlow:
    def test_pass_swallow_in_serve_path(self):
        src = ("def dispatch(q):\n"
               "    try:\n"
               "        q.pop()\n"
               "    except Exception:\n"
               "        pass\n")
        found = analyze_source(src, _SERVE_PATH, rules=["H12"])
        assert len(_unsup(found, "H12")) == 1

    def test_log_only_swallow(self):
        src = ("import logging\n"
               "logger = logging.getLogger(__name__)\n"
               "def dispatch(q):\n"
               "    try:\n"
               "        q.pop()\n"
               "    except Exception:\n"
               "        logger.exception('dispatch failed')\n")
        found = analyze_source(src, _SERVE_PATH, rules=["H12"])
        hits = _unsup(found, "H12")
        assert len(hits) == 1
        assert "log-only" in hits[0].message

    def test_chained_getlogger_swallow_is_caught(self):
        """`logging.getLogger(__name__).warning(...)` — the repo's own
        degrade idiom — is a log-only swallow; the chained receiver
        (a Call, invisible to _dotted) must still classify."""
        src = ("import logging\n"
               "def dispatch(q):\n"
               "    try:\n"
               "        q.pop()\n"
               "    except Exception:\n"
               "        logging.getLogger(__name__).warning('x')\n")
        found = analyze_source(src, _SERVE_PATH, rules=["H12"])
        assert len(_unsup(found, "H12")) == 1

    def test_path_scope_holds_for_cwd_relative_paths(self, tmp_path,
                                                     monkeypatch):
        """Linting `obs/x.py` from INSIDE the package dir must not
        silently skip the path-scoped rule — the absolute form is
        consulted too."""
        pkg_obs = tmp_path / "sparkdl_tpu" / "obs"
        pkg_obs.mkdir(parents=True)
        (pkg_obs / "x.py").write_text(
            "def f(q):\n"
            "    try:\n"
            "        q.pop()\n"
            "    except Exception:\n"
            "        pass\n")
        monkeypatch.chdir(tmp_path / "sparkdl_tpu")
        found = analyze_paths(["obs"], rules=["H12"], cache_path=None)
        assert len(_unsup(found, "H12")) == 1, \
            [f.render() for f in found]

    def test_bare_continue_swallow(self):
        src = ("def drain(items):\n"
               "    for it in items:\n"
               "        try:\n"
               "            it.flush()\n"
               "        except Exception:\n"
               "            continue\n")
        found = analyze_source(src, _SERVE_PATH, rules=["H12"])
        hits = _unsup(found, "H12")
        assert len(hits) == 1
        assert "continue" in hits[0].message

    def test_counter_recording_form_is_accepted(self):
        """THE acceptance negative: the handler records a failure
        counter — the PR-7 population-separation contract satisfied."""
        src = ("from sparkdl_tpu.obs.registry import default_registry\n"
               "def dispatch(q):\n"
               "    try:\n"
               "        q.pop()\n"
               "    except Exception:\n"
               "        default_registry().counter("
               "'serve.failures').add()\n")
        found = analyze_source(src, _SERVE_PATH, rules=["H12"])
        assert _unsup(found, "H12") == []

    @pytest.mark.parametrize("handler", [
        "        raise\n",
        "        return None\n",
        "        out['error'] = 'boom'\n",
        "        fut.set_exception(ValueError('x'))\n",
        "        slo_tracker().record(ok=False)\n",
    ], ids=["reraise", "return", "assign", "set-exception", "slo"])
    def test_accountable_handlers_are_clean(self, handler):
        src = ("def dispatch(q, out, fut, slo_tracker):\n"
               "    try:\n"
               "        q.pop()\n"
               "    except Exception:\n" + handler)
        found = analyze_source(src, _SERVE_PATH, rules=["H12"])
        assert _unsup(found, "H12") == [], \
            [f.render() for f in _unsup(found, "H12")]

    def test_outside_hot_paths_is_out_of_scope(self):
        src = ("def load(q):\n"
               "    try:\n"
               "        q.pop()\n"
               "    except Exception:\n"
               "        pass\n")
        found = analyze_source(src, "sparkdl_tpu/data/loader.py",
                               rules=["H12"])
        assert found == []

    def test_suppressed_with_reason(self):
        src = ("def dispatch(q):\n"
               "    try:\n"
               "        q.pop()\n"
               "    # sparkdl-lint: allow[H12] -- empty-queue race is "
               "the normal idle path, not a failure\n"
               "    except IndexError:\n"
               "        pass\n")
        found = analyze_source(src, _SERVE_PATH, rules=["H12"])
        assert _unsup(found, "H12") == []
        sup = _sup(found, "H12")
        assert len(sup) == 1
        assert "idle path" in sup[0].suppression


# ---------------------------------------------------------------------------
# fix-on-find regressions (the counters the sweep added)


class TestFixOnFindRegressions:
    def test_watchdog_monitor_error_is_counted(self):
        from sparkdl_tpu.obs.registry import default_registry
        from sparkdl_tpu.obs.watchdog import watchdog
        wd = watchdog()
        reg = default_registry()
        before = reg.snapshot().get("watchdog.monitor_errors", 0)
        orig = wd.check_once
        wd.check_once = lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("injected monitor failure"))
        try:
            wd.arm(threshold_s=0.05)
            deadline = time.perf_counter() + 5.0
            while reg.snapshot().get("watchdog.monitor_errors",
                                     0) <= before:
                assert time.perf_counter() < deadline, \
                    "monitor error never counted"
                time.sleep(0.01)
        finally:
            wd.check_once = orig
            wd.disarm()
        assert reg.snapshot()["watchdog.monitor_errors"] > before

    def test_telemetry_handler_failure_is_counted(self):
        import urllib.error
        import urllib.request
        from sparkdl_tpu.obs.export import start_telemetry
        from sparkdl_tpu.obs.registry import default_registry
        reg = default_registry()
        tel = start_telemetry()
        try:
            before = reg.snapshot().get("telemetry.errors", 0)
            tel._statusz = lambda *a: (_ for _ in ()).throw(
                RuntimeError("injected statusz failure"))
            try:
                with urllib.request.urlopen(tel.url("/statusz"),
                                            timeout=5) as r:
                    code = r.status
            except urllib.error.HTTPError as e:
                code = e.code
            assert code == 500
            assert reg.snapshot()["telemetry.errors"] > before
        finally:
            tel.close()

    def test_probe_degrade_swallow_is_suppressed_not_invisible(self):
        """The runner's NotImplementedError probe swallow must appear
        as a SUPPRESSED H12 with its justification."""
        found = analyze_paths(
            [os.path.join(PKG_DIR, "runtime", "runner.py")],
            rules=["H12"], cache_path=None)
        sup = _sup(found, "H12")
        assert any("probe-and-degrade" in f.suppression for f in sup), \
            [f.render() for f in found]


# ---------------------------------------------------------------------------
# SARIF 2.1.0 output


def _validate_sarif(doc: dict) -> None:
    """Structural SARIF 2.1.0 validation (the schema's required
    properties for the subset sparkdl-lint emits)."""
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-schema-2.1.0.json")
    assert isinstance(doc["runs"], list) and len(doc["runs"]) == 1
    run = doc["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "sparkdl-lint"
    rule_ids = {r["id"] for r in driver["rules"]}
    for r in driver["rules"]:
        assert r["shortDescription"]["text"]
    assert isinstance(run["results"], list)
    for res in run["results"]:
        assert res["ruleId"] in rule_ids, \
            "result references an unlisted rule"
        assert res["level"] in ("none", "note", "warning", "error")
        assert res["message"]["text"]
        [loc] = res["locations"]
        phys = loc["physicalLocation"]
        assert phys["artifactLocation"]["uri"]
        assert phys["region"]["startLine"] >= 1
        for sup in res.get("suppressions", ()):
            assert sup["kind"] in ("inSource", "external")


class TestSarif:
    def test_document_schema_and_suppressions(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "import jax\n"
            "def fine():\n"
            "    jax.device_get(1)  # sparkdl-lint: allow[H1] -- test\n"
            "def bad():\n"
            "    jax.device_get(2)\n")})
        found = analyze_paths([root], cache_path=None)
        doc = to_sarif(found, ALL_RULES)
        _validate_sarif(doc)
        results = doc["runs"][0]["results"]
        by_supp = [r for r in results if "suppressions" in r]
        assert len(by_supp) == 1
        assert "test" in by_supp[0]["suppressions"][0]["justification"]
        assert any("suppressions" not in r for r in results)
        # the full thirteen-rule catalogue rides in the driver
        ids = {r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]}
        assert {"H1", "H10", "H11", "H12"} <= ids

    def test_cli_sarif_round_trip(self, tmp_path):
        root = _tree(tmp_path, {"m.py": (
            "import jax\n"
            "def bad():\n"
            "    jax.device_get(2)\n")})
        out = tmp_path / "out.sarif"
        r = _run_cli("--no-cache", "--sarif", str(out), root)
        assert r.returncode == 1, (r.stdout, r.stderr)
        doc = json.loads(out.read_text())
        _validate_sarif(doc)
        assert len(doc["runs"][0]["results"]) == 1
        assert "SARIF" in r.stderr

    def test_ci_emits_schema_validated_sarif_for_the_package(
            self, tmp_path):
        """The CI-shaped invocation: package dir, SARIF out — the
        document must validate and carry only suppressed results."""
        out = tmp_path / "pkg.sarif"
        r = _run_cli("--sarif", str(out), "--no-cache",
                     os.path.join(PKG_DIR, "analysis"))
        assert r.returncode == 0, (r.stdout[-800:], r.stderr[-800:])
        _validate_sarif(json.loads(out.read_text()))


# ---------------------------------------------------------------------------
# --changed-only


class TestChangedOnly:
    def _git(self, cwd, *args):
        return subprocess.run(
            ["git", "-c", "user.email=t@t", "-c", "user.name=t",
             *args], cwd=cwd, capture_output=True, text=True)

    def test_dirty_file_detection(self, tmp_path):
        from sparkdl_tpu.analysis.__main__ import _git_dirty_files
        if self._git(tmp_path, "init").returncode != 0:
            pytest.skip("git unavailable")
        (tmp_path / "clean.py").write_text("x = 1\n")
        (tmp_path / "dirty.py").write_text("y = 1\n")
        self._git(tmp_path, "add", "-A")
        self._git(tmp_path, "commit", "-m", "seed")
        (tmp_path / "dirty.py").write_text("y = 2\n")
        (tmp_path / "fresh.py").write_text("z = 1\n")
        got = _git_dirty_files(str(tmp_path))
        names = sorted(os.path.basename(p) for p in got)
        assert names == ["dirty.py", "fresh.py"]

    def test_paths_anchor_at_the_git_toplevel(self, tmp_path):
        """Porcelain paths are toplevel-relative: a package vendored
        in a SUBDIRECTORY of a larger repo must still resolve its
        dirty files to real paths (a silent [] here made the --fast
        loop false-green)."""
        from sparkdl_tpu.analysis.__main__ import _git_dirty_files
        if self._git(tmp_path, "init").returncode != 0:
            pytest.skip("git unavailable")
        sub = tmp_path / "vendor" / "pkg"
        sub.mkdir(parents=True)
        (sub / "mod.py").write_text("x = 1\n")
        self._git(tmp_path, "add", "-A")
        self._git(tmp_path, "commit", "-m", "seed")
        (sub / "mod.py").write_text("x = 2\n")
        got = _git_dirty_files(str(sub))      # root BELOW the toplevel
        assert got and all(os.path.isfile(p) for p in got), got
        assert os.path.basename(got[0]) == "mod.py"

    def test_outside_checkout_returns_none(self, tmp_path):
        from sparkdl_tpu.analysis.__main__ import _git_dirty_files
        # tmp_path is not a git repo (and not inside one)
        assert _git_dirty_files(str(tmp_path)) is None

    def test_cli_smoke_exits_zero_on_clean_or_dirty_tree(self):
        """The pre-commit loop's contract: a lint-clean repo exits 0
        under --changed-only whether or not anything is dirty — and
        --json ALWAYS emits a parseable document, nothing-changed
        included (a consumer json.loads()ing stdout must never
        crash)."""
        r = _run_cli("--changed-only", "--no-cache", "--json")
        assert r.returncode == 0, (r.stdout[-800:], r.stderr[-800:])
        d = json.loads(r.stdout)
        assert d["unsuppressed"] == 0
        for key in ("findings", "suppressed", "rules", "by_rule",
                    "targets", "cache"):
            assert key in d, sorted(d)


# ---------------------------------------------------------------------------
# cache invalidation across analyzer-version bumps


class TestCacheVersionBump:
    def _paths(self, tmp_path):
        root = _tree(tmp_path, {
            "a.py": "def f():\n    pass\n",
            "b.py": "def g():\n    pass\n"})
        return root, str(tmp_path / "cache.json")

    def test_version_bump_forces_cold_reanalysis(self, tmp_path,
                                                 monkeypatch):
        """A facts-schema (analyzer version) bump must invalidate
        EVERY cached entry — file content and rule set are unchanged,
        so only the version key can force the cold pass."""
        root, cache = self._paths(tmp_path)
        stats: dict = {}
        analyze_paths([root], cache_path=cache, cache_stats=stats)
        assert stats["misses"] == 2 and stats["hits"] == 0
        stats = {}
        analyze_paths([root], cache_path=cache, cache_stats=stats)
        assert stats["hits"] == 2 and stats["misses"] == 0
        monkeypatch.setattr(cache_mod, "ANALYZER_VERSION",
                            cache_mod.ANALYZER_VERSION + 1)
        stats = {}
        analyze_paths([root], cache_path=cache, cache_stats=stats)
        assert stats["misses"] == 2 and stats["hits"] == 0, \
            "version bump did not force a cold re-analysis"

    def test_bumped_cache_rewrites_under_new_version(self, tmp_path,
                                                     monkeypatch):
        root, cache = self._paths(tmp_path)
        analyze_paths([root], cache_path=cache)
        monkeypatch.setattr(cache_mod, "ANALYZER_VERSION",
                            cache_mod.ANALYZER_VERSION + 1)
        analyze_paths([root], cache_path=cache)
        stats: dict = {}
        analyze_paths([root], cache_path=cache, cache_stats=stats)
        assert stats["hits"] == 2, \
            "re-analysis under the new version did not repopulate"

    def test_effect_facts_survive_the_cache_round_trip(self, tmp_path):
        """Cached effect facts must reproduce the same H10 verdicts —
        the serialization is part of the facts schema."""
        root = _tree(tmp_path, {"m.py": (
            "import jax\n"
            "def eff(reg):\n"
            "    reg.counter('a.b').add()\n"
            "@jax.jit\n"
            "def step(x, reg):\n"
            "    return eff(reg)\n")})
        cache = str(tmp_path / "c.json")
        cold = analyze_paths([root], rules=["H10"], cache_path=cache)
        stats: dict = {}
        warm = analyze_paths([root], rules=["H10"], cache_path=cache,
                             cache_stats=stats)
        assert stats["hits"] == 1
        assert [f.message for f in _unsup(cold, "H10")] == \
            [f.message for f in _unsup(warm, "H10")]


# ---------------------------------------------------------------------------
# meta: the nineteen-rule acceptance gate


class TestMetaNineteenRules:
    def test_all_rules_includes_the_effect_system(self):
        assert {"H10", "H11", "H12", "H13", "H14", "H15",
                "H16", "H17", "H18", "H19"} <= set(ALL_RULES)
        assert len(ALL_RULES) == 19

    def test_package_tools_examples_clean_under_nineteen_rules(self):
        """THE acceptance gate: zero unsuppressed findings under all
        nineteen rules across the package + tools/ + examples/."""
        targets = [PKG_DIR]
        for extra in ("tools", "examples"):
            d = os.path.join(REPO_ROOT, extra)
            if os.path.isdir(d):
                targets.append(d)
        found = analyze_paths(targets, cache_path=None)
        unsup = [f for f in found if not f.suppressed]
        assert unsup == [], "\n".join(f.render() for f in unsup)

    def test_real_package_jit_roots_are_detected(self):
        """The effect system must SEE the package's actual jit
        boundaries — including the streaming estimator's step defined
        inside an epoch loop (the walk-depth fix)."""
        from sparkdl_tpu.analysis import iter_python_files
        g = build_graph(list(iter_python_files(
            os.path.join(PKG_DIR, "estimators"))))
        roots = {k for m in g.modules.values()
                 for k, fe in m.effects.items() if fe.jitted}
        assert any("_run_full_batch" in k for k in roots), roots
        assert any("_run_streaming" in k for k in roots), roots

    def test_h12_fixes_are_part_of_the_record(self):
        """The sweep's accounting counters exist in the source the
        rules gate (a refactor dropping them re-opens the H12 hole)."""
        with open(os.path.join(PKG_DIR, "obs", "watchdog.py")) as f:
            assert "watchdog.monitor_errors" in f.read()
        with open(os.path.join(PKG_DIR, "obs", "export.py")) as f:
            assert "telemetry.errors" in f.read()
